#!/usr/bin/env python
"""Audio modem over a simulated acoustic channel (reference: examples/rattlegram)."""

import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu.models.rattlegram import Modem


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("message", nargs="?", default="hello through the speaker")
    p.add_argument("--noise", type=float, default=0.02)
    a = p.parse_args()

    rng = np.random.default_rng(0)
    m = Modem(payload_size=64)
    audio = m.tx(a.message.encode())
    print(f"burst: {len(audio)} samples @8 kHz = {len(audio)/8000:.2f} s")
    channel = np.concatenate([np.zeros(1000, np.float32), 0.5 * audio,
                              np.zeros(500, np.float32)])
    channel += a.noise * rng.standard_normal(len(channel)).astype(np.float32)
    got = m.rx(channel)
    print("decoded:", got)


if __name__ == "__main__":
    main()
