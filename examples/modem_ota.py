#!/usr/bin/env python
"""Audio modem over a simulated acoustic channel (reference: examples/rattlegram)."""

import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu.models.rattlegram import Modem, ModemParams, demodulate_auto


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("message", nargs="?", default="hello through the speaker")
    p.add_argument("--noise", type=float, default=0.02)
    p.add_argument("--callsign", default=None,
                   help="polar fec + in-band metadata: RX needs no payload size")
    a = p.parse_args()

    rng = np.random.default_rng(0)
    if a.callsign:
        m = Modem(payload_size=85, params=ModemParams(fec="polar"),
                  callsign=a.callsign)
    else:
        m = Modem(payload_size=64)
    audio = m.tx(a.message.encode())
    print(f"burst: {len(audio)} samples @8 kHz = {len(audio)/8000:.2f} s")
    channel = np.concatenate([np.zeros(1000, np.float32), 0.5 * audio,
                              np.zeros(500, np.float32)])
    channel += a.noise * rng.standard_normal(len(channel)).astype(np.float32)
    if a.callsign:
        cs, payload = demodulate_auto(channel, m.params)
        print(f"decoded from {cs}:", payload.rstrip(b"\x00"))
    else:
        print("decoded:", m.rx(channel))


if __name__ == "__main__":
    main()
