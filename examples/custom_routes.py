#!/usr/bin/env python
"""Custom REST routes beside the control port (reference: examples/custom-routes).

The reference builds an axum ``Router`` with two extra routes and hands it to
``Runtime::with_custom_routes`` (`examples/custom-routes/src/main.rs:33-46`):
``/my_route/`` serves a static HTML page, ``/start_fg/`` launches a second
flowgraph on the SAME runtime from inside a handler. Same shape here: the
``Runtime(extra_routes=…)`` tuples are mounted on the control-port aiohttp app
beside the ``/api/fg/`` families, and the handler starts a flowgraph through
the runtime handle.

Run it, then:  curl http://127.0.0.1:1337/my_route/
               curl http://127.0.0.1:1337/start_fg/
               curl http://127.0.0.1:1337/api/fg/
"""

import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import MessageSource, MessageSink
from futuresdr_tpu.config import config
from futuresdr_tpu.types import Pmt

PAGE = """<html>
  <head><meta charset='utf-8'/><title>FutureSDR TPU</title></head>
  <body><h1>My Custom Route</h1></body>
</html>"""


def build_beacon(n_messages=None) -> Flowgraph:
    fg = Flowgraph()
    src = MessageSource(Pmt.string("foo"), interval=0.1, count=n_messages)
    snk = MessageSink()
    fg.connect_message(src, "out", snk, "in")
    return fg


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=18137,
                   help="dedicated port (default off 1337 so a leaked server "
                        "can't shadow the CI smoke)")
    a = p.parse_args()
    config().ctrlport_enable = True
    config().ctrlport_bind = f"127.0.0.1:{a.port}"

    runtime_box = {}

    async def my_route(request):
        from aiohttp import web
        return web.Response(text=PAGE, content_type="text/html")

    async def start_fg(request):
        # launch a SECOND flowgraph on the same runtime from a handler
        # (`main.rs:65-76` start_fg): respond with its descriptor
        from aiohttp import web
        rt = runtime_box["rt"]
        running = await rt.start_async(build_beacon(n_messages=50))
        desc = await running.handle.describe()
        return web.json_response(desc.to_json())

    rt = Runtime(extra_routes=[("GET", "/my_route/", my_route),
                               ("GET", "/start_fg/", start_fg)])
    runtime_box["rt"] = rt

    print("custom routes at http://%s/my_route/ and /start_fg/"
          % config().ctrlport_bind)
    running = rt.start(build_beacon(n_messages=20))
    time.sleep(0.5)

    # self-demonstrate (the CI smoke runs exactly this)
    import urllib.request
    base = "http://" + config().ctrlport_bind
    html = urllib.request.urlopen(base + "/my_route/", timeout=5).read().decode()
    assert "My Custom Route" in html
    desc = urllib.request.urlopen(base + "/start_fg/", timeout=5).read().decode()
    assert "blocks" in desc
    fgs = urllib.request.urlopen(base + "/api/fg/", timeout=5).read().decode()
    assert fgs.strip() == "[0, 1]", fgs   # handler-launched fg registered too
    print("GET /my_route/ ->", html.splitlines()[2].strip())
    print("GET /start_fg/ -> launched:", desc[:72], "...")
    print("GET /api/fg/   ->", fgs.strip())
    running.stop_sync()


if __name__ == "__main__":
    main()
