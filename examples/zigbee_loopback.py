#!/usr/bin/env python
"""ZigBee (802.15.4 O-QPSK, 2.4 GHz DSSS) loopback over a noisy channel.

Reference role: ``examples/zigbee``. Payload blobs go in on the transmitter's ``tx``
message port, travel as O-QPSK baseband through an AWGN channel, and decoded MAC
payloads print on the way out. (Clock-offset tolerance of the Mueller-Müller timing
path is exercised separately in ``tests/test_zigbee.py`` at ±50 ppm.)
"""
import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Pmt, Runtime
from futuresdr_tpu.blocks import Apply
from futuresdr_tpu.models.zigbee import ZigbeeReceiver, ZigbeeTransmitter


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=4)
    p.add_argument("--noise", type=float, default=0.1)
    a = p.parse_args()

    rng = np.random.default_rng(11)
    fg = Flowgraph()
    tx = ZigbeeTransmitter()
    chan = Apply(lambda x: (x + a.noise * (rng.standard_normal(len(x))
                                           + 1j * rng.standard_normal(len(x)))
                            ).astype(np.complex64), np.complex64)
    rx = ZigbeeReceiver()
    fg.connect(tx, chan, rx)

    rt = Runtime()
    running = rt.start(fg)
    payloads = [f"zigbee frame {i}".encode() for i in range(a.frames)]
    for pl in payloads:
        r = rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.blob(pl)))
        assert r == Pmt.ok()
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()

    print(f"decoded {len(rx.frames)}/{a.frames} MPDUs:")
    for f in rx.frames:
        print(f"  {f!r}")
    assert list(rx.frames) == payloads


if __name__ == "__main__":
    main()
