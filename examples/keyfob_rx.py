#!/usr/bin/env python
"""Keyfob OOK transceiver (reference: ``examples/keyfob/src/main.rs`` —
capture replay → envelope → Manchester slicer; tx: bits → OOK burst).

rx chain, as REAL blocks on the seify file-replay HAL (``hw/__init__.py``):

    SeifySource(driver=file) → Apply(|x|) [envelope] → Fir(lowpass) →
    VectorSink → host Manchester slicer (``models/misc.ook_demodulate``)

tx chain:

    ook_modulate(bits) × carrier → FileSink (a cf32 burst any SDR could play)

With no ``--input``, the script first runs its OWN tx to a temp capture
(default key code 0xA53C96, 24 bits), then decodes it back and checks the
bits — a self-validating loopback.

Run: ``python examples/keyfob_rx.py``                    (tx → rx loopback)
     ``python examples/keyfob_rx.py --input burst.cf32`` (decode a capture)
     ``python examples/keyfob_rx.py tx --out burst.cf32``
"""

import argparse
import sys
import tempfile

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Apply, FileSink, Fir, SeifyBuilder, VectorSink, \
    VectorSource
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.models.misc import ook_demodulate, ook_modulate
from futuresdr_tpu.utils.backend import ensure_backend


def key_bits(code: int, n_bits: int) -> np.ndarray:
    return np.array([(code >> (n_bits - 1 - i)) & 1 for i in range(n_bits)],
                    dtype=np.uint8)


def run_tx(out_path: str, code: int, n_bits: int, fs: float, bit_rate: float,
           carrier: float) -> None:
    """bits → Manchester OOK envelope → carrier burst → cf32 file."""
    env = ook_modulate(key_bits(code, n_bits), fs, bit_rate)
    t = np.arange(len(env)) / fs
    iq = (env * np.exp(2j * np.pi * carrier * t)).astype(np.complex64)
    pad = np.zeros(int(fs * 0.002), np.complex64)          # leading silence
    fg = Flowgraph()
    fg.connect(VectorSource(np.concatenate([pad, iq, pad])),
               FileSink(out_path, np.complex64))
    Runtime().run(fg)
    print(f"# tx: {n_bits}-bit code 0x{code:X} → {out_path}")


def run_rx(in_path: str, n_bits: int, fs: float, bit_rate: float):
    """Replay the capture through the envelope chain; slice on the host."""
    fg = Flowgraph()
    src = (SeifyBuilder()
           .args(f"driver=file,path={in_path},repeat=false,throttle=false")
           .sample_rate(fs).build_source())
    envelope = Apply(lambda x: np.abs(x).astype(np.float32),
                     np.complex64, np.float32)
    # smooth over ~1/4 bit period: kills carrier ripple, keeps edges sharp
    n_taps = max(8, int(fs / bit_rate) // 4) | 1
    lp = Fir(firdes.lowpass(1.5 * bit_rate / fs, n_taps).astype(np.float32),
             np.float32)
    vs = VectorSink(np.float32)
    fg.connect(src, envelope, lp, vs)
    Runtime().run(fg)
    env = vs.items()
    print(f"# rx: {len(env)} envelope samples")
    return ook_demodulate(env, fs, bit_rate, n_bits)


def main(argv=None):
    p = argparse.ArgumentParser(description="keyfob OOK tx/rx on the file-replay HAL")
    p.add_argument("mode", nargs="?", choices=("rx", "tx"), default="rx")
    p.add_argument("--input", default=None, help="cf32 capture to decode "
                   "(default: synthesize via the tx path first)")
    p.add_argument("--out", default=None, help="tx: write the burst here")
    p.add_argument("--code", type=lambda s: int(s, 0), default=0xA53C96)
    p.add_argument("--bits", type=int, default=24)
    p.add_argument("--rate", type=float, default=250e3)
    p.add_argument("--bit-rate", type=float, default=1000.0)
    p.add_argument("--carrier", type=float, default=20e3,
                   help="carrier offset inside the capture")
    a = p.parse_args(argv)
    ensure_backend()

    if a.mode == "tx":
        run_tx(a.out or "keyfob_burst.cf32", a.code, a.bits, a.rate,
               a.bit_rate, a.carrier)
        return 0

    loopback = a.input is None
    tmp_path = None
    try:
        if loopback:
            tmp = tempfile.NamedTemporaryFile(suffix=".cf32", delete=False)
            run_tx(tmp.name, a.code, a.bits, a.rate, a.bit_rate, a.carrier)
            a.input = tmp_path = tmp.name

        bits = run_rx(a.input, a.bits, a.rate, a.bit_rate)
        if bits is None:
            print("# no keyfob burst found")
            return 1
        code = int("".join(map(str, bits)), 2)
        print(f"# decoded {a.bits}-bit code: 0x{code:X}")
        if loopback:
            assert code == a.code, \
                f"loopback mismatch: 0x{code:X} != 0x{a.code:X}"
            print("# loopback OK: code round-tripped")
        return 0
    finally:
        if tmp_path is not None:
            import os
            try:
                os.unlink(tmp_path)
            except OSError:
                pass


if __name__ == "__main__":
    sys.exit(main())
