#!/usr/bin/env python
"""SSB receiver (reference: ``examples/ssb/src/main.rs`` — file replay → SSB
product detector → audio).

The chain runs as REAL blocks on the seify HAL's file-replay driver (the same
path a live SDR would take; ``hw/__init__.py`` FileDriver):

    SeifySource(driver=file) → XlatingFir(BFO shift + analytic bandpass,
    decim) → Apply(real) [product detector] → Agc → WavSink / AudioSink

The XlatingFir rotates the BFO to DC and applies a one-sided 300..3000 Hz
analytic bandpass, so only the chosen sideband survives; taking the real
part is the product detector — the block twin of
``models/misc.ssb_demodulate``.

With no ``--input``, a two-tone USB test transmission (700 + 1900 Hz) is
synthesized to a temp file and demodulated back; the script then checks the
recovered audio spectrum peaks at those tones (a self-validating loopback).

Run: ``python examples/ssb_rx.py --wav /tmp/ssb.wav``
     ``python examples/ssb_rx.py --input capture.cf32 --bfo 12000 --sideband lsb``
"""

import argparse
import sys
import tempfile

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Agc, Apply, SeifyBuilder, VectorSink, WavSink, \
    XlatingFir
from futuresdr_tpu.utils.backend import ensure_backend


def sideband_taps(fs: float, sideband: str, audio_bw: float,
                  n_taps: int = 257) -> np.ndarray:
    """Analytic (one-sided) bandpass selecting [300, audio_bw] Hz (USB) or the
    mirror (LSB) at BASEBAND — applied after the XlatingFir's BFO rotation;
    the Hamming-windowed design of `models/misc.py:98`."""
    lo, hi = (300.0, audio_bw) if sideband == "usb" else (-audio_bw, -300.0)
    f1, f2 = sorted((lo / fs, hi / fs))
    k = np.arange(n_taps) - (n_taps - 1) / 2
    h = (np.exp(2j * np.pi * f2 * k) - np.exp(2j * np.pi * f1 * k)) / \
        (2j * np.pi * k + 1e-30)
    h[(n_taps - 1) // 2] = 2 * np.pi * (f2 - f1) / (2 * np.pi)
    h *= np.hamming(n_taps)
    return h.astype(np.complex64)


def synthesize_usb(fs: float, bfo: float, seconds: float,
                   tones=(700.0, 1900.0)) -> np.ndarray:
    """Two-tone USB transmission at the BFO offset (upper sideband only:
    analytic tones e^{j2πft} translated by the BFO)."""
    t = np.arange(int(fs * seconds)) / fs
    sig = sum(np.exp(2j * np.pi * (bfo + f) * t) for f in tones)
    sig = sig / np.abs(sig).max() * 0.5
    noise = (np.random.default_rng(9).standard_normal((len(t), 2)) @
             np.array([1, 1j])) * 0.01
    return (sig + noise).astype(np.complex64)


def main(argv=None):
    p = argparse.ArgumentParser(description="SSB receiver on the file-replay HAL")
    p.add_argument("--input", default=None, help="cf32 IQ capture (default: "
                   "synthesize a two-tone USB test signal)")
    p.add_argument("--rate", type=float, default=256e3)
    p.add_argument("--bfo", type=float, default=12e3,
                   help="carrier offset of the SSB signal in the capture")
    p.add_argument("--sideband", choices=("usb", "lsb"), default="usb")
    p.add_argument("--audio-bw", type=float, default=3000.0)
    p.add_argument("--decim", type=int, default=4)
    p.add_argument("--wav", default=None, help="write demodulated audio here")
    p.add_argument("--audio", action="store_true",
                   help="play via the soundcard (AudioSink) instead of a WAV")
    a = p.parse_args(argv)
    ensure_backend()

    synthesized = a.input is None
    tmp_path = None
    if synthesized:
        tmp = tempfile.NamedTemporaryFile(suffix=".cf32", delete=False)
        synthesize_usb(a.rate, a.bfo, 0.6).tofile(tmp.name)
        a.input = tmp_path = tmp.name
        print(f"# no --input: synthesized two-tone USB test signal → {a.input}")

    try:
        return _run(a, synthesized)
    finally:
        if tmp_path is not None:
            import os
            try:
                os.unlink(tmp_path)
            except OSError:
                pass


def _run(a, synthesized: bool) -> int:
    fs_audio = a.rate / a.decim
    fg = Flowgraph()
    src = (SeifyBuilder()
           .args(f"driver=file,path={a.input},repeat=false,throttle=false")
           .sample_rate(a.rate).build_source())
    bp = XlatingFir(sideband_taps(a.rate, a.sideband, a.audio_bw),
                    decim=a.decim, offset_freq=a.bfo, sample_rate=a.rate)
    detector = Apply(lambda x: x.real.astype(np.float32) * 2.0,
                     np.complex64, np.float32)
    agc = Agc(np.float32, reference=0.3, adjustment_rate=1e-2, mode="block")
    probe = VectorSink(np.float32)
    fg.connect(src, bp, detector, agc)
    if a.audio:
        from futuresdr_tpu.blocks import AudioSink
        fg.connect(agc, AudioSink(int(fs_audio)))
    else:
        wav = a.wav or "ssb_audio.wav"
        fg.connect(agc, WavSink(wav, int(fs_audio)))
    fg.connect_stream(agc, "out", probe, "in")       # analysis tap
    Runtime().run(fg)

    audio = probe.items()
    print(f"# demodulated {len(audio)} audio samples at {fs_audio:.0f} Hz")
    if len(audio) > 1024:
        spec = np.abs(np.fft.rfft(audio[1024:] * np.hanning(len(audio) - 1024)))
        freqs = np.fft.rfftfreq(len(audio) - 1024, 1.0 / fs_audio)
        top = freqs[np.argsort(spec)[-6:]]
        peaks = sorted(set(round(f / 50) * 50 for f in top))
        print(f"# dominant audio tones (Hz, 50 Hz bins): {peaks}")
        if synthesized:
            for want in (700.0, 1900.0):
                assert any(abs(f - want) <= 50 for f in top), \
                    f"expected {want} Hz tone missing from {sorted(top)}"
            print("# loopback OK: both test tones recovered")
    return 0


if __name__ == "__main__":
    sys.exit(main())
