#!/usr/bin/env python
"""Rattlegram acoustic modem loopback: OFDM PSK over an "audio" channel.

Reference role: ``examples/rattlegram``. Text payloads ride the 48-carrier OFDM audio
waveform with the reference's FEC family (BCH-protected header, polar-coded payload
with list-SCL decoding + OSD fallback); the channel adds gain mismatch and noise.
"""
import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Pmt, Runtime
from futuresdr_tpu.blocks import Apply
from futuresdr_tpu.models.rattlegram import ModemReceiver, ModemTransmitter


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--messages", type=int, default=3)
    p.add_argument("--payload-size", type=int, default=48)
    p.add_argument("--noise", type=float, default=0.01)
    a = p.parse_args()

    rng = np.random.default_rng(3)
    fg = Flowgraph()
    tx = ModemTransmitter(payload_size=a.payload_size)
    chan = Apply(lambda x: (0.5 * x + a.noise * rng.standard_normal(len(x))
                            ).astype(np.float32), np.float32)
    rx = ModemReceiver(payload_size=a.payload_size)
    fg.connect(tx, chan, rx)

    payloads = [f"over-the-air text {i}".encode() for i in range(a.messages)]
    rt = Runtime()
    running = rt.start(fg)
    for pl in payloads:
        r = rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.blob(pl)))
        assert r == Pmt.ok()
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()

    print(f"decoded {len(rx.frames)}/{a.messages} payloads:")
    for f in rx.frames:
        print(f"  {f!r}")
    assert rx.frames == payloads


if __name__ == "__main__":
    main()
