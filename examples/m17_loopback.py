#!/usr/bin/env python
"""M17 loopback: LSF frames → 4FSK baseband → noisy channel → RX.

Reference role: ``examples/m17`` (the reference's M17 example crate). Messages go in on
the transmitter's ``tx`` message port; decoded link-setup frames come back on the
receiver's ``rx`` port and are printed.
"""
import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Pmt, Runtime
from futuresdr_tpu.blocks import Apply
from futuresdr_tpu.models.m17 import M17Receiver, M17Transmitter


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=3)
    p.add_argument("--snr-noise", type=float, default=0.05,
                   help="additive noise sigma on the 4FSK baseband")
    p.add_argument("--src", default="N0CALL")
    a = p.parse_args()

    rng = np.random.default_rng(7)
    fg = Flowgraph()
    tx = M17Transmitter(src_callsign=a.src)
    chan = Apply(lambda x: (x + a.snr_noise * rng.standard_normal(len(x))
                            ).astype(np.float32), np.float32)
    rx = M17Receiver()
    fg.connect(tx, chan, rx)

    rt = Runtime()
    running = rt.start(fg)
    for i in range(a.frames):
        msg = Pmt.map({"dst": "@ALL", "src": a.src,
                       "meta": Pmt.blob(f"beacon {i}".ljust(14).encode())})
        r = rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", msg))
        assert r == Pmt.ok()
    # stream mode: a payload blob rides LICH-chunked frames after the LSF
    payload = b"M17 stream-mode payload over the air"
    r = rt.scheduler.run_coro_sync(running.handle.call(
        tx, "tx", Pmt.map({"dst": "SP5WWP", "payload": Pmt.blob(payload)})))
    assert r == Pmt.ok()
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()

    print(f"decoded {len(rx.frames)}/{a.frames + 1} LSFs:")
    for f in rx.frames:
        print(f"  {f.src} -> {f.dst}  meta={f.meta!r}")
    assert len(rx.frames) >= a.frames
    print(f"stream transmissions: {len(rx.transmissions)}")
    for lsf, pl in rx.transmissions:
        print(f"  {lsf.src if lsf else '?'} -> {lsf.dst if lsf else '?'}: {pl!r}")
    assert len(rx.transmissions) == 1
    assert rx.transmissions[0][1][:len(payload)] == payload


if __name__ == "__main__":
    main()
