#!/usr/bin/env python
"""WLAN loopback: TX → noisy channel → RX inside one flowgraph
(reference: examples/wlan/src/bin/loopback.rs)."""

import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import Apply
from futuresdr_tpu.models.wlan import WlanEncoder, WlanDecoder


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=10)
    p.add_argument("--mcs", default="qpsk_1_2")
    p.add_argument("--noise", type=float, default=0.02)
    a = p.parse_args()

    rng = np.random.default_rng(0)
    fg = Flowgraph()
    enc = WlanEncoder(a.mcs)
    chan = Apply(lambda x: (x + a.noise * (rng.standard_normal(len(x))
                                           + 1j * rng.standard_normal(len(x)))
                            ).astype(np.complex64), np.complex64)
    dec = WlanDecoder()
    fg.connect(enc, chan, dec)

    rt = Runtime()
    running = rt.start(fg)
    sent = [f"hello wlan frame {i} ".encode() * 4 for i in range(a.frames)]
    for s in sent:
        rt.scheduler.run_coro_sync(running.handle.call(enc, "tx", Pmt.blob(s)))
    rt.scheduler.run_coro_sync(running.handle.call(enc, "tx", Pmt.finished()))
    running.wait_sync()
    ok = sum(1 for s, r in zip(sent, dec.frames) if s == r)
    print(f"{ok}/{a.frames} frames decoded correctly ({a.mcs}, noise={a.noise})")


if __name__ == "__main__":
    main()
