#!/usr/bin/env python
"""LoRa loopback: chirp TX → noisy channel → RX (reference: examples/lora)."""

import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import Apply
from futuresdr_tpu.models.lora import LoraParams, LoraTransmitter, LoraReceiver


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--frames", type=int, default=8)
    p.add_argument("--sf", type=int, default=7)
    p.add_argument("--cr", type=int, default=2)
    p.add_argument("--noise", type=float, default=0.2)
    a = p.parse_args()

    params = LoraParams(sf=a.sf, cr=a.cr)
    rng = np.random.default_rng(0)
    fg = Flowgraph()
    tx = LoraTransmitter(params)
    chan = Apply(lambda x: (x + a.noise * (rng.standard_normal(len(x))
                                           + 1j * rng.standard_normal(len(x)))
                            ).astype(np.complex64), np.complex64)
    rx = LoraReceiver(params)
    fg.connect(tx, chan, rx)

    rt = Runtime()
    running = rt.start(fg)
    sent = [f"lora sf{a.sf} payload {i}".encode() for i in range(a.frames)]
    for s in sent:
        rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.blob(s)))
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()
    ok = len(set(sent) & set(rx.frames))
    print(f"{ok}/{a.frames} frames decoded (SF{a.sf} CR4/{4+a.cr}, noise={a.noise}); "
          f"CRC ok: {sum(rx.crc_flags)}")


if __name__ == "__main__":
    main()
