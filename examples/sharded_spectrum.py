#!/usr/bin/env python
"""Sequence-parallel spectrum over a device mesh — the multi-chip showcase.

One logical stream is TIME-SHARDED across every device on the mesh: each shard
filters its slice (halo samples ride ``ppermute`` from the left neighbour, so
the FIR is exact across shard edges and frame edges), FFTs locally, and the
|x|² spectra come back still sharded. On real hardware the halo crosses ICI;
here an 8-device virtual CPU mesh demonstrates the identical program
(``XLA_FLAGS=--xla_force_host_platform_device_count=8`` is set below).

Reference role: this is the distribution story the reference delegates to
ZMQ/TCP blocks between processes (``examples/zeromq``), re-designed as ONE
sharded XLA program over the mesh (SURVEY §2.7 sequence parallelism).

Run: ``python examples/sharded_spectrum.py [--devices 8] [--frames 32]``
"""
import argparse
import os
import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "..")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--frames", type=int, default=32)
    p.add_argument("--fft", type=int, default=1024)
    p.add_argument("--frame-size", type=int, default=1 << 18)
    a = p.parse_args()

    # virtual mesh BEFORE jax init (no-op when the flag is already set)
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            f"{flags} --xla_force_host_platform_device_count={a.devices}".strip()

    import jax
    from futuresdr_tpu.tpu.instance import force_cpu_platform
    force_cpu_platform()
    import jax.numpy as jnp
    import numpy as np
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.parallel import (NamedSharding, P, make_mesh,
                                        sp_fir_fft_mag2_stream)

    n_dev = min(a.devices, len(jax.devices()))
    mesh = make_mesh(("sp",), shape=(n_dev,), devices=jax.devices()[:n_dev])
    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    fn, init_carry = sp_fir_fft_mag2_stream(taps, a.fft, mesh)
    jfn = jax.jit(fn, donate_argnums=(0,))

    n = a.frame_size - (a.frame_size % (n_dev * a.fft))
    rng = np.random.default_rng(0)
    shard = NamedSharding(mesh, P("sp"))
    carry = init_carry(np.float32)

    # pre-generate frames OUTSIDE the timed window — the measurement is the
    # sharded mesh program, not host RNG + transfer (a small rotating pool so
    # XLA can't constant-fold a single repeated input)
    pool = [jax.device_put(rng.standard_normal(n).astype(np.float32), shard)
            for _ in range(4)]
    carry, y = jfn(carry, pool[0])        # warm/compile
    jax.block_until_ready(y)
    t0 = time.perf_counter()
    for k in range(a.frames):
        carry, y = jfn(carry, pool[k % len(pool)])
    jax.block_until_ready(y)
    dt = time.perf_counter() - t0

    spec = np.asarray(y).reshape(-1, a.fft)
    print(f"mesh: {n_dev} devices ('sp' axis), frame {n} samples, "
          f"{a.frames} frames")
    print(f"throughput: {a.frames * n / dt / 1e6:.1f} Msamples/s "
          f"({a.frames * n / dt / 1e6 / n_dev:.1f} per shard)")
    print(f"spectra: {spec.shape[0]} x {a.fft} bins, "
          f"peak bin power {spec.max():.1f}")


if __name__ == "__main__":
    main()
