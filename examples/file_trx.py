#!/usr/bin/env python
"""File record/playback utility (reference: examples/file-trx/{tx,rx}.rs).

Same shape as the reference: the IQ file format is sniffed from the extension
(``cs8`` = interleaved complex int8, ``cf32`` = complex float32), with
``--format-in/--format-out`` overrides; a power meter taps the stream and
warns about clipping (|x| > 0.95) while printing running average/max
magnitudes; ``--samples`` bounds a recording via Head.

    rx:  [seify source | --input FILE] → powermeter → FILE (format-converted)
    tx:  FILE → (format convert) → seify sink

Run: ``python examples/file_trx.py rx --out /tmp/capture.cf32 --samples 100000``
     ``python examples/file_trx.py tx --input /tmp/capture.cf32``
"""

import argparse
import sys
import time

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Apply, FileSink, FileSource, Head, SeifyBuilder

FORMATS = ("cs8", "cf32")


def sniff(path: str, override) -> str:
    fmt = override or path.rsplit(".", 1)[-1]
    if fmt not in FORMATS:
        raise SystemExit(f"Unrecognized format {fmt!r} (known: {FORMATS})")
    return fmt


def cs8_to_cf32() -> Apply:
    # interleaved i8 pairs → complex64 (the reference's per-item Apply,
    # vectorized: the stream dtype is the raw i8 pair viewed as int16)
    def cvt(x):
        pairs = x.view(np.int8).astype(np.float32).reshape(-1, 2) / 127.0
        return (pairs[:, 0] + 1j * pairs[:, 1]).astype(np.complex64)
    return Apply(cvt, np.int16, np.complex64)


def cf32_to_cs8() -> Apply:
    def cvt(x):
        out = np.empty((len(x), 2), np.int8)
        out[:, 0] = np.clip(x.real * 127.0, -127, 127)
        out[:, 1] = np.clip(x.imag * 127.0, -127, 127)
        return out.view(np.int16).reshape(-1)
    return Apply(cvt, np.complex64, np.int16)


def file_iq_source(fg: Flowgraph, path: str, fmt: str, repeat: bool):
    """FileSource (+ cs8 conversion) wired into fg; returns the cf32 tail."""
    if fmt == "cs8":
        src = FileSource(path, np.int16, repeat=repeat)
        cvt = cs8_to_cf32()
        fg.connect(src, cvt)
        return cvt
    return FileSource(path, np.complex64, repeat=repeat)


def power_meter() -> Apply:
    state = {"avg": 0.0, "max": 0.0, "t_clip": 0.0, "t_print": time.monotonic()}

    def meter(x):
        mags = np.abs(x)
        now = time.monotonic()
        if mags.size:
            if float(mags.max()) > 0.95 and now - state["t_clip"] > 0.1:
                state["t_clip"] = now
                print("Possible clipping!", file=sys.stderr)
            # same exponential average the reference keeps per sample
            state["avg"] = float(state["avg"] * (0.9999 ** mags.size)
                                 + mags.mean() * (1 - 0.9999 ** mags.size))
            state["max"] = max(state["max"], float(mags.max()))
        if now - state["t_print"] > 2.0:
            print(f"Average/max signal magnitudes: "
                  f"{state['avg']:.4f}/{state['max']:.4f}")
            state["max"] = 0.0
            state["t_print"] = now
        return x
    return Apply(meter, np.complex64, np.complex64)


def main(argv=None):
    p = argparse.ArgumentParser(description="file record/playback (file-trx)")
    p.add_argument("mode", choices=("tx", "rx"))
    p.add_argument("--args", default="driver=dummy,throttle=false")
    p.add_argument("-f", "--frequency", type=float, default=100e6)
    p.add_argument("-s", "--sample-rate", type=float, default=1e6)
    p.add_argument("-g", "--gain", type=float, default=0.0)
    p.add_argument("--input", default=None)
    p.add_argument("--format-in", default=None, choices=FORMATS)
    p.add_argument("--out", default=None)
    p.add_argument("--format-out", default=None, choices=FORMATS)
    p.add_argument("--samples", type=int, default=None,
                   help="bound the recording (continuous if omitted)")
    p.add_argument("--repeat", action="store_true")
    a = p.parse_args(argv)

    fg = Flowgraph()
    if a.mode == "tx":
        if not a.input:
            raise SystemExit("tx needs --input")
        last = file_iq_source(fg, a.input, sniff(a.input, a.format_in),
                              a.repeat)
        snk = (SeifyBuilder().args(a.args).frequency(a.frequency)
               .sample_rate(a.sample_rate).gain(a.gain).build_sink())
        fg.connect(last, snk)
        Runtime().run(fg)
        return

    # rx: record from a seify source (or transcode from --input)
    if not a.out:
        raise SystemExit("rx needs --out")
    if a.input:
        last = file_iq_source(fg, a.input, sniff(a.input, a.format_in),
                              a.repeat)
    else:
        last = (SeifyBuilder().args(a.args).frequency(a.frequency)
                .sample_rate(a.sample_rate).gain(a.gain).build_source())
    if a.samples is not None:
        head = Head(np.complex64, a.samples)
        fg.connect(last, head)
        last = head
    meter = power_meter()
    fg.connect(last, meter)
    fmt_out = sniff(a.out, a.format_out)
    if fmt_out == "cs8":
        cvt = cf32_to_cs8()
        snk = FileSink(a.out, np.int16)
        fg.connect(meter, cvt, snk)
    else:
        snk = FileSink(a.out, np.complex64)
        fg.connect(meter, snk)
    Runtime().run(fg)
    print(f"wrote {snk.n_written} items to {a.out}")


if __name__ == "__main__":
    main()
