#!/usr/bin/env python
"""ADS-B receiver over a magnitude stream (reference: examples/adsb binaries).

With no input file, synthesizes a stream carrying the published Mode S test frames.
"""

import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import FileSource, VectorSource
from futuresdr_tpu.models.adsb import AdsbReceiver, modulate_frame


def synth_stream() -> np.ndarray:
    frames = ["8D4840D6202CC371C32CE0576098",      # KLM1023 ident
              "8D40621D58C382D690C8AC2863A7",      # position even
              "8D40621D58C386435CC412692AD6",      # position odd
              "8D485020994409940838175B284F"]      # velocity
    rng = np.random.default_rng(0)
    parts = []
    for h in frames:
        bits = np.unpackbits(np.frombuffer(bytes.fromhex(h), np.uint8)).astype(np.uint8)
        parts += [0.03 * rng.random(1000).astype(np.float32), modulate_frame(bits)]
    parts.append(0.03 * rng.random(500).astype(np.float32))
    return np.concatenate(parts)


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--file", default=None, help="float32 magnitude stream @2 Msps")
    a = p.parse_args()

    fg = Flowgraph()
    src = FileSource(a.file, np.float32) if a.file else VectorSource(synth_stream())
    rx = AdsbReceiver()
    fg.connect_stream(src, "out", rx, "in")
    Runtime().run(fg)
    print(f"decoded {rx.n_frames} frames; aircraft:")
    for ac in rx.tracker.aircraft.values():
        print(f"  {ac.icao:06X} callsign={ac.callsign} alt={ac.altitude_ft} "
              f"pos=({ac.lat}, {ac.lon}) gs={ac.ground_speed_kt}")


if __name__ == "__main__":
    main()
