#!/usr/bin/env python
"""ADS-B receiver over a magnitude stream (reference: examples/adsb binaries).

With no input file, synthesizes a stream carrying the published Mode S test frames.
"""

import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import FileSource, VectorSource
from futuresdr_tpu.models.adsb import AdsbReceiver, modulate_frame


def _df11(icao: int) -> np.ndarray:
    """Parity-consistent DF11 all-call so the AP-overlay replies get through
    the tracker's acquisition gate."""
    from futuresdr_tpu.models.adsb.decoder import crc24
    head = np.zeros(32, dtype=np.uint8)
    head[0:5] = [0, 1, 0, 1, 1]
    head[8:32] = [(icao >> (23 - i)) & 1 for i in range(24)]
    rem = crc24(np.concatenate([head, np.zeros(24, np.uint8)]))
    return np.concatenate([head, np.array([(rem >> (23 - i)) & 1
                                           for i in range(24)], np.uint8)])


def synth_stream() -> np.ndarray:
    frames = ["8D4840D6202CC371C32CE0576098",      # KLM1023 ident
              "8D40621D58C382D690C8AC2863A7",      # position even
              "8D40621D58C386435CC412692AD6",      # position odd
              "8D485020994409940838175B284F",      # velocity
              _df11(0x4CA7E8),                     # all-call: acquire 4CA7E8
              "2000171806A983",                    # DF4 altitude (AP icao 4CA7E8)
              "2A00516D492B80"]                    # DF5 squawk — foreign icao: gated
    rng = np.random.default_rng(0)
    parts = []
    for f in frames:
        bits = (f if isinstance(f, np.ndarray) else
                np.unpackbits(np.frombuffer(bytes.fromhex(f), np.uint8)).astype(np.uint8))
        parts += [0.03 * rng.random(1000).astype(np.float32), modulate_frame(bits)]
    parts.append(0.03 * rng.random(500).astype(np.float32))
    return np.concatenate(parts)


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--file", default=None, help="float32 magnitude stream @2 Msps")
    p.add_argument("--ref-pos", default="52.25,3.92",
                   help="receiver site lat,lon for single-message CPR "
                        "(empty string disables)")
    a = p.parse_args()

    ref = tuple(float(v) for v in a.ref_pos.split(",")) if a.ref_pos else None
    fg = Flowgraph()
    src = FileSource(a.file, np.float32) if a.file else VectorSource(synth_stream())
    rx = AdsbReceiver(ref_pos=ref)
    fg.connect_stream(src, "out", rx, "in")
    Runtime().run(fg)
    print(f"decoded {rx.n_frames} frames; aircraft:")
    for ac in rx.tracker.aircraft.values():
        print(f"  {ac.icao:06X} callsign={ac.callsign} squawk={ac.squawk} "
              f"alt={ac.altitude_ft} pos=({ac.lat}, {ac.lon}) "
              f"gs={ac.ground_speed_kt}")


if __name__ == "__main__":
    main()
