#!/usr/bin/env python
"""Morse beacon: text → CW audio (WAV) and back (reference: examples/cw)."""

import sys

sys.path.insert(0, ".")
sys.path.insert(0, "..")

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSource, WavSink
from futuresdr_tpu.models.misc import cw_modulate, cw_demodulate


def main():
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("text", nargs="?", default="CQ CQ DE FUTURESDR TPU K")
    p.add_argument("--wav", default="/tmp/cw.wav")
    p.add_argument("--wpm", type=float, default=20.0)
    p.add_argument("--tone", type=float, default=600.0)
    a = p.parse_args()

    fs = 8000.0
    audio = cw_modulate(a.text, a.tone, fs, a.wpm)
    fg = Flowgraph()
    fg.connect(VectorSource(audio), WavSink(a.wav, int(fs)))
    Runtime().run(fg)
    print(f"wrote {a.wav}; decoding back:")
    print(" ", cw_demodulate(audio, fs, a.wpm))


if __name__ == "__main__":
    main()
