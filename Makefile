# Developer conveniences; see check.sh for the full health check.

.PHONY: test native tsan check bench perf clean

test:
	python -m pytest tests/ -q

native:
	$(MAKE) -C native test

tsan:
	$(MAKE) -C native test-tsan

check:
	bash check.sh

bench:
	python bench.py

perf:
	python perf/fir.py --runs 1
	python perf/null.py --runs 1
	python perf/msg.py --runs 1

clean:
	$(MAKE) -C native clean
	find . -name __pycache__ -type d -exec rm -rf {} + 2>/dev/null || true
