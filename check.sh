#!/bin/bash
# Repo health check (reference: check.sh — fmt/clippy/test across targets).
# Runs: native C++ tests, the Python suite on the virtual 8-device CPU mesh, and the
# driver entry validation (single-chip compile + multi-chip sharding dry-run).
set -e
cd "$(dirname "$0")"

echo "== native =="
make -C native test

echo "== telemetry overhead gate (docs/observability.md budget) =="
JAX_PLATFORMS=cpu python -m pytest -q \
    tests/test_telemetry.py::test_telemetry_disabled_overhead_null_rand

echo "== profile plane smoke (docs/observability.md 'The profile plane') =="
# a warmed streamed run bills exactly ONE warmup compile and ZERO
# steady-state fsdr_compiles_total increments; the live mfu stamp is
# present (config peak overrides exercise the unknown-chip path); serving
# bucket compiles bill once per resident bucket, never per step
JAX_PLATFORMS=cpu python perf/profile_smoke.py --smoke

echo "== device-graph fusion gate (docs/tpu_notes.md 'Device-graph fusion') =="
# fused A/B smoke: the linear pass engages (dispatches drop 3x -> 1x per
# frame), the fan-out pass engages (1->2 broadcast region: H2D bytes bill
# exactly ONE upload per marginal frame via fsdr_xfer_bytes_total, one
# multi-output dispatch per frame, replayed-link throughput win), AND the
# general-DAG pass engages (diamond broadcast->merge + nested fan-out:
# dispatches/frame == 1 with interior-edge D2H bytes == 0 — the fused side's
# marginal D2H equals exactly the sink payloads)
JAX_PLATFORMS=cpu python perf/devchain_ab.py --smoke
# fusion equality tests, then the DECLINED mode (FSDR_NO_DEVCHAIN=1) over the
# device-plane suite: the per-hop fallback must stand alone
JAX_PLATFORMS=cpu python -m pytest -q tests/test_devchain.py
FSDR_NO_DEVCHAIN=1 JAX_PLATFORMS=cpu python -m pytest -q \
    tests/test_devchain.py tests/test_tpu_stages.py tests/test_tpu_tags.py \
    tests/test_tpu_frames.py tests/test_retune.py

echo "== host data path gate (docs/tpu_notes.md 'The host data path') =="
# deterministic fake-link replay: the staging arena's steady-state allocation
# count is O(1) per frame class (misses flat over a sustained window) and the
# streamed utilization with arena + codec pool + credit controller armed is
# no worse than the pre-arena baseline
JAX_PLATFORMS=cpu python perf/hostpath_ab.py --smoke

echo "== single-shot uplink gate (docs/tpu_notes.md 'The single-shot uplink') =="
# coalesced H2D: a quantizing-wire streamed chain bills exactly ONE physical
# h2d start per dispatch group (payload + scale ride one packed buffer) and
# stays bit-identical to the per-part path; zero-copy ingest: a registered
# read-only capture over the aliasing (f32) wire skips every ring-exit copy
# (frac == 1.0). The dedicated suite behind it carries the rest (packed
# replay/fault bit-equality, deferred consume, adaptive wire switching,
# autotune wire axis).
JAX_PLATFORMS=cpu python - <<'EOF'
import numpy as np
from futuresdr_tpu import Mocker
from futuresdr_tpu.config import config
from futuresdr_tpu.ops import fir_stage, rotator_stage
from futuresdr_tpu.ops import ingest, xfer
from futuresdr_tpu.tpu import TpuKernel

FS = 2048
rng = np.random.default_rng(7)
n = FS * 8
data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)) \
    .astype(np.complex64)
taps = rng.standard_normal(33).astype(np.float32)

def run(wire, coalesce=True, register=False):
    config().tpu_coalesce = coalesce
    if register:
        ingest.register(data, name="gate-capture")
    tk = TpuKernel([fir_stage(taps, fft_len=256), rotator_stage(0.05)],
                   np.complex64, frame_size=FS, frames_in_flight=2,
                   wire=wire)
    m = Mocker(tk)
    m.input("in", data)
    m.init_output("out", n * 2)
    m.init()                   # compile + cost-model probes bill separately
    s0 = xfer._XFER_STARTS.get(direction="h2d")
    m.run()
    starts = xfer._XFER_STARTS.get(direction="h2d") - s0
    out = m.output("out").copy()
    em = tk.extra_metrics()
    ingest.reset()
    config().tpu_coalesce = True
    return out, starts, em

groups = 8
a, sa, ema = run("sc16", coalesce=True)
b, sb, emb = run("sc16", coalesce=False)
np.testing.assert_array_equal(a, b)
assert ema["uplink_coalesced"] == 1 and ema["h2d_starts_per_frame"] == 1, ema
assert sa == groups, f"packed chain billed {sa} h2d starts / {groups} groups"
assert sb == 2 * groups, sb
_, _, emc = run("f32", register=True)
assert emc["ingest_zero_copy_frac"] == 1.0, emc
print(f"uplink gate: {sa} h2d starts / {groups} groups packed (vs {sb} "
      f"per-part, bit-identical), ingest zero-copy frac "
      f"{emc['ingest_zero_copy_frac']}: OK")
EOF
JAX_PLATFORMS=cpu python -m pytest -q tests/test_uplink.py

echo "== interior precision gate (docs/tpu_notes.md 'Interior precision') =="
# SNR-budgeted lowering correctness: interior_precision=off is BIT-identical
# (same program object, same bits), the auto plan lowers the resident
# fir64+fft2048 chain with every MEASURED per-edge SNR over the budget and
# the end-to-end output inside budget − incoherent-sum allowance, and the
# fused Pallas PFB / FIR→decimate kernels match the matmul paths they replace
JAX_PLATFORMS=cpu python perf/precision_ab.py --smoke

echo "== pallas autotune cache gate (docs/tpu_notes.md 'Pallas autotune plane') =="
# streamed-pick cache round-trip for the pallas_blocks axis: recorded block
# winners survive a streamed k/inflight re-record, a malformed axis on disk
# loses ONLY itself (per-axis guarded parse — the k pick survives), and a
# second autotune_pallas_blocks call is a cache hit that skips the sweep
JAX_PLATFORMS=cpu python - <<'EOF'
import importlib, json, os, tempfile
td = tempfile.mkdtemp()
os.environ["FUTURESDR_TPU_AUTOTUNE_CACHE_DIR"] = td
import numpy as np
from futuresdr_tpu.ops.stages import fir_stage, mag2_stage, Pipeline
from futuresdr_tpu.ops import pallas_kernels as pk
at = importlib.import_module("futuresdr_tpu.tpu.autotune")
pallas_tune = importlib.import_module("futuresdr_tpu.tpu.pallas_tune")

taps = np.random.default_rng(0).standard_normal(33).astype(np.float32)
P = Pipeline([fir_stage(taps), mag2_stage()], np.complex64)

# record (junk keys dropped at the gate) + read back, per-device-kind keyed
at.record_pallas_blocks(P.stages, P.in_dtype, "cpu", "v5e",
                        {"fir": 2048, "bogus": 7, "pfb": -1})
assert at.cached_pallas_blocks(P.stages, P.in_dtype, "cpu", "v5e") == \
    {"fir": 2048}
assert at.cached_pallas_blocks(P.stages, P.in_dtype, "cpu", "v5p") is None

# axis survives a streamed k/inflight re-record on the same signature
at.record_streamed_pick(P.stages, P.in_dtype, "cpu", 4, inflight=2)
assert at.cached_pallas_blocks(P.stages, P.in_dtype, "cpu", "v5e") == \
    {"fir": 2048}
e = at.cached_streamed_pick(P.stages, P.in_dtype, "cpu")
assert e["k"] == 4 and e["inflight"] == 2, e

# disk round-trip through a cleared memo (a fresh process would see this)
at._disk_memo.clear(); at._streamed_cache.clear()
assert at.cached_pallas_blocks(P.stages, P.in_dtype, "cpu", "v5e") == \
    {"fir": 2048}

# a malformed axis on disk loses only itself — the entry (k pick) survives
path = os.path.join(td, "streamed_picks.json")
with open(path) as f:
    d = json.load(f)
d[next(iter(d))]["pallas_blocks"] = "garbage"
with open(path, "w") as f:
    json.dump(d, f)
at._disk_memo.clear(); at._streamed_cache.clear()
e = at.cached_streamed_pick(P.stages, P.in_dtype, "cpu")
assert e is not None and e["k"] == 4 and "pallas_blocks" not in e, e

# driver: first call sweeps + records, second is a cache hit (no sweep)
at._disk_memo.clear(); at._streamed_cache.clear()
calls = {"n": 0}
orig = pallas_tune.sweep_blocks
def counting(*a, **k):
    calls["n"] += 1
    return orig(*a, **k)
pallas_tune.sweep_blocks = counting
w1 = at.autotune_pallas_blocks(P.stages, P.in_dtype, kernels=("rotator",),
                               frame=1 << 14, reps=1)
assert calls["n"] == 1 and "rotator" in w1, (calls, w1)
w2 = at.autotune_pallas_blocks(P.stages, P.in_dtype, kernels=("rotator",),
                               frame=1 << 14, reps=1)
assert calls["n"] == 1, "cache hit must skip the sweep"
assert w2 == w1 and pk.tuned_blocks()["rotator"] == w1["rotator"]
pk.set_tuned_blocks(None)
print("pallas autotune cache round-trip: OK")
EOF

echo "== multi-tenant serving gate (docs/serving.md) =="
# N sessions of one receiver chain through a single vmapped dispatch per
# frame: dispatches/frame == 1 regardless of the active session count,
# session join/leave under load causes ZERO recompiles of resident slot
# buckets, the sessions/chip ratio vs independent per-session dispatch
# loops clears the smoke floor, a simulated crash-restart with durable
# persistence resumes 100% of sessions bit-identically
# (serve_restart_resume_frac == 1.0), and an admission storm sheds
# newcomers while residents keep delivering (serve_shed_p99_ms stamped)
JAX_PLATFORMS=cpu python perf/serve_ab.py --smoke

echo "== serve churn gate (docs/serving.md 'Paged session carries') =="
# the paged-engine acceptance regime: join/leave EVERY step for 100 events
# at N=64, K in {1,4} — ZERO recompiles of the resident capacity (the page
# table absorbs all churn as host map edits) and churn p99 within 1.5x the
# no-churn p99 at the same capacity
JAX_PLATFORMS=cpu python perf/serve_ab.py --churn --smoke

echo "== mesh-sharded device plane gate (docs/parallel.md) =="
# the data-sharded fused program on the virtual 8-device mesh: bit-identical
# per shard to the D=1 program at matched K, ONE dispatch per group (the
# per-shard dispatch count never multiplies with D), ZERO cross-shard
# collectives in the compiled HLO (interior edges never leave their shard),
# and the D=8 scaling fraction vs the independent-per-device-loop linear
# reference clears the floor (multichip_scaling_frac stamped, regress-graded)
JAX_PLATFORMS=cpu python perf/multichip_ab.py --smoke

echo "== fleet observability gate (docs/observability.md 'The fleet plane') =="
# three live control-port hosts over real sockets: the FleetView reaches 3
# ready, the merged /api/fleet/metrics exposition is host-labelled and
# scrape-stable, the first admit lands on the least-pressure host, and after
# SIGKILL of that host the view flips it stale -> down (journal-ordered) with
# 100% of subsequent admits routed to the survivors
JAX_PLATFORMS=cpu python perf/fleet_smoke.py --smoke

echo "== chaos smoke (docs/robustness.md invariants) =="
# seeded fault injection at every site × every failure policy on the CPU
# backend: restart recovers bit-correct, isolate finishes independent
# branches, fail_fast keeps today's behavior, transfer retries are
# deterministic, no run hangs past its deadline or leaks threads — plus
# the serving plane: SIGKILL mid-serve + restart resumes every persisted
# session bit-identically (serve-crash-restart) and an overload storm
# sheds only via the documented ladder (serve-overload-shed)
JAX_PLATFORMS=cpu python perf/chaos.py --smoke

echo "== lineage & journal smoke (docs/observability.md 'Frame lineage') =="
# 1-in-1 sampled streamed run: the Perfetto export renders a sampled frame
# as ONE connected s/t/f flow chain spanning >=4 lanes, tail attribution
# names a slowest pipeline lane consistent with its own per-lane split, and
# the lifecycle journal drains through the REST cursor contract (pages of 3,
# no gaps, same seq order as the unlimited read)
FUTURESDR_TPU_LINEAGE_STRIDE=1 JAX_PLATFORMS=cpu python - <<'EOF'
import json
import numpy as np
from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Head, NullSink, NullSource
from futuresdr_tpu.config import config
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import fir_stage, mag2_stage
from futuresdr_tpu.telemetry import journal, lineage, spans
from futuresdr_tpu.tpu import TpuKernel

assert lineage.tracer().stride == 1, lineage.tracer().stride
frame = 1 << 14
n = 24 * frame
c = config()
c.buffer_size = max(c.buffer_size, 4 * frame * 8)
fg = Flowgraph()
taps = firdes.lowpass(0.2, 64).astype(np.float32)
tk = TpuKernel([fir_stage(taps), mag2_stage()], np.complex64,
               frame_size=frame, frames_in_flight=4)
fg.connect(NullSource(np.complex64), Head(np.complex64, n), tk,
           NullSink(np.float32))
Runtime().run(fg)

recs = lineage.tracer().records()
assert recs, "1-in-1 sampling produced no completed lineage records"

# Perfetto flow chains: at least one record renders as a connected
# s -> t... -> f chain sharing one id across >=4 lanes
trace = spans.chrome_trace()
flows = {}
for ev in trace["traceEvents"]:
    if ev.get("cat") == "lineage":
        flows.setdefault(ev["id"], []).append(ev)
assert trace["otherData"]["lineage_flows"] == len(flows) > 0, \
    trace["otherData"]
chained = 0
for tid, evs in flows.items():
    phs = [e["ph"] for e in evs]
    if len(evs) >= 4 and phs[0] == "s" and phs[-1] == "f" and \
            all(p == "t" for p in phs[1:-1]) and evs[-1].get("bp") == "e":
        lanes = [e["args"]["lane"] for e in evs]
        assert lanes[0] == "ingest" and lanes[-1] == "emit", lanes
        chained += 1
assert chained, "no connected s/t/f flow chain spanning >=4 lanes"
json.dumps(trace)  # the export must stay JSON-serializable

# tail attribution: slowest lane named, consistent with its own split
tail = lineage.tail_report()
assert tail and tail["e2e_samples"] > 0, tail
sl = tail["slowest_lane"]
assert sl in lineage.PIPELINE_LANES, tail
pipe = {ln: d["total_s"] for ln, d in tail["lanes"].items()
        if ln in lineage.PIPELINE_LANES}
assert sl == max(pipe, key=pipe.get), (sl, pipe)

# journal: the run journaled its kernel init; the cursor contract drains
# everything in order without gaps
j = journal.journal()
full = j.events()["events"]
assert any(e["cat"] == "kernel" and e["event"] == "init" for e in full)
drained, cur = [], 0
while True:
    page = j.events(since=cur, limit=3)
    assert not page["gap"], page
    drained.extend(page["events"])
    if not page["events"] or page["next"] == cur:
        break
    cur = page["next"]
seqs = [e["seq"] for e in drained]
assert seqs == [e["seq"] for e in full] == sorted(seqs), \
    "cursor drain disagrees with the unlimited read"
print(f"lineage smoke: {len(recs)} records, {chained} flow chain(s), "
      f"slowest lane {sl}, journal drained {len(seqs)} events: OK")
EOF

echo "== perf-regression gate (non-fatal; perf/regress.py vs BENCH_r*.json) =="
# quick reduced bench on the CPU backend, graded against the committed
# trajectory with a generous tolerance — warnings only, never fails the check
FSDR_FORCE_CPU=1 JAX_PLATFORMS=cpu python perf/regress.py --run --quick || \
    echo "WARNING: perf-regression gate could not be graded (non-fatal)"

echo "== python suite =="
python -m pytest tests/ -q

echo "== graft entries =="
XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import sys
sys.path.insert(0, ".")
from __graft_entry__ import entry, dryrun_multichip
fn, args = entry()
jax.jit(fn)(*args)
dryrun_multichip(8)
print("entry + dryrun_multichip(8): OK")
EOF

echo "ALL CHECKS PASSED"
