// Double-mapped circular buffer allocator + SPSC index arithmetic.
//
// Native equivalent of the reference's `vmcircbuffer` crate (used by
// src/runtime/buffer/circular.rs): a memfd-backed region mapped twice back-to-back in
// virtual memory so that any window of up to `size` bytes starting at any offset is
// contiguous — readers/writers never see a wrap seam and work windows are never split.
//
// Exposed as a tiny C ABI consumed from Python via ctypes (no pybind11 in this image).

#ifndef _GNU_SOURCE
#define _GNU_SOURCE
#endif

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <sys/mman.h>
#include <unistd.h>

#ifndef MFD_CLOEXEC
#define MFD_CLOEXEC 0x0001U
#endif

extern "C" {

struct fsdr_dbuf {
    uint8_t *base;     // start of the first mapping; base[0 .. 2*size) valid
    size_t size;       // logical capacity in bytes (page-multiple)
    int fd;
};

// Round up to a page multiple and map the same memfd twice, adjacently.
fsdr_dbuf *fsdr_dbuf_create(size_t min_size) {
    long page = sysconf(_SC_PAGESIZE);
    if (page <= 0) page = 4096;
    size_t size = ((min_size + page - 1) / page) * page;
    if (size == 0) size = (size_t)page;

    int fd = memfd_create("fsdr_ringbuf", MFD_CLOEXEC);
    if (fd < 0) return nullptr;
    if (ftruncate(fd, (off_t)size) != 0) { close(fd); return nullptr; }

    // Reserve 2*size of address space, then overlay the two file mappings.
    void *reserve = mmap(nullptr, 2 * size, PROT_NONE,
                         MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
    if (reserve == MAP_FAILED) { close(fd); return nullptr; }
    uint8_t *base = (uint8_t *)reserve;

    void *a = mmap(base, size, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_FIXED, fd, 0);
    void *b = mmap(base + size, size, PROT_READ | PROT_WRITE,
                   MAP_SHARED | MAP_FIXED, fd, 0);
    if (a == MAP_FAILED || b == MAP_FAILED) {
        munmap(reserve, 2 * size);
        close(fd);
        return nullptr;
    }

    auto *h = (fsdr_dbuf *)std::malloc(sizeof(fsdr_dbuf));
    h->base = base;
    h->size = size;
    h->fd = fd;
    return h;
}

void fsdr_dbuf_destroy(fsdr_dbuf *h) {
    if (!h) return;
    munmap(h->base, 2 * h->size);
    close(h->fd);
    std::free(h);
}

uint8_t *fsdr_dbuf_ptr(fsdr_dbuf *h) { return h->base; }
size_t fsdr_dbuf_size(fsdr_dbuf *h) { return h->size; }

// ---------------------------------------------------------------------------
// Lock-free SPSC ring indices: one writer, up to FSDR_MAX_READERS readers.
// Positions are monotonically increasing byte/item counters (as in the Rust
// vmcircbuffer). The Python layer maps slices from these; produce/consume are
// single atomic stores so the GIL never serializes the data plane accounting.
// ---------------------------------------------------------------------------

#define FSDR_MAX_READERS 16

// Cache-line padding: the writer hammers wpos while each reader hammers its own rpos;
// sharing a line would false-share every produce/consume (the reference pads its SPSC
// indices the same way, perf/perf/src/spsc.rs).
struct alignas(128) fsdr_padded_u64 {
    std::atomic<uint64_t> v;
};

struct fsdr_ring {
    fsdr_padded_u64 wpos;
    fsdr_padded_u64 rpos[FSDR_MAX_READERS];
    std::atomic<uint32_t> reader_active;  // bitmask
    uint64_t capacity;                    // in items
};

fsdr_ring *fsdr_ring_create(uint64_t capacity_items) {
    auto *r = (fsdr_ring *)std::calloc(1, sizeof(fsdr_ring));
    r->capacity = capacity_items;
    return r;
}

void fsdr_ring_destroy(fsdr_ring *r) { std::free(r); }

int fsdr_ring_add_reader(fsdr_ring *r) {
    for (int i = 0; i < FSDR_MAX_READERS; i++) {
        uint32_t mask = r->reader_active.load(std::memory_order_acquire);
        if (!(mask & (1u << i))) {
            r->rpos[i].v.store(r->wpos.v.load(std::memory_order_acquire),
                             std::memory_order_release);
            if (r->reader_active.compare_exchange_strong(mask, mask | (1u << i)))
                return i;
            i--;  // raced; retry this slot scan
        }
    }
    return -1;
}

void fsdr_ring_remove_reader(fsdr_ring *r, int idx) {
    r->reader_active.fetch_and(~(1u << idx), std::memory_order_acq_rel);
}

uint64_t fsdr_ring_wpos(fsdr_ring *r) {
    return r->wpos.v.load(std::memory_order_acquire);
}

uint64_t fsdr_ring_rpos(fsdr_ring *r, int idx) {
    return r->rpos[idx].v.load(std::memory_order_acquire);
}

// Free space for the writer = capacity - max over active readers of (wpos - rpos).
uint64_t fsdr_ring_space(fsdr_ring *r) {
    uint64_t w = r->wpos.v.load(std::memory_order_acquire);
    uint32_t mask = r->reader_active.load(std::memory_order_acquire);
    uint64_t used = 0;
    for (int i = 0; i < FSDR_MAX_READERS; i++) {
        if (mask & (1u << i)) {
            uint64_t lag = w - r->rpos[i].v.load(std::memory_order_acquire);
            if (lag > used) used = lag;
        }
    }
    return r->capacity - used;
}

uint64_t fsdr_ring_available(fsdr_ring *r, int idx) {
    return r->wpos.v.load(std::memory_order_acquire) -
           r->rpos[idx].v.load(std::memory_order_acquire);
}

void fsdr_ring_produce(fsdr_ring *r, uint64_t n) {
    r->wpos.v.fetch_add(n, std::memory_order_acq_rel);
}

void fsdr_ring_consume(fsdr_ring *r, int idx, uint64_t n) {
    r->rpos[idx].v.fetch_add(n, std::memory_order_acq_rel);
}

}  // extern "C"
