// fastchain: single-threaded round-robin executor for source-rooted TREES of
// stream blocks (linear chains as the degenerate case) — the native work-loop
// driver for the small-chunk regime, with real DSP stages (FIR with carried
// history + decimation, quadrature demod, and the rotate→FIR→decimate
// xlating stage — which Python only fuses behind an explicit fastchain_static
// opt-in, since a fused chain cannot service the block's live freq retune
// handler). v3 protocol: an in_ring[] topology array; a ring consumed by
// several stages BROADCASTS (per-consumer read indices, finished consumers
// released) — the actor runtime's 1-writer→N-reader port groups.
//
// Reference role: src/runtime/scheduler/flow.rs:265-442 — the reference's
// FlowScheduler runs pinned workers with LOCAL run queues precisely because
// per-work-call executor overhead dominates when blocks forward tiny chunks
// (perf/null_rand: 512-item CopyRand chains) — and its north-star perf grid
// (perf/fir/fir.rs:49-95) interleaves those CopyRands with 64-tap FIRs.
// Python's asyncio actor loop costs ~10 us per work() call in that regime;
// this driver runs a WHOLE pipe (source → head → copyrands/firs/demod → sink)
// inside one C++ thread with plain ring buffers between stages
// (single-threaded: no atomics, no wakeups — the round-robin IS the schedule,
// like one pinned flow.rs worker that owns every block of the pipe).
//
// v2 protocol: stages carry their OWN output item size (isz_out), so
// rate/dtype-changing stages (complex FIR → f32 demod) fuse too. Stateful
// stages carry their state across chunks exactly like the Python cores
// (dsp/kernels.py FirFilter/DecimatingFirFilter, blocks/dsp.py
// QuadratureDemod): FIR history is nt-1 zero-initialized items, decimation
// phase is chunk-invariant, demod seeds last=1+0j. Numeric note: FIR
// accumulation order differs from numpy's np.convolve (BLAS dot), so outputs
// match to float32 rounding (~1e-6 relative), not bit-exactly — the A/B
// tests use allclose for FIR/demod chains and exact equality for copy chains.
//
// The Python runtime substitutes eligible chains at launch
// (futuresdr_tpu/runtime/fastchain.py). Opt out with FSDR_NO_NATIVE=1 or
// FSDR_NO_FASTCHAIN=1.

#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <vector>

#ifdef __AVX512F__
#include <immintrin.h>
#endif

extern "C" {

// Stage kinds (keep in sync with futuresdr_tpu/runtime/fastchain.py)
enum {
    FC_NULL_SOURCE = 0,   // produce zeros forever
    FC_HEAD = 1,          // p0 = max items to forward, then EOS downstream
    FC_COPY = 2,          // forward everything
    FC_COPY_RAND = 3,     // p0 = max_copy (forward 1..=max_copy per pass), p1 = seed
    FC_NULL_SINK = 4,     // consume; p0 = count to finish after (-1 = until EOS)
    FC_VEC_SOURCE = 5,    // emit data cyclically: p0 = total items, p1 = period
    FC_VEC_SINK = 6,      // collect into data: p0 = capacity (exact bound)
    FC_FIR_FF = 7,        // f32 FIR, f32 taps: p0 = ntaps, p1 = decim, data = taps
    FC_FIR_CF = 8,        // c64 FIR, f32 taps: p0 = ntaps, p1 = decim, data = taps
    FC_FIR_CC = 9,        // c64 FIR, c64 taps: p0 = ntaps, p1 = decim, data = taps
    FC_QUAD_DEMOD = 10,   // c64 → f32: f0 = gain; y = gain*arg(x[n]*conj(x[n-1]))
    FC_XLATING = 11,      // c64 rotate(f0=phase_inc) → f32-tap FIR → decim
    FC_AGC = 12,          // per-sample AGC: p0 = 1 if complex items,
                          // data = double[4]{reference, rate, max_gain, gain0}
    FC_RESAMPLE = 13,     // rational polyphase resampler: p0 = K (sub-filter
                          // len), p1 = interp | decim<<32, data = poly[I][K]
                          // f32 row-major (dsp/kernels.py:88 layout)
    // FC_VEC_SOURCE with p0 < 0 = INFINITE cyclic emission (FileSource
    // repeat=true over a memmap; bounded downstream by Head/sink count)
    FC_SIG = 14,          // fxpt NCO source: p0 = waveform (0 sin, 1 cos,
                          // 2 complex, 3 square), p1 = inc_u32 | start<<32,
                          // data = double[2]{amplitude, offset}. The phase is
                          // a wrapping u32 (dsp/fxpt.py) — integer, so the
                          // native ramp is BIT-exact vs the Python block.
    FC_DELAY = 15,        // p0 = pad (leading zero items), p1 = skip
                          // (leading input items dropped); then 1:1 copy
    FC_THROTTLE = 16,     // wall-clock rate limit: f0 = items/s. Python fuses
                          // it only behind the fastchain_static opt-in (the
                          // block has a live rate retune handler, like
                          // FC_XLATING/FC_AGC).
};

struct FcStage {
    int32_t kind;
    int32_t isz_out;      // bytes per item on this stage's OUTPUT (sink: on input)
    int64_t p0;
    int64_t p1;
    double f0;            // float parameter (FC_QUAD_DEMOD: gain)
    uint8_t* data;        // vec data / taps / sink out buf
};

}  // extern "C"

namespace {

struct Ring {
    char* buf = nullptr;
    int64_t cap = 0;       // items
    int64_t isz = 0;       // bytes per item
    int64_t head = 0;      // write index (items, not wrapped)
    bool eos = false;
    // v3 topology: one read index per consumer (broadcast ring — every
    // consumer sees every item, like the actor runtime's 1-writer→N-reader
    // port groups, `runtime/buffer/circular.py:108`). Linear chains have
    // exactly one entry.
    std::vector<int64_t> tails;
    // A finished consumer's slot is RELEASED so its frozen tail no longer
    // constrains the writer — the actor runtime likewise drops a finished
    // block's reader from the port group (an early-finishing Head branch
    // must not wedge its broadcast siblings).
    std::vector<char> released;

    int64_t min_tail() const {
        int64_t m = head;
        for (size_t c = 0; c < tails.size(); ++c)
            if (!released[c] && tails[c] < m) m = tails[c];
        return m;
    }
    int64_t count(int c) const { return head - tails[static_cast<size_t>(c)]; }
    int64_t space() const { return cap - (head - min_tail()); }
    void release(int c) { released[static_cast<size_t>(c)] = 1; }
};

// xorshift64* — per-stage chunk-size RNG for FC_COPY_RAND
inline uint64_t xs(uint64_t& s) {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1DULL;
}

// copy k items between buffers; a cap of 0 means LINEAR (no wrap), nonzero
// means ring with that capacity. The single audited wrap-splitting loop for
// ring->ring (inter-stage), vec->ring (source), ring->linear (FIR gather) and
// linear->ring (FIR scatter) paths.
inline void span_copy(const uint8_t* sb, int64_t scap, int64_t& si,
                      uint8_t* db, int64_t dcap, int64_t& di,
                      int64_t k, int64_t isz) {
    while (k > 0) {
        int64_t s_off = scap ? si % scap : si;
        int64_t d_off = dcap ? di % dcap : di;
        int64_t c = k;
        if (scap && scap - s_off < c) c = scap - s_off;
        if (dcap && dcap - d_off < c) c = dcap - d_off;
        std::memcpy(db + d_off * isz, sb + s_off * isz,
                    static_cast<size_t>(c * isz));
        si += c;
        di += c;
        k -= c;
    }
}

inline void ring_copy(Ring& src, int ci, Ring& dst, int64_t k) {
    span_copy(reinterpret_cast<const uint8_t*>(src.buf), src.cap,
              src.tails[static_cast<size_t>(ci)],
              reinterpret_cast<uint8_t*>(dst.buf), dst.cap, dst.head, k,
              src.isz);
}

// ---- FIR compute kernels ----------------------------------------------------
//
// Layout trick that makes every variant a pure float saxpy the compiler
// auto-vectorizes WITHOUT -ffast-math: outer loop over taps, inner loop over
// outputs (independent accumulations — no float reduction reordering needed),
// blocked so the accumulator tile and its input window stay in L1. A
// complex64 stream with real taps is the SAME kernel on the interleaved float
// view with the tap offset doubled.

constexpr int64_t FIR_BLK = 1024;   // floats per accumulator tile (4 KiB)

// y[j] = sum_t taps[t] * x[j - t*stride], j in [0, n) — x may be read back to
// x[-(nt-1)*stride] (history prefix guaranteed by the caller).
//
// Tap-unrolled 8-wide: one accumulator load/store services 8 FMAs instead of
// 1, lifting the loop from load/store-bound (~3 memory ops per FMA) to
// FMA-bound. The per-output accumulation ORDER stays ascending-t — the 8 adds
// are sequential on the same lane — so results are bit-identical to the
// straight loop.
inline void fir_real_taps(const float* x, const float* taps, int64_t nt,
                          int64_t stride, float* y, int64_t n) {
    float acc[FIR_BLK];
    for (int64_t j0 = 0; j0 < n; j0 += FIR_BLK) {
        int64_t jb = n - j0 < FIR_BLK ? n - j0 : FIR_BLK;
        std::memset(acc, 0, static_cast<size_t>(jb) * sizeof(float));
        int64_t t = 0;
        for (; t + 8 <= nt; t += 8) {
            const float c0 = taps[t], c1 = taps[t + 1], c2 = taps[t + 2],
                        c3 = taps[t + 3], c4 = taps[t + 4], c5 = taps[t + 5],
                        c6 = taps[t + 6], c7 = taps[t + 7];
            const float* xs = x + j0 - t * stride;
            for (int64_t j = 0; j < jb; ++j) {
                float a = acc[j];
                a += c0 * xs[j];
                a += c1 * xs[j - stride];
                a += c2 * xs[j - 2 * stride];
                a += c3 * xs[j - 3 * stride];
                a += c4 * xs[j - 4 * stride];
                a += c5 * xs[j - 5 * stride];
                a += c6 * xs[j - 6 * stride];
                a += c7 * xs[j - 7 * stride];
                acc[j] = a;
            }
        }
        for (; t < nt; ++t) {
            const float c = taps[t];
            const float* xs = x + j0 - t * stride;
            for (int64_t j = 0; j < jb; ++j) acc[j] += c * xs[j];
        }
        std::memcpy(y + j0, acc, static_cast<size_t>(jb) * sizeof(float));
    }
}

// Folded symmetric FIR (taps palindromic, nt even): y[f] = Σ_{k<nt/2}
// taps[k] · (x[f−k·stride] + x[f−(nt−1−k)·stride]) on the float view —
// halves the multiplies, and the ADD issues on a different port than the FMA,
// which matters on parts with a single 512-bit FMA unit (this box: folded
// ~480 Msps vs ~375 straight at 64 taps). Accumulation order: ascending k
// with the mirror pair pre-added — a third numeric order besides numpy's and
// the straight kernel's, all within float32 rounding of each other.
inline void fir_sym(const float* x, const float* taps, int64_t nt,
                    int64_t stride, float* y, int64_t nf) {
    const int64_t h = nt / 2;
    const int64_t Ls = (nt - 1) * stride;
    int64_t j0 = 0;
#ifdef __AVX512F__
    for (; j0 + 64 <= nf; j0 += 64) {
        __m512 a0 = _mm512_setzero_ps(), a1 = a0, a2 = a0, a3 = a0;
        for (int64_t k = 0; k < h; ++k) {
            const float* xa = x + j0 - k * stride;
            const float* xb = x + j0 - Ls + k * stride;
            const __m512 c = _mm512_set1_ps(taps[k]);
            a0 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa), _mm512_loadu_ps(xb)), a0);
            a1 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa + 16),
                                 _mm512_loadu_ps(xb + 16)), a1);
            a2 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa + 32),
                                 _mm512_loadu_ps(xb + 32)), a2);
            a3 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa + 48),
                                 _mm512_loadu_ps(xb + 48)), a3);
        }
        _mm512_storeu_ps(y + j0, a0);
        _mm512_storeu_ps(y + j0 + 16, a1);
        _mm512_storeu_ps(y + j0 + 32, a2);
        _mm512_storeu_ps(y + j0 + 48, a3);
    }
#endif
    for (; j0 < nf; ++j0) {
        float s = 0;
        for (int64_t k = 0; k < h; ++k)
            s += taps[k] * (x[j0 - k * stride] + x[j0 - Ls + k * stride]);
        y[j0] = s;
    }
}

#ifdef __AVX512F__
// The valignd folded-symmetric kernel is shared with the design-space
// microbench so the benchmarked kernel IS the production kernel.
#include "fir_valign.h"
#endif  // __AVX512F__

// Symmetric-tap dispatch: valignd kernel where it wins, plain folded
// otherwise (bit-identical either way).
inline void fir_sym_best(const float* x, const float* taps, int64_t nt,
                         int64_t stride, float* y, int64_t nf) {
#ifdef __AVX512F__
    if (stride == 1) return fir_sym_valign<1>(x, taps, nt, y, nf);
    if (stride == 2) return fir_sym_valign<2>(x, taps, nt, y, nf);
#endif
    fir_sym(x, taps, nt, stride, y, nf);
}

// complex64 stream, complex64 taps: yr = Σ tr·xr − ti·xi ; yi = Σ tr·xi + ti·xr
// on the interleaved float view (x/y are float pointers, n complex items).
inline void fir_cc(const float* x, const float* taps, int64_t nt,
                   float* y, int64_t n) {
    float acc[FIR_BLK];                      // interleaved re/im tile
    const int64_t n2 = 2 * n;
    for (int64_t j0 = 0; j0 < n2; j0 += FIR_BLK) {
        int64_t jb = n2 - j0 < FIR_BLK ? n2 - j0 : FIR_BLK;
        std::memset(acc, 0, static_cast<size_t>(jb) * sizeof(float));
        for (int64_t t = 0; t < nt; ++t) {
            const float tr = taps[2 * t], ti = taps[2 * t + 1];
            const float* xs = x + j0 - 2 * t;
            // even lanes (re): tr·xr − ti·xi ; odd lanes (im): tr·xi + ti·xr
            for (int64_t j = 0; j + 1 < jb; j += 2) {
                acc[j] += tr * xs[j] - ti * xs[j + 1];
                acc[j + 1] += tr * xs[j + 1] + ti * xs[j];
            }
        }
        std::memcpy(y + j0, acc, static_cast<size_t>(jb) * sizeof(float));
    }
}

// Per-stage mutable state for compute stages.
struct StageState {
    std::vector<uint8_t> hist;   // FIR: nt-1 items (zero-init = virtual history)
    std::vector<uint8_t> xbuf;   // FIR: linear gather buffer (hist ++ chunk)
    std::vector<uint8_t> ybuf;   // FIR/demod: linear output before ring scatter
    int64_t phase = 0;           // decimation phase (dsp/kernels.py:64 contract)
    float last_re = 1.0f;        // quad demod x[n-1] seed (blocks/dsp.py:407)
    float last_im = 0.0f;
    double rot_phase = 0.0;      // FC_XLATING rotator phase (dsp Rotator carry)
    double agc_gain = 1.0;       // FC_AGC feedback state (blocks/dsp.py Agc)
    int64_t rs_m = 0;            // FC_RESAMPLE absolute output index
    int64_t rs_total = 0;        // FC_RESAMPLE absolute inputs seen
    double thr_t0 = -1.0;        // FC_THROTTLE clock anchor (monotonic s; <0 unset)
    int64_t thr_sent = 0;        // FC_THROTTLE items forwarded since anchor
};

inline double mono_seconds() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<double>(ts.tv_sec) + 1e-9 * static_cast<double>(ts.tv_nsec);
}

inline int64_t mono_ns() {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<int64_t>(ts.tv_sec) * 1000000000LL + ts.tv_nsec;
}

// Per-stage busy-time attribution (the tracing/profiling plane reaching into
// the native driver): times every scheduling pass of a stage — including its
// cheap no-work polls, so the round-robin's bookkeeping is attributed where
// it is spent — via a scope guard that fires on every `continue`. One vDSO
// clock pair (~50 ns) per stage pass against passes that move hundreds to
// thousands of items; per_ns == nullptr disables entirely.
struct ProfGuard {
    int64_t* slot;
    int64_t t0;
    explicit ProfGuard(int64_t* s) : slot(s), t0(s ? mono_ns() : 0) {}
    ~ProfGuard() {
        if (slot) *slot += mono_ns() - t0;
    }
};

// Outputs producible once `total` absolute inputs are visible: the largest m
// with (m·D)//I ≤ total−1 is (I·total−1)//D, plus one — the closed form of
// PolyphaseResamplingFir.process's m_hi (dsp/kernels.py; the core's former
// decrement-loop undershot it for some I>D alignments, which the fast-chain
// A/B exposed as chunk-dependent results — fixed together).
inline int64_t resample_m_hi(int64_t total, int64_t I, int64_t D) {
    if (total <= 0) return 0;
    return (I * total - 1) / D + 1;
}

// Run a chain/tree to completion (every sink finished) or until *stop becomes
// nonzero. ``inr[i]`` is the index of the stage whose output ring stage i
// consumes (-1 for the source at index 0); stages listed in topological order.
// A ring read by several stages is a BROADCAST ring: every consumer sees every
// item, own read index each (the actor runtime's 1-writer→N-reader port
// groups). per_in[i]/per_out[i] accumulate items consumed/produced by stage i
// (sources consume 0, sinks produce 0); per_calls[i] counts chunks moved (the
// work-call analog). All arrays are updated DURING the run, so the Python side
// reads them live for metrics. Returns total items consumed across sinks, or
// -1 on malformed input / stall (-2: sink capacity bound violated).
int64_t fc_run_core(const FcStage* st, int32_t n, const int32_t* inr,
                    int64_t ring_items, volatile int32_t* stop,
                    int64_t* per_in, int64_t* per_out, int64_t* per_calls,
                    int64_t* per_ns) {
    if (n < 2 || ring_items <= 0) return -1;
    // ---- topology: consumer counts + per-stage consumer slot ---------------
    std::vector<int> n_cons(n, 0), slot(n, 0);
    if (inr[0] != -1) return -1;
    for (int i = 1; i < n; ++i) {
        if (inr[i] < 0 || inr[i] >= i) return -1;   // topo order, single root
        slot[i] = n_cons[inr[i]]++;
    }
    for (int i = 0; i < n; ++i) {
        if (st[i].isz_out <= 0) return -1;
        if (st[i].kind == FC_COPY_RAND && st[i].p0 <= 0)
            return -1;                   // modulo-by-zero guard (max_copy >= 1)
        if (st[i].kind == FC_VEC_SOURCE &&
            (st[i].p1 <= 0 || st[i].data == nullptr))
            return -1;                   // empty/unbacked source
        if (st[i].kind == FC_VEC_SINK && st[i].data == nullptr)
            return -1;
        if (st[i].kind >= FC_FIR_FF && st[i].kind <= FC_XLATING &&
            st[i].kind != FC_QUAD_DEMOD &&
            (st[i].p0 < 1 || (st[i].p1 & 0xFFFFFFFFLL) < 1 ||
             st[i].data == nullptr))
            return -1;                   // ntaps/decim/taps sanity
        if (st[i].kind == FC_THROTTLE &&
            !(st[i].f0 > 0.0 && std::isfinite(st[i].f0)))
            return -1;    // rate must be positive finite (inf·elapsed → NaN
                          // budget → int64 min → permanent 0-item passes: the
                          // loop would sleep forever instead of erroring)
    }
    if (st[0].kind != FC_NULL_SOURCE && st[0].kind != FC_VEC_SOURCE &&
        st[0].kind != FC_SIG)
        return -1;
    if (st[0].kind == FC_SIG && st[0].data == nullptr) return -1;
    int n_sinks = 0;
    for (int i = 1; i < n; ++i) {
        if (n_cons[i] == 0) {            // leaf: must be a sink kind
            if (st[i].kind != FC_NULL_SINK && st[i].kind != FC_VEC_SINK)
                return -1;
            ++n_sinks;
            continue;
        }
        // middle stage (has both an input ring and consumers)
        if (st[i].kind < FC_HEAD || st[i].kind > FC_THROTTLE ||
            st[i].kind == FC_SIG ||
            st[i].kind == FC_NULL_SINK || st[i].kind == FC_VEC_SOURCE ||
            st[i].kind == FC_VEC_SINK)
            return -1;
        if (st[i].kind == FC_AGC && st[i].data == nullptr)
            return -1;                  // params block required
        if (st[i].kind == FC_RESAMPLE &&
            (st[i].p0 < 1 || (st[i].p1 & 0xFFFFFFFFLL) < 1 ||
             (st[i].p1 >> 32) < 1 || st[i].data == nullptr))
            return -1;                  // K / interp / decim / poly sanity
        // width conservation: every middle stage except the dtype-changing
        // demod must see equal in/out item sizes, or ring_copy would write
        // src-width items into a dst-width ring (defense in depth — the
        // Python chain finder enforces the same rule)
        if (st[i].kind != FC_QUAD_DEMOD &&
            st[inr[i]].isz_out != st[i].isz_out)
            return -1;
    }
    if (n_sinks == 0) return -1;

    // one output ring per stage with consumers (ring index = producer index)
    std::vector<Ring> rings(n);
    for (int i = 0; i < n; ++i) {
        if (n_cons[i] == 0) continue;
        Ring& r = rings[i];
        r.isz = st[i].isz_out;
        // calloc: rings start zeroed, so the zero-producing source can advance
        // indices without writing (same fast path as the Python NullSource)
        r.buf = static_cast<char*>(
            std::calloc(static_cast<size_t>(ring_items),
                        static_cast<size_t>(r.isz)));
        if (!r.buf) {
            for (auto& q : rings) std::free(q.buf);
            return -1;
        }
        r.cap = ring_items;
        r.tails.assign(static_cast<size_t>(n_cons[i]), 0);
        r.released.assign(static_cast<size_t>(n_cons[i]), 0);
    }

    std::vector<int64_t> head_left(n, -1);   // FC_HEAD remaining budget
    std::vector<uint64_t> rng(n, 0);
    std::vector<bool> done(n, false);
    std::vector<StageState> ss(n);
    int64_t src_emitted = 0;                 // FC_VEC_SOURCE progress (stage 0)
    for (int i = 0; i < n; ++i) {
        if (st[i].kind == FC_HEAD) head_left[i] = st[i].p0;
        if (st[i].kind == FC_COPY_RAND)
            rng[i] = static_cast<uint64_t>(st[i].p1) * 0x9E3779B97F4A7C15ULL + 1;
        if ((st[i].kind >= FC_FIR_FF && st[i].kind <= FC_FIR_CC) ||
            st[i].kind == FC_XLATING) {
            const int64_t in_isz = st[inr[i]].isz_out;
            ss[i].hist.assign(
                static_cast<size_t>((st[i].p0 - 1) * in_isz), 0);
            ss[i].xbuf.resize(
                static_cast<size_t>((st[i].p0 - 1 + ring_items) * in_isz));
            std::memset(ss[i].xbuf.data(), 0,
                        static_cast<size_t>((st[i].p0 - 1) * in_isz));
            ss[i].ybuf.resize(static_cast<size_t>(ring_items * st[i].isz_out));
        }
        if (st[i].kind == FC_QUAD_DEMOD || st[i].kind == FC_AGC ||
            st[i].kind == FC_SIG)
            ss[i].ybuf.resize(static_cast<size_t>(ring_items * st[i].isz_out));
        if (st[i].kind == FC_AGC)
            ss[i].agc_gain =
                reinterpret_cast<const double*>(st[i].data)[3];   // gain0
        if (st[i].kind == FC_DELAY) {
            ss[i].rs_m = st[i].p0;       // pad remaining
            ss[i].rs_total = st[i].p1;   // skip remaining
        }
        if (st[i].kind == FC_RESAMPLE) {
            const int64_t in_isz = st[inr[i]].isz_out;
            const int64_t K = st[i].p0;
            ss[i].hist.assign(static_cast<size_t>((K - 1) * in_isz), 0);
            ss[i].xbuf.resize(
                static_cast<size_t>((K - 1 + ring_items) * in_isz));
            std::memset(ss[i].xbuf.data(), 0,
                        static_cast<size_t>((K - 1) * in_isz));
            // per-chunk outputs are limited by out.space() ≤ ring_items
            ss[i].ybuf.resize(static_cast<size_t>(ring_items * st[i].isz_out));
        }
    }
    // per-sink finish bounds (-1 = until EOS) and consumed counters
    std::vector<int64_t> snk_count(n, -1), snk_items(n, 0);
    for (int i = 1; i < n; ++i)
        if (n_cons[i] == 0 && st[i].kind == FC_NULL_SINK)
            snk_count[i] = st[i].p0;
    int sinks_left = n_sinks;

    // relaxed atomic load: the flag is written from a Python thread; plain
    // volatile is a data race under the C++ memory model
    while (!__atomic_load_n(stop, __ATOMIC_RELAXED) && sinks_left > 0) {
        bool progress = false;
        bool throttled = false;    // a throttle is pacing (not a stall)
        for (int i = 0; i < n; ++i) {
            if (done[i]) continue;
            ProfGuard prof_(per_ns ? &per_ns[i] : nullptr);
            if (i == 0) {
                Ring& out = rings[0];
                if (st[0].kind == FC_VEC_SOURCE) {
                    int64_t k = out.space();
                    const bool finite = st[0].p0 >= 0;
                    if (finite && st[0].p0 - src_emitted < k)
                        k = st[0].p0 - src_emitted;
                    if (k > 0) {
                        // source data is a RING of period p1 (cyclic repeat)
                        span_copy(st[0].data, st[0].p1, src_emitted,
                                  reinterpret_cast<uint8_t*>(out.buf), out.cap,
                                  out.head, k, out.isz);
                        progress = true;
                        if (per_out) per_out[0] += k;
                        if (per_calls) per_calls[0] += 1;
                    }
                    if (finite && src_emitted >= st[0].p0) {
                        out.eos = true;
                        done[0] = true;
                    }
                    continue;
                }
                if (st[0].kind == FC_SIG) {
                    int64_t k = out.space();
                    if (k > 0) {
                        const double* pr =
                            reinterpret_cast<const double*>(st[0].data);
                        const double amp = pr[0], off = pr[1];
                        const uint32_t inc =
                            static_cast<uint32_t>(st[0].p1 & 0xFFFFFFFFLL);
                        const uint32_t ph0 =
                            static_cast<uint32_t>(st[0].p1 >> 32);
                        const int64_t wf = st[0].p0;
                        float* yb = reinterpret_cast<float*>(ss[0].ybuf.data());
                        const double scale = M_PI / 2147483648.0;
                        // square: the sign of sin(ph) is exactly the sign of
                        // the int32 phase (ph in [-pi, pi); sin(-pi) in f64 is
                        // a tiny negative, matching numpy) — no trig at all
                        if (wf == 3) {
                            for (int64_t j = 0; j < k; ++j) {
                                const uint32_t pu = ph0 + inc *
                                    static_cast<uint32_t>(
                                        (src_emitted + j) & 0xFFFFFFFFLL);
                                const int32_t pi_ = static_cast<int32_t>(pu);
                                const double y = (pi_ > 0) - (pi_ < 0);
                                yb[j] = static_cast<float>(amp * y + off);
                            }
                        } else {
                            // chunk-anchored rotation: one exact sincos per
                            // 256 samples (re-anchored on the INTEGER phase,
                            // so error never exceeds ~256 rotations of f64
                            // eps ≈ 1e-13 — far inside the f32 cast), then a
                            // complex recurrence — ~10x over per-sample libm
                            // trig, which lost to numpy's SIMD sin otherwise
                            const double inc_rad =
                                static_cast<double>(static_cast<int32_t>(inc))
                                * scale;
                            double rs, rc;
                            ::sincos(inc_rad, &rs, &rc);
                            for (int64_t j0 = 0; j0 < k; j0 += 256) {
                                const int64_t jb =
                                    (k - j0 < 256) ? k - j0 : 256;
                                const uint32_t pu = ph0 + inc *
                                    static_cast<uint32_t>(
                                        (src_emitted + j0) & 0xFFFFFFFFLL);
                                double cs, cc;
                                ::sincos(static_cast<double>(
                                             static_cast<int32_t>(pu)) * scale,
                                         &cs, &cc);
                                for (int64_t j = 0; j < jb; ++j) {
                                    if (wf == 2) {
                                        yb[2 * (j0 + j)] = static_cast<float>(
                                            amp * cc + off);
                                        yb[2 * (j0 + j) + 1] =
                                            static_cast<float>(amp * cs);
                                    } else if (wf == 1) {
                                        yb[j0 + j] = static_cast<float>(
                                            amp * cc + off);
                                    } else {
                                        yb[j0 + j] = static_cast<float>(
                                            amp * cs + off);
                                    }
                                    const double nc = cc * rc - cs * rs;
                                    cs = cc * rs + cs * rc;
                                    cc = nc;
                                }
                            }
                        }
                        int64_t yi = 0;
                        span_copy(ss[0].ybuf.data(), 0, yi,
                                  reinterpret_cast<uint8_t*>(out.buf), out.cap,
                                  out.head, k, out.isz);
                        src_emitted += k;
                        progress = true;
                        if (per_out) per_out[0] += k;
                        if (per_calls) per_calls[0] += 1;
                    }
                    continue;                         // never EOS on its own
                }
                int64_t k = out.space();
                if (k > 0) {
                    out.head += k;                    // zeros pre-filled
                    progress = true;
                    if (per_out) per_out[0] += k;
                    if (per_calls) per_calls[0] += 1;
                }
                continue;
            }
            Ring& in = rings[inr[i]];
            const int ci = slot[i];
            if (n_cons[i] == 0) {                      // sink leaf
                int64_t k = in.count(ci);
                if (st[i].kind == FC_VEC_SINK) {
                    if (snk_items[i] + k > st[i].p0) {
                        for (auto& r : rings) std::free(r.buf);
                        return -2;        // capacity bound violated (bug)
                    }
                    span_copy(reinterpret_cast<const uint8_t*>(in.buf),
                              in.cap, in.tails[ci], st[i].data, 0,
                              snk_items[i], k, in.isz);
                    if (k > 0) {
                        progress = true;
                        if (per_in) per_in[i] += k;
                        if (per_calls) per_calls[i] += 1;
                    }
                    if (in.eos && in.count(ci) == 0) {
                        done[i] = true;
                        in.release(ci);
                        --sinks_left;
                    }
                    continue;
                }
                if (snk_count[i] >= 0 && snk_items[i] + k > snk_count[i])
                    k = snk_count[i] - snk_items[i];
                if (k > 0) {
                    in.tails[ci] += k;
                    snk_items[i] += k;
                    progress = true;
                    if (per_in) per_in[i] += k;
                    if (per_calls) per_calls[i] += 1;
                }
                if ((in.eos && in.count(ci) == 0) ||
                    (snk_count[i] >= 0 && snk_items[i] >= snk_count[i])) {
                    done[i] = true;
                    in.release(ci);
                    --sinks_left;
                }
                continue;
            }
            Ring& out = rings[i];

            // ---- compute middle stages -------------------------------------
            if ((st[i].kind >= FC_FIR_FF && st[i].kind <= FC_FIR_CC) ||
                st[i].kind == FC_XLATING) {
                const int64_t nt = st[i].p0;
                const int64_t decim = st[i].p1 & 0xFFFFFFFFLL;
                const bool sym = ((st[i].p1 >> 32) & 1) != 0;
                const int64_t isz_in = in.isz;
                StageState& s = ss[i];
                // inputs we may consume so outputs fit: with phase p, n inputs
                // yield (n > p) ? (n-1-p)/decim + 1 : 0 outputs → n ≤ p + space·decim
                int64_t k = in.count(ci);
                int64_t lim = s.phase + out.space() * decim;
                if (lim < k) k = lim;
                // keep chunks tile-aligned while upstream is live: the
                // vector kernels fall back to a ~10x-slower scalar loop for
                // the k%tile tail, and CopyRand-sized chunks (~2k items)
                // would pay that on EVERY pass; the remainder just waits in
                // the ring until EOS drains it. Rings smaller than one tile
                // could never satisfy the gate (review: livelock), so they
                // skip alignment entirely.
                const int64_t tile =
                    (ring_items < 64) ? 1
                    : (st[i].kind == FC_FIR_CF || st[i].kind == FC_XLATING)
                        ? 32 : 64;
                if (!in.eos && k > tile) k -= k % tile;
                else if (!in.eos && k < tile) k = 0;
                if (k > 0) {
                    uint8_t* xb = s.xbuf.data();
                    // linear gather: [hist | chunk]
                    std::memcpy(xb, s.hist.data(), s.hist.size());
                    int64_t xi = nt - 1;
                    span_copy(reinterpret_cast<const uint8_t*>(in.buf), in.cap,
                              in.tails[ci], xb, 0, xi, k, isz_in);
                    if (st[i].kind == FC_XLATING) {
                        // rotate the fresh chunk in place BEFORE the filter:
                        // downstream (kernel, history carry) then sees the
                        // rotated stream, exactly like blocks.XlatingFir
                        // feeding Rotator output into its DecimatingFirFilter
                        float* xc = reinterpret_cast<float*>(
                            xb + (nt - 1) * isz_in);
                        const double inc = st[i].f0;
                        for (int64_t j = 0; j < k; ++j) {
                            // phase0 + inc*j, like the numpy Rotator's ramp
                            // (NOT sequential accumulation — same rounding);
                            // one fused sincos per sample instead of two
                            // libm calls (glibc extension, present under
                            // g++'s default _GNU_SOURCE)
                            const double ph =
                                s.rot_phase + inc * static_cast<double>(j);
                            double sd, cd;
                            ::sincos(ph, &sd, &cd);
                            const float cr = static_cast<float>(cd);
                            const float ci = static_cast<float>(sd);
                            const float xr = xc[2 * j], xi_ = xc[2 * j + 1];
                            xc[2 * j] = xr * cr - xi_ * ci;
                            xc[2 * j + 1] = xr * ci + xi_ * cr;
                        }
                        s.rot_phase = std::fmod(s.rot_phase + inc * k,
                                                2.0 * M_PI);
                    }
                    const float* x0 = reinterpret_cast<const float*>(
                        xb + (nt - 1) * isz_in);
                    float* yb = reinterpret_cast<float*>(s.ybuf.data());
                    const float* taps =
                        reinterpret_cast<const float*>(st[i].data);
                    if (st[i].kind == FC_FIR_FF)
                        sym ? fir_sym_best(x0, taps, nt, 1, yb, k)
                            : fir_real_taps(x0, taps, nt, 1, yb, k);
                    else if (st[i].kind == FC_FIR_CF ||
                             st[i].kind == FC_XLATING)
                        // interleaved float view: same saxpy, tap offset ×2
                        sym ? fir_sym_best(x0, taps, nt, 2, yb, 2 * k)
                            : fir_real_taps(x0, taps, nt, 2, yb, 2 * k);
                    else
                        fir_cc(x0, taps, nt, yb, k);
                    // decimate y[phase::decim] (dsp/kernels.py:70-81 contract)
                    int64_t m = (k > s.phase)
                                    ? (k - 1 - s.phase) / decim + 1 : 0;
                    if (decim > 1 && m > 0) {
                        const int64_t osz = st[i].isz_out;
                        for (int64_t j = 0; j < m; ++j)
                            std::memmove(s.ybuf.data() + j * osz,
                                         s.ybuf.data() +
                                             (s.phase + j * decim) * osz,
                                         static_cast<size_t>(osz));
                    }
                    if (decim > 1) {
                        if (m > 0) {
                            int64_t last = s.phase + (m - 1) * decim;
                            s.phase = last + decim - k;
                        } else {
                            s.phase -= k;
                        }
                    }
                    // carry history: last nt-1 items of [hist | chunk]
                    std::memcpy(s.hist.data(),
                                xb + (k) * isz_in,   // = (nt-1+k)-(nt-1) items in
                                s.hist.size());
                    int64_t yi = 0;
                    span_copy(s.ybuf.data(), 0, yi,
                              reinterpret_cast<uint8_t*>(out.buf), out.cap,
                              out.head, m, st[i].isz_out);
                    progress = true;
                    if (per_in) per_in[i] += k;
                    if (per_out) per_out[i] += m;
                    if (per_calls) per_calls[i] += 1;
                }
                if (in.eos && in.count(ci) == 0) {
                    out.eos = true;      // history tail dropped, like the actor
                    done[i] = true;
                    in.release(ci);
                }
                continue;
            }
            if (st[i].kind == FC_QUAD_DEMOD) {
                StageState& s = ss[i];
                int64_t k = in.count(ci);
                if (out.space() < k) k = out.space();
                if (k > 0) {
                    const float gain = static_cast<float>(st[i].f0);
                    float* yb = reinterpret_cast<float*>(s.ybuf.data());
                    const float* rb = reinterpret_cast<const float*>(in.buf);
                    float pr = s.last_re, pi = s.last_im;
                    for (int64_t j = 0; j < k; ++j) {
                        int64_t off = (in.tails[ci] + j) % in.cap;
                        const float xr = rb[2 * off], xi_ = rb[2 * off + 1];
                        // x·conj(prev) = (xr·pr + xi·pi) + j(xi·pr − xr·pi)
                        yb[j] = gain * std::atan2(xi_ * pr - xr * pi,
                                                  xr * pr + xi_ * pi);
                        pr = xr;
                        pi = xi_;
                    }
                    s.last_re = pr;
                    s.last_im = pi;
                    in.tails[ci] += k;
                    int64_t yi = 0;
                    span_copy(s.ybuf.data(), 0, yi,
                              reinterpret_cast<uint8_t*>(out.buf), out.cap,
                              out.head, k, out.isz);
                    progress = true;
                    if (per_in) per_in[i] += k;
                    if (per_out) per_out[i] += k;
                    if (per_calls) per_calls[i] += 1;
                }
                if (in.eos && in.count(ci) == 0) {
                    out.eos = true;
                    done[i] = true;
                    in.release(ci);
                }
                continue;
            }
            if (st[i].kind == FC_RESAMPLE) {
                StageState& s = ss[i];
                const int64_t K = st[i].p0;
                const int64_t I = st[i].p1 & 0xFFFFFFFFLL;
                const int64_t D = st[i].p1 >> 32;
                const int64_t isz_in = in.isz;
                const bool cx = isz_in == 8;
                // max inputs consumable so producible outputs fit out.space():
                // binary search the monotone m_hi(total_in + n') − m ≤ space
                int64_t n_av = in.count(ci), space = out.space();
                int64_t lo = 0, hi = n_av;
                while (lo < hi) {
                    const int64_t mid = (lo + hi + 1) / 2;
                    if (resample_m_hi(s.rs_total + mid, I, D) - s.rs_m <= space)
                        lo = mid;
                    else
                        hi = mid - 1;
                }
                const int64_t k = lo;
                if (k > 0) {
                    uint8_t* xb = s.xbuf.data();
                    std::memcpy(xb, s.hist.data(), s.hist.size());
                    int64_t xi = K - 1;
                    span_copy(reinterpret_cast<const uint8_t*>(in.buf), in.cap,
                              in.tails[ci], xb, 0, xi, k, isz_in);
                    const int64_t total = s.rs_total + k;
                    const int64_t m_hi = resample_m_hi(total, I, D);
                    const int64_t mcount = m_hi - s.rs_m;
                    const float* poly =
                        reinterpret_cast<const float*>(st[i].data);
                    const float* xf = reinterpret_cast<const float*>(xb);
                    float* yb = reinterpret_cast<float*>(s.ybuf.data());
                    // abs index of xbuf[0] is rs_total − (K−1); windows never
                    // reach below it (n_m ≥ rs_total for the first pending
                    // output by m_hi's construction — the virtual-zero region
                    // is the zeroed history prefix)
                    const int64_t base = s.rs_total - (K - 1);
                    for (int64_t j = 0; j < mcount; ++j) {
                        const int64_t mj = s.rs_m + j;
                        const int64_t pos = (mj * D) / I - base;
                        const float* row = poly + ((mj * D) % I) * K;
                        if (cx) {
                            float ar = 0.0f, ai = 0.0f;
                            for (int64_t t = 0; t < K; ++t) {
                                ar += row[t] * xf[2 * (pos - t)];
                                ai += row[t] * xf[2 * (pos - t) + 1];
                            }
                            yb[2 * j] = ar;
                            yb[2 * j + 1] = ai;
                        } else {
                            float a = 0.0f;
                            for (int64_t t = 0; t < K; ++t)
                                a += row[t] * xf[pos - t];
                            yb[j] = a;
                        }
                    }
                    s.rs_m = m_hi;
                    s.rs_total = total;
                    std::memcpy(s.hist.data(), xb + k * isz_in, s.hist.size());
                    int64_t yi = 0;
                    span_copy(s.ybuf.data(), 0, yi,
                              reinterpret_cast<uint8_t*>(out.buf), out.cap,
                              out.head, mcount, st[i].isz_out);
                    progress = true;
                    if (per_in) per_in[i] += k;
                    if (per_out) per_out[i] += mcount;
                    if (per_calls) per_calls[i] += 1;
                }
                if (in.eos && in.count(ci) == 0) {
                    out.eos = true;
                    done[i] = true;
                    in.release(ci);
                }
                continue;
            }
            if (st[i].kind == FC_DELAY) {
                StageState& s = ss[i];
                // 1. flush leading zero padding (delay.rs Pad state)
                if (s.rs_m > 0) {
                    int64_t k = out.space() < s.rs_m ? out.space() : s.rs_m;
                    if (k > 0 && per_calls) per_calls[i] += 1;
                    while (k > 0) {
                        const int64_t off = out.head % out.cap;
                        int64_t c = out.cap - off < k ? out.cap - off : k;
                        std::memset(out.buf + off * out.isz, 0,
                                    static_cast<size_t>(c * out.isz));
                        out.head += c;
                        s.rs_m -= c;
                        k -= c;
                        progress = true;
                        if (per_out) per_out[i] += c;
                    }
                }
                // 2. drop leading inputs (negative delay)
                if (s.rs_total > 0 && in.count(ci) > 0) {
                    int64_t k = in.count(ci) < s.rs_total ? in.count(ci)
                                                        : s.rs_total;
                    in.tails[ci] += k;
                    s.rs_total -= k;
                    progress = true;
                    if (per_in) per_in[i] += k;
                }
                // 3. 1:1 copy
                int64_t k = in.count(ci);
                if (out.space() < k) k = out.space();
                if (k > 0) {
                    ring_copy(in, ci, out, k);
                    progress = true;
                    if (per_in) per_in[i] += k;
                    if (per_out) per_out[i] += k;
                    if (per_calls) per_calls[i] += 1;
                }
                if (in.eos && in.count(ci) == 0 && s.rs_m == 0) {
                    out.eos = true;   // pad must flush before EOS, like the
                    done[i] = true;   // actor's `_pad == 0` finish condition
                    in.release(ci);
                }
                continue;
            }
            if (st[i].kind == FC_AGC) {
                StageState& s = ss[i];
                int64_t k = in.count(ci);
                if (out.space() < k) k = out.space();
                if (k > 0) {
                    double* pr = reinterpret_cast<double*>(st[i].data);
                    // FLOAT32 feedback, exactly like the actor loop under
                    // NumPy 2 weak promotion: mag(f32)*g makes every update
                    // f32 there, so the sequential gain trajectory is f32 —
                    // double here would drift from the actor path's values
                    const float ref = static_cast<float>(pr[0]);
                    const float rate = static_cast<float>(pr[1]);
                    const float mg = static_cast<float>(pr[2]);
                    const bool cx = st[i].p0 != 0;
                    const float* rb = reinterpret_cast<const float*>(in.buf);
                    float* yb = reinterpret_cast<float*>(s.ybuf.data());
                    float g = static_cast<float>(s.agc_gain);
                    for (int64_t j = 0; j < k; ++j) {
                        const int64_t off = (in.tails[ci] + j) % in.cap;
                        // |x| like np.abs: hypotf for complex64, fabsf real
                        float mag;
                        if (cx) {
                            const float xr = rb[2 * off], xi = rb[2 * off + 1];
                            mag = hypotf(xr, xi);
                            // output multiply in f64 like numpy's
                            // gains(f64-array) * complex64 → complex128 → f32
                            yb[2 * j] = static_cast<float>(
                                static_cast<double>(g) * xr);
                            yb[2 * j + 1] = static_cast<float>(
                                static_cast<double>(g) * xi);
                        } else {
                            const float xr = rb[off];
                            mag = fabsf(xr);
                            yb[j] = static_cast<float>(
                                static_cast<double>(g) * xr);
                        }
                        g += rate * (ref - mag * g);
                        if (g < 0.0f) g = 0.0f;
                        if (g > mg) g = mg;
                    }
                    s.agc_gain = g;
                    pr[3] = g;          // live gain, read back by Python
                    in.tails[ci] += k;
                    int64_t yi = 0;
                    span_copy(s.ybuf.data(), 0, yi,
                              reinterpret_cast<uint8_t*>(out.buf), out.cap,
                              out.head, k, out.isz);
                    progress = true;
                    if (per_in) per_in[i] += k;
                    if (per_out) per_out[i] += k;
                    if (per_calls) per_calls[i] += 1;
                }
                if (in.eos && in.count(ci) == 0) {
                    out.eos = true;
                    done[i] = true;
                    in.release(ci);
                }
                continue;
            }

            if (st[i].kind == FC_THROTTLE) {
                // wall-clock pacing, the actor Throttle's exact budget math
                // (blocks/stream.py:94-106): budget = elapsed·rate − sent.
                // The anchor starts at the first pass, like the actor's
                // first work() call.
                StageState& s = ss[i];
                const double now = mono_seconds();
                if (s.thr_t0 < 0.0) {
                    s.thr_t0 = now;
                    s.thr_sent = 0;
                }
                // the elapsed·rate draw in double first: a finite-but-huge
                // rate (1e19) would overflow the int64 cast (UB → INT64_MIN
                // on x86) and freeze the loop in a permanent throttled sleep;
                // clamp far above any real budget instead
                const double draw = (now - s.thr_t0) * st[i].f0;
                int64_t budget =
                    (draw >= 4.0e18 ? (int64_t)4000000000000000000LL
                                    : static_cast<int64_t>(draw)) -
                    s.thr_sent;
                if (budget < 0) budget = 0;
                int64_t k = in.count(ci);
                if (out.space() < k) k = out.space();
                const bool starved_by_rate = k > budget;
                if (k > budget) k = budget;
                if (k > 0) {
                    ring_copy(in, ci, out, k);
                    s.thr_sent += k;
                    progress = true;
                    if (per_in) per_in[i] += k;
                    if (per_out) per_out[i] += k;
                    if (per_calls) per_calls[i] += 1;
                }
                if (in.eos && in.count(ci) == 0) {
                    out.eos = true;
                    done[i] = true;
                    in.release(ci);
                } else if (starved_by_rate) {
                    throttled = true;   // pacing, not a stall
                }
                continue;
            }

            // ---- copy-class middle stages ----------------------------------
            int64_t k = in.count(ci);
            if (out.space() < k) k = out.space();
            if (st[i].kind == FC_HEAD) {
                if (head_left[i] < k) k = head_left[i];
            } else if (st[i].kind == FC_COPY_RAND && k > 0) {
                int64_t cap = 1 + static_cast<int64_t>(
                    xs(rng[i]) % static_cast<uint64_t>(st[i].p0));
                if (cap < k) k = cap;
            }
            if (k > 0) {
                ring_copy(in, ci, out, k);
                progress = true;
                if (per_in) per_in[i] += k;
                if (per_out) per_out[i] += k;
                if (per_calls) per_calls[i] += 1;
                if (st[i].kind == FC_HEAD) head_left[i] -= k;
            }
            bool upstream_over = in.eos && in.count(ci) == 0;
            if (upstream_over || (st[i].kind == FC_HEAD && head_left[i] == 0)) {
                out.eos = true;
                done[i] = true;
                in.release(ci);
            }
        }
        if (!progress && sinks_left > 0) {
            if (throttled) {
                // every idle stage is waiting on a throttle's clock: sleep a
                // beat instead of spinning the core or mis-reporting a stall
                struct timespec ts = {0, 200 * 1000};   // 200 µs
                nanosleep(&ts, nullptr);
                continue;
            }
            // single-threaded chains always progress unless malformed; never spin
            for (auto& r : rings) std::free(r.buf);
            return -1;
        }
    }

    for (auto& r : rings) std::free(r.buf);
    int64_t total = 0;
    for (int i = 0; i < n; ++i) total += snk_items[i];
    return total;
}

}  // namespace

extern "C" {

// ABI version, checked by fastchain.py's _load(): bump on ANY FcStage layout
// or protocol change so a stale .so can never be driven with a newer struct.
int64_t fsdr_fastchain_abi(void) { return 9; }

// v2 entry: a linear chain (stage i consumes stage i-1's ring).
int64_t fsdr_fastchain_run_v2(const FcStage* st, int32_t n, int64_t ring_items,
                              volatile int32_t* stop, int64_t* per_in,
                              int64_t* per_out, int64_t* per_calls) {
    if (n < 2) return -1;
    std::vector<int32_t> inr(static_cast<size_t>(n));
    for (int i = 0; i < n; ++i) inr[static_cast<size_t>(i)] = i - 1;
    return fc_run_core(st, n, inr.data(), ring_items, stop, per_in, per_out,
                       per_calls, nullptr);
}

// v3 entry: a tree — in_ring[i] names the stage whose output ring stage i
// consumes (-1 for the single source at index 0; stages in topological
// order). Rings with several consumers broadcast: every consumer sees every
// item (the 1-writer→N-reader semantics of the actor runtime's port groups).
// per_ns (nullable): per-stage busy-time accumulation in nanoseconds — every
// scheduling pass of a live stage is attributed, productive or not, so the
// sum across stages approaches the driver thread's wall time.
int64_t fsdr_fastchain_run_v3(const FcStage* st, int32_t n,
                              const int32_t* in_ring, int64_t ring_items,
                              volatile int32_t* stop, int64_t* per_in,
                              int64_t* per_out, int64_t* per_calls,
                              int64_t* per_ns) {
    return fc_run_core(st, n, in_ring, ring_items, stop, per_in, per_out,
                       per_calls, per_ns);
}

}  // extern "C"
