// fastchain: single-threaded round-robin executor for linear chains of trivial
// stream blocks — the native work-loop driver for the small-chunk regime.
//
// Reference role: src/runtime/scheduler/flow.rs:265-442 — the reference's
// FlowScheduler runs pinned workers with LOCAL run queues precisely because
// per-work-call executor overhead dominates when blocks forward tiny chunks
// (perf/null_rand: 512-item CopyRand chains). Python's asyncio actor loop costs
// ~10 us per work() call in that regime; this driver runs a WHOLE pipe
// (source → head → copies → sink) inside one C++ thread with plain ring
// buffers between stages (single-threaded: no atomics, no wakeups — the
// round-robin IS the schedule, like one pinned flow.rs worker that owns every
// block of the pipe).
//
// The Python runtime substitutes eligible chains at launch
// (futuresdr_tpu/runtime/fastchain.py): whole pipes whose members are all
// native-capable, with no message ports, taps, or broadcasts. Data content
// matches the Python path (zeros from NullSource, byte-wise copies); CopyRand
// chunk SIZES come from a different RNG than numpy's — the stress pattern is
// equivalent, the per-chunk split is not bit-identical (documented in
// perf/null_rand.py).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

// Stage kinds (keep in sync with futuresdr_tpu/runtime/fastchain.py)
enum {
    FC_NULL_SOURCE = 0,   // produce zeros forever
    FC_HEAD = 1,          // p0 = max items to forward, then EOS downstream
    FC_COPY = 2,          // forward everything
    FC_COPY_RAND = 3,     // p0 = max_copy (forward 1..=max_copy per pass), p1 = seed
    FC_NULL_SINK = 4,     // consume; p0 = count to finish after (-1 = until EOS)
    FC_VEC_SOURCE = 5,    // emit data cyclically: p0 = total items, p1 = period
    FC_VEC_SINK = 6,      // collect into data: p0 = capacity (exact bound)
};

struct FcStage {
    int32_t kind;
    int32_t _pad;
    int64_t p0;
    int64_t p1;
    uint8_t* data;        // FC_VEC_SOURCE: items to emit; FC_VEC_SINK: out buf
};

}  // extern "C"

namespace {

struct Ring {
    char* buf = nullptr;
    int64_t cap = 0;       // items
    int64_t head = 0;      // write index (items, not wrapped)
    int64_t tail = 0;      // read index
    bool eos = false;

    int64_t count() const { return head - tail; }
    int64_t space() const { return cap - count(); }
};

// xorshift64* — per-stage chunk-size RNG for FC_COPY_RAND
inline uint64_t xs(uint64_t& s) {
    s ^= s >> 12;
    s ^= s << 25;
    s ^= s >> 27;
    return s * 0x2545F4914F6CDD1DULL;
}

// copy k items between buffers; a cap of 0 means LINEAR (no wrap), nonzero
// means ring with that capacity. The single audited wrap-splitting loop for
// ring->ring (inter-stage), vec->ring (source) and ring->vec (sink) paths.
inline void span_copy(const uint8_t* sb, int64_t scap, int64_t& si,
                      uint8_t* db, int64_t dcap, int64_t& di,
                      int64_t k, int64_t isz) {
    while (k > 0) {
        int64_t s_off = scap ? si % scap : si;
        int64_t d_off = dcap ? di % dcap : di;
        int64_t c = k;
        if (scap && scap - s_off < c) c = scap - s_off;
        if (dcap && dcap - d_off < c) c = dcap - d_off;
        std::memcpy(db + d_off * isz, sb + s_off * isz,
                    static_cast<size_t>(c * isz));
        si += c;
        di += c;
        k -= c;
    }
}

inline void ring_copy(Ring& src, Ring& dst, int64_t k, int64_t isz) {
    span_copy(reinterpret_cast<const uint8_t*>(src.buf), src.cap, src.tail,
              reinterpret_cast<uint8_t*>(dst.buf), dst.cap, dst.head, k, isz);
}

}  // namespace

extern "C" {

// Run the chain to completion (sink finished) or until *stop becomes nonzero.
// per_stage_out[i] accumulates items produced (for sinks: consumed) by stage i;
// per_stage_calls[i] counts chunks moved (the work-call analog). Both arrays
// are updated DURING the run, so the Python side reads them live for metrics.
// Returns items the sink consumed, or -1 on malformed input / stall.
int64_t fsdr_fastchain_run(const FcStage* st, int32_t n, int64_t item_size,
                           int64_t ring_items, volatile int32_t* stop,
                           int64_t* per_stage_out, int64_t* per_stage_calls) {
    if (n < 2 || item_size <= 0 || ring_items <= 0) return -1;
    for (int i = 0; i < n; ++i) {
        if (st[i].kind == FC_COPY_RAND && st[i].p0 <= 0)
            return -1;                   // modulo-by-zero guard (max_copy >= 1)
        if (st[i].kind == FC_VEC_SOURCE &&
            (st[i].p1 <= 0 || st[i].data == nullptr))
            return -1;                   // empty/unbacked source
        if (st[i].kind == FC_VEC_SINK && st[i].data == nullptr)
            return -1;
    }
    if (st[0].kind != FC_NULL_SOURCE && st[0].kind != FC_VEC_SOURCE) return -1;
    if (st[n - 1].kind != FC_NULL_SINK && st[n - 1].kind != FC_VEC_SINK)
        return -1;
    for (int i = 1; i + 1 < n; ++i)
        if (st[i].kind != FC_HEAD && st[i].kind != FC_COPY &&
            st[i].kind != FC_COPY_RAND)
            return -1;

    std::vector<Ring> rings(n - 1);
    for (auto& r : rings) {
        // calloc: rings start zeroed, so the zero-producing source can advance
        // indices without writing (same fast path as the Python NullSource)
        r.buf = static_cast<char*>(
            std::calloc(static_cast<size_t>(ring_items), static_cast<size_t>(item_size)));
        if (!r.buf) {
            for (auto& q : rings) std::free(q.buf);
            return -1;
        }
        r.cap = ring_items;
    }

    std::vector<int64_t> head_left(n, -1);   // FC_HEAD remaining budget
    std::vector<uint64_t> rng(n, 0);
    std::vector<bool> done(n, false);
    int64_t src_emitted = 0;                 // FC_VEC_SOURCE progress (stage 0)
    for (int i = 0; i < n; ++i) {
        if (st[i].kind == FC_HEAD) head_left[i] = st[i].p0;
        if (st[i].kind == FC_COPY_RAND)
            rng[i] = static_cast<uint64_t>(st[i].p1) * 0x9E3779B97F4A7C15ULL + 1;
    }
    int64_t sink_count =
        (st[n - 1].kind == FC_VEC_SINK) ? -1 : st[n - 1].p0;  // -1 = until EOS
    int64_t sink_items = 0;

    // relaxed atomic load: the flag is written from a Python thread; plain
    // volatile is a data race under the C++ memory model
    while (!__atomic_load_n(stop, __ATOMIC_RELAXED) && !done[n - 1]) {
        bool progress = false;
        for (int i = 0; i < n; ++i) {
            if (done[i]) continue;
            if (i == 0) {
                Ring& out = rings[0];
                if (st[0].kind == FC_VEC_SOURCE) {
                    int64_t k = out.space();
                    if (st[0].p0 - src_emitted < k) k = st[0].p0 - src_emitted;
                    if (k > 0) {
                        // source data is a RING of period p1 (cyclic repeat)
                        span_copy(st[0].data, st[0].p1, src_emitted,
                                  reinterpret_cast<uint8_t*>(out.buf), out.cap,
                                  out.head, k, item_size);
                        progress = true;
                        if (per_stage_out) per_stage_out[0] += k;
                        if (per_stage_calls) per_stage_calls[0] += 1;
                    }
                    if (src_emitted >= st[0].p0) { out.eos = true; done[0] = true; }
                    continue;
                }
                int64_t k = out.space();
                if (k > 0) {
                    out.head += k;                    // zeros pre-filled
                    progress = true;
                    if (per_stage_out) per_stage_out[0] += k;
                    if (per_stage_calls) per_stage_calls[0] += 1;
                }
                continue;
            }
            Ring& in = rings[i - 1];
            if (i == n - 1) {
                int64_t k = in.count();
                if (st[i].kind == FC_VEC_SINK) {
                    if (sink_items + k > st[i].p0) {
                        for (auto& r : rings) std::free(r.buf);
                        return -2;        // capacity bound violated (bug)
                    }
                    span_copy(reinterpret_cast<const uint8_t*>(in.buf),
                              in.cap, in.tail, st[i].data, 0, sink_items,
                              k, item_size);
                    if (k > 0) {
                        progress = true;
                        if (per_stage_out) per_stage_out[i] += k;
                        if (per_stage_calls) per_stage_calls[i] += 1;
                    }
                    if (in.eos && in.count() == 0) done[i] = true;
                    continue;
                }
                if (sink_count >= 0 && sink_items + k > sink_count)
                    k = sink_count - sink_items;
                if (k > 0) {
                    in.tail += k;
                    sink_items += k;
                    progress = true;
                    if (per_stage_out) per_stage_out[i] += k;
                    if (per_stage_calls) per_stage_calls[i] += 1;
                }
                if ((in.eos && in.count() == 0) ||
                    (sink_count >= 0 && sink_items >= sink_count))
                    done[i] = true;
                continue;
            }
            Ring& out = rings[i];
            int64_t k = in.count();
            if (out.space() < k) k = out.space();
            if (st[i].kind == FC_HEAD) {
                if (head_left[i] < k) k = head_left[i];
            } else if (st[i].kind == FC_COPY_RAND && k > 0) {
                int64_t cap = 1 + static_cast<int64_t>(
                    xs(rng[i]) % static_cast<uint64_t>(st[i].p0));
                if (cap < k) k = cap;
            }
            if (k > 0) {
                ring_copy(in, out, k, item_size);
                progress = true;
                if (per_stage_out) per_stage_out[i] += k;
                if (per_stage_calls) per_stage_calls[i] += 1;
                if (st[i].kind == FC_HEAD) head_left[i] -= k;
            }
            bool upstream_over = in.eos && in.count() == 0;
            if (upstream_over || (st[i].kind == FC_HEAD && head_left[i] == 0)) {
                out.eos = true;
                done[i] = true;
            }
        }
        if (!progress && !done[n - 1]) {
            // single-threaded chains always progress unless malformed; never spin
            for (auto& r : rings) std::free(r.buf);
            return -1;
        }
    }

    for (auto& r : rings) std::free(r.buf);
    return sink_items;
}

}  // extern "C"
