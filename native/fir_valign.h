// Folded symmetric FIR with valignd tap groups — the round-5 kernel
// iteration, shared verbatim by the production driver (fastchain.cpp) and
// the design-space microbench (bench_fir.cpp) so the benchmarked kernel IS
// the production kernel.
//
// The plain folded kernel walks its two loadu windows one float per tap, so
// 15 of every 16 issues split a cache line and the load ports replay — port
// math says ~2 cycles/output but it measures ~4.2. Here each side's 32-float
// window is loaded ONCE per 16-float tap group and the 16 shifted views are
// synthesized with register alignment (valignd) ops; the FMA unit becomes
// the binding port. Measured +14-21% on a quiet machine across 32-256 taps,
// both strides (bench_fir sweep). Remainder taps (h % group) take the loadu
// step in the SAME ascending-k per-lane order, so output is bit-identical to
// the plain folded kernel for every tap count.
//
// Contract: textual include under __AVX512F__ only, AFTER <immintrin.h> and
// <cstdint> — the includer controls the enclosing namespace (fastchain.cpp
// pulls it into its anonymous namespace), so this header includes nothing.
#ifndef FSDR_FIR_VALIGN_H
#define FSDR_FIR_VALIGN_H

// concat[lo:hi][IMM + i] for i in [0,16). gcc12's _mm512_alignr_epi32 passes
// _mm512_undefined_epi32() as the masked-blend fallback operand, which
// -Wmaybe-uninitialized flags at every inlined instantiation — a known
// header false positive, suppressed here.
#pragma GCC diagnostic push
#pragma GCC diagnostic ignored "-Wmaybe-uninitialized"
template <int IMM>
static inline __m512 fc_pair_view(__m512 lo, __m512 hi) {
    return _mm512_castsi512_ps(_mm512_alignr_epi32(
        _mm512_castps_si512(hi), _mm512_castps_si512(lo), IMM));
}
#pragma GCC diagnostic pop

// One tap inside a group: the xa side descends S floats per tap from ha's
// base (la:ha covers [base-16, base+16)); the xb side ascends S floats per
// tap from lb's base (lb:hb covers [base2, base2+32)). IMM must be a
// compile-time constant, so the group is unrolled by template recursion.
template <int K, int G, int S>
struct FcTapG {
    static inline void run(const float* tp, __m512 la, __m512 ha, __m512 lb,
                           __m512 hb, __m512& acc) {
        const __m512 c = _mm512_set1_ps(tp[K]);
        __m512 va, vb;
        if constexpr (K == 0) {        // if constexpr: the alignr expansion in
            va = ha;                   // the dead branch trips gcc12's
            vb = lb;                   // -Wmaybe-uninitialized
        } else {
            va = fc_pair_view<(16 - K * S) & 15>(la, ha);
            vb = fc_pair_view<(K * S) & 15>(lb, hb);
        }
        acc = _mm512_fmadd_ps(c, _mm512_add_ps(va, vb), acc);
        FcTapG<K + 1, G, S>::run(tp, la, ha, lb, hb, acc);
    }
};
template <int G, int S>
struct FcTapG<G, G, S> {
    static inline void run(const float*, __m512, __m512, __m512, __m512,
                           __m512&) {}
};

// S = float stride (1 = f32 stream, 2 = interleaved c64 with real taps);
// group size G = 16/S taps spans exactly one register width per side.
template <int S>
inline void fir_sym_valign(const float* x, const float* taps, int64_t nt,
                           float* y, int64_t nf) {
    constexpr int G = 16 / S;
    const int64_t h = nt / 2;
    const int64_t Ls = (nt - 1) * S;
    const int64_t hg = (h / G) * G;
    int64_t j0 = 0;
    for (; j0 + 64 <= nf; j0 += 64) {
        __m512 acc[4] = {_mm512_setzero_ps(), _mm512_setzero_ps(),
                         _mm512_setzero_ps(), _mm512_setzero_ps()};
        for (int64_t g = 0; g < hg; g += G) {
            const float* pa = x + j0 - g * S;
            const float* pb = x + j0 - Ls + g * S;
            for (int r = 0; r < 4; ++r) {
                const __m512 la = _mm512_loadu_ps(pa + 16 * r - 16);
                const __m512 ha = _mm512_loadu_ps(pa + 16 * r);
                const __m512 lb = _mm512_loadu_ps(pb + 16 * r);
                const __m512 hb = _mm512_loadu_ps(pb + 16 * r + 16);
                FcTapG<0, G, S>::run(taps + g, la, ha, lb, hb, acc[r]);
            }
        }
        for (int64_t k = hg; k < h; ++k) {            // remainder taps
            const float* xa = x + j0 - k * S;
            const float* xb = x + j0 - Ls + k * S;
            const __m512 c = _mm512_set1_ps(taps[k]);
            for (int r = 0; r < 4; ++r)
                acc[r] = _mm512_fmadd_ps(
                    c,
                    _mm512_add_ps(_mm512_loadu_ps(xa + 16 * r),
                                  _mm512_loadu_ps(xb + 16 * r)),
                    acc[r]);
        }
        for (int r = 0; r < 4; ++r) _mm512_storeu_ps(y + j0 + 16 * r, acc[r]);
    }
    for (; j0 < nf; ++j0) {
        float s = 0;
        for (int64_t k = 0; k < h; ++k)
            s += taps[k] * (x[j0 - k * S] + x[j0 - Ls + k * S]);
        y[j0] = s;
    }
}

#endif  // FSDR_FIR_VALIGN_H
