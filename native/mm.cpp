// Mueller-Müller symbol timing recovery — the per-symbol adaptation loop.
//
// The MM control loop is inherently sequential (each recovered symbol updates the
// timing phase/rate used for the next), so it cannot vectorize; the reference runs
// it as compiled Rust (examples/zigbee/src/clock_recovery_mm.rs). This is the same
// loop as blocks/dsp.py::ClockRecoveryMm's Python fallback, bit-matched (double
// state, float32 stream), exported with a C ABI for the ctypes binding.

#include <cmath>
#include <cstdint>

extern "C" {

struct fsdr_mm_state {
    double omega;      // current samples/symbol estimate
    double omega0;     // nominal samples/symbol
    double mu;         // fractional sample phase in [0, 1)
    double last;       // previous interpolant s[k-1]
    double last_d;     // previous decision d[k-1]
    double gain_omega;
    double gain_mu;
    double limit;      // omega adaptation bound (fraction of omega0)
};

// Consume from in[0..n_in), producing at most max_out symbols. Returns the number
// of symbols produced; *consumed receives the number of input samples consumed.
// State is updated in place so successive calls continue the stream seamlessly.
//
// Arithmetic is float32 throughout, mirroring the Python loop under NEP 50: numpy
// weak promotion keeps every intermediate (interpolant, error, omega, mu) at the
// stream's float32 precision, and bit-matching the fallback is what makes the
// native path a drop-in (the golden tests pin these exact trajectories).
int64_t fsdr_mm_work(const float *in, int64_t n_in, float *out, int64_t max_out,
                     fsdr_mm_state *st, int64_t *consumed) {
    const int64_t need =
        static_cast<int64_t>(std::ceil(st->omega * (1.0 + st->limit))) + 2;
    int64_t i = 0, n_out = 0;
    float mu = static_cast<float>(st->mu);
    float omega = static_cast<float>(st->omega);
    float last = static_cast<float>(st->last);
    float last_d = static_cast<float>(st->last_d);
    const float gain_omega = static_cast<float>(st->gain_omega);
    const float gain_mu = static_cast<float>(st->gain_mu);
    const float lo = static_cast<float>(st->omega0 * (1.0 - st->limit));
    const float hi = static_cast<float>(st->omega0 * (1.0 + st->limit));
    while (i + need < n_in && n_out < max_out) {
        const float s = in[i] * (1.0f - mu) + in[i + 1] * mu;
        const float d = s > 0.0f ? 1.0f : -1.0f;
        const float err = last_d * s - d * last;
        last = s;
        last_d = d;
        out[n_out++] = s;
        omega += gain_omega * err;
        omega = omega < lo ? lo : (omega > hi ? hi : omega);
        const float step = omega + gain_mu * err;
        const float pos = (static_cast<float>(i) + mu) + step;
        i = static_cast<int64_t>(pos);
        mu = pos - static_cast<float>(i);
    }
    st->mu = mu;
    st->omega = omega;
    st->last = last;
    st->last_d = last_d;
    *consumed = i;
    return n_out;
}

}  // extern "C"
