// Soft-decision Viterbi for the IEEE 802.11 rate-1/2 mother code (K=7,
// g0=0133, g1=0171) — the WLAN CPU block path's hot loop.
//
// The per-step add-compare-select over 64 states is a tight sequential loop;
// numpy pays Python-loop overhead per trellis step, and the jax scan decoder
// only wins for long frames on a live backend. The reference decodes natively
// (examples/wlan/src/decoder.rs + viterbi crate); this is the C++ analog:
// branch metrics from two LLRs, butterfly ACS, per-step decision bytes, final
// traceback from state 0 (terminated trellis). Bit-matches the numpy path —
// ties broken identically (argmax takes the FIRST maximum, i.e. candidate 0).

#include <cstddef>
#include <cstdint>
#include <vector>

namespace {

constexpr int kNStates = 64;
constexpr uint32_t kG0 = 0133;   // octal, per 802.11 Clause 17.3.5.6
constexpr uint32_t kG1 = 0171;

struct Tables {
    // prev_s[s][j]: predecessor state for new state s via candidate j
    // prev_b[s][j]: the INPUT BIT that caused that transition
    // bm0/bm1[s][j]: +-1 weights multiplying llr0/llr1 for that branch
    int8_t prev_b[kNStates][2];
    uint8_t prev_s[kNStates][2];
    float bm0[kNStates][2];
    float bm1[kNStates][2];
};

int parity(uint32_t v) {
    return __builtin_parity(v);
}

// Mirrors models/wlan/coding.py exactly: shift register reg = (bit << 6) |
// state with the NEWEST input at the MSB, next_state = reg >> 1 = (bit << 5) |
// (state >> 1). Hence next-state t has TWO predecessors 2*(t & 31) and
// 2*(t & 31) + 1, both reached by the SAME input bit t >> 5; coding.py's
// _build_prev_tables appends them in increasing state order, so candidate
// j == 0 is the even predecessor (numpy argmax breaks ties toward it).
Tables build_tables() {
    Tables t{};
    for (int next = 0; next < kNStates; ++next) {
        const int bit = next >> 5;
        for (int j = 0; j < 2; ++j) {
            const int state = 2 * (next & 0x1f) + j;
            const uint32_t reg =
                (static_cast<uint32_t>(bit) << 6) | static_cast<uint32_t>(state);
            t.prev_s[next][j] = static_cast<uint8_t>(state);
            t.prev_b[next][j] = static_cast<int8_t>(bit);
            // LLR convention: positive => bit 1, so a branch emitting output
            // bit o adds +llr when o==1 and -llr when o==0
            t.bm0[next][j] = parity(reg & kG0) ? 1.0f : -1.0f;
            t.bm1[next][j] = parity(reg & kG1) ? 1.0f : -1.0f;
        }
    }
    return t;
}

const Tables &tables() {
    static const Tables t = build_tables();
    return t;
}

}  // namespace

extern "C" {

// Decode n_steps trellis steps from llrs[2*n_steps] (double, matching the
// numpy path's float64 metrics); writes n_steps bits to out. Traceback starts
// at state 0 (tail-flushed). Returns 0 on success.
int fsdr_viterbi_k7(const double *llrs, int64_t n_steps, uint8_t *out) {
    if (n_steps <= 0) return -1;
    const Tables &t = tables();

    std::vector<double> metrics(kNStates, -1e18);
    std::vector<double> next(kNStates);
    metrics[0] = 0.0;
    std::vector<uint8_t> decisions(static_cast<size_t>(n_steps) * kNStates);
    std::vector<uint8_t> src(static_cast<size_t>(n_steps) * kNStates);

    for (int64_t step = 0; step < n_steps; ++step) {
        const double l0 = llrs[2 * step];
        const double l1 = llrs[2 * step + 1];
        uint8_t *dec = &decisions[static_cast<size_t>(step) * kNStates];
        uint8_t *sr = &src[static_cast<size_t>(step) * kNStates];
        for (int s = 0; s < kNStates; ++s) {
            const double c0 = metrics[t.prev_s[s][0]]
                + t.bm0[s][0] * l0 + t.bm1[s][0] * l1;
            const double c1 = metrics[t.prev_s[s][1]]
                + t.bm0[s][1] * l0 + t.bm1[s][1] * l1;
            // numpy argmax keeps the FIRST max on ties — use strict > for c1
            const int j = (c1 > c0) ? 1 : 0;
            next[s] = j ? c1 : c0;
            sr[s] = t.prev_s[s][j];
            dec[s] = static_cast<uint8_t>(t.prev_b[s][j]);
        }
        metrics.swap(next);
    }

    int state = 0;
    for (int64_t step = n_steps - 1; step >= 0; --step) {
        out[step] = decisions[static_cast<size_t>(step) * kNStates + state];
        state = src[static_cast<size_t>(step) * kNStates + state];
    }
    return 0;
}

}  // extern "C"
