// FIR kernel design-space microbench (build: g++ -O3 -march=native -o bench_fir
// bench_fir.cpp && ./bench_fir [ntaps] [reps] [stride]).
//
// Round-5 measured space (2.1 GHz single-core VM, AVX-512, one 512-bit FMA
// unit): straight 8-wide tap-unrolled 360-395 Msps @64 taps; phase-major
// 440-455; folded symmetric 465-507; folded 128-wide tile 437. Port math for
// the folded kernel says ~2 cycles/output (4 loads/output on 2 load ports; 2
// fma + 2 add split across ports) but it measures ~4.2 — the gap is split
// (cache-line-crossing) unaligned loads: at 64 taps every 16-float loadu
// walks one float per tap, so 15 of 16 issues split a cache line and the
// load ports replay. The valignd variant loads each side's window ONCE per
// 16-tap group and synthesizes the 16 shifted views with register alignment
// (valignd, port-5) ops — split-load replays disappear and the FMA unit
// becomes the binding port. Hybrid: any tap remainder (h % group) falls back
// to the loadu step IN THE SAME accumulation order, so results stay
// bit-identical to the plain folded kernel for every tap count.
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>
#ifdef __AVX512F__
#include <immintrin.h>
#endif

// ---- baseline: folded symmetric (round-5 production kernel) ----------------
inline void fir_sym(const float* x, const float* taps, int64_t nt,
                    int64_t stride, float* y, int64_t nf) {
    const int64_t h = nt / 2;
    const int64_t Ls = (nt - 1) * stride;
    int64_t j0 = 0;
#ifdef __AVX512F__
    for (; j0 + 64 <= nf; j0 += 64) {
        __m512 a0 = _mm512_setzero_ps(), a1 = a0, a2 = a0, a3 = a0;
        for (int64_t k = 0; k < h; ++k) {
            const float* xa = x + j0 - k * stride;
            const float* xb = x + j0 - Ls + k * stride;
            const __m512 c = _mm512_set1_ps(taps[k]);
            a0 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa), _mm512_loadu_ps(xb)), a0);
            a1 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa + 16),
                                 _mm512_loadu_ps(xb + 16)), a1);
            a2 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa + 32),
                                 _mm512_loadu_ps(xb + 32)), a2);
            a3 = _mm512_fmadd_ps(
                c, _mm512_add_ps(_mm512_loadu_ps(xa + 48),
                                 _mm512_loadu_ps(xb + 48)), a3);
        }
        _mm512_storeu_ps(y + j0, a0);
        _mm512_storeu_ps(y + j0 + 16, a1);
        _mm512_storeu_ps(y + j0 + 32, a2);
        _mm512_storeu_ps(y + j0 + 48, a3);
    }
#endif
    for (; j0 < nf; ++j0) {
        float s = 0;
        for (int64_t k = 0; k < h; ++k)
            s += taps[k] * (x[j0 - k * stride] + x[j0 - Ls + k * stride]);
        y[j0] = s;
    }
}

#ifdef __AVX512F__
// The candidate kernel under test IS the production kernel (shared header).
#include "fir_valign.h"
#endif  // __AVX512F__

using Fn = void (*)(const float*, const float*, int64_t, int64_t, float*,
                    int64_t);

static void sym_wrap(const float* x, const float* taps, int64_t nt,
                     int64_t stride, float* y, int64_t n) {
    fir_sym(x, taps, nt, stride, y, n);
}
#ifdef __AVX512F__
static void valign_wrap(const float* x, const float* taps, int64_t nt,
                        int64_t stride, float* y, int64_t n) {
    if (stride == 1)
        fir_sym_valign<1>(x, taps, nt, y, n);
    else
        fir_sym_valign<2>(x, taps, nt, y, n);
}
#endif

static double bench(Fn fn, const float* x, const float* taps, int64_t nt,
                    int64_t stride, float* y, int64_t n, int reps) {
    using clk = std::chrono::steady_clock;
    fn(x, taps, nt, stride, y, n);  // warm
    double best = 0;
    for (int outer = 0; outer < 3; ++outer) {
        auto t0 = clk::now();
        for (int r = 0; r < reps; ++r) fn(x, taps, nt, stride, y, n);
        double dt = std::chrono::duration<double>(clk::now() - t0).count();
        double rate = n * double(reps) / dt / 1e6 / stride;  // items/s
        if (rate > best) best = rate;
    }
    return best;
}

int main(int argc, char** argv) {
    int nt = argc > 1 ? atoi(argv[1]) : 64;
    int reps = argc > 2 ? atoi(argv[2]) : 40;
    int64_t stride = argc > 3 ? atoi(argv[3]) : 1;
    int64_t n = (int64_t(1) << 21) * stride;     // floats in the output span
    std::vector<float> xs(n + 4 * nt, 0.0f), y1(n), y2(n), taps(nt);
    for (size_t i = 0; i < xs.size(); ++i)
        xs[i] = float((i * 2654435761u) % 1000) / 1000.f;
    for (int i = 0; i < nt / 2; ++i) taps[i] = taps[nt - 1 - i] = 1.f / (i + 1);
    const float* x = xs.data() + 4 * nt;

    double r1 = bench(sym_wrap, x, taps.data(), nt, stride, y1.data(), n, reps);
    printf("folded-loadu   %4d taps stride %d: %7.1f Msps\n", nt, int(stride),
           r1);
#ifdef __AVX512F__
    double r2 =
        bench(valign_wrap, x, taps.data(), nt, stride, y2.data(), n, reps);
    printf("folded-valignd %4d taps stride %d: %7.1f Msps  (%+.0f%%)\n", nt,
           int(stride), r2, 100.0 * (r2 / r1 - 1.0));
    if (std::memcmp(y1.data(), y2.data(), size_t(n) * sizeof(float)) == 0)
        printf("bit-identical\n");
    else {
        double md = 0;
        for (int64_t i = 0; i < n; ++i) {
            double d = double(y1[i]) - double(y2[i]);
            if (d < 0) d = -d;
            if (d > md) md = d;
        }
        printf("MISMATCH max |diff| = %g\n", md);
        return 1;
    }
#endif
    return 0;
}
