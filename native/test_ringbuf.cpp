// Native unit test for the double-mapped ring buffer (run via `make test`).
//
// Covers: double-mapping aliasing ([i] == [i+size]), SPSC wrap-around correctness under
// a writer thread + reader thread, and multi-reader space accounting — the invariants
// the Python layer relies on.

#include <atomic>
#include <cassert>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {
struct fsdr_dbuf;
fsdr_dbuf *fsdr_dbuf_create(size_t);
void fsdr_dbuf_destroy(fsdr_dbuf *);
unsigned char *fsdr_dbuf_ptr(fsdr_dbuf *);
size_t fsdr_dbuf_size(fsdr_dbuf *);

struct fsdr_ring;
fsdr_ring *fsdr_ring_create(unsigned long long);
void fsdr_ring_destroy(fsdr_ring *);
int fsdr_ring_add_reader(fsdr_ring *);
void fsdr_ring_remove_reader(fsdr_ring *, int);
unsigned long long fsdr_ring_wpos(fsdr_ring *);
unsigned long long fsdr_ring_rpos(fsdr_ring *, int);
unsigned long long fsdr_ring_space(fsdr_ring *);
unsigned long long fsdr_ring_available(fsdr_ring *, int);
void fsdr_ring_produce(fsdr_ring *, unsigned long long);
void fsdr_ring_consume(fsdr_ring *, int, unsigned long long);
}

static void test_double_mapping() {
    fsdr_dbuf *b = fsdr_dbuf_create(4096);
    assert(b);
    unsigned char *p = fsdr_dbuf_ptr(b);
    size_t n = fsdr_dbuf_size(b);
    for (size_t i = 0; i < n; i++) p[i] = (unsigned char)(i * 7);
    for (size_t i = 0; i < n; i++) assert(p[i] == p[i + n]);
    p[n + 5] = 0xAB;              // write through the second mapping
    assert(p[5] == 0xAB);
    fsdr_dbuf_destroy(b);
    printf("double-mapping aliasing: OK\n");
}

static void test_spsc_threads() {
    const unsigned long long CAP = 1024, TOTAL = 1000000;
    fsdr_dbuf *b = fsdr_dbuf_create(CAP);
    unsigned char *data = fsdr_dbuf_ptr(b);
    size_t cap = fsdr_dbuf_size(b);
    fsdr_ring *r = fsdr_ring_create(cap);
    int rid = fsdr_ring_add_reader(r);
    assert(rid >= 0);

    std::thread writer([&] {
        unsigned long long sent = 0;
        while (sent < TOTAL) {
            unsigned long long space = fsdr_ring_space(r);
            if (!space) continue;
            unsigned long long n = space < TOTAL - sent ? space : TOTAL - sent;
            unsigned long long off = fsdr_ring_wpos(r) % cap;
            for (unsigned long long i = 0; i < n; i++)
                data[off + i] = (unsigned char)((sent + i) & 0xFF);
            fsdr_ring_produce(r, n);
            sent += n;
        }
    });
    unsigned long long got = 0;
    bool ok = true;
    while (got < TOTAL) {
        unsigned long long avail = fsdr_ring_available(r, rid);
        if (!avail) continue;
        unsigned long long off = fsdr_ring_rpos(r, rid) % cap;
        for (unsigned long long i = 0; i < avail; i++)
            if (data[off + i] != (unsigned char)((got + i) & 0xFF)) ok = false;
        fsdr_ring_consume(r, rid, avail);
        got += avail;
    }
    writer.join();
    assert(ok);
    fsdr_ring_destroy(r);
    fsdr_dbuf_destroy(b);
    printf("SPSC wrap-around under threads: OK (%llu items)\n", TOTAL);
}

static void test_multi_reader_space() {
    fsdr_ring *r = fsdr_ring_create(100);
    int a = fsdr_ring_add_reader(r);
    int b2 = fsdr_ring_add_reader(r);
    fsdr_ring_produce(r, 60);
    fsdr_ring_consume(r, a, 60);
    assert(fsdr_ring_space(r) == 40);   // slowest reader (b) gates the writer
    fsdr_ring_consume(r, b2, 10);
    assert(fsdr_ring_space(r) == 50);
    fsdr_ring_remove_reader(r, b2);
    assert(fsdr_ring_space(r) == 100);  // detached reader no longer counted
    fsdr_ring_destroy(r);
    printf("multi-reader space accounting: OK\n");
}

int main() {
    test_double_mapping();
    test_spsc_threads();
    test_multi_reader_space();
    printf("all native tests passed\n");
    return 0;
}
