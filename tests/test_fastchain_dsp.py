"""Native fast-chain v2 DSP stages (`native/fastchain.cpp` FC_FIR_*/FC_QUAD_DEMOD):
whole pipes containing real filters run as one C++ thread, A/B-checked against
the Python actor path. FIR outputs match to float32 rounding (the native kernel
accumulates taps in ascending order; `np.convolve` routes through BLAS), so the
comparisons use allclose; copy-class chains elsewhere stay bit-exact."""

import os

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import CopyRand, Fir, Head, NullSink, NullSource, \
    QuadratureDemod, VectorSink, VectorSource
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.runtime.fastchain import fastchain_available, find_native_chains

pytestmark = pytest.mark.skipif(not fastchain_available(),
                                reason="native fastchain unavailable")


def _run_ab(build):
    """Run `build()`-produced (fg, sink) twice — fused and actor — and return
    both collected arrays."""
    fg, vs = build()
    assert len(find_native_chains(fg)) == 1, "chain did not fuse"
    Runtime().run(fg)
    got_native = vs.items().copy()
    os.environ["FSDR_NO_FASTCHAIN"] = "1"
    try:
        fg2, vs2 = build()
        assert find_native_chains(fg2) == []
        Runtime().run(fg2)
    finally:
        os.environ.pop("FSDR_NO_FASTCHAIN", None)
    return got_native, vs2.items()


def test_fir_chain_matches_actor_path():
    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    rng = np.random.default_rng(11)
    data = rng.standard_normal(30_000).astype(np.float32)

    def build():
        fg = Flowgraph()
        src = VectorSource(data)
        vs = VectorSink(np.float32)
        fg.connect(src, CopyRand(np.float32, max_copy=777, seed=3),
                   Fir(taps, np.float32),
                   CopyRand(np.float32, max_copy=129, seed=5),
                   Fir(taps, np.float32), vs)
        return fg, vs

    native, actor = _run_ab(build)
    assert len(native) == len(actor) == len(data)
    np.testing.assert_allclose(native, actor, rtol=2e-5, atol=1e-6)


def test_decimating_fir_chain_counts_and_values():
    taps = firdes.lowpass(0.1, 48).astype(np.float32)
    rng = np.random.default_rng(12)
    data = rng.standard_normal(10_001).astype(np.float32)   # odd length on purpose

    def build():
        fg = Flowgraph()
        vs = VectorSink(np.float32)
        fg.connect(VectorSource(data), Fir(taps, np.float32, decim=4), vs)
        return fg, vs

    native, actor = _run_ab(build)
    assert len(native) == len(actor) == -(-len(data) // 4)   # ceil(n/decim)
    np.testing.assert_allclose(native, actor, rtol=2e-5, atol=1e-6)


def test_complex_fir_quad_demod_fm_chain():
    """The FM front-end shape: c64 stream → decimating FIR (f32 taps) → quad
    demod (c64 → f32) — exercises per-edge item sizes across a dtype change."""
    taps = firdes.lowpass(0.15, 64).astype(np.float32)
    rng = np.random.default_rng(13)
    iq = (rng.standard_normal(20_000) + 1j * rng.standard_normal(20_000)) \
        .astype(np.complex64)

    def build():
        fg = Flowgraph()
        vs = VectorSink(np.float32)
        fg.connect(VectorSource(iq), Fir(taps, np.complex64, decim=2),
                   QuadratureDemod(gain=0.7), vs)
        return fg, vs

    native, actor = _run_ab(build)
    assert len(native) == len(actor) == 10_000
    # atan2 near small-magnitude arguments amplifies the f32 FIR rounding
    np.testing.assert_allclose(native, actor, rtol=2e-4, atol=1e-5)


def test_complex_taps_xlating_fir():
    base = firdes.lowpass(0.2, 32).astype(np.float32)
    taps = (base * np.exp(2j * np.pi * 0.05 * np.arange(32))).astype(np.complex64)
    rng = np.random.default_rng(14)
    iq = (rng.standard_normal(8_000) + 1j * rng.standard_normal(8_000)) \
        .astype(np.complex64)

    def build():
        fg = Flowgraph()
        vs = VectorSink(np.complex64)
        fg.connect(VectorSource(iq), CopyRand(np.complex64, max_copy=333, seed=7),
                   Fir(taps, np.complex64), vs)
        return fg, vs

    native, actor = _run_ab(build)
    np.testing.assert_allclose(native, actor, rtol=3e-5, atol=2e-6)


def test_xlating_fir_chain_matches_actor_path():
    """FC_XLATING: rotate→FIR→decimate in one native stage — the front half of
    every receiver (blocks.XlatingFir) now fuses."""
    from futuresdr_tpu.blocks import XlatingFir
    fs = 250e3
    taps = firdes.lowpass(0.1, 64).astype(np.float32)
    rng = np.random.default_rng(21)
    iq = (rng.standard_normal(24_000) + 1j * rng.standard_normal(24_000)) \
        .astype(np.complex64)

    def build():
        fg = Flowgraph()
        vs = VectorSink(np.complex64)
        xf = XlatingFir(taps, decim=5, offset_freq=12e3, sample_rate=fs)
        xf.fastchain_static = True     # promise: no runtime freq retunes
        fg.connect(VectorSource(iq),
                   CopyRand(np.complex64, max_copy=513, seed=4), xf, vs)
        return fg, vs

    native, actor = _run_ab(build)
    assert len(native) == len(actor) == -(-24_000 // 5)
    np.testing.assert_allclose(native, actor, rtol=2e-4, atol=2e-5)


def test_xlating_fir_not_fused_without_static_optin():
    """Default: a block with a live retune handler stays on the actor path —
    a fused chain cannot service handle.call(freq) (review regression)."""
    from futuresdr_tpu.blocks import XlatingFir
    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    fg = Flowgraph()
    fg.connect(VectorSource(np.zeros(1000, np.complex64)),
               XlatingFir(taps, decim=2, offset_freq=1e3, sample_rate=48e3),
               NullSink(np.complex64))
    assert find_native_chains(fg) == []


def test_xlating_fir_with_connected_freq_port_not_fused():
    """A message EDGE into the xlating block's freq port must keep it on the
    actor path (retunes need the live handler)."""
    from futuresdr_tpu.blocks import MessageBurst, XlatingFir
    from futuresdr_tpu import Pmt
    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    fg = Flowgraph()
    xf = XlatingFir(taps, decim=2, offset_freq=1e3, sample_rate=48e3)
    # opt-in SET: the message-EDGE exclusion must hold even when the user
    # promised static operation (the edge proves they lied) — without this
    # line the opt-in gate already excludes the block and the test is vacuous
    xf.fastchain_static = True
    fg.connect(VectorSource(np.zeros(1000, np.complex64)), xf,
               NullSink(np.complex64))
    tuner = MessageBurst(Pmt.f64(2e3), 1)
    fg.connect_message(tuner, "out", xf, "freq")
    assert find_native_chains(fg) == []


def test_agc_chain_matches_actor_path_and_writes_back_gain():
    """FC_AGC: the per-sample feedback loop (blocks.Agc mode='sample') runs
    natively; the final gain is written back to kernel.gain like the actor
    path leaves it."""
    from futuresdr_tpu.blocks import Agc
    rng = np.random.default_rng(31)
    iq = (0.25 * (rng.standard_normal(20_000) + 1j * rng.standard_normal(20_000))
          ).astype(np.complex64)
    gains = {}

    def build():
        fg = Flowgraph()
        vs = VectorSink(np.complex64)
        agc = Agc(np.complex64, reference=1.0, adjustment_rate=1e-3)
        agc.fastchain_static = True    # promise: no gain_lock/reference calls
        fg.connect(VectorSource(iq), CopyRand(np.complex64, max_copy=601,
                                              seed=6), agc, vs)
        gains["last"] = agc
        return fg, vs

    native, actor = _run_ab(build)
    # _run_ab's second run was the actor build — its kernel holds actor gain
    actor_gain = gains["last"].gain
    fg_n, _ = build()
    Runtime().run(fg_n)
    native_gain = gains["last"].gain

    np.testing.assert_allclose(native, actor, rtol=2e-5, atol=1e-6)
    assert native_gain > 1.0           # quiet input: gain climbed
    # glibc hypotf and numpy's npy_hypotf can differ by 1 ulp on |x|, so the
    # 20k-step feedback trajectory lands within a few ulps, not bit-equal
    np.testing.assert_allclose(native_gain, actor_gain, rtol=1e-6)


def test_agc_not_fused_without_static_optin_or_in_block_mode():
    from futuresdr_tpu.blocks import Agc
    fg = Flowgraph()
    fg.connect(VectorSource(np.zeros(1000, np.complex64)),
               Agc(np.complex64), NullSink(np.complex64))
    assert find_native_chains(fg) == []          # no opt-in
    fg2 = Flowgraph()
    a2 = Agc(np.complex64, mode="block")
    a2.fastchain_static = True
    fg2.connect(VectorSource(np.zeros(1000, np.complex64)), a2,
                NullSink(np.complex64))
    assert find_native_chains(fg2) == []         # block mode stays actor


def test_kernel_state_writeback_after_fused_run():
    """Round-4 advisory: post-run attribute reads must match the actor path —
    Head.remaining hits 0, VectorSource shows its position consumed."""
    taps = firdes.lowpass(0.2, 16).astype(np.float32)
    data = np.arange(4_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data, repeat=3)
    head = Head(np.float32, 7_000)
    snk = NullSink(np.float32)
    fg.connect(src, head, Fir(taps, np.float32), snk)
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    assert head.remaining == 0
    # the source EMITS its full budget into the (64k-item) ring even though the
    # Head only forwards 7000 — exactly like the actor path, whose 256 KiB
    # stream buffer also swallows all 12000 before the Head stops consuming
    assert (src._round, src._pos) == (3, 0)
    assert snk.n_received == 7_000


def test_mid_stream_fir_state_not_eligible():
    taps = firdes.lowpass(0.2, 16).astype(np.float32)
    fir = Fir(taps, np.float32)
    fir.core.process(np.zeros(10, dtype=np.float32))   # leaves history behind
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Head(np.float32, 1000), fir,
               NullSink(np.float32))
    assert find_native_chains(fg) == []


def test_f64_taps_not_eligible():
    taps = firdes.lowpass(0.2, 16)                     # float64 by default
    assert taps.dtype == np.float64
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Head(np.float32, 1000),
               Fir(taps, np.float32), NullSink(np.float32))
    assert find_native_chains(fg) == []


def test_untyped_passthrough_between_widths_not_fused():
    """Review regression (heap overflow): an UNTYPED Copy between a c64 edge
    and an f32 edge must not fuse — the C driver would memcpy 8-byte items
    into a 4-byte ring."""
    from futuresdr_tpu.blocks import Copy
    taps = firdes.lowpass(0.2, 16).astype(np.float32)
    iq = np.zeros(1000, dtype=np.complex64)
    fg = Flowgraph()
    fg.connect(VectorSource(iq), Fir(taps, np.complex64), Copy(None),
               NullSink(np.float32))
    assert find_native_chains(fg) == []


def test_rate_changing_stage_metrics_are_per_port():
    """A decimating FIR reports consumed ≠ produced through the live bridge."""
    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    fg = Flowgraph()
    fir = Fir(taps, np.float32, decim=8)
    snk = NullSink(np.float32)
    fg.connect(NullSource(np.float32), Head(np.float32, 80_000), fir, snk)
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    w = fg.wrapped(fir)
    m = w.metrics()
    assert m["fused_native"] is True
    assert m["items_in"]["in"] == 80_000
    assert m["items_out"]["out"] == 10_000
    assert snk.n_received == 10_000


def test_fused_dsp_chain_live_metrics_over_rest():
    """The fused chain's live counter bridge serves honest per-port counts for
    a RATE-CHANGING stage through the real REST surface while the native loop
    is mid-run — the HTTP twin of test_fastchain's handle-based check."""
    import json
    import time
    import urllib.request

    from futuresdr_tpu import Runtime
    from futuresdr_tpu.runtime.ctrl_port import ControlPort

    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    fg = Flowgraph()
    fir = Fir(taps, np.float32, decim=8)
    snk = NullSink(np.float32)
    fg.connect(NullSource(np.float32), Head(np.float32, 300_000_000), fir, snk)
    assert len(find_native_chains(fg)) == 1
    rt = Runtime()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29633")
    cp.start()
    running = rt.start(fg)
    try:
        base = "http://127.0.0.1:29633"
        deadline = time.time() + 15
        seen = None
        while time.time() < deadline:
            m = json.load(urllib.request.urlopen(f"{base}/api/fg/0/metrics/"))
            # the decimating FIR is the one fused member consuming MORE than
            # it produces (Head/source/sink are 1:1)
            fir_m = next((v for v in m.values()
                          if v.get("fused_native")
                          and v["items_out"].get("out", 0) > 0
                          and v["items_in"].get("in", 0)
                          > v["items_out"]["out"]), None)
            if fir_m:
                seen = fir_m
                break
            time.sleep(0.05)
        assert seen is not None, "fused metrics never appeared over REST"
        # decimating stage: consumed ≈ produced × 8, live mid-run
        assert seen["items_in"]["in"] >= 8 * seen["items_out"]["out"] > 0
    finally:
        running.stop_sync()
        cp.stop()


@pytest.mark.parametrize("interp,decim,dtype", [
    (3, 2, np.float32), (2, 5, np.float32), (12, 5, np.complex64),
    (5, 12, np.complex64)])
def test_rational_resampler_chain_matches_actor(interp, decim, dtype):
    """FC_RESAMPLE: Fir(interp≠1) — the rational polyphase resampler — fuses
    with exact output counts (the m_hi contract of dsp/kernels.py) and
    allclose values across up/down ratios and both dtypes."""
    taps = firdes.lowpass(0.4 / max(interp, decim), 48).astype(np.float32)
    rng = np.random.default_rng(41)
    n = 10_007                                     # odd on purpose
    if dtype == np.complex64:
        data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)) \
            .astype(np.complex64)
    else:
        data = rng.standard_normal(n).astype(np.float32)

    def build():
        fg = Flowgraph()
        vs = VectorSink(dtype)
        fg.connect(VectorSource(data),
                   CopyRand(dtype, max_copy=431, seed=9),
                   Fir(taps, dtype, decim=decim, interp=interp), vs)
        return fg, vs

    native, actor = _run_ab(build)
    assert len(native) == len(actor), (len(native), len(actor))
    np.testing.assert_allclose(native, actor, rtol=3e-5, atol=3e-6)


def test_resampler_f64_taps_not_fused():
    taps = firdes.lowpass(0.1, 32)                 # float64
    fg = Flowgraph()
    fg.connect(VectorSource(np.zeros(1000, np.float32)),
               Fir(taps, np.float32, interp=2, decim=3), NullSink(np.float32))
    assert find_native_chains(fg) == []


def test_file_source_dsp_chain_fuses(tmp_path):
    """FileSource replays as a memmap-backed native source: a whole
    file → FIR → demod receiver pipe runs in C, matching the actor path."""
    rng = np.random.default_rng(61)
    iq = (rng.standard_normal(16_000) + 1j * rng.standard_normal(16_000)) \
        .astype(np.complex64)
    path = str(tmp_path / "capture.cf32")
    iq.tofile(path)
    taps = firdes.lowpass(0.2, 48).astype(np.float32)

    def build():
        from futuresdr_tpu.blocks import FileSource
        fg = Flowgraph()
        vs = VectorSink(np.float32)
        fg.connect(FileSource(path, np.complex64),
                   Fir(taps, np.complex64, decim=4),
                   QuadratureDemod(gain=1.0), vs)
        return fg, vs

    native, actor = _run_ab(build)
    assert len(native) == len(actor) == 4_000
    np.testing.assert_allclose(native, actor, rtol=2e-4, atol=1e-5)


def test_file_source_repeat_bounded_by_head(tmp_path):
    """repeat=True replays the file forever natively (infinite cyclic
    budget); Head bounds it and the wrap seam matches the actor path."""
    from futuresdr_tpu.blocks import Copy, FileSource
    data = np.arange(1000, dtype=np.float32)
    path = str(tmp_path / "loop.f32")
    data.tofile(path)

    def build():
        fg = Flowgraph()
        vs = VectorSink(np.float32)
        fg.connect(FileSource(path, np.float32, repeat=True),
                   Head(np.float32, 3_500), Copy(np.float32), vs)
        return fg, vs

    native, actor = _run_ab(build)
    want = np.concatenate([data, data, data, data[:500]])
    np.testing.assert_array_equal(native, want)
    np.testing.assert_array_equal(actor, want)


def test_file_to_file_dsp_chain_fully_native(tmp_path):
    """file → xlating front end → quad demod → resampler → file, all in C:
    the file_trx rx shape end to end, byte-compared against the actor path."""
    from futuresdr_tpu.blocks import FileSink, FileSource, XlatingFir
    rng = np.random.default_rng(71)
    iq = (rng.standard_normal(30_000) + 1j * rng.standard_normal(30_000)) \
        .astype(np.complex64)
    src_path = str(tmp_path / "in.cf32")
    iq.tofile(src_path)
    taps = firdes.lowpass(0.08, 64).astype(np.float32)
    rtaps = firdes.lowpass(0.2, 36).astype(np.float32)
    outs = {}

    def build():
        fg = Flowgraph()
        xf = XlatingFir(taps, decim=5, offset_freq=20e3, sample_rate=250e3)
        xf.fastchain_static = True
        path = str(tmp_path / f"out{len(outs)}.f32")
        outs[len(outs)] = path
        sink = FileSink(path, np.float32)
        fg.connect(FileSource(src_path, np.complex64), xf,
                   QuadratureDemod(gain=1.0),
                   Fir(rtaps, np.float32, interp=2, decim=3), sink)
        # VectorSink-style probe is absent: compare the files themselves
        return fg, sink

    fg_n, sink_n = build()
    assert len(find_native_chains(fg_n)) == 1
    Runtime().run(fg_n)
    os.environ["FSDR_NO_FASTCHAIN"] = "1"
    try:
        fg_a, sink_a = build()
        assert find_native_chains(fg_a) == []
        Runtime().run(fg_a)
    finally:
        os.environ.pop("FSDR_NO_FASTCHAIN", None)
    native = np.fromfile(outs[0], np.float32)
    actor = np.fromfile(outs[1], np.float32)
    assert sink_n.n_written == len(native) == len(actor) > 0
    np.testing.assert_allclose(native, actor, rtol=3e-4, atol=2e-5)


def test_unbounded_file_sink_not_fused(tmp_path):
    """NullSource (infinite) → FileSink must stay on the actor path: a fused
    bounded-collection sink would buffer forever."""
    from futuresdr_tpu.blocks import Copy, FileSink
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Copy(np.float32),
               FileSink(str(tmp_path / "x.f32"), np.float32))
    assert find_native_chains(fg) == []


def test_large_bounded_file_sink_not_fused(tmp_path):
    """A bounded output above the 256 MB RAM gate streams on the actor path
    (the fused sink buffers everything before its one-shot flush)."""
    from futuresdr_tpu.blocks import FileSink
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Head(np.float32, 100_000_000),
               FileSink(str(tmp_path / "big.f32"), np.float32))
    assert find_native_chains(fg) == []


def test_unwritable_file_sink_path_errors_cleanly(tmp_path):
    """An unwritable sink path must surface as a flowgraph error (like the
    actor path's init failure), never hang the supervisor."""
    from futuresdr_tpu.blocks import FileSink
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Head(np.float32, 1000),
               FileSink(str(tmp_path / "no" / "such" / "dir" / "x.f32"),
                        np.float32))
    assert len(find_native_chains(fg)) == 1
    with pytest.raises(Exception):
        Runtime().run(fg)


def test_bounded_file_sink_above_gate_not_fused(tmp_path):
    """A bounded-but-huge output (here 500M f32 = 2 GB after decimation)
    stays on the streaming actor path — the RAM gate applies to the
    POST-rate-transform bound, not the source budget."""
    from futuresdr_tpu.blocks import FileSink
    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Head(np.float32, 2_000_000_000),
               Fir(taps, np.float32, decim=4),
               FileSink(str(tmp_path / "part.f32"), np.float32))
    assert find_native_chains(fg) == []


def test_terminate_stops_fused_dsp_chain():
    """Terminate mid-run stops a DSP-bearing fused chain cleanly: the stop
    flag reaches the C loop, BlockDone flows for every member, and the
    decimating stage's counters stay rate-consistent."""
    import time

    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    fg = Flowgraph()
    fir = Fir(taps, np.float32, decim=4)
    snk = NullSink(np.float32)
    fg.connect(NullSource(np.float32), fir, snk)      # unbounded: stop() ends it
    assert len(find_native_chains(fg)) == 1
    rt = Runtime()
    running = rt.start(fg)
    deadline = time.perf_counter() + 10.0
    seen = 0
    while time.perf_counter() < deadline and seen == 0:
        m = running.handle.metrics_sync()
        seen = max((v["items_out"].get("out", 0) for v in m.values()
                    if v.get("fused_native")), default=0)
        time.sleep(0.01)
    assert seen > 0, "fused DSP chain never made progress"
    running.stop_sync()                    # Terminate → stop flag → clean join
    assert snk.n_received > 0
    w = fg.wrapped(fir)
    m = w.metrics()
    # consumed ≈ produced × decim (within one in-flight chunk)
    assert m["items_in"]["in"] >= 4 * m["items_out"]["out"] > 0


def test_signal_source_chain_bit_exact():
    """FC_SIG: the fxpt NCO source fuses with a BIT-exact phase schedule (the
    wrapping-u32 ramp is integer) — sample values match the actor path to
    float32 rounding of the same f64 trig, and the tone lands on frequency."""
    from futuresdr_tpu.blocks import SignalSource

    from futuresdr_tpu.dsp import fxpt
    sigs = {}

    def build(waveform, dtype):
        fg = Flowgraph()
        vs = VectorSink(dtype)
        sig = SignalSource(waveform, 12_500.0, 250e3, amplitude=0.8,
                           offset=0.1)
        sig.fastchain_static = True    # promise: no runtime freq/amp calls
        sigs["last"] = sig
        fg.connect(sig, Head(dtype, 50_000), vs)
        return fg, vs

    for waveform, dtype in (("complex", np.complex64), ("sin", np.float32),
                            ("square", np.float32)):
        fg, vs = build(waveform, dtype)
        assert len(find_native_chains(fg)) == 1, waveform
        Runtime().run(fg)
        native = vs.items().copy()
        # NCO phase write-back: post-fused-run state matches the actor
        # path's wrap-advance over everything the source EMITTED (the ring
        # swallows more than Head forwards)
        sig_n = sigs["last"]
        assert sig_n._phase_i == fxpt.advance_u32(
            0, sig_n._inc_i, sig_n.output.items_produced)
        os.environ["FSDR_NO_FASTCHAIN"] = "1"
        try:
            fg2, vs2 = build(waveform, dtype)
            Runtime().run(fg2)
        finally:
            os.environ.pop("FSDR_NO_FASTCHAIN", None)
        actor = vs2.items()
        assert len(native) == len(actor) == 50_000
        np.testing.assert_allclose(native, actor, rtol=1e-6, atol=1e-6,
                                   err_msg=waveform)
        if waveform == "complex":
            # single-sided spectral check: the complex tone lands on its bin
            # (a real waveform would have an equal mirror bin — review)
            spec = np.abs(np.fft.fft(native[:16384]))
            assert np.argmax(spec) == round(12_500.0 / 250e3 * 16384)


def test_signal_source_not_fused_without_optin_or_float_nco():
    from futuresdr_tpu.blocks import SignalSource
    fg = Flowgraph()
    fg.connect(SignalSource("sin", 1e3, 48e3), Head(np.float32, 100),
               NullSink(np.float32))
    assert find_native_chains(fg) == []          # no opt-in
    fg2 = Flowgraph()
    s2 = SignalSource("sin", 1e3, 48e3, nco="float")
    s2.fastchain_static = True
    fg2.connect(s2, Head(np.float32, 100), NullSink(np.float32))
    assert find_native_chains(fg2) == []         # float NCO stays actor


def test_random_chain_shapes_fuzz():
    """Seeded sweep over random ELIGIBLE chain shapes: stage mixes across
    both dtype lanes (copies, plain/decim/resampling FIRs, xlating, AGC,
    quad demod), random data and chunking — every fused chain must match its
    actor twin. The chain-composition analog of the receiver family fuzzes;
    also run by perf/fuzz_campaign.py with shifted seeds."""
    from futuresdr_tpu.blocks import Agc, XlatingFir
    if not fastchain_available():
        return          # campaign calls this directly, bypassing the skipif
    rng = np.random.default_rng(4242)
    for trial in range(6):
        complex_lane = bool(rng.integers(0, 2))
        dt = np.complex64 if complex_lane else np.float32
        n = int(rng.integers(6_000, 20_000))
        if complex_lane:
            data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)) \
                .astype(np.complex64)
        else:
            data = rng.standard_normal(n).astype(np.float32)
        n_stages = int(rng.integers(1, 5))
        spec = []
        for _ in range(n_stages):
            kind = rng.choice(["copyrand", "fir", "decim", "resample",
                               "xlating", "agc"] if complex_lane else
                              ["copyrand", "fir", "decim", "resample"])
            spec.append(str(kind))
        demod_tail = complex_lane and bool(rng.integers(0, 2))

        def build():
            nonlocal rng_b
            rng_b = np.random.default_rng(pseed)   # identical params per path
            fg = Flowgraph()
            src = VectorSource(data)
            last = src
            cur_dt = dt
            for kind in spec:
                if kind == "copyrand":
                    b = CopyRand(cur_dt, int(rng_b.integers(64, 1024)),
                                 seed=int(rng_b.integers(1, 99)))
                elif kind == "fir":
                    b = Fir(firdes.lowpass(0.2, int(rng_b.integers(8, 65))
                                           ).astype(np.float32), cur_dt)
                elif kind == "decim":
                    b = Fir(firdes.lowpass(0.1, 32).astype(np.float32),
                            cur_dt, decim=int(rng_b.integers(2, 5)))
                elif kind == "resample":
                    b = Fir(firdes.lowpass(0.1, 24).astype(np.float32),
                            cur_dt, interp=int(rng_b.integers(2, 4)),
                            decim=int(rng_b.integers(2, 6)))
                elif kind == "xlating":
                    b = XlatingFir(firdes.lowpass(0.1, 32).astype(np.float32),
                                   decim=int(rng_b.integers(1, 4)),
                                   offset_freq=float(rng_b.uniform(-2e4, 2e4)),
                                   sample_rate=250e3)
                    b.fastchain_static = True
                else:
                    b = Agc(cur_dt, reference=0.8, adjustment_rate=1e-3)
                    b.fastchain_static = True
                fg.connect(last, b)
                last = b
            if demod_tail:
                b = QuadratureDemod(gain=float(rng_b.uniform(0.3, 2.0)))
                gains["demod"] = b.gain
                fg.connect(last, b)
                last = b
                cur_dt = np.float32
            vs = VectorSink(cur_dt)
            fg.connect(last, vs)
            return fg, vs

        gains = {}
        pseed = int(rng.integers(0, 1 << 30))
        rng_b = None
        native, actor = _run_ab(build)
        assert len(native) == len(actor), (trial, spec)
        bad = ~np.isclose(native, actor, rtol=5e-4, atol=5e-5)
        if demod_tail and bad.any():
            # the demod's ±π branch cut: a 1-ulp FIR difference can flip
            # atan2 across the cut, giving wrap-EQUIVALENT outputs that
            # differ by exactly 2π·gain — both are correct demod values
            wrap = 2 * np.pi * gains["demod"]
            np.testing.assert_allclose(
                np.abs(np.asarray(native)[bad] - np.asarray(actor)[bad]),
                wrap, rtol=1e-3,
                err_msg=f"{trial} {spec} non-wrap mismatch")
        else:
            assert not bad.any(), (trial, spec, int(bad.sum()))


def test_delay_chain_pad_and_skip_match_actor():
    """FC_DELAY: positive delay zero-pads the front, negative skips inputs —
    both through the native chain bit-exactly (copy-class data)."""
    from futuresdr_tpu.blocks import Delay
    data = np.arange(1, 9_001, dtype=np.float32)
    for n in (137, -251):
        def build():
            fg = Flowgraph()
            vs = VectorSink(np.float32)
            d = Delay(np.float32, n)
            d.fastchain_static = True   # promise: no new_value retunes
            fg.connect(VectorSource(data),
                       CopyRand(np.float32, max_copy=333, seed=2), d, vs)
            return fg, vs

        native, actor = _run_ab(build)
        np.testing.assert_array_equal(native, actor)
        if n > 0:
            assert len(native) == len(data) + n
            assert not native[:n].any() and native[n] == 1.0
        else:
            assert len(native) == len(data) + n
            assert native[0] == float(-n + 1)


def test_delay_not_fused_without_static_optin():
    from futuresdr_tpu.blocks import Delay
    fg = Flowgraph()
    fg.connect(VectorSource(np.zeros(100, np.float32)),
               Delay(np.float32, 5), NullSink(np.float32))
    assert find_native_chains(fg) == []
