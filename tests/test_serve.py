"""Multi-tenant flowgraph serving (ISSUE 11 tentpole, docs/serving.md):
slot-table ragged admission over the vmapped serving engine, per-session
carry evict/re-admit riding the checkpoint leaf contract, per-tenant fair
credits, per-session fault isolation, slot-bucket autotune axis, and the
REST session plane."""

import json
import urllib.request

import numpy as np
import pytest

from futuresdr_tpu.ops.stages import (FanoutPipeline, Pipeline, fir_stage,
                                      rotator_stage)
from futuresdr_tpu.serve import (ServeEngine, ServeFull,
                                 TenantCreditController, register_app,
                                 unregister_app)

FRAME = 1024


def _pipe():
    taps = np.hanning(31).astype(np.float32)
    return Pipeline([fir_stage(taps, fft_len=256), rotator_stage(0.03)],
                    np.complex64)


def _frames(n, seed=0, frame=FRAME):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal(frame) + 1j * rng.standard_normal(frame))
            .astype(np.complex64) for _ in range(n)]


def _solo(pipe, frames):
    """The bare fused pipeline, frame by frame — the bit-equality
    reference."""
    fn, carry = pipe.compile(FRAME, donate=False)
    out = []
    for f in frames:
        carry, y = fn(carry, f)
        out.append(np.asarray(y))
    return out


def _drain(eng, *sessions):
    while eng.step():
        pass
    return [eng.results(s.sid) for s in sessions]


# ---------------------------------------------------------------------------
# bit-equality: the serving program IS the pipeline, per lane
# ---------------------------------------------------------------------------

def test_n1_serving_bit_equals_bare_pipeline():
    """Acceptance: N=1 serving ≡ the bare fused pipeline, bit for bit — in
    the capacity-1 bucket AND in a capacity-4 bucket with three masked pad
    lanes (the masked-lane merge must not perturb the active lane)."""
    pipe = _pipe()
    data = _frames(6)
    exp = _solo(pipe, data)
    for buckets in ((1,), (4,)):
        eng = ServeEngine(_pipe(), frame_size=FRAME, app=f"n1b{buckets[0]}",
                          buckets=buckets, queue_frames=8)
        s = eng.admit(tenant="a")
        for f in data:
            assert eng.submit(s.sid, f)
        (out,) = _drain(eng, s)
        assert len(out) == len(exp)
        for a, b in zip(out, exp):
            np.testing.assert_array_equal(a, b)


def test_join_leave_mid_stream_bit_equality():
    """Sessions joining and leaving mid-stream never perturb a resident
    session's stream: every session's outputs equal its own solo run."""
    pipe = _pipe()
    d0, d1, d2 = _frames(6, 1), _frames(4, 2), _frames(3, 3)
    exp = [_solo(pipe, d) for d in (d0, d1, d2)]
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="joinleave",
                      buckets=(1, 2, 4), queue_frames=8)
    s0 = eng.admit(tenant="a")
    for f in d0[:2]:
        assert eng.submit(s0.sid, f)
    eng.step()
    s1 = eng.admit(tenant="b")        # join mid-flight (bucket growth)
    for f in d0[2:]:
        assert eng.submit(s0.sid, f)
    for f in d1:
        assert eng.submit(s1.sid, f)
    out0, out1 = _drain(eng, s0, s1)
    eng.close(s1.sid)                 # leave mid-stream
    s2 = eng.admit(tenant="c")        # reuses the freed lane, fresh carry
    for f in d2:
        assert eng.submit(s2.sid, f)
    (out2,) = _drain(eng, s2)
    out0 += eng.results(s0.sid)
    for got, want in zip((out0, out1, out2), exp):
        assert len(got) == len(want)
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b)


def test_stalled_lane_carry_is_bit_frozen():
    """A session with no input simply masks its lane: its carry is frozen
    bit-exactly while siblings dispatch, and its stream resumes as if
    nothing happened."""
    pipe = _pipe()
    d0, d1 = _frames(6, 4), _frames(9, 5)
    exp0 = _solo(pipe, d0)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="stall",
                      buckets=(2,), queue_frames=16)
    s0 = eng.admit(tenant="a")
    s1 = eng.admit(tenant="b")
    for f in d0[:3]:
        assert eng.submit(s0.sid, f)
    for f in d1:
        assert eng.submit(s1.sid, f)
    (head,) = _drain(eng, s0)         # s0 stalls after 3 frames; s1 keeps going
    assert eng.table.get(s0.sid).stall_steps > 0
    for f in d0[3:]:
        assert eng.submit(s0.sid, f)
    (tail,) = _drain(eng, s0)
    out0 = head + tail
    assert len(out0) == 6
    for a, b in zip(out0, exp0):
        np.testing.assert_array_equal(a, b)


def test_megabatch_k4_join_leave_at_boundaries():
    """K>1 megabatch serving: joins/leaves land at megabatch boundaries via
    the ragged per-lane-frame mask — a resident session's outputs under
    churn are BIT-IDENTICAL to the same session served alone at the same K
    (K>1 scan programs round differently from K=1 by repo contract, so the
    pin is interference-freedom at matched K, exactly like the devchain
    megabatch tests pin K=4 against K=4)."""
    d0, d1 = _frames(7, 6), _frames(3, 7)
    solo_eng = ServeEngine(_pipe(), frame_size=FRAME, app="k4solo",
                           buckets=(2,), queue_frames=16,
                           frames_per_dispatch=4)
    sA = solo_eng.admit(tenant="a")
    for f in d0[:4]:
        assert solo_eng.submit(sA.sid, f)
    assert solo_eng.step() == 4       # one full megabatch group
    for f in d0[4:]:
        assert solo_eng.submit(sA.sid, f)
    assert solo_eng.step() == 3       # ragged tail masked in-program
    solo = solo_eng.results(sA.sid)
    assert len(solo) == 7

    churn = ServeEngine(_pipe(), frame_size=FRAME, app="k4churn",
                        buckets=(2,), queue_frames=16,
                        frames_per_dispatch=4)
    sX = churn.admit(tenant="a")
    for f in d0[:4]:
        assert churn.submit(sX.sid, f)
    assert churn.step() == 4
    sY = churn.admit(tenant="b")      # join at the megabatch boundary
    for f in d0[4:]:
        assert churn.submit(sX.sid, f)
    for f in d1:
        assert churn.submit(sY.sid, f)
    assert churn.step() == 6          # both lanes ragged inside one dispatch
    churn.close(sY.sid)               # leave at the boundary
    outX = churn.results(sX.sid)
    assert len(outX) == 7
    for a, b in zip(outX, solo):
        np.testing.assert_array_equal(a, b)
    assert churn.dispatches == 2      # still one dispatch per step


def test_stall_evict_readmit_round_trip():
    """Acceptance: stall → evict (carry to host) → re-admit restores the
    session BIT-IDENTICALLY — the serving-plane analog of the kernel
    checkpoint restore, on the same leaf contract."""
    pipe = _pipe()
    data = _frames(10, 8)
    exp = _solo(pipe, data)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="evict",
                      buckets=(1, 2), queue_frames=16)
    s = eng.admit(tenant="a")
    for f in data[:5]:
        assert eng.submit(s.sid, f)
    (head,) = _drain(eng, s)
    eng.evict(s.sid)
    assert s.state == "evicted" and s.slot is None
    assert s.carry_leaves is not None
    # queued input survives eviction, but an evicted session never
    # dispatches
    for f in data[5:]:
        assert eng.submit(s.sid, f)
    eng.step()
    assert len(eng.results(s.sid)) == 0
    # a sibling may take the lane meanwhile
    other = eng.admit(tenant="b")
    eng.readmit(s.sid)
    (tail,) = _drain(eng, s)
    got = head + tail
    assert len(got) == 10
    for a, b in zip(got, exp):
        np.testing.assert_array_equal(a, b)
    assert other.state == "active"


def test_readmit_validates_carry_contract():
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="badcarry",
                      buckets=(1, 2), queue_frames=4)
    s = eng.admit(tenant="a")
    assert eng.submit(s.sid, _frames(1, 9)[0])
    eng.step()
    eng.evict(s.sid)
    s.carry_leaves = [np.zeros(3, np.uint8) for _ in s.carry_leaves]
    with pytest.raises(ValueError, match="contract"):
        eng.readmit(s.sid)


# ---------------------------------------------------------------------------
# slot buckets: growth without recompiles
# ---------------------------------------------------------------------------

def test_bucket_growth_without_recompile_of_resident_buckets():
    """Acceptance pin: session churn inside resident buckets causes ZERO
    recompiles; crossing a bucket boundary compiles exactly the new bucket
    once (and restacks carries without disturbing resident sessions)."""
    pipe = _pipe()
    data = _frames(4, 10)
    exp = _solo(pipe, data)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="grow",
                      buckets=(1, 2, 4), queue_frames=32)
    s0 = eng.admit(tenant="a")
    assert eng.submit(s0.sid, data[0])
    eng.step()
    assert eng.compiles == 1 and eng.capacity == 1
    s1 = eng.admit(tenant="b")        # 1 -> 2 growth
    assert eng.capacity == 2
    assert eng.submit(s0.sid, data[1])
    eng.step()
    assert eng.compiles == 2
    # churn INSIDE the resident bucket: close + admit repeatedly
    for i in range(5):
        eng.close(s1.sid)
        s1 = eng.admit(tenant="b")
        assert eng.submit(s1.sid, _frames(1, 20 + i)[0])
        eng.step()
    assert eng.compiles == 2, "churn recompiled a resident bucket"
    # the resident session's stream was never perturbed
    for f in data[2:]:
        assert eng.submit(s0.sid, f)
    (out0,) = _drain(eng, s0)
    assert len(out0) == 4
    for a, b in zip(out0, exp):
        np.testing.assert_array_equal(a, b)
    # growth to 4, then refusal past the largest bucket
    eng.admit(tenant="c")
    eng.admit(tenant="c")
    assert eng.capacity == 4 and eng.compiles == 2   # compile is lazy (next step)
    with pytest.raises(ServeFull):
        for _ in range(8):
            eng.admit(tenant="d")


def test_configured_bucket_ladder(monkeypatch):
    from futuresdr_tpu.config import config
    monkeypatch.setattr(config(), "serve_buckets", "2, 8")
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="ladder")
    assert eng.buckets == (2, 8)


# ---------------------------------------------------------------------------
# per-tenant fairness
# ---------------------------------------------------------------------------

def test_tenant_credit_fairness_unit():
    c = TenantCreditController(8)
    c.register("a")
    c.register("b")
    assert c.fair_share() == 4
    # a may borrow past its fair share only out of unreserved headroom
    grants = sum(c.try_acquire("a") for _ in range(8))
    assert grants == 4, "borrowing ate into b's guaranteed share"
    # b's fair share is grantable no matter how wedged a is
    assert all(c.try_acquire("b") for _ in range(4))
    assert not c.try_acquire("b")
    # released credits go back to their OWNER's guarantee first: b still
    # cannot borrow past its share while a's reserve is unexhausted, but a
    # can always reclaim up to its fair share
    c.release("a", 2)
    assert not c.try_acquire("b")
    assert c.try_acquire("a") and c.try_acquire("a")
    # lone tenant uses the whole budget
    solo = TenantCreditController(8)
    solo.register("x")
    assert sum(solo.try_acquire("x") for _ in range(10)) == 8


def test_stalled_tenant_cannot_starve_siblings():
    """Engine-level starvation guard: a tenant whose session stalls with a
    full queue cannot deny a sibling tenant its fair share of submit
    credits."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="fair",
                      buckets=(2,), queue_frames=2)     # total = 4 credits
    hog = eng.admit(tenant="hog")
    vip = eng.admit(tenant="vip")
    data = _frames(6, 11)
    # hog fills its queue and never dispatches (we never step) — its fair
    # share is 2 of 4, and borrowing must stop before vip's guarantee
    got = sum(eng.submit(hog.sid, f) for f in data[:4])
    assert got == 2
    assert eng.submit(vip.sid, data[4])
    assert eng.submit(vip.sid, data[5])


# ---------------------------------------------------------------------------
# per-session fault isolation
# ---------------------------------------------------------------------------

def test_session_fault_retires_only_its_slot():
    from futuresdr_tpu.runtime import faults
    pipe = _pipe()
    da, db = _frames(4, 12), _frames(4, 13)
    expa = _solo(pipe, da)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="faulty",
                      buckets=(2,), queue_frames=16)
    sa = eng.admit(tenant="a", sid="iso_a")
    sb = eng.admit(tenant="b", sid="iso_b")
    plan = faults.reset()
    plan.arm("dispatch:iso_b", rate=1.0, max_faults=1, seed=1)
    try:
        for fa, fb in zip(da, db):
            assert eng.submit(sa.sid, fa)
            if sb.state == "active":
                eng.submit(sb.sid, fb)
            eng.step()
    finally:
        faults.reset()
    assert sb.state == "retired" and sb.error
    assert eng.session_view("iso_b")["state"] == "retired"
    outa = eng.results(sa.sid)
    assert len(outa) == 4
    for a, b in zip(outa, expa):
        np.testing.assert_array_equal(a, b)
    # the retired session refuses new input
    with pytest.raises(ValueError, match="retired"):
        eng.submit(sb.sid, db[0])


def test_retired_tenant_releases_its_fair_share_reservation():
    """A tenant whose sessions all faulted must not keep its fair-share
    credits reserved forever: retirement unregisters the tenant once it has
    no live (active/evicted) session left, so a lone surviving tenant can
    use the whole budget again."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="fairret",
                      buckets=(2,), queue_frames=4)      # total = 8 credits
    a = eng.admit(tenant="dead")
    b = eng.admit(tenant="live")
    eng._retire(eng.table.get(a.sid), RuntimeError("injected"))
    # the retired session stays viewable, but its tenant no longer divides
    # the budget — "live" gets all 8 credits, not total - fair = 4
    assert eng.session_view(a.sid)["state"] == "retired"
    assert all(eng.submit(b.sid, f) for f in _frames(8, 17))
    # and closing the last live session of a tenant with only retired
    # siblings left unregisters it too
    eng.close(b.sid)
    assert eng.credits.snapshot() == {}


def test_retired_sessions_are_pruned_beyond_retention():
    """Bounded retired-session retention (config ``serve_retired_keep``):
    fault churn in a long-running process must not grow the session
    registry without bound — only the newest N retired views survive."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="retkeep", buckets=(2,))
    eng._retired_keep = 2
    sids = []
    for _ in range(4):
        s = eng.admit(tenant="t")
        eng._retire(eng.table.get(s.sid), RuntimeError("injected"))
        sids.append(s.sid)
    assert eng.table.get(sids[0]) is None and eng.table.get(sids[1]) is None
    assert eng.table.get(sids[2]).state == "retired"
    assert eng.table.get(sids[3]).state == "retired"


def test_step_dispatch_failure_requeues_frames(monkeypatch):
    """A real (non-injected) transfer/dispatch error inside step() must not
    silently lose the popped frames: they go back to the front of their
    queues with their credits re-taken, the carries stay untouched, and a
    retry dispatches the exact same frames — output bit-identical to a
    fault-free run."""
    from futuresdr_tpu.ops import xfer
    pipe = _pipe()
    data = _frames(3, 19)
    expected = _solo(pipe, data)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="rollback",
                      buckets=(2,), queue_frames=4)
    s = eng.admit(tenant="t0")
    for f in data:
        assert eng.submit(s.sid, f)
    assert eng.credits.used("t0") == 3

    real = xfer.to_device
    state = {"boom": True}

    def flaky(*args, **kw):
        if state["boom"]:
            state["boom"] = False
            raise RuntimeError("transient transfer error")
        return real(*args, **kw)

    monkeypatch.setattr(xfer, "to_device", flaky)
    with pytest.raises(RuntimeError, match="transient transfer error"):
        eng.step()
    # rolled back: frames re-queued in order, credits re-taken, nothing out
    sess = eng.table.get(s.sid)
    assert len(sess.pending) == 3 and sess.frames_out == 0
    assert eng.credits.used("t0") == 3
    assert eng.dispatches == 0
    # the retry re-dispatches the same frames bit-identically
    while eng.step():
        pass
    got = eng.results(s.sid)
    assert len(got) == 3
    for g, e in zip(got, expected):
        np.testing.assert_array_equal(g, e)


# ---------------------------------------------------------------------------
# fan-out pipelines serve too (multi-sink delivery)
# ---------------------------------------------------------------------------

def test_fanout_pipeline_serving_multi_sink():
    import jax
    taps = np.hanning(17).astype(np.float32)

    def mk():
        return FanoutPipeline(
            [rotator_stage(0.01)],
            [[fir_stage(taps, fft_len=128)], [rotator_stage(0.2)]],
            np.complex64)

    fan = mk()
    data = _frames(3, 14)
    fn = jax.jit(fan.fn())
    carry = fan.init_carry()
    exp = []
    for f in data:
        carry, ys = fn(carry, f)
        exp.append(tuple(np.asarray(y) for y in ys))
    eng = ServeEngine(mk(), frame_size=FRAME, app="fanout",
                      buckets=(2,), queue_frames=8)
    s = eng.admit(tenant="a")
    for f in data:
        assert eng.submit(s.sid, f)
    (out,) = _drain(eng, s)
    assert len(out) == 3
    for got, want in zip(out, exp):
        assert isinstance(got, tuple) and len(got) == 2
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# autotuned slot buckets (tpu/autotune.py serve axis)
# ---------------------------------------------------------------------------

def test_autotune_serve_buckets_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("FUTURESDR_TPU_AUTOTUNE_CACHE_DIR", str(tmp_path))
    from futuresdr_tpu.config import reload_config
    reload_config()
    try:
        import importlib
        at = importlib.import_module("futuresdr_tpu.tpu.autotune")
        pipe = Pipeline([rotator_stage(0.07)], np.complex64)
        ladder, results = at.autotune_serve(pipe, frame_size=256,
                                            capacities=(1, 2, 4), reps=2)
        assert ladder and ladder[0] == 1
        assert set(results) >= set(ladder)
        got = at.cached_serve_buckets(pipe, np.complex64, "cpu")
        assert got == ladder
        # the serving-plane axis must survive a streamed re-record
        at.record_streamed_pick(pipe.stages, np.complex64, "cpu", 2,
                                inflight=3)
        entry = at.cached_streamed_pick(pipe.stages, np.complex64, "cpu")
        assert entry["k"] == 2 and entry["serve_buckets"] == ladder
        # and the engine consumes the cached ladder
        eng = ServeEngine(Pipeline([rotator_stage(0.07)], np.complex64),
                          frame_size=256, app="tuned")
        assert list(eng.buckets) == ladder
    finally:
        monkeypatch.delenv("FUTURESDR_TPU_AUTOTUNE_CACHE_DIR")
        reload_config()


# ---------------------------------------------------------------------------
# REST session plane + per-tenant exposition
# ---------------------------------------------------------------------------

def test_serve_rest_session_api():
    from futuresdr_tpu import Runtime
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="restapp",
                      buckets=(1, 2), queue_frames=8)
    register_app(eng)
    rt = Runtime()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29644")
    cp.start()
    base = "http://127.0.0.1:29644"
    try:
        apps = json.load(urllib.request.urlopen(f"{base}/api/serve/"))
        assert "restapp" in apps

        def post(path, body=None):
            req = urllib.request.Request(
                f"{base}{path}", data=json.dumps(body or {}).encode(),
                headers={"Content-Type": "application/json"}, method="POST")
            return json.load(urllib.request.urlopen(req))

        s = post("/api/serve/restapp/session/", {"tenant": "gold"})
        sid = s["sid"]
        assert s["state"] == "active" and s["tenant"] == "gold"
        # drive a frame through so the view carries real numbers
        assert eng.submit(sid, _frames(1, 15)[0])
        eng.step()
        view = json.load(urllib.request.urlopen(
            f"{base}/api/serve/restapp/session/{sid}/"))
        assert view["frames_out"] == 1 and view["tenant"] == "gold"
        desc = json.load(urllib.request.urlopen(f"{base}/api/serve/restapp/"))
        assert desc["dispatches"] == 1
        assert "gold" in desc["tenants"]
        # per-tenant Prometheus labels on /metrics
        text = urllib.request.urlopen(f"{base}/metrics").read().decode()
        assert 'fsdr_serve_frames_total{app="restapp",tenant="gold"} 1' \
            in text
        # evict → readmit → delete over REST
        assert post(f"/api/serve/restapp/session/{sid}/evict/")["state"] \
            == "evicted"
        assert post(f"/api/serve/restapp/session/{sid}/readmit/")["state"] \
            == "active"
        req = urllib.request.Request(
            f"{base}/api/serve/restapp/session/{sid}/", method="DELETE")
        assert json.load(urllib.request.urlopen(req)) == {"ok": True}
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"{base}/api/serve/restapp/session/{sid}x/")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/api/serve/nosuchapp/")
    finally:
        cp.stop()
        unregister_app("restapp")


def test_prometheus_stable_label_ordering():
    """Satellite: /metrics exposition emits samples of a family in a stable
    order regardless of label-set CREATION order — scrape diffing and the
    regress harness see deterministic text."""
    from futuresdr_tpu.telemetry import prom
    c1 = prom.Counter("order_probe_total", "t", ("app", "tenant"))
    c1.inc(app="z", tenant="t9")
    c1.inc(app="a", tenant="t1")
    c1.inc(app="m", tenant="t5")
    first = "\n".join(c1.render())
    c2 = prom.Counter("order_probe_total", "t", ("app", "tenant"))
    c2.inc(app="m", tenant="t5")
    c2.inc(app="z", tenant="t9")
    c2.inc(app="a", tenant="t1")
    assert "\n".join(c2.render()) == first
    lines = [l for l in first.splitlines() if not l.startswith("#")]
    assert lines == sorted(lines)
    # histogram children follow the same contract
    h1 = prom.Histogram("order_probe_seconds", "t", ("tenant",))
    h1.observe(0.1, tenant="zz")
    h1.observe(0.2, tenant="aa")
    h2 = prom.Histogram("order_probe_seconds", "t", ("tenant",))
    h2.observe(0.2, tenant="aa")
    h2.observe(0.1, tenant="zz")
    assert "\n".join(h1.render()) == "\n".join(h2.render())
