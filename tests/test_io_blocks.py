"""I/O block tests: file roundtrip, TCP pipe, seify dummy driver, ctrl port REST.

Reference: `tests/seify.rs` (dummy driver), `tests/channel_source.rs`, ctrl_port routes.
"""

import asyncio
import threading

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import (FileSource, FileSink, VectorSource, VectorSink, Head,
                                  SeifySource, SeifySink, SeifyBuilder, TcpSink, TcpSource,
                                  ChannelSource, ChannelSink, NullSink)


def test_file_roundtrip(tmp_path):
    path = str(tmp_path / "samples.bin")
    data = np.random.default_rng(0).standard_normal(10_000).astype(np.float32)
    fg = Flowgraph()
    fg.connect(VectorSource(data), FileSink(path, np.float32))
    Runtime().run(fg)

    fg2 = Flowgraph()
    src = FileSource(path, np.float32)
    snk = VectorSink(np.float32)
    fg2.connect(src, snk)
    Runtime().run(fg2)
    np.testing.assert_array_equal(snk.items(), data)


def test_seify_dummy_source():
    fg = Flowgraph()
    src = SeifyBuilder().args("driver=dummy,throttle=false").sample_rate(1e6).build_source()
    head = Head(np.complex64, 50_000)
    snk = VectorSink(np.complex64)
    fg.connect(src, head, snk)
    Runtime().run(fg)
    x = snk.items()
    assert len(x) == 50_000
    # dummy driver: tone at 10% of fs dominates
    spec = np.abs(np.fft.fft(x[:16384] * np.hanning(16384)))
    assert abs(np.fft.fftfreq(16384)[np.argmax(spec)] - 0.1) < 0.01


def test_seify_sink_and_handlers():
    fg = Flowgraph()
    src = ChannelSource(np.complex64)
    snk = SeifySink("driver=dummy")
    fg.connect(src, snk)
    rt = Runtime()
    running = rt.start(fg)
    rt.scheduler.run_coro_sync(src.queue.put(np.zeros(10_000, np.complex64)))
    r = rt.scheduler.run_coro_sync(running.handle.call(snk, "freq", Pmt.f64(433e6)))
    assert r == Pmt.ok()
    rt.scheduler.run_coro_sync(src.queue.put(None))   # EOS after the call landed
    running.wait_sync()
    assert snk.device.driver.tx_written == 10_000
    assert snk.device.driver.frequency == 433e6


def test_file_driver_replay(tmp_path):
    """driver=file replays an IQ recording through the seify source (file-trx role)."""
    path = str(tmp_path / "iq.c64")
    data = np.exp(1j * 2 * np.pi * 0.05 * np.arange(5000)).astype(np.complex64)
    data.tofile(path)
    fg = Flowgraph()
    src = SeifySource(f"driver=file,path={path},throttle=false,repeat=true")
    head = Head(np.complex64, 12_000)
    snk = VectorSink(np.complex64)
    fg.connect(src, head, snk)
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == 12_000
    np.testing.assert_array_equal(got[:5000], data)
    np.testing.assert_array_equal(got[5000:10000], data)   # looped


def test_seify_cmd_config_map():
    fg = Flowgraph()
    src = SeifySource("driver=dummy,throttle=false")
    head = Head(np.complex64, 1000)
    snk = NullSink(np.complex64)
    fg.connect(src, head, snk)
    rt = Runtime()
    running = rt.start(fg)
    r = rt.scheduler.run_coro_sync(running.handle.call(
        src, "cmd", Pmt.map({"freq": 94.2e6, "gain": 30.0})))
    assert r == Pmt.ok()
    running.stop_sync()
    assert src.device.driver.frequency == 94.2e6
    assert src.device.driver.gain == 30.0


def test_tcp_pipe():
    port = 28712
    data = np.arange(20_000, dtype=np.float32)

    fg_rx = Flowgraph()
    tsrc = TcpSource("127.0.0.1", port, np.float32, listen=True)
    rsnk = VectorSink(np.float32)
    fg_rx.connect(tsrc, rsnk)
    rt_rx = Runtime()
    running_rx = rt_rx.start(fg_rx)

    fg_tx = Flowgraph()
    fg_tx.connect(VectorSource(data), TcpSink("127.0.0.1", port, np.float32))
    Runtime().run(fg_tx)

    running_rx.wait_sync()
    np.testing.assert_array_equal(rsnk.items(), data)


def test_channel_source_sink():
    q_in = None
    fg = Flowgraph()
    src = ChannelSource(np.float32)
    snk = ChannelSink(np.float32)
    fg.connect(src, snk)
    rt = Runtime()
    running = rt.start(fg)

    async def feed():
        await src.queue.put(np.arange(100, dtype=np.float32))
        await src.queue.put(np.arange(100, 200, dtype=np.float32))
        await src.queue.put(None)

    rt.scheduler.run_coro_sync(feed())
    running.wait_sync()

    chunks = []
    async def drain():
        while True:
            c = snk.queue.get_nowait()
            if c is None:
                return
            chunks.append(c)

    rt.scheduler.run_coro_sync(drain())
    np.testing.assert_array_equal(np.concatenate(chunks), np.arange(200, dtype=np.float32))


def test_ctrl_port_rest_roundtrip():
    """Full REST path: list → describe → call handler (reference ctrl_port routes)."""
    import json
    import urllib.request

    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    from futuresdr_tpu.blocks import SignalSource

    fg = Flowgraph()
    src = SignalSource("complex", 1000.0, 48000.0)
    head = Head(np.complex64, 10_000_000)
    snk = NullSink(np.complex64)
    fg.connect(src, head, snk)
    rt = Runtime()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29317")
    cp.start()
    running = rt.start(fg)
    try:
        base = "http://127.0.0.1:29317"
        ids = json.load(urllib.request.urlopen(f"{base}/api/fg/"))
        assert ids == [0]
        desc = json.load(urllib.request.urlopen(f"{base}/api/fg/0/"))
        assert len(desc["blocks"]) == 3
        b0 = json.load(urllib.request.urlopen(f"{base}/api/fg/0/block/0/"))
        assert b0["type_name"] == "SignalSource"
        req = urllib.request.Request(
            f"{base}/api/fg/0/block/0/call/freq/",
            data=json.dumps({"F64": 2000.0}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        r = json.load(urllib.request.urlopen(req))
        assert r == "Ok"
        # remote client speaks the same API
        from futuresdr_tpu.ctrl import Remote

        async def via_client():
            rfg = await Remote(base).flowgraph(0)
            blk = await rfg.block(0)
            assert "freq" in blk.handlers()          # typed handler enumeration
            conns = await rfg.connections()
            assert any(c.kind == "stream" for c in conns)
            return await blk.callback("freq", Pmt.f64(3000.0))

        res = rt.scheduler.run_coro_sync(via_client())
        assert res == Pmt.ok()
    finally:
        running.stop_sync()
        cp.stop()
