"""M17 tests: codecs (callsign/CRC/Golay/conv) and 4FSK LSF loopback."""

import numpy as np
import pytest

from futuresdr_tpu.models.m17 import (encode_callsign, decode_callsign, crc16_m17,
                                      golay24_encode, golay24_decode, conv_encode_m17,
                                      viterbi_decode_m17, Lsf, build_lsf_frame,
                                      modulate, demodulate_stream)


def test_callsign_roundtrip():
    for cs in ["W2FBI", "SP5WWP", "N0CALL", "AB1CDE-9"]:
        assert decode_callsign(encode_callsign(cs)) == cs
    assert decode_callsign(encode_callsign("@ALL")) == "@ALL"


def test_crc16_m17_known_vectors():
    # vectors from the M17 spec §2.5.4
    assert crc16_m17(b"") == 0xFFFF
    assert crc16_m17(b"A") == 0x206E
    assert crc16_m17(b"123456789") == 0x772B


def test_golay_roundtrip_and_correction():
    rng = np.random.default_rng(0)
    for d in [0x000, 0xFFF, 0xABC, 0x123]:
        w = golay24_encode(d)
        assert golay24_decode(w) == d
        # up to 3 errors in the 23-bit part are corrected
        for n_err in (1, 2, 3):
            pos = rng.choice(23, n_err, replace=False)
            bad = w
            for p in pos:
                bad ^= 1 << (p + 1)
            assert golay24_decode(bad) == d


def test_conv_viterbi_m17():
    rng = np.random.default_rng(1)
    bits = np.concatenate([rng.integers(0, 2, 240), np.zeros(4)]).astype(np.uint8)
    coded = conv_encode_m17(bits)
    llrs = coded.astype(np.float64) * 2 - 1
    flip = rng.choice(len(llrs), 20, replace=False)
    llrs[flip] *= -1
    dec = viterbi_decode_m17(llrs, len(bits))
    np.testing.assert_array_equal(dec, bits)


def test_lsf_roundtrip():
    lsf = Lsf(dst="@ALL", src="SP5WWP", type_field=0x0005, meta=b"hello meta din")
    raw = lsf.to_bytes()
    assert len(raw) == 30
    back = Lsf.from_bytes(raw)
    assert back.dst == "@ALL" and back.src == "SP5WWP"
    assert back.type_field == 0x0005
    bad = bytearray(raw)
    bad[3] ^= 0xFF
    assert Lsf.from_bytes(bytes(bad)) is None


def test_4fsk_lsf_loopback():
    lsf = Lsf(dst="N0CALL", src="W2FBI")
    syms = build_lsf_frame(lsf)
    sig = modulate(syms)
    sig = np.concatenate([np.zeros(173, np.float32), sig, np.zeros(200, np.float32)])
    found = demodulate_stream(sig)
    assert len(found) == 1
    assert found[0].dst == "N0CALL" and found[0].src == "W2FBI"


def test_m17_flowgraph_loopback():
    import numpy as _np
    from futuresdr_tpu import Flowgraph, Runtime, Pmt
    from futuresdr_tpu.blocks import Apply
    from futuresdr_tpu.models.m17 import M17Transmitter, M17Receiver

    rng = _np.random.default_rng(4)
    fg = Flowgraph()
    tx = M17Transmitter()
    chan = Apply(lambda x: (x + 0.05 * rng.standard_normal(len(x))
                            ).astype(_np.float32), _np.float32)
    rx = M17Receiver()
    fg.connect(tx, chan, rx)
    rt = Runtime()
    running = rt.start(fg)
    msgs = [{"dst": "@ALL", "src": "W2FBI", "meta": Pmt.blob(b"beacon 1 meta!")},
            {"dst": "N0CALL", "src": "SP5WWP", "meta": Pmt.blob(b"second beacon.")}]
    for m in msgs:
        r = rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.map(m)))
        assert r == Pmt.ok()
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()
    assert [(f.dst, f.src) for f in rx.frames] == [("@ALL", "W2FBI"),
                                                   ("N0CALL", "SP5WWP")]


def test_4fsk_loopback_noise():
    rng = np.random.default_rng(2)
    lsf = Lsf(dst="AB1CDE", src="SP5WWP")
    sig = modulate(build_lsf_frame(lsf))
    sig = sig + 0.1 * rng.standard_normal(len(sig)).astype(np.float32)
    found = demodulate_stream(sig)
    assert len(found) == 1 and found[0].src == "SP5WWP"


def test_stream_returns_frames_in_time_order():
    """Interrogation standard: 8 noisy bursts decode exactly once each, IN TIME
    ORDER — the per-phase sync search used to return them phase-major."""
    rng = np.random.default_rng(4)
    parts, sent = [], []
    for i in range(8):
        lsf = Lsf(src=f"N{i}CALL", dst="ALLCALL")
        sent.append(lsf.src)
        parts += [np.zeros(500 + 53 * i, np.float32),
                  modulate(build_lsf_frame(lsf)).astype(np.float32)]
    parts.append(np.zeros(600, np.float32))
    sig = np.concatenate(parts)
    sig = (sig + 0.08 * rng.standard_normal(len(sig))).astype(np.float32)
    got = [l.src for l in demodulate_stream(sig)]
    assert got == sent, got


def test_stream_mode_loopback():
    """Stream mode (`encoder.rs:226-289`): LSF + LICH-chunked payload frames
    with P2-punctured conv coding and EOS; two noisy transmissions decode
    exactly once each, in time order."""
    from futuresdr_tpu.models.m17 import (Lsf, build_stream_frames, modulate,
                                          demodulate_payload_stream)
    rng = np.random.default_rng(4)
    lsf = Lsf(dst="SP5WWP", src="N0CALL")
    pl_a = b"M17 stream mode carries voice or data frames end to end!"
    pl_b = b"second transmission"
    parts = [np.zeros(400, np.float32)]
    for pl in (pl_a, pl_b):
        parts += [modulate(build_stream_frames(lsf, pl)).astype(np.float32),
                  np.zeros(700, np.float32)]
    x = np.concatenate(parts)
    x = (x + 0.08 * rng.standard_normal(len(x))).astype(np.float32)
    out = demodulate_payload_stream(x)
    assert len(out) == 2, len(out)
    for (l, p, complete), pl in zip(out, (pl_a, pl_b)):
        assert complete
        assert l is not None and l.src == "N0CALL" and l.dst == "SP5WWP"
        assert p[:len(pl)] == pl and len(p) % 16 == 0


def test_stream_mode_lsf_from_lich():
    """With the link-setup frame unusable (mid-LSF cut), the LSF reassembles
    from the six cycling Golay-protected LICH chunks, CRC-checked."""
    from futuresdr_tpu.models.m17 import (Lsf, build_stream_frames, modulate,
                                          demodulate_payload_stream)
    rng = np.random.default_rng(5)
    lsf = Lsf(dst="SP5WWP", src="N0CALL")
    payload = bytes(range(112))                  # 7 frames: full LICH cycle
    sig = modulate(build_stream_frames(lsf, payload))
    x = np.concatenate([np.zeros(300, np.float32), sig.astype(np.float32),
                        np.zeros(300, np.float32)])
    x = (x + 0.06 * rng.standard_normal(len(x))).astype(np.float32)
    out = demodulate_payload_stream(x[300 + 1000:])
    assert len(out) == 1
    l, p, complete = out[0]
    assert complete and p[:len(payload)] == payload
    assert l is not None and l.src == "N0CALL" and l.dst == "SP5WWP"


def test_stream_mode_through_blocks():
    """Transmitter tx message with a payload blob → stream-mode frames →
    receiver posts the transmission with dst/src/payload."""
    from futuresdr_tpu import Flowgraph, Runtime, Pmt
    from futuresdr_tpu.blocks import Apply
    from futuresdr_tpu.models.m17 import M17Receiver, M17Transmitter

    rng = np.random.default_rng(6)
    tx = M17Transmitter(src_callsign="N0CALL")
    chan = Apply(lambda v: (v + 0.05 * rng.standard_normal(len(v))
                            ).astype(np.float32), np.float32)
    rx = M17Receiver()
    fg = Flowgraph()
    fg.connect(tx, chan, rx)
    rt = Runtime()
    running = rt.start(fg)
    payload = b"hello from the stream path"
    rt.scheduler.run_coro_sync(running.handle.call(
        tx, "tx", Pmt.map({"dst": "@ALL", "payload": Pmt.blob(payload)})))
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()
    assert len(rx.transmissions) == 1, rx.transmissions
    l, p = rx.transmissions[0]
    assert l is not None and l.src == "N0CALL" and l.dst == "@ALL"
    assert p[:len(payload)] == payload


def test_stream_mode_rejects_truncated_group():
    """A window catching only the TAIL of a transmission (fn 2..) must not
    report a complete — and therefore silently corrupted — payload."""
    from futuresdr_tpu.models.m17 import (Lsf, build_stream_frames, modulate,
                                          demodulate_payload_stream)
    lsf = Lsf(dst="SP5WWP", src="N0CALL")
    payload = bytes(range(64))                    # 4 frames
    sig = modulate(build_stream_frames(lsf, payload)).astype(np.float32)
    n_lsf = (8 + 184) * 10
    n_frame = (8 + 48 + 136) * 10
    # cut into frame 1: only fn 2,3 (incl. EOS) remain decodable
    x = sig[n_lsf + n_frame + n_frame // 2:]
    out = demodulate_payload_stream(np.concatenate([x, np.zeros(200, np.float32)]))
    assert all(not complete for _, _, complete in out), out


def test_random_stream_roundtrip_fuzz():
    """Seeded sweep over random M17 stream transmissions (payload length 1..96,
    random callsigns): exact loopback through the sample-domain receiver."""
    from futuresdr_tpu.models.m17 import (Lsf, build_stream_frames, modulate,
                                          demodulate_payload_stream)
    rng = np.random.default_rng(1717)
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    for trial in range(8):
        src = "".join(alphabet[int(rng.integers(0, 36))] for _ in range(6))
        dst = "".join(alphabet[int(rng.integers(0, 36))] for _ in range(6))
        n_pay = int(rng.integers(1, 97))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        lsf = Lsf(dst=dst, src=src)
        sig = modulate(build_stream_frames(lsf, payload)).astype(np.float32)
        x = np.concatenate([np.zeros(int(rng.integers(100, 800)), np.float32),
                            sig, np.zeros(300, np.float32)])
        x = (x + 0.05 * rng.standard_normal(len(x))).astype(np.float32)
        out = demodulate_payload_stream(x)
        assert len(out) == 1, (trial, len(out))
        l, p, complete = out[0]
        assert complete and l is not None and (l.src, l.dst) == (src, dst), trial
        assert p[:n_pay] == payload, trial


def test_stream_frame_ghost_inside_lsf_rejected():
    """Regression (r4 fuzz campaign): the LSF frame body can correlate > 0.9
    against the STREAM sync and pass the un-CRC'd Golay gate, injecting a ghost
    frame whose fn breaks contiguity (clean signal, (SQ8485->RHHIUD, 44 B)).
    Stream hits starting inside a decoded LSF span must be rejected."""
    from futuresdr_tpu.models.m17 import (Lsf, build_stream_frames, modulate,
                                          demodulate_payload_stream)
    lsf = Lsf(dst="RHHIUD", src="SQ8485")
    payload = bytes(range(44))
    sig = modulate(build_stream_frames(lsf, payload)).astype(np.float32)
    for pad in (0, 784):
        x = np.concatenate([np.zeros(pad, np.float32), sig,
                            np.zeros(300, np.float32)])
        out = demodulate_payload_stream(x)
        assert len(out) == 1
        l, p, complete = out[0]
        assert complete and (l.src, l.dst) == ("SQ8485", "RHHIUD")
        assert p[:44] == payload


def test_misframed_ghost_does_not_suppress_eos_frame():
    """Regression (r5 fuzz campaign, offset 62682 trial 7): a misframed hit
    330 samples before the final frame correlated at saturation against the
    stream sync, passed the Golay gate, and decoded a mostly-consistent
    (shifted) codeword — under this exact noise draw it out-ranked the true
    EOS frame in the NMS and suppressed it, so the transmission never
    completed. Hits are now ranked by re-encode codeword agreement first
    (the true frame is exact; a shifted window never is)."""
    from futuresdr_tpu.models.m17 import (Lsf, build_stream_frames, modulate,
                                          demodulate_payload_stream)
    rng = np.random.default_rng(1717 + 62682)
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    cfg = None
    for trial in range(8):
        src = "".join(alphabet[int(rng.integers(0, 36))] for _ in range(6))
        dst = "".join(alphabet[int(rng.integers(0, 36))] for _ in range(6))
        n_pay = int(rng.integers(1, 97))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        sig = modulate(build_stream_frames(Lsf(dst=dst, src=src), payload)) \
            .astype(np.float32)
        pad = int(rng.integers(100, 800))
        x = np.concatenate([np.zeros(pad, np.float32), sig,
                            np.zeros(300, np.float32)])
        noise = 0.05 * rng.standard_normal(len(x))
        if trial == 7:
            cfg = (src, dst, n_pay, payload, (x + noise).astype(np.float32))
    src, dst, n_pay, payload, x = cfg
    out = demodulate_payload_stream(x)
    assert len(out) == 1
    l, p, complete = out[0]
    assert complete and (l.src, l.dst) == (src, dst)
    assert p[:n_pay] == payload


def test_chance_crc_ghost_lsf_cannot_suppress_stream_frames():
    """Regression (r5 fuzz campaign, offset 166156 — the practice's eighth
    finding): a stream-frame body decoded as a CRC16-VALID ghost LSF with
    garbage callsigns (one random decode in ~65k passes CRC by chance at
    campaign scale), and the LSF-interior guard then rejected the REAL frame
    fn=2 inside the ghost's span — an incomplete payload from a clean
    transmission. LSF candidates are now gated by re-encode codeword
    agreement (true ≥0.95, misframed chance-CRC ghosts ≤0.91), the same
    plausibility measure the stream-frame path ranks by."""
    from futuresdr_tpu.models.m17 import (Lsf, build_stream_frames, modulate,
                                          demodulate_payload_stream)

    # the exact campaign draw, reproduced via the shifted-seed convention
    rng = np.random.default_rng(1717 + 166156)
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789"
    src = "".join(alphabet[int(rng.integers(0, 36))] for _ in range(6))
    dst = "".join(alphabet[int(rng.integers(0, 36))] for _ in range(6))
    n_pay = int(rng.integers(1, 97))
    payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
    sig = modulate(build_stream_frames(Lsf(dst=dst, src=src),
                                       payload)).astype(np.float32)
    x = np.concatenate([np.zeros(int(rng.integers(100, 800)), np.float32),
                        sig, np.zeros(300, np.float32)])
    x = (x + 0.05 * rng.standard_normal(len(x))).astype(np.float32)
    out = demodulate_payload_stream(x)
    assert len(out) == 1
    lsf, p, complete = out[0]
    assert complete and (lsf.src, lsf.dst) == (src, dst)
    assert p[:n_pay] == payload
