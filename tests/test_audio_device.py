"""Audio device path through the REAL work() loops (`blocks/audio.py`):
FakeAudioBackend stands in for the soundcard so the stream read/write branches
— previously unreachable in CI — execute in actual flowgraphs (reference:
`src/blocks/audio/audio_sink.rs` / `audio_source.rs` cpal streams)."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import AudioSink, AudioSource, Head, VectorSink, \
    VectorSource
from futuresdr_tpu.blocks.audio import FakeAudioBackend, set_audio_backend


@pytest.fixture
def fake_backend():
    b = FakeAudioBackend()
    set_audio_backend(b)
    yield b
    set_audio_backend(None)


def test_tone_to_audio_sink_captured(fake_backend):
    """Round-4 verdict item 7's done-criterion: tone → AudioSink → captured
    buffer asserted in a flowgraph test (the real write() path)."""
    fs = 8000
    t = np.arange(fs, dtype=np.float32) / fs
    tone = (0.5 * np.sin(2 * np.pi * 440 * t)).astype(np.float32)
    fg = Flowgraph()
    snk = AudioSink(fs)
    fg.connect(VectorSource(tone), snk)
    Runtime().run(fg)
    got = fake_backend.played_samples()
    np.testing.assert_array_equal(got, tone)
    assert fake_backend.opened == ["output"]
    assert snk._stream is not None                 # device path, not null path


def test_audio_source_captures_from_device(fake_backend):
    """AudioSource pulls frames from the device read() loop; a bounded capture
    drains into a VectorSink sample-exact."""
    fs = 8000
    n_total = 20_000
    src_data = np.linspace(-1, 1, n_total, dtype=np.float32)
    pos = [0]

    def capture(n, ch):
        a, b = pos[0], min(pos[0] + n, n_total)
        pos[0] = b
        return src_data[a:b].reshape(-1, 1)

    fake_backend.capture_fn = capture
    fg = Flowgraph()
    vs = VectorSink(np.float32)
    fg.connect(AudioSource(fs), Head(np.float32, 15_000), vs)
    Runtime().run(fg)
    np.testing.assert_array_equal(vs.items(), src_data[:15_000])


def test_audio_source_finishes_when_capture_exhausted(fake_backend):
    fs = 8000
    chunks = [np.ones((500, 1), np.float32), np.zeros((0, 1), np.float32)]

    def capture(n, ch):
        return chunks.pop(0) if chunks else np.zeros((0, ch), np.float32)

    fake_backend.capture_fn = capture
    fg = Flowgraph()
    vs = VectorSink(np.float32)
    fg.connect(AudioSource(fs), vs)
    Runtime().run(fg)                    # EOS from the device, not a Head
    assert len(vs.items()) == 500


def test_stereo_sink_preserves_interleaving(fake_backend):
    """Odd-length chunks mid-stream (CopyRand) must not flip L/R alignment:
    the sink consumes only whole frames and leaves the dangling sample for
    its partner (review regression)."""
    from futuresdr_tpu.blocks import CopyRand
    fs = 4000
    inter = np.arange(1000, dtype=np.float32)      # L0 R0 L1 R1 …
    fg = Flowgraph()
    snk = AudioSink(fs, n_channels=2)
    fg.connect(VectorSource(inter), CopyRand(np.float32, max_copy=7, seed=3),
               snk)
    Runtime().run(fg)
    got = fake_backend.played_samples()
    np.testing.assert_array_equal(got, inter)
    # frames written as [n, 2]
    assert all(p.ndim == 2 and p.shape[1] == 2 for p in fake_backend.played)


def test_device_open_failure_raises_without_allow_null():
    """A backend whose open() fails must surface at init (trap, not silence).
    A failing stub is installed rather than clearing the backend: on a machine
    with a working soundcard, sounddevice would open a REAL stream and the
    unbounded source flowgraph would run forever (review)."""
    class NoDevice:
        def open(self, kind, samplerate, channels):
            raise RuntimeError("simulated absent device")

    set_audio_backend(NoDevice())
    try:
        fg = Flowgraph()
        fg.connect(AudioSource(8000), VectorSink(np.float32))
        with pytest.raises(Exception, match="audio backend"):
            Runtime().run(fg)
    finally:
        set_audio_backend(None)
