"""Crash-safe serving (ISSUE 14, docs/robustness.md "Serving-plane
recovery"): durable per-session carry snapshots + virgin-incarnation
restore, graceful drain lifecycle (drain/healthz/readyz + Retry-After),
the SLO-aware overload-shedding ladder, and doctor coverage of the serving
plane."""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from futuresdr_tpu.ops.stages import Pipeline, fir_stage, rotator_stage
from futuresdr_tpu.serve import (ServeDraining, ServeEngine, ServeFull,
                                 ServeOverload, ShedLadder, register_app,
                                 unregister_app)

FRAME = 1024


def _pipe():
    taps = np.hanning(31).astype(np.float32)
    return Pipeline([fir_stage(taps, fft_len=256), rotator_stage(0.03)],
                    np.complex64)


def _frames(n, seed=0, frame=FRAME):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal(frame) + 1j * rng.standard_normal(frame))
            .astype(np.complex64) for _ in range(n)]


def _solo(pipe, frames):
    fn, carry = pipe.compile(FRAME, donate=False)
    out = []
    for f in frames:
        carry, y = fn(carry, f)
        out.append(np.asarray(y))
    return out


def _drain_results(eng, *sessions):
    while eng.step():
        pass
    return [eng.results(s.sid) for s in sessions]


# ---------------------------------------------------------------------------
# durable session state: persist -> virgin incarnation restores bit-identically
# ---------------------------------------------------------------------------

def test_persisted_sessions_resume_bit_identically(tmp_path):
    """Acceptance (tentpole 1): a virgin ServeEngine incarnation re-admits
    every persisted session and continues its stream BIT-IDENTICAL to an
    unfailed run — the serving analog of the kernel checkpoint_dir
    contract, through the carry_matches-validated readmit path."""
    pipe = _pipe()
    da, db = _frames(9, 1), _frames(9, 2)
    expa, expb = _solo(pipe, da), _solo(pipe, db)

    a = ServeEngine(_pipe(), frame_size=FRAME, app="crashsafe",
                    buckets=(2,), queue_frames=16,
                    persist_dir=str(tmp_path), persist_every=1)
    sa = a.admit(tenant="t0", sid="dura")
    sb = a.admit(tenant="t1", sid="durb")
    for fa, fb in zip(da[:5], db[:5]):
        assert a.submit(sa.sid, fa) and a.submit(sb.sid, fb)
    outa, outb = _drain_results(a, sa, sb)
    assert len(outa) == 5 and len(outb) == 5
    a.flush_persist()
    a.shutdown()                     # "crash": never closed, never drained

    b = ServeEngine(_pipe(), frame_size=FRAME, app="crashsafe",
                    buckets=(2,), queue_frames=16,
                    persist_dir=str(tmp_path), persist_every=1)
    assert b.restored_sessions == 2
    # restore WARMS the current bucket (all-masked no-op dispatch): the
    # restarted pod reports ready without waiting for traffic — readyz
    # would otherwise sit 503 forever on idle restored sessions
    assert b.health()["ready"] and b.health()["compiled"]
    ra, rb = b.table.get("dura"), b.table.get("durb")
    assert ra.state == "active" and ra.tenant == "t0"
    assert ra.frames_out == 5 and rb.frames_out == 5
    for fa, fb in zip(da[5:], db[5:]):
        assert b.submit("dura", fa) and b.submit("durb", fb)
    tail_a, tail_b = _drain_results(b, ra, rb)
    for got, want in ((outa + tail_a, expa), (outb + tail_b, expb)):
        assert len(got) == 9
        for x, y in zip(got, want):
            np.testing.assert_array_equal(x, y)
    b.shutdown()


def test_corrupted_snapshot_skipped_per_session(tmp_path):
    """One torn/corrupted file must not block the OTHER sessions' recovery
    — per-session skip, exactly the kernel disk-checkpoint rule."""
    a = ServeEngine(_pipe(), frame_size=FRAME, app="corrupt",
                    buckets=(2,), queue_frames=8,
                    persist_dir=str(tmp_path), persist_every=1)
    a.admit(tenant="t", sid="good")
    a.admit(tenant="t", sid="bad")
    for f in _frames(2, 3):
        a.submit("good", f)
        a.submit("bad", f)
    while a.step():
        pass
    a.flush_persist()
    a.shutdown()
    path = a._store.path("bad")
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))

    b = ServeEngine(_pipe(), frame_size=FRAME, app="corrupt",
                    buckets=(2,), queue_frames=8,
                    persist_dir=str(tmp_path), persist_every=1)
    assert b.restored_sessions == 1
    assert b.table.get("good") is not None
    assert b.table.get("bad") is None
    b.shutdown()


def test_clean_close_and_retire_purge_snapshots(tmp_path):
    """A cleanly closed session's state is complete and a retired (faulted)
    session must not resurrect — both purge their durable files; evicted
    and active sessions keep theirs."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="purge",
                      buckets=(4,), queue_frames=8,
                      persist_dir=str(tmp_path), persist_every=1)
    for sid in ("pa", "pb", "pc"):
        eng.admit(tenant="t", sid=sid)
        eng.submit(sid, _frames(1, 7)[0])
    while eng.step():
        pass
    eng.flush_persist()
    for sid in ("pa", "pb", "pc"):
        assert os.path.exists(eng._store.path(sid)), sid
    eng.close("pa")
    eng._retire(eng.table.get("pb"), RuntimeError("injected"))
    eng.flush_persist()
    assert not os.path.exists(eng._store.path("pa"))
    assert not os.path.exists(eng._store.path("pb"))
    assert os.path.exists(eng._store.path("pc"))
    eng.shutdown()


def test_pipeline_signature_separates_app_snapshots(tmp_path):
    """A DIFFERENT pipeline under a reused app name maps to different
    snapshot files (signature hash) — restore finds nothing instead of
    restoring a mismatched carry."""
    a = ServeEngine(_pipe(), frame_size=FRAME, app="sig",
                    buckets=(1,), queue_frames=4,
                    persist_dir=str(tmp_path), persist_every=1)
    a.admit(tenant="t", sid="s1")
    a.submit("s1", _frames(1, 9)[0])
    a.step()
    a.flush_persist()
    a.shutdown()
    other = Pipeline([rotator_stage(0.2)], np.complex64)
    b = ServeEngine(other, frame_size=FRAME, app="sig",
                    buckets=(1,), queue_frames=4,
                    persist_dir=str(tmp_path), persist_every=1)
    assert b.restored_sessions == 0
    assert a._store.signature != b._store.signature
    b.shutdown()


def test_persist_off_is_one_falsy_check(tmp_path):
    """serve_persist_every=0 (the default) must keep step() free of any
    persistence work — no store, no snapshot, no executor traffic."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="pfree", buckets=(1,),
                      queue_frames=4)
    assert eng._store is None and eng._persist_every == 0
    s = eng.admit(tenant="t")
    eng.submit(s.sid, _frames(1, 4)[0])
    eng.step()
    eng.shutdown()


# ---------------------------------------------------------------------------
# graceful lifecycle: drain + health/readiness
# ---------------------------------------------------------------------------

def test_drain_refuses_admissions_finishes_and_persists(tmp_path):
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="drainy",
                      buckets=(2,), queue_frames=16,
                      persist_dir=str(tmp_path), persist_every=0)
    s = eng.admit(tenant="t", sid="dr1")
    for f in _frames(4, 5):
        assert eng.submit(s.sid, f)
    report = eng.drain()
    assert report["drained"] and report["frames_drained"] == 4
    assert report["pending_frames"] == 0
    assert report["sessions_persisted"] == 1
    eng.flush_persist()
    assert os.path.exists(eng._store.path("dr1"))
    assert len(eng.results(s.sid)) == 4
    with pytest.raises(ServeDraining):
        eng.admit(tenant="t2")
    # the shed counter bills the refused admission under reason=drain
    from futuresdr_tpu.telemetry import prom
    from futuresdr_tpu.serve.engine import _SHED
    assert _SHED.get(app="drainy", tenant="t2", reason="drain") == 1
    assert eng.health()["ready"] is False
    eng.shutdown()


def test_drain_is_idempotent_and_describe_reports_lifecycle():
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="drain2", buckets=(1,),
                      queue_frames=4)
    r1 = eng.drain()
    r2 = eng.drain()
    assert r1["drained"] and r2["drained"]
    d = eng.describe()
    assert d["draining"] and d["drained"]
    assert d["shed"]["rung"] == "ok"
    eng.shutdown()


def test_retry_after_derived_from_step_rate():
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="retry", buckets=(1,),
                      queue_frames=4)
    assert eng.retry_after_s() == 1          # no rate measured yet
    s = eng.admit(tenant="t")
    for f in _frames(6, 6):
        eng.submit(s.sid, f)
        eng.step()
    after = eng.retry_after_s()
    assert 1 <= after <= 30
    eng.shutdown()


# ---------------------------------------------------------------------------
# SLO-aware overload shedding
# ---------------------------------------------------------------------------

def test_shed_ladder_unit_escalates_and_unwinds_in_order():
    lad = ShedLadder(hi=0.8, lo=0.3, trip=2, clear=2)
    # healthy observations keep rung 0
    assert lad.observe(0.1, None, 0.0) == 0
    # two consecutive over-watermark steps escalate exactly one rung
    assert lad.observe(0.9, None, 0.0) == 0
    assert lad.observe(0.9, None, 0.0) == 1
    # SLO misses escalate too (pressure fine, p99 over budget)
    assert lad.observe(0.1, 50.0, 10.0) == 1
    assert lad.observe(0.1, 50.0, 10.0) == 2
    assert lad.observe(0.9, None, 0.0) == 2
    assert lad.observe(0.9, None, 0.0) == 3
    assert lad.observe(0.9, None, 0.0) == 3      # capped at brownout
    # the band between watermarks HOLDS the rung (hysteresis)
    for _ in range(6):
        assert lad.observe(0.5, None, 0.0) == 3
    # recovery unwinds ONE rung per clear window, in order
    assert lad.observe(0.1, 1.0, 10.0) == 3
    assert lad.observe(0.1, 1.0, 10.0) == 2
    assert lad.observe(0.1, None, 0.0) == 2
    assert lad.observe(0.1, None, 0.0) == 1
    assert lad.observe(0.1, None, 0.0) == 1
    assert lad.observe(0.1, None, 0.0) == 0
    assert lad.escalations == 3


def test_overload_sheds_admissions_then_recovers():
    """Rung 1 integration: sustained queue pressure refuses NEW admissions
    (ServeOverload, billed on fsdr_serve_shed_total{reason=admission});
    resident sessions stay bit-exact; draining the backlog unwinds the
    ladder and admissions reopen."""
    pipe = _pipe()
    data = _frames(8, 11)
    exp = _solo(pipe, data)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="storm",
                      buckets=(2,), queue_frames=2)    # total = 4 credits
    eng._ladder = ShedLadder(hi=0.5, lo=0.25, trip=2, clear=2)
    s = eng.admit(tenant="hot", sid="res")
    out = []
    # storm: offer two frames per dispatched one — post-step pressure 0.5+;
    # a credit-refused submit RETRIES later (backpressure, not loss), so
    # the resident stream stays gap-free
    backlog = list(data)
    refused = 0
    for _ in range(50):
        if not backlog:
            break
        for _ in range(2):
            if backlog and eng.submit(s.sid, backlog[0]):
                backlog.pop(0)
            elif backlog:
                refused += 1
                break
        eng.step()
        out.extend(eng.results(s.sid))
    assert not backlog
    assert eng._ladder.level >= 1
    with pytest.raises(ServeOverload):
        eng.admit(tenant="newcomer")
    from futuresdr_tpu.serve.engine import _SHED
    assert _SHED.get(app="storm", tenant="newcomer",
                     reason="admission") >= 1
    # the resident stream never shed a frame and stays bit-exact
    while eng.step():
        pass
    out.extend(eng.results(s.sid))
    assert len(out) == 8
    for a, b in zip(out, exp):
        np.testing.assert_array_equal(a, b)
    # recovery: idle steps observe pressure 0 and unwind the ladder —
    # INCLUDING with an SLO set whose rolling p99 window is frozen at the
    # storm's values (idle ticks skip the stale SLO term; a frozen p99
    # must never keep escalating an empty engine)
    eng._slo_ms = 0.001                   # every recorded latency "misses"
    for _ in range(8):
        eng.step()
    assert eng._ladder.level == 0
    eng._slo_ms = 0.0
    s2 = eng.admit(tenant="newcomer")
    assert s2.state == "active"
    eng.shutdown()


def test_shed_rung2_evicts_most_stalled_session(tmp_path):
    """Rung 2: the most-stalled lane (no queued input the longest) evicts
    to host/disk, freeing its lane without touching resident bits."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="rung2",
                      buckets=(2,), queue_frames=2,
                      persist_dir=str(tmp_path), persist_every=0)
    eng._ladder = ShedLadder(hi=0.5, lo=0.25, trip=1, clear=8)
    # same tenant: its fair share is the whole budget, so one hog session
    # can push aggregate pressure past the watermark while its sibling
    # lane sits stalled
    hog = eng.admit(tenant="t", sid="hogs")
    idle = eng.admit(tenant="t", sid="idles")
    data = _frames(10, 12)
    for i in range(0, 10, 2):
        eng.submit(hog.sid, data[i])
        eng.submit(hog.sid, data[i + 1])
        eng.step()
        if eng._ladder.level >= 2:
            break
    assert eng._ladder.level >= 2
    assert idle.state == "evicted" and idle.carry_leaves is not None
    assert eng.shed_evictions >= 1
    eng.flush_persist()
    assert os.path.exists(eng._store.path("idles"))   # evict-to-disk
    eng.shutdown()


def test_brownout_k_lever_drops_megabatch_on_residents(monkeypatch):
    """Rung 3 with serve_brownout="k": resident buckets re-dispatch at K=1
    (per-dispatch latency over throughput), and recovery returns to the
    configured K reusing the cached base program — no recompile."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="bk",
                      buckets=(1,), queue_frames=16, frames_per_dispatch=4)
    eng._brownout = "k"
    s = eng.admit(tenant="t")
    data = _frames(12, 13)
    for f in data[:4]:
        assert eng.submit(s.sid, f)
    assert eng.step() == 4                   # K=4 megabatch
    compiles_k4 = eng.compiles
    eng._set_brownout(True)
    assert eng._k_eff == 1
    for f in data[4:8]:
        assert eng.submit(s.sid, f)
    assert eng.step() == 1                   # browned out: one frame per step
    assert eng.compiles == compiles_k4 + 1   # the K=1 program, once
    while eng.step():
        pass
    eng._set_brownout(False)
    for f in data[8:12]:
        assert eng.submit(s.sid, f)
    assert eng.step() == 4                   # back to K=4 ...
    assert eng.compiles == compiles_k4 + 1   # ... with zero new compiles
    eng.shutdown()


def test_brownout_precision_int8_lever():
    """Rung 3 with serve_brownout="precision" and the int8 mode
    (serve_brownout_precision="int8"): residents re-dispatch through the
    int8-lowered program for the duration (bounded quality loss — int8
    stages carry FLOAT weights and quantize in-trace, so the leafwise carry
    conversion is a dtype no-op), and release restores the base program."""
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="bp8", buckets=(1,),
                      queue_frames=16)
    eng._brownout = "precision"
    eng._brownout_prec = "int8"
    s = eng.admit(tenant="t")
    data = _frames(6, 21)

    def run(frames):
        got = []
        for f in frames:
            assert eng.submit(s.sid, f)
            while eng.step():
                pass
            got.extend(np.asarray(y).ravel() for y in eng.results(s.sid))
        return np.concatenate(got) if got else np.zeros(0, np.complex64)

    run(data[:2])
    eng._set_brownout(True)
    assert eng._brownout_active and eng._pipe_tag == "int8"
    assert eng.pipeline is not eng._base_pipeline
    mid = run(data[2:4])
    eng._set_brownout(False)
    assert not eng._brownout_active and eng._pipe_tag == "base"
    assert eng.pipeline is eng._base_pipeline
    run(data[4:6])
    # the browned-out window approximates the base program within the int8
    # rung's quantization band: replay the same stream through a solo base
    # pipeline and compare the window
    pipe = _pipe()
    fn, c = pipe.fn(), pipe.init_carry()
    ref = []
    import jax.numpy as jnp
    for f in data:
        c, y = fn(c, jnp.asarray(f))
        ref.append(np.asarray(y).ravel())
    ref_mid = np.concatenate(ref[2:4])
    err = float(np.mean(np.abs(mid - ref_mid) ** 2))
    sig = float(np.mean(np.abs(ref_mid) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 20.0
    eng.shutdown()


# ---------------------------------------------------------------------------
# doctor coverage of the serving plane
# ---------------------------------------------------------------------------

def test_doctor_trips_serve_wedged_and_reports_serve_section():
    from futuresdr_tpu.telemetry import doctor as doc
    d = doc.doctor()
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="wedge", buckets=(1,),
                      queue_frames=4)
    try:
        att = next(a for a in d._serve.values() if a.engine() is eng)
        s = eng.admit(tenant="t", sid="wedged_sid")
        assert eng.submit(s.sid, _frames(1, 14)[0])
        saved = d.window
        d.window = 2
        try:
            for _ in range(4):               # baseline + strikes past window
                d._tick_serve()
        finally:
            d.window = saved
        diag = att.diagnosis
        assert diag and diag["state"] == "serve_wedged"
        assert diag["app"] == "wedge"
        assert "wedged_sid" in diag["stuck_sessions"]
        assert diag["pending_frames"] == 1
        # flight record carries the serve section with the diagnosis
        rec = d.flight_record("test")
        assert rec["serve"]["wedge"]["diagnosis"]["state"] == "serve_wedged"
        # progress re-arms
        eng.step()
        d._tick_serve()
        assert att.diagnosis is None and not att.tripped
        # doctor.report() serves the full engine view
        rep = d.report(events=[])
        assert rep["serve"]["wedge"]["app"] == "wedge"
        assert rep["serve"]["wedge"]["capacity"] == 1
    finally:
        eng.shutdown()


def test_engine_shutdown_detaches_from_doctor():
    from futuresdr_tpu.telemetry import doctor as doc
    d = doc.doctor()
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="detach", buckets=(1,))
    assert any(a.engine() is eng for a in d._serve.values())
    eng.shutdown()
    assert not any(a.engine() is eng for a in d._serve.values())


# ---------------------------------------------------------------------------
# REST lifecycle: drain route, healthz/readyz, Retry-After, structured errors
# ---------------------------------------------------------------------------

def _get(url):
    return json.load(urllib.request.urlopen(url))


def _post(url, body=None):
    req = urllib.request.Request(
        url, data=json.dumps(body or {}).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    return json.load(urllib.request.urlopen(req))


def test_rest_lifecycle_drain_healthz_readyz_retry_after():
    from futuresdr_tpu import Runtime
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="lifecycle",
                      buckets=(1,), queue_frames=8)
    register_app(eng)
    rt = Runtime()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29671")
    cp.start()
    base = "http://127.0.0.1:29671"
    try:
        assert _get(f"{base}/healthz") == {"ok": True}
        # ready: nothing admitted yet
        r = _get(f"{base}/readyz")
        assert r["ready"] and r["apps"]["lifecycle"]["compiled"]
        # admitted + pending but not yet compiled -> NOT ready (503)
        s = _post(f"{base}/api/serve/lifecycle/session/", {"tenant": "g"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/readyz")
        assert ei.value.code == 503
        assert ei.value.headers.get("Retry-After")
        body = json.load(ei.value)
        assert body["ready"] is False
        assert body["apps"]["lifecycle"]["compiled"] is False
        # first dispatch compiles the bucket -> ready again
        assert eng.submit(s["sid"], _frames(1, 15)[0])
        eng.step()
        assert _get(f"{base}/readyz")["ready"]
        # ServeFull past the largest bucket: 503 + Retry-After + structured
        # JSON body naming the app
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/api/serve/lifecycle/session/", {"tenant": "g"})
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        body = json.load(ei.value)
        assert body["app"] == "lifecycle" and "error" in body
        # drain over REST: report + refused admissions + unready
        rep = _post(f"{base}/api/serve/lifecycle/drain/")
        assert rep["drained"] and rep["app"] == "lifecycle"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"{base}/api/serve/lifecycle/session/", {"tenant": "x"})
        assert ei.value.code == 503
        assert "draining" in json.load(ei.value)["error"]
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/readyz")
        assert json.load(ei.value)["apps"]["lifecycle"]["draining"] is True
        # structured 404 bodies carry the app too
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/api/serve/lifecycle/session/nosuch/")
        assert json.load(ei.value) == {"error": "session not found",
                                       "app": "lifecycle"}
    finally:
        cp.stop()
        unregister_app("lifecycle")
        eng.shutdown()


def test_readiness_storm_gate_scopes_to_serving_programs():
    """readyz must gate on SERVING-program compile storms only: flowgraph
    instance names collide across runs by design, so an unrelated kernel's
    recompile churn (e.g. a busy test/bench process) must never pull the
    pod out of rotation — a churning slot-bucket ladder must."""
    from futuresdr_tpu.serve import api
    from futuresdr_tpu.telemetry import profile
    for _ in range(4):                     # an unrelated kernel "storm"
        profile.record_compile("tk_readyz_probe", "warmup", "sig", 0.01)
    assert any(s["program"] == "tk_readyz_probe"
               for s in profile.plane().storm_report())
    ready, detail = api.readiness()
    assert ready and detail["compile_storms"] is None
    try:
        for _ in range(4):                 # a genuine serving-plane storm
            profile.record_compile("serve:readyz_probe", "serve_bucket",
                                   "cap=2", 0.01)
        ready, detail = api.readiness()
        assert not ready
        assert any(s["program"] == "serve:readyz_probe"
                   for s in detail["compile_storms"])
    finally:
        # drop the synthetic records: the storm window is 60 s and a later
        # test's readyz probe must not inherit this test's fake storm
        plane = profile.plane()
        with plane._lock:
            keep = [e for e in plane._recent
                    if e[1] not in ("tk_readyz_probe", "serve:readyz_probe")]
            plane._recent.clear()
            plane._recent.extend(keep)


def test_sigterm_hook_drains_registered_apps():
    """install_sigterm_drain: SIGTERM marks every registered app draining,
    finishes queued frames, then chains the previous handler."""
    from futuresdr_tpu.serve.engine import install_sigterm_drain
    import futuresdr_tpu.serve.engine as engine_mod
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="sigterm",
                      buckets=(1,), queue_frames=8)
    register_app(eng)
    chained = threading.Event()
    prev = signal.signal(signal.SIGTERM, lambda s, f: chained.set())
    engine_mod._sigterm_installed = False    # fresh install for this test
    try:
        assert install_sigterm_drain(timeout=10.0)
        s = eng.admit(tenant="t")
        for f in _frames(3, 16):
            assert eng.submit(s.sid, f)
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 10.0
        while not (eng.drained and chained.is_set()):
            assert time.monotonic() < deadline, "sigterm drain did not land"
            time.sleep(0.02)
        assert len(eng.results(s.sid)) == 3
        with pytest.raises(ServeDraining):
            eng.admit(tenant="late")
    finally:
        signal.signal(signal.SIGTERM, prev)
        engine_mod._sigterm_installed = False
        unregister_app("sigterm")
        eng.shutdown()
