"""Edge cases: EOS partial frames through device paths, rate-changing TpuKernel EOS,
empty streams, zero-length messages."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import VectorSource, VectorSink
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import fir_stage, fft_stage, mag2_stage
from futuresdr_tpu.tpu import TpuKernel


def test_tpu_kernel_eos_partial_frame():
    """A stream that is NOT a frame multiple still flushes its valid tail."""
    taps = np.zeros(16, np.float32)
    taps[0] = 1.0
    n = 10_000                      # frame 4096 → 2 full frames + 1808 tail
    data = np.arange(n, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    tk = TpuKernel([fir_stage(taps, fft_len=512)], np.float32, frame_size=4096)
    snk = VectorSink(np.float32)
    fg.connect(src, tk, snk)
    Runtime().run(fg)
    got = snk.items()
    # valid tail = floor to frame_multiple (hop 256): 1808 → 1792
    assert len(got) == 8192 + 1792
    np.testing.assert_allclose(got, data[:len(got)], rtol=1e-4, atol=1e-3)


def test_tpu_kernel_rate_change_eos():
    n_fft = 64
    n = 5 * 1024 + 100              # not a frame multiple
    data = np.exp(1j * 2 * np.pi * 0.25 * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(data)
    tk = TpuKernel([fft_stage(n_fft), mag2_stage()], np.complex64, frame_size=1024)
    snk = VectorSink(np.float32)
    fg.connect(src, tk, snk)
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == 5 * 1024 + 64    # 100 → 64 valid at the fft multiple
    assert np.argmax(got[:n_fft]) == 16


def test_tpu_kernel_stream_shorter_than_frame():
    data = np.ones(100, np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    tk = TpuKernel([fir_stage(np.ones(4, np.float32), fft_len=64)], np.float32,
                   frame_size=4096)
    snk = VectorSink(np.float32)
    fg.connect(src, tk, snk)
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == 96               # 100 floored to hop 32
    np.testing.assert_allclose(got[4:90], 4.0, rtol=1e-4)


def test_empty_vector_source():
    fg = Flowgraph()
    src = VectorSource(np.zeros(0, np.float32))
    snk = VectorSink(np.float32)
    fg.connect(src, snk)
    Runtime().run(fg)
    assert len(snk.items()) == 0


def test_empty_blob_message():
    from futuresdr_tpu.blocks import MessageBurst, MessageSink
    fg = Flowgraph()
    burst = MessageBurst(Pmt.blob(b""), 3)
    snk = MessageSink()
    fg.connect_message(burst, "out", snk, "in")
    Runtime().run(fg)
    assert len(snk.received) == 3
    assert all(p.to_blob() == b"" for p in snk.received)


def test_autotune_default_frame_grid_per_platform():
    """Accelerator platforms sweep up to 2M-sample frames; the CPU grid
    stays at 1M (measured rationale: ``autotune.default_frames``)."""
    from futuresdr_tpu.tpu.autotune import default_frames
    assert (1 << 21) not in default_frames("cpu")
    assert (1 << 21) in default_frames("tpu")
    assert default_frames("tpu")[:4] == default_frames("cpu")
