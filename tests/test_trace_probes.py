"""utils/trace.py latency probes: granularity edge cases, multi-hop tag
propagation, and latency_stats degenerate inputs (satellite coverage — before
this file only tests/test_trace_gui.py touched the module incidentally)."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Copy, VectorSource
from futuresdr_tpu.utils import (LatencyProbeSink, LatencyProbeSource,
                                 latency_stats)


def _run_probe_chain(data, granularity, hops=1):
    fg = Flowgraph()
    src = VectorSource(np.asarray(data, dtype=np.float32))
    probe_in = LatencyProbeSource(np.float32, granularity=granularity)
    sink = LatencyProbeSink(np.float32)
    chain = [src, probe_in] + [Copy(np.float32) for _ in range(hops)] + [sink]
    fg.connect(*chain)
    Runtime().run(fg)
    return sink.records


def test_granularity_larger_than_stream():
    """Probe interval beyond the whole stream (and so beyond any single work
    chunk): exactly ONE probe fires — the index-0 stamp — and single-record
    latency_stats is well-formed (p50 == p99 == max)."""
    records = _run_probe_chain(np.zeros(50_000), granularity=1_000_000)
    assert len(records) == 1
    idx, sent, seen = records[0]
    assert idx == 0 and seen >= sent
    stats = latency_stats(records)
    assert stats["count"] == 1
    assert stats["p50_us"] == pytest.approx(stats["p99_us"])
    assert stats["max_us"] == pytest.approx(stats["mean_us"])


def test_granularity_larger_than_work_chunk():
    """Interval bigger than any one work() chunk but smaller than the stream:
    probes land every `granularity` items regardless of how the scheduler
    splits the chunks — the source tracks the ABSOLUTE index across calls."""
    n, g = 300_000, 65_536
    records = _run_probe_chain(np.zeros(n), granularity=g)
    expect = [i * g for i in range(-(-n // g))]     # 0, g, 2g, … < n
    assert [r[0] for r in records] == expect


def test_zero_length_stream_records_nothing():
    """n=0 calls: an empty stream still runs EOS through the probes without a
    single record, and latency_stats degrades to a bare count."""
    records = _run_probe_chain(np.empty(0), granularity=128)
    assert records == []
    assert latency_stats(records) == {"count": 0}
    assert latency_stats([]) == {"count": 0}


def test_tag_propagation_across_multi_block_hops():
    """Probe tags must survive several ring-buffer hops (each hop re-bases tag
    indices into its own output window): every probe index arrives exactly
    once, in order, with non-negative latency."""
    n, g = 200_000, 16_384
    records = _run_probe_chain(np.zeros(n), granularity=g, hops=3)
    idxs = [r[0] for r in records]
    assert idxs == [i * g for i in range(-(-n // g))]
    assert all(seen >= sent for _, sent, seen in records)
    stats = latency_stats(records)
    assert stats["count"] == len(records)
    assert stats["max_us"] >= stats["p99_us"] >= stats["p50_us"] >= 0
