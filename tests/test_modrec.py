"""Modulation recognition: training convergence + in-flowgraph inference
(reference: examples/burn train/infer/radio)."""

import numpy as np
import pytest

from futuresdr_tpu.models.mcldnn import MCLDNN
from futuresdr_tpu.models.modrec import CLASSES, synth_batch, train, ModClassifier


def test_synth_batch_shapes_and_balance():
    rng = np.random.default_rng(0)
    X, y = synth_batch(rng, 128, 64)
    assert X.shape == (128, 2, 64) and y.shape == (128,)
    assert X.dtype == np.float32
    assert set(np.unique(y)).issubset(set(range(len(CLASSES))))


def test_training_learns():
    """A tiny MCLDNN beats chance comfortably within a few dozen steps."""
    model = MCLDNN(n_classes=len(CLASSES), conv_features=12, lstm_features=24)
    model, params, history = train(n_steps=60, batch=64, n=64, model=model, lr=2e-3)
    first = np.mean([a for _, a in history[:5]])
    last = np.mean([a for _, a in history[-10:]])
    assert last > 0.5, f"accuracy {last} not above chance (first={first})"
    assert last > first


def test_classifier_block_in_flowgraph():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource

    model = MCLDNN(n_classes=len(CLASSES), conv_features=12, lstm_features=24)
    model, params, _ = train(n_steps=80, batch=64, n=64, model=model, lr=2e-3)

    # an FM stream (most separable class) fed through the flowgraph classifier,
    # impaired like the training distribution (15 dB SNR)
    rng = np.random.default_rng(1)
    from futuresdr_tpu.models.modrec import _fm
    x = _fm(rng, 64 * 64)
    x = x / np.sqrt(np.mean(np.abs(x) ** 2))
    sigma = np.sqrt(10 ** (-15 / 10) / 2)
    x = (x + sigma * (rng.standard_normal(len(x))
                      + 1j * rng.standard_normal(len(x)))).astype(np.complex64)

    fg = Flowgraph()
    src = VectorSource(x)
    clf = ModClassifier(model, params, n=64, batch=8)
    fg.connect_stream(src, "out", clf, "in")
    Runtime().run(fg)
    assert len(clf.predictions) >= 8
    labels = [c for c, _ in clf.predictions]
    assert labels.count("fm") >= len(labels) // 2, labels
