"""Native fast-chain substitution (`runtime/fastchain.py` + `native/fastchain.cpp`):
whole pipes of trivial stream blocks run as one C++ round-robin thread — the
`flow.rs:265-442` pinned-executor analog for the small-chunk regime."""

import os
import time

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Copy, CopyRand, Head, NullSink, NullSource
from futuresdr_tpu.runtime.fastchain import fastchain_available, find_native_chains

pytestmark = pytest.mark.skipif(not fastchain_available(),
                                reason="native fastchain unavailable")


def _pipe(fg, samples, stages=2):
    src, head = NullSource(np.float32), Head(np.float32, samples)
    fg.connect(src, head)
    last = head
    for s in range(stages):
        c = CopyRand(np.float32, max_copy=512, seed=s + 1)
        fg.connect(last, c)
        last = c
    snk = NullSink(np.float32)
    fg.connect(last, snk)
    return snk


def test_fused_pipe_runs_and_counts():
    fg = Flowgraph()
    snk = _pipe(fg, 100_000)
    assert len(find_native_chains(fg)) == 1
    fg2 = Flowgraph()
    snk2 = _pipe(fg2, 100_000)
    Runtime().run(fg2)
    assert snk2.n_received == 100_000
    # metrics carry the counters + the fused marker
    w = fg2.wrapped(snk2)
    m = w.metrics()
    assert m["work_calls"] > 0
    assert m["fused_native"] is True
    assert m["items_in"]["in"] == 100_000
    del fg, snk


def test_opt_out_env_runs_python_path():
    os.environ["FSDR_NO_FASTCHAIN"] = "1"
    try:
        fg = Flowgraph()
        snk = _pipe(fg, 50_000)
        assert find_native_chains(fg) == []
        Runtime().run(fg)
        assert snk.n_received == 50_000
        assert "fused_native" not in fg.wrapped(snk).metrics()
    finally:
        os.environ.pop("FSDR_NO_FASTCHAIN", None)


def test_broadcast_tap_fuses_as_tree():
    """A broadcast tap (one output port wired to two sinks) fuses as a TREE
    since the v3 driver (round 5): every consumer of the tapped ring sees
    every item, matching the actor runtime's 1-writer→N-reader port groups."""
    fg = Flowgraph()
    src, head = NullSource(np.float32), Head(np.float32, 1000)
    cp, snk = Copy(np.float32), NullSink(np.float32)
    fg.connect(src, head, cp, snk)
    snk2 = NullSink(np.float32)
    fg.connect_stream(cp, "out", snk2, "in")
    trees = find_native_chains(fg)
    assert len(trees) == 1 and len(trees[0]) == 5
    assert trees[0].in_ring == [-1, 0, 1, 2, 2]
    Runtime().run(fg)
    assert snk.n_received == 1000 and snk2.n_received == 1000


def test_vector_endpoints_fuse_with_exact_data():
    """VectorSource/VectorSink are native-capable: a real data pipe fuses and
    the collected samples are BIT-exact — the data-integrity check the Null
    chains cannot provide."""
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    rng = np.random.default_rng(3)
    data = rng.standard_normal(50_000).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data, repeat=2)
    cp = CopyRand(np.float32, max_copy=512, seed=9)
    vs = VectorSink(np.float32)
    fg.connect(src, cp, vs)
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    got = vs.items()
    np.testing.assert_array_equal(got, np.concatenate([data, data]))
    m = fg.wrapped(vs).metrics()
    assert m["fused_native"] is True and m["items_in"]["in"] == 100_000

    # a Head mid-chain clamps the collected count exactly
    fg2 = Flowgraph()
    src2 = VectorSource(data)
    h2 = Head(np.float32, 12_345)
    vs2 = VectorSink(np.float32)
    fg2.connect(src2, h2, Copy(np.float32), vs2)
    assert len(find_native_chains(fg2)) == 1
    Runtime().run(fg2)
    np.testing.assert_array_equal(vs2.items(), data[:12_345])


def test_unbounded_into_vector_sink_not_fused():
    """NullSource (infinite) into a collecting VectorSink must NOT fuse — the
    capacity bound would be unbounded."""
    from futuresdr_tpu.blocks import VectorSink
    fg = Flowgraph()
    src, cp = NullSource(np.float32), Copy(np.float32)
    vs = VectorSink(np.float32)
    fg.connect(src, cp, vs)
    assert find_native_chains(fg) == []
    # (not run: the python path would stream forever without a Head)


def test_terminate_stops_unbounded_fused_chain():
    fg = Flowgraph()
    src, cp, snk = NullSource(np.float32), Copy(np.float32), NullSink(np.float32)
    fg.connect(src, cp, snk)
    assert len(find_native_chains(fg)) == 1
    rt = Runtime()
    running = rt.start(fg)
    deadline = time.perf_counter() + 10.0
    seen = 0
    while time.perf_counter() < deadline:
        m = running.handle.metrics_sync()
        seen = max((v["work_calls"] for v in m.values()), default=0)
        if seen > 0:
            break
        time.sleep(0.01)
    assert seen > 0, "live metrics never observed the fused chain"
    running.stop_sync()                    # Terminate → stop flag → clean join
    assert snk.n_received > 0


def test_fused_beside_python_pipe():
    """A fused pipe and a plain Python pipe coexist in one flowgraph."""
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    fg = Flowgraph()
    snk_native = _pipe(fg, 20_000)
    data = np.arange(5000, dtype=np.float32)
    vsrc, vsnk = VectorSource(data), VectorSink(np.float32)
    fg.connect(vsrc, Copy(np.float32), vsnk)
    assert len(find_native_chains(fg)) == 2    # the vector pipe fuses too now
    Runtime().run(fg)
    assert snk_native.n_received == 20_000
    np.testing.assert_array_equal(vsnk.items(), data)


def test_untyped_sink_port_uses_chain_dtype():
    """Regression (review): the sink buffer must be sized by the CHAIN dtype,
    not the sink port's own (possibly None) dtype — deriving them separately
    wrote item_size-wide items into a uint8 buffer (heap corruption)."""
    from futuresdr_tpu.blocks import VectorSink
    fg = Flowgraph()
    src = NullSource(np.float64)
    head = Head(np.float64, 1000)
    vs = VectorSink(None)                   # untyped collecting port
    fg.connect(src, head, vs)
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    got = vs.items()
    assert got.dtype == np.float64 and len(got) == 1000
    assert not got.any()                    # NullSource emits zeros


def test_fused_chain_busy_ns_profile():
    """The native driver attributes per-stage busy time (every scheduling
    pass, productive or not) into the metrics bridge: a 64-tap FIR stage must
    dominate the copies, and the per-stage sum must stay within the run's
    wall time (nothing double-counted)."""
    import time as _t

    from futuresdr_tpu.blocks import Fir
    from futuresdr_tpu.dsp import firdes

    fg = Flowgraph()
    src = NullSource(np.float32)
    head = Head(np.float32, 4_000_000)
    fir = Fir(firdes.lowpass(0.2, 64).astype(np.float32))
    cp = Copy(np.float32)
    snk = NullSink(np.float32)
    fg.connect(src, head, fir, cp, snk)
    assert len(find_native_chains(fg)) == 1
    t0 = _t.perf_counter()
    Runtime().run(fg)
    wall_ns = (_t.perf_counter() - t0) * 1e9
    busy = {type(b.kernel).__name__: b.metrics().get("busy_ns", 0)
            for b in (fg.wrapped(k) for k in (src, head, fir, cp, snk))}
    assert all(v > 0 for v in busy.values()), busy
    assert busy["Fir"] > busy["Copy"], busy          # the FIR does the FLOPs
    assert sum(busy.values()) <= wall_ns * 1.1, (busy, wall_ns)


def test_refused_flowgraph_metrics_stay_fresh():
    """Re-running the SAME flowgraph re-bridges the fused members: the second
    run's counters must reflect the second run (review regression: chaining
    off the previous bridge re-applied run 1's counters after refresh, so
    stale values won and every re-fuse pinned another set of arrays)."""
    fg = Flowgraph()
    src, head = NullSource(np.float32), Head(np.float32, 100_000)
    cp, snk = Copy(np.float32), NullSink(np.float32)
    fg.connect(src, head, cp, snk)
    Runtime().run(fg)
    assert fg.wrapped(cp).metrics()["items_in"]["in"] == 100_000
    # second run: the Head is exhausted, so the actor semantics are 0 items
    Runtime().run(fg)
    m = fg.wrapped(cp).metrics()
    assert m["items_in"]["in"] == 0, m


def test_fused_then_actor_relaunch_metrics_not_stomped():
    """A kernel that fused once and is then relaunched on the ACTOR path (new
    flowgraph, FSDR_NO_FASTCHAIN A/B pattern) must shed the stale bridge:
    the actor run's live counters, not the old fused run's frozen values."""
    src, head = NullSource(np.float32), Head(np.float32, 70_000)
    cp, snk = Copy(np.float32), NullSink(np.float32)
    fg = Flowgraph()
    fg.connect(src, head, cp, snk)
    Runtime().run(fg)
    assert fg.wrapped(cp).metrics()["fused_native"] is True

    os.environ["FSDR_NO_FASTCHAIN"] = "1"     # same fg, actor path this time
    try:
        head.remaining = 12_000                # rearm for the second run
        Runtime().run(fg)
        m = fg.wrapped(cp).metrics()
        assert "fused_native" not in m, m
        # port counters are kernel-lifetime cumulative (70k fused + 12k
        # actor); the stale bridge would have frozen this at 70k
        assert m["items_in"]["in"] == 82_000, m
    finally:
        os.environ.pop("FSDR_NO_FASTCHAIN", None)
