"""Native fast-chain substitution (`runtime/fastchain.py` + `native/fastchain.cpp`):
whole pipes of trivial stream blocks run as one C++ round-robin thread — the
`flow.rs:265-442` pinned-executor analog for the small-chunk regime."""

import os
import time

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Copy, CopyRand, Head, NullSink, NullSource
from futuresdr_tpu.runtime.fastchain import fastchain_available, find_native_chains

pytestmark = pytest.mark.skipif(not fastchain_available(),
                                reason="native fastchain unavailable")


def _pipe(fg, samples, stages=2):
    src, head = NullSource(np.float32), Head(np.float32, samples)
    fg.connect(src, head)
    last = head
    for s in range(stages):
        c = CopyRand(np.float32, max_copy=512, seed=s + 1)
        fg.connect(last, c)
        last = c
    snk = NullSink(np.float32)
    fg.connect(last, snk)
    return snk


def test_fused_pipe_runs_and_counts():
    fg = Flowgraph()
    snk = _pipe(fg, 100_000)
    assert len(find_native_chains(fg)) == 1
    fg2 = Flowgraph()
    snk2 = _pipe(fg2, 100_000)
    Runtime().run(fg2)
    assert snk2.n_received == 100_000
    # metrics carry the counters + the fused marker
    w = fg2.wrapped(snk2)
    m = w.metrics()
    assert m["work_calls"] > 0
    assert m["fused_native"] is True
    assert m["items_in"]["in"] == 100_000
    del fg, snk


def test_opt_out_env_runs_python_path():
    os.environ["FSDR_NO_FASTCHAIN"] = "1"
    try:
        fg = Flowgraph()
        snk = _pipe(fg, 50_000)
        assert find_native_chains(fg) == []
        Runtime().run(fg)
        assert snk.n_received == 50_000
        assert "fused_native" not in fg.wrapped(snk).metrics()
    finally:
        os.environ.pop("FSDR_NO_FASTCHAIN", None)


def test_not_fused_with_message_edge_or_tap():
    from futuresdr_tpu.blocks import MessageSink

    # a message edge on a member disqualifies the chain
    fg = Flowgraph()
    src, head = NullSource(np.float32), Head(np.float32, 1000)
    cp, snk = Copy(np.float32), NullSink(np.float32)
    fg.connect(src, head, cp, snk)
    probe = MessageSink()
    # no native block HAS message ports, so craft the other disqualifier:
    # a broadcast tap on the copy output
    snk2 = NullSink(np.float32)
    fg.connect_stream(cp, "out", snk2, "in")
    assert find_native_chains(fg) == []
    Runtime().run(fg)                      # python path still works
    assert snk.n_received == 1000 and snk2.n_received == 1000
    del probe


def test_not_fused_when_sink_is_python_block():
    from futuresdr_tpu.blocks import VectorSink
    fg = Flowgraph()
    src, head = NullSource(np.float32), Head(np.float32, 4096)
    vs = VectorSink(np.float32)
    fg.connect(src, head, vs)
    assert find_native_chains(fg) == []    # chain must END at a native sink
    Runtime().run(fg)
    assert len(vs.items()) == 4096


def test_terminate_stops_unbounded_fused_chain():
    fg = Flowgraph()
    src, cp, snk = NullSource(np.float32), Copy(np.float32), NullSink(np.float32)
    fg.connect(src, cp, snk)
    assert len(find_native_chains(fg)) == 1
    rt = Runtime()
    running = rt.start(fg)
    deadline = time.perf_counter() + 10.0
    seen = 0
    while time.perf_counter() < deadline:
        m = running.handle.metrics_sync()
        seen = max((v["work_calls"] for v in m.values()), default=0)
        if seen > 0:
            break
        time.sleep(0.01)
    assert seen > 0, "live metrics never observed the fused chain"
    running.stop_sync()                    # Terminate → stop flag → clean join
    assert snk.n_received > 0


def test_fused_beside_python_pipe():
    """A fused pipe and a plain Python pipe coexist in one flowgraph."""
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    fg = Flowgraph()
    snk_native = _pipe(fg, 20_000)
    data = np.arange(5000, dtype=np.float32)
    vsrc, vsnk = VectorSource(data), VectorSink(np.float32)
    fg.connect(vsrc, Copy(np.float32), vsnk)
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    assert snk_native.n_received == 20_000
    np.testing.assert_array_equal(vsnk.items(), data)
