"""Pallas kernel tests (interpret mode on CPU; same numerics compiled on TPU)."""

import numpy as np
import pytest
from scipy import signal as sps

from futuresdr_tpu.ops.pallas_kernels import pallas_fir, pallas_fir_stage
from futuresdr_tpu.ops import Pipeline


def test_pallas_fir_matches_lfilter():
    rng = np.random.default_rng(0)
    taps = rng.standard_normal(16).astype(np.float32)
    x = rng.standard_normal(8192).astype(np.float32)
    y = np.asarray(pallas_fir(x, taps, block=2048))
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_pallas_fir_multi_block_overlap():
    """Outputs at block boundaries must use the previous block's tail."""
    taps = np.ones(8, np.float32)
    x = np.arange(4096 * 3, dtype=np.float32)
    y = np.asarray(pallas_fir(x, taps, block=4096))
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(y, ref, rtol=1e-5)


def test_pallas_fir_stage_streaming():
    rng = np.random.default_rng(1)
    taps = rng.standard_normal(24).astype(np.float32)
    x = rng.standard_normal(3 * 4096).astype(np.complex64) \
        + 1j * rng.standard_normal(3 * 4096).astype(np.complex64)
    x = x.astype(np.complex64)
    pipe = Pipeline([pallas_fir_stage(taps, block=2048)], np.complex64)
    fn, carry = pipe.compile(4096)
    outs = []
    for i in range(0, len(x), 4096):
        carry, y = fn(carry, x[i:i + 4096])
        outs.append(np.asarray(y))
    got = np.concatenate(outs)
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)
