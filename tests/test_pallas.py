"""Pallas kernel tests (interpret mode on CPU; same numerics compiled on TPU)."""

import numpy as np
import pytest
from scipy import signal as sps

from futuresdr_tpu.ops.pallas_kernels import pallas_fir, pallas_fir_stage
from futuresdr_tpu.ops import Pipeline


def test_pallas_fir_matches_lfilter():
    rng = np.random.default_rng(0)
    taps = rng.standard_normal(16).astype(np.float32)
    x = rng.standard_normal(8192).astype(np.float32)
    y = np.asarray(pallas_fir(x, taps, block=2048))
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_pallas_fir_multi_block_overlap():
    """Outputs at block boundaries must use the previous block's tail."""
    taps = np.ones(8, np.float32)
    x = np.arange(4096 * 3, dtype=np.float32)
    y = np.asarray(pallas_fir(x, taps, block=4096))
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(y, ref, rtol=1e-5)


def test_pallas_fir_stage_streaming():
    rng = np.random.default_rng(1)
    taps = rng.standard_normal(24).astype(np.float32)
    x = rng.standard_normal(3 * 4096).astype(np.complex64) \
        + 1j * rng.standard_normal(3 * 4096).astype(np.complex64)
    x = x.astype(np.complex64)
    pipe = Pipeline([pallas_fir_stage(taps, block=2048)], np.complex64)
    fn, carry = pipe.compile(4096)
    outs = []
    for i in range(0, len(x), 4096):
        carry, y = fn(carry, x[i:i + 4096])
        outs.append(np.asarray(y))
    got = np.concatenate(outs)
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------------------------
# round-20: tuned block table, fused FIR→FFT, rotator/demod kernels, ragged
# tails at swept shapes, and the pallas_blocks autotune axis
# ---------------------------------------------------------------------------

import jax.numpy as jnp

from futuresdr_tpu.ops.pallas_kernels import (DEFAULT_BLOCKS, pallas_fir_fft,
                                              pallas_pfb, pallas_poly_fir,
                                              pallas_quad_demod,
                                              pallas_rotator,
                                              set_tuned_blocks, tuned_blocks)


@pytest.fixture
def clean_tuned_blocks():
    set_tuned_blocks(None)
    yield
    set_tuned_blocks(None)


def test_tuned_block_table_guarded_parse(clean_tuned_blocks):
    """set_tuned_blocks mirrors the autotune cache's guarded-parse contract:
    unknown kernels and non-positive shapes are ignored, coercible strings
    coerce, and None clears back to the hand-picked defaults."""
    set_tuned_blocks({"fir": 2048, "bogus": 4, "pfb": -1, "poly_fir": "512"})
    tb = tuned_blocks()
    assert tb["fir"] == 2048
    assert tb["poly_fir"] == 512
    assert tb["pfb"] == DEFAULT_BLOCKS["pfb"]       # junk ignored
    assert "bogus" not in tb
    set_tuned_blocks(None)
    assert tuned_blocks() == DEFAULT_BLOCKS


def test_tuned_blocks_reach_block_none_callers(clean_tuned_blocks):
    """A kernel called WITHOUT a block (the stage calling convention)
    resolves against the tuned table — the consumption path kernel init
    relies on. pallas_fir asserts frame % block == 0, so a 2048 frame only
    traces when the tuned 2048 (not the default 4096) reached it."""
    rng = np.random.default_rng(4)
    taps = rng.standard_normal(16).astype(np.float32)
    x = rng.standard_normal(2048).astype(np.float32)
    set_tuned_blocks({"fir": 2048})
    y = np.asarray(pallas_fir(x, taps))
    np.testing.assert_allclose(y, sps.lfilter(taps, 1.0, x),
                               rtol=1e-4, atol=1e-4)
    set_tuned_blocks(None)
    with pytest.raises(AssertionError):
        pallas_fir(x, taps)                         # default 4096 ∤ 2048


def test_candidate_grids_cover_defaults():
    """Every sweep grid contains its kernel's default — the never-regress
    contract (a sweep can always record the hand-picked shape)."""
    from futuresdr_tpu.tpu.pallas_tune import CANDIDATE_BLOCKS
    assert set(CANDIDATE_BLOCKS) == set(DEFAULT_BLOCKS)
    for k, d in DEFAULT_BLOCKS.items():
        assert d in CANDIDATE_BLOCKS[k], k


@pytest.mark.parametrize("block", [3, 5])
def test_pallas_fir_fft_matches_composed_ragged(block):
    """Fused FIR→FFT vs lfilter+FFT at row counts not divisible by the
    block (the swept shapes are odd; tails must not corrupt)."""
    rng = np.random.default_rng(block)
    n_fft, nt, rows = 128, 17, 7                    # 7 % 3, 7 % 5 ≠ 0
    taps = rng.standard_normal(nt).astype(np.float32)
    hist = (rng.standard_normal(nt - 1)
            + 1j * rng.standard_normal(nt - 1)).astype(np.complex64)
    x = (rng.standard_normal(n_fft * rows)
         + 1j * rng.standard_normal(n_fft * rows)).astype(np.complex64)
    got = np.asarray(pallas_fir_fft(jnp.asarray(hist), jnp.asarray(x),
                                    jnp.asarray(taps), n_fft, block=block))
    filt = sps.lfilter(taps, 1.0, np.concatenate([hist, x]))[nt - 1:]
    ref = np.fft.fft(filt.reshape(-1, n_fft), axis=1).reshape(-1)
    err = float(np.mean(np.abs(got - ref) ** 2))
    sig = float(np.mean(np.abs(ref) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 80.0


def test_fir_fft_stage_streaming_matches_composed():
    """The fused stage streamed over carry-chained frames is the composed
    fir+fft program's output (and routes as one Pallas stage)."""
    from futuresdr_tpu.ops import precision as P
    from futuresdr_tpu.ops.stages import fft_stage, fir_fft_stage, fir_stage
    rng = np.random.default_rng(9)
    taps = rng.standard_normal(33).astype(np.float32)
    fused = Pipeline([fir_fft_stage(taps, 256)], np.complex64)
    composed = Pipeline([fir_stage(taps), fft_stage(256)], np.complex64)
    assert P.pallas_stage_count(fused) == 1
    fa, ca = fused.fn(), fused.init_carry()
    fb, cb = composed.fn(), composed.init_carry()
    for i in range(3):
        x = (rng.standard_normal(8192)
             + 1j * rng.standard_normal(8192)).astype(np.complex64)
        ca, ya = fa(ca, jnp.asarray(x))
        cb, yb = fb(cb, jnp.asarray(x))
        np.testing.assert_allclose(np.asarray(ya), np.asarray(yb),
                                   rtol=1e-3, atol=1e-2)


@pytest.mark.parametrize("n,block", [(1000, 1), (257, 2)])
def test_pallas_rotator_matches_reference_ragged(n, block):
    rng = np.random.default_rng(n)
    x = (rng.standard_normal(n)
         + 1j * rng.standard_normal(n)).astype(np.complex64)
    ph0, inc = 0.3, 0.011
    got = np.asarray(pallas_rotator(jnp.asarray(x), ph0, inc, block=block))
    ref = x * np.exp(1j * (ph0 + inc * np.arange(n))).astype(np.complex64)
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("n,block", [(1000, 1), (129, 2)])
def test_pallas_quad_demod_matches_reference_ragged(n, block):
    rng = np.random.default_rng(n)
    x = (rng.standard_normal(n)
         + 1j * rng.standard_normal(n)).astype(np.complex64)
    prev = np.complex64(0.7 - 0.2j)
    gain = 0.8
    got = np.asarray(pallas_quad_demod(jnp.asarray(prev), jnp.asarray(x),
                                       gain, block=block))
    ext = np.concatenate([[prev], x])
    ref = gain * np.angle(ext[1:] * np.conj(ext[:-1]))
    np.testing.assert_allclose(got, ref.astype(np.float32),
                               rtol=1e-4, atol=1e-5)


def test_pfb_poly_ragged_at_swept_shapes():
    """Swept candidates larger than the workload (block > t / block ∤ nq)
    still produce exact tails — the autotuner may record any grid shape."""
    rng = np.random.default_rng(11)
    K, N = 4, 16
    taps = rng.standard_normal((K, N)).astype(np.float32)
    rows = (rng.standard_normal((300 + K - 1, N))
            + 1j * rng.standard_normal((300 + K - 1, N))).astype(np.complex64)
    t = 300
    windows = np.stack([rows[(K - 1) - k:(K - 1) - k + t] for k in range(K)],
                       axis=1)
    ref = np.fft.ifft(np.einsum("tkc,kc->tc", windows, taps), axis=1) * N
    got = np.asarray(pallas_pfb(jnp.asarray(rows), jnp.asarray(taps),
                                block=512))
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)

    D, m, nq = 8, 7, 777                            # 777 % 512 ≠ 0
    W = rng.standard_normal((m + 1, D)).astype(np.float32)
    prows = rng.standard_normal((nq + m, D)).astype(np.float32)
    ref2 = np.zeros(nq, np.float32)
    for a in range(m + 1):
        ref2 += prows[m - a:m - a + nq] @ W[a]
    got2 = np.asarray(pallas_poly_fir(jnp.asarray(prows), jnp.asarray(W),
                                      block=512))
    np.testing.assert_allclose(got2, ref2, rtol=1e-4, atol=1e-4)


def test_pallas_blocks_cache_axis():
    """The guarded pallas_blocks parse + record/cached round-trip + the
    orthogonal-axes contract (a streamed re-record preserves the axis)."""
    import importlib
    at = importlib.import_module("futuresdr_tpu.tpu.autotune")
    from futuresdr_tpu.ops.stages import fir_stage, mag2_stage
    # per-axis guarded parse: junk kernels/shapes are stripped; a fully
    # malformed axis loses ONLY itself, never the entry's valid picks
    e = at._norm_entry({"k": 2, "inflight": None,
                        "pallas_blocks": {"v5e": {"fir": 2048, "bogus": 1,
                                                  "pfb": -2}}})
    assert e["pallas_blocks"] == {"v5e": {"fir": 2048}}
    e = at._norm_entry({"k": 2, "inflight": None,
                        "pallas_blocks": "garbage"})
    assert e is not None and e["k"] == 2 and "pallas_blocks" not in e
    taps = np.hanning(17).astype(np.float32)
    # unique stage name: these records must never collide with a real
    # ("fir", ...) chain's signature in this process (kernel init consumes
    # the axis globally)
    pipe = Pipeline([fir_stage(taps, name="fir_r20ax"), mag2_stage()],
                    np.complex64)
    at.record_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu", "v5e",
                            {"fir": 2048, "bogus": 7, "pfb": -1})
    got = at.cached_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu", "v5e")
    assert got == {"fir": 2048}
    assert at.cached_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu",
                                   "v5p") is None
    at.record_streamed_pick(pipe.stages, pipe.in_dtype, "cpu", 4, inflight=2)
    assert at.cached_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu",
                                   "v5e") == {"fir": 2048}
    # a second device kind rides the SAME axis without clobbering the first
    at.record_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu", "v5p",
                            {"pfb": 128})
    assert at.cached_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu",
                                   "v5e") == {"fir": 2048}
    assert at.cached_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu",
                                   "v5p") == {"pfb": 128}
    # all-junk records are dropped, not stored
    at.record_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu", "v5e",
                            {"bogus": 7})
    assert at.cached_pallas_blocks(pipe.stages, pipe.in_dtype, "cpu",
                                   "v5e") == {"fir": 2048}


def test_autotune_pallas_blocks_cache_hit_skips_sweep(monkeypatch,
                                                      clean_tuned_blocks):
    import importlib
    at = importlib.import_module("futuresdr_tpu.tpu.autotune")
    from futuresdr_tpu.ops.stages import fir_stage
    from futuresdr_tpu.tpu import pallas_tune
    taps = np.hanning(19).astype(np.float32)
    pipe = Pipeline([fir_stage(taps, name="fir_r20hit")], np.complex64)
    calls = {"n": 0}
    real = pallas_tune.sweep_blocks

    def counting(*a, **k):
        calls["n"] += 1
        return real(*a, **k)

    monkeypatch.setattr(pallas_tune, "sweep_blocks", counting)
    w1 = at.autotune_pallas_blocks(pipe.stages, pipe.in_dtype,
                                   kernels=("rotator",), frame=1 << 14,
                                   reps=1)
    assert calls["n"] == 1 and "rotator" in w1
    w2 = at.autotune_pallas_blocks(pipe.stages, pipe.in_dtype,
                                   kernels=("rotator",), frame=1 << 14,
                                   reps=1)
    assert calls["n"] == 1, "cache hit must skip the sweep"
    assert w2 == w1
    assert tuned_blocks()["rotator"] == w1["rotator"]


def test_kernel_init_installs_cached_blocks(clean_tuned_blocks):
    """TpuKernel construction consumes the cached sweep: impl="pallas"
    stages then trace with the measured shapes (block=None resolves
    against the installed table)."""
    import importlib
    at = importlib.import_module("futuresdr_tpu.tpu.autotune")
    from futuresdr_tpu.ops.stages import fir_stage, mag2_stage
    from futuresdr_tpu.tpu.kernel_block import TpuKernel
    from futuresdr_tpu.tpu.pallas_tune import device_key
    taps = np.hanning(21).astype(np.float32)
    stages = [fir_stage(taps, name="fir_r20init"), mag2_stage()]
    pipe = Pipeline(stages, np.complex64)
    kern = TpuKernel(stages, np.complex64, frame_size=8192)
    platform = kern.inst.platform
    at.record_pallas_blocks(pipe.stages, pipe.in_dtype, platform,
                            device_key(), {"fir": 2048, "poly_fir": 512})
    kern2 = TpuKernel(stages, np.complex64, frame_size=8192)
    tb = tuned_blocks()
    assert tb["fir"] == 2048 and tb["poly_fir"] == 512
    assert tb["pfb"] == DEFAULT_BLOCKS["pfb"]
