"""Jax-free control-port child for the live fleet tests.

Runs one ControlPort with a duck-typed fake serving engine registered
under app "app" — enough surface for the fleet plane (``health()``,
``retry_after_s()``, ``credits.pressure()``, the slot table) and for REST
admissions, without paying the compute plane's jax import per child (the
control port and serve/api.py are deliberately jax-free; perf/fleet_smoke
covers the real-engine topology).

Usage: ``python -m tests._fleet_child <port> [pressure] [shed_level]``.
Prints ``READY`` once the port is listening, then parks.
"""

import os
import sys
import time


class _Credits:
    def __init__(self, p: float):
        self._p = float(p)

    def pressure(self) -> float:
        return self._p


class FakeEngine:
    """The lock-free subset of ServeEngine the fleet plane reads, plus
    ``admit`` for routed REST admissions."""

    def __init__(self, app: str, pressure: float = 0.0,
                 shed_level: int = 0, capacity: int = 64):
        from futuresdr_tpu.serve.slots import SlotTable
        self.app = app
        self.table = SlotTable(capacity)
        self.credits = _Credits(pressure)
        self.draining = False
        self.shed_level = int(shed_level)

    @property
    def capacity(self) -> int:
        return self.table.capacity

    def health(self) -> dict:
        return {"ready": True, "compiled": True, "draining": False,
                "drained": False, "shed_level": self.shed_level,
                "shed_rung": "ok" if not self.shed_level else "admission",
                "active": self.table.active,
                "capacity": self.table.capacity}

    def retry_after_s(self) -> int:
        return 1

    def admit(self, tenant: str = "default", sid=None):
        from futuresdr_tpu.serve.slots import Session
        s = Session(tenant, sid)
        self.table.admit(s)
        return s


class _Handle:
    def flowgraph_ids(self):
        return []

    def get_flowgraph(self, fg):
        return None


def main() -> None:
    port = int(sys.argv[1])
    pressure = float(sys.argv[2]) if len(sys.argv) > 2 else 0.0
    shed = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    # fleet identity = the control-port address (what the aggregator polls)
    os.environ.setdefault("FUTURESDR_TPU_FLEET_HOST_ID",
                          f"127.0.0.1:{port}")
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    from futuresdr_tpu.serve import api as serve_api
    serve_api.register_app(FakeEngine("app", pressure, shed), "app")
    cp = ControlPort(_Handle(), bind=f"127.0.0.1:{port}")
    cp.start()
    print("READY", flush=True)
    while True:
        time.sleep(1)


if __name__ == "__main__":
    main()
