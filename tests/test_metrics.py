"""Observability: per-block runtime metrics via handle + REST (SURVEY §5)."""

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSource, VectorSink, Copy


def test_metrics_via_handle():
    data = np.zeros(50_000, np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cp = Copy(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, cp, snk)
    rt = Runtime()
    running = rt.start(fg)
    fg = running.wait_sync()
    # after completion the handle returns {}; use the block counters directly
    w = fg.wrapped(cp)
    m = w.metrics()
    assert m["work_calls"] > 0
    assert m["items_in"]["in"] == 50_000
    assert m["items_out"]["out"] == 50_000
    assert m["work_time_s"] >= 0


def test_metrics_live_query():
    from futuresdr_tpu.blocks import NullSource, NullSink
    fg = Flowgraph()
    src = NullSource(np.float32)
    snk = NullSink(np.float32)
    fg.connect(src, snk)
    rt = Runtime()
    running = rt.start(fg)
    import time
    # poll: a fixed nap is flake-bait on a loaded box
    deadline = time.perf_counter() + 10.0
    m = {}
    while time.perf_counter() < deadline:
        m = running.handle.metrics_sync()
        if any(v["work_calls"] > 0 for v in m.values()):
            break
        time.sleep(0.01)
    assert any(v["work_calls"] > 0 for v in m.values())
    running.stop_sync()
