"""CW / SSB / keyfob example tests (reference: examples/cw, examples/ssb,
examples/keyfob)."""

import numpy as np

from futuresdr_tpu.models.misc import (text_to_morse_keying, decode_morse_keying,
                                       cw_modulate, cw_demodulate, ssb_demodulate,
                                       ook_modulate, ook_demodulate)


def test_morse_keying_roundtrip():
    msg = "CQ CQ DE W2FBI K"
    keying = text_to_morse_keying(msg, 10)
    assert decode_morse_keying(keying, 10) == msg


def test_cw_audio_roundtrip():
    fs = 8000.0
    msg = "HELLO TPU"
    audio = cw_modulate(msg, 600.0, fs, wpm=25)
    assert cw_demodulate(audio, fs, wpm=25) == msg


def test_ssb_recovers_tone():
    fs = 48000.0
    n = 48000
    t = np.arange(n) / fs
    # a USB signal: carrier at +5 kHz offset, 1 kHz audio tone → component at 6 kHz
    iq = np.exp(2j * np.pi * (5000 + 1000) * t).astype(np.complex64)
    audio = ssb_demodulate(iq, fs, bfo_offset=5000.0, sideband="usb")
    seg = audio[2000:]
    spec = np.abs(np.fft.rfft(seg * np.hanning(len(seg))))
    peak = np.fft.rfftfreq(len(seg), 1 / fs)[np.argmax(spec)]
    assert abs(peak - 1000.0) < 10.0


def test_keyfob_ook_roundtrip():
    rng = np.random.default_rng(0)
    bits = rng.integers(0, 2, 64).astype(np.uint8)
    fs, rate = 100_000.0, 2_000.0
    burst = ook_modulate(bits, fs, rate)
    env = burst + 0.05 * rng.random(len(burst)).astype(np.float32)
    got = ook_demodulate(env, fs, rate, 64)
    assert got is not None
    np.testing.assert_array_equal(got, bits)


def test_random_roundtrip_fuzz():
    """Seeded sweep: random CW texts and OOK bit patterns loop back exactly."""
    from futuresdr_tpu.models.misc import (cw_demodulate, cw_modulate,
                                           ook_demodulate, ook_modulate)
    rng = np.random.default_rng(73)
    alphabet = "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 "
    for trial in range(6):
        text = "".join(alphabet[int(rng.integers(0, len(alphabet)))]
                       for _ in range(int(rng.integers(3, 16)))).strip() or "OK"
        wpm = float(rng.uniform(12, 30))
        audio = cw_modulate(text, tone_hz=600.0, fs=8000.0, wpm=wpm)
        audio = (audio + 0.05 * rng.standard_normal(len(audio))).astype(np.float32)
        assert cw_demodulate(audio, fs=8000.0, wpm=wpm) == " ".join(text.split())

        bits = rng.integers(0, 2, int(rng.integers(8, 64))).astype(np.uint8)
        env = ook_modulate(bits, fs=48000.0, bit_rate=2000.0)
        env = (env + 0.05 * rng.standard_normal(len(env))).astype(np.float32)
        got = ook_demodulate(env, fs=48000.0, bit_rate=2000.0, n_bits=len(bits))
        np.testing.assert_array_equal(got, bits)
