"""rtl_tcp HAL driver against a mock rtl_tcp server (reference capability:
seify's RTL-SDR path, ``src/blocks/seify/builder.rs``)."""

import socket
import struct
import threading

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import Head, SeifySource, VectorSink


class MockRtlTcpServer:
    """Speaks the rtl_tcp protocol: greeting, command recording, IQ streaming."""

    def __init__(self, n_samples: int = 100_000):
        self.n_samples = n_samples
        self.commands = []          # (cmd_id, param)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(1)
        self.addr = self.sock.getsockname()
        self.thread = threading.Thread(target=self._run, daemon=True)
        self.thread.start()

    def _run(self):
        conn, _ = self.sock.accept()
        conn.settimeout(5.0)
        # greeting: magic + tuner type 5 (R820T) + 29 gain steps
        conn.sendall(b"RTL0" + struct.pack(">II", 5, 29))
        # read tuning commands until the client has sent at least the rate+freq
        conn.setblocking(True)
        conn.settimeout(0.5)
        try:
            while len(self.commands) < 3:
                pkt = conn.recv(5)
                if len(pkt) == 5:
                    self.commands.append(struct.unpack(">BI", pkt))
        except socket.timeout:
            pass
        # stream deterministic IQ bytes: ramp pattern
        iq = (np.arange(2 * self.n_samples) % 256).astype(np.uint8).tobytes()
        try:
            conn.sendall(iq)
        except (BrokenPipeError, ConnectionResetError):
            pass
        conn.close()
        self.sock.close()


def test_seify_source_streams_from_rtl_tcp():
    server = MockRtlTcpServer()
    n = 8192
    src = SeifySource(args=f"driver=rtl_tcp,host=127.0.0.1,port={server.addr[1]}",
                      sample_rate=2_400_000, frequency=100_000_000, gain=28.0)
    head = Head(np.complex64, n)
    snk = VectorSink(np.complex64)
    fg = Flowgraph()
    fg.connect(src, head, snk)
    Runtime().run(fg)
    server.thread.join(timeout=5)

    got = snk.items()
    assert len(got) == n
    # the stream is the deterministic u8 ramp mapped through (x-127.5)/127.5
    u = (np.arange(2 * n) % 256).astype(np.float32)
    expect = ((u[0::2] - 127.5) / 127.5 + 1j * (u[1::2] - 127.5) / 127.5)
    np.testing.assert_allclose(got, expect.astype(np.complex64), atol=1e-6)

    # the tuning commands reached the server: sample rate, frequency, gain path
    cmds = {c for c, _ in server.commands}
    assert 0x02 in cmds, f"no sample-rate command, got {server.commands}"
    by_cmd = dict((c, p) for c, p in server.commands)
    assert by_cmd.get(0x02) == 2_400_000
    assert by_cmd.get(0x01) == 100_000_000


def test_rtl_tcp_rejects_non_rtl_server():
    """A server with the wrong magic is refused with a clear error."""
    import pytest

    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    addr = sock.getsockname()

    def bad_server():
        conn, _ = sock.accept()
        conn.sendall(b"HTTP" + bytes(8))
        conn.close()
        sock.close()

    t = threading.Thread(target=bad_server, daemon=True)
    t.start()
    from futuresdr_tpu.hw.rtl_tcp import RtlTcpDriver
    d = RtlTcpDriver({"host": "127.0.0.1", "port": str(addr[1])})
    with pytest.raises(ConnectionError, match="not an rtl_tcp server"):
        d.activate_rx()
    t.join(timeout=5)


def test_rtl_tcp_server_disconnect_finishes_flowgraph():
    """Server closing the stream is EOS, not a busy-spin: the flowgraph finishes."""
    server = MockRtlTcpServer(n_samples=20_000)
    src = SeifySource(args=f"driver=rtl_tcp,host=127.0.0.1,port={server.addr[1]}",
                      sample_rate=1_000_000)
    snk = VectorSink(np.complex64)
    fg = Flowgraph()
    fg.connect(src, snk)
    Runtime().run(fg)                 # returns only if EOS propagates
    assert 0 < len(snk.items()) <= 20_000
