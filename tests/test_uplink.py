"""Single-shot uplink plane (ISSUE 18): coalesced H2D transfers, zero-copy
ingest, deferred-consume staging, and mid-stream adaptive wire switching.

Acceptance contracts exercised here:
* packed-path output BIT-IDENTICAL to the per-part path across wire formats
  x K in {1, 4} x linear / fan-out kernels, with ``h2d_starts_per_frame==1``
  and ONE billed transfer start per dispatch group;
* fault-injected replay re-ships the EXACT packed bytes (bit-identical
  output through a recovery mid-stream);
* dlpack/registered-buffer ingest frames stay pinned until drain AND a
  covering checkpoint (the owner's ``pinned`` flag honors fault replay);
* an adaptive wire switch lands only at a quiescent dispatch boundary, is
  bit-exact from the switch group on, and survives recovery (the wire-switch
  log replays like the retune log).
"""

import asyncio

import numpy as np
import pytest

from futuresdr_tpu import Mocker
from futuresdr_tpu.config import config
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import (FanoutPipeline, fir_stage, mag2_stage,
                               rotator_stage)
from futuresdr_tpu.ops import ingest, xfer
from futuresdr_tpu.ops.arena import PackedAlloc, StagingArena
from futuresdr_tpu.ops.wire import WIRE_FORMATS, get_wire
from futuresdr_tpu.tpu import TpuKernel
from futuresdr_tpu.tpu.kernel_block import TpuFanoutKernel, WireController

FS = 2048


@pytest.fixture(autouse=True)
def _uplink_defaults():
    """Every test starts from the shipped uplink defaults and leaves no
    ingest registrations behind."""
    c = config()
    saved = (c.tpu_coalesce, c.tpu_zero_copy_ingest, c.tpu_deferred_consume,
             c.tpu_adaptive_wire)
    ingest.reset()
    yield
    (c.tpu_coalesce, c.tpu_zero_copy_ingest, c.tpu_deferred_consume,
     c.tpu_adaptive_wire) = saved
    ingest.reset()


def _taps():
    return firdes.lowpass(0.2, 31).astype(np.float32)


def _data(n_frames, seed=7):
    rng = np.random.default_rng(seed)
    n = FS * n_frames
    return (rng.standard_normal(n) + 1j * rng.standard_normal(n)) \
        .astype(np.complex64)


def _kernel(wire="sc16", k=1, ck=None):
    return TpuKernel([fir_stage(_taps(), fft_len=256, name="f"),
                      rotator_stage(0.05, name="rot")],
                     np.complex64, frame_size=FS, frames_in_flight=2,
                     wire=wire, frames_per_dispatch=k,
                     checkpoint_every=ck)


def _drive(mk, data, out_scale=2):
    m = Mocker(mk)
    m.input("in", data)
    m.init_output("out", len(data) * out_scale)
    m.init()
    m.run()
    return m.output("out").copy()


# ---------------------------------------------------------------------------
# coalescing: layout + alloc units
# ---------------------------------------------------------------------------

def test_packed_layout_probe_gates():
    """Single-part wires never pack (coalescing is moot at one H2D start);
    quantizers pack payload+scale; the config kill switch wins."""
    assert xfer.PackedLayout.probe(get_wire("f32"), FS, np.complex64,
                                   k=1) is None
    lay = xfer.PackedLayout.probe(get_wire("sc16"), FS, np.complex64, k=1)
    assert lay is not None and len(lay.slots) == 2
    assert lay.nbytes % xfer.PackedLayout.ALIGN == 0
    # every slot offset is aligned
    for _, _, off, _ in lay.slots:
        assert off % xfer.PackedLayout.ALIGN == 0


def test_packed_layout_roundtrip_bit_exact():
    """pack → device unpack prolog → bitcast views reproduce every part
    bit-for-bit, gaps zeroed (deterministic replay bytes)."""
    import jax
    for wname in ("sc16", "sc8"):
        for k in (1, 4):
            w = get_wire(wname)
            lay = xfer.PackedLayout.probe(w, FS, np.complex64, k=k)
            rng = np.random.default_rng(3)
            frames = [(rng.standard_normal(FS) + 1j
                       * rng.standard_normal(FS)).astype(np.complex64)
                      for _ in range(k)]
            encs = [w.encode_host(f) for f in frames]
            parts = [np.stack([np.asarray(e[i]) for e in encs])
                     if k > 1 else np.asarray(encs[0][i])
                     for i in range(len(encs[0]))]
            buf = lay.pack(parts, np.empty(lay.nbytes, np.uint8))
            out = jax.jit(lay.unpack_jax)(buf)
            assert len(out) == len(parts)
            for a, b in zip(parts, out):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b), err_msg=wname)


def test_packed_alloc_writes_through_slots():
    """A PackedAlloc encode writes int payloads at their packed offsets —
    pack() then skips the copy (np.shares_memory) and only settles bare
    parts (the quantizer's scale scalar) and gap bytes."""
    w = get_wire("sc16")
    lay = xfer.PackedLayout.probe(w, FS, np.complex64, k=1)
    a = StagingArena()
    alloc = PackedAlloc(a, lay)
    x = _data(1)
    parts = w.encode_into(x, alloc)
    assert np.shares_memory(np.asarray(parts[0]), alloc.packed)
    packed = alloc.finish(parts)
    ref = [np.asarray(p) for p in w.encode_host(x)]
    got = lay.unpack_host(packed) if hasattr(lay, "unpack_host") else None
    # settle through the slot table directly
    for (sh, dt, off, nb), r in zip(lay.slots, ref):
        np.testing.assert_array_equal(
            packed[off:off + nb].view(dt).reshape(sh), r)
    for h in alloc.handles:
        h.release()


# ---------------------------------------------------------------------------
# coalescing: end-to-end bit-equality + starts billing
# ---------------------------------------------------------------------------

def _run_chain(wire, k, coalesce, n_frames=8, seed=7):
    c = config()
    c.tpu_coalesce = coalesce
    data = _data(n_frames, seed)
    mk = _kernel(wire=wire, k=k)
    m = Mocker(mk)
    m.input("in", data)
    m.init_output("out", len(data) * 2)
    m.init()                 # compile + warmup + cost probes bill separately
    starts0 = xfer._XFER_STARTS.get(direction="h2d")
    m.run()
    starts = xfer._XFER_STARTS.get(direction="h2d") - starts0
    return m.output("out").copy(), starts, mk.extra_metrics()


@pytest.mark.parametrize("wire", ["sc16", "sc8"])
@pytest.mark.parametrize("k", [1, 4])
def test_packed_bit_identical_and_single_start(wire, k):
    a, sa, ema = _run_chain(wire, k, coalesce=True)
    b, sb, emb = _run_chain(wire, k, coalesce=False)
    np.testing.assert_array_equal(a, b)
    assert ema["uplink_coalesced"] == 1 and emb["uplink_coalesced"] == 0
    assert ema["h2d_starts_per_frame"] == 1
    assert emb["h2d_starts_per_frame"] == 2      # payload + scale
    groups = 8 // k
    # ONE billed transfer start per packed group; per-part pays one per
    # wire part (quantizer payload + scale)
    assert sa == groups, (sa, groups)
    assert sb == 2 * groups, (sb, groups)


def test_packed_single_part_wires_stay_per_part():
    out, _, em = _run_chain("f32", 1, coalesce=True)
    assert em["uplink_coalesced"] == 0
    assert em["h2d_starts_per_frame"] == 1       # already single-start


def test_packed_fanout_bit_identical():
    """Fan-out kernels ride the same packed upload (one input crossing)."""
    def mk_fan():
        return TpuFanoutKernel(
            FanoutPipeline([fir_stage(_taps(), fft_len=256, name="p")],
                           [[mag2_stage()], [rotator_stage(0.1)]],
                           np.complex64),
            frame_size=FS, frames_in_flight=2, wire="sc16")
    data = _data(6)
    outs = {}
    for coalesce in (True, False):
        config().tpu_coalesce = coalesce
        mk = mk_fan()
        m = Mocker(mk)
        m.input("in", data)
        m.init_output("out0", len(data) * 2)
        m.init_output("out1", len(data) * 2)
        m.init()
        m.run()
        outs[coalesce] = (m.output("out0").copy(), m.output("out1").copy())
        if coalesce:
            assert mk.extra_metrics()["uplink_coalesced"] == 1
    np.testing.assert_array_equal(outs[True][0], outs[False][0])
    np.testing.assert_array_equal(outs[True][1], outs[False][1])


@pytest.mark.parametrize("k", [1, 4])
def test_packed_replay_bit_identical(k):
    """A recovery mid-stream re-ships the logged PACKED buffers untouched:
    the full output matches the unfailed run bit-for-bit."""
    config().tpu_coalesce = True
    data = _data(8, seed=11)
    want = _drive(_kernel(wire="sc16", k=k, ck=2), data)

    mk = _kernel(wire="sc16", k=k, ck=2)
    m = Mocker(mk)
    m.init_output("out", len(data) * 2)
    m.init()
    m.input("in", data[:FS * 4])
    m.run()
    assert mk._packed is not None
    assert asyncio.run(mk.recover(RuntimeError("injected test fault")))
    m.input("in", data[FS * 4:])
    m.run()
    np.testing.assert_array_equal(m.output("out"), want)


def test_packed_survives_fake_link_faults():
    """Transient H2D faults under the seeded fake link retry the SAME packed
    buffer — output equals the clean run exactly."""
    config().tpu_coalesce = True
    data = _data(8, seed=5)
    want = _drive(_kernel(wire="sc16", k=1), data)
    old_backoff = config().xfer_backoff
    config().xfer_backoff = 0.0005
    try:
        xfer.set_fake_link(fault_rate=0.2, fault_seed=3)
        r0 = xfer._RETRIES.get(direction="h2d")
        got = _drive(_kernel(wire="sc16", k=1), data)
        assert xfer._RETRIES.get(direction="h2d") > r0   # faults actually hit
    finally:
        xfer.set_fake_link()
        config().xfer_backoff = old_backoff
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# zero-copy ingest
# ---------------------------------------------------------------------------

def test_ingest_registry_lookup_and_writable_fallback():
    a = np.arange(4096, dtype=np.complex64)
    h = ingest.register(a, name="t")
    assert not a.flags.writeable                 # tripwire armed
    assert ingest.lookup(a[10:100]) is h         # views resolve to the root
    assert ingest.register(a) is h               # idempotent per root
    w = np.arange(64, dtype=np.complex64)
    assert ingest.lookup(w) is None              # writable → copy path
    ingest.unregister(h)
    assert ingest.lookup(a) is None


def test_ingest_refcount_idle_callback():
    idled = []
    a = np.zeros(1024, np.float32)
    h = ingest.register(a, on_idle=idled.append)
    assert not h.pinned
    h.retain()
    assert h.pinned and not idled
    h.release()
    assert not h.pinned and idled == [h]


def test_ingest_zero_copy_frames_on_aliasing_wire():
    """A registered read-only buffer skips the ring-exit copy on the f32
    wire; output is bit-identical to the copying run and the buffer is
    unpinned once everything drained."""
    data = _data(6, seed=9)
    want = _drive(_kernel(wire="f32", k=1), data)
    h = ingest.register(data, name="capture")
    mk = _kernel(wire="f32", k=1)
    got = _drive(mk, data)
    em = mk.extra_metrics()
    assert em["ingest_zero_copy_frac"] == 1.0, em
    assert not h.pinned                          # drained + pruned
    np.testing.assert_array_equal(got, want)


def test_ingest_pinned_through_checkpoint_replay():
    """The ingest pin rides the replay log: after a recovery the re-staged
    frames come from the STILL-PINNED registered buffer and the output stays
    bit-exact; only when replay coverage commits does the pin drop."""
    data = _data(8, seed=13)
    want = _drive(_kernel(wire="f32", k=1, ck=2), data)
    h = ingest.register(data, name="capture")
    mk = _kernel(wire="f32", k=1, ck=2)
    m = Mocker(mk)
    m.init_output("out", len(data) * 2)
    m.init()
    m.input("in", data[:FS * 4])
    m.run()
    assert asyncio.run(mk.recover(RuntimeError("injected test fault")))
    m.input("in", data[FS * 4:])
    m.run()
    np.testing.assert_array_equal(m.output("out"), want)
    assert mk.extra_metrics()["ingest_zero_copy_frac"] > 0
    # sparse cadence: the replay log still covers the tail groups (the
    # committed floor is the OLDER of the two retained checkpoints), so the
    # owner must keep the buffer alive — pinned stays True at EOS...
    assert h.pinned
    # ...and drops only when the kernel's retention actually ends
    mk._recovery_reset()
    assert not h.pinned


def test_ingest_disabled_on_quant_wire():
    """Quantizing wires materialize fresh int payloads — no copy to skip, so
    the fast path must not engage (deferred consume covers that case)."""
    data = _data(4)
    ingest.register(data)
    mk = _kernel(wire="sc16", k=1)
    assert not mk._ingest_enabled
    _drive(mk, data)
    assert mk.extra_metrics()["ingest_zero_copy_frac"] == 0.0


def test_ingest_from_dlpack():
    import jax
    x = jax.numpy.arange(256, dtype=jax.numpy.float32)
    arr = ingest.from_dlpack(x)
    assert ingest.lookup(arr) is not None
    np.testing.assert_array_equal(np.asarray(x), arr)


# ---------------------------------------------------------------------------
# deferred-consume staging (quantizing wires, K=1 pool mode)
# ---------------------------------------------------------------------------

def test_deferred_consume_engages_and_matches():
    config().tpu_deferred_consume = True
    data = _data(8)
    mk = _kernel(wire="sc16", k=1)
    want_engaged = mk._codec_pool is not None
    got = _drive(mk, data)
    em = mk.extra_metrics()
    assert em["deferred_consume"] == int(want_engaged)
    assert mk._pending_consume is None           # fully settled at EOS
    config().tpu_deferred_consume = False
    off = _drive(_kernel(wire="sc16", k=1), data)
    np.testing.assert_array_equal(got, off)


# ---------------------------------------------------------------------------
# adaptive wire switching
# ---------------------------------------------------------------------------

def _feed(ctl, frames, wire_s=0.0, n=16):
    """Feed n dispatch groups' worth of signal + wire windows."""
    for _ in range(n):
        for f in frames:
            ctl.observe_frame(f)
        ctl.note_dispatch((0.0, wire_s) if wire_s else None)


def test_wire_controller_widens_on_low_snr():
    """A high crest-factor signal (one huge spike over a quiet floor)
    predicts sub-budget sc8 SNR → two agreeing windows propose widening."""
    ctl = WireController(budget_db=40.0, window=4)
    quiet = np.full(512, 1e-4, np.complex64)
    quiet[0] = 1.0 + 0j                          # crest: peak >> rms
    assert ctl.predicted_snr_db("f32") == float("inf")
    _feed(ctl, [quiet], n=4)
    assert ctl.propose("sc8") is None            # first agreeing window
    _feed(ctl, [quiet], n=4)
    assert ctl.propose("sc8") == "sc16"          # second → widen one step
    # holdoff mutes the next windows
    _feed(ctl, [quiet], n=4)
    assert ctl.propose("sc16") is None


def test_wire_controller_narrows_only_when_link_busy():
    """A well-conditioned signal clears the sc16 budget+margin, but the
    narrow proposal needs measured H2D occupancy ≥ the bar."""
    sig = (np.ones(512) * 0.5).astype(np.complex64)
    idle = WireController(budget_db=40.0, window=4)
    _feed(idle, [sig], wire_s=0.0, n=8)
    assert idle.propose("f32") is None           # idle link: stay exact
    busy = WireController(budget_db=40.0, window=4)
    # occupancy ≈ busy_s/span ≥ bar: claim 10 s of wire time per window
    _feed(busy, [sig], wire_s=10.0, n=4)
    assert busy.propose("f32") is None
    _feed(busy, [sig], wire_s=10.0, n=4)
    assert busy.propose("f32") == "sc16"


def test_apply_wire_retune_switches_at_quiescent_boundary():
    """Manual wire surgery mid-stream: the switch lands between dispatch
    groups and the tail is bit-identical to a run built on the new wire."""
    data = _data(8, seed=13)
    mk = _kernel(wire="sc16", k=1, ck=2)
    m = Mocker(mk)
    m.init_output("out", len(data) * 2)
    m.init()
    m.input("in", data[:FS * 4])
    m.run()
    mk.apply_wire_retune("f32")
    m.input("in", data[FS * 4:])
    m.run()
    assert mk.wire.name == "f32"
    assert mk.extra_metrics()["wire_switches"] == 1
    want_tail = _drive(_kernel(wire="f32", k=1, ck=2), data)[FS * 8:]
    np.testing.assert_array_equal(m.output("out")[FS * 8:], want_tail)


def test_wire_switch_survives_recovery():
    """The wire-switch log replays like the retune log: a restore point
    after the switch recovers INTO the switched format."""
    data = _data(8, seed=13)
    mk = _kernel(wire="sc16", k=1, ck=2)
    m = Mocker(mk)
    m.init_output("out", len(data) * 2)
    m.init()
    m.input("in", data[:FS * 4])
    m.run()
    mk.apply_wire_retune("sc8")
    m.input("in", data[FS * 4:FS * 6])
    m.run()
    assert mk.wire.name == "sc8"
    assert asyncio.run(mk.recover(RuntimeError("injected test fault")))
    assert mk.wire.name == "sc8"                 # restored from the log
    m.input("in", data[FS * 6:])
    m.run()
    assert mk.wire.name == "sc8"


def test_wire_retune_rejects_unknown_format():
    mk = _kernel(wire="sc16", k=1)
    with pytest.raises(Exception):
        mk.apply_wire_retune("nope")


# ---------------------------------------------------------------------------
# autotune wire axis
# ---------------------------------------------------------------------------

def test_autotune_wire_axis_roundtrip(tmp_path, monkeypatch):
    import sys
    at = sys.modules["futuresdr_tpu.tpu.autotune"]
    monkeypatch.setattr(config(), "autotune_cache_dir", str(tmp_path))
    at._streamed_cache.clear()
    at._disk_memo.clear()
    stages = [fir_stage(_taps(), fft_len=256, name="f")]
    at.record_streamed_pick(stages, np.complex64, "cpu", 4, inflight=2)
    at.record_wire_start(stages, np.complex64, "cpu", "sc16")
    # a later K re-record preserves the orthogonal wire axis
    at.record_streamed_pick(stages, np.complex64, "cpu", 1, inflight=4)
    assert at.cached_wire_start(stages, np.complex64, "cpu") == "sc16"
    # disk round-trip through _norm_entry
    at._streamed_cache.clear()
    at._disk_memo.clear()
    e = at.cached_streamed_pick(stages, np.complex64, "cpu")
    assert e == {"k": 1, "inflight": 4, "wire": "sc16"}
    # unknown formats are dropped, not stored
    at.record_wire_start(stages, np.complex64, "cpu", "bogus")
    assert at.cached_wire_start(stages, np.complex64, "cpu") == "sc16"
    at._streamed_cache.clear()
    at._disk_memo.clear()


def test_adaptive_kernel_starts_from_cached_pick(tmp_path, monkeypatch):
    """Arming tpu_adaptive_wire adopts the cached autotune_streamed wire as
    the policy's start point (the build-time wire is just the fallback)."""
    import sys
    at = sys.modules["futuresdr_tpu.tpu.autotune"]
    monkeypatch.setattr(config(), "autotune_cache_dir", str(tmp_path))
    monkeypatch.setattr(config(), "tpu_adaptive_wire", True)
    at._streamed_cache.clear()
    at._disk_memo.clear()
    stages = [fir_stage(_taps(), fft_len=256, name="f"),
              rotator_stage(0.05, name="rot")]
    at.record_wire_start(stages, np.complex64, "cpu", "sc16")
    mk = TpuKernel(stages, np.complex64, frame_size=FS,
                   frames_in_flight=2, wire="f32")
    assert mk.wire.name == "sc16" and mk._wire0 == "sc16"
    assert mk._wirectl is not None
    assert mk._packed is not None                # re-derived for the start
    at._streamed_cache.clear()
    at._disk_memo.clear()
