"""Interior-precision lowering correctness (ops/precision.py) + the Pallas
PFB/FIR hot kernels (ops/pallas_kernels.py) + the per-call-site ``impl=``
plumbing and per-dtype chip peaks that ride the same PR.

The contract under test (docs/tpu_notes.md "Interior precision"):

* ``interior_precision="off"`` is BIT-identical to an unlowered build — the
  planner returns the SAME pipeline object.
* ``"auto"`` lowers only where the MEASURED per-edge SNR vs the f32 reference
  clears the budget; refusals carry machine-readable reasons; the end-to-end
  composition guard rolls the whole plan back when the sink SNR blows the
  incoherent-sum allowance.
* Lowered programs keep the full streaming contract: carry checkpoint/replay
  round-trips bf16 leaves bit-exactly, fan-out/DAG shapes lower per node,
  merges decline.
* The Pallas kernels are tolerance-pinned against the matmul paths they
  replace, including ragged tails that exercise the block padding.
"""

import json
from fractions import Fraction

import numpy as np
import pytest

import jax.numpy as jnp

from futuresdr_tpu.ops import precision as P
from futuresdr_tpu.ops.stages import (DagPipeline, FanoutPipeline, MergeStage,
                                      Pipeline, Stage, channelizer_stage,
                                      fft_stage, fir_stage, mag2_stage)


def _run(pipe, x, frame=None):
    """Compile + run one frame through a pipeline, return host output."""
    fn, c = pipe.compile(len(x) if frame is None else frame, donate=False)
    _c, y = fn(c, jnp.asarray(x))
    return np.asarray(y)


def _stream(pipe, x, frame):
    """Run ``x`` through ``pipe`` frame by frame (carry chained); returns the
    concatenated output and the final carry."""
    fn, c = pipe.compile(frame, donate=False)
    outs = []
    for i in range(0, len(x), frame):
        c, y = fn(c, jnp.asarray(x[i:i + frame]))
        outs.append(np.asarray(y))
    return np.concatenate(outs), c


def _frames(n, dtype=np.complex64, seed=0):
    rng = np.random.default_rng(seed)
    if np.issubdtype(np.dtype(dtype), np.complexfloating):
        return ((rng.standard_normal(n) + 1j * rng.standard_normal(n))
                / np.sqrt(2)).astype(dtype)
    return rng.standard_normal(n).astype(dtype)


def _chain():
    taps = np.hanning(64).astype(np.float32)
    taps /= taps.sum()
    return [fir_stage(taps, fft_len=2048, name="fir"), fft_stage(2048)]


# ---------------------------------------------------------------------------
# planner: off / auto / bf16 / overrides / declines
# ---------------------------------------------------------------------------

def test_off_returns_same_object():
    p = Pipeline(_chain(), np.complex64)
    low, plan = P.plan_interior_precision(p, mode="off")
    assert low is p                     # bit-identical BY CONSTRUCTION
    assert plan.mode == "off" and plan.lowered == 0
    # config default is off: the no-arg form is also the same object
    low2, _ = P.plan_interior_precision(p)
    assert low2 is p


def test_auto_lowers_fir_fft_within_budget():
    p = Pipeline(_chain(), np.complex64)
    low, plan = P.plan_interior_precision(p, mode="auto", budget_db=40.0)
    assert low is not p
    assert plan.lowered == 2            # fir accum+edge, fft accum
    assert plan.declined_e2e is False
    # every accepted lowering carries a measured SNR ≥ budget (inf = exact)
    for e in plan.edges:
        if e.edge == "bf16" and e.edge_snr_db is not None:
            assert e.edge_snr_db >= 40.0
    # the sink SNR the guard measured clears the incoherent-sum floor
    assert plan.e2e_snr_db >= 40.0 - 10 * np.log10(plan.lowered)
    # and the pinned floor the bench stamps exists and sits in the bf16 band
    assert plan.min_snr_db is not None and plan.min_snr_db >= 40.0
    # tolerance pin vs the f32 reference on fresh data
    x = _frames(1 << 14, seed=3)
    yr, yl = _run(p, x), _run(low, x)
    err = float(np.mean(np.abs(yl - yr) ** 2))
    sig = float(np.mean(np.abs(yr) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 37.0


def test_tight_budget_declines_everything():
    """Stages whose lowering has REAL cost on this backend (bf16-cast carried
    weights — the OS-FIR/FFT accum knob is an MXU precision flag that is
    exact on CPU, so those measure inf and rightly pass any budget) must all
    decline under an unmeetable budget and return the original object."""
    taps = np.hanning(128).astype(np.float32)
    taps /= taps.sum()
    p = Pipeline([fir_stage(taps, decim=16, name="dec"),
                  _noise_stage("nz", 50.0)], np.complex64)
    low, plan = P.plan_interior_precision(p, mode="auto", budget_db=200.0)
    assert low is p                     # nothing lowered → original object
    assert plan.lowered == 0
    # refusals are recorded with reasons, not silently dropped
    reasons = [e.declined for e in plan.edges]
    assert any(r and "snr<" in r for r in reasons)


def test_bf16_mode_force_lowers_and_still_measures():
    p = Pipeline(_chain(), np.complex64)
    low, plan = P.plan_interior_precision(p, mode="bf16", budget_db=200.0)
    assert plan.mode == "bf16"
    assert plan.lowered == 2            # budget ignored
    # SNR is still MEASURED and reported (the honest-force contract)
    assert plan.e2e_snr_db is not None
    assert plan.declined_e2e is False   # the e2e guard is auto-only


def test_override_off_pins_stage_f32():
    p = Pipeline(_chain(), np.complex64)
    _low, plan = P.plan_interior_precision(
        p, mode="bf16", overrides={"fir": "off"})
    d = {e.stage: e for e in plan.edges}
    assert d["fir"].accum == "f32" and d["fir"].edge == "f32"
    assert d["fir"].declined == "override"
    assert d["fft2048"].accum == "bf16"


def test_override_string_form_and_bad_value():
    assert P.parse_overrides("fir=off;fft2048=bf16") == {
        "fir": "off", "fft2048": "bf16"}
    assert P.parse_overrides("") == {}
    with pytest.raises(ValueError):
        P.parse_overrides("fir=fp8")


def test_bad_mode_raises():
    p = Pipeline(_chain(), np.complex64)
    with pytest.raises(ValueError):
        P.plan_interior_precision(p, mode="int4")


def test_non_float_edges_decline():
    """An integer-valued edge (symbol stream) must pass through untouched."""
    sym = Stage(lambda c, x: (c, (jnp.abs(x) > 0.5).astype(jnp.int32)),
                lambda d: jnp.zeros(()), Fraction(1, 1), np.int32, 1, "slice")
    widen = Stage(lambda c, x: (c, x.astype(jnp.float32) * 2.0),
                  lambda d: jnp.zeros(()), Fraction(1, 1), np.float32, 1,
                  "widen")
    p = Pipeline([sym, widen], np.float32)
    _low, plan = P.plan_interior_precision(p, mode="bf16")
    d = {e.stage: e for e in plan.edges}
    assert d["slice"].declined == "non-float"
    assert d["slice"].accum == "f32" and d["slice"].edge == "f32"


def test_int8_ladder_reaches_declaring_stage():
    """The int8 rung is tried first wherever a stage's ``lower`` hook accepts
    it — the mechanism pinned with a synthetic declaring stage (scale-by-2
    rebuilt at int8 as an exact int op), independent of the FIR family's
    real int8 forms (tested below)."""
    def lower(prec):
        if prec not in ("int8", "bf16"):
            return None
        return Stage(lambda c, x: (c, (x.astype(jnp.int8) * 2)
                                   .astype(jnp.float32)),
                     lambda d: jnp.zeros(()), Fraction(1, 1), np.float32, 1,
                     "dbl", compute_dtype="bf16")

    dbl = Stage(lambda c, x: (c, x * 2.0), lambda d: jnp.zeros(()),
                Fraction(1, 1), np.float32, 1, "dbl", lower=lower)
    sink = Stage(lambda c, x: (c, x + 0.0), lambda d: jnp.zeros(()),
                 Fraction(1, 1), np.float32, 1, "sink")
    p = Pipeline([dbl, sink], np.float32)

    # int8-exact inputs: the int8 candidate is bit-exact → SNR inf → accepted
    # at the FIRST (most-compressed) rung
    def frames(in_dtype, frame, n, seed):
        rng = np.random.default_rng(seed)
        return [rng.integers(-50, 50, frame).astype(np.float32)
                for _ in range(n)]
    orig = P._calib_frames
    P._calib_frames = frames
    try:
        _low, plan = P.plan_interior_precision(p, mode="auto", budget_db=40.0)
    finally:
        P._calib_frames = orig
    d = {e.stage: e for e in plan.edges}
    assert d["dbl"].accum == "int8"


def test_int8_mode_forces_fir_rung_and_carry_compat():
    """mode="int8" walks the FIR family down to the quantized int8 matmul
    form (edges stay bf16 — forced modes never widen the wire), mode="bf16"
    must NOT force-accept the deeper rung, and the int8-lowered carries
    stay treedef/shape-compatible with the f32 chain's (the serve brownout
    leafwise-conversion contract: int8 stages carry FLOAT weights and
    quantize in-trace)."""
    import jax
    p = Pipeline(_chain() + [mag2_stage()], np.complex64)
    low, plan = P.plan_interior_precision(p, mode="int8")
    d = {e.stage: e for e in plan.edges}
    assert d["fir"].accum == "int8"
    cd = {s.name: s.compute_dtype for s in low.stages}
    assert cd["fir"] == "int8"
    for e in plan.edges:
        assert e.edge in ("bf16", "f32")        # int8 never hits the wire

    # forced bf16 stays bf16 — the deeper rung needs mode="int8"
    _lb, plan_b = P.plan_interior_precision(p, mode="bf16")
    db = {e.stage: e for e in plan_b.edges}
    assert db["fir"].accum == "bf16"

    # carry compatibility: same treedefs, same leaf shapes (dtype may
    # narrow — the brownout converts leafwise)
    a_l, a_def = jax.tree_util.tree_flatten(p.init_carry())
    b_l, b_def = jax.tree_util.tree_flatten(low.init_carry())
    assert a_def == b_def
    assert [np.shape(a) for a in a_l] == [np.shape(b) for b in b_l]

    # numerics: the quantization band, not garbage — and decim paths too
    x = _frames(4 * 4096, seed=31)
    ref, _ = _stream(p, x, 4096)
    got, _ = _stream(low, x, 4096)
    err = float(np.mean(np.abs(got - ref) ** 2))
    sig = float(np.mean(np.abs(ref) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 25.0

    taps = np.hanning(96).astype(np.float32)
    taps /= taps.sum()
    pd = Pipeline([fir_stage(taps, decim=8, impl="poly", name="dfir")],
                  np.complex64)
    lowd, pland = P.plan_interior_precision(pd, mode="int8")
    assert {e.stage: e.accum for e in pland.edges}["dfir"] == "int8"
    refd, _ = _stream(pd, x, 4096)
    gotd, _ = _stream(lowd, x, 4096)
    errd = float(np.mean(np.abs(gotd - refd) ** 2))
    sigd = float(np.mean(np.abs(refd) ** 2))
    assert 10 * np.log10(sigd / max(errd, 1e-30)) >= 25.0

    # int8 routes never count as Pallas stages (they lower to quantized
    # XLA matmuls, not hand-written kernels)
    assert P.pallas_stage_count(lowd) == 0


def _noise_stage(name, snr_target_db, phase=0.0):
    """Identity stage whose bf16-lowering candidate adds a DETERMINISTIC
    noise vector at exactly ``snr_target_db`` below unit power — the e2e
    guard's test vehicle (same ``phase`` → coherent noise across stages)."""
    eps = 10.0 ** (-snr_target_db / 20.0)

    def fn(c, x):
        return c, x

    def lower(prec):
        if prec != "bf16":
            return None

        def lfn(c, x):
            i = jnp.arange(x.shape[0], dtype=jnp.float32)
            n = jnp.sin(12.9898 * i + phase)
            n = n / jnp.sqrt(jnp.mean(n * n))      # exactly unit power
            return c, x + eps * n.astype(x.dtype)

        return Stage(lfn, lambda d: jnp.zeros(()), Fraction(1, 1), None, 1,
                     name, compute_dtype="bf16")

    return Stage(fn, lambda d: jnp.zeros(()), Fraction(1, 1), None, 1, name,
                 lower=lower)


def test_e2e_guard_rolls_back_coherent_composition():
    """Four stages whose per-edge SNR each clears the budget but whose noise
    adds COHERENTLY compose to 20·log10(4) = 12 dB worse — past the
    incoherent-sum allowance (10·log10(4) ≈ 6 dB), so the auto plan must
    decline as a whole and return the original pipeline."""
    budget = 60.0
    stages = [_noise_stage(f"n{i}", budget + 3.0, phase=1.0)
              for i in range(4)]
    p = Pipeline(stages, np.float32)
    low, plan = P.plan_interior_precision(p, mode="auto", budget_db=budget)
    assert plan.declined_e2e is True
    assert low is p
    assert plan.lowered == 0            # verdicts rolled back
    assert all(e.declined and e.declined.startswith("e2e-snr<")
               for e in plan.edges)


def test_e2e_guard_keeps_incoherent_composition():
    """Two stages with INDEPENDENT noise at budget+3 compose ~3 dB worse —
    inside the allowance, so the plan stands."""
    budget = 60.0
    stages = [_noise_stage("na", budget + 3.0, phase=1.0),
              _noise_stage("nb", budget + 3.0, phase=40.7)]
    p = Pipeline(stages, np.float32)
    low, plan = P.plan_interior_precision(p, mode="auto", budget_db=budget)
    assert plan.declined_e2e is False
    assert low is not p
    assert plan.lowered == 2


# ---------------------------------------------------------------------------
# graph shapes: fan-out, DAG, merge declines
# ---------------------------------------------------------------------------

def test_fanout_pipeline_lowers_per_node():
    taps = np.hanning(32).astype(np.float32)
    taps /= taps.sum()
    fan = FanoutPipeline([fir_stage(taps, name="prod")],
                         [[fft_stage(256)], [mag2_stage()]], np.complex64)
    low, plan = P.plan_interior_precision(fan, mode="auto", budget_db=40.0)
    assert isinstance(low, FanoutPipeline)
    assert plan.lowered >= 1
    x = _frames(4096, seed=5)
    fn_r, c_r = fan.compile(4096, donate=False)
    fn_l, c_l = low.compile(4096, donate=False)
    _c, ys_r = fn_r(c_r, jnp.asarray(x))
    _c, ys_l = fn_l(c_l, jnp.asarray(x))
    for yr, yl in zip(ys_r, ys_l):
        yr, yl = np.asarray(yr), np.asarray(yl)
        err = float(np.mean(np.abs(yl - yr) ** 2))
        sig = float(np.mean(np.abs(yr) ** 2))
        assert 10 * np.log10(sig / max(err, 1e-30)) >= 37.0


def test_dag_merge_declines_and_dag_lowers():
    taps = np.hanning(16).astype(np.float32)
    taps /= taps.sum()
    merge = MergeStage(lambda c, xs: (c, xs[0] + xs[1]),
                       lambda d: jnp.zeros(()), k=2, name="sum")
    dag = DagPipeline([
        ([fir_stage(taps, name="prod")], []),
        ([fft_stage(256)], [0]),
        ([fft_stage(256, direction="inverse")], [0]),
        ([merge], [1, 2]),
    ], np.complex64)
    low, plan = P.plan_interior_precision(dag, mode="bf16")
    d = {e.stage: e for e in plan.edges}
    assert d["sum"].declined == "merge"
    assert plan.lowered >= 2
    x = _frames(4096, seed=6)
    yr = _run(dag, x)
    yl = _run(low, x)
    err = float(np.mean(np.abs(yl - yr) ** 2))
    sig = float(np.mean(np.abs(yr) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 37.0


# ---------------------------------------------------------------------------
# streaming contract: carry dtypes, checkpoint/replay round trip
# ---------------------------------------------------------------------------

def test_lowered_poly_fir_carries_bf16_weights():
    taps = np.hanning(128).astype(np.float32)
    taps /= taps.sum()
    p = Pipeline([fir_stage(taps, decim=16, name="dec")], np.complex64)
    low, plan = P.plan_interior_precision(p, mode="bf16")
    assert plan.lowered == 1
    carry = low.init_carry()
    import jax
    leaves = jax.tree_util.tree_flatten(carry)[0]
    dts = {str(np.asarray(l).dtype) for l in leaves}
    assert "bfloat16" in dts            # the carried weight matrix halved


def test_lowered_checkpoint_replay_bit_identical():
    """snapshot_carry → restore_carry of a LOWERED pipeline reproduces the
    unfailed run bit-for-bit (bf16 leaves round-trip the host hop)."""
    taps = np.hanning(128).astype(np.float32)
    taps /= taps.sum()
    frame = 8192
    x = _frames(4 * frame, seed=9)
    p = Pipeline([fir_stage(taps, decim=16, name="dec"), fft_stage(256)],
                 np.complex64)
    low, _plan = P.plan_interior_precision(p, mode="bf16")

    ref, _c = _stream(low, x, frame)

    # run 2 frames, checkpoint, restore into a FRESH compile, run the rest
    fn, c = low.compile(frame, donate=False)
    outs = []
    for i in range(0, 2 * frame, frame):
        c, y = fn(c, jnp.asarray(x[i:i + frame]))
        outs.append(np.asarray(y))
    fins, treedef = low.snapshot_carry(c)
    leaves = [np.asarray(f()) for f in fins]
    assert low.carry_matches(leaves, treedef, low.init_carry())
    c2 = low.restore_carry(leaves, treedef)
    fn2, _fresh = low.compile(frame, donate=False)
    for i in range(2 * frame, 4 * frame, frame):
        c2, y = fn2(c2, jnp.asarray(x[i:i + frame]))
        outs.append(np.asarray(y))
    got = np.concatenate(outs)
    np.testing.assert_array_equal(got, ref)


def test_mismatched_dtype_checkpoint_rejected():
    """A checkpoint taken from the f32 build must FAIL the lowered build's
    carry integrity check (the dtype contract the restore path enforces)."""
    taps = np.hanning(128).astype(np.float32)
    taps /= taps.sum()
    p = Pipeline([fir_stage(taps, decim=16, name="dec")], np.complex64)
    low, _plan = P.plan_interior_precision(p, mode="bf16")
    fn, c = p.compile(8192, donate=False)
    c, _y = fn(c, jnp.asarray(_frames(8192)))
    fins, treedef = p.snapshot_carry(c)
    leaves = [np.asarray(f()) for f in fins]
    assert p.carry_matches(leaves, treedef, p.init_carry())
    assert not low.carry_matches(leaves, treedef, low.init_carry())


# ---------------------------------------------------------------------------
# kernel plane: off bit-identity, pre-init retune scoping, plan publication
# ---------------------------------------------------------------------------

def _kernel_run(x, frame, **kw):
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.tpu import TpuKernel
    fg = Flowgraph()
    src = VectorSource(x)
    tk = TpuKernel(_chain(), np.complex64, frame_size=frame, **kw)
    snk = VectorSink(np.complex64)
    fg.connect(src, tk, snk)
    Runtime().run(fg)
    return np.asarray(snk.items()), tk


def test_kernel_off_bit_identical_and_auto_within_budget():
    x = _frames(1 << 15, seed=11)
    y_default, _ = _kernel_run(x, 8192)
    y_off, tk_off = _kernel_run(x, 8192, interior_precision="off")
    np.testing.assert_array_equal(y_default, y_off)
    assert tk_off._precision_plan is None
    assert tk_off.extra_metrics()["interior_precision"] == "off"

    y_auto, tk = _kernel_run(x, 8192, interior_precision="auto")
    assert tk._precision_plan is not None and tk._precision_plan.lowered == 2
    assert tk.extra_metrics()["interior_lowered"] == 2
    err = float(np.mean(np.abs(y_auto - y_off) ** 2))
    sig = float(np.mean(np.abs(y_off) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 37.0
    # the applied plan is published under the kernel's program name for
    # doctor.report()["precision"] and the REST profile view
    plans = P.plans_report()
    hit = [v for v in plans.values() if v["mode"] == "auto"]
    assert hit and hit[-1]["lowered"] == 2


def test_precision_retune_preinit_scopes_to_named_stage():
    """A single-stage retune on an 'off' kernel lowers ONLY that stage —
    entering auto mode must not drag the rest of the chain with it."""
    from futuresdr_tpu.tpu import TpuKernel
    tk = TpuKernel(_chain(), np.complex64, frame_size=8192,
                   interior_precision="off")
    tk.apply_precision_retune("fft2048", "bf16")
    plan = tk._precision_plan
    d = {e.stage: e for e in plan.edges}
    assert d["fft2048"].accum == "bf16"
    assert d["fir"].accum == "f32" and d["fir"].edge == "f32"
    assert d["fir"].declined == "override"
    with pytest.raises(ValueError):
        tk.apply_precision_retune("fir", "fp8")
    with pytest.raises(KeyError):
        tk.apply_precision_retune("nope", "bf16")


def test_widening_retune_restores_pristine_parameters():
    """Retuning bf16 → off must take WIDENED parameter leaves from the
    pristine template, not upcast the quantized bf16 values — an 'f32'
    program carrying frozen bf16 quantization would be a silent lie."""
    import jax
    import time
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Throttle, VectorSink, VectorSource
    from futuresdr_tpu.tpu import TpuKernel
    from futuresdr_tpu.types import Pmt

    taps = np.hanning(128).astype(np.float32)
    taps /= taps.sum()
    n = 1 << 16
    x = _frames(n, seed=41)
    fg = Flowgraph()
    src = VectorSource(x)
    thr = Throttle(np.complex64, rate=300_000.0)
    tk = TpuKernel([fir_stage(taps, decim=16, name="dec")], np.complex64,
                   frame_size=8192, frames_in_flight=2,
                   interior_precision="bf16")
    snk = VectorSink(np.complex64)
    fg.connect(src, thr, tk, snk)
    rt = Runtime()
    running = rt.start(fg)
    t0 = time.perf_counter()
    while len(snk.items()) < n // 64 and time.perf_counter() - t0 < 30:
        time.sleep(0.02)
    r = rt.scheduler.run_coro_sync(running.handle.call(
        tk, "ctrl", Pmt.map({"stage": "dec", "interior_precision": "off"})))
    assert r == Pmt.ok()
    running.wait_sync()
    assert len(snk.items()) == (n // 8192) * 8192 // 16
    # the widened W leaf is BIT-equal to the pristine f32 build's parameter
    # (inspected AFTER the drain — mid-stream the carry buffers are donated;
    # dispatches thread W through unchanged, so the pin holds at the end)
    ref = {a.tobytes() for a in
           (np.asarray(l) for l in jax.tree_util.tree_flatten(
               tk._base_pipeline.init_carry())[0])
           if a.dtype == np.float32 and a.ndim == 2}
    got = [np.asarray(l) for l in jax.tree_util.tree_flatten(tk._carry)[0]
           if np.asarray(l).dtype == np.float32 and np.asarray(l).ndim == 2]
    assert got and all(w.tobytes() in ref for w in got)


def test_noop_retune_keeps_off_mode_and_program():
    """Pinning 'off' on an already-off kernel must not recompile or flip the
    reported mode to 'auto' — the program is unchanged."""
    from futuresdr_tpu.tpu import TpuKernel
    tk = TpuKernel(_chain(), np.complex64, frame_size=8192,
                   interior_precision="off")
    pipe = tk.pipeline
    tk.apply_precision_retune("fir", "off")
    assert tk.pipeline is pipe
    assert tk._precision_mode == "off"
    assert tk.extra_metrics()["interior_precision"] == "off"
    # the pin is still remembered for later retunes of OTHER stages
    assert tk._precision_overrides["fir"] == "off"


def test_kernel_init_corrects_stale_precision_axis():
    """An off-mode kernel's init must overwrite a stale lowering stamp in
    the streamed-pick cache (a cached K measured under bf16 must not claim
    to describe an f32 rebuild) — and must NOT create entries for chains
    that were never tuned or lowered."""
    from futuresdr_tpu.tpu.autotune import (cached_interior_precision,
                                            record_interior_precision)
    x = _frames(1 << 14, seed=43)
    stages = _chain()
    record_interior_precision(stages, np.complex64, "cpu", "bf16")
    _y, tk = _kernel_run(x, 8192, interior_precision="off")
    assert cached_interior_precision(
        stages, np.complex64, tk.inst.platform) == "off"
    # a DIFFERENT never-stamped chain gains no entry from an off-mode init
    other = [fir_stage(np.hanning(32).astype(np.float32), name="solo")]
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.tpu import TpuKernel
    fg = Flowgraph()
    tk2 = TpuKernel(other, np.complex64, frame_size=8192,
                    interior_precision="off")
    fg.connect(VectorSource(x), tk2, VectorSink(np.complex64))
    Runtime().run(fg)
    assert cached_interior_precision(
        other, np.complex64, tk2.inst.platform) is None


def test_doctor_and_profile_report_carry_plans():
    from futuresdr_tpu.telemetry import doctor as doc
    from futuresdr_tpu.telemetry import profile as prof
    p = Pipeline(_chain(), np.complex64)
    _low, plan = P.plan_interior_precision(p, mode="auto", budget_db=40.0)
    P.note_plan("t-precision-prog", plan)
    try:
        snap = prof.plane().snapshot()
        assert snap["precision"]["t-precision-prog"]["lowered"] == 2
        rep = doc.report([])
        assert rep["precision"]["t-precision-prog"]["mode"] == "auto"
        # the view is JSON-clean (REST body)
        json.dumps(snap["precision"])
    finally:
        P.clear_plans()


# ---------------------------------------------------------------------------
# Pallas kernels: PFB + fused FIR→decimate vs the matmul paths
# ---------------------------------------------------------------------------

def _pfb_matmul_ref(rows, taps_kn):
    """Reference: the channelizer matmul path's branch MAC + ifft·N."""
    K, N = taps_kn.shape
    t = rows.shape[0] - (K - 1)
    windows = np.stack([rows[(K - 1) - k:(K - 1) - k + t] for k in range(K)],
                       axis=1)                       # [t, K, N]
    v = np.einsum("tkc,kc->tc", windows, taps_kn)
    return np.fft.ifft(v, axis=1) * N


@pytest.mark.parametrize("t,block", [(37, 8), (64, 64), (200, 256), (1, 4)])
def test_pallas_pfb_matches_matmul_ragged(t, block):
    """Tolerance pin vs the matmul path, incl. ragged tails where t is not a
    block multiple (the EOS-tail shape after frame padding)."""
    from futuresdr_tpu.ops.pallas_kernels import pallas_pfb
    rng = np.random.default_rng(t)
    K, N = 4, 16
    taps = rng.standard_normal((K, N)).astype(np.float32)
    rows = (rng.standard_normal((t + K - 1, N))
            + 1j * rng.standard_normal((t + K - 1, N))).astype(np.complex64)
    got = np.asarray(pallas_pfb(jnp.asarray(rows), jnp.asarray(taps),
                                block=block))
    ref = _pfb_matmul_ref(rows, taps)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_pallas_pfb_bf16_band():
    from futuresdr_tpu.ops.pallas_kernels import pallas_pfb
    rng = np.random.default_rng(2)
    K, N = 4, 32
    taps = (rng.standard_normal((K, N)) / K).astype(np.float32)
    rows = (rng.standard_normal((512 + K - 1, N))
            + 1j * rng.standard_normal((512 + K - 1, N))).astype(np.complex64)
    ref = np.asarray(pallas_pfb(jnp.asarray(rows), jnp.asarray(taps)))
    got = np.asarray(pallas_pfb(jnp.asarray(rows), jnp.asarray(taps),
                                precision="bf16"))
    err = float(np.mean(np.abs(got - ref) ** 2))
    sig = float(np.mean(np.abs(ref) ** 2))
    snr = 10 * np.log10(sig / max(err, 1e-30))
    assert 35.0 <= snr                      # bf16 band, far above sc8


def test_channelizer_pallas_impl_matches_matmul():
    x = _frames(8192, seed=13)
    ym = _run(Pipeline([channelizer_stage(16, impl="matmul")], np.complex64), x)
    yp = _run(Pipeline([channelizer_stage(16, impl="pallas")], np.complex64), x)
    err = float(np.mean(np.abs(yp - ym) ** 2))
    sig = float(np.mean(np.abs(ym) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 80.0


def test_channelizer_lower_hook_roundtrip():
    st = channelizer_stage(16, impl="matmul")
    low = st.lower("bf16")
    assert low is not None and low.compute_dtype == "bf16"
    assert st.lower("int8") is None


@pytest.mark.parametrize("nq,m,block", [(1, 3, 4), (100, 7, 16), (513, 1, 256)])
def test_pallas_poly_fir_matches_matvec_ragged(nq, m, block):
    from futuresdr_tpu.ops.pallas_kernels import pallas_poly_fir
    rng = np.random.default_rng(nq)
    D = 8
    W = rng.standard_normal((m + 1, D)).astype(np.float32)
    rows = rng.standard_normal((nq + m, D)).astype(np.float32)
    got = np.asarray(pallas_poly_fir(jnp.asarray(rows), jnp.asarray(W),
                                     block=block))
    ref = np.zeros(nq, np.float32)
    for a in range(m + 1):
        ref += rows[m - a:m - a + nq] @ W[a]
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_fir_stage_pallas_impl_matches_poly_decim():
    taps = np.hanning(128).astype(np.float32)
    taps /= taps.sum()
    x = _frames(8192, seed=17)
    ya = _run(Pipeline([fir_stage(taps, decim=16, impl="poly")], np.complex64), x)
    yb = _run(Pipeline([fir_stage(taps, decim=16, impl="pallas")], np.complex64), x)
    np.testing.assert_allclose(yb, ya, rtol=1e-4, atol=1e-5)


def test_fir_stage_pallas_decim_streaming_matches_poly():
    """Streaming (carry-chained) equality across frames — the history rows
    crossing dispatch boundaries are the part the fused kernel must get
    right."""
    taps = np.hanning(96).astype(np.float32)
    taps /= taps.sum()
    x = _frames(4 * 4096, seed=19)
    ya, _ = _stream(Pipeline([fir_stage(taps, decim=8, impl="poly")],
                             np.complex64), x, 4096)
    yb, _ = _stream(Pipeline([fir_stage(taps, decim=8, impl="pallas")],
                             np.complex64), x, 4096)
    np.testing.assert_allclose(yb, ya, rtol=1e-4, atol=1e-5)


def test_lowered_pallas_poly_fir_bf16_band():
    taps = np.hanning(128).astype(np.float32)
    taps /= taps.sum()
    x = _frames(8192, seed=23)
    p = Pipeline([fir_stage(taps, decim=16, impl="pallas")], np.complex64)
    ref = _run(p, x)
    low, plan = P.plan_interior_precision(p, mode="bf16")
    assert plan.lowered == 1
    got = _run(low, x)
    err = float(np.mean(np.abs(got - ref) ** 2))
    sig = float(np.mean(np.abs(ref) ** 2))
    assert 10 * np.log10(sig / max(err, 1e-30)) >= 40.0


def test_pallas_stage_count():
    taps = np.hanning(32).astype(np.float32)
    p = Pipeline([fir_stage(taps, decim=16, impl="pallas", name="d"),
                  fft_stage(256)], np.complex64)
    assert P.pallas_stage_count(p) == 1


def test_lti_merge_preserves_matching_pins_refuses_mixed():
    """Adjacent pinned FIRs merge only when their (fft_impl, precision) pins
    AGREE — and the merged stage keeps them; mixed pins refuse to merge (a
    pin must never silently revert to module policy / f32)."""
    t1 = np.hanning(16).astype(np.float32)
    t2 = np.hanning(8).astype(np.float32)
    same = Pipeline([fir_stage(t1, name="a", precision="bf16"),
                     fir_stage(t2, name="b", precision="bf16")], np.complex64)
    assert len(same.stages) == 1
    assert same.stages[0].compute_dtype == "bf16"
    assert same.stages[0].route[2] == "bf16"
    mixed = Pipeline([fir_stage(t1, name="a", precision="bf16"),
                      fir_stage(t2, name="b")], np.complex64)
    assert len(mixed.stages) == 2
    # unpinned firs keep merging exactly as before
    plain = Pipeline([fir_stage(t1, name="a"), fir_stage(t2, name="b")],
                     np.complex64)
    assert len(plain.stages) == 1


def test_precision_retune_rejects_ambiguous_name():
    """Overrides are name-keyed, so a retune addressing one of two
    same-named stages (by name OR by index) must be rejected, not silently
    lower both."""
    from futuresdr_tpu.tpu import TpuKernel
    taps = np.hanning(16).astype(np.float32)
    tk = TpuKernel([fir_stage(taps, fft_len=256),
                    fft_stage(256),
                    fir_stage(taps, fft_len=256)],
                   np.complex64, frame_size=4096, interior_precision="off")
    with pytest.raises(KeyError, match="ambiguous"):
        tk.apply_precision_retune("fir", "bf16")
    with pytest.raises(KeyError, match="ambiguous"):
        tk.apply_precision_retune(2, "bf16")


def test_pallas_stage_count_respects_pins_and_dtype():
    taps = np.hanning(32).astype(np.float32)
    # explicit matmul pin never counts, forced pallas counts on any backend
    assert P.pallas_stage_count(Pipeline(
        [channelizer_stage(16, impl="matmul")], np.complex64)) == 0
    assert P.pallas_stage_count(Pipeline(
        [channelizer_stage(16, impl="pallas")], np.complex64)) == 1
    assert P.pallas_stage_count(Pipeline(
        [fir_stage(taps, decim=16, impl="pallas")], np.complex64)) == 1
    # auto short-real-taps FIR only counts on TPU, and never on a complex
    # stream (_pallas_fir_wins) — on the CPU test backend both are 0
    assert P.pallas_stage_count(Pipeline(
        [fir_stage(taps[:16])], np.float32)) == 0


def test_partial_lowering_not_reported_declined():
    """A stage whose accum refuses but whose edge lowers IS lowered — the
    plan must not show a decline reason on it (the accum refusal stays
    readable as accum='f32' + its measured SNR)."""
    budget = 52.0          # between the 48 dB accum target and ~55 dB edge
    sink = Stage(lambda c, x: (c, x * 2.0), lambda d: jnp.zeros(()),
                 Fraction(1, 1), None, 1, "gain")
    p = Pipeline([_noise_stage("nz", 48.0), sink], np.float32)
    _low, plan = P.plan_interior_precision(p, mode="auto", budget_db=budget)
    nz = {e.stage: e for e in plan.edges}["nz"]
    assert nz.edge == "bf16"            # edge accepted (~55 ≥ 52)
    assert nz.accum == "f32"            # accum refused (48 < 52)
    assert nz.accum_snr_db == pytest.approx(48.0, abs=1.5)
    assert nz.declined is None          # partially lowered ≠ declined


# ---------------------------------------------------------------------------
# per-call-site impl= plumbing (the ops/mxu_fft.py header promise)
# ---------------------------------------------------------------------------

def test_fft_stage_impl_pins_route_per_call_site():
    """Two fft stages with DIFFERENT impl= in one process keep their own
    routes: the forced-mxu stage runs the direct-DFT matmul (different
    rounding than jnp.fft), the forced-xla stage runs jnp.fft — regardless
    of the module set_impl policy at trace time."""
    from futuresdr_tpu.ops import mxu_fft
    x = _frames(2048, seed=29)
    y_xla = _run(Pipeline([fft_stage(512, impl="xla")], np.complex64), x)
    old = mxu_fft._impl
    mxu_fft.set_impl("xla")             # module policy says xla...
    try:
        y_mxu = _run(Pipeline([fft_stage(512, impl="mxu")], np.complex64), x)
    finally:
        mxu_fft.set_impl(old)
    # ...but the per-call-site pin wins: matmul DFT, not jnp.fft
    assert not np.array_equal(y_mxu, y_xla)
    np.testing.assert_allclose(y_mxu, y_xla, rtol=2e-3, atol=2e-3)


def test_fir_stage_fft_impl_pins_os_core():
    taps = np.hanning(64).astype(np.float32)
    taps /= taps.sum()
    x = _frames(4096, seed=31)
    y_def = _run(Pipeline([fir_stage(taps, fft_len=512)], np.complex64), x)
    y_mxu = _run(Pipeline([fir_stage(taps, fft_len=512, fft_impl="mxu")],
                          np.complex64), x)
    assert not np.array_equal(y_mxu, y_def)     # different FFT route engaged
    np.testing.assert_allclose(y_mxu, y_def, rtol=2e-3, atol=2e-3)


# ---------------------------------------------------------------------------
# per-dtype chip peaks (utils/roofline + telemetry/profile)
# ---------------------------------------------------------------------------

def test_detect_peaks_dtype_keying(monkeypatch):
    from futuresdr_tpu.config import config
    from futuresdr_tpu.utils.roofline import detect_peaks, dtype_peak_flops
    monkeypatch.setattr(config(), "peak_flops", 200e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 800.0)
    base = detect_peaks("cpu")
    assert base["flops"] == 200e12              # back-compat: tabled bf16 peak
    f32 = detect_peaks("cpu", dtype="f32")
    assert f32["flops"] == 100e12 and f32["dtype"] == "f32"
    bf16 = detect_peaks("cpu", dtype="bf16")
    assert bf16["flops"] == 200e12
    assert dtype_peak_flops(base, "f32") == 100e12
    assert dtype_peak_flops(base, None) == 200e12


def test_dominant_dtype_of_lowered_chain():
    from futuresdr_tpu.utils.roofline import dominant_dtype
    p = Pipeline(_chain(), np.complex64)
    assert dominant_dtype(p.stages) == "f32"
    low, _ = P.plan_interior_precision(p, mode="bf16")
    assert dominant_dtype(low.stages) == "bf16"
    assert P.dominant_compute_dtype(low) == "bf16"


# ---------------------------------------------------------------------------
# autotune precision axis
# ---------------------------------------------------------------------------

def test_autotune_norm_entry_precision_axis():
    from futuresdr_tpu.tpu.autotune import _norm_entry
    good = _norm_entry({"k": 4, "inflight": 2, "interior_precision": "bf16"})
    assert good["interior_precision"] == "bf16"
    # a malformed precision field loses ONLY its axis, never (k, inflight,
    # serve_buckets)
    bad = _norm_entry({"k": 4, "inflight": 2, "serve_buckets": [2, 8],
                       "interior_precision": {"mode": "bf16"}})
    assert bad == {"k": 4, "inflight": 2, "serve_buckets": [2, 8]}
    typo = _norm_entry({"k": 4, "inflight": None,
                        "interior_precision": "fp8"})
    assert "interior_precision" not in typo and typo["k"] == 4
    assert _norm_entry("garbage") is None


def test_autotune_precision_axis_roundtrip_and_preservation():
    from futuresdr_tpu.tpu.autotune import (cached_interior_precision,
                                            cached_streamed_pick,
                                            record_interior_precision,
                                            record_streamed_pick)
    st = _chain()
    record_streamed_pick(st, np.complex64, "t-prec-plat", 8, inflight=4)
    record_interior_precision(st, np.complex64, "t-prec-plat", "auto")
    assert cached_interior_precision(st, np.complex64, "t-prec-plat") == "auto"
    entry = cached_streamed_pick(st, np.complex64, "t-prec-plat")
    assert entry["k"] == 8 and entry["inflight"] == 4
    # a later streamed re-tune must NOT wipe the precision axis
    record_streamed_pick(st, np.complex64, "t-prec-plat", 16, inflight=2)
    entry = cached_streamed_pick(st, np.complex64, "t-prec-plat")
    assert entry["k"] == 16
    assert entry["interior_precision"] == "auto"
    # unknown modes are dropped at record time, not stored-then-stripped
    record_interior_precision(st, np.complex64, "t-prec-plat", "fp8")
    assert cached_interior_precision(st, np.complex64, "t-prec-plat") == "auto"
