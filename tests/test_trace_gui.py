"""Latency tracepoints + GUI serving tests."""

import json
import urllib.request

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSource, Copy, NullSink
from futuresdr_tpu.utils import LatencyProbeSource, LatencyProbeSink, latency_stats


def test_latency_probes():
    data = np.zeros(500_000, np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    probe_in = LatencyProbeSource(np.float32, granularity=65536)
    mid = Copy(np.float32)
    probe_out = LatencyProbeSink(np.float32)
    fg.connect(src, probe_in, mid, probe_out)
    Runtime().run(fg)
    stats = latency_stats(probe_out.records)
    assert stats["count"] >= 7
    assert stats["p99_us"] >= stats["p50_us"] >= 0
    assert stats["max_us"] < 5e6


def test_gui_served_from_ctrl_port():
    from aiohttp import web
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    from futuresdr_tpu.runtime.runtime import RuntimeHandle
    from futuresdr_tpu import AsyncScheduler

    async def my_route(request):
        return web.json_response({"custom": True})

    handle = RuntimeHandle(AsyncScheduler())
    cp = ControlPort(handle, bind="127.0.0.1:29417",
                     extra_routes=[("GET", "/my/app/", my_route)])
    cp.start()
    try:
        html = urllib.request.urlopen("http://127.0.0.1:29417/").read().decode()
        assert "waterfall" in html
        ids = json.load(urllib.request.urlopen("http://127.0.0.1:29417/api/fg/"))
        assert ids == []
        # custom-routes extension point (reference: examples/custom-routes)
        r = json.load(urllib.request.urlopen("http://127.0.0.1:29417/my/app/"))
        assert r == {"custom": True}
    finally:
        cp.stop()


def test_gui_widgets_and_interactive_retune():
    """The GUI's widget library is served, and the slider/PmtEditor call path —
    a typed-Pmt POST to the call route — retunes the running FM app (the
    'interactive retune from the browser' criterion)."""
    from futuresdr_tpu.apps.fm_receiver import build_flowgraph
    from futuresdr_tpu.runtime.ctrl_port import ControlPort

    fg, xlate, _ = build_flowgraph(input_rate=1_000_000.0, n_samples=2_000_000)
    rt = Runtime()
    running = rt.start(fg)
    cp = ControlPort(rt.handle, bind="127.0.0.1:29431")
    cp.start()
    try:
        base = "http://127.0.0.1:29431"
        js = urllib.request.urlopen(base + "/static/widgets.js").read().decode()
        for widget in ("FlowgraphCanvas", "PmtEditor", "ConstellationSinkDensity",
                       "Slider", "RadioSelector", "ListSelector", "Waterfall",
                       "TimeSink", "ArrayView"):
            assert widget in js, f"widget {widget} missing from widgets.js"
        html = urllib.request.urlopen(base + "/").read().decode()
        assert "widgets.js" in html and "PmtEditor".lower() in html.lower()

        # the flowgraph description feeds the canvas: blocks + edges present
        desc = json.load(urllib.request.urlopen(base + "/api/fg/0/"))
        assert desc["blocks"] and desc["stream_edges"]
        xlate_id = next(b["id"] for b in desc["blocks"]
                        if "XlatingFir" in b["instance_name"])
        assert "freq" in next(b for b in desc["blocks"]
                              if b["id"] == xlate_id)["message_inputs"]

        # what the Slider widget sends: POST {"F64": offset} to .../call/freq/
        before = xlate.rotator.phase_inc
        req = urllib.request.Request(
            f"{base}/api/fg/0/block/{xlate_id}/call/freq/",
            data=json.dumps({"F64": 250_000.0}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        r = json.load(urllib.request.urlopen(req))
        assert r == "Ok"
        import time
        for _ in range(100):
            if xlate.rotator.phase_inc != before:
                break
            time.sleep(0.02)
        assert xlate.rotator.phase_inc != before, "retune did not reach the block"
    finally:
        running.stop_sync()
        cp.stop()
