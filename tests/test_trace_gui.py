"""Latency tracepoints + GUI serving tests."""

import json
import urllib.request

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSource, Copy, NullSink
from futuresdr_tpu.utils import LatencyProbeSource, LatencyProbeSink, latency_stats


def test_latency_probes():
    data = np.zeros(500_000, np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    probe_in = LatencyProbeSource(np.float32, granularity=65536)
    mid = Copy(np.float32)
    probe_out = LatencyProbeSink(np.float32)
    fg.connect(src, probe_in, mid, probe_out)
    Runtime().run(fg)
    stats = latency_stats(probe_out.records)
    assert stats["count"] >= 7
    assert stats["p99_us"] >= stats["p50_us"] >= 0
    assert stats["max_us"] < 5e6


def test_gui_served_from_ctrl_port():
    from aiohttp import web
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    from futuresdr_tpu.runtime.runtime import RuntimeHandle
    from futuresdr_tpu import AsyncScheduler

    async def my_route(request):
        return web.json_response({"custom": True})

    handle = RuntimeHandle(AsyncScheduler())
    cp = ControlPort(handle, bind="127.0.0.1:29417",
                     extra_routes=[("GET", "/my/app/", my_route)])
    cp.start()
    try:
        html = urllib.request.urlopen("http://127.0.0.1:29417/").read().decode()
        assert "waterfall" in html
        ids = json.load(urllib.request.urlopen("http://127.0.0.1:29417/api/fg/"))
        assert ids == []
        # custom-routes extension point (reference: examples/custom-routes)
        r = json.load(urllib.request.urlopen("http://127.0.0.1:29417/my/app/"))
        assert r == {"custom": True}
    finally:
        cp.stop()
