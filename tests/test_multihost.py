"""Real multi-process distributed backend: two OS processes, one global mesh.

The virtual 8-device mesh in conftest validates sharding semantics in one process;
this test goes one step further and runs the SAME sp_fir program across TWO jax
processes connected through jax's distributed runtime (Gloo over localhost — the CPU
stand-in for DCN between TPU hosts). Each process owns 4 virtual devices of a global
8-device mesh; the ppermute halo exchange in sp_fir crosses the process boundary.

Marked as an integration-style test: it spawns subprocesses and binds a localhost
port. Reference role: SURVEY §2.7 distributed-comm row (the reference has no
intra-runtime distribution at all; its story is socket blocks).
"""
import os
import socket
import subprocess
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: error markers of a HOST ENVIRONMENT that cannot run the multihost story
#: at all (vs a genuine regression in our code): a jaxlib built without the
#: cross-process CPU collectives backend (no Gloo) fails every multiprocess
#: computation with the first marker; a sandbox that cannot bind/reach the
#: coordinator port fails distributed init with the others. Such runs SKIP
#: instead of failing — tier-1 output stays clean where the env, not the
#: repo, is missing the capability.
_ENV_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "Failed to connect to distributed service",
    "DEADLINE_EXCEEDED: Barrier timed out",
    "UNAVAILABLE: failed to connect",
)

#: one verdict per process: the first detected env limitation short-circuits
#: later scenarios (each would spawn + time out on the same missing backend)
_env_unsupported: list = []


def _skip_if_env_unsupported(outs) -> None:
    for out in outs:
        for marker in _ENV_MARKERS:
            if marker in out:
                _env_unsupported.append(marker)
                pytest.skip(f"multihost env unsupported: {marker}")


def _run_two_workers(worker_src: str, tmp_path):
    """Spawn two worker processes on a fresh coordinator port, retry once on a
    port race, and assert both print their OK line (shared flake handling —
    a fix to the timeout/retry behavior applies to every scenario)."""
    wf = tmp_path / "worker.py"
    wf.write_text(worker_src)
    pypath = _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               JAX_PLATFORMS="", PYTHONPATH=pypath.rstrip(os.pathsep))

    def attempt():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen([sys.executable, str(wf), str(i), str(port)],
                                  stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                                  text=True, env=env)
                 for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=220)
                outs.append(out)
        except subprocess.TimeoutExpired:
            # a wedged first attempt (e.g. the port raced) must count as a
            # failed attempt eligible for the retry, not propagate straight
            # to failure
            for p in procs:
                p.kill()
            for p in procs:
                p.wait(timeout=10)
            return procs, ["<timeout after 220s>"] * len(procs)
        finally:
            for p in procs:
                p.kill()
        return procs, outs

    if _env_unsupported:
        pytest.skip(f"multihost env unsupported: {_env_unsupported[0]}")
    procs, outs = attempt()
    if any(p.returncode != 0 for p in procs):
        # an env that fundamentally lacks the capability must not burn a
        # retry (the second attempt fails identically, ~30 s later)
        _skip_if_env_unsupported(outs)
        # bind-then-close port probing races other processes on busy hosts;
        # one retry with a fresh port removes the flake
        procs, outs = attempt()
    _skip_if_env_unsupported(outs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} rc={p.returncode}\n{out[-2000:]}"
        assert f"proc {i} OK" in out, out[-2000:]


WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
from futuresdr_tpu.parallel import multihost
multihost.initialize(coordinator=f"127.0.0.1:{port}", num_processes=2, process_id=pid)
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from futuresdr_tpu.parallel.stream_sp import sp_fir

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
mesh = multihost.global_mesh(("sp",))

rng = np.random.default_rng(42)          # same seed -> same global input everywhere
taps = rng.standard_normal(31).astype(np.float32)
x = rng.standard_normal(8 * 1024).astype(np.float32)

sharding = NamedSharding(mesh, P("sp"))
# each process materializes ITS OWN shards of the global array
xg = jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])
fir = jax.jit(sp_fir(taps, mesh), out_shardings=sharding)
yg = fir(xg)

from jax.experimental import multihost_utils
y = np.asarray(multihost_utils.process_allgather(yg, tiled=True))
ref = np.convolve(np.concatenate([np.zeros(30, np.float32), x]), taps,
                  mode="valid").astype(np.float32)
err = np.abs(y - ref).max()
assert err < 1e-3, err
print(f"proc {pid} OK err={err:.2e}", flush=True)
"""


@pytest.mark.integration
def test_two_process_global_mesh_sp_fir(tmp_path):
    _run_two_workers(WORKER, tmp_path)


WORKER_TRAIN = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
from futuresdr_tpu.parallel import multihost
multihost.initialize(coordinator=f"127.0.0.1:{port}", num_processes=2, process_id=pid)
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P
from futuresdr_tpu.models import MCLDNN, init_params, make_train_step
from futuresdr_tpu.parallel.stream_sp import sp_fir_stream

assert jax.process_count() == 2

# ---- cross-process DATA-PARALLEL train step: the gradient all-reduce (psum
# over "dp") crosses the process boundary — the NCCL/MPI role of the
# reference's distributed story, expressed as an XLA collective over the
# jax distributed runtime
mesh = multihost.global_mesh(("dp",))
model = MCLDNN(n_classes=11, conv_features=8, lstm_features=16)
params = init_params(model, n=64)
params = jax.device_put(params, NamedSharding(mesh, P()))
opt = optax.adam(1e-3)
opt_state = jax.device_put(opt.init(params), NamedSharding(mesh, P()))
step = jax.jit(make_train_step(model, opt))

rng = np.random.default_rng(7)           # same seed -> same global batch
b = 2 * 8
iq = rng.standard_normal((b, 2, 64)).astype(np.float32)
labels = (np.arange(b) % 11).astype(np.int32)
iq_g = jax.make_array_from_callback(
    iq.shape, NamedSharding(mesh, P("dp")), lambda idx: iq[idx])
lab_g = jax.make_array_from_callback(
    labels.shape, NamedSharding(mesh, P("dp")), lambda idx: labels[idx])
params, opt_state, loss, acc = step(params, opt_state, iq_g, lab_g)
jax.block_until_ready(loss)
l = float(loss)
assert np.isfinite(l), l

# every process must see the SAME loss (the psum made the update global)
from jax.experimental import multihost_utils
ls = np.asarray(multihost_utils.process_allgather(jnp.asarray([l])))
assert np.allclose(ls, ls.reshape(-1)[0]), ls

# ---- cross-process STATEFUL stream: carry chained over frames, the halo
# ppermute crossing the process boundary on every frame
mesh_sp = multihost.global_mesh(("sp",))
taps = rng.standard_normal(31).astype(np.float32)
fn, init_c = sp_fir_stream(taps, mesh_sp)
jfn = jax.jit(fn, donate_argnums=(0,))
carry = init_c(np.float32)
F = 8 * 512
xs = rng.standard_normal(2 * F).astype(np.float32)
outs = []
for k in range(2):
    xk = xs[k * F:(k + 1) * F]
    xg = jax.make_array_from_callback(
        xk.shape, NamedSharding(mesh_sp, P("sp")), lambda idx, xk=xk: xk[idx])
    carry, yg = jfn(carry, xg)
    outs.append(np.asarray(multihost_utils.process_allgather(yg, tiled=True)))
y = np.concatenate(outs)
ref = np.convolve(np.concatenate([np.zeros(30, np.float32), xs]), taps,
                  mode="valid").astype(np.float32)
err = np.abs(y - ref).max()
assert err < 1e-3, err
print(f"proc {pid} OK loss={l:.4f} err={err:.2e}", flush=True)
"""


@pytest.mark.integration
def test_two_process_train_and_stateful_stream(tmp_path):
    """Cross-process dp-train (gradient psum over the process boundary; every
    process observes the identical loss) and a carry-chained stateful stream
    whose halo exchange crosses processes on every frame."""
    _run_two_workers(WORKER_TRAIN, tmp_path)
