"""Real multi-process distributed backend: two OS processes, one global mesh.

The virtual 8-device mesh in conftest validates sharding semantics in one process;
this test goes one step further and runs the SAME sp_fir program across TWO jax
processes connected through jax's distributed runtime (Gloo over localhost — the CPU
stand-in for DCN between TPU hosts). Each process owns 4 virtual devices of a global
8-device mesh; the ppermute halo exchange in sp_fir crosses the process boundary.

Marked as an integration-style test: it spawns subprocesses and binds a localhost
port. Reference role: SURVEY §2.7 distributed-comm row (the reference has no
intra-runtime distribution at all; its story is socket blocks).
"""
import os
import socket
import subprocess
import sys

import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
pid = int(sys.argv[1]); port = sys.argv[2]
from futuresdr_tpu.parallel import multihost
multihost.initialize(coordinator=f"127.0.0.1:{port}", num_processes=2, process_id=pid)
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from futuresdr_tpu.parallel.stream_sp import sp_fir

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 8, jax.device_count()
mesh = multihost.global_mesh(("sp",))

rng = np.random.default_rng(42)          # same seed -> same global input everywhere
taps = rng.standard_normal(31).astype(np.float32)
x = rng.standard_normal(8 * 1024).astype(np.float32)

sharding = NamedSharding(mesh, P("sp"))
# each process materializes ITS OWN shards of the global array
xg = jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])
fir = jax.jit(sp_fir(taps, mesh), out_shardings=sharding)
yg = fir(xg)

from jax.experimental import multihost_utils
y = np.asarray(multihost_utils.process_allgather(yg, tiled=True))
ref = np.convolve(np.concatenate([np.zeros(30, np.float32), x]), taps,
                  mode="valid").astype(np.float32)
err = np.abs(y - ref).max()
assert err < 1e-3, err
print(f"proc {pid} OK err={err:.2e}", flush=True)
"""


@pytest.mark.integration
def test_two_process_global_mesh_sp_fir(tmp_path):
    # bounded by the communicate(timeout=220) below — no pytest-timeout dependency
    wf = tmp_path / "worker.py"
    wf.write_text(WORKER)
    pypath = _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=4",
               JAX_PLATFORMS="", PYTHONPATH=pypath.rstrip(os.pathsep))

    def attempt():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [subprocess.Popen([sys.executable, str(wf), str(i), str(port)],
                                  stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                                  text=True, env=env)
                 for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=220)
                outs.append(out)
        except subprocess.TimeoutExpired:
            # a wedged first attempt (e.g. the port raced) must count as a failed
            # attempt eligible for the retry, not propagate straight to failure
            for p in procs:
                p.kill()
            for p in procs:
                p.wait(timeout=10)
            return procs, ["<timeout after 220s>"] * len(procs)
        finally:
            for p in procs:
                p.kill()
        return procs, outs

    procs, outs = attempt()
    if any(p.returncode != 0 for p in procs):
        # bind-then-close port probing races other processes on busy hosts; one
        # retry with a fresh port removes the flake
        procs, outs = attempt()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} rc={p.returncode}\n{out[-2000:]}"
        assert f"proc {i} OK" in out, out[-2000:]
