"""Property-style fuzz: random nested Pmts survive the JSON wire format, and the REST
call-by-index path resolves handlers positionally."""

import json

import numpy as np
import pytest

from futuresdr_tpu.types import Pmt, PmtKind


def _random_pmt(rng, depth=0):
    kinds = ["null", "bool", "int", "float", "str", "blob", "vecf32", "veccf32"]
    if depth < 2:
        kinds += ["vec", "map"]
    k = rng.choice(kinds)
    if k == "null":
        return Pmt.null()
    if k == "bool":
        return Pmt.bool_(bool(rng.integers(2)))
    if k == "int":
        return Pmt.isize(int(rng.integers(-2**40, 2**40)))
    if k == "float":
        return Pmt.f64(float(rng.standard_normal()))
    if k == "str":
        return Pmt.string("".join(chr(rng.integers(32, 127)) for _ in range(8)))
    if k == "blob":
        return Pmt.blob(bytes(rng.integers(0, 256, rng.integers(0, 32),
                                           dtype=np.uint8)))
    if k == "vecf32":
        return Pmt.vec_f32(rng.standard_normal(rng.integers(0, 16)).astype(np.float32))
    if k == "veccf32":
        n = rng.integers(0, 8)
        return Pmt.vec_cf32((rng.standard_normal(n)
                             + 1j * rng.standard_normal(n)).astype(np.complex64))
    if k == "vec":
        return Pmt(PmtKind.VEC_PMT, tuple(_random_pmt(rng, depth + 1)
                                          for _ in range(rng.integers(0, 4))))
    return Pmt(PmtKind.MAP_STR_PMT,
               {f"k{i}": _random_pmt(rng, depth + 1)
                for i in range(rng.integers(0, 4))})


def test_pmt_json_fuzz_roundtrip():
    rng = np.random.default_rng(0)
    for _ in range(300):
        p = _random_pmt(rng)
        wire = json.dumps(p.to_json())
        q = Pmt.from_json(json.loads(wire))
        assert q == p, f"roundtrip mismatch for {p!r} -> {q!r}"


def test_handler_call_by_index():
    """Handlers are addressable positionally (REST /call/{int}/ route semantics)."""
    import asyncio
    from futuresdr_tpu.blocks import Delay
    from futuresdr_tpu.runtime.work_io import WorkIo

    blk = Delay(np.float32, 0)

    async def go():
        io = WorkIo()
        r0 = await blk.call_handler(io, blk.meta, 0, Pmt.usize(5))   # new_value
        r_bad = await blk.call_handler(io, blk.meta, 99, Pmt.usize(5))
        return r0, r_bad

    r0, r_bad = asyncio.run(go())
    assert r0 == Pmt.ok()
    assert r_bad == Pmt.invalid_value()
