"""Closed-loop control through the message plane: a measurement block feeds a
controller that retunes an upstream source at runtime (the reference's AGC/sync-style
feedback loops live on the host exactly like this — SURVEY §7 'feedback stays on host')."""

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Kernel, Pmt, message_handler


class PeakFreqDetector(Kernel):
    """Measures the dominant frequency per FFT window and posts it."""

    def __init__(self, fft_size: int, sample_rate: float):
        super().__init__()
        self.n = fft_size
        self.fs = sample_rate
        self.input = self.add_stream_input("in", np.complex64, min_items=fft_size)
        self.add_message_output("freq")

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        if len(inp) >= self.n:
            spec = np.abs(np.fft.fft(inp[:self.n]))
            peak = float(np.fft.fftfreq(self.n, 1 / self.fs)[int(np.argmax(spec))])
            mio.post("freq", Pmt.f64(peak))
            self.input.consume(len(inp) - len(inp) % self.n)
        if self.input.finished():
            io.finished = True


class TuneController(Kernel):
    """Steers the source toward ``target`` from measured peaks; connected back to the
    source's ``freq`` handler — a feedback edge in the message plane."""

    def __init__(self, target: float, gain: float = 0.7):
        super().__init__()
        self.target = target
        self.gain = gain
        self.current = None
        self.history = []
        self.add_message_output("retune")

    @message_handler(name="measured")
    async def measured(self, io, mio, meta, p: Pmt) -> Pmt:
        if p.is_finished():
            io.finished = True
            return Pmt.ok()
        peak = p.to_float()
        self.history.append(peak)
        if self.current is None:
            self.current = peak
        err = self.target - peak
        if abs(err) > 1.0:
            self.current = self.current + self.gain * err
            mio.post("retune", Pmt.f64(self.current))
        return Pmt.ok()


def test_message_plane_feedback_converges():
    from futuresdr_tpu.blocks import SignalSource, Head

    fs = 100_000.0
    fg = Flowgraph()
    src = SignalSource("complex", 5_000.0, fs)        # starts far from the target
    head = Head(np.complex64, 3_000_000)
    det = PeakFreqDetector(1024, fs)
    ctl = TuneController(target=20_000.0)
    fg.connect(src, head, det)
    fg.connect_message(det, "freq", ctl, "measured")
    fg.connect_message(ctl, "retune", src, "freq")    # the feedback edge
    rt = Runtime()
    running = rt.start(fg)
    import time
    deadline = time.time() + 20
    while time.time() < deadline:
        time.sleep(0.2)
        if ctl.history and abs(ctl.history[-1] - 20_000.0) < 200:
            break
    running.stop_sync()
    assert ctl.history, "no measurements flowed"
    assert abs(ctl.history[-1] - 20_000.0) < 200, ctl.history[-5:]
