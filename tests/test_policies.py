"""Fault-tolerant runtime (ISSUE 6 tentpole): per-block failure policies
(restart / isolate / fail_fast), structured multi-error FlowgraphError,
``Runtime.run(timeout=)`` graceful deadlines, and the doctor's
``doctor_action: cancel`` escalation."""

import os
import time

import numpy as np
import pytest

from futuresdr_tpu import (BlockPolicy, Flowgraph, FlowgraphCancelled,
                           FlowgraphError, Kernel, Runtime)
from futuresdr_tpu.blocks import Copy, NullSource, VectorSink, VectorSource
from futuresdr_tpu.config import config
from futuresdr_tpu.telemetry import doctor as doc


class FlakyCopy(Kernel):
    """Copies input, raising on chosen work calls BEFORE touching any port —
    the same fault point as the ``work:<block>`` injection site, so a restart
    loses no consumed input."""

    def __init__(self, dtype, fail_on=(), always=False):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.fail_on = set(fail_on)
        self.always = always
        self.calls = 0
        self.init_calls = 0

    async def init(self, mio, meta):
        self.init_calls += 1

    async def work(self, io, mio, meta):
        self.calls += 1
        if self.always or self.calls in self.fail_on:
            raise RuntimeError(f"flaky boom #{self.calls}")
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True


class FlakyInit(Kernel):
    """Init fails ``fail_times`` times, then comes up and copies."""

    def __init__(self, dtype, fail_times: int):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.fail_times = fail_times
        self.init_calls = 0

    async def init(self, mio, meta):
        self.init_calls += 1
        if self.init_calls <= self.fail_times:
            raise RuntimeError(f"init boom #{self.init_calls}")

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True


class WedgeSink(Kernel):
    """Never consumes, never finishes — the canonical wedged flowgraph."""

    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)

    async def work(self, io, mio, meta):
        pass


def _restarts(block_name: str) -> float:
    from futuresdr_tpu.runtime.block import _RESTARTS
    return _RESTARTS.get(block=block_name)


# ---------------------------------------------------------------------------
# restart policy
# ---------------------------------------------------------------------------

def test_restart_recovers_bit_correct():
    """Acceptance: `restart` recovers to bit-correct output for a transient
    single-fault run — fresh init, billed restart counter, no graph teardown."""
    data = np.arange(200_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    fc = FlakyCopy(np.float32, fail_on=(2,))
    fc.policy = BlockPolicy(on_error="restart", max_restarts=3, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, fc, snk)
    before = _restarts(f"FlakyCopy_{fg.block_id(fc)}")
    Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    wk = fg.wrapped(fc)
    assert wk.restarts == 1
    assert fc.init_calls == 2             # original init + one restart re-init
    assert _restarts(wk.instance_name) - before == 1
    assert wk.metrics()["restarts"] == 1


def test_restart_exhausted_escalates_to_failure():
    fg = Flowgraph()
    src = VectorSource(np.zeros(10_000, np.float32))
    fc = FlakyCopy(np.float32, always=True)
    fc.policy = BlockPolicy(on_error="restart", max_restarts=2, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, fc, snk)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    wk = fg.wrapped(fc)
    assert wk.restarts == 2
    assert e.blocks == [wk.instance_name]
    actions = [d["action"] for d in e.policy_decisions]
    assert actions.count("restart") == 2
    assert actions[-1] == "restarts_exhausted"


def test_restart_covers_init_failures():
    data = np.arange(50_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    fi = FlakyInit(np.float32, fail_times=2)
    fi.policy = BlockPolicy(on_error="restart", max_restarts=3, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, fi, snk)
    Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    assert fi.init_calls == 3
    assert fg.wrapped(fi).restarts == 2


def test_policy_validation():
    with pytest.raises(ValueError):
        BlockPolicy(on_error="explode")
    assert BlockPolicy.from_config().on_error == "fail_fast"


# ---------------------------------------------------------------------------
# isolate policy
# ---------------------------------------------------------------------------

def test_isolate_lets_independent_branches_finish():
    """Acceptance: `isolate` retires the failed block (EOS downstream,
    upstream detach) while an independent branch completes bit-correct; the
    run still raises a structured FlowgraphError naming the faulted block."""
    data = np.arange(100_000, dtype=np.float32)
    fg = Flowgraph()
    src_a = VectorSource(data)
    cp = Copy(np.float32)
    snk_a = VectorSink(np.float32)
    fg.connect(src_a, cp, snk_a)
    src_b = VectorSource(np.zeros(50_000, np.float32))
    bad = FlakyCopy(np.float32, always=True)
    bad.policy = BlockPolicy(on_error="isolate")
    snk_b = VectorSink(np.float32)
    fg.connect(src_b, bad, snk_b)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    # the healthy branch finished ALL its data despite the peer failure
    np.testing.assert_array_equal(np.asarray(snk_a.items()), data)
    assert e.blocks == [fg.wrapped(bad).instance_name]
    assert [d["action"] for d in e.policy_decisions] == ["isolate"]
    assert isinstance(e.errors[0], RuntimeError)


def test_isolate_covers_init_failures():
    data = np.arange(60_000, dtype=np.float32)
    fg = Flowgraph()
    src_a = VectorSource(data)
    snk_a = VectorSink(np.float32)
    fg.connect(src_a, Copy(np.float32), snk_a)
    src_b = VectorSource(np.zeros(1000, np.float32))
    bad = FlakyInit(np.float32, fail_times=99)
    bad.policy = BlockPolicy(on_error="isolate")
    snk_b = VectorSink(np.float32)
    fg.connect(src_b, bad, snk_b)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk_a.items()), data)
    dec = ei.value.policy_decisions
    assert dec and dec[0]["action"] == "isolate" and dec[0]["phase"] == "init"


# ---------------------------------------------------------------------------
# fail_fast default + multi-error aggregation (satellite: errors[0]-only bug)
# ---------------------------------------------------------------------------

def test_fail_fast_default_structured_error():
    fg = Flowgraph()
    src = VectorSource(np.zeros(10_000, np.float32))
    bad = FlakyCopy(np.float32, always=True)     # no policy set anywhere
    snk = VectorSink(np.float32)
    fg.connect(src, bad, snk)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    assert str(e) == str(e.errors[0])            # single-error message contract
    assert e.blocks == [fg.wrapped(bad).instance_name]
    assert [d["action"] for d in e.policy_decisions] == ["fail_fast"]
    assert e.flight_record is None
    assert len(fg) == 3                          # blocks restored


def test_multi_block_failures_are_aggregated():
    """Satellite: FlowgraphError used to stringify only errors[0] — concurrent
    failures must all surface, with the count in the message."""
    fg = Flowgraph()
    src = NullSource(np.float32)
    bad1 = FlakyInit(np.float32, fail_times=99)
    bad2 = FlakyInit(np.float32, fail_times=99)
    snk = VectorSink(np.float32)
    fg.connect(src, bad1, bad2, snk)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    assert len(e.errors) == 2
    assert "2 blocks failed" in str(e)
    names = {fg.wrapped(bad1).instance_name, fg.wrapped(bad2).instance_name}
    assert set(e.blocks) == names
    for n in names:
        assert n in str(e)


# ---------------------------------------------------------------------------
# run deadlines (Runtime.run(timeout=) / run_timeout config)
# ---------------------------------------------------------------------------

def _wedged_fg():
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Copy(np.float32),
               WedgeSink(np.float32))
    return fg


def test_run_timeout_converts_hang_to_error(monkeypatch):
    monkeypatch.setattr(config(), "run_timeout_grace", 3.0)
    t0 = time.perf_counter()
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(_wedged_fg(), timeout=0.6)
    elapsed = time.perf_counter() - t0
    assert elapsed < 8.0, f"deadline did not bound the run ({elapsed:.1f}s)"
    e = ei.value
    assert any(isinstance(x, FlowgraphCancelled) for x in e.errors)
    assert any(d["action"] == "cancel" for d in e.policy_decisions)
    assert "deadline" in str(e)


def test_run_timeout_config_knob(monkeypatch):
    monkeypatch.setattr(config(), "run_timeout", 0.6)
    monkeypatch.setattr(config(), "run_timeout_grace", 3.0)
    with pytest.raises(FlowgraphError):
        Runtime().run(_wedged_fg())


def test_run_timeout_bounds_wedged_init():
    """The deadline is a TOTAL budget: a kernel.init wedged on a dead link
    must not hang run() any more than a wedged work() may."""
    import asyncio

    class WedgedInit(Kernel):
        def __init__(self, dtype):
            super().__init__()
            self.input = self.add_stream_input("in", dtype)

        async def init(self, mio, meta):
            await asyncio.sleep(3600)

    fg = Flowgraph()
    fg.connect(NullSource(np.float32), WedgedInit(np.float32))
    t0 = time.perf_counter()
    with pytest.raises(FlowgraphError, match="init barrier"):
        Runtime().run(fg, timeout=0.5)
    assert time.perf_counter() - t0 < 4.0
    e_ok = False
    try:
        Runtime().run(fg, timeout=0.5)
    except FlowgraphError as e:
        e_ok = any(isinstance(x, FlowgraphCancelled) for x in e.errors)
    except RuntimeError:
        e_ok = True        # second launch of a taken flowgraph also raises
    assert e_ok


def test_run_timeout_not_triggered_on_healthy_run():
    data = np.arange(10_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    snk = VectorSink(np.float32)
    fg.connect(src, Copy(np.float32), snk)
    Runtime().run(fg, timeout=30.0)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)


# ---------------------------------------------------------------------------
# doctor escalation (doctor_action: cancel) — acceptance
# ---------------------------------------------------------------------------

def test_doctor_cancel_converts_wedge_to_error(tmp_path, monkeypatch):
    """Acceptance: with `doctor_action: cancel` a wedged-sink flowgraph turns
    from an indefinite hang into a FlowgraphError with an attached flight
    record."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    monkeypatch.setattr(config(), "doctor_action", "cancel")
    monkeypatch.setattr(config(), "doctor_dir", str(tmp_path))
    d = doc.doctor()
    d.enable(interval=0.05, window=3)
    try:
        with pytest.raises(FlowgraphError) as ei:
            Runtime().run(_wedged_fg())
        e = ei.value
        assert any(isinstance(x, FlowgraphCancelled) for x in e.errors)
        assert "doctor watchdog: backpressured" in str(e)
        assert e.flight_record is not None and os.path.exists(e.flight_record)
    finally:
        d.disable()
        d.last_trip = None


def test_doctor_cancel_unwedges_init_barrier(monkeypatch):
    """A block wedged inside init() never answers the barrier — the doctor's
    cancel must still convert the run into a FlowgraphError (the supervisor
    abandons the barrier) instead of queueing the cancel forever."""
    import asyncio

    class WedgedInit(Kernel):
        def __init__(self, dtype):
            super().__init__()
            self.input = self.add_stream_input("in", dtype)

        async def init(self, mio, meta):
            await asyncio.sleep(3600)

    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    monkeypatch.setattr(config(), "doctor_action", "cancel")
    d = doc.doctor()
    d.enable(interval=0.05, window=3)
    try:
        fg = Flowgraph()
        fg.connect(NullSource(np.float32), WedgedInit(np.float32))
        t0 = time.perf_counter()
        with pytest.raises(FlowgraphError) as ei:
            Runtime().run(fg)
        assert time.perf_counter() - t0 < 15.0
        assert any(isinstance(x, FlowgraphCancelled) for x in ei.value.errors)
    finally:
        d.disable()
        d.last_trip = None


def test_supervisor_flight_record_carries_error_count():
    """Satellite: the supervisor's on-error flight record surfaces how many
    blocks failed and which policy decisions were taken."""
    d = doc.doctor()
    d.enable(interval=30.0, window=5)     # enabled → supervisor errors dump
    try:
        fg = Flowgraph()
        src = VectorSource(np.zeros(1000, np.float32))
        bad = FlakyCopy(np.float32, always=True)
        snk = VectorSink(np.float32)
        fg.connect(src, bad, snk)
        with pytest.raises(FlowgraphError):
            Runtime().run(fg)
        sup = (d.last_report or {}).get("supervisor")
        assert sup is not None
        assert sup["block_errors"] == 1
        assert sup["blocks"] == [fg.wrapped(bad).instance_name]
        assert sup["policy_decisions"][0]["action"] == "fail_fast"
    finally:
        d.disable()
        d.last_trip = None


# ---------------------------------------------------------------------------
# fusion × policy: isolate refuses, restart fuses (device-plane recovery)
# ---------------------------------------------------------------------------

def test_devchain_refuses_isolate_members():
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuStage
    frame = 4096
    n = 4 * frame
    tone = np.exp(2j * np.pi * 0.05 * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(tone)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    st = TpuStage([mag2_stage()], np.complex64)
    st.policy = BlockPolicy(on_error="isolate")
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, h2d, st, d2h, snk)
    done = Runtime().run(fg)
    m = done.wrapped(st).metrics()
    assert not m.get("fused_devchain"), \
        "an isolate-policy member must refuse device-graph fusion"
    np.testing.assert_allclose(
        np.asarray(snk.items()),
        (tone.real ** 2 + tone.imag ** 2).astype(np.float32), rtol=1e-5)


def test_devchain_fuses_restart_members():
    """Device-plane recovery acceptance: a restart-policy member NO LONGER
    declines fusion — the fused kernel carries the recovery contract
    (checkpoint/replay) itself."""
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuStage
    frame = 4096
    n = 4 * frame
    tone = np.exp(2j * np.pi * 0.05 * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(tone)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    st = TpuStage([mag2_stage()], np.complex64)
    st.policy = BlockPolicy(on_error="restart")
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, h2d, st, d2h, snk)
    done = Runtime().run(fg)
    m = done.wrapped(st).metrics()
    assert m.get("fused_devchain"), \
        "a restart-policy member should fuse (recovery AND fusion)"
    np.testing.assert_allclose(
        np.asarray(snk.items()),
        (tone.real ** 2 + tone.imag ** 2).astype(np.float32), rtol=1e-5)


def test_devchain_degrades_under_global_policy(monkeypatch):
    from futuresdr_tpu.runtime.devchain import devchain_enabled
    assert devchain_enabled()
    # a global restart default no longer degrades (fused kernels restart in
    # place from their composed-carry checkpoint); isolate still does
    monkeypatch.setattr(config(), "block_policy", "restart")
    assert devchain_enabled()
    monkeypatch.setattr(config(), "block_policy", "isolate")
    assert not devchain_enabled()


def test_devchain_degrades_under_work_faults():
    from futuresdr_tpu.runtime import faults
    from futuresdr_tpu.runtime.devchain import devchain_enabled
    faults.reset().arm("work:some_block", rate=0.5)
    try:
        assert not devchain_enabled()
    finally:
        faults.reset()
    assert devchain_enabled()


def test_devchain_dispatch_fault_gating():
    """A bare `dispatch` site keeps fusion on (the fused kernel polls it);
    a block-ADDRESSED dispatch:<name> site degrades — fused mode would
    silently un-arm it."""
    from futuresdr_tpu.runtime import faults
    from futuresdr_tpu.runtime.devchain import devchain_enabled
    faults.reset().arm("dispatch", rate=0.5)
    try:
        assert devchain_enabled()
    finally:
        faults.reset()
    faults.reset().arm("dispatch:TpuKernel_1", rate=0.5)
    try:
        assert not devchain_enabled()
    finally:
        faults.reset()
    assert devchain_enabled()


# ---------------------------------------------------------------------------
# injected work faults drive the same machinery end to end
# ---------------------------------------------------------------------------

def test_injected_work_fault_with_restart_policy(monkeypatch):
    """The chaos harness's core recovery path as a unit test: a seeded
    single-shot work fault + restart policy → bit-correct output."""
    from futuresdr_tpu.runtime import faults
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    data = np.arange(120_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cp = Copy(np.float32)
    cp.policy = BlockPolicy(on_error="restart", max_restarts=2, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, cp, snk)
    name = fg.wrapped(cp).instance_name
    faults.reset().arm(f"work:{name}", rate=1.0, max_faults=1, seed=3)
    try:
        Runtime().run(fg)
    finally:
        faults.reset()
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    assert fg.wrapped(cp).restarts == 1


# ---------------------------------------------------------------------------
# policy surface on the control plane (REST describe, ISSUE 7 satellite)
# ---------------------------------------------------------------------------

def test_describe_carries_policy_decisions_and_restarts(monkeypatch):
    """A run that RECOVERED via restart leaves its policy story readable:
    block descriptions carry the resolved policy + restart count and the
    flowgraph description the supervisor's decision log — the surface
    ``GET /api/fg/{fg}/`` serves (FlowgraphError only exists for failed
    runs; recovered runs report here)."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    data = np.arange(50_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cp = FlakyCopy(np.float32, fail_on=(1,))
    cp.policy = BlockPolicy(on_error="restart", max_restarts=3, backoff=0.0)
    snk = VectorSink(np.float32)
    fg.connect(src, cp, snk)
    Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    desc = fg.describe().to_json()
    blk = next(b for b in desc["blocks"] if b["type_name"] == "FlakyCopy")
    assert blk["policy"] == "restart"
    assert blk["restarts"] == 1
    others = [b for b in desc["blocks"] if b["type_name"] != "FlakyCopy"]
    assert all(b["policy"] == "fail_fast" and b["restarts"] == 0
               for b in others)
    acts = [d for d in desc["policy_decisions"] if d["action"] == "restart"]
    assert len(acts) == 1 and acts[0]["block"] == blk["instance_name"]
    assert acts[0]["attempt"] == 1 and acts[0]["phase"] == "work"


def test_describe_policy_decisions_empty_on_clean_run():
    fg = Flowgraph()
    src = VectorSource(np.arange(1000, dtype=np.float32))
    snk = VectorSink(np.float32)
    fg.connect(src, snk)
    Runtime().run(fg)
    desc = fg.describe().to_json()
    assert desc["policy_decisions"] == []
    assert all(b["restarts"] == 0 for b in desc["blocks"])


# ---------------------------------------------------------------------------
# device-plane recovery: carry checkpoint/replay (ISSUE 8 tentpole)
# ---------------------------------------------------------------------------

_FRAME = 1 << 11
_N = _FRAME * 21 + 517        # partial tail frame + partial K-batch at EOS


def _stateful_data():
    rng = np.random.default_rng(7)
    return (rng.standard_normal(_N) + 1j * rng.standard_normal(_N)) \
        .astype(np.complex64)


def _stateful_stages():
    """FIR history + rotator phase: both carries must survive a restart for
    bit-equality to hold — exactly the state a fresh re-init forfeits."""
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, rotator_stage
    taps = firdes.lowpass(0.2, 31).astype(np.float32)
    return [fir_stage(taps, fft_len=256), rotator_stage(0.05)]


def _run_stateful(data, fault=None, restart=False, k=1, ck=None,
                  max_faults=1):
    """One VectorSource → TpuKernel(FIR→rotator) → VectorSink run; ``fault``
    = (site, rate, seed) armed NON-transient (h2d/d2h included — the fatal
    class is what exercises restart, the transient class only the retry
    plane). Returns (output, restarts)."""
    from futuresdr_tpu.runtime import faults
    from futuresdr_tpu.tpu import TpuKernel
    fg = Flowgraph()
    tk = TpuKernel(_stateful_stages(), np.complex64, frame_size=_FRAME,
                   frames_in_flight=2, frames_per_dispatch=k,
                   checkpoint_every=ck)
    if restart:
        tk.policy = BlockPolicy(on_error="restart", max_restarts=4,
                                backoff=0.002)
    snk = VectorSink(np.complex64)
    fg.connect(VectorSource(data), tk, snk)
    name = fg.wrapped(tk).instance_name
    plan = faults.reset()
    if fault:
        site, rate, seed = fault
        plan.arm(f"{site}:{name}" if site == "dispatch" else site,
                 rate=rate, max_faults=max_faults, seed=seed,
                 transient=False)
    try:
        Runtime().run(fg, timeout=60.0)
    finally:
        faults.reset()
    return np.asarray(snk.items()), fg.wrapped(tk).restarts


def _replayed() -> float:
    from futuresdr_tpu.tpu.kernel_block import _REPLAYED
    return sum(v for _, v in _REPLAYED.samples())


def _forfeited() -> float:
    from futuresdr_tpu.tpu.kernel_block import _FORFEITED
    return sum(v for _, v in _FORFEITED.samples())


def test_stateful_restart_replay_dispatch_fault():
    """Acceptance: a carry-bearing device chain with `restart` policy and a
    seeded dispatch fault injected MID-STREAM produces output bit-identical
    to the fault-free run — the checkpoint restore + replay path, billed on
    fsdr_frames_replayed_total."""
    data = _stateful_data()
    exp, r0 = _run_stateful(data)
    assert r0 == 0
    before = _replayed()
    got, r = _run_stateful(data, fault=("dispatch", 0.12, 9), restart=True)
    assert r == 1
    assert _replayed() - before > 0
    np.testing.assert_array_equal(got, exp)


def test_stateful_restart_replay_transfer_faults():
    """Fatal (non-transient) h2d/d2h failures mid-stream recover bit-correct
    too — including a second fault landing DURING recovery (it consumes
    another restart attempt and the retried recovery completes)."""
    data = _stateful_data()
    exp, _ = _run_stateful(data)
    for site, rate, seed, mf in (("h2d", 0.08, 4, 1), ("h2d", 0.05, 11, 2),
                                 ("d2h", 0.03, 2, 2)):
        got, r = _run_stateful(data, fault=(site, rate, seed), restart=True,
                               max_faults=mf)
        assert r >= 1, (site, seed)
        np.testing.assert_array_equal(got, exp, err_msg=f"{site}@{seed}")


def test_stateful_restart_replay_megabatch():
    """Megabatch K=4 replay respects partial-batch semantics: the log
    retains the exact zero-padded scan payload, so the partial EOS group
    replays bit-identical (compared against the fault-free K=4 run — the
    scan program's own rounding differs from K=1's by contract)."""
    data = _stateful_data()
    exp, _ = _run_stateful(data, k=4)
    got, r = _run_stateful(data, fault=("dispatch", 0.3, 5), restart=True,
                           k=4)
    assert r == 1
    np.testing.assert_array_equal(got, exp)


def test_sparse_checkpoint_cadence_replays_bit_correct():
    """checkpoint_every=3: longer replay window, same bit-equality."""
    data = _stateful_data()
    exp, _ = _run_stateful(data)
    got, r = _run_stateful(data, fault=("dispatch", 0.12, 9), restart=True,
                           ck=3)
    assert r == 1
    np.testing.assert_array_equal(got, exp)


def test_checkpoint_off_forfeits_and_bills():
    """checkpoint_every=0: recover() declines, the fresh re-init forfeits the
    in-flight window (billed on fsdr_frames_forfeited_total) and the run
    completes with the gap — the pre-recovery behavior, now accounted."""
    data = _stateful_data()
    exp, _ = _run_stateful(data)
    before = _forfeited()
    got, r = _run_stateful(data, fault=("dispatch", 0.12, 9), restart=True,
                           ck=0)
    assert r == 1
    assert _forfeited() - before > 0
    assert len(got) < len(exp)            # frames really were dropped


def test_carry_fault_falls_back_to_previous_checkpoint():
    """Satellite: the `carry` site corrupts checkpoint candidates; the
    restore path's integrity check (tree/shape/dtype) must reject them and
    fall back — output stays bit-identical."""
    from futuresdr_tpu.runtime import faults
    from futuresdr_tpu.tpu import TpuKernel
    data = _stateful_data()
    exp, _ = _run_stateful(data)
    fg = Flowgraph()
    tk = TpuKernel(_stateful_stages(), np.complex64, frame_size=_FRAME,
                   frames_in_flight=2)
    tk.policy = BlockPolicy(on_error="restart", max_restarts=4,
                            backoff=0.002)
    snk = VectorSink(np.complex64)
    fg.connect(VectorSource(data), tk, snk)
    name = fg.wrapped(tk).instance_name
    plan = faults.reset()
    carry_inj = plan.arm("carry", rate=0.3, max_faults=2, seed=3)
    plan.arm(f"dispatch:{name}", rate=0.10, max_faults=1, seed=9,
             transient=False)
    try:
        Runtime().run(fg, timeout=60.0)
    finally:
        faults.reset()
    assert carry_inj.fired >= 1, "the carry corruption never fired"
    assert fg.wrapped(tk).restarts == 1
    np.testing.assert_array_equal(np.asarray(snk.items()), exp)


def test_fused_devchain_restart_replay():
    """Acceptance: the FUSED devchain path recovers bit-identically too —
    a restart-policy member fuses, the drive loop restarts the fused kernel
    from its composed-carry checkpoint, and the supervisor records the
    restart decision under the member's name."""
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, rotator_stage
    from futuresdr_tpu.runtime import faults
    from futuresdr_tpu.tpu import TpuKernel
    data = _stateful_data()
    taps = firdes.lowpass(0.2, 31).astype(np.float32)

    def run(fault):
        fg = Flowgraph()
        k1 = TpuKernel([fir_stage(taps, fft_len=256)], np.complex64,
                       frame_size=_FRAME, frames_in_flight=2)
        k2 = TpuKernel([rotator_stage(0.05)], np.complex64,
                       frame_size=_FRAME, frames_in_flight=2)
        k2.policy = BlockPolicy(on_error="restart", max_restarts=4,
                                backoff=0.002)
        snk = VectorSink(np.complex64)
        fg.connect(VectorSource(data), k1, k2, snk)
        plan = faults.reset()
        if fault:
            plan.arm("dispatch", rate=0.12, max_faults=1, seed=5,
                     transient=False)
        try:
            Runtime().run(fg, timeout=60.0)
        finally:
            faults.reset()
        wk2 = fg.wrapped(k2)
        return (np.asarray(snk.items()), wk2.restarts,
                bool(wk2.metrics().get("fused_devchain")),
                fg.describe().to_json())

    exp, _, fused0, _ = run(fault=False)
    assert fused0, "restart-policy member should fuse"
    got, restarts, fused1, desc = run(fault=True)
    assert fused1 and restarts == 1
    np.testing.assert_array_equal(got, exp)
    acts = [d for d in desc["policy_decisions"] if d["action"] == "restart"]
    assert len(acts) == 1 and acts[0]["phase"] == "work"


def test_fanout_fused_restart_replay():
    """Acceptance: a fused fan-out region (TpuFanoutKernel, FLAT composed
    carry) recovers bit-identically on EVERY branch."""
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, mag2_stage, rotator_stage
    from futuresdr_tpu.runtime import faults
    from futuresdr_tpu.tpu import TpuKernel
    taps = firdes.lowpass(0.2, 31).astype(np.float32)
    n = _FRAME * 13 + 300
    rng = np.random.default_rng(3)
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)) \
        .astype(np.complex64)

    def run(fault):
        fg = Flowgraph()
        prod = TpuKernel([fir_stage(taps, fft_len=256)], np.complex64,
                         frame_size=_FRAME, frames_in_flight=2)
        prod.policy = BlockPolicy(on_error="restart", max_restarts=4,
                                  backoff=0.002)
        b1 = TpuKernel([rotator_stage(0.05)], np.complex64,
                       frame_size=_FRAME, frames_in_flight=2)
        b2 = TpuKernel([mag2_stage()], np.complex64, frame_size=_FRAME,
                       frames_in_flight=2)
        s1, s2 = VectorSink(np.complex64), VectorSink(np.float32)
        src = VectorSource(data)
        fg.connect(src, prod)
        fg.connect(prod, b1, s1)
        fg.connect(prod, b2, s2)
        plan = faults.reset()
        if fault:
            plan.arm("dispatch", rate=0.15, max_faults=1, seed=6,
                     transient=False)
        try:
            Runtime().run(fg, timeout=60.0)
        finally:
            faults.reset()
        wp = fg.wrapped(prod)
        return (np.asarray(s1.items()), np.asarray(s2.items()),
                wp.restarts, bool(wp.metrics().get("fused_devchain")))

    e1, e2, _, fused0 = run(fault=False)
    assert fused0
    g1, g2, restarts, fused1 = run(fault=True)
    assert fused1 and restarts == 1
    np.testing.assert_array_equal(g1, e1)
    np.testing.assert_array_equal(g2, e2)


# ---------------------------------------------------------------------------
# isolate groups: retire a subgraph, not just one block (ISSUE 8 tentpole)
# ---------------------------------------------------------------------------

def test_isolate_group_retires_whole_subgraph():
    """Acceptance: one member of a named 3-block group dies → the whole
    group retires (topo-order EOS), the sibling branch finishes bit-correct,
    and policy_decisions carries ONE isolate_group verdict naming the group
    and every member."""
    from futuresdr_tpu.runtime import faults
    data = np.arange(100_000, dtype=np.float32)
    fg = Flowgraph()
    snk_a = VectorSink(np.float32)
    fg.connect(VectorSource(data), Copy(np.float32), snk_a)
    g1, g2, g3 = (Copy(np.float32) for _ in range(3))
    for g in (g1, g2, g3):
        g.policy = BlockPolicy(isolate_group="rx-branch")
    snk_b = VectorSink(np.float32)
    fg.connect(VectorSource(np.zeros(200_000, np.float32)), g1, g2, g3,
               snk_b)
    name = fg.wrapped(g2).instance_name
    members = [fg.wrapped(g).instance_name for g in (g1, g2, g3)]
    faults.reset().arm(f"work:{name}", rate=1.0, max_faults=1, seed=5)
    try:
        with pytest.raises(FlowgraphError) as ei:
            Runtime().run(fg, timeout=30.0)
    finally:
        faults.reset()
    e = ei.value
    np.testing.assert_array_equal(np.asarray(snk_a.items()), data)
    dec = [d for d in e.policy_decisions if d["action"] == "isolate_group"]
    assert len(dec) == 1, e.policy_decisions
    assert dec[0]["group"] == "rx-branch"
    assert dec[0]["block"] == name
    assert dec[0]["members"] == members   # topological order
    assert e.blocks == [name]
    # the description surface carries the group per block
    desc = fg.describe().to_json()
    grouped = [b["instance_name"] for b in desc["blocks"]
               if b.get("isolate_group") == "rx-branch"]
    assert sorted(grouped) == sorted(members)


def test_isolate_group_from_config(monkeypatch):
    """config `block_isolate_groups = "name=group;…"` assigns groups to
    blocks with no own policy — same retirement semantics."""
    from futuresdr_tpu.runtime import faults
    data = np.arange(60_000, dtype=np.float32)
    fg = Flowgraph()
    snk_a = VectorSink(np.float32)
    fg.connect(VectorSource(data), Copy(np.float32), snk_a)
    b1, b2 = Copy(np.float32), Copy(np.float32)
    snk_b = VectorSink(np.float32)
    fg.connect(VectorSource(np.zeros(80_000, np.float32)), b1, b2, snk_b)
    n1 = fg.wrapped(b1).instance_name
    n2 = fg.wrapped(b2).instance_name
    monkeypatch.setattr(config(), "block_isolate_groups",
                        f"{n1}=grp;{n2}=grp")
    faults.reset().arm(f"work:{n1}", rate=1.0, max_faults=1, seed=5)
    try:
        with pytest.raises(FlowgraphError) as ei:
            Runtime().run(fg, timeout=30.0)
    finally:
        faults.reset()
    np.testing.assert_array_equal(np.asarray(snk_a.items()), data)
    dec = [d for d in ei.value.policy_decisions
           if d["action"] == "isolate_group"]
    assert dec and dec[0]["group"] == "grp"
    assert set(dec[0]["members"]) == {n1, n2}


def test_isolate_group_covers_init_failures():
    """A group member failing INIT retires the whole group during the
    barrier; the sibling branch still finishes."""
    data = np.arange(50_000, dtype=np.float32)
    fg = Flowgraph()
    snk_a = VectorSink(np.float32)
    fg.connect(VectorSource(data), Copy(np.float32), snk_a)
    bad = FlakyInit(np.float32, fail_times=99)
    tail = Copy(np.float32)
    for b in (bad, tail):
        b.policy = BlockPolicy(isolate_group="dead-branch")
    snk_b = VectorSink(np.float32)
    fg.connect(VectorSource(np.zeros(1000, np.float32)), bad, tail, snk_b)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg, timeout=30.0)
    np.testing.assert_array_equal(np.asarray(snk_a.items()), data)
    dec = [d for d in ei.value.policy_decisions
           if d["action"] == "isolate_group"]
    assert len(dec) == 1 and dec[0]["group"] == "dead-branch"


def test_isolate_group_policy_validation():
    assert BlockPolicy(isolate_group="x").on_error == "isolate"
    assert BlockPolicy(on_error="isolate", isolate_group="x") \
        .isolate_group == "x"
    with pytest.raises(ValueError):
        BlockPolicy(on_error="restart", isolate_group="x")


# ---------------------------------------------------------------------------
# host staging arena × device-plane recovery (ISSUE 10 satellite): recycling
# under memory pressure must never alias a buffer fault recovery re-ships
# ---------------------------------------------------------------------------


def test_arena_recycling_under_recovery_bit_identical(monkeypatch):
    """Seeded h2d/d2h/dispatch faults while the staging arena recycles under
    MEMORY PRESSURE (a tiny pool cap keeps every released buffer in
    immediate circulation) with the codec worker pool armed: replayed output
    is bit-identical to the fault-free run — a buffer the replay log pins is
    never recycled into a newer frame (ops/arena.py pinning contract)."""
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import arena as arena_mod
    from futuresdr_tpu.ops import codec_pool as codec_mod
    c = config()
    monkeypatch.setattr(c, "host_arena", True)
    monkeypatch.setattr(c, "host_arena_mb", 1)
    monkeypatch.setattr(c, "host_codec_workers", 2)
    arena_mod.reset_arena()
    codec_mod.reset_pool()
    try:
        data = _stateful_data()
        exp, _ = _run_stateful(data)
        for site, rate, seed, mf in (("dispatch", 0.12, 9, 1),
                                     ("h2d", 0.08, 4, 1),
                                     ("d2h", 0.03, 2, 2)):
            got, r = _run_stateful(data, fault=(site, rate, seed),
                                   restart=True, max_faults=mf)
            assert r >= 1, (site, seed)
            np.testing.assert_array_equal(got, exp, err_msg=f"{site}@{seed}")
        # K=4 megabatch under the same pressure: the STACKED arena-backed
        # parts (incl. the zero-padded EOS group) replay bit-identical
        exp4, _ = _run_stateful(data, k=4)
        got4, r = _run_stateful(data, fault=("dispatch", 0.3, 5),
                                restart=True, k=4)
        assert r == 1
        np.testing.assert_array_equal(got4, exp4)
    finally:
        arena_mod.reset_arena()
        codec_mod.reset_pool()


def test_replay_bit_identical_with_hostpath_disabled(monkeypatch):
    """The pre-round-14 synchronous host path (arena off, inline codec) is a
    supported fallback config — its replay contract must keep holding now
    that the defaults moved on."""
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import arena as arena_mod
    from futuresdr_tpu.ops import codec_pool as codec_mod
    c = config()
    monkeypatch.setattr(c, "host_arena", False)
    monkeypatch.setattr(c, "host_codec_workers", 0)
    arena_mod.reset_arena()
    codec_mod.reset_pool()
    try:
        data = _stateful_data()
        exp, _ = _run_stateful(data)
        got, r = _run_stateful(data, fault=("dispatch", 0.12, 9),
                               restart=True)
        assert r == 1
        np.testing.assert_array_equal(got, exp)
    finally:
        arena_mod.reset_arena()
        codec_mod.reset_pool()
