"""Fault-tolerant runtime (ISSUE 6 tentpole): per-block failure policies
(restart / isolate / fail_fast), structured multi-error FlowgraphError,
``Runtime.run(timeout=)`` graceful deadlines, and the doctor's
``doctor_action: cancel`` escalation."""

import os
import time

import numpy as np
import pytest

from futuresdr_tpu import (BlockPolicy, Flowgraph, FlowgraphCancelled,
                           FlowgraphError, Kernel, Runtime)
from futuresdr_tpu.blocks import Copy, NullSource, VectorSink, VectorSource
from futuresdr_tpu.config import config
from futuresdr_tpu.telemetry import doctor as doc


class FlakyCopy(Kernel):
    """Copies input, raising on chosen work calls BEFORE touching any port —
    the same fault point as the ``work:<block>`` injection site, so a restart
    loses no consumed input."""

    def __init__(self, dtype, fail_on=(), always=False):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.fail_on = set(fail_on)
        self.always = always
        self.calls = 0
        self.init_calls = 0

    async def init(self, mio, meta):
        self.init_calls += 1

    async def work(self, io, mio, meta):
        self.calls += 1
        if self.always or self.calls in self.fail_on:
            raise RuntimeError(f"flaky boom #{self.calls}")
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True


class FlakyInit(Kernel):
    """Init fails ``fail_times`` times, then comes up and copies."""

    def __init__(self, dtype, fail_times: int):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.output = self.add_stream_output("out", dtype)
        self.fail_times = fail_times
        self.init_calls = 0

    async def init(self, mio, meta):
        self.init_calls += 1
        if self.init_calls <= self.fail_times:
            raise RuntimeError(f"init boom #{self.init_calls}")

    async def work(self, io, mio, meta):
        inp = self.input.slice()
        out = self.output.slice()
        n = min(len(inp), len(out))
        if n:
            out[:n] = inp[:n]
            self.input.consume(n)
            self.output.produce(n)
        if self.input.finished() and n == len(inp):
            io.finished = True


class WedgeSink(Kernel):
    """Never consumes, never finishes — the canonical wedged flowgraph."""

    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)

    async def work(self, io, mio, meta):
        pass


def _restarts(block_name: str) -> float:
    from futuresdr_tpu.runtime.block import _RESTARTS
    return _RESTARTS.get(block=block_name)


# ---------------------------------------------------------------------------
# restart policy
# ---------------------------------------------------------------------------

def test_restart_recovers_bit_correct():
    """Acceptance: `restart` recovers to bit-correct output for a transient
    single-fault run — fresh init, billed restart counter, no graph teardown."""
    data = np.arange(200_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    fc = FlakyCopy(np.float32, fail_on=(2,))
    fc.policy = BlockPolicy(on_error="restart", max_restarts=3, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, fc, snk)
    before = _restarts(f"FlakyCopy_{fg.block_id(fc)}")
    Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    wk = fg.wrapped(fc)
    assert wk.restarts == 1
    assert fc.init_calls == 2             # original init + one restart re-init
    assert _restarts(wk.instance_name) - before == 1
    assert wk.metrics()["restarts"] == 1


def test_restart_exhausted_escalates_to_failure():
    fg = Flowgraph()
    src = VectorSource(np.zeros(10_000, np.float32))
    fc = FlakyCopy(np.float32, always=True)
    fc.policy = BlockPolicy(on_error="restart", max_restarts=2, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, fc, snk)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    wk = fg.wrapped(fc)
    assert wk.restarts == 2
    assert e.blocks == [wk.instance_name]
    actions = [d["action"] for d in e.policy_decisions]
    assert actions.count("restart") == 2
    assert actions[-1] == "restarts_exhausted"


def test_restart_covers_init_failures():
    data = np.arange(50_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    fi = FlakyInit(np.float32, fail_times=2)
    fi.policy = BlockPolicy(on_error="restart", max_restarts=3, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, fi, snk)
    Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    assert fi.init_calls == 3
    assert fg.wrapped(fi).restarts == 2


def test_policy_validation():
    with pytest.raises(ValueError):
        BlockPolicy(on_error="explode")
    assert BlockPolicy.from_config().on_error == "fail_fast"


# ---------------------------------------------------------------------------
# isolate policy
# ---------------------------------------------------------------------------

def test_isolate_lets_independent_branches_finish():
    """Acceptance: `isolate` retires the failed block (EOS downstream,
    upstream detach) while an independent branch completes bit-correct; the
    run still raises a structured FlowgraphError naming the faulted block."""
    data = np.arange(100_000, dtype=np.float32)
    fg = Flowgraph()
    src_a = VectorSource(data)
    cp = Copy(np.float32)
    snk_a = VectorSink(np.float32)
    fg.connect(src_a, cp, snk_a)
    src_b = VectorSource(np.zeros(50_000, np.float32))
    bad = FlakyCopy(np.float32, always=True)
    bad.policy = BlockPolicy(on_error="isolate")
    snk_b = VectorSink(np.float32)
    fg.connect(src_b, bad, snk_b)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    # the healthy branch finished ALL its data despite the peer failure
    np.testing.assert_array_equal(np.asarray(snk_a.items()), data)
    assert e.blocks == [fg.wrapped(bad).instance_name]
    assert [d["action"] for d in e.policy_decisions] == ["isolate"]
    assert isinstance(e.errors[0], RuntimeError)


def test_isolate_covers_init_failures():
    data = np.arange(60_000, dtype=np.float32)
    fg = Flowgraph()
    src_a = VectorSource(data)
    snk_a = VectorSink(np.float32)
    fg.connect(src_a, Copy(np.float32), snk_a)
    src_b = VectorSource(np.zeros(1000, np.float32))
    bad = FlakyInit(np.float32, fail_times=99)
    bad.policy = BlockPolicy(on_error="isolate")
    snk_b = VectorSink(np.float32)
    fg.connect(src_b, bad, snk_b)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk_a.items()), data)
    dec = ei.value.policy_decisions
    assert dec and dec[0]["action"] == "isolate" and dec[0]["phase"] == "init"


# ---------------------------------------------------------------------------
# fail_fast default + multi-error aggregation (satellite: errors[0]-only bug)
# ---------------------------------------------------------------------------

def test_fail_fast_default_structured_error():
    fg = Flowgraph()
    src = VectorSource(np.zeros(10_000, np.float32))
    bad = FlakyCopy(np.float32, always=True)     # no policy set anywhere
    snk = VectorSink(np.float32)
    fg.connect(src, bad, snk)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    assert str(e) == str(e.errors[0])            # single-error message contract
    assert e.blocks == [fg.wrapped(bad).instance_name]
    assert [d["action"] for d in e.policy_decisions] == ["fail_fast"]
    assert e.flight_record is None
    assert len(fg) == 3                          # blocks restored


def test_multi_block_failures_are_aggregated():
    """Satellite: FlowgraphError used to stringify only errors[0] — concurrent
    failures must all surface, with the count in the message."""
    fg = Flowgraph()
    src = NullSource(np.float32)
    bad1 = FlakyInit(np.float32, fail_times=99)
    bad2 = FlakyInit(np.float32, fail_times=99)
    snk = VectorSink(np.float32)
    fg.connect(src, bad1, bad2, snk)
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(fg)
    e = ei.value
    assert len(e.errors) == 2
    assert "2 blocks failed" in str(e)
    names = {fg.wrapped(bad1).instance_name, fg.wrapped(bad2).instance_name}
    assert set(e.blocks) == names
    for n in names:
        assert n in str(e)


# ---------------------------------------------------------------------------
# run deadlines (Runtime.run(timeout=) / run_timeout config)
# ---------------------------------------------------------------------------

def _wedged_fg():
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), Copy(np.float32),
               WedgeSink(np.float32))
    return fg


def test_run_timeout_converts_hang_to_error(monkeypatch):
    monkeypatch.setattr(config(), "run_timeout_grace", 3.0)
    t0 = time.perf_counter()
    with pytest.raises(FlowgraphError) as ei:
        Runtime().run(_wedged_fg(), timeout=0.6)
    elapsed = time.perf_counter() - t0
    assert elapsed < 8.0, f"deadline did not bound the run ({elapsed:.1f}s)"
    e = ei.value
    assert any(isinstance(x, FlowgraphCancelled) for x in e.errors)
    assert any(d["action"] == "cancel" for d in e.policy_decisions)
    assert "deadline" in str(e)


def test_run_timeout_config_knob(monkeypatch):
    monkeypatch.setattr(config(), "run_timeout", 0.6)
    monkeypatch.setattr(config(), "run_timeout_grace", 3.0)
    with pytest.raises(FlowgraphError):
        Runtime().run(_wedged_fg())


def test_run_timeout_bounds_wedged_init():
    """The deadline is a TOTAL budget: a kernel.init wedged on a dead link
    must not hang run() any more than a wedged work() may."""
    import asyncio

    class WedgedInit(Kernel):
        def __init__(self, dtype):
            super().__init__()
            self.input = self.add_stream_input("in", dtype)

        async def init(self, mio, meta):
            await asyncio.sleep(3600)

    fg = Flowgraph()
    fg.connect(NullSource(np.float32), WedgedInit(np.float32))
    t0 = time.perf_counter()
    with pytest.raises(FlowgraphError, match="init barrier"):
        Runtime().run(fg, timeout=0.5)
    assert time.perf_counter() - t0 < 4.0
    e_ok = False
    try:
        Runtime().run(fg, timeout=0.5)
    except FlowgraphError as e:
        e_ok = any(isinstance(x, FlowgraphCancelled) for x in e.errors)
    except RuntimeError:
        e_ok = True        # second launch of a taken flowgraph also raises
    assert e_ok


def test_run_timeout_not_triggered_on_healthy_run():
    data = np.arange(10_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    snk = VectorSink(np.float32)
    fg.connect(src, Copy(np.float32), snk)
    Runtime().run(fg, timeout=30.0)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)


# ---------------------------------------------------------------------------
# doctor escalation (doctor_action: cancel) — acceptance
# ---------------------------------------------------------------------------

def test_doctor_cancel_converts_wedge_to_error(tmp_path, monkeypatch):
    """Acceptance: with `doctor_action: cancel` a wedged-sink flowgraph turns
    from an indefinite hang into a FlowgraphError with an attached flight
    record."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    monkeypatch.setattr(config(), "doctor_action", "cancel")
    monkeypatch.setattr(config(), "doctor_dir", str(tmp_path))
    d = doc.doctor()
    d.enable(interval=0.05, window=3)
    try:
        with pytest.raises(FlowgraphError) as ei:
            Runtime().run(_wedged_fg())
        e = ei.value
        assert any(isinstance(x, FlowgraphCancelled) for x in e.errors)
        assert "doctor watchdog: backpressured" in str(e)
        assert e.flight_record is not None and os.path.exists(e.flight_record)
    finally:
        d.disable()
        d.last_trip = None


def test_doctor_cancel_unwedges_init_barrier(monkeypatch):
    """A block wedged inside init() never answers the barrier — the doctor's
    cancel must still convert the run into a FlowgraphError (the supervisor
    abandons the barrier) instead of queueing the cancel forever."""
    import asyncio

    class WedgedInit(Kernel):
        def __init__(self, dtype):
            super().__init__()
            self.input = self.add_stream_input("in", dtype)

        async def init(self, mio, meta):
            await asyncio.sleep(3600)

    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    monkeypatch.setattr(config(), "doctor_action", "cancel")
    d = doc.doctor()
    d.enable(interval=0.05, window=3)
    try:
        fg = Flowgraph()
        fg.connect(NullSource(np.float32), WedgedInit(np.float32))
        t0 = time.perf_counter()
        with pytest.raises(FlowgraphError) as ei:
            Runtime().run(fg)
        assert time.perf_counter() - t0 < 15.0
        assert any(isinstance(x, FlowgraphCancelled) for x in ei.value.errors)
    finally:
        d.disable()
        d.last_trip = None


def test_supervisor_flight_record_carries_error_count():
    """Satellite: the supervisor's on-error flight record surfaces how many
    blocks failed and which policy decisions were taken."""
    d = doc.doctor()
    d.enable(interval=30.0, window=5)     # enabled → supervisor errors dump
    try:
        fg = Flowgraph()
        src = VectorSource(np.zeros(1000, np.float32))
        bad = FlakyCopy(np.float32, always=True)
        snk = VectorSink(np.float32)
        fg.connect(src, bad, snk)
        with pytest.raises(FlowgraphError):
            Runtime().run(fg)
        sup = (d.last_report or {}).get("supervisor")
        assert sup is not None
        assert sup["block_errors"] == 1
        assert sup["blocks"] == [fg.wrapped(bad).instance_name]
        assert sup["policy_decisions"][0]["action"] == "fail_fast"
    finally:
        d.disable()
        d.last_trip = None


# ---------------------------------------------------------------------------
# fusion degrades for policy-bearing members
# ---------------------------------------------------------------------------

def test_devchain_refuses_policy_members():
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuStage
    frame = 4096
    n = 4 * frame
    tone = np.exp(2j * np.pi * 0.05 * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(tone)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    st = TpuStage([mag2_stage()], np.complex64)
    st.policy = BlockPolicy(on_error="restart")
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, h2d, st, d2h, snk)
    done = Runtime().run(fg)
    m = done.wrapped(st).metrics()
    assert not m.get("fused_devchain"), \
        "a restart-policy member must refuse device-graph fusion"
    np.testing.assert_allclose(
        np.asarray(snk.items()),
        (tone.real ** 2 + tone.imag ** 2).astype(np.float32), rtol=1e-5)


def test_devchain_degrades_under_global_policy(monkeypatch):
    from futuresdr_tpu.runtime.devchain import devchain_enabled
    assert devchain_enabled()
    monkeypatch.setattr(config(), "block_policy", "restart")
    assert not devchain_enabled()


def test_devchain_degrades_under_work_faults():
    from futuresdr_tpu.runtime import faults
    from futuresdr_tpu.runtime.devchain import devchain_enabled
    faults.reset().arm("work:some_block", rate=0.5)
    try:
        assert not devchain_enabled()
    finally:
        faults.reset()
    assert devchain_enabled()


# ---------------------------------------------------------------------------
# injected work faults drive the same machinery end to end
# ---------------------------------------------------------------------------

def test_injected_work_fault_with_restart_policy(monkeypatch):
    """The chaos harness's core recovery path as a unit test: a seeded
    single-shot work fault + restart policy → bit-correct output."""
    from futuresdr_tpu.runtime import faults
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    data = np.arange(120_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cp = Copy(np.float32)
    cp.policy = BlockPolicy(on_error="restart", max_restarts=2, backoff=0.002)
    snk = VectorSink(np.float32)
    fg.connect(src, cp, snk)
    name = fg.wrapped(cp).instance_name
    faults.reset().arm(f"work:{name}", rate=1.0, max_faults=1, seed=3)
    try:
        Runtime().run(fg)
    finally:
        faults.reset()
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    assert fg.wrapped(cp).restarts == 1


# ---------------------------------------------------------------------------
# policy surface on the control plane (REST describe, ISSUE 7 satellite)
# ---------------------------------------------------------------------------

def test_describe_carries_policy_decisions_and_restarts(monkeypatch):
    """A run that RECOVERED via restart leaves its policy story readable:
    block descriptions carry the resolved policy + restart count and the
    flowgraph description the supervisor's decision log — the surface
    ``GET /api/fg/{fg}/`` serves (FlowgraphError only exists for failed
    runs; recovered runs report here)."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    data = np.arange(50_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cp = FlakyCopy(np.float32, fail_on=(1,))
    cp.policy = BlockPolicy(on_error="restart", max_restarts=3, backoff=0.0)
    snk = VectorSink(np.float32)
    fg.connect(src, cp, snk)
    Runtime().run(fg)
    np.testing.assert_array_equal(np.asarray(snk.items()), data)
    desc = fg.describe().to_json()
    blk = next(b for b in desc["blocks"] if b["type_name"] == "FlakyCopy")
    assert blk["policy"] == "restart"
    assert blk["restarts"] == 1
    others = [b for b in desc["blocks"] if b["type_name"] != "FlakyCopy"]
    assert all(b["policy"] == "fail_fast" and b["restarts"] == 0
               for b in others)
    acts = [d for d in desc["policy_decisions"] if d["action"] == "restart"]
    assert len(acts) == 1 and acts[0]["block"] == blk["instance_name"]
    assert acts[0]["attempt"] == 1 and acts[0]["phase"] == "work"


def test_describe_policy_decisions_empty_on_clean_run():
    fg = Flowgraph()
    src = VectorSource(np.arange(1000, dtype=np.float32))
    snk = VectorSink(np.float32)
    fg.connect(src, snk)
    Runtime().run(fg)
    desc = fg.describe().to_json()
    assert desc["policy_decisions"] == []
    assert all(b["restarts"] == 0 for b in desc["blocks"])
