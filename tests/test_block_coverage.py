"""Flowgraph-level coverage for the remaining block-library entries (reference:
per-block tests `tests/{apply,combine,filter,split}.rs` etc.)."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, Pmt, Mocker
from futuresdr_tpu.blocks import (VectorSource, VectorSink, Filter, Split, Selector,
                                  Throttle, ApplyNM, ApplyIntoIter, MovingAvg,
                                  StreamDuplicator, StreamDeinterleaver, Delay,
                                  FiniteSource, Source, Sink, Head, TagDebug, Combine)
from futuresdr_tpu.runtime.tag import Tag


def test_filter_block():
    data = np.arange(10_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    flt = Filter(lambda x: x % 2 == 0, np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, flt, snk)
    Runtime().run(fg)
    np.testing.assert_array_equal(snk.items(), data[::2])


def test_split_block():
    data = (np.arange(5000) + 1j * np.arange(5000)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(data)
    sp = Split(lambda x: (x.real, x.imag), np.complex64, np.float32, np.float32)
    s0, s1 = VectorSink(np.float32), VectorSink(np.float32)
    fg.connect_stream(src, "out", sp, "in")
    fg.connect_stream(sp, "out0", s0, "in")
    fg.connect_stream(sp, "out1", s1, "in")
    Runtime().run(fg)
    np.testing.assert_allclose(s0.items(), data.real)
    np.testing.assert_allclose(s1.items(), data.imag)


def test_selector_routing_and_switch():
    import time
    from futuresdr_tpu.blocks import SignalSource, NullSink

    fg = Flowgraph()
    sa = SignalSource("cos", 0.0, 1e6, amplitude=0.0)       # constant 0s, endless
    sb = SignalSource("cos", 0.0, 1e6, amplitude=1.0)       # constant 1s, endless
    sel = Selector(np.float32, 2, 1, drop_policy="drop_all")
    snk = VectorSink(np.float32)
    fg.connect_stream(sa, "out", sel, "in0")
    fg.connect_stream(sb, "out", sel, "in1")
    fg.connect_stream(sel, "out0", snk, "in")
    rt = Runtime()
    running = rt.start(fg)

    def poll_for(value, deadline=10.0):
        # poll instead of a fixed sleep: on a loaded box a 50 ms nap is flake-bait
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < deadline:
            if value in snk.items():
                return True
            time.sleep(0.01)
        return False

    assert poll_for(0.0), "no samples from input 0 before the switch"
    r = rt.scheduler.run_coro_sync(running.handle.call(sel, "input_index", Pmt.usize(1)))
    assert r == Pmt.usize(1)
    assert poll_for(1.0), "no samples from input 1 after the switch"
    running.stop_sync()
    got = snk.items()
    assert 0.0 in got and 1.0 in got        # routed input switched mid-stream


def test_throttle_rate():
    import time
    data = np.zeros(30_000, np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    thr = Throttle(np.float32, rate=100_000.0)
    snk = VectorSink(np.float32)
    fg.connect(src, thr, snk)
    t0 = time.perf_counter()
    Runtime().run(fg)
    dt = time.perf_counter() - t0
    assert len(snk.items()) == 30_000
    assert dt >= 0.25                      # 30k at 100k/s ≥ 0.3s (scheduling slack)


def test_apply_nm_block():
    data = np.arange(12_000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    nm = ApplyNM(lambda x: x.reshape(-1, 3).sum(axis=1), 3, 1, np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, nm, snk)
    Runtime().run(fg)
    np.testing.assert_allclose(snk.items(), data.reshape(-1, 3).sum(axis=1))


def test_apply_into_iter_block():
    data = np.arange(1000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    rep = ApplyIntoIter(lambda x: np.repeat(x, 3), np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, rep, snk)
    Runtime().run(fg)
    np.testing.assert_array_equal(snk.items(), np.repeat(data, 3))


def test_moving_avg_block():
    frame = 64
    data = np.tile(np.ones(frame, np.float32), 10)
    fg = Flowgraph()
    src = VectorSource(data)
    avg = MovingAvg(frame, width=3, decay=0.5)
    snk = VectorSink(np.float32)
    fg.connect(src, avg, snk)
    Runtime().run(fg)
    out = snk.items()
    assert len(out) >= frame
    assert np.all(out[-frame:] <= 1.0 + 1e-6)


def test_stream_duplicator_and_deinterleaver():
    data = np.arange(6000, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    dup = StreamDuplicator(np.float32, 2)
    deint = StreamDeinterleaver(np.float32, 2)
    s_dup = VectorSink(np.float32)
    s_even, s_odd = VectorSink(np.float32), VectorSink(np.float32)
    fg.connect_stream(src, "out", dup, "in")
    fg.connect_stream(dup, "out0", s_dup, "in")
    fg.connect_stream(dup, "out1", deint, "in")
    fg.connect_stream(deint, "out0", s_even, "in")
    fg.connect_stream(deint, "out1", s_odd, "in")
    Runtime().run(fg)
    np.testing.assert_array_equal(s_dup.items(), data)
    np.testing.assert_array_equal(s_even.items(), data[0::2])
    np.testing.assert_array_equal(s_odd.items(), data[1::2])


def test_delay_in_flowgraph_with_message():
    data = np.arange(1, 1001, dtype=np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    dl = Delay(np.float32, 10)
    snk = VectorSink(np.float32)
    fg.connect(src, dl, snk)
    Runtime().run(fg)
    out = snk.items()
    np.testing.assert_array_equal(out[:10], np.zeros(10))
    np.testing.assert_array_equal(out[10:], data)


def test_source_sink_closures():
    state = {"n": 0}

    def gen(n):
        start = state["n"]
        state["n"] += n
        return np.arange(start, start + n, dtype=np.float32)

    collected = []
    fg = Flowgraph()
    src = Source(gen, np.float32)
    head = Head(np.float32, 5000)
    snk = Sink(lambda chunk: collected.append(chunk.copy()), np.float32)
    fg.connect(src, head, snk)
    Runtime().run(fg)
    got = np.concatenate(collected)
    np.testing.assert_array_equal(got, np.arange(5000, dtype=np.float32))


def test_finite_source():
    emitted = {"count": 0}

    def gen(n):
        if emitted["count"] >= 1000:
            return None
        k = min(n, 1000 - emitted["count"])
        out = np.full(k, 7.0, np.float32)
        emitted["count"] += k
        return out

    fg = Flowgraph()
    src = FiniteSource(gen, np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, snk)
    Runtime().run(fg)
    assert len(snk.items()) == 1000


def test_tags_remap_through_decimation():
    """Tag indices scale by the rate change through a decimating FIR (SURVEY hard part)."""
    from futuresdr_tpu import Kernel
    from futuresdr_tpu.blocks import Fir, TagDebug
    from futuresdr_tpu.dsp import firdes

    class TaggingSource(Kernel):
        def __init__(self):
            super().__init__()
            self.output = self.add_stream_output("out", np.complex64)
            self._sent = False

        async def work(self, io, mio, meta):
            if self._sent:
                io.finished = True
                return
            out = self.output.slice()
            n = min(4000, len(out))
            out[:n] = 0
            self.output.add_tag(400, Tag.named_usize("marker", 1))
            self.output.add_tag(2000, Tag.named_usize("marker", 2))
            self.output.produce(n)
            self._sent = True
            io.call_again = True

    fg = Flowgraph()
    src = TaggingSource()
    fir = Fir(firdes.lowpass(0.1, 32), np.complex64, decim=4)
    dbg = TagDebug(np.complex64, "decim")
    snk = VectorSink(np.complex64)
    fg.connect(src, fir, dbg, snk)
    Runtime().run(fg)
    idx = sorted(t.index for t in dbg.seen)
    assert len(idx) == 2
    assert abs(idx[0] - 100) <= 2 and abs(idx[1] - 500) <= 2


def test_tags_flow_through_chain():
    from futuresdr_tpu import Kernel

    class TaggingSource(Kernel):
        def __init__(self):
            super().__init__()
            self.output = self.add_stream_output("out", np.float32)
            self._sent = False

        async def work(self, io, mio, meta):
            if self._sent:
                io.finished = True
                return
            out = self.output.slice()
            n = min(1000, len(out))
            out[:n] = 0
            self.output.add_tag(5, Tag.named_usize("burst_start", 42))
            self.output.add_tag(500, Tag.string("mid"))
            self.output.produce(n)
            self._sent = True
            io.call_again = True

    fg = Flowgraph()
    src = TaggingSource()
    dbg = TagDebug(np.float32, "t")
    snk = VectorSink(np.float32)
    fg.connect(src, dbg, snk)
    Runtime().run(fg)
    assert len(dbg.seen) == 2
    assert dbg.seen[0].index == 5 and dbg.seen[0].tag.value == 42
    assert dbg.seen[1].tag.value == "mid"
