"""ClockRecoveryMm block + STA equalizer tests."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSource, VectorSink, ClockRecoveryMm


def test_mm_clock_recovery_extracts_symbols():
    rng = np.random.default_rng(0)
    sps = 8
    bits = rng.integers(0, 2, 500) * 2.0 - 1.0
    # rectangular pulses with a fractional timing offset
    wave = np.repeat(bits, sps).astype(np.float32)
    wave = np.concatenate([np.zeros(3, np.float32), wave])  # timing offset
    fg = Flowgraph()
    src = VectorSource(wave)
    mm = ClockRecoveryMm(omega=sps)
    snk = VectorSink(np.float32)
    fg.connect(src, mm, snk)
    Runtime().run(fg)
    got = np.sign(snk.items())
    assert len(got) > 400
    # recovered symbol decisions must match the bit sequence at some alignment
    best = 0
    for lag in range(4):
        g = got[lag:lag + 450]
        b = bits[:len(g)]
        best = max(best, float(np.mean(g == b)))
    assert best > 0.95, best


def test_sta_equalizer_tracks_drift():
    from futuresdr_tpu.models.wlan import encode_frame, ofdm, coding
    from futuresdr_tpu.models.wlan.phy import _parse_signal

    psdu = b"sta equalizer test payload!!" * 2
    frame = encode_frame(psdu, "qpsk_1_2")
    # slow channel drift over the frame: small growing phase slope
    drift = np.exp(1j * 2e-5 * np.arange(len(frame)) ** 1.0)
    rx = (frame * drift).astype(np.complex64)
    H = ofdm.estimate_channel(rx, 192)
    n_sym = -(-(16 + 8 * len(psdu) + 6) // 96)     # data symbols at qpsk_1_2
    spec = ofdm.ofdm_demodulate_symbols(rx[192 + 128 + 80:], n_sym)
    eq_ls = ofdm.equalize(spec, H, symbol_offset=1, algorithm="ls")
    eq_sta = ofdm.equalize(spec, H, symbol_offset=1, algorithm="sta")
    # both algorithms produce constellation points near QPSK; sta at least as tight
    def evm(eq):
        pts = eq.reshape(-1)
        ideal = (np.sign(pts.real) + 1j * np.sign(pts.imag)) / np.sqrt(2)
        return float(np.mean(np.abs(pts - ideal) ** 2))
    assert evm(eq_sta) <= evm(eq_ls) * 1.1
