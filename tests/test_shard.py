"""Mesh-sharded device plane (futuresdr_tpu/shard) — docs/parallel.md.

The heavy scenarios run in a FRESH subprocess pinned to the virtual
8-device CPU mesh (the ``__graft_entry__.dryrun_multichip`` pattern: the
``--xla_force_host_platform_device_count`` flag only acts BEFORE jax
initializes, so a worker process guarantees the mesh regardless of how
this test process was launched — an ``FSDR_TEST_TPU`` run keeps working).
Each worker covers one acceptance area end to end:

* data-shard bit-equality vs the D=1 program at matched K (+ the wired
  form, + zero cross-shard collectives in the compiled HLO);
* whole-mesh checkpoint + per-shard replay-log recovery (bit-identical
  after an injected dispatch fault; corrupt newest candidate evicted in
  favor of the previous one);
* serve slot-axis sharding (sharded engine bit-equal to unsharded,
  evict/readmit round trip, (device, lane) addressing, bucket growth
  across the shard-divisibility boundary).

Plan refusals, the mesh fixes, the autotune device axis and the
doctor/profile surfaces are cheap and run in-process.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def shard_worker(tmp_path):
    """Run a worker script in a fresh process on the 8-device virtual CPU
    mesh; asserts it prints OK and returns its output."""

    def run(src: str, timeout: float = 240.0) -> str:
        wf = tmp_path / "worker.py"
        wf.write_text(src)
        pypath = _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
        env = dict(os.environ,
                   XLA_FLAGS="--xla_force_host_platform_device_count=8",
                   JAX_PLATFORMS="cpu",
                   FUTURESDR_TPU_AUTOTUNE_CACHE_DIR="off",
                   PYTHONPATH=pypath.rstrip(os.pathsep))
        r = subprocess.run([sys.executable, str(wf)], env=env,
                           capture_output=True, text=True, timeout=timeout)
        assert r.returncode == 0, \
            f"worker rc={r.returncode}\n{r.stdout[-3000:]}\n" \
            f"{r.stderr[-3000:]}"
        assert "WORKER OK" in r.stdout, r.stdout[-3000:]
        return r.stdout

    return run


_PRELUDE = r"""
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from futuresdr_tpu.ops.stages import Pipeline, fir_stage, rotator_stage, \
    mag2_stage
from futuresdr_tpu.shard import (ShardRunner, ShardedProgram,
                                 collective_ops, plan_shard, shard_pipeline)
assert len(jax.devices()) == 8, jax.devices()
PIPE = Pipeline([fir_stage(np.hanning(33).astype(np.float32)),
                 rotator_stage(0.05), mag2_stage()], np.complex64)
D, K, F = 8, 2, 8192
RNG = np.random.default_rng(0)

def cplx(shape):
    return (RNG.standard_normal(shape)
            + 1j * RNG.standard_normal(shape)).astype(np.complex64)
"""


def test_data_shard_bit_equality_and_zero_collectives(shard_worker):
    """The tentpole pin: every shard's output (and carry) of the D=8
    data-sharded program is bit-identical to the D=1 program fed that row
    at MATCHED K (the repo's megabatch scan-rounding convention), at K=1
    and K=2, raw and wired — and the compiled HLO carries zero cross-shard
    collectives."""
    shard_worker(_PRELUDE + r"""
prog = shard_pipeline(PIPE, mode="data", n_devices=D, name="eq")
assert isinstance(prog, ShardedProgram)

# zero cross-shard collectives, raw + wired, K=1 + K=2
for k in (1, 2):
    assert collective_ops(prog.compiled_text(F, k)) == [], k
assert collective_ops(prog.compiled_text(F, 2, wire="sc16")) == []

# K=2: rows + carries bit-equal vs the D=1 scan program
fn, carries = prog.compile(F, K)
x = cplx((D, K, F))
nc, y = fn(carries, prog.place(x))
got = np.asarray(y)
inner = PIPE.fn()
scan1 = jax.jit(lambda c, xs: jax.lax.scan(
    lambda cc, xk: inner(cc, xk), c, xs))
nc_leaves = jax.tree_util.tree_flatten(nc)[0]
for d in range(D):
    c1, y1 = scan1(PIPE.init_carry(), jnp.asarray(x[d]))
    assert np.array_equal(np.asarray(y1), got[d]), d
    for got_leaf, ref_leaf in zip(nc_leaves,
                                  jax.tree_util.tree_flatten(c1)[0]):
        assert np.array_equal(np.asarray(got_leaf[d]),
                              np.asarray(ref_leaf)), d

# K=1: vs the plain jitted per-frame program
fn1, car1 = prog.compile(F, 1)
x1 = cplx((D, F))
_, y1v = fn1(car1, prog.place(x1))
jin = jax.jit(inner)
for d in range(D):
    _, yr = jin(PIPE.init_carry(), jnp.asarray(x1[d]))
    assert np.array_equal(np.asarray(yr), np.asarray(y1v)[d]), d

# the wired form round-trips through the codec with per-device stacks
from futuresdr_tpu.ops.wire import get_wire
w = get_wire("sc16")
fnw, cw = prog.compile(F, K, wire="sc16")
enc = [[w.encode_host(x[d, k]) for k in range(K)] for d in range(D)]
parts = tuple(np.stack([np.stack([np.asarray(enc[d][k][j])
                                  for k in range(K)]) for d in range(D)])
              for j in range(len(enc[0][0])))
ncw, yw = fnw(cw, *[prog.place(p) for p in parts])
outs = yw if isinstance(yw, tuple) else (yw,)
assert np.asarray(outs[0]).shape[:2] == (D, K)

# shard=off / D=1 return the SAME program object (bit-identity by
# construction)
assert shard_pipeline(PIPE, mode="off") is PIPE
assert shard_pipeline(PIPE, mode="data", n_devices=1) is PIPE
print("WORKER OK")
""")


def test_shard_runner_checkpoint_replay_recovery(shard_worker):
    """Whole-mesh snapshot + per-shard replay logs: an injected dispatch
    fault mid-stream recovers bit-identically; a corrupted NEWEST snapshot
    candidate is evicted in favor of the previous one; the per-shard
    dispatch count never multiplies with D."""
    shard_worker(_PRELUDE + r"""
from futuresdr_tpu.runtime import faults as _faults

def make_runner(name, checkpoint_every=1):
    prog = ShardedProgram(PIPE, plan_shard(PIPE, mode="data", n_devices=D),
                          name=name)
    return ShardRunner(prog, F, k=K, checkpoint_every=checkpoint_every,
                       name=name)

groups = [cplx((D, K, F)) for _ in range(5)]
ref_runner = make_runner("ref")
ref = [ref_runner.run_group(g) for g in groups]
# ONE dispatch per group, never x D (the multichip smoke's pin, unit here)
assert ref_runner.dispatches == len(groups)

# injected dispatch fault -> recover -> bit-identical
hit = make_runner("hit", checkpoint_every=2)
_faults.arm("dispatch:hit", rate=0.5, seed=5, max_faults=1)
out, recoveries = [], 0
try:
    for g in groups:
        try:
            out.append(hit.run_group(g))
        except _faults.InjectedFault:
            hit.recover()
            recoveries += 1
            out.append(hit.run_group(g))
finally:
    _faults.disarm()
assert recoveries == 1, recoveries
for a, b in zip(ref, out):
    np.testing.assert_array_equal(a, b)

# corrupt the NEWEST checkpoint candidate: recover() evicts it, restores
# the previous one, replays the per-shard window, and the next group is
# still bit-identical
c2 = make_runner("c2")
for g in groups[:4]:
    c2.run_group(g)
seq, leaves, treedef = c2._ckpts[-1]
bad = [np.asarray(l)[..., :1] if np.ndim(l) else l for l in leaves]
c2._ckpts[-1] = (seq, bad, treedef)
replayed = c2.recover()
assert replayed >= 1, replayed
np.testing.assert_array_equal(c2.run_group(groups[4]), ref[4])

# the replay log prunes to the previous committed snapshot: depth bounded
depth = max(len(q) for q in c2._rlog.values())
assert depth <= 2 + c2.checkpoint_every, depth

# degenerate: the SOLE committed snapshot is corrupt -> fresh-init + FULL
# replay (the log must still hold the whole window) stays bit-identical
c3 = make_runner("c3")
c3.run_group(groups[0])
seq, leaves, treedef = c3._ckpts[-1]
assert len(c3._ckpts) == 1
c3._ckpts[-1] = (seq, [np.asarray(l)[..., :1] if np.ndim(l) else l
                       for l in leaves], treedef)
assert c3.recover() == 1
np.testing.assert_array_equal(c3.run_group(groups[1]), ref[1])
print("WORKER OK")
""")


def test_serve_slot_axis_sharding(shard_worker):
    """Slot-axis sharding (sessions x devices): the sharded engine's
    per-session streams are bit-identical to the unsharded engine's,
    evict/readmit round-trips on the sharded carries, sessions address a
    (device, lane) pair, and bucket growth crosses the shard-divisibility
    boundary cleanly."""
    shard_worker(r"""
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from futuresdr_tpu.ops.stages import Pipeline, rotator_stage, mag2_stage
from futuresdr_tpu.serve.engine import ServeEngine
assert len(jax.devices()) == 8
PIPE = Pipeline([rotator_stage(0.05), mag2_stage()], np.complex64)

def run(shard):
    eng = ServeEngine(PIPE, frame_size=1024, app=f"sh{shard}",
                      buckets=(8, 16), shard_devices=shard)
    sids = [eng.admit(tenant="t", sid=f"s{i}").sid for i in range(6)]
    frames = {}
    for s in sids:
        r = np.random.default_rng(abs(hash(s)) % 2**31)
        frames[s] = [(r.standard_normal(1024)
                      + 1j * r.standard_normal(1024)).astype(np.complex64)
                     for _ in range(4)]
    outs = {s: [] for s in sids}
    for step in range(4):
        for s in sids:
            eng.submit(s, frames[s][step])
        eng.step()
        for s in sids:
            outs[s].extend(eng.results(s))
    # evict -> readmit round trip (the checkpoint leaf contract) on the
    # SHARDED stacked carries, then one more frame to prove the lane lives
    eng.evict(sids[0])
    eng.readmit(sids[0])
    view = eng.session_view(sids[0])
    eng.submit(sids[0], frames[sids[0]][0])
    eng.step()
    outs[sids[0]].extend(eng.results(sids[0]))
    eng.shutdown()
    return outs, view

o8, v8 = run(8)
o0, v0 = run(0)
for s in o0:
    assert len(o0[s]) == len(o8[s]), s
    for a, b in zip(o0[s], o8[s]):
        assert np.array_equal(a, b), s
# (device, lane) addressing on the sharded engine; absent unsharded
assert v8.get("device") is not None and v8.get("device_lane") is not None
assert v0.get("device") is None

# growth across the shard-divisibility boundary: bucket 6 (unsharded,
# 6 % 8 != 0) grows into bucket 16 (sharded, 2 lanes/device)
eng = ServeEngine(PIPE, frame_size=1024, app="grow", buckets=(6, 16),
                  shard_devices=8)
for i in range(7):
    eng.admit(tenant="t", sid=f"g{i}")
assert eng.table.capacity == 16
assert eng._shard_ok(16) and not eng._shard_ok(6)
for i in range(7):
    eng.submit(f"g{i}", np.zeros(1024, np.complex64))
assert eng.step() == 7
d = eng.describe()["shard"]
assert d == {"devices": 8, "sharded": True, "lanes_per_device": 2}, d
eng.shutdown()

# loud refusal: more shard devices than exist (the make_mesh contract)
try:
    ServeEngine(PIPE, frame_size=1024, app="over", shard_devices=16)
    raise SystemExit("no refusal")
except ValueError as e:
    assert "refusing" in str(e) or "devices" in str(e)
print("WORKER OK")
""")


# ---------------------------------------------------------------------------
# in-process units: plan pass, mesh fixes, autotune axis, observability
# ---------------------------------------------------------------------------

def _pipe():
    from futuresdr_tpu.ops.stages import (Pipeline, fir_stage, mag2_stage,
                                          rotator_stage)
    return Pipeline([fir_stage(np.hanning(33).astype(np.float32)),
                     rotator_stage(0.05), mag2_stage()], np.complex64)


def test_factor_devices_balanced_and_prime_counts():
    from futuresdr_tpu.parallel.mesh import factor_devices
    # prime counts on deep meshes: the whole prime on one axis, 1s elsewhere
    assert factor_devices(7, 3) == (7, 1, 1)
    assert factor_devices(13, 4) == (13, 1, 1, 1)
    # the product ALWAYS equals n at every (n, n_axes)
    for n in range(1, 65):
        for n_axes in (1, 2, 3, 4):
            t = factor_devices(n, n_axes)
            assert len(t) == n_axes and int(np.prod(t)) == n, (n, n_axes, t)
    assert factor_devices(8, 3) == (2, 2, 2)
    assert factor_devices(12, 2) == (4, 3)
    with pytest.raises(ValueError):
        factor_devices(0, 2)
    with pytest.raises(ValueError):
        factor_devices(8, 0)


def test_make_mesh_refuses_short_mesh():
    import jax

    from futuresdr_tpu.parallel.mesh import make_mesh
    avail = len(jax.devices())
    with pytest.raises(ValueError, match="refusing"):
        make_mesh(("a", "b"), shape=(avail, 2))
    with pytest.raises(ValueError, match="axis names"):
        make_mesh(("a",), shape=(1, 1))
    # an explicit SUB-mesh stays valid (the 1-device reference pattern)
    m = make_mesh(("sp",), shape=(1,))
    assert m.shape["sp"] == 1


def test_plan_refusals_declines_and_off_identity():
    import jax

    from futuresdr_tpu.shard import plan_shard, shard_pipeline
    pipe = _pipe()
    with pytest.raises(ValueError, match="unknown shard mode"):
        plan_shard(pipe, mode="banana")
    with pytest.raises(ValueError, match="exist"):
        plan_shard(pipe, mode="data", n_devices=len(jax.devices()) + 1)
    with pytest.raises(ValueError, match=">= 1 device"):
        plan_shard(pipe, mode="data", n_devices=0)
    # off / D=1: inert plan, SAME program object
    for kw in ({"mode": "off"}, {"mode": "data", "n_devices": 1}):
        p = plan_shard(pipe, **kw)
        assert p.applied == "off" and not p.active
        assert shard_pipeline(pipe, **kw) is pipe
    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices for active plans")
    # model declines fall back to data, with the reason recorded
    from futuresdr_tpu.ops.stages import Pipeline, rotator_stage
    flat = Pipeline([rotator_stage(0.1)], np.complex64)
    p = plan_shard(flat, mode="model", n_devices=4)
    assert p.applied == "data" and any("no FFT/PFB" in r for r in p.declined)
    p = plan_shard(pipe, mode="model", n_devices=4, frame_size=4098)
    assert p.applied == "data" and any("divisible" in r for r in p.declined)
    # an eligible model plan applies, with per-stage decisions
    p = plan_shard(pipe, mode="model", n_devices=4)
    assert p.applied == "model"
    modes = {d.stage: d.mode for d in p.decisions}
    assert modes["fir"] == "model" and modes["rotator"] == "replicate"
    d = p.describe()
    assert d["applied"] == "model" and len(d["stages"]) == len(pipe.stages)


def test_autotune_shard_device_axis(tmp_path, monkeypatch):
    from futuresdr_tpu.tpu.autotune import (_norm_entry, _streamed_cache,
                                            cached_shard_devices,
                                            record_shard_devices,
                                            record_streamed_pick)
    pipe = _pipe()
    # guarded parse: a malformed width loses only its axis
    assert _norm_entry({"k": 2, "inflight": None,
                        "n_devices": "8"})["n_devices"] == 8
    assert "n_devices" not in _norm_entry({"k": 2, "inflight": None,
                                           "n_devices": "x"})
    assert _norm_entry({"k": 2, "inflight": None,
                        "n_devices": -4}) is not None
    assert "n_devices" not in _norm_entry({"k": 2, "inflight": None,
                                           "n_devices": -4})
    record_shard_devices(pipe.stages, pipe.in_dtype, "cpu", 4)
    assert cached_shard_devices(pipe.stages, pipe.in_dtype, "cpu") == 4
    # a streamed re-record PRESERVES the device axis (the orthogonal-axes
    # contract of the streamed-pick cache)
    record_streamed_pick(pipe.stages, pipe.in_dtype, "cpu", 2, inflight=4)
    assert cached_shard_devices(pipe.stages, pipe.in_dtype, "cpu") == 4
    # dropped, not stored: junk widths never enter the cache
    record_shard_devices(pipe.stages, pipe.in_dtype, "cpu", "junk")
    assert cached_shard_devices(pipe.stages, pipe.in_dtype, "cpu") == 4


def test_doctor_shard_section_and_per_device_gauges(monkeypatch):
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a multi-device mesh")
    # pinned peaks: the CPU backend has no chip peak, and the per-device
    # gauges only publish against a known denominator
    from futuresdr_tpu.config import config
    monkeypatch.setattr(config(), "peak_flops", 1e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 100.0)
    from futuresdr_tpu.shard import (ShardRunner, ShardedProgram,
                                     clear_plans, plan_shard)
    from futuresdr_tpu.telemetry import doctor as _doc
    from futuresdr_tpu.telemetry import profile as _profile
    from futuresdr_tpu.telemetry import prom
    from futuresdr_tpu.telemetry.spans import SpanEvent
    clear_plans()
    pipe = _pipe()
    D = min(4, len(jax.devices()))
    prog = ShardedProgram(pipe, plan_shard(pipe, mode="data", n_devices=D),
                          name="doc_shard")
    runner = ShardRunner(prog, 8192, k=1, name="doc_shard")
    rng = np.random.default_rng(0)
    rows = (rng.standard_normal((D, 8192))
            + 1j * rng.standard_normal((D, 8192))).astype(np.complex64)
    runner.run_group(rows)
    # plans + live runner stats under doctor.report()["shard"]; per-shard
    # lanes from cat="shard" spans (synthetic here — the runner only emits
    # when the recorder is armed)
    evs = [SpanEvent(1, "t", int(i * 1e6), int(5e5), "shard",
                     f"shard:d{i}", {"runner": "doc_shard"})
           for i in range(D)]
    rep = _doc.doctor().report(events=evs)
    plans = rep["shard"]["plans"]
    assert plans["doc_shard"]["applied"] == "data"
    assert plans["doc_shard"]["n_devices"] == D
    assert plans["doc_shard"]["dispatches"] == 1
    lanes = rep["shard"]["lanes"]
    assert set(lanes) == {f"shard:d{i}" for i in range(D)}
    assert all(v["spans"] == 1 for v in lanes.values())
    # per-device roofline entries + the fsdr_mfu_device gauge family
    pl = _profile.plane()
    pl.ensure_costs()
    pl.update_live_gauges(min_interval=0.0)   # seeds the gauge window
    runner.run_group(rows)                    # units inside the window
    pl.update_live_gauges(min_interval=0.0)
    progs = pl.roofline_report()["programs"]
    dev_entries = {k: v for k, v in progs.items()
                   if k.startswith("doc_shard@dev")}
    assert len(dev_entries) == D, sorted(progs)
    assert all(v["units"] >= 1 for v in dev_entries.values())
    assert {v["device"] for v in dev_entries.values()} \
        == {str(i) for i in range(D)}
    text = prom.registry().render()
    assert "fsdr_mfu_device" in text
    assert 'program="doc_shard"' in text
