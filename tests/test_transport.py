"""Transport blocks: ZMQ pub/sub, UDP, and the ThreadedScheduler end-to-end."""

import time

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, ThreadedScheduler
from futuresdr_tpu.blocks import (VectorSource, VectorSink, Head, Copy, NullSink,
                                  PubSink, SubSource, UdpSource, BlobToUdp,
                                  MessageBurst)
from futuresdr_tpu import Pmt


def test_zmq_pub_sub_pipe():
    # PUB/SUB slow-joiner: the SUB only completes its (re)connect some time after the
    # publisher binds, so the TX must keep publishing over wall-time — pace it with a
    # Throttle and repeat the ramp until the RX Head fills.
    from futuresdr_tpu.blocks import Throttle

    ramp = np.arange(10_000, dtype=np.float32)
    addr = "tcp://127.0.0.1:28913"

    fg_rx = Flowgraph()
    sub = SubSource(addr, np.float32)
    head = Head(np.float32, 20_000)
    snk = VectorSink(np.float32)
    fg_rx.connect(sub, head, snk)
    rt_rx = Runtime()
    running_rx = rt_rx.start(fg_rx)

    fg_tx = Flowgraph()
    src = VectorSource(ramp, repeat=2000)
    thr = Throttle(np.float32, rate=2e5)
    pub = PubSink(addr, np.float32)
    fg_tx.connect(src, thr, pub)
    tx_rt = Runtime()
    tx_running = tx_rt.start(fg_tx)

    running_rx.wait_sync()
    tx_running.stop_sync()
    got = snk.items()
    assert len(got) == 20_000
    # contiguity: consecutive values differ by 1 (mod the ramp wrap)
    d = np.diff(got)
    assert np.all((d == 1) | (d == -(len(ramp) - 1)))


def test_udp_blob_to_udp_source():
    port = 28914
    fg_rx = Flowgraph()
    src = UdpSource("127.0.0.1", port, np.uint8)
    head = Head(np.uint8, 3000)
    snk = VectorSink(np.uint8)
    fg_rx.connect(src, head, snk)
    rt = Runtime()
    running = rt.start(fg_rx)
    time.sleep(0.2)

    fg_tx = Flowgraph()
    burst = MessageBurst(Pmt.blob(bytes(range(100)) * 10), 3)
    udp = BlobToUdp("127.0.0.1", port)
    fg_tx.connect_message(burst, "out", udp, "in")
    Runtime().run(fg_tx)

    running.wait_sync()
    got = snk.items()
    assert len(got) == 3000
    np.testing.assert_array_equal(got[:100], np.arange(100, dtype=np.uint8))


def test_threaded_scheduler_runs_flowgraph():
    data = np.random.default_rng(0).random(300_000).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    chain = [Copy(np.float32) for _ in range(6)]
    snk = VectorSink(np.float32)
    fg.connect(src, *chain, snk)
    rt = Runtime(ThreadedScheduler(workers=4))
    rt.run(fg)
    np.testing.assert_array_equal(snk.items(), data)
    rt.shutdown()


def test_tpb_scheduler_runs_flowgraph():
    """Thread-per-block comparison scheduler (perf/perf/src/tpb_scheduler.rs role):
    every block runs on its own OS thread; results must match bit-exactly."""
    from futuresdr_tpu import TpbScheduler
    data = np.random.default_rng(1).random(300_000).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    chain = [Copy(np.float32) for _ in range(6)]
    snk = VectorSink(np.float32)
    fg.connect(src, *chain, snk)
    rt = Runtime(TpbScheduler())
    rt.run(fg)
    np.testing.assert_array_equal(snk.items(), data)
    rt.shutdown()


def test_tpb_scheduler_reuse_does_not_leak_threads():
    """Per-block workers must be retired after each run (repeated rt.run on one
    scheduler instance), and blocking blocks get dedicated threads too."""
    import threading
    from futuresdr_tpu import TpbScheduler
    sched = TpbScheduler()
    rt = Runtime(sched)
    data = np.arange(50_000, dtype=np.float32)
    for _ in range(3):
        fg = Flowgraph()
        src, snk = VectorSource(data), VectorSink(np.float32)
        fg.connect(src, Copy(np.float32), snk)
        rt.run(fg)
        np.testing.assert_array_equal(snk.items(), data)
    # only the supervisor worker should remain registered
    assert len(sched._workers) <= 1, len(sched._workers)
    rt.shutdown()
