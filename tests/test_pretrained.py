"""Packaged pretrained MCLDNN: loads and classifies accurately out of the box
(the burn example ships a trained model the same way)."""

import numpy as np
import pytest

from futuresdr_tpu.models.modrec import load_pretrained, synth_batch, CLASSES


def test_pretrained_loads_and_classifies():
    try:
        model, params = load_pretrained()
    except FileNotFoundError:
        pytest.skip("no packaged weights")
    from futuresdr_tpu.models.mcldnn import loss_fn

    rng = np.random.default_rng(42)
    X, y = synth_batch(rng, 256, 128, snr_db_range=(10.0, 20.0))
    _, acc = loss_fn(model, params, X, y)
    assert float(acc) > 0.9


def test_pretrained_in_flowgraph_classifier():
    try:
        model, params = load_pretrained()
    except FileNotFoundError:
        pytest.skip("no packaged weights")
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.modrec import ModClassifier, _psk_qam

    rng = np.random.default_rng(1)
    x = _psk_qam(rng, 64 * 128, "qpsk")
    x = x / np.sqrt(np.mean(np.abs(x) ** 2))
    sigma = np.sqrt(10 ** (-15 / 10) / 2)
    x = (x + sigma * (rng.standard_normal(len(x))
                      + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(x)
    clf = ModClassifier(model, params, n=128, batch=8)
    fg.connect_stream(src, "out", clf, "in")
    Runtime().run(fg)
    labels = [c for c, _ in clf.predictions]
    assert labels and labels.count("qpsk") >= len(labels) * 0.7, labels
