"""Profile plane (telemetry/profile.py): compile registry reason labels,
recompile-storm detection, the doctor's "compiling" verdict, live-gauge math,
peak autodetection/overrides, and the REST round trip."""

import json
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

from futuresdr_tpu.telemetry import doctor as doc
from futuresdr_tpu.telemetry import profile
from futuresdr_tpu.telemetry.spans import SpanRecorder


# ---------------------------------------------------------------------------
# compile registry: reasons, histogram, active window
# ---------------------------------------------------------------------------

def test_record_compile_reasons_and_histogram():
    pl = profile.ProfilePlane()
    before = profile.COMPILES.get(program="t-reasons", reason="warmup")
    with pl.compiling("t-reasons", "warmup", "frame=1024"):
        time.sleep(0.01)
    assert profile.COMPILES.get(program="t-reasons",
                                reason="warmup") == before + 1
    pl.record_compile("t-reasons", "recover", "frame=1024", seconds=0.5)
    assert profile.COMPILES.get(program="t-reasons", reason="recover") == 1
    assert pl.compiles_total == 2
    assert pl.compile_seconds_total > 0.5       # ctx-manager secs + 0.5
    # the histogram family carries the observation
    h = profile.COMPILE_SECONDS.labels(program="t-reasons")
    assert h.count >= 2


def test_active_compile_window_visible():
    pl = profile.ProfilePlane()
    assert pl.compiling_or_recent(10.0) is None
    with pl.compiling("t-active", "warmup", "sig"):
        act = pl.active_compiles()
        assert len(act) == 1 and act[0]["program"] == "t-active"
        comp = pl.compiling_or_recent(0.001)
        assert comp["in_progress"] and comp["program"] == "t-active"
    assert pl.active_compiles() == []
    # finished inside the window still reports (not in progress)
    comp = pl.compiling_or_recent(10.0)
    assert comp is not None and not comp["in_progress"]
    assert comp["program"] == "t-active" and comp["reason"] == "warmup"
    # ... and ages out of a short window
    time.sleep(0.02)
    assert pl.compiling_or_recent(0.001) is None


def test_storm_detection_names_signatures_and_skips_autotune():
    pl = profile.ProfilePlane()
    # autotune sweeps never read as storms
    for i in range(5):
        pl.record_compile("t-sweep", "autotune", f"frame={i}")
    assert pl.storm_report() == []
    # shape churn on one program: storm naming the signatures
    for sig in ("frame=1024", "frame=2048", "frame=4096"):
        pl.record_compile("t-churn", "warmup", sig)
    (storm,) = pl.storm_report()
    assert storm["program"] == "t-churn" and storm["compiles"] == 3
    assert storm["signatures"] == ["frame=1024", "frame=2048", "frame=4096"]
    assert storm["signature_churn"] is True
    # below threshold: quiet
    pl2 = profile.ProfilePlane()
    pl2.record_compile("t-two", "warmup", "a")
    pl2.record_compile("t-two", "warmup", "b")
    assert pl2.storm_report() == []
    # cost-analysis compiles are one-per-signature by construction: like
    # autotune they never read as a storm (a bench prefix sweep compiles
    # many signatures back to back)
    for i in range(5):
        pl2.record_compile("cost_analysis", "cost", f"sig{i}")
    assert pl2.storm_report() == []


def test_finished_benign_reasons_do_not_downgrade_verdicts():
    """A FINISHED autotune/cost compile is invisible to the doctor's
    compiling-verdict lookback (a background sweep must not mask a real
    deadlock); an IN-PROGRESS one still counts."""
    pl = profile.ProfilePlane()
    pl.record_compile("t-sweep", "autotune", "frame=1", seconds=0.2)
    pl.record_compile("cost_analysis", "cost", "sig", seconds=0.2)
    assert pl.compiling_or_recent(60.0) is None
    pl.record_compile("t-real", "warmup", "frame=2", seconds=0.2)
    comp = pl.compiling_or_recent(60.0)
    assert comp is not None and comp["program"] == "t-real"
    with pl.compiling("t-sweep", "autotune", "frame=3"):
        comp = pl.compiling_or_recent(0.001)
        assert comp is not None and comp["in_progress"]


def test_reregistration_replaces_cost_source():
    """register() with a new cost_thunk REPLACES an already-materialized
    cost (a re-init can change the program); dispatch counters survive."""
    pl = profile.ProfilePlane()
    p = pl.register("t-rereg", cost={"flops": 1.0, "bytes": 1.0})
    p.dispatch(3)
    pl.register("t-rereg", cost_thunk=lambda: {"flops": 9.0, "bytes": 2.0})
    assert p.cost is None                 # stale cost dropped
    assert p.units == 3                   # counters kept
    assert p.ensure_cost() == {"flops": 9.0, "bytes": 2.0}


# ---------------------------------------------------------------------------
# doctor "compiling" verdict
# ---------------------------------------------------------------------------

def _fake_wk(name="fake_0"):
    wk = types.SimpleNamespace()
    wk.instance_name = name
    wk.kernel = types.SimpleNamespace(stream_inputs=(), stream_outputs=())
    wk.counters = {"work_calls": 0}
    wk.metrics = lambda: dict(wk.counters)
    return wk


def test_watchdog_compiling_verdict_rearms():
    """An in-progress compile inside the no-progress window classifies
    `compiling` (no flight record, window re-arms); once the compile ages
    out, the same silence gets its real diagnosis."""
    d = doc.Doctor()
    d.interval, d.window = 0.01, 3
    token = d.attach([_fake_wk()], [])
    with profile.plane().compiling("t-doctor-prog", "warmup", "frame=2M"):
        for _ in range(5):
            d.tick()
        assert d.last_trip is not None
        assert d.last_trip["state"] == "compiling"
        assert d.last_trip["suspect_block"] == "t-doctor-prog"
        assert "warmup" in d.last_trip["detail"]
        assert d.last_report is None          # benign: no flight record
        att = d._fgs[token]
        assert not att.tripped                # window re-armed
    # compile done and aged out of the (strikes x interval) window: the
    # quiet message-plane flowgraph now reports its genuine verdict
    time.sleep(0.1)
    att = d._fgs[token]
    att.strikes = 0
    for _ in range(4):
        d.tick()
    assert d.last_trip["state"] == "idle"
    d.detach(token)


# ---------------------------------------------------------------------------
# live-gauge math + roofline report
# ---------------------------------------------------------------------------

def test_live_gauge_math(monkeypatch):
    from futuresdr_tpu.config import config
    monkeypatch.setattr(config(), "peak_flops", 1e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 100.0)   # 1e11 B/s
    pl = profile.ProfilePlane()
    p = pl.register("t-gauge-math", cost={"flops": 2e9, "bytes": 1e8})
    pl.update_live_gauges(min_interval=0.0)   # seed the window
    p.dispatch(4, t=time.monotonic())     # dispatch SITES own the group
    time.sleep(0.05)                      # stamp (kernel drive loop/serve
    p.dispatch(4, t=time.monotonic())     # step); the hook stays bare
    pl.update_live_gauges(min_interval=0.0)
    assert p.mfu is not None and p.mfu > 0
    # the config peak_flops is the BF16 matmul peak; an unlowered program
    # defaults to compute_dtype="f32" whose peak is half (per-dtype chip
    # peaks, utils/roofline.dtype_peak_flops): flops/(peak/2) = 2e9/5e11 =
    # 4e-3 per unit-rate; bytes/peak_bw = 1e8/1e11 = 1e-3 — mfu must be
    # exactly 4x hbm_util (same window)
    assert p.compute_dtype == "f32"
    assert p.mfu == pytest.approx(4 * p.hbm_util, rel=1e-6)
    assert profile.MFU.get(program="t-gauge-math") == pytest.approx(p.mfu)
    # run-average lands in the roofline report with bound classification
    rep = pl.roofline_report()
    entry = rep["programs"]["t-gauge-math"]
    assert entry["units"] == 8
    assert entry["compute_dtype"] == "f32"
    assert entry["mfu_avg"] > 0
    # the run average spans first..last dispatch and the FIRST call's units
    # mark the left edge: rate = (8 - 4) / (t_last - t_first), not 8/dt —
    # units/(units-1) inflation on short runs is the bug this pins
    dt = p.t_last - p.t_first
    want = (4 / dt) * 2e9 / (1e12 / 2)
    assert entry["mfu_avg"] == pytest.approx(want, rel=1e-3)
    # arith intensity 2e9/1e8 = 20 flop/B vs the f32 ridge 5e11/1e11 = 5
    # → compute
    assert entry["bound"] == "compute"
    # a bf16-lowered program re-registered with dtype="bf16" grades against
    # the FULL tabled peak: same dispatch record, half the mfu
    pl.register("t-gauge-math", cost={"flops": 2e9, "bytes": 1e8},
                dtype="bf16")
    rep2 = pl.roofline_report()
    e2 = rep2["programs"]["t-gauge-math"]
    assert e2["compute_dtype"] == "bf16"
    assert e2["mfu_avg"] == pytest.approx(want / 2, rel=1e-3)


def test_int8_program_grades_against_int8_peak(monkeypatch):
    """An int8-lowered program's MFU denominator is the int8 peak where the
    chip tables one (2x the bf16 MXU figure), the bf16 peak where it does
    not — NEVER the f32 half (the pre-round-20 fallback this pins out)."""
    from futuresdr_tpu.config import config
    from futuresdr_tpu.utils.roofline import (CHIP_PEAKS, dominant_dtype,
                                              dtype_peak_flops)
    v5e = CHIP_PEAKS["v5e"]
    assert dtype_peak_flops(v5e, "int8") == v5e["int8_flops"]
    assert dtype_peak_flops(v5e, "int8") == 2 * dtype_peak_flops(v5e, "bf16")
    # v2-v4 MXUs have no int8 mode: fall back to the bf16 figure
    v4 = CHIP_PEAKS["v4"]
    assert "int8_flops" not in v4
    assert dtype_peak_flops(v4, "int8") == dtype_peak_flops(v4, "bf16")
    assert dtype_peak_flops(v4, "int8") == 2 * dtype_peak_flops(v4, "f32")

    # the registration path TpuKernel drives: a mode="int8"-lowered chain's
    # dominant dtype is "int8", so fsdr_mfu{program} keys the peak above
    from futuresdr_tpu.ops import precision as P
    from futuresdr_tpu.ops.stages import (Pipeline, fft_stage, fir_stage,
                                          mag2_stage)
    taps = np.hanning(33).astype(np.float32)
    pipe = Pipeline([fir_stage(taps), fft_stage(256), mag2_stage()],
                    np.complex64)
    low, plan = P.plan_interior_precision(pipe, mode="int8")
    assert plan.lowered >= 1
    assert dominant_dtype(low.stages) == "int8"

    # gauge math end-to-end: config peaks carry no int8 figure, so an
    # int8-registered program grades against the FULL bf16 peak
    monkeypatch.setattr(config(), "peak_flops", 1e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 100.0)
    pl = profile.ProfilePlane()
    p = pl.register("t-int8-peak", cost={"flops": 2e9, "bytes": 1e8},
                    dtype="int8")
    p.dispatch(4, t=time.monotonic())
    time.sleep(0.05)
    p.dispatch(4, t=time.monotonic())
    rep = pl.roofline_report()
    e = rep["programs"]["t-int8-peak"]
    assert e["compute_dtype"] == "int8"
    dt = p.t_last - p.t_first
    want = (4 / dt) * 2e9 / 1e12
    assert e["mfu_avg"] == pytest.approx(want, rel=1e-3)


def test_dispatch_hook_bound_before_first_call_advances_window(monkeypatch):
    """A dispatch hook reference captured at init (before any dispatch —
    the hot-path pattern _Program's docstring encourages) must keep
    advancing t_last on later stamped calls: the bound method still points
    at _dispatch_first after the slot swap, and a frozen right edge would
    silently zero mfu_avg for that program."""
    from futuresdr_tpu.config import config
    monkeypatch.setattr(config(), "peak_flops", 1e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 100.0)
    pl = profile.ProfilePlane()
    p = pl.register("t-stale-hook", cost={"flops": 1e6, "bytes": 1e6})
    hook = p.dispatch                     # bound BEFORE the first call
    t0 = time.monotonic()
    hook(2, t=t0)
    hook(2, t=t0 + 1.0)                   # same stale reference
    assert p.units == 4
    assert p.t_first == pytest.approx(t0)
    assert p.t_last == pytest.approx(t0 + 1.0)
    rep = pl.roofline_report()
    assert rep["programs"]["t-stale-hook"]["mfu_avg"] is not None


def test_live_gauge_bound_classification(monkeypatch):
    from futuresdr_tpu.config import config
    monkeypatch.setattr(config(), "peak_flops", 1e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 100.0)   # ridge = 10 f/B
    pl = profile.ProfilePlane()
    pl.register("t-bound-hbm", cost={"flops": 1e6, "bytes": 1e6})   # ai 1
    pl.register("t-bound-mxu", cost={"flops": 1e8, "bytes": 1e6})   # ai 100
    rep = pl.roofline_report()
    assert rep["programs"]["t-bound-hbm"]["bound"] == "hbm"
    assert rep["programs"]["t-bound-mxu"]["bound"] == "compute"
    assert rep["ridge_flop_per_byte"] == pytest.approx(10.0)


def test_unmaterialized_cost_publishes_nothing(monkeypatch):
    """A lazily-registered program with no materialized cost degrades to
    dispatch counting — no gauge, no wrong denominator; ensure_costs
    swallows a failing thunk."""
    from futuresdr_tpu.config import config
    monkeypatch.setattr(config(), "peak_flops", 1e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 100.0)

    def boom():
        raise RuntimeError("no cost for you")

    pl = profile.ProfilePlane()
    p = pl.register("t-no-cost", cost_thunk=boom)
    p.dispatch(3)
    pl.ensure_costs()
    pl.update_live_gauges(min_interval=0.0)
    assert p.cost is None and p.mfu is None
    assert profile.MFU.get(program="t-no-cost") == 0.0
    entry = pl.roofline_report()["programs"]["t-no-cost"]
    assert entry == {"units": 3}


# ---------------------------------------------------------------------------
# peak autodetection (utils/roofline.detect_peaks)
# ---------------------------------------------------------------------------

def test_detect_peaks_config_override(monkeypatch):
    from futuresdr_tpu.config import config
    from futuresdr_tpu.utils.roofline import detect_peaks
    monkeypatch.setattr(config(), "peak_flops", 5e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 123.0)
    p = detect_peaks("cpu")
    assert p == {"flops": 5e12, "hbm_bytes": 123e9, "chip": "config"}


def test_detect_peaks_device_kind(monkeypatch):
    import jax

    from futuresdr_tpu.utils import roofline

    class _Dev:
        def __init__(self, platform, kind):
            self.platform = platform
            self.device_kind = kind

    # known chip kinds map to the public table
    monkeypatch.setattr(jax, "devices",
                        lambda *a: [_Dev("tpu", "TPU v5 lite")])
    p = roofline.detect_peaks("tpu")
    assert p["chip"] == "v5e" and p["flops"] == 197e12
    monkeypatch.setattr(jax, "devices", lambda *a: [_Dev("tpu", "TPU v4")])
    assert roofline.detect_peaks()["chip"] == "v4"
    # UNKNOWN accelerator: degrade to flops/bytes-only, never a wrong
    # denominator — even when the backend label would map
    monkeypatch.setattr(jax, "devices", lambda *a: [_Dev("tpu", "TPU v99")])
    assert roofline.detect_peaks("tpu") is None
    # a cpu host asking about the "tpu" label keeps the historical mapping
    monkeypatch.setattr(jax, "devices", lambda *a: [_Dev("cpu", "cpu")])
    assert roofline.detect_peaks("tpu")["chip"] == "v5e"
    assert roofline.detect_peaks("cpu") is None


def test_kind_to_chip_mapping():
    from futuresdr_tpu.utils.roofline import _kind_to_chip
    assert _kind_to_chip("TPU v5 lite") == "v5e"
    assert _kind_to_chip("tpu_v5_lite") == "v5e"
    assert _kind_to_chip("TPU v5p") == "v5p"
    assert _kind_to_chip("TPU v6e") == "v6e"
    assert _kind_to_chip("TPU v4") == "v4"
    assert _kind_to_chip("TPU v3") == "v3"
    assert _kind_to_chip("TPU v2") == "v2"
    assert _kind_to_chip("Quantum Accelerator Mk1") is None


# ---------------------------------------------------------------------------
# Perfetto counter tracks
# ---------------------------------------------------------------------------

def test_span_counter_exports_as_counter_phase():
    rec = SpanRecorder(capacity=64, enabled=True)
    rec.counter("mfu:t-prog", 0.25)
    doc_json = rec.chrome_trace()
    c = [e for e in doc_json["traceEvents"] if e.get("ph") == "C"]
    assert len(c) == 1
    assert c[0]["name"] == "mfu:t-prog"
    assert c[0]["args"] == {"value": 0.25}
    # disabled recorder records nothing
    rec2 = SpanRecorder(capacity=64, enabled=False)
    rec2.counter("mfu:x", 1.0)
    assert rec2.drain() == []


# ---------------------------------------------------------------------------
# kernel integration: warmup billed once, dispatches billed as units
# ---------------------------------------------------------------------------

def test_tpu_kernel_bills_warmup_and_dispatches():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Head, NullSink, NullSource
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu import TpuKernel

    frame = 1 << 12
    config().buffer_size = max(config().buffer_size, 4 * frame * 8)
    fg = Flowgraph()
    # frames_per_dispatch pinned to an EXPLICIT 1: a streamed pick recorded
    # by an earlier test could otherwise resolve K>1 from the in-memory
    # autotune cache and halve the dispatch count this test asserts on
    tk = TpuKernel([mag2_stage()], np.complex64, frame_size=frame,
                   frames_in_flight=2, frames_per_dispatch=1)
    fg.connect(NullSource(np.complex64), Head(np.complex64, 8 * frame),
               tk, NullSink(np.float32))
    # DELTA assertions: instance names are per-flowgraph, so an earlier
    # test's TpuKernel_2 shares this program label (and its plane entry —
    # register() keeps counters across re-registration by design)
    prog = tk.meta.instance_name
    warm0 = profile.COMPILES.get(program=prog, reason="warmup")
    reinit0 = profile.COMPILES.get(program=prog, reason="reinit")
    prev = profile.plane().program(prog)
    units0 = prev.units if prev is not None else 0
    Runtime().run(fg)
    assert profile.COMPILES.get(program=prog, reason="warmup") == warm0 + 1
    assert profile.COMPILES.get(program=prog, reason="reinit") == reinit0
    assert tk._prof is not None
    assert tk._prof.units - units0 == tk._dispatches >= 8
    # the registered cost materializes on demand (cached cost analysis)
    cost = tk._prof.ensure_cost()
    assert cost is not None and cost["bytes"] > 0


def test_doctor_report_roofline_and_resource(monkeypatch):
    """doctor.report() carries the roofline table and the binding-resource
    verdict: a compute-lane bottleneck names the dominant program's bound
    resource, not just the lane."""
    from futuresdr_tpu.config import config
    from futuresdr_tpu.telemetry.spans import SpanEvent
    monkeypatch.setattr(config(), "peak_flops", 1e12)
    monkeypatch.setattr(config(), "peak_hbm_gbps", 100.0)
    p = profile.plane().register("t-resource",
                                 cost={"flops": 1e6, "bytes": 1e6})  # hbm
    p.dispatch(2)
    mk = lambda name, s, e: SpanEvent(1, "t", s, e - s, "tpu", name, None)
    rep = doc.Doctor().report(events=[mk("compute", 0, 10_000_000),
                                      mk("H2D", 0, 1_000_000)])
    assert rep["bottleneck_lane"] == "compute"
    assert rep["bottleneck_resource"] == "hbm"
    assert "t-resource" in rep["roofline"]["programs"]
    # link-bound run names the link
    rep2 = doc.Doctor().report(events=[mk("compute", 0, 1_000_000),
                                       mk("H2D", 0, 10_000_000)])
    assert rep2["bottleneck_resource"] == "link"


# ---------------------------------------------------------------------------
# REST round trip
# ---------------------------------------------------------------------------

def test_profile_endpoint_round_trip():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import NullSink, NullSource
    from futuresdr_tpu.runtime.ctrl_port import ControlPort

    profile.plane().register("t-rest-prog",
                             cost={"flops": 1e6, "bytes": 1e6})
    profile.record_compile("t-rest-prog", "warmup", "frame=4096", 0.1)
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), NullSink(np.float32))
    rt = Runtime()
    running = rt.start(fg)
    cp = ControlPort(rt.handle, bind="127.0.0.1:29473")
    cp.start()
    base = "http://127.0.0.1:29473"
    try:
        snap = json.load(urllib.request.urlopen(base + "/api/fg/0/profile/"))
        assert snap["compiles"]["t-rest-prog"]["warmup"] >= 1
        assert snap["compiles_total"] >= 1
        assert "t-rest-prog" in snap["roofline"]["programs"]
        assert "storms" in snap and "active_compiles" in snap
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/api/fg/99/profile/")
        assert ei.value.code == 404
        # the gauges live on GET /metrics (acceptance: fsdr_mfu /
        # fsdr_compiles_total on the scrape endpoint)
        text = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "fsdr_compiles_total" in text
        assert 'program="t-rest-prog"' in text
        assert "# TYPE fsdr_mfu gauge" in text
        assert "# TYPE fsdr_compile_seconds histogram" in text
    finally:
        running.stop_sync()
        cp.stop()
