"""WLAN transceiver tests: coding round-trips, PHY loopback (clean + impaired), and the
full flowgraph loopback — mirroring the reference's `examples/wlan/src/bin/loopback.rs`.
"""

import numpy as np
import pytest

from futuresdr_tpu.models.wlan import (MCS_TABLE, encode_frame, decode_frame,
                                       decode_stream, Mac, WlanEncoder, WlanDecoder,
                                       coding, ofdm)
from futuresdr_tpu.models.wlan.phy import bytes_to_bits, bits_to_bytes


def test_scrambler_roundtrip():
    rng = np.random.default_rng(0)
    bits = rng.integers(0, 2, 500).astype(np.uint8)
    s = coding.scramble(bits, 0x5B)
    assert not np.array_equal(s, bits)
    np.testing.assert_array_equal(coding.descramble(s, 0x5B), bits)


def test_conv_code_viterbi_clean():
    rng = np.random.default_rng(1)
    bits = np.concatenate([rng.integers(0, 2, 200), np.zeros(6)]).astype(np.uint8)
    coded = coding.conv_encode(bits)
    llrs = coded.astype(np.float64) * 2 - 1
    dec = coding.viterbi_decode(llrs, len(bits))
    np.testing.assert_array_equal(dec, bits)


def test_viterbi_corrects_errors():
    rng = np.random.default_rng(2)
    bits = np.concatenate([rng.integers(0, 2, 400), np.zeros(6)]).astype(np.uint8)
    coded = coding.conv_encode(bits)
    llrs = (coded.astype(np.float64) * 2 - 1)
    flip = rng.choice(len(llrs), size=len(llrs) // 20, replace=False)  # 5% bit flips
    llrs[flip] *= -1
    dec = coding.viterbi_decode(llrs, len(bits))
    np.testing.assert_array_equal(dec, bits)


@pytest.mark.parametrize("rate", ["1/2", "2/3", "3/4"])
def test_puncture_depuncture_viterbi(rate):
    rng = np.random.default_rng(3)
    bits = np.concatenate([rng.integers(0, 2, 300), np.zeros(6)]).astype(np.uint8)
    coded = coding.conv_encode(bits)
    punct = coding.puncture(coded, rate)
    llrs = punct.astype(np.float64) * 2 - 1
    dep = coding.depuncture(llrs, rate)
    dec = coding.viterbi_decode(dep, len(bits))
    np.testing.assert_array_equal(dec, bits)


def test_interleaver_roundtrip():
    for n_bpsc in (1, 2, 4, 6):
        n_cbps = 48 * n_bpsc
        rng = np.random.default_rng(4)
        bits = rng.integers(0, 2, 3 * n_cbps).astype(np.uint8)
        inter = coding.interleave(bits, n_cbps, n_bpsc)
        deint = coding.deinterleave(inter.astype(np.float64), n_cbps, n_bpsc)
        np.testing.assert_array_equal(deint.astype(np.uint8), bits)


@pytest.mark.parametrize("mod", ["bpsk", "qpsk", "qam16", "qam64"])
def test_map_demap_roundtrip(mod):
    rng = np.random.default_rng(5)
    n_bpsc = {"bpsk": 1, "qpsk": 2, "qam16": 4, "qam64": 6}[mod]
    bits = rng.integers(0, 2, 48 * n_bpsc).astype(np.uint8)
    syms = ofdm.map_bits(bits, mod)
    llrs = ofdm.demap_llrs(syms, mod)
    np.testing.assert_array_equal((llrs > 0).astype(np.uint8), bits)


@pytest.mark.parametrize("mcs", list(MCS_TABLE))
def test_phy_loopback_clean(mcs):
    psdu = bytes(f"Hello TPU-native 802.11 with {mcs}!".encode()) * 3
    frame = encode_frame(psdu, mcs)
    decoded = decode_stream(frame)
    assert len(decoded) == 1, f"{mcs}: expected 1 frame, got {len(decoded)}"
    assert decoded[0].psdu == psdu
    assert decoded[0].mcs.name == mcs


def test_phy_loopback_noise_cfo_delay():
    """Impaired channel: delay + AWGN + carrier frequency offset (loopback.rs adds
    channel impairments the same way)."""
    rng = np.random.default_rng(6)
    psdu = b"The quick brown fox jumps over the lazy dog" * 4
    frame = encode_frame(psdu, "qpsk_1_2")
    sig = np.concatenate([np.zeros(777, np.complex64), frame,
                          np.zeros(500, np.complex64)])
    n = np.arange(len(sig))
    cfo = 2 * np.pi * 1e-4
    sig = sig * np.exp(1j * cfo * n)
    sig = sig + (0.02 * (rng.standard_normal(len(sig))
                         + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    decoded = decode_stream(sig.astype(np.complex64))
    assert len(decoded) == 1
    assert decoded[0].psdu == psdu


def test_mac_roundtrip():
    mac = Mac()
    mpdu = mac.frame(b"payload!")
    assert mac.deframe(mpdu) == b"payload!"
    corrupted = bytearray(mpdu)
    corrupted[10] ^= 0xFF
    assert mac.deframe(bytes(corrupted)) is None


def test_flowgraph_loopback():
    """Full actor-runtime loopback: Encoder block → channel Apply → Decoder block
    (the reference's `loopback.rs:30-123`)."""
    from futuresdr_tpu import Flowgraph, Runtime, Pmt
    from futuresdr_tpu.blocks import Apply

    rng = np.random.default_rng(7)
    fg = Flowgraph()
    enc = WlanEncoder("qpsk_1_2")
    chan = Apply(lambda x: x + (0.01 * (rng.standard_normal(len(x))
                                        + 1j * rng.standard_normal(len(x)))
                                ).astype(np.complex64), np.complex64)
    dec = WlanDecoder()
    fg.connect(enc, chan, dec)

    payloads = [f"frame number {i}".encode() * 5 for i in range(5)]
    rt = Runtime()
    running = rt.start(fg)
    for p in payloads:
        rt.scheduler.run_coro_sync(running.handle.call(enc, "tx", Pmt.blob(p)))
    rt.scheduler.run_coro_sync(running.handle.call(enc, "tx", Pmt.finished()))
    running.wait_sync()
    assert dec.frames == payloads


def test_decode_stream_batch_matches_per_frame():
    """Burst-batched Viterbi decoding must find the same frames as the per-frame path."""
    rng = np.random.default_rng(11)
    from futuresdr_tpu.models.wlan import decode_stream_batch

    mac = Mac()
    parts = []
    sent = []
    for i in range(6):
        psdu = mac.frame(f"batch frame {i}".encode() * 3)
        sent.append(psdu)
        parts += [encode_frame(psdu, "qam16_1_2"), np.zeros(400, np.complex64)]
    sig = np.concatenate(parts)
    sig = (sig + 0.01 * (rng.standard_normal(len(sig))
                         + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    per_frame = [f.psdu for f in decode_stream(sig)]
    batched = [f.psdu for f in decode_stream_batch(sig)]
    assert per_frame == sent
    assert batched == sent


def test_bit_packing():
    data = b"\x01\x80\xff"
    bits = bytes_to_bits(data)
    assert bits[0] == 1 and bits[7] == 0
    assert bits[8] == 0 and bits[15] == 1
    assert bits_to_bytes(bits) == data


def test_jitted_head_matches_host_path():
    """demod_head_jax (LTS channel est + SIGNAL demap in one jit) agrees with the
    host path (estimate_channel + equalize + BPSK demap) including under CFO."""
    from futuresdr_tpu.models.wlan import ofdm
    from futuresdr_tpu.models.wlan.jax_demod import demod_head_jax
    from futuresdr_tpu.models.wlan.phy import encode_frame

    mac = Mac()
    psdu = mac.frame(b"head path check" * 4)
    sig = encode_frame(psdu, "bpsk_1_2")
    sig = np.concatenate([np.zeros(100, np.complex64), sig])
    start = ofdm.detect_packets(sig)[0]
    _, lts_start, _cfo = ofdm.sync_long(sig, start)
    for cfo in (0.0, 0.003, -0.008):
        head = sig[lts_start:lts_start + 208]
        Hj, llrs_j = demod_head_jax(head, cfo)
        host = head * np.exp(-1j * cfo * np.arange(208)) if cfo else head
        Hh = ofdm.estimate_channel(host, 0)
        spec = ofdm.ofdm_demodulate_symbols(host[128:], 1)
        eq = ofdm.equalize(spec, Hh, symbol_offset=0)
        llrs_h = ofdm.demap_llrs(eq.reshape(-1), "bpsk")
        np.testing.assert_allclose(Hj, Hh.astype(np.complex64), atol=2e-4)
        np.testing.assert_allclose(llrs_j, llrs_h.astype(np.float32), atol=2e-3)


def test_full_decode_with_jax_paths_forced():
    """End-to-end decode with the jax head+body paths guaranteed active (backend
    initialized): every MCS loops back clean."""
    import jax
    jax.devices()                         # ensure backend_ready() is True
    mac = Mac()
    for mcs in ("bpsk_1_2", "qam16_1_2", "qam64_3_4"):
        psdu = mac.frame(f"jax path {mcs}".encode() * 20)   # > 8 symbols
        sig = encode_frame(psdu, mcs)
        sig = np.concatenate([np.zeros(171, np.complex64), sig,
                              np.zeros(64, np.complex64)])
        sig = (sig * np.exp(1j * 0.002 * np.arange(len(sig)))).astype(np.complex64)
        frames = decode_stream(sig)
        assert len(frames) == 1 and frames[0].psdu == psdu, mcs


def test_short_frame_jax_head_host_body():
    """n_sym < 8 with a ready backend: the jax HEAD (complex64 H) feeds the host
    numpy body demod — the mixed path must decode clean too."""
    import jax
    jax.devices()                         # backend_ready() -> True
    mac = Mac()
    psdu = mac.frame(b"tiny")             # few symbols at qam16
    sig = encode_frame(psdu, "qam16_1_2")
    sig = np.concatenate([np.zeros(130, np.complex64), sig,
                          np.zeros(64, np.complex64)])
    sig = (sig * np.exp(1j * 0.0015 * np.arange(len(sig)))).astype(np.complex64)
    frames = decode_stream(sig)
    assert len(frames) == 1 and frames[0].psdu == psdu
    assert frames[0].n_symbols < 8        # really the mixed path


def test_native_viterbi_bit_matches_numpy():
    """The C++ ACS loop decodes bit-identically to the numpy trellis (same tie
    convention), across short/long frames and noisy LLRs."""
    import futuresdr_tpu.models.wlan.coding as c
    if c._native_lib() is None:
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(0)
    for n in (24, 97, 511, 513, 3000):
        bits = rng.integers(0, 2, n).astype(np.uint8)
        bits[-6:] = 0
        llrs = (c.conv_encode(bits).astype(np.float64) * 2 - 1
                + 0.5 * rng.standard_normal(2 * n))
        native = c.viterbi_decode(llrs, n)
        saved, c._NATIVE = c._NATIVE, 0          # force the numpy path
        try:
            import futuresdr_tpu.ops.viterbi as ov
            saved_br, ov.backend_ready = ov.backend_ready, lambda: False
            try:
                ref = c.viterbi_decode(llrs, n)
            finally:
                ov.backend_ready = saved_br
        finally:
            c._NATIVE = saved
        assert np.array_equal(native, ref), n
        assert np.array_equal(native, bits), f"decode errors at n={n}"


def test_noisy_burst_train_no_mislock_no_dup():
    """Regression for two RX-chain defects found at 25 dB: (1) sync_long's
    search window ended before LTS2 when detection fired early, so the
    cyclic-prefix ghost won the 64-apart pairing — a deterministic one-symbol
    mislock whose garbage SIGNAL passed parity and LOST the real frame;
    (2) noise re-triggering the plateau detector inside a burst produced
    duplicate/garbage decodes. 60 noisy frames must come back exactly once
    each, nothing else."""
    rng = np.random.default_rng(1234)
    mac = Mac()
    parts, sent = [], []
    for i in range(60):
        psdu = mac.frame(bytes(rng.integers(0, 256, 256, dtype=np.uint8)))
        sent.append(psdu)
        parts += [encode_frame(psdu, "qpsk_1_2"), np.zeros(300, np.complex64)]
    sig = np.concatenate(parts)
    sigma = np.sqrt(np.mean(np.abs(sig) ** 2) * 10 ** (-25 / 10) / 2)
    sig = (sig + sigma * (rng.standard_normal(len(sig))
                          + 1j * rng.standard_normal(len(sig)))
           ).astype(np.complex64)
    got = [f.psdu for f in decode_stream(sig)]
    assert got == sent, (len(got), len(set(got) & set(sent)))


def test_frame_snr_estimate():
    """Per-frame SNR from the LTS repetitions (`frame_equalizer.rs:64` snr()):
    tracks the actual channel SNR within a few dB, and orders clean vs noisy."""
    from futuresdr_tpu.models.wlan.phy import decode_stream, encode_frame
    rng = np.random.default_rng(8)
    psdu = b"snr probe frame" * 3
    burst = encode_frame(psdu, "qpsk_1_2")
    sig_p = np.mean(np.abs(burst) ** 2)
    got = {}
    for snr_db in (30.0, 10.0):
        sigma = np.sqrt(sig_p / (2 * 10 ** (snr_db / 10)))
        x = np.concatenate([np.zeros(300, np.complex64), burst,
                            np.zeros(300, np.complex64)])
        x = (x + sigma * (rng.standard_normal(len(x))
                          + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
        frames = decode_stream(x)
        assert len(frames) == 1 and frames[0].psdu == psdu
        got[snr_db] = frames[0].snr_db
        assert abs(frames[0].snr_db - snr_db) < 6.0, (snr_db, frames[0].snr_db)
    assert got[30.0] > got[10.0]


def test_random_config_roundtrip_fuzz():
    """Seeded sweep over random (MCS, length, CFO, delay) frames: every
    combination decodes exactly through the full stream RX."""
    from futuresdr_tpu.models.wlan.phy import decode_stream, encode_frame
    from futuresdr_tpu.models.wlan.consts import MCS_TABLE
    rng = np.random.default_rng(80211)
    names = list(MCS_TABLE)
    for trial in range(10):
        mcs = names[int(rng.integers(0, len(names)))]
        n_pay = int(rng.integers(1, 500))
        psdu = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        burst = encode_frame(psdu, mcs)
        x = np.concatenate([np.zeros(int(rng.integers(100, 900)), np.complex64),
                            burst, np.zeros(300, np.complex64)])
        cfo = float(rng.uniform(-0.002, 0.002))
        x = (x * np.exp(1j * cfo * np.arange(len(x)))).astype(np.complex64)
        # 28 dB channel: comfortably above 64QAM-3/4's requirement, so every
        # MCS in the sweep must decode error-free
        sigma = float(np.sqrt(np.mean(np.abs(burst) ** 2) / (2 * 10 ** 2.8)))
        x = (x + sigma * (rng.standard_normal(len(x))
                          + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
        frames = decode_stream(x)
        assert len(frames) == 1 and frames[0].psdu == psdu, (trial, mcs, n_pay)


def test_viterbi_terminates_at_tail_not_pad():
    """Regression (r4 fuzz campaign): the decoder must decode exactly
    SERVICE+PSDU+tail — the pad bits after the tail stay scrambled, so tracing
    back from state 0 at the padded n_sym*n_dbps length corrupted the final
    bytes for seed/content combos with nonzero scrambled pad."""
    from futuresdr_tpu.models.wlan.phy import decode_stream, encode_frame
    # the exact (mcs, length, content) triple the campaign caught
    rng = np.random.default_rng(5)
    for _ in range(6):
        rng.integers(0, 256, 1)
    rng.integers(0, 256, 195)
    psdu = rng.integers(0, 256, 195).astype(np.uint8).tobytes()
    burst = encode_frame(psdu, "qam16_3_4")
    x = np.concatenate([np.zeros(200, np.complex64), burst,
                        np.zeros(200, np.complex64)])
    frames = decode_stream(x)
    assert len(frames) == 1 and frames[0].psdu == psdu
    # sweep a band of lengths at the highest-rate MCSes (clean channel: every
    # single one must be exact; pre-fix this band failed sporadically)
    for mcs in ("qam16_3_4", "qam64_2_3", "qam64_3_4"):
        for n_pay in (185, 189, 195):
            p2 = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
            b2 = encode_frame(p2, mcs)
            x2 = np.concatenate([np.zeros(150, np.complex64), b2,
                                 np.zeros(150, np.complex64)])
            f2 = decode_stream(x2)
            assert len(f2) == 1 and f2[0].psdu == p2, (mcs, n_pay)


def test_channel_table_matches_reference():
    """models/wlan/channels.py: the 67-channel table equals `channels.rs:1-72`
    entry by entry (derived arithmetic vs the reference's literal list), and
    the parse API mirrors its error semantics."""
    import re
    from pathlib import Path

    import pytest

    from futuresdr_tpu.models.wlan.channels import (CHANNELS, channel_to_freq,
                                                    freq_to_channel,
                                                    parse_channel)
    assert len(CHANNELS) == 67
    assert channel_to_freq(1) == 2412e6 and channel_to_freq(14) == 2484e6
    assert channel_to_freq(36) == 5180e6 and channel_to_freq(184) == 5920e6
    assert channel_to_freq(35) is None          # gaps stay gaps
    assert freq_to_channel(5860e6) == 172
    assert parse_channel("165") == 5825e6
    for bad in ("x", "35", "0"):
        with pytest.raises(ValueError, match="WLAN channel"):
            parse_channel(bad)
    ref = Path("/root/reference/examples/wlan/src/channels.rs")
    if ref.exists():                            # full parity check when present
        pairs = re.findall(r"\((\d+),\s*([\d.]+)e6\)", ref.read_text())
        assert CHANNELS == {int(c): float(f) * 1e6 for c, f in pairs}
