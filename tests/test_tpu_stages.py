"""TPU stage/pipeline tests (on the CPU jax backend in CI; same code runs on TPU).

Golden parity: fused stage chains must match the numpy/scipy CPU cores frame-for-frame,
including carry across frame boundaries (SURVEY §7 "determinism for tests").
"""

import numpy as np
import pytest
from scipy import signal as sps

import jax

from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import (Pipeline, fir_stage, fft_stage, mag2_stage,
                               rotator_stage, quad_demod_stage, moving_avg_stage)


def run_pipeline(pipe: Pipeline, x: np.ndarray, frame: int) -> np.ndarray:
    fn, carry = pipe.compile(frame)
    outs = []
    for i in range(0, len(x) - frame + 1, frame):
        carry, y = fn(carry, x[i:i + frame])
        outs.append(np.asarray(y))
    return np.concatenate(outs)


def test_fir_stage_matches_lfilter_across_frames():
    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    x = np.random.default_rng(0).standard_normal(8192).astype(np.float32)
    pipe = Pipeline([fir_stage(taps, fft_len=512)], np.float32)
    assert pipe.frame_multiple == 256   # hop L = fft_len/2
    y = run_pipeline(pipe, x, 1024)
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)


def test_fir_stage_complex_with_decim():
    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    x = (np.exp(1j * 2 * np.pi * 0.03 * np.arange(8192))).astype(np.complex64)
    pipe = Pipeline([fir_stage(taps, decim=4, fft_len=512)], np.complex64)
    assert pipe.frame_multiple == 4     # poly-decim path: multiple = D, not lcm(hop, D)
    assert pipe.out_items(1024) == 256
    y = run_pipeline(pipe, x, 1024)
    ref = sps.lfilter(taps, 1.0, x)[::4]
    np.testing.assert_allclose(y, ref[:len(y)], rtol=1e-3, atol=1e-4)


def test_fused_fir_fft_mag2_chain():
    """The north-star fusion: FIR → FFT → |x|² as ONE program."""
    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    n_fft = 256
    x = np.random.default_rng(1).standard_normal(16 * 1024).astype(np.complex64)
    pipe = Pipeline([fir_stage(taps), fft_stage(n_fft), mag2_stage()], np.complex64)
    assert pipe.out_dtype == np.float32
    y = run_pipeline(pipe, x, 4096)
    filtered = sps.lfilter(taps, 1.0, x)
    ref = np.abs(np.fft.fft(filtered[:len(y)].reshape(-1, n_fft), axis=1)) ** 2
    np.testing.assert_allclose(y, ref.reshape(-1), rtol=1e-2, atol=1e-2)


def test_rotator_stage_phase_continuity():
    pipe = Pipeline([rotator_stage(0.05)], np.complex64)
    x = np.ones(4096, dtype=np.complex64)
    y = run_pipeline(pipe, x, 512)
    ref = np.exp(1j * 0.05 * np.arange(4096))
    np.testing.assert_allclose(y, ref, rtol=1e-3, atol=1e-3)


def test_quad_demod_stage_carry():
    fs, fdev = 250e3, 5e3
    t = np.arange(8192) / fs
    msg = np.sin(2 * np.pi * 1e3 * t)
    iq = np.exp(1j * 2 * np.pi * fdev * np.cumsum(msg) / fs).astype(np.complex64)
    pipe = Pipeline([quad_demod_stage(fs / (2 * np.pi * fdev))], np.complex64)
    y = run_pipeline(pipe, iq, 1024)
    assert np.corrcoef(y[100:], msg[99:8191])[0, 1] > 0.999


def test_moving_avg_stage():
    frame_len = 64
    pipe = Pipeline([moving_avg_stage(frame_len, decay=0.5)], np.float32)
    x = np.ones(1024, dtype=np.float32)
    y = run_pipeline(pipe, x, 256)
    # EMA of ones converges to 1
    assert abs(y[-frame_len:].mean() - 1.0) < 1e-3


def test_lora_demod_stage():
    from futuresdr_tpu.ops import lora_demod_stage
    from futuresdr_tpu.models.lora.phy import _upchirp

    sf = 7
    n = 1 << sf
    symbols = np.array([0, 17, 64, 127, 3, 99], dtype=np.int64)
    sig = np.concatenate([_upchirp(n, int(s)) for s in symbols]).astype(np.complex64)
    pipe = Pipeline([lora_demod_stage(sf)], np.complex64)
    fn, carry = pipe.compile(len(sig))
    _, out = fn(carry, sig)
    np.testing.assert_array_equal(np.asarray(out), symbols)


def test_channelizer_stage_matches_block():
    from futuresdr_tpu.ops import channelizer_stage
    from futuresdr_tpu.blocks.pfb import pfb_default_taps
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource, VectorSink, PfbChannelizer

    N = 4
    taps = pfb_default_taps(N)
    rng = np.random.default_rng(8)
    x = (rng.standard_normal(4096) + 1j * rng.standard_normal(4096)).astype(np.complex64)

    pipe = Pipeline([channelizer_stage(N, taps)], np.complex64)
    y = run_pipeline(pipe, x, 1024).reshape(-1, N).T      # [N, t]

    fg = Flowgraph()
    src = VectorSource(x)
    chan = PfbChannelizer(N, taps)
    sinks = [VectorSink(np.complex64) for _ in range(N)]
    fg.connect_stream(src, "out", chan, "in")
    for i, s in enumerate(sinks):
        fg.connect_stream(chan, f"out{i}", s, "in")
    Runtime().run(fg)
    for c in range(N):
        ref = sinks[c].items()
        n = min(len(ref), y.shape[1])
        np.testing.assert_allclose(y[c, :n], ref[:n], rtol=1e-3, atol=1e-4)


def test_agc_stage_converges():
    from futuresdr_tpu.ops import agc_stage

    pipe = Pipeline([agc_stage(reference=1.0, rate=5.0, block=64)], np.complex64)
    x = (0.01 * np.exp(1j * 2 * np.pi * 0.01 * np.arange(32768))).astype(np.complex64)
    y = run_pipeline(pipe, x, 4096)
    assert abs(np.abs(y[-2000:]).mean() - 1.0) < 0.1


def test_pipeline_rate_math():
    taps = np.ones(16, dtype=np.float32)
    pipe = Pipeline([fir_stage(taps, decim=2, fft_len=128), fft_stage(64), mag2_stage()],
                    np.complex64)
    # input multiple: hop 64, decim 2, and fft 64 at post-decim rate → 128 input items
    assert pipe.frame_multiple == 128
    assert pipe.out_items(1024) == 512


def test_tpu_kernel_block_in_flowgraph():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource, VectorSink
    from futuresdr_tpu.tpu import TpuKernel

    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    data = np.random.default_rng(2).standard_normal(100_000).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    tk = TpuKernel([fir_stage(taps)], np.float32, frame_size=8192)
    snk = VectorSink(np.float32)
    fg.connect(src, tk, snk)
    Runtime().run(fg)
    got = snk.items()
    ref = sps.lfilter(taps, 1.0, data)
    assert len(got) >= (len(data) // 8192) * 8192
    np.testing.assert_allclose(got, ref[:len(got)], rtol=1e-4, atol=1e-5)


def test_tpu_kernel_spectrum_chain():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource, VectorSink
    from futuresdr_tpu.tpu import TpuKernel

    n_fft = 512
    tone = np.exp(1j * 2 * np.pi * 0.1 * np.arange(64 * 1024)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(tone)
    tk = TpuKernel([fft_stage(n_fft), mag2_stage()], np.complex64, frame_size=16 * 1024)
    snk = VectorSink(np.float32)
    fg.connect(src, tk, snk)
    Runtime().run(fg)
    spec = snk.items()[:n_fft]
    assert np.argmax(spec) == round(0.1 * n_fft)


def test_lti_merge_cascade_matches_unmerged():
    """A cascade of FIR stages collapses to ONE overlap-save (noble-identity merge)."""
    rng = np.random.default_rng(2)
    taps1 = firdes.lowpass(0.3, 48).astype(np.float32)
    taps2 = firdes.lowpass(0.25, 32).astype(np.float32)
    taps3 = firdes.lowpass(0.2, 64).astype(np.float32)
    stages = lambda: [fir_stage(taps1, fft_len=512), fir_stage(taps2, fft_len=512),
                      fir_stage(taps3, fft_len=512)]
    merged = Pipeline(stages(), np.float32)
    plain = Pipeline(stages(), np.float32, optimize=False)
    assert len(merged.stages) == 1 and len(plain.stages) == 3
    x = rng.standard_normal(16384).astype(np.float32)
    frame = int(np.lcm(merged.frame_multiple, plain.frame_multiple)) * 4
    y_m = run_pipeline(merged, x, frame)
    y_p = run_pipeline(plain, x, frame)
    np.testing.assert_allclose(y_m, y_p[:len(y_m)], rtol=1e-3, atol=1e-4)


def test_lti_merge_with_decimation():
    """(t1, d1)·(t2, d2) → (t1 * stuff(t2, d1), d1·d2) across frame boundaries."""
    rng = np.random.default_rng(3)
    taps1 = firdes.lowpass(0.2, 32).astype(np.float32)
    taps2 = firdes.lowpass(0.4, 24).astype(np.float32)
    stages = lambda: [fir_stage(taps1, decim=2, fft_len=512),
                      fir_stage(taps2, decim=3, fft_len=512)]
    merged = Pipeline(stages(), np.complex64)
    plain = Pipeline(stages(), np.complex64, optimize=False)
    assert len(merged.stages) == 1
    assert merged.ratio == plain.ratio
    x = (rng.standard_normal(36864) + 1j * rng.standard_normal(36864)).astype(np.complex64)
    frame = int(np.lcm(merged.frame_multiple, plain.frame_multiple)) * 2
    y_m = run_pipeline(merged, x, frame)
    y_p = run_pipeline(plain, x, frame)
    n = min(len(y_m), len(y_p))
    np.testing.assert_allclose(y_m[:n], y_p[:n], rtol=1e-3, atol=1e-4)


def test_lti_merge_complex_taps_gated_on_real_stream():
    """Complex-tap cascades only merge on complex streams (real streams take .real at
    each stage boundary, which merging would change)."""
    ct = (firdes.lowpass(0.2, 16) * np.exp(1j * 0.3 * np.arange(16))).astype(np.complex64)
    real_pipe = Pipeline([fir_stage(ct, fft_len=512), fir_stage(ct, fft_len=512)],
                         np.float32)
    cplx_pipe = Pipeline([fir_stage(ct, fft_len=512), fir_stage(ct, fft_len=512)],
                         np.complex64)
    assert len(real_pipe.stages) == 2      # NOT merged
    assert len(cplx_pipe.stages) == 1      # merged
    rng = np.random.default_rng(4)
    x = (rng.standard_normal(8192) + 1j * rng.standard_normal(8192)).astype(np.complex64)
    plain = Pipeline([fir_stage(ct, fft_len=512), fir_stage(ct, fft_len=512)],
                     np.complex64, optimize=False)
    y_m = run_pipeline(cplx_pipe, x, 2048)
    y_p = run_pipeline(plain, x, 2048)
    np.testing.assert_allclose(y_m, y_p[:len(y_m)], rtol=1e-3, atol=1e-4)


def test_lti_merge_tracks_stream_dtype():
    """Complex-tap FIRs AFTER a complex→real stage must not merge (real stream takes
    .real each boundary), even when the pipeline INPUT is complex."""
    ct = (firdes.lowpass(0.2, 16) * np.exp(1j * 0.3 * np.arange(16))).astype(np.complex64)
    pipe = Pipeline([quad_demod_stage(), fir_stage(ct, fft_len=512),
                     fir_stage(ct, fft_len=512)], np.complex64)
    assert len(pipe.stages) == 3       # NOT merged: stream is real after quad_demod
    rt = Pipeline([quad_demod_stage(), fir_stage(ct, fft_len=512),
                   fir_stage(ct, fft_len=512)], np.complex64, optimize=False)
    rng = np.random.default_rng(5)
    x = np.exp(1j * np.cumsum(0.1 * rng.standard_normal(8192))).astype(np.complex64)
    y_m = run_pipeline(pipe, x, 2048)
    y_p = run_pipeline(rt, x, 2048)
    np.testing.assert_allclose(y_m, y_p, rtol=1e-4, atol=1e-5)
