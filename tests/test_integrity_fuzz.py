"""No-silent-corruption integrity fuzz across the SNR range.

All three round-5 campaign findings were the same CLASS: a receiver
*accepting* something wrong under an unlucky draw (a Meshtastic wrong-key
decode surviving a hash collision, an M17 ghost LSF passing CRC16 by chance,
an M17 misframed ghost out-ranking the true frame). The family roundtrip
fuzzes assert success at GOOD SNR; this fuzz asserts the stronger invariant
the CRC/FEC-gated receivers are designed around, at EVERY SNR from clean to
hopeless: whatever a receiver ACCEPTS must be bit-correct — failure must be
silence (or a flagged bad CRC), never a corrupted payload presented as good.

The 16-bit-CRC families (zigbee, lora) carry an INHERENT chance-collision
floor the protocol cannot prevent (p ≈ 2^-16 per garbage candidate — the
same arithmetic that produced the M17 ghost LSF). Those tests therefore
assert hard only on same-length accepts (a collision that ALSO matches the
transmitted length is ~2^-22 and below campaign scale) and tolerate at most
ONE wrong-length chance accept per invocation — two or more is systematic.
The 24/32-bit-gated families (adsb, rattlegram polar+CRC32) assert hard.

Run by perf/fuzz_campaign.py with shifted seeds like every family fuzz; the
SNR is drawn per trial, so campaign scale explores the marginal region where
wrong-accepts would live."""

import numpy as np


def test_zigbee_accepts_are_exact_at_any_snr():
    """802.15.4: any frame surviving SHR correlation + CRC16 must equal a
    transmitted MPDU — across noise from negligible to frame-destroying."""
    from futuresdr_tpu.models.zigbee import (demodulate_stream, mac_deframe,
                                             mac_frame, modulate_frame)
    rng = np.random.default_rng(31500)
    for trial in range(8):
        n_pay = int(rng.integers(1, 90))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        psdu = mac_frame(payload, seq=trial)
        sig = modulate_frame(psdu)
        sigma = float(rng.uniform(0.01, 1.2))        # clean → hopeless
        x = np.concatenate([np.zeros(int(rng.integers(64, 400)), np.complex64),
                            sig, np.zeros(256, np.complex64)])
        x = (x * np.exp(1j * float(rng.uniform(0, 6.28)))
             + sigma * (rng.standard_normal(len(x))
                        + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
        timing = ("phase", "mm", "coherent")[int(rng.integers(0, 3))]
        odd_accepts = 0
        for got_psdu in demodulate_stream(x, timing=timing):
            # demodulate_stream emits RAW candidates (spurious correlation
            # windows included) — the CRC16 gate is mac_deframe, exactly how
            # the RX block and the roundtrip fuzz consume it. The integrity
            # invariant: anything that PASSES the CRC must be the
            # transmitted payload (modulo the documented CRC16 chance floor).
            got = mac_deframe(got_psdu)
            if got is None:
                continue
            if len(got) == len(payload):
                assert got == payload, (trial, sigma, timing)
            else:
                odd_accepts += 1
        assert odd_accepts <= 1, (trial, sigma, timing, odd_accepts)


def test_lora_crc_flagged_accepts_are_exact_at_any_snr():
    """LoRa explicit-header mode: any frame whose in-band CRC16 reports OK
    must carry the transmitted payload — at any SNR."""
    from futuresdr_tpu.models.lora.phy import (LoraParams, detect_frames,
                                               demodulate_frame,
                                               modulate_frame)
    rng = np.random.default_rng(31600)
    for trial in range(6):
        sf = int(rng.integers(7, 10))
        p = LoraParams(sf=sf, cr=int(rng.integers(1, 5)), has_crc=True)
        n_pay = int(rng.integers(1, 32))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        sigma = float(rng.uniform(0.02, 1.5))
        sig = np.concatenate([np.zeros(300, np.complex64),
                              modulate_frame(payload, p),
                              np.zeros(300, np.complex64)])
        sig = (sig + sigma * (rng.standard_normal(len(sig))
                              + 1j * rng.standard_normal(len(sig)))
               ).astype(np.complex64)
        odd_accepts = 0
        for start in detect_frames(sig, p):
            r = demodulate_frame(sig, start, p)
            if r is None:
                continue                       # failed decode: fine, silent
            got, crc_ok, _hdr = r
            if not crc_ok:
                continue
            if len(got) == len(payload):
                # a same-length CRC-OK accept must be exact
                assert got == payload, (trial, sf, sigma)
            else:
                odd_accepts += 1               # CRC16 chance floor (see module doc)
        assert odd_accepts <= 1, (trial, sf, sigma, odd_accepts)


def test_rattlegram_accepts_are_exact_at_any_snr():
    """Rattlegram: the BCH-protected call + polar-coded payload — an accept
    (non-None decode) must match the transmission at any SNR."""
    from futuresdr_tpu.models.rattlegram.modem import (ModemParams,
                                                       demodulate_auto,
                                                       modulate)
    rng = np.random.default_rng(31700)
    for trial in range(4):
        n_pay = 85
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        p = ModemParams(fec="polar")
        audio = modulate(payload, p, callsign="CALLSGN")
        sigma = float(rng.uniform(0.005, 0.6))
        x = (np.asarray(audio, np.float64)
             + sigma * rng.standard_normal(len(audio))).astype(np.float32)
        r = demodulate_auto(x, p)
        if r is None:
            continue                           # failed decode: fine, silent
        _cs, got = r
        assert got[:n_pay] == payload, (trial, sigma)


def test_adsb_crc_gated_accepts_are_exact_at_any_snr():
    """ADS-B: any demodulated frame whose Mode-S CRC validates must be the
    transmitted 112-bit message, across noise levels (the demodulator itself
    returns raw bits; the CRC24 gate is what an accept means downstream —
    `decoder.rs` drops bad-CRC frames the same way)."""
    from futuresdr_tpu.models.adsb import (crc24, detect_and_demodulate,
                                           modulate_frame)
    rng = np.random.default_rng(31800)
    hexes = ["8D4840D6202CC371C32CE0576098",
             "8D40621D58C382D690C8AC2863A7",
             "8D485020994409940838175B284F"]
    for trial in range(6):
        bits = np.unpackbits(np.frombuffer(
            bytes.fromhex(hexes[trial % len(hexes)]), np.uint8))
        sig = modulate_frame(bits)
        sigma = float(rng.uniform(0.01, 0.8))
        x = np.concatenate([
            sigma * np.abs(rng.standard_normal(int(rng.integers(50, 300)))),
            np.asarray(sig, np.float64) + sigma * np.abs(
                rng.standard_normal(len(sig))),
            sigma * np.abs(rng.standard_normal(200))]).astype(np.float32)
        for _start, got in detect_and_demodulate(x):
            if len(got) == 112 and crc24(got) == 0:
                np.testing.assert_array_equal(got, bits,
                                              err_msg=f"{trial} {sigma}")
