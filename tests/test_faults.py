"""Fault injection (runtime/faults.py) + transfer retry (ops/xfer.py).

Covers: seeded injector determinism, site addressing, env-spec arming, the
transient-vs-fatal classifier, H2D/D2H retry recovery with
``fsdr_retries_total`` billing, retry-budget and per-transfer-deadline
exhaustion, and the seeded fake-link fault model's same-seed → same-retry
contract (ISSUE 6 acceptance)."""

import numpy as np
import pytest

from futuresdr_tpu.config import config
from futuresdr_tpu.ops import xfer
from futuresdr_tpu.runtime import faults


@pytest.fixture
def fresh_plan():
    p = faults.reset()
    yield p
    faults.reset()


@pytest.fixture
def clean_link():
    yield
    xfer.set_fake_link()


def _retries(direction: str) -> float:
    return xfer._RETRIES.get(direction=direction)


# ---------------------------------------------------------------------------
# injector unit behavior
# ---------------------------------------------------------------------------

def _fire_pattern(inj, draws: int):
    out = []
    for _ in range(draws):
        try:
            inj.check()
            out.append(0)
        except faults.InjectedFault:
            out.append(1)
    return out


def test_injector_determinism_same_seed():
    a = faults.FaultPlan().arm("h2d", rate=0.3, seed=42)
    b = faults.FaultPlan().arm("h2d", rate=0.3, seed=42)
    pa, pb = _fire_pattern(a, 200), _fire_pattern(b, 200)
    assert pa == pb
    assert 0 < sum(pa) < 200              # actually Bernoulli, not constant
    c = faults.FaultPlan().arm("h2d", rate=0.3, seed=43)
    assert _fire_pattern(c, 200) != pa    # seed matters


def test_injector_streams_are_per_site():
    """Arming order / other sites never shift a site's draw stream."""
    p1 = faults.FaultPlan()
    i1 = p1.arm("h2d", rate=0.5, seed=7)
    p2 = faults.FaultPlan()
    p2.arm("d2h", rate=0.5, seed=7)       # extra site armed first
    i2 = p2.arm("h2d", rate=0.5, seed=7)
    assert _fire_pattern(i1, 64) == _fire_pattern(i2, 64)


def test_site_addressing_exact_beats_bare(fresh_plan):
    bare = fresh_plan.arm("work", rate=0.0)
    exact = fresh_plan.arm("work:blk_a", rate=1.0)
    assert fresh_plan.resolve("work", "blk_a") is exact
    assert fresh_plan.resolve("work", "blk_b") is bare
    assert fresh_plan.resolve("h2d") is None
    with pytest.raises(faults.InjectedFault):
        fresh_plan.maybe("work", "blk_a")
    fresh_plan.maybe("work", "blk_b")     # rate 0: never fires
    assert fresh_plan.counts() == {"work": 0, "work:blk_a": 1}


def test_max_faults_cap(fresh_plan):
    inj = fresh_plan.arm("dispatch", rate=1.0, max_faults=2)
    fired = sum(_fire_pattern(inj, 10))
    assert fired == 2 and inj.fired == 2 and inj.draws == 10


def test_disarm(fresh_plan):
    fresh_plan.arm("h2d", rate=1.0)
    fresh_plan.arm("d2h", rate=1.0)
    fresh_plan.disarm("h2d")
    fresh_plan.maybe("h2d")               # gone
    with pytest.raises(faults.TransientInjectedFault):
        fresh_plan.maybe("d2h")
    fresh_plan.disarm()
    assert not fresh_plan.armed()


def test_env_spec_parsing():
    p = faults.FaultPlan("seed=5; work:foo@1.0@1; h2d@0.25, bogus, x@y")
    assert set(p.counts()) == {"work:foo", "h2d"}
    wf = p.resolve("work", "foo")
    assert wf.rate == 1.0 and wf.max_faults == 1 and wf.seed == 5
    assert wf.transient is False          # work faults are not retryable
    h = p.resolve("h2d")
    assert h.rate == 0.25 and h.max_faults is None
    assert h.transient is True            # transfer faults default transient


def test_classification():
    assert xfer.classify_transfer_error(xfer.FakeLinkFault("x"))
    assert xfer.classify_transfer_error(
        faults.TransientInjectedFault("h2d", 1))
    assert not xfer.classify_transfer_error(faults.InjectedFault("work", 1))
    assert not xfer.classify_transfer_error(xfer.TransferError("already fatal"))
    assert xfer.classify_transfer_error(RuntimeError("UNAVAILABLE: link down"))
    assert xfer.classify_transfer_error(OSError("Connection reset by peer"))
    assert not xfer.classify_transfer_error(ValueError("bad dtype"))


# ---------------------------------------------------------------------------
# transfer retry: recovery, billing, budget/deadline exhaustion
# ---------------------------------------------------------------------------

def test_h2d_retry_recovers_bit_identical(fresh_plan, monkeypatch):
    monkeypatch.setattr(config(), "xfer_backoff", 0.0005)
    fresh_plan.arm("h2d", rate=1.0, max_faults=2)
    data = np.arange(4096, dtype=np.float32)
    before = _retries("h2d")
    dev = xfer.to_device(data)
    np.testing.assert_array_equal(xfer.to_host(dev), data)
    assert _retries("h2d") - before == 2  # one tick per retried attempt


def test_d2h_retry_recovers(fresh_plan, monkeypatch):
    monkeypatch.setattr(config(), "xfer_backoff", 0.0005)
    data = (np.arange(2048) + 1j * np.arange(2048)).astype(np.complex64)
    dev = xfer.to_device(data)
    fresh_plan.arm("d2h", rate=1.0, max_faults=1)
    before = _retries("d2h")
    np.testing.assert_array_equal(xfer.to_host(dev), data)
    assert _retries("d2h") - before == 1


def test_link_site_covers_both_directions(fresh_plan, monkeypatch):
    monkeypatch.setattr(config(), "xfer_backoff", 0.0005)
    inj = fresh_plan.arm("link", rate=1.0, max_faults=2)
    data = np.ones(1024, np.float32)
    np.testing.assert_array_equal(xfer.to_host(xfer.to_device(data)), data)
    assert inj.fired == 2                 # one per crossing, both recovered


def test_retry_budget_exhaustion_is_fatal(fresh_plan, monkeypatch):
    monkeypatch.setattr(config(), "xfer_retries", 2)
    monkeypatch.setattr(config(), "xfer_backoff", 0.0005)
    fresh_plan.arm("h2d", rate=1.0)       # unlimited faults
    with pytest.raises(xfer.TransferError, match="retry budget"):
        xfer.to_device(np.zeros(64, np.float32))


def test_transfer_deadline_is_fatal(fresh_plan, monkeypatch):
    monkeypatch.setattr(config(), "xfer_deadline", 0.001)
    monkeypatch.setattr(config(), "xfer_backoff", 0.25)   # one pause blows it
    fresh_plan.arm("h2d", rate=1.0)
    with pytest.raises(xfer.TransferError, match="deadline"):
        xfer.to_device(np.zeros(64, np.float32))


def test_fatal_faults_propagate_unwrapped(fresh_plan):
    fresh_plan.arm("h2d", rate=1.0, transient=False)
    with pytest.raises(faults.InjectedFault):
        xfer.to_device(np.zeros(64, np.float32))


# ---------------------------------------------------------------------------
# seeded fake link: same seed → same faults → same retry count (acceptance)
# ---------------------------------------------------------------------------

def _run_link_campaign(seed: int, n: int = 24) -> float:
    xfer.set_fake_link(fault_rate=0.25, fault_seed=seed)
    data = np.arange(1024, dtype=np.float32)
    before = _retries("h2d") + _retries("d2h")
    for i in range(n):
        np.testing.assert_array_equal(
            xfer.to_host(xfer.to_device(data + i)), data + i)
    return _retries("h2d") + _retries("d2h") - before


def test_fake_link_fault_determinism(clean_link, monkeypatch):
    monkeypatch.setattr(config(), "xfer_backoff", 0.0005)
    a = _run_link_campaign(seed=9)
    b = _run_link_campaign(seed=9)
    assert a == b and a > 0               # same seed, same billed retries
    c = _run_link_campaign(seed=10)
    assert c != a                         # the seed drives the fault pattern
