"""Curated on-chip validation (``FSDR_TEST_TPU=1`` + a live chip).

The main suite runs on a forced 8-device virtual CPU mesh (conftest.py). This
module is the live-tunnel practice established in round 5: the compute plane
driven on the REAL chip with TPU-calibrated workload sizes (the tunnel's
~100 ms dispatch latency makes CPU-sized workloads ill-conditioned) and
TPU-calibrated tolerances (MXU f32 accumulates differently than host f64).

Run: ``FSDR_TEST_TPU=1 python -m pytest tests/test_on_chip.py -q``
(expect ~100 ms per dispatch through the tunnel; the module is a no-op skip
in the normal CPU-forced suite).

These tests exist because two tunnel-only bug classes never show on the CPU
mesh: broken complex transfers (both directions since round 5 — the
closure-constant trap caught live in perf/wlan.py), and numerical deltas of
the MXU matmul-FFT path that only engages when ``jax.default_backend()`` is
tpu.
"""

import os

import numpy as np
import pytest

if not os.environ.get("FSDR_TEST_TPU"):
    pytest.skip("FSDR_TEST_TPU not set (suite runs on the virtual CPU mesh)",
                allow_module_level=True)

import jax  # noqa: E402

if jax.default_backend() != "tpu":
    pytest.skip("no live TPU behind FSDR_TEST_TPU", allow_module_level=True)

from futuresdr_tpu.dsp import firdes  # noqa: E402
from futuresdr_tpu.ops import fft_stage, fir_stage, mag2_stage  # noqa: E402
from futuresdr_tpu.ops.stages import Pipeline, _pallas_fir_wins  # noqa: E402
from futuresdr_tpu.ops.xfer import to_device, to_host  # noqa: E402
from futuresdr_tpu.tpu.instance import instance  # noqa: E402

# MXU f32 (and the bf16x3 matmul decomposition inside the four-step FFT) land
# within ~1e-4 relative of the host-f64 reference at these sizes; 1e-3 is the
# assertion line — loose enough for accumulation-order noise, tight enough
# that a wrong twiddle/layout (the bugs these tests exist for) blows through.
REL_TOL = 1e-3


def _rel_err(got, want):
    scale = max(1e-9, float(np.max(np.abs(want))))
    return float(np.max(np.abs(got - want))) / scale


def test_complex_xfer_roundtrip_exact():
    """H2D + D2H of complex64 through the shim is bit-exact (the raw path is
    UNIMPLEMENTED on the tunnel in both directions — docs/tpu_notes.md)."""
    rng = np.random.default_rng(1)
    host = (rng.standard_normal(4096)
            + 1j * rng.standard_normal(4096)).astype(np.complex64)
    dev = to_device(host)
    assert dev.dtype == np.complex64
    back = to_host(dev)
    np.testing.assert_array_equal(back, host)


@pytest.mark.parametrize("nt,dtype", [(16, np.float32), (48, np.float32),
                                      (64, np.float32), (16, np.complex64)])
def test_fir_auto_impl_matches_numpy(nt, dtype):
    """fir_stage(impl='auto') across the r5-measured routing boundaries
    (pallas for real <=48 taps, overlap-save beyond and for complex) against
    a host f64 convolution."""
    taps = firdes.lowpass(0.2, nt).astype(np.float32)
    st = fir_stage(taps)
    rng = np.random.default_rng(5)
    n = 8192
    if dtype == np.float32:
        host = rng.standard_normal(n).astype(np.float32)
    else:
        host = (rng.standard_normal(n)
                + 1j * rng.standard_normal(n)).astype(np.complex64)
    carry = jax.device_put(st.init_carry(host.dtype), instance().device)
    fn = jax.jit(st.fn)
    _, y = fn(carry, to_device(host, instance().device))
    got = to_host(y)
    want = np.convolve(np.concatenate([np.zeros(nt - 1, dtype), host]),
                       taps)[nt - 1:nt - 1 + n].astype(dtype)
    assert _rel_err(got, want) < REL_TOL


def test_fir_routing_is_the_measured_crossover():
    assert _pallas_fir_wins(16, False)
    assert _pallas_fir_wins(48, False)
    assert not _pallas_fir_wins(64, False)
    assert not _pallas_fir_wins(16, True)


def test_fir_carry_chunk_invariance_on_chip():
    """One 8192-frame vs two 4096-frames produce identical outputs (the
    carried tail is correct on the device path, not just the CPU mesh)."""
    taps = firdes.lowpass(0.25, 32).astype(np.float32)
    rng = np.random.default_rng(9)
    host = (rng.standard_normal(8192)
            + 1j * rng.standard_normal(8192)).astype(np.complex64)
    st = fir_stage(taps)
    fn = jax.jit(st.fn)

    c = jax.device_put(st.init_carry(host.dtype), instance().device)
    _, y_once = fn(c, to_device(host))

    c = jax.device_put(st.init_carry(host.dtype), instance().device)
    c, y_a = fn(c, to_device(host[:4096]))
    _, y_b = fn(c, to_device(host[4096:]))
    got = np.concatenate([to_host(y_a), to_host(y_b)])
    want = to_host(y_once)
    assert _rel_err(got, want) < 1e-6      # same kernel, same math: ~bit-equal


def test_mxu_fft_matches_numpy():
    """The four-step matmul FFT (auto-engaged on TPU at 2048) vs np.fft."""
    from futuresdr_tpu.ops import mxu_fft
    rng = np.random.default_rng(2)
    x = (rng.standard_normal((8, 2048))
         + 1j * rng.standard_normal((8, 2048))).astype(np.complex64)
    got = to_host(jax.jit(mxu_fft.fft)(to_device(x)))
    want = np.fft.fft(x)
    assert _rel_err(got, want) < REL_TOL


def test_mxu_ifft_roundtrip():
    from futuresdr_tpu.ops import mxu_fft
    rng = np.random.default_rng(3)
    x = (rng.standard_normal((4, 2048))
         + 1j * rng.standard_normal((4, 2048))).astype(np.complex64)
    y = jax.jit(lambda v: mxu_fft.ifft(mxu_fft.fft(v)))(to_device(x))
    assert _rel_err(to_host(y), x) < REL_TOL


def test_headline_pipeline_matches_numpy():
    """The bench chain (fir64 → fft2048 → |x|²) fused, one frame, vs a host
    reference of the same math."""
    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    pipe = Pipeline([fir_stage(taps), fft_stage(2048), mag2_stage()],
                    np.complex64)
    rng = np.random.default_rng(4)
    host = (rng.standard_normal(16384)
            + 1j * rng.standard_normal(16384)).astype(np.complex64)
    carry = jax.device_put(pipe.init_carry(), instance().device)
    _, y = jax.jit(pipe.fn())(carry, to_device(host))
    got = to_host(y)

    fir = np.convolve(np.concatenate([np.zeros(63, np.complex64), host]),
                      taps)[63:63 + 16384]
    spec = np.fft.fft(fir.reshape(-1, 2048), axis=1).reshape(-1)
    want = (spec.real ** 2 + spec.imag ** 2).astype(np.float32)
    assert _rel_err(got, want) < REL_TOL


def test_wlan_demod_body_recovers_bits_on_chip():
    """demod_body_jax (the fixed shim-riding entry point) on a clean
    constructed OFDM symbol: BPSK LLR signs must equal the transmitted bits.

    Regression scope: the round-5 live failure was complex arrays reaching
    jit as raw args/closure constants — this drives the repaired crossing
    end to end on the chip."""
    from futuresdr_tpu.models.wlan.consts import (CP_LEN, DATA_CARRIERS,
                                                  FFT_SIZE, PILOT_CARRIERS,
                                                  PILOT_VALUES, PILOT_POLARITY)
    from futuresdr_tpu.models.wlan.jax_demod import demod_body_jax

    rng = np.random.default_rng(6)
    bits = rng.integers(0, 2, 48)
    spec = np.zeros(FFT_SIZE, np.complex64)
    spec[DATA_CARRIERS % FFT_SIZE] = 2.0 * bits - 1.0
    spec[PILOT_CARRIERS % FFT_SIZE] = PILOT_VALUES * PILOT_POLARITY[1]
    sym = np.fft.ifft(spec).astype(np.complex64) * FFT_SIZE
    body = np.concatenate([sym[-CP_LEN:], sym])          # one 80-sample symbol
    llrs = demod_body_jax(body, np.ones(64, np.complex64), 1, 1,
                          0.0, 0.0, "bpsk")
    assert llrs.shape == (48,)
    assert np.all((llrs > 0) == (bits == 1))


def test_wlan_demod_head_runs_on_chip():
    """demod_head_jax end to end on the chip (complex in AND complex out —
    the H readback exercises the to_host split)."""
    from futuresdr_tpu.models.wlan.jax_demod import demod_head_jax
    rng = np.random.default_rng(7)
    head = (rng.standard_normal(208)
            + 1j * rng.standard_normal(208)).astype(np.complex64)
    H, llrs = demod_head_jax(head, 1e-4)
    assert H.shape == (64,) and H.dtype == np.complex64
    assert llrs.shape == (48,) and np.all(np.isfinite(llrs))
    assert np.all(np.isfinite(H))


def test_streamed_tpu_kernel_flowgraph():
    """The actor-runtime streamed path (host ring → H2D staging → fused chain
    → D2H → host ring) against the real chip: VectorSource → TpuKernel(fir)
    → VectorSink, output checked vs numpy. Drives h2d_needs_staging and the
    frame-chaining drain loop on real hardware."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.tpu import TpuKernel

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    rng = np.random.default_rng(8)
    n = 4 * 4096
    host = (rng.standard_normal(n)
            + 1j * rng.standard_normal(n)).astype(np.complex64)

    fg = Flowgraph()
    src = VectorSource(host)
    tk = TpuKernel([fir_stage(taps)], np.complex64, frame_size=4096,
                   frames_in_flight=2)
    snk = VectorSink(np.complex64)
    fg.connect(src, tk, snk)
    Runtime().run(fg)

    got = snk.items()
    assert got.shape == (n,)
    want = np.convolve(np.concatenate([np.zeros(31, np.complex64), host]),
                       taps)[31:31 + n].astype(np.complex64)
    assert _rel_err(got, want) < REL_TOL


def test_lora_dechirp_demod_on_chip():
    """lora_demod_stage (BASELINE #5's hot loop) on the real chip: modulated
    symbols round-trip through dechirp → MXU-era FFT → argmax exactly —
    integer symbol recovery leaves no tolerance question."""
    from futuresdr_tpu.models.lora.phy import LoraParams, _upchirp
    from futuresdr_tpu.ops.stages import lora_demod_stage

    sf = 7
    n = 1 << sf
    rng = np.random.default_rng(11)
    syms = rng.integers(0, n, 24)
    chips = np.concatenate([_upchirp(n, int(s)) for s in syms]) \
        .astype(np.complex64)
    st = lora_demod_stage(sf)
    carry = jax.device_put(st.init_carry(np.complex64), instance().device)
    _, got = jax.jit(st.fn)(carry, to_device(chips))
    np.testing.assert_array_equal(np.asarray(to_host(got)), syms)


def test_fm_front_end_on_chip():
    """BASELINE #3's front half (xlating FIR decimator → quadrature demod) on
    the chip vs the numpy twin: a real FM tone demodulates to its frequency."""
    from futuresdr_tpu.ops.stages import quad_demod_stage, xlating_fir_stage

    fs = 256_000.0
    decim = 4
    taps = firdes.lowpass(0.1, 48).astype(np.float32)
    offset = 2 * np.pi * 25_000.0 / fs           # shift the signal to baseband
    n = 16_384
    t = np.arange(n) / fs
    # FM tone at +25 kHz carrier, 1 kHz deviation payload
    dev = np.cumsum(2 * np.pi * 5_000.0 * np.cos(2 * np.pi * 1_000.0 * t) / fs)
    host = np.exp(1j * (2 * np.pi * 25_000.0 * t + dev)).astype(np.complex64)

    pipe = Pipeline([xlating_fir_stage(taps, -offset, decim),
                     quad_demod_stage(gain=1.0)], np.complex64)
    carry = jax.device_put(pipe.init_carry(), instance().device)
    _, y = jax.jit(pipe.fn())(carry, to_device(host))
    got = np.asarray(to_host(y))
    # steady-state demod ≈ instantaneous frequency of the payload: a 1 kHz
    # cosine with ±(2π·5000/fs·decim) swing
    body = got[64:]
    expect_peak = 2 * np.pi * 5_000.0 / fs * decim
    assert abs(float(np.max(body)) - expect_peak) < 0.15 * expect_peak
    assert abs(float(np.min(body)) + expect_peak) < 0.15 * expect_peak


def test_throttleless_tree_shapes_compile_on_chip():
    """A fused-stage pipeline with a rate change (decimating FIR) keeps its
    frame-multiple contract on device: two frames chunk-invariant vs one."""
    taps = firdes.lowpass(0.1, 32).astype(np.float32)
    st = fir_stage(taps, decim=4)
    rng = np.random.default_rng(12)
    host = (rng.standard_normal(8192)
            + 1j * rng.standard_normal(8192)).astype(np.complex64)
    fn = jax.jit(st.fn)
    c = jax.device_put(st.init_carry(host.dtype), instance().device)
    _, y_once = fn(c, to_device(host))
    c = jax.device_put(st.init_carry(host.dtype), instance().device)
    c, y_a = fn(c, to_device(host[:4096]))
    _, y_b = fn(c, to_device(host[4096:]))
    got = np.concatenate([to_host(y_a), to_host(y_b)])
    assert _rel_err(got, to_host(y_once)) < 1e-6


def test_wlan_full_rx_decode_on_chip():
    """The COMPLETE 802.11 RX (sync → equalize → per-axis demap → lax.scan
    Viterbi → descramble) decodes real frames on the chip, bit-matching the
    CPU behavior: clean frames decode perfectly across modulations, and the
    impaired-channel config the CPU suite passes (delay + AWGN + CFO,
    `test_wlan.test_phy_loopback_noise_cfo_delay`) decodes here too.
    FSDR_NO_NATIVE routes the Viterbi to the jitted scan so the trellis
    actually runs on the device."""
    import importlib

    prev = os.environ.get("FSDR_NO_NATIVE")
    os.environ["FSDR_NO_NATIVE"] = "1"
    try:
        from futuresdr_tpu.models.wlan import coding
        importlib.reload(coding)      # drop a cached native-viterbi handle
        from futuresdr_tpu.models.wlan.phy import decode_stream, encode_frame

        rng = np.random.default_rng(6)
        for mcs in ("bpsk_1_2", "qpsk_1_2", "qam16_1_2", "qam64_3_4"):
            psdu = bytes(rng.integers(0, 256, 160).astype(np.uint8))
            dec = decode_stream(encode_frame(psdu, mcs))
            assert len(dec) == 1 and dec[0].psdu == psdu, mcs
            assert dec[0].mcs.name == mcs

        psdu = b"The quick brown fox jumps over the lazy dog" * 4
        frame = encode_frame(psdu, "qpsk_1_2")
        sig = np.concatenate([np.zeros(777, np.complex64), frame,
                              np.zeros(500, np.complex64)])
        n = np.arange(len(sig))
        sig = sig * np.exp(1j * 2 * np.pi * 1e-4 * n)
        sig = sig + (0.02 * (rng.standard_normal(len(sig))
                             + 1j * rng.standard_normal(len(sig))))
        dec = decode_stream(sig.astype(np.complex64))
        assert len(dec) == 1 and dec[0].psdu == psdu
    finally:
        # restore the operator's setting AND drop the fallback-mode cache the
        # reload baked into the module, or every later test in this session
        # would silently run the numpy/scan Viterbi instead of the native one
        if prev is None:
            os.environ.pop("FSDR_NO_NATIVE", None)
        else:
            os.environ["FSDR_NO_NATIVE"] = prev
        importlib.reload(coding)
