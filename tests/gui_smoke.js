/* Headless execution smoke for gui/widgets.js (run under node when available;
 * tests/test_gui_js.py gates on it). Exercises the canvas-2D fallback paths and
 * the histogram/autorange math with stub DOM/canvas objects — no GPU needed. */
'use strict';

function stubCtx() {
  return {
    fillStyle: '', strokeStyle: '', font: '',
    fillRect() {}, strokeRect() {}, fillText() {}, beginPath() {}, moveTo() {},
    lineTo() {}, stroke() {}, fill() {}, setLineDash() {}, bezierCurveTo() {},
    drawImage() {}, putImageData() {},
    createImageData(w, h) { return {data: new Uint8ClampedArray(4 * w * h)}; },
    imageSmoothingEnabled: true,
  };
}
function stubCanvas(w, h) {
  return {
    width: w, height: h,
    getContext(kind) { return kind === '2d' ? stubCtx() : null; },  // no WebGL2
    addEventListener() {},
    getBoundingClientRect() { return {left: 0, top: 0}; },
  };
}
global.document = {
  createElement(tag) {
    if (tag === 'canvas') return stubCanvas(128, 128);
    return {appendChild() {}, style: {}, textContent: '', innerHTML: ''};
  },
};

const FSDR = require(process.argv[2] || '../futuresdr_tpu/gui/widgets.js');
let failures = 0;
function check(name, fn) {
  try { fn(); console.log('ok  ' + name); }
  catch (e) { failures++; console.log('FAIL ' + name + ': ' + e.message); }
}

check('Waterfall falls back to 2D without WebGL2', () => {
  const wf = new FSDR.Waterfall(stubCanvas(256, 128));
  // constructor-return fallback: the object IS the 2D sink (controls/zoom
  // state then operate on the renderer)
  if (!(wf instanceof FSDR.Waterfall2D)) throw new Error('expected 2D sink');
  wf.frame(new Float32Array(512).map((_, i) => Math.sin(i / 10)));
});

check('Waterfall2D renders a frame', () => {
  new FSDR.Waterfall2D(stubCanvas(256, 128)).frame(new Float32Array(1024));
});

check('TimeSink line + dots', () => {
  const data = new Float32Array(300).map((_, i) => Math.cos(i / 7));
  new FSDR.TimeSink(stubCanvas(256, 128), 'line').frame(data);
  new FSDR.TimeSink(stubCanvas(256, 128), 'dots').frame(data);
});

check('ConstellationSinkDensity accumulates + decays', () => {
  const sink = new FSDR.ConstellationSinkDensity(stubCanvas(128, 128), {bins: 64});
  const iq = new Float32Array(512);
  for (let i = 0; i < iq.length; i += 2) { iq[i] = 0.5; iq[i + 1] = -0.5; }
  sink.frame(iq);
  const inner = sink;   // constructor-return fallback: sink IS the 2D object
  const sum1 = inner.hist.reduce((a, b) => a + b, 0);
  if (sum1 <= 0) throw new Error('histogram empty after frame');
  sink.frame(new Float32Array(2));   // near-empty frame: decay dominates
  const sum2 = inner.hist.reduce((a, b) => a + b, 0);
  if (sum2 >= sum1) throw new Error('decay not applied');
});

check('FlowgraphCanvas lays out a two-block graph', () => {
  const fc = new FSDR.FlowgraphCanvas(stubCanvas(400, 200));
  fc.update({
    blocks: [
      {id: 0, instance_name: 'src', stream_inputs: [], stream_outputs: ['out'],
       message_inputs: []},
      {id: 1, instance_name: 'snk', stream_inputs: ['in'], stream_outputs: [],
       message_inputs: ['ctrl']},
    ],
    stream_edges: [[0, 'out', 1, 'in']],
    message_edges: [],
  });
  if (fc.boxes.length !== 2) throw new Error('expected 2 boxes');
});

check('Pmt helpers round-trip', () => {
  if (JSON.stringify(FSDR.Pmt.f64(1.5)) !== '{"F64":1.5}') throw new Error('f64');
  if (JSON.stringify(FSDR.Pmt.parse('U32', '7')) !== '{"U32":7}') throw new Error('parse');
});

check('GL LUT anchors interpolate monotonically in index', () => {
  // pure-function check of the colormap builder via a stub GL
  const calls = [];
  const gl = {
    TEXTURE0: 0, TEXTURE_2D: 1, RGBA: 2, UNSIGNED_BYTE: 3,
    CLAMP_TO_EDGE: 4, LINEAR: 5, TEXTURE_WRAP_S: 6, TEXTURE_WRAP_T: 7,
    TEXTURE_MIN_FILTER: 8, TEXTURE_MAG_FILTER: 9,
    createTexture() { return {}; }, activeTexture() {}, bindTexture() {},
    texParameteri() {},
    texImage2D(...a) { calls.push(a[8]); },
  };
  FSDR.GL.lutTexture(gl, 1);
  const data = calls[0];
  if (data.length !== 1024) throw new Error('LUT must be 256 RGBA texels');
  if (data[3] !== 255) throw new Error('alpha');
});

process.exit(failures ? 1 : 0);
