"""DSP block tests on real flowgraphs and the Mocker (reference: `tests/fir.rs`,
FFT/PFB behavior from block docs)."""

import numpy as np
import pytest
from scipy import signal as sps

from futuresdr_tpu import Flowgraph, Runtime, Mocker, Pmt
from futuresdr_tpu.blocks import (VectorSource, VectorSink, Fir, FirBuilder, Iir, Fft,
                                  SignalSource, QuadratureDemod, XlatingFir, Head,
                                  PfbChannelizer, PfbSynthesizer, PfbArbResampler, Agc)
from futuresdr_tpu.dsp import firdes


def test_fir_block_matches_lfilter():
    rng = np.random.default_rng(0)
    taps = firdes.lowpass(0.2, 64)
    data = rng.standard_normal(50_000).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    fir = Fir(taps, np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, fir, snk)
    Runtime().run(fg)
    ref = sps.lfilter(taps, 1.0, data.astype(np.float64))
    np.testing.assert_allclose(snk.items(), ref, rtol=1e-4, atol=1e-5)


def test_decimating_fir_block():
    rng = np.random.default_rng(1)
    taps = firdes.lowpass(0.1, 48)
    data = rng.standard_normal(20_000).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(data)
    fir = Fir(taps, np.complex64, decim=5)
    snk = VectorSink(np.complex64)
    fg.connect(src, fir, snk)
    Runtime().run(fg)
    ref = sps.lfilter(taps, 1.0, data)[::5]
    got = snk.items()
    assert len(got) == len(ref)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_resampling_fir_block():
    data = np.exp(1j * 2 * np.pi * 0.01 * np.arange(8000)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(data)
    fir = FirBuilder.resampling(3, 2, np.complex64)
    snk = VectorSink(np.complex64)
    fg.connect(src, fir, snk)
    Runtime().run(fg)
    got = snk.items()
    assert abs(len(got) - len(data) * 3 // 2) < 100
    # tone frequency scales by 2/3
    spec = np.abs(np.fft.fft(got[1000:5000] * np.hanning(4000)))
    peak = np.fft.fftfreq(4000)[np.argmax(spec)]
    assert abs(peak - 0.01 * 2 / 3) < 1e-3


def test_fft_block_roundtrip():
    rng = np.random.default_rng(2)
    n = 256
    data = (rng.standard_normal(8 * n) + 1j * rng.standard_normal(8 * n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(data)
    fwd = Fft(n, "forward")
    inv = Fft(n, "inverse")
    snk = VectorSink(np.complex64)
    fg.connect(src, fwd, inv, snk)
    Runtime().run(fg)
    # fwd(unnormalized) → inv(×N) = ×N² ... reference semantics: fft then ifft*n = n·x
    np.testing.assert_allclose(snk.items() / n, data, rtol=1e-3, atol=1e-3)


def test_fft_shift_and_normalize():
    n = 64
    tone = np.exp(1j * 2 * np.pi * 8 / n * np.arange(n)).astype(np.complex64)
    m = Mocker(Fft(n, "forward", shift=True, normalize=True))
    m.input("in", tone)
    m.init_output("out", n)
    m.run()
    out = m.output("out")
    assert np.argmax(np.abs(out)) == n // 2 + 8
    assert abs(np.max(np.abs(out)) - n / np.sqrt(n)) < 1e-3


def test_fft_window_reduces_leakage():
    n = 256
    # off-bin tone: rectangular FFT leaks broadly; a Hann window concentrates it
    tone = np.exp(1j * 2 * np.pi * (10.5 / n) * np.arange(n)).astype(np.complex64)
    rect = Mocker(Fft(n))
    rect.input("in", tone)
    rect.init_output("out", n)
    rect.run()
    hann = Mocker(Fft(n, window="hann"))
    hann.input("in", tone)
    hann.init_output("out", n)
    hann.run()
    far_rect = np.abs(rect.output("out"))[100:150].max()
    far_hann = np.abs(hann.output("out"))[100:150].max()
    assert far_hann < far_rect / 10


def test_signal_source_tone():
    fs, f = 48000.0, 1000.0
    fg = Flowgraph()
    src = SignalSource("complex", f, fs)
    head = Head(np.complex64, 4096)
    snk = VectorSink(np.complex64)
    fg.connect(src, head, snk)
    Runtime().run(fg)
    x = snk.items()
    assert len(x) == 4096
    spec = np.abs(np.fft.fft(x * np.hanning(len(x))))
    fpeak = np.fft.fftfreq(len(x), 1 / fs)[np.argmax(spec)]
    assert abs(fpeak - f) < fs / len(x)


def test_quadrature_demod_recovers_fm():
    fs = 250e3
    fdev = 5e3
    msg_f = 1e3
    n = 20000
    t = np.arange(n) / fs
    msg = np.sin(2 * np.pi * msg_f * t)
    phase = 2 * np.pi * fdev * np.cumsum(msg) / fs
    iq = np.exp(1j * phase).astype(np.complex64)
    m = Mocker(QuadratureDemod(gain=fs / (2 * np.pi * fdev)))
    m.input("in", iq)
    m.init_output("out", n)
    m.run()
    demod = m.output("out")[100:]
    ref = msg[99:n - 1]
    assert np.corrcoef(demod, ref)[0, 1] > 0.999


def test_xlating_fir_shifts_tone():
    fs = 1e6
    data = np.exp(1j * 2 * np.pi * 100e3 / fs * np.arange(20000)).astype(np.complex64)
    taps = firdes.lowpass(0.05, 64)
    m = Mocker(XlatingFir(taps, decim=4, offset_freq=100e3, sample_rate=fs))
    m.input("in", data)
    m.init_output("out", len(data))
    m.run()
    out = m.output("out")[200:]
    # tone moved to DC: nearly constant phase increments ≈ 0
    assert np.abs(np.angle(out[1:] * np.conj(out[:-1]))).max() < 1e-2


def test_pfb_channelizer_routes_tone():
    n_chan = 8
    fs = 1.0
    n = 1 << 14
    c = 3  # put a tone at center of channel 3
    x = np.exp(1j * 2 * np.pi * (c / n_chan) * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(x)
    chan = PfbChannelizer(n_chan)
    sinks = [VectorSink(np.complex64) for _ in range(n_chan)]
    fg.add(chan)
    fg.connect_stream(src, "out", chan, "in")
    for i, s in enumerate(sinks):
        fg.connect_stream(chan, f"out{i}", s, "in")
    Runtime().run(fg)
    powers = np.array([np.mean(np.abs(s.items()[64:]) ** 2) for s in sinks])
    assert np.argmax(powers) == c
    others = np.delete(powers, c)
    assert powers[c] > 100 * others.max()


def test_pfb_chain_channelize_synthesize():
    """Analysis → synthesis should approximately reconstruct (within filter delay)."""
    n_chan = 4
    n = 1 << 12
    rng = np.random.default_rng(5)
    x = np.exp(1j * 2 * np.pi * 0.07 * np.arange(n)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(x)
    chan = PfbChannelizer(n_chan)
    synth = PfbSynthesizer(n_chan)
    snk = VectorSink(np.complex64)
    fg.connect_stream(src, "out", chan, "in")
    for i in range(n_chan):
        fg.connect_stream(chan, f"out{i}", synth, f"in{i}")
    fg.connect_stream(synth, "out", snk, "in")
    Runtime().run(fg)
    y = snk.items()
    assert len(y) > n // 2
    # reconstructed tone should dominate at the same frequency
    w = 2048
    spec = np.abs(np.fft.fft(y[256:256 + w] * np.hanning(w)))
    peak = np.fft.fftfreq(w)[np.argmax(spec)]
    assert abs(abs(peak) - 0.07) < 2e-3


def test_pfb_arb_resampler_rate():
    rate = 1.37
    n = 8192
    x = np.exp(1j * 2 * np.pi * 0.02 * np.arange(n)).astype(np.complex64)
    m = Mocker(PfbArbResampler(rate))
    m.input("in", x)
    m.init_output("out", int(n * rate) + 64)
    m.run()
    y = m.output("out")
    assert abs(len(y) - n * rate) < 64
    spec = np.abs(np.fft.fft(y[500:4596] * np.hanning(4096)))
    peak = abs(np.fft.fftfreq(4096)[np.argmax(spec)])
    assert abs(peak - 0.02 / rate) < 1e-3


def test_agc_converges():
    x = (0.01 * np.exp(1j * 2 * np.pi * 0.01 * np.arange(30000))).astype(np.complex64)
    m = Mocker(Agc(reference=1.0, adjustment_rate=2e-2))
    m.input("in", x)
    m.init_output("out", len(x))
    m.run()
    y = m.output("out")
    assert abs(np.abs(y[-1000:]).mean() - 1.0) < 0.05


def test_agc_block_mode():
    x = (0.01 * np.exp(1j * 2 * np.pi * 0.01 * np.arange(60000))).astype(np.complex64)
    m = Mocker(Agc(reference=1.0, adjustment_rate=2e-2, mode="block"))
    m.input("in", x)
    m.init_output("out", len(x))
    m.run()
    y = m.output("out")
    assert abs(np.abs(y[-1000:]).mean() - 1.0) < 0.05


def test_iir_block():
    b, a = sps.butter(2, 0.3)
    data = np.random.default_rng(6).standard_normal(10_000).astype(np.float32)
    m = Mocker(Iir(b, a, np.float32))
    m.input("in", data)
    m.init_output("out", len(data))
    m.run()
    np.testing.assert_allclose(m.output("out"),
                               sps.lfilter(b, a, data).astype(np.float32), rtol=1e-3, atol=1e-4)
