"""Audio OFDM modem (rattlegram-role) tests."""

import numpy as np
import pytest

from futuresdr_tpu.models.rattlegram import mls, Modem, ModemParams, modulate, demodulate


def test_mls_properties():
    seq = mls()                      # length 63
    assert len(seq) == 63
    pm = seq.astype(np.int8) * 2 - 1
    # ML sequences: near-perfect cyclic autocorrelation
    for lag in range(1, 63):
        assert abs(np.sum(pm * np.roll(pm, lag))) <= 1


def test_modem_clean_roundtrip():
    m = Modem(payload_size=64)
    audio = m.tx(b"rattle the speaker with data")
    got = m.rx(np.concatenate([np.zeros(1234, np.float32), audio,
                               np.zeros(500, np.float32)]))
    assert got == b"rattle the speaker with data"


def test_modem_noise_and_scale():
    rng = np.random.default_rng(0)
    m = Modem(payload_size=48)
    audio = 0.3 * m.tx(b"quiet but still decodable")
    audio = np.concatenate([np.zeros(777, np.float32), audio, np.zeros(100, np.float32)])
    audio = (audio + 0.01 * rng.standard_normal(len(audio))).astype(np.float32)
    assert m.rx(audio) == b"quiet but still decodable"


def test_modem_flowgraph_loopback():
    from futuresdr_tpu import Flowgraph, Runtime, Pmt
    from futuresdr_tpu.blocks import Apply
    from futuresdr_tpu.models.rattlegram import ModemTransmitter, ModemReceiver

    rng = np.random.default_rng(3)
    fg = Flowgraph()
    tx = ModemTransmitter(payload_size=48)
    chan = Apply(lambda x: (0.5 * x + 0.01 * rng.standard_normal(len(x))
                            ).astype(np.float32), np.float32)
    rx = ModemReceiver(payload_size=48)
    fg.connect(tx, chan, rx)
    payloads = [f"acoustic packet {i}".encode() for i in range(3)]
    rt = Runtime()
    running = rt.start(fg)
    for p in payloads:
        rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.blob(p)))
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()
    assert rx.frames == payloads


def test_modem_rejects_garbage():
    m = Modem(payload_size=32)
    rng = np.random.default_rng(1)
    assert m.rx(rng.standard_normal(16000).astype(np.float32)) is None
