"""Audio OFDM modem (rattlegram-role) tests."""

import numpy as np
import pytest

from futuresdr_tpu.models.rattlegram import mls, Modem, ModemParams, modulate, demodulate


def test_mls_properties():
    seq = mls()                      # length 63
    assert len(seq) == 63
    pm = seq.astype(np.int8) * 2 - 1
    # ML sequences: near-perfect cyclic autocorrelation
    for lag in range(1, 63):
        assert abs(np.sum(pm * np.roll(pm, lag))) <= 1


def test_modem_clean_roundtrip():
    m = Modem(payload_size=64)
    audio = m.tx(b"rattle the speaker with data")
    got = m.rx(np.concatenate([np.zeros(1234, np.float32), audio,
                               np.zeros(500, np.float32)]))
    assert got == b"rattle the speaker with data"


def test_modem_noise_and_scale():
    rng = np.random.default_rng(0)
    m = Modem(payload_size=48)
    audio = 0.3 * m.tx(b"quiet but still decodable")
    audio = np.concatenate([np.zeros(777, np.float32), audio, np.zeros(100, np.float32)])
    audio = (audio + 0.01 * rng.standard_normal(len(audio))).astype(np.float32)
    assert m.rx(audio) == b"quiet but still decodable"


def test_modem_flowgraph_loopback():
    from futuresdr_tpu import Flowgraph, Runtime, Pmt
    from futuresdr_tpu.blocks import Apply
    from futuresdr_tpu.models.rattlegram import ModemTransmitter, ModemReceiver

    rng = np.random.default_rng(3)
    fg = Flowgraph()
    tx = ModemTransmitter(payload_size=48)
    chan = Apply(lambda x: (0.5 * x + 0.01 * rng.standard_normal(len(x))
                            ).astype(np.float32), np.float32)
    rx = ModemReceiver(payload_size=48)
    fg.connect(tx, chan, rx)
    payloads = [f"acoustic packet {i}".encode() for i in range(3)]
    rt = Runtime()
    running = rt.start(fg)
    for p in payloads:
        rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.blob(p)))
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()
    assert rx.frames == payloads


def test_modem_rejects_garbage():
    m = Modem(payload_size=32)
    rng = np.random.default_rng(1)
    assert m.rx(rng.standard_normal(16000).astype(np.float32)) is None


def test_polar_fec_all_modes_loopback():
    """ModemParams(fec="polar") — the reference's actual pipeline (xorshift
    scramble → systematic polar with CRC32-aided SCL-32, `encoder.rs:162-180`)
    — loops back at every operation mode's payload capacity."""
    from futuresdr_tpu.models.rattlegram import Modem, ModemParams
    rng = np.random.default_rng(0)
    for size in (85, 128, 170):                    # Mode16 / Mode15 / Mode14
        m = Modem(payload_size=size, params=ModemParams(fec="polar"))
        payload = (((np.arange(size) * 7 + 3) % 251).astype(np.uint8) + 1).tobytes()
        audio = m.tx(payload)
        x = np.concatenate([np.zeros(500, np.float32), audio,
                            np.zeros(500, np.float32)])
        x = (x + 0.02 * rng.standard_normal(len(x))).astype(np.float32)
        assert m.rx(x) == payload, size


def test_polar_fec_outdecodes_conv():
    """At noise where the K=7 conv path collapses, SCL-32 + CRC arbitration
    still decodes — the reason the reference ships polar."""
    from futuresdr_tpu.models.rattlegram import Modem, ModemParams
    payload = b"polar fec over the audio modem!"
    wins = {"conv": 0, "polar": 0}
    for fec in wins:
        m = Modem(payload_size=85, params=ModemParams(fec=fec))
        for t in range(6):
            r2 = np.random.default_rng(100 + t)
            audio = m.tx(payload)
            x = np.concatenate([np.zeros(300, np.float32), audio,
                                np.zeros(300, np.float32)])
            x = (x + 0.1 * r2.standard_normal(len(x))).astype(np.float32)
            wins[fec] += m.rx(x) == payload
    assert wins["polar"] >= 5, wins
    assert wins["polar"] > wins["conv"], wins


def test_polar_fec_config_validation():
    """Config errors surface at build time: unknown fec names and payload sizes
    beyond the largest operation mode are rejected immediately."""
    from futuresdr_tpu.models.rattlegram import Modem, ModemParams
    with pytest.raises(ValueError, match="fec"):
        ModemParams(fec="Polar")
    with pytest.raises(ValueError, match="170"):
        Modem(payload_size=200, params=ModemParams(fec="polar"))
    Modem(payload_size=200)                        # conv: any size is fine


def test_in_band_metadata_auto_rx():
    """In-band metadata (`encoder.rs:144-145` meta_data role): BPSK BCH(255,71)
    symbols carry callsign + operation mode, so the receiver sizes the polar
    decode from the air — no a-priori payload size."""
    from futuresdr_tpu.models.rattlegram import Modem, ModemParams
    from futuresdr_tpu.models.rattlegram.modem import (demodulate_auto, _base37,
                                                       _base37_str)
    for cs in ("N0CALL", "SP5WWP", "X", "DF9XYZ 1"):
        assert _base37_str(_base37(cs)) == cs.upper().rstrip()

    rng = np.random.default_rng(1)
    p = ModemParams(fec="polar")
    for size, pl in ((85, b"small"), (128, b"medium sized payload"),
                     (170, b"large payload rides mode 14")):
        m = Modem(payload_size=size, params=p, callsign="DF9XYZ")
        x = np.concatenate([np.zeros(300, np.float32), m.tx(pl),
                            np.zeros(300, np.float32)])
        x = (x + 0.05 * rng.standard_normal(len(x))).astype(np.float32)
        cs, got = demodulate_auto(x, p)      # NB: no size passed anywhere
        assert cs == "DF9XYZ" and got.rstrip(b"\x00") == pl, (size, cs)
        assert m.rx_auto(x) == ("DF9XYZ", pl)

    # config guards: metadata requires the polar pipeline (mode field)
    with pytest.raises(ValueError, match="polar"):
        Modem(payload_size=85, callsign="N0CALL")
    with pytest.raises(ValueError, match="polar"):
        demodulate_auto(np.zeros(4096, np.float32), ModemParams())
    # erasing HALF the metadata symbols still decodes — BCH(255,71) designed
    # distance 47 + OSD handles erasures; that robustness is the point
    m = Modem(payload_size=85, params=p, callsign="N0CALL")
    audio = m.tx(b"x")
    erased = audio.copy()
    erased[m.params.sym_len:3 * m.params.sym_len] = 0.0
    assert demodulate_auto(erased, p) is not None
    # but confidently-random metadata must fail the CRC16 gate, not pass garbage
    garbled = audio.copy()
    sl = m.params.sym_len
    garbled[sl:5 * sl] = 0.5 * rng.standard_normal(4 * sl).astype(np.float32)
    assert demodulate_auto(garbled, p) is None


def test_metadata_modem_fixed_rx_paths_still_work():
    """A callsign-equipped Modem's rx()/rx_all() skip the metadata symbols, so
    the fixed-size paths decode their own tx() too; callsign input validation
    rejects non-base37 characters and overlong signs."""
    from futuresdr_tpu.models.rattlegram import Modem, ModemParams
    from futuresdr_tpu.models.rattlegram.modem import _base37
    m = Modem(payload_size=85, params=ModemParams(fec="polar"), callsign="N0CALL")
    rng = np.random.default_rng(5)
    parts = [np.zeros(200, np.float32)]
    for pl in (b"first", b"second"):
        parts += [m.tx(pl), np.zeros(300, np.float32)]
    x = np.concatenate(parts)
    x = (x + 0.04 * rng.standard_normal(len(x))).astype(np.float32)
    # rx() decodes the strongest single burst; rx_all() returns both in order
    assert m.rx(x[:200 + m.burst_samples() + 200]) == b"first"
    assert [pl for _, pl in m.rx_all(x)] == [b"first", b"second"]

    with pytest.raises(ValueError, match="base-37|9 char"):
        _base37("LONGCALL10")
    with pytest.raises(ValueError, match="base-37"):
        _base37("٥")                       # non-ASCII digit must not pass


def test_auto_receiver_block_mixed_modes():
    """ModemReceiver(auto=True): one receiver block decodes senders of
    DIFFERENT operation modes from the stream, posting (callsign, payload)."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.rattlegram import (Modem, ModemParams,
                                                 ModemReceiver)
    rng = np.random.default_rng(9)
    p = ModemParams(fec="polar")
    small = Modem(payload_size=85, params=p, callsign="N0CALL")
    large = Modem(payload_size=170, params=p, callsign="SP5WWP")
    parts = [np.zeros(400, np.float32)]
    for m, pl in ((small, b"small mode burst"), (large, b"large mode burst"),
                  (small, b"small again")):
        parts += [m.tx(pl), np.zeros(500, np.float32)]
    x = np.concatenate(parts)
    x = (x + 0.04 * rng.standard_normal(len(x))).astype(np.float32)

    rx = ModemReceiver(params=p, auto=True)
    fg = Flowgraph()
    fg.connect_stream(VectorSource(x), "out", rx, "in")
    Runtime().run(fg)
    assert rx.frames == [("N0CALL", b"small mode burst"),
                         ("SP5WWP", b"large mode burst"),
                         ("N0CALL", b"small again")], rx.frames

    with pytest.raises(ValueError, match="polar"):
        ModemReceiver(auto=True)                  # conv params: rejected


def test_noise_symbol_prefix():
    """noise_symbols prepends squelch/AGC-opening symbols (`encoder.rs:308`)
    of comparable power that do not disturb sync or decoding."""
    from futuresdr_tpu.models.rattlegram.modem import (ModemParams, demodulate,
                                                       modulate)
    p = ModemParams()
    payload = b"squelch opener".ljust(32, b"\x00")
    plain = modulate(payload, p)
    noisy = modulate(payload, p, noise_symbols=5)
    assert len(noisy) == len(plain) + 5 * p.sym_len
    pw_prefix = float(np.mean(noisy[:5 * p.sym_len] ** 2))
    pw_data = float(np.mean(plain ** 2))
    assert 0.3 * pw_data < pw_prefix < 3 * pw_data
    x = np.concatenate([np.zeros(400, np.float32), noisy,
                        np.zeros(200, np.float32)]).astype(np.float32)
    assert demodulate(x, 32, p) == payload


def test_random_config_roundtrip_fuzz():
    """Seeded sweep over random modem configs (fec, payload size/content,
    metadata, noise prefix): every combination loops back under mild noise."""
    from futuresdr_tpu.models.rattlegram import Modem, ModemParams
    rng = np.random.default_rng(4096)
    for trial in range(12):
        fec = ("conv", "polar")[int(rng.integers(0, 2))]
        size = int(rng.integers(1, 171)) if fec == "polar" else int(rng.integers(1, 200))
        callsign = ("N0CALL" if fec == "polar" and rng.integers(0, 2) else None)
        m = Modem(payload_size=size, params=ModemParams(fec=fec), callsign=callsign)
        n_pay = int(rng.integers(1, size + 1))
        payload = (rng.integers(1, 256, n_pay).astype(np.uint8)).tobytes()
        audio = m.tx(payload)
        x = np.concatenate([np.zeros(int(rng.integers(50, 900)), np.float32),
                            audio, np.zeros(200, np.float32)])
        x = (x + 0.02 * rng.standard_normal(len(x))).astype(np.float32)
        if callsign:
            r = m.rx_auto(x)
            assert r is not None and r == (callsign, payload), (trial, fec, size)
        else:
            assert m.rx(x) == payload, (trial, fec, size, n_pay)
