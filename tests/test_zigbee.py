"""ZigBee 802.15.4 tests: chip table sanity, CRC, clean + impaired loopback."""

import numpy as np
import pytest

from futuresdr_tpu.models.zigbee import (CHIP_SEQUENCES, modulate_frame,
                                         demodulate_stream, mac_frame, mac_deframe,
                                         crc16_802154)


def test_chip_table_distances():
    """All 16 sequences must be mutually far apart (DSSS property)."""
    pm = CHIP_SEQUENCES.astype(np.int8) * 2 - 1
    g = pm @ pm.T
    off_diag = g - np.diag(np.diag(g))
    assert (np.diag(g) == 32).all()
    assert np.abs(off_diag).max() <= 8


def test_crc_known_behavior():
    assert crc16_802154(b"") == 0x0000
    c1 = crc16_802154(b"\x01\x02\x03")
    assert 0 <= c1 <= 0xFFFF
    assert c1 != crc16_802154(b"\x01\x02\x04")


def test_mac_roundtrip():
    m = mac_frame(b"zigbee payload", seq=7)
    assert mac_deframe(m) == b"zigbee payload"
    bad = bytearray(m)
    bad[4] ^= 0x10
    assert mac_deframe(bytes(bad)) is None


def test_loopback_clean():
    psdu = mac_frame(b"hello 802.15.4")
    sig = modulate_frame(psdu)
    frames = demodulate_stream(np.concatenate(
        [np.zeros(333, np.complex64), sig, np.zeros(200, np.complex64)]))
    assert len(frames) == 1
    assert frames[0] == psdu
    assert mac_deframe(frames[0]) == b"hello 802.15.4"


def test_loopback_noise_and_phase():
    rng = np.random.default_rng(0)
    psdu = mac_frame(bytes(range(40)))
    sig = modulate_frame(psdu)
    sig = np.concatenate([np.zeros(100, np.complex64), sig, np.zeros(100, np.complex64)])
    sig = sig * np.exp(1j * 1.234)                      # arbitrary phase rotation
    sig = (sig + 0.1 * (rng.standard_normal(len(sig))
                        + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    frames = demodulate_stream(sig)
    assert len(frames) == 1 and frames[0] == psdu


def test_multiple_frames():
    parts = []
    psdus = [mac_frame(f"frame {i}".encode(), seq=i) for i in range(3)]
    for p in psdus:
        parts += [modulate_frame(p), np.zeros(300, np.complex64)]
    frames = demodulate_stream(np.concatenate(parts))
    assert frames == psdus


def test_mm_timing_mode_realtime_with_drift():
    """Block-vectorized Mueller-Muller mode (VERDICT r1 item 10): 20 drifting-clock
    frames decode, and throughput clears the 4 Mchip/s real-time bar."""
    import time
    rng = np.random.default_rng(0)
    frames = [bytes(rng.integers(0, 256, 20, dtype=np.uint8).tolist())
              for _ in range(20)]
    parts = []
    for f in frames:
        parts.append(np.zeros(200, np.complex64))
        parts.append(modulate_frame(f))
    parts.append(np.zeros(200, np.complex64))
    sig = np.concatenate(parts)
    ppm = 50
    t_new = np.arange(int(len(sig) / (1 + ppm * 1e-6))) * (1 + ppm * 1e-6)
    i = np.clip(t_new.astype(int), 0, len(sig) - 2)
    fr = t_new - i
    x = ((1 - fr) * sig[i] + fr * sig[i + 1]).astype(np.complex64)
    x = x + 0.02 * (rng.standard_normal(len(x))
                    + 1j * rng.standard_normal(len(x))).astype(np.complex64)
    t0 = time.perf_counter()
    got = demodulate_stream(x, timing="mm")
    rate = len(x) / (time.perf_counter() - t0) / 1e6
    n_ok = sum(1 for f in frames if f in got)
    assert n_ok >= 18, f"only {n_ok}/20 frames decoded under 50ppm drift"
    import os
    if os.environ.get("FSDR_PERF_ASSERT"):    # wall-clock: opt-in (flaky on shared CI)
        assert rate > 2.0, f"MM mode too slow: {rate:.2f} Msps"  # 5+ typical
