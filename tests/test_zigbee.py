"""ZigBee 802.15.4 tests: chip table sanity, CRC, clean + impaired loopback."""

import numpy as np
import pytest

from futuresdr_tpu.models.zigbee import (CHIP_SEQUENCES, modulate_frame,
                                         demodulate_stream, mac_frame, mac_deframe,
                                         crc16_802154)


def test_chip_table_distances():
    """All 16 sequences must be mutually far apart (DSSS property)."""
    pm = CHIP_SEQUENCES.astype(np.int8) * 2 - 1
    g = pm @ pm.T
    off_diag = g - np.diag(np.diag(g))
    assert (np.diag(g) == 32).all()
    assert np.abs(off_diag).max() <= 8


def test_crc_known_behavior():
    assert crc16_802154(b"") == 0x0000
    c1 = crc16_802154(b"\x01\x02\x03")
    assert 0 <= c1 <= 0xFFFF
    assert c1 != crc16_802154(b"\x01\x02\x04")


def test_mac_roundtrip():
    m = mac_frame(b"zigbee payload", seq=7)
    assert mac_deframe(m) == b"zigbee payload"
    bad = bytearray(m)
    bad[4] ^= 0x10
    assert mac_deframe(bytes(bad)) is None


def test_loopback_clean():
    psdu = mac_frame(b"hello 802.15.4")
    sig = modulate_frame(psdu)
    frames = demodulate_stream(np.concatenate(
        [np.zeros(333, np.complex64), sig, np.zeros(200, np.complex64)]))
    assert len(frames) == 1
    assert frames[0] == psdu
    assert mac_deframe(frames[0]) == b"hello 802.15.4"


def test_loopback_noise_and_phase():
    rng = np.random.default_rng(0)
    psdu = mac_frame(bytes(range(40)))
    sig = modulate_frame(psdu)
    sig = np.concatenate([np.zeros(100, np.complex64), sig, np.zeros(100, np.complex64)])
    sig = sig * np.exp(1j * 1.234)                      # arbitrary phase rotation
    sig = (sig + 0.1 * (rng.standard_normal(len(sig))
                        + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    frames = demodulate_stream(sig)
    assert len(frames) == 1 and frames[0] == psdu


def test_multiple_frames():
    parts = []
    psdus = [mac_frame(f"frame {i}".encode(), seq=i) for i in range(3)]
    for p in psdus:
        parts += [modulate_frame(p), np.zeros(300, np.complex64)]
    frames = demodulate_stream(np.concatenate(parts))
    assert frames == psdus


def test_mm_timing_mode_realtime_with_drift():
    """Block-vectorized Mueller-Muller mode (VERDICT r1 item 10): 20 drifting-clock
    frames decode, and throughput clears the 4 Mchip/s real-time bar."""
    import time
    rng = np.random.default_rng(0)
    frames = [bytes(rng.integers(0, 256, 20, dtype=np.uint8).tolist())
              for _ in range(20)]
    parts = []
    for f in frames:
        parts.append(np.zeros(200, np.complex64))
        parts.append(modulate_frame(f))
    parts.append(np.zeros(200, np.complex64))
    sig = np.concatenate(parts)
    ppm = 50
    t_new = np.arange(int(len(sig) / (1 + ppm * 1e-6))) * (1 + ppm * 1e-6)
    i = np.clip(t_new.astype(int), 0, len(sig) - 2)
    fr = t_new - i
    x = ((1 - fr) * sig[i] + fr * sig[i + 1]).astype(np.complex64)
    x = x + 0.02 * (rng.standard_normal(len(x))
                    + 1j * rng.standard_normal(len(x))).astype(np.complex64)
    t0 = time.perf_counter()
    got = demodulate_stream(x, timing="mm")
    rate = len(x) / (time.perf_counter() - t0) / 1e6
    n_ok = sum(1 for f in frames if f in got)
    assert n_ok >= 18, f"only {n_ok}/20 frames decoded under 50ppm drift"
    import os
    if os.environ.get("FSDR_PERF_ASSERT"):    # wall-clock: opt-in (flaky on shared CI)
        assert rate > 2.0, f"MM mode too slow: {rate:.2f} Msps"  # 5+ typical


def test_coherent_demod_clean_and_impaired():
    """Coherent burst-synchronized RX: clean, CFO within pull-in, phase, noise."""
    psdu = mac_frame(b"coherent zigbee!")
    sig = np.concatenate([np.zeros(100, np.complex64), modulate_frame(psdu),
                          np.zeros(100, np.complex64)])
    rng = np.random.default_rng(0)
    assert demodulate_stream(sig, timing="coherent") == [psdu]
    for cfo, namp in ((0.004, 0.15), (-0.003, 0.25), (0.006, 0.3)):
        x = sig * np.exp(1j * (0.7 + cfo * np.arange(len(sig))))
        x = (x + namp * (rng.standard_normal(len(x))
                         + 1j * rng.standard_normal(len(x))) / np.sqrt(2)
             ).astype(np.complex64)
        assert demodulate_stream(x, timing="coherent") == [psdu], (cfo, namp)


def test_coherent_beats_discriminator_at_low_snr():
    """The coherent matched receiver's raison d'etre: at ~0 dB SNR it still
    decodes every burst while the discriminator paths (which square the noise)
    have collapsed. Deterministic seeds."""
    psdu = mac_frame(b"snr sweep payload")
    base = np.concatenate([np.zeros(80, np.complex64), modulate_frame(psdu),
                           np.zeros(80, np.complex64)])
    rng = np.random.default_rng(42)
    namp = 0.9
    wins = {"phase": 0, "coherent": 0}
    for _ in range(10):
        n = (rng.standard_normal(len(base))
             + 1j * rng.standard_normal(len(base))) / np.sqrt(2)
        x = (base * np.exp(1j * 0.4) + namp * n).astype(np.complex64)
        for m in wins:
            wins[m] += demodulate_stream(x, timing=m) == [psdu]
    assert wins["coherent"] >= 8, wins
    assert wins["phase"] <= 3, wins       # discriminator collapsed here


def test_coherent_multi_burst():
    """Several bursts with distinct payloads and per-burst phases in one stream."""
    rng = np.random.default_rng(5)
    parts, sent = [], []
    for i in range(4):
        psdu = mac_frame(f"burst {i}".encode() * (i + 1))
        sent.append(psdu)
        burst = modulate_frame(psdu) * np.exp(1j * rng.uniform(0, 2 * np.pi))
        parts += [np.zeros(150 + 31 * i, np.complex64), burst.astype(np.complex64)]
    parts.append(np.zeros(150, np.complex64))
    sig = np.concatenate(parts)
    sig = (sig + 0.1 * (rng.standard_normal(len(sig))
                        + 1j * rng.standard_normal(len(sig))) / np.sqrt(2)
           ).astype(np.complex64)
    assert demodulate_stream(sig, timing="coherent") == sent


def test_iq_delay_block():
    """IqDelay (`iq_delay.rs` role): the Q rail is delayed by `delay` samples
    relative to I, seeded with zeros, streaming across work() windows."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.models.zigbee import IqDelay

    rng = np.random.default_rng(0)
    x = (rng.standard_normal(10000) + 1j * rng.standard_normal(10000)
         ).astype(np.complex64)
    fg = Flowgraph()
    snk = VectorSink(np.complex64)
    fg.connect(VectorSource(x), IqDelay(delay=2), snk)
    Runtime().run(fg)
    y = np.asarray(snk.items())
    assert len(y) == len(x)
    np.testing.assert_allclose(y.real, x.real, atol=0)
    np.testing.assert_allclose(y.imag[:2], 0.0)
    np.testing.assert_allclose(y.imag[2:], x.imag[:-2], atol=0)


def test_random_payload_roundtrip_fuzz():
    """Seeded sweep over random payload lengths/content and timing modes."""
    from futuresdr_tpu.models.zigbee import (demodulate_stream, mac_deframe,
                                             mac_frame, modulate_frame)
    rng = np.random.default_rng(154)
    for trial in range(8):
        timing = ("phase", "mm", "coherent")[int(rng.integers(0, 3))]
        n_pay = int(rng.integers(1, 100))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        sig = modulate_frame(mac_frame(payload, seq=trial))
        x = np.concatenate([np.zeros(int(rng.integers(64, 600)), np.complex64),
                            sig, np.zeros(256, np.complex64)])
        x = (x * np.exp(1j * float(rng.uniform(0, 6.28)))
             + 0.05 * (rng.standard_normal(len(x))
                       + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
        got = [mac_deframe(ps) for ps in demodulate_stream(x, timing=timing)]
        assert payload in got, (trial, timing, n_pay)


def test_mm_acquisition_survives_noise_only_prefix():
    """Regression (r5 campaign batch 12, offset 2112168 — the fourth
    finding): the Mueller-Müller loop adapted its clock on the noise-only
    prefix (random discriminator angles), occasionally wrecking acquisition
    so badly that a clean σ=0.05 frame produced ZERO candidates while the
    phase and coherent paths both recovered it. Low-energy blocks now freeze
    the loop (no step/phase adaptation), so acquisition starts from nominal
    timing at the burst. This is the exact campaign draw."""
    from futuresdr_tpu.models.zigbee import (demodulate_stream, mac_deframe,
                                             mac_frame, modulate_frame)
    rng = np.random.default_rng(154 + 2112168)
    payload = None
    for trial in range(8):                     # trial 7 is the failing draw
        timing = ("phase", "mm", "coherent")[int(rng.integers(0, 3))]
        n_pay = int(rng.integers(1, 100))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        sig = modulate_frame(mac_frame(payload, seq=trial))
        x = np.concatenate([np.zeros(int(rng.integers(64, 600)), np.complex64),
                            sig, np.zeros(256, np.complex64)])
        x = (x * np.exp(1j * float(rng.uniform(0, 6.28)))
             + 0.05 * (rng.standard_normal(len(x))
                       + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
        if trial == 7:
            assert timing == "mm"
            got = [mac_deframe(ps) for ps in demodulate_stream(x, timing="mm")]
            assert payload in got

    # the gate must hold at ANY burst duty cycle (review caught the first-cut
    # quantile gate collapsing when the burst covers <10% of the capture):
    # a ~5% duty frame in a long idle capture, and an all-signal capture
    # where adaptation must still run
    rng = np.random.default_rng(9)
    payload = bytes(range(50))
    sig = modulate_frame(mac_frame(payload))
    x = np.concatenate([np.zeros(90_000, np.complex64), sig,
                        np.zeros(8_000, np.complex64)])
    x = (x + 0.05 * (rng.standard_normal(len(x))
                     + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
    assert payload in [mac_deframe(ps)
                       for ps in demodulate_stream(x, timing="mm")]
    x2 = (sig + 0.05 * (rng.standard_normal(len(sig))
                        + 1j * rng.standard_normal(len(sig)))
          ).astype(np.complex64)
    assert payload in [mac_deframe(ps)
                       for ps in demodulate_stream(x2, timing="mm")]


def test_mm_dual_start_phase_covers_pull_in_range():
    """Regression (r5 campaign batch 13, offset 5528176 — the fifth finding):
    with adaptation frozen during the noise prefix, the MM loop's INITIAL
    phase persists to the burst, and its pull-in range is only ~a quarter
    chip — one draw's default start produced chips too poor for the SFD scan
    while every start ≥1.5 samples recovered the frame. The mm path now runs
    two half-chip-spaced starts (one is always within pull-in). Exact
    campaign draw."""
    from futuresdr_tpu.models.zigbee import (demodulate_stream, mac_deframe,
                                             mac_frame, modulate_frame)
    rng = np.random.default_rng(154 + 5528176)
    for trial in range(4):
        timing = ("phase", "mm", "coherent")[int(rng.integers(0, 3))]
        n_pay = int(rng.integers(1, 100))
        payload = rng.integers(0, 256, n_pay).astype(np.uint8).tobytes()
        sig = modulate_frame(mac_frame(payload, seq=trial))
        x = np.concatenate([np.zeros(int(rng.integers(64, 600)), np.complex64),
                            sig, np.zeros(256, np.complex64)])
        x = (x * np.exp(1j * float(rng.uniform(0, 6.28)))
             + 0.05 * (rng.standard_normal(len(x))
                       + 1j * rng.standard_normal(len(x)))).astype(np.complex64)
        if trial == 3:
            assert timing == "mm"
            got = [mac_deframe(ps) for ps in demodulate_stream(x, timing="mm")]
            assert payload in got
