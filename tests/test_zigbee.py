"""ZigBee 802.15.4 tests: chip table sanity, CRC, clean + impaired loopback."""

import numpy as np
import pytest

from futuresdr_tpu.models.zigbee import (CHIP_SEQUENCES, modulate_frame,
                                         demodulate_stream, mac_frame, mac_deframe,
                                         crc16_802154)


def test_chip_table_distances():
    """All 16 sequences must be mutually far apart (DSSS property)."""
    pm = CHIP_SEQUENCES.astype(np.int8) * 2 - 1
    g = pm @ pm.T
    off_diag = g - np.diag(np.diag(g))
    assert (np.diag(g) == 32).all()
    assert np.abs(off_diag).max() <= 8


def test_crc_known_behavior():
    assert crc16_802154(b"") == 0x0000
    c1 = crc16_802154(b"\x01\x02\x03")
    assert 0 <= c1 <= 0xFFFF
    assert c1 != crc16_802154(b"\x01\x02\x04")


def test_mac_roundtrip():
    m = mac_frame(b"zigbee payload", seq=7)
    assert mac_deframe(m) == b"zigbee payload"
    bad = bytearray(m)
    bad[4] ^= 0x10
    assert mac_deframe(bytes(bad)) is None


def test_loopback_clean():
    psdu = mac_frame(b"hello 802.15.4")
    sig = modulate_frame(psdu)
    frames = demodulate_stream(np.concatenate(
        [np.zeros(333, np.complex64), sig, np.zeros(200, np.complex64)]))
    assert len(frames) == 1
    assert frames[0] == psdu
    assert mac_deframe(frames[0]) == b"hello 802.15.4"


def test_loopback_noise_and_phase():
    rng = np.random.default_rng(0)
    psdu = mac_frame(bytes(range(40)))
    sig = modulate_frame(psdu)
    sig = np.concatenate([np.zeros(100, np.complex64), sig, np.zeros(100, np.complex64)])
    sig = sig * np.exp(1j * 1.234)                      # arbitrary phase rotation
    sig = (sig + 0.1 * (rng.standard_normal(len(sig))
                        + 1j * rng.standard_normal(len(sig)))).astype(np.complex64)
    frames = demodulate_stream(sig)
    assert len(frames) == 1 and frames[0] == psdu


def test_multiple_frames():
    parts = []
    psdus = [mac_frame(f"frame {i}".encode(), seq=i) for i in range(3)]
    for p in psdus:
        parts += [modulate_frame(p), np.zeros(300, np.complex64)]
    frames = demodulate_stream(np.concatenate(parts))
    assert frames == psdus
