"""Robustness: repeated start/stop cycles, relaunch after completion, multi-channel
hardware source, runtime reuse across flowgraphs."""

import time

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import (NullSource, NullSink, VectorSource, VectorSink, Copy,
                                  SeifySource, Head)


def test_start_stop_cycles_one_runtime():
    """Many short-lived flowgraphs on one runtime: no leaked state between runs."""
    rt = Runtime()
    for i in range(10):
        fg = Flowgraph()
        src = NullSource(np.float32)
        cp = Copy(np.float32)
        snk = NullSink(np.float32)
        fg.connect(src, cp, snk)
        running = rt.start(fg)
        time.sleep(0.01)
        fg_back = running.stop_sync()
        assert fg_back is fg
        assert snk.n_received > 0
    assert rt.handle.flowgraph_ids() == []    # all unregistered


def test_concurrent_flowgraphs_one_runtime():
    rt = Runtime()
    runs = []
    sinks = []
    for i in range(4):
        fg = Flowgraph()
        data = np.full(50_000, float(i), np.float32)
        src = VectorSource(data)
        snk = VectorSink(np.float32)
        fg.connect(src, snk)
        runs.append(rt.start(fg))
        sinks.append(snk)
    for i, r in enumerate(runs):
        r.wait_sync()
        got = sinks[i].items()
        assert len(got) == 50_000
        assert (got == float(i)).all()


def test_seify_multichannel():
    fg = Flowgraph()
    src = SeifySource("driver=dummy,throttle=false", n_channels=2)
    h0 = Head(np.complex64, 10_000)
    h1 = Head(np.complex64, 10_000)
    s0, s1 = VectorSink(np.complex64), VectorSink(np.complex64)
    fg.connect_stream(src, "out0", h0, "in")
    fg.connect_stream(src, "out1", h1, "in")
    fg.connect_stream(h0, "out", s0, "in")
    fg.connect_stream(h1, "out", s1, "in")
    Runtime().run(fg)
    assert len(s0.items()) == 10_000
    assert len(s1.items()) == 10_000
    np.testing.assert_array_equal(s0.items(), s1.items())  # same RF, both channels


def test_soak_stream_minutes_of_samples():
    """Push ~50M samples through a 3-block chain; verifies no stalls at scale."""
    n = 50_000_000
    fg = Flowgraph()
    src = NullSource(np.float32)
    head = Head(np.float32, n)
    snk = NullSink(np.float32)
    fg.connect(src, head, snk)
    t0 = time.perf_counter()
    Runtime().run(fg)
    dt = time.perf_counter() - t0
    assert snk.n_received >= n
    assert dt < 60


def test_random_topology_fuzz():
    """Seeded sweep of random flowgraph topologies: chains with random fan-out
    splits/joins, random chunk sizes (CopyRand) and buffer backends — every
    graph completes with exact sample counts at every sink."""
    import numpy as np
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import (Combine, CopyRand, Head, NullSource,
                                      Sink, Split)
    from futuresdr_tpu.runtime.buffer.ring import RingWriter
    from futuresdr_tpu.runtime.buffer import circular

    backends = [RingWriter]
    if circular.available():
        backends.append(circular.CircularWriter)
    rng = np.random.default_rng(12321)
    for trial in range(6):
        fg = Flowgraph()
        samples = int(rng.integers(50_000, 400_000))
        buf = backends[int(rng.integers(0, len(backends)))]
        src = NullSource(np.float32)
        head = Head(np.float32, samples)
        fg.connect_stream(src, "out", head, "in", buffer=buf)
        last = head
        n_stages = int(rng.integers(1, 5))
        for s in range(n_stages):
            c = CopyRand(np.float32, max_copy=int(rng.integers(64, 2048)),
                         seed=trial * 10 + s)
            fg.connect_stream(last, "out", c, "in", buffer=buf)
            last = c
        counts = []

        def counting_sink():
            c = [0]
            counts.append(c)
            return Sink(lambda chunk, c=c: c.__setitem__(0, c[0] + len(chunk)),
                        np.float32)

        if rng.integers(0, 2):
            # fan out, process each arm, rejoin, then sink
            sp = Split(lambda x: (x, x), np.float32)
            fg.connect_stream(last, "out", sp, "in", buffer=buf)
            arms = []
            for arm in ("out0", "out1"):
                c = CopyRand(np.float32, max_copy=512, seed=99)
                fg.connect_stream(sp, arm, c, "in", buffer=buf)
                arms.append(c)
            comb = Combine(lambda a, b: a + b, np.float32)
            fg.connect_stream(arms[0], "out", comb, "in0", buffer=buf)
            fg.connect_stream(arms[1], "out", comb, "in1", buffer=buf)
            fg.connect_stream(comb, "out", counting_sink(), "in", buffer=buf)
        else:
            fg.connect_stream(last, "out", counting_sink(), "in", buffer=buf)
        Runtime().run(fg)
        for c in counts:
            assert c[0] == samples, (trial, c[0], samples)


def test_no_fd_or_thread_leak_across_launches():
    """Resource-leak soak: many sequential launches across the actor path,
    the fused fast-chain path, and a control-port flowgraph must leave the
    process fd count and thread count where they started — a leaked socket,
    ring memfd, or executor thread per launch would compound in any
    long-lived deployment (the reference's runtime reuses one executor for
    the process lifetime; ours must be as clean across Runtime() cycles)."""
    import gc
    import os
    import threading

    def fd_count():
        gc.collect()       # cycle-pending handles are not leaks; unreachable
        return len(os.listdir("/proc/self/fd"))

    def one_actor():
        fg = Flowgraph()
        fg.connect(VectorSource(np.ones(4096, np.float32)),
                   Copy(np.float32), NullSink(np.float32))
        Runtime().run(fg)

    def one_fused():
        fg = Flowgraph()
        fg.connect(NullSource(np.float32), Head(np.float32, 50_000),
                   NullSink(np.float32))
        Runtime().run(fg)

    def one_ctrl():
        from futuresdr_tpu.runtime.ctrl_port import ControlPort
        rt = Runtime()
        cp = ControlPort(rt.handle, bind="127.0.0.1:29641")
        cp.start()
        try:
            fg = Flowgraph()
            fg.connect(VectorSource(np.ones(1024, np.float32)),
                       NullSink(np.float32))
            rt.run(fg)
        finally:
            cp.stop()

    for fn in (one_actor, one_fused, one_ctrl):
        fn()                                  # warm lazy imports/singletons
    fd0 = fd_count()
    thr0 = threading.active_count()
    for _ in range(15):
        one_actor()
        one_fused()
        one_ctrl()
    # teardown is asynchronous (the finalizer posts loop.stop; the daemon
    # thread closes the epoll/socketpair fds afterwards) — poll with a
    # deadline instead of racing it; small slack since a GC-pending socket
    # can linger one cycle
    deadline = time.time() + 10
    while time.time() < deadline and (
            fd_count() > fd0 + 3 or threading.active_count() > thr0 + 2):
        time.sleep(0.1)
    assert fd_count() <= fd0 + 3, (fd0, fd_count())
    assert threading.active_count() <= thr0 + 2, (thr0,
                                                  threading.active_count())
