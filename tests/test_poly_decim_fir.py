"""Polyphase decimating fir_stage vs the full-rate overlap-save + slice form.

fir_stage(decim=D) routes (auto, when ntaps/D is modest) to a stride-D window einsum
costing ntaps/D MACs per input instead of filtering at full rate and slicing y[::D]
(the reference's decimate=true FIR cores, futuredsp/fir.rs:31, re-designed for the
MXU). The poly form must stream identically to the OS form, carry history across
frame edges, and shrink the stage's frame multiple from lcm(hop, D) to D.
"""
import numpy as np
import pytest

from futuresdr_tpu.ops.stages import Pipeline, fir_stage


def _run(st, x, frame, dtype):
    carry = st.init_carry(dtype)
    outs = []
    for i in range(0, len(x), frame):
        carry, y = st.fn(carry, x[i:i + frame])
        outs.append(np.asarray(y))
    return np.concatenate(outs)


@pytest.mark.parametrize("d_nt", [(2, 31), (4, 63), (8, 64), (3, 17), (25, 200)])
@pytest.mark.parametrize("dtype", [np.float32, np.complex64])
def test_poly_decim_matches_os(d_nt, dtype):
    D, nt = d_nt
    rng = np.random.default_rng(D * 1000 + nt)
    taps = (rng.standard_normal(nt) * np.hanning(nt)).astype(np.float32)
    s_os = fir_stage(taps, decim=D, impl="os")
    s_po = fir_stage(taps, decim=D, impl="poly")
    assert s_po.frame_multiple == D
    frame = int(np.lcm(s_os.frame_multiple, s_po.frame_multiple))
    x = rng.standard_normal(4 * frame).astype(np.float32)
    if dtype == np.complex64:
        x = (x + 1j * rng.standard_normal(len(x))).astype(np.complex64)
    y_os = _run(s_os, x, frame, dtype)
    y_po = _run(s_po, x, frame, dtype)
    assert y_po.shape == y_os.shape
    scale = max(1e-9, np.abs(y_os).max())
    assert np.abs(y_po - y_os).max() / scale < 1e-5


def test_auto_routes_decim_to_poly():
    taps = np.hanning(64).astype(np.float32)
    assert fir_stage(taps, decim=8).frame_multiple == 8          # poly: multiple = D
    assert fir_stage(taps, decim=1).frame_multiple > 8           # non-decim: OS hop
    # huge tap count at small D: MACs/input too high, stays on the OS path
    assert fir_stage(np.ones(8192, np.float32), decim=2).frame_multiple > 2


def test_merge_preserves_forced_poly():
    # two poly-forced stages whose merged taps exceed the auto cap must STAY poly
    rng = np.random.default_rng(9)
    t1 = rng.standard_normal(120).astype(np.float32)
    t2 = rng.standard_normal(80).astype(np.float32)
    pipe = Pipeline([fir_stage(t1, decim=2, impl="poly"),
                     fir_stage(t2, decim=1, impl="poly")], np.complex64)
    assert len(pipe.stages) == 1
    merged_nt = len(pipe.stages[0].lti[0])
    assert merged_nt > 32 * 2                    # beyond the auto threshold...
    assert pipe.frame_multiple == 2              # ...yet still on the poly path


def test_poly_decim_merges_in_pipeline():
    rng = np.random.default_rng(5)
    t1 = rng.standard_normal(33).astype(np.float32)
    t2 = rng.standard_normal(21).astype(np.float32)
    pipe = Pipeline([fir_stage(t1, decim=4), fir_stage(t2, decim=2)], np.complex64)
    assert len(pipe.stages) == 1                                  # LTI merge fired
    ref = Pipeline([fir_stage(t1, decim=4, impl="os"),
                    fir_stage(t2, decim=2, impl="os")], np.complex64, optimize=False)
    frame = int(np.lcm(pipe.frame_multiple, ref.frame_multiple))
    x = (rng.standard_normal(2 * frame)
         + 1j * rng.standard_normal(2 * frame)).astype(np.complex64)
    cm, cr = pipe.init_carry(), ref.init_carry()
    fm, fr = pipe.fn(), ref.fn()
    outs_m, outs_r = [], []
    for i in range(0, len(x), frame):
        cm, ym = fm(cm, x[i:i + frame])
        cr, yr = fr(cr, x[i:i + frame])
        outs_m.append(np.asarray(ym))
        outs_r.append(np.asarray(yr))
    ym, yr = np.concatenate(outs_m), np.concatenate(outs_r)
    scale = max(1e-9, np.abs(yr).max())
    assert np.abs(ym - yr).max() / scale < 1e-4
