"""Mocker harness tests (reference: `tests/mocker.rs`, `tests/moving_avg.rs`)."""

import numpy as np

from futuresdr_tpu import Mocker, Pmt
from futuresdr_tpu.blocks import Apply, Head, Delay


def test_apply_doubles():
    blk = Apply(lambda x: 2.0 * x, np.float32)
    m = Mocker(blk)
    data = np.arange(128, dtype=np.float32)
    m.input("in", data)
    m.init_output("out", 256)
    m.init()
    m.run()
    m.deinit()
    np.testing.assert_array_equal(m.output("out"), 2.0 * data)


def test_head_stops():
    blk = Head(np.float32, 10)
    m = Mocker(blk)
    m.input("in", np.ones(100, np.float32))
    m.init_output("out", 100)
    m.run()
    assert len(m.output("out")) == 10
    assert m.finished


def test_delay_pad():
    blk = Delay(np.float32, 4)
    m = Mocker(blk)
    m.input("in", np.arange(1, 9, dtype=np.float32))
    m.input_finished("in")
    m.init_output("out", 64)
    m.run()
    out = m.output("out")
    np.testing.assert_array_equal(out[:4], np.zeros(4, np.float32))
    np.testing.assert_array_equal(out[4:12], np.arange(1, 9, dtype=np.float32))


def test_message_handler_via_post():
    blk = Delay(np.float32, 0)
    m = Mocker(blk)
    r = m.post("new_value", Pmt.usize(3))
    assert r == Pmt.ok()
    r = m.post("new_value", Pmt.string("bogus"))
    assert r == Pmt.invalid_value()
    r = m.post("nonexistent", Pmt.null())
    assert r == Pmt.invalid_value()
