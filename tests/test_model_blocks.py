"""Flowgraph loopbacks for the ZigBee and ADS-B streaming blocks + websocket e2e."""

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import Apply, VectorSource


def test_zigbee_flowgraph_loopback():
    from futuresdr_tpu.models.zigbee import ZigbeeTransmitter, ZigbeeReceiver

    rng = np.random.default_rng(0)
    fg = Flowgraph()
    tx = ZigbeeTransmitter()
    chan = Apply(lambda x: (x * np.exp(1j * 0.7)
                            + 0.05 * (rng.standard_normal(len(x))
                                      + 1j * rng.standard_normal(len(x)))
                            ).astype(np.complex64), np.complex64)
    rx = ZigbeeReceiver()
    fg.connect(tx, chan, rx)
    payloads = [f"zb frame {i}".encode() for i in range(3)]
    rt = Runtime()
    running = rt.start(fg)
    for p in payloads:
        rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.blob(p)))
    rt.scheduler.run_coro_sync(running.handle.call(tx, "tx", Pmt.finished()))
    running.wait_sync()
    assert rx.frames == payloads


def test_adsb_receiver_block():
    from futuresdr_tpu.models.adsb import AdsbReceiver, modulate_frame
    from tests.test_adsb import hex_to_bits, CALLSIGN_FRAME, VELOCITY_FRAME

    rng = np.random.default_rng(1)
    parts = []
    for h in (CALLSIGN_FRAME, VELOCITY_FRAME):
        parts += [0.03 * rng.random(700).astype(np.float32),
                  modulate_frame(hex_to_bits(h))]
    parts.append(0.03 * rng.random(500).astype(np.float32))
    sig = np.concatenate(parts)

    fg = Flowgraph()
    src = VectorSource(sig)
    rx = AdsbReceiver()
    fg.connect_stream(src, "out", rx, "in")
    Runtime().run(fg)
    assert rx.n_frames == 2
    assert 0x4840D6 in rx.tracker.aircraft
    assert rx.tracker.aircraft[0x4840D6].callsign == "KLM1023"


def test_websocket_sink_end_to_end():
    """A real websocket client receives the latest float32 chunk."""
    import asyncio
    from futuresdr_tpu.blocks import WebsocketSink, NullSource

    fg = Flowgraph()
    src = NullSource(np.float32)
    ws = WebsocketSink(29518, np.float32, chunk_items=256)
    fg.connect(src, ws)
    rt = Runtime()
    running = rt.start(fg)

    async def client():
        import websockets
        for _ in range(50):
            try:
                async with websockets.connect("ws://127.0.0.1:29518") as c:
                    msg = await asyncio.wait_for(c.recv(), timeout=5)
                    return msg
            except (ConnectionRefusedError, OSError):
                await asyncio.sleep(0.1)
        raise RuntimeError("could not connect")

    msg = rt.scheduler.run_coro_sync(client())
    assert len(msg) == 256 * 4
    np.testing.assert_array_equal(np.frombuffer(msg, np.float32),
                                  np.zeros(256, np.float32))
    running.stop_sync()
