"""Tags across the TPU plane (SURVEY §7: item-indexed metadata rides the tensors).

A tag attached upstream must survive a device FIR+decimation segment — through the
fused TpuKernel and through the TpuH2D → TpuStage → TpuD2H frame plane — and land on
the rate-rebased output index (reference index math: ``buffer/circular.rs:37-64`` and
the CPU path's ``blocks/dsp.py`` remap).
"""
import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import fir_stage, mag2_stage
from futuresdr_tpu.runtime.kernel import Kernel
from futuresdr_tpu.runtime.tag import Tag

DECIM = 4
TAG_AT = [5, 4099, 10_000]          # first frame, second frame, mid-stream


class TaggedRampSource(Kernel):
    """Ramp source that tags chosen absolute indices with their value."""

    def __init__(self, n, dtype=np.complex64):
        super().__init__()
        self.n = n
        self._pos = 0
        self.output = self.add_stream_output("out", dtype)

    async def work(self, io, mio, meta):
        out = self.output.slice()
        k = min(len(out), self.n - self._pos)
        if k:
            out[:k] = np.arange(self._pos, self._pos + k)
            for a in TAG_AT:
                if self._pos <= a < self._pos + k:
                    self.output.add_tag(a - self._pos, Tag.named_usize("mark", a))
            self.output.produce(k)
            self._pos += k
        if self._pos >= self.n:
            io.finished = True
        elif k:
            io.call_again = True


class TagRecordingSink(Kernel):
    """Record (absolute index, tag) pairs as they arrive."""

    def __init__(self, dtype):
        super().__init__()
        self.input = self.add_stream_input("in", dtype)
        self.n_received = 0
        self.seen = []

    async def work(self, io, mio, meta):
        n = self.input.available()
        if n:
            for t in self.input.tags(n):
                self.seen.append((self.n_received + t.index, t.tag))
            self.input.consume(n)
            self.n_received += n
        if self.input.finished() and self.input.available() == 0:
            io.finished = True


def _expect(seen):
    got = {t.value: idx for idx, t in seen}
    assert set(got) == set(TAG_AT), got
    for a in TAG_AT:
        assert got[a] == a // DECIM, (a, got[a])


def test_tags_survive_fused_kernel_with_decim():
    from futuresdr_tpu.tpu import TpuKernel

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    n = 3 * 4096 + 1000
    fg = Flowgraph()
    src = TaggedRampSource(n)
    tk = TpuKernel([fir_stage(taps, decim=DECIM)], np.complex64, frame_size=4096)
    snk = TagRecordingSink(np.complex64)
    fg.connect(src, tk, snk)
    Runtime().run(fg)
    assert snk.n_received >= (n // 4096) * (4096 // DECIM)
    _expect(snk.seen)


def test_tags_survive_frame_plane_with_rate_change():
    from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuStage

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    n = 3 * 4096
    fg = Flowgraph()
    src = TaggedRampSource(n)
    h2d = TpuH2D(np.complex64, frame_size=4096)
    st1 = TpuStage([fir_stage(taps, decim=DECIM)], np.complex64)
    st2 = TpuStage([mag2_stage()], np.complex64)       # 1:1 stage keeps indices
    d2h = TpuD2H(np.float32)
    snk = TagRecordingSink(np.float32)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st1, "in")
    fg.connect_inplace(st1, "out", st2, "in")
    fg.connect_inplace(st2, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    Runtime().run(fg)
    assert snk.n_received == n // DECIM
    _expect(snk.seen)
