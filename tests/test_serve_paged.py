"""Paged carries + the overlapped serve step (docs/serving.md).

The serving-engine-2.0 contract: the per-bucket stacked carry became a
PAGE POOL indexed by the slot table's lane→page permutation, and ``step()``
became an overlapped launch/commit pipeline governed by the streamed
path's CreditController. These tests pin the acceptance surface:

* bit-identity per session survives the paging AND the overlap (N=1 at
  in-flight depth > 1 ≡ the bare fused pipeline);
* a join lands MID-megabatch at its own frame cursor (K>1 ragged mask +
  fresh-page substitution), a leave frees the page without touching a
  sibling's bits, and neither ever recompiles the resident capacity;
* evict→readmit rides the same snapshot leaf surface under overlap;
* the overlap is PROVEN by trace interval-union (the test_wire.py
  discipline: serialized ratio ≈ 1, pipelined ≤ 0.75);
* lane-addressed retunes touch exactly one session's page, journaled;
* the step lock is narrow: /metrics, ``health()`` and ``describe()``
  answer while a compile-bearing step is in flight.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from futuresdr_tpu.ops.stages import (Pipeline, fir_stage, rotator_stage)
from futuresdr_tpu.serve import ServeEngine
from futuresdr_tpu.serve.api import register_app, unregister_app

FRAME = 1024


def _pipe():
    taps = np.hanning(31).astype(np.float32)
    return Pipeline([fir_stage(taps, fft_len=256), rotator_stage(0.03)],
                    np.complex64)


def _frames(n, seed=0, frame=FRAME):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal(frame) + 1j * rng.standard_normal(frame))
            .astype(np.complex64) for _ in range(n)]


def _solo(pipe, frames):
    fn, carry = pipe.compile(FRAME, donate=False)
    out = []
    for f in frames:
        carry, y = fn(carry, f)
        out.append(np.asarray(y))
    return out


def _pump(eng, feeds):
    """Feed ``{sid: [frames]}`` through the engine (submit as credits
    allow, step until everything drained)."""
    cursors = {sid: 0 for sid in feeds}
    while True:
        moved = False
        for sid, frames in feeds.items():
            while cursors[sid] < len(frames) and \
                    eng.submit(sid, frames[cursors[sid]]):
                cursors[sid] += 1
                moved = True
        if not eng.step() and not moved and \
                all(cursors[s] >= len(feeds[s]) for s in feeds):
            break


# ---------------------------------------------------------------------------
# bit-identity through paging + overlap
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("depth", [1, 3])
def test_paged_n1_bit_equals_bare_pipeline(depth):
    """N=1 through the paged pool at in-flight depth 1 AND >1 ≡ the bare
    fused pipeline, bit for bit — the overlapped step's speculative
    head/commit chain must not perturb a single carry bit."""
    pipe = _pipe()
    data = _frames(8)
    expected = _solo(pipe, data)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app=f"paged{depth}",
                      buckets=(1,), queue_frames=8, inflight=depth)
    s = eng.admit(tenant="t0")
    _pump(eng, {s.sid: data})
    got = eng.results(s.sid)
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        np.testing.assert_array_equal(g, e)
    assert eng.compiles == 1


def test_mid_megabatch_join_lands_at_own_cursor():
    """K=4 megabatch serving: a session that joins while a sibling is
    mid-stream rides the NEXT dispatch with its own frames — no waiting
    for a group boundary, no recompile — and its outputs are bit-identical
    to the same session served alone AT THE SAME K (K>1 scan programs
    round differently from K=1 by repo contract, so the pin is
    interference-freedom at matched K; the fresh-page substitution starts
    the joiner from the init-carry template at its own frame 0)."""
    da, db = _frames(8, seed=3), _frames(6, seed=4)

    def solo_k4(app, frames):
        e = ServeEngine(_pipe(), frame_size=FRAME, app=app, buckets=(2,),
                        queue_frames=16, frames_per_dispatch=4)
        s = e.admit(tenant="solo")
        _pump(e, {s.sid: frames})
        out = e.results(s.sid)
        assert len(out) == len(frames)
        return out

    ref_a, ref_b = solo_k4("mjsa", da), solo_k4("mjsb", db)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="midjoin",
                      buckets=(2,), queue_frames=8, frames_per_dispatch=4)
    a = eng.admit(tenant="ta")
    for f in da[:4]:
        assert eng.submit(a.sid, f)
    assert eng.step() == 4            # full group for A alone
    # A mid-stream with a PARTIAL group queued; B joins mid-megabatch
    for f in da[4:7]:
        assert eng.submit(a.sid, f)
    b = eng.admit(tenant="tb")
    for f in db[:2]:
        assert eng.submit(b.sid, f)
    # ONE ragged dispatch carries A's 3-frame tailgroup and B's first 2
    # frames from B's own cursor (frame 0)
    assert eng.step() == 5
    assert eng.dispatches == 2
    _pump(eng, {a.sid: da[7:], b.sid: db[2:]})
    got_a, got_b = eng.results(a.sid), eng.results(b.sid)
    assert len(got_a) == 8 and len(got_b) == 6
    for g, e in zip(got_a, ref_a):
        np.testing.assert_array_equal(g, e)
    for g, e in zip(got_b, ref_b):
        np.testing.assert_array_equal(g, e)
    assert eng.compiles == 1          # churn never recompiled capacity 2


def test_leave_mid_group_frees_page_without_disturbing_siblings():
    """A session leaving mid-stream is a page-map edit: its page returns
    to the free list, every sibling's stream stays bit-identical, and the
    resident capacity never recompiles."""
    pipe = _pipe()
    data = [_frames(6, seed=10 + i) for i in range(3)]
    refs = [_solo(pipe, d) for d in data]
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="leave",
                      buckets=(4,), queue_frames=8)
    ss = [eng.admit(tenant=f"t{i}") for i in range(3)]
    for i, s in enumerate(ss):
        for f in data[i][:3]:
            assert eng.submit(s.sid, f)
    while eng.step():
        pass
    free_before = eng.table.free_slots()
    eng.close(ss[1].sid)              # leave mid-stream
    assert eng.table.free_slots() == free_before + 1
    _pump(eng, {ss[0].sid: data[0][3:], ss[2].sid: data[2][3:]})
    for i in (0, 2):
        got = eng.results(ss[i].sid)
        assert len(got) == 6
        for g, e in zip(got, refs[i]):
            np.testing.assert_array_equal(g, e)
    assert eng.compiles == 1


def test_page_map_stays_permutation_under_churn():
    """The page_of_lane map must remain a permutation of [0, capacity)
    through arbitrary admit/close churn — the in-program scatter's
    determinism rests on never seeing a duplicate page index."""
    eng = ServeEngine(Pipeline([rotator_stage(0.05)], np.complex64),
                      frame_size=256, app="perm", buckets=(8,))
    rng = np.random.default_rng(7)
    live = []
    for _ in range(200):
        if live and rng.random() < 0.45:
            sid = live.pop(rng.integers(len(live)))
            eng.close(sid)
        elif len(live) < 8:
            live.append(eng.admit(tenant="t").sid)
        t = eng.table
        assert sorted(t.page_of_lane) == list(range(t.capacity))
        assert all(t.lane_of_page[t.page_of_lane[i]] == i
                   for i in range(t.capacity))
        assert all(t.sessions[sid].page == t.page_of_lane[
            t.sessions[sid].slot] for sid in live)


def test_evict_readmit_round_trip_under_overlap():
    """Evict→readmit with in-flight groups pending: the surgery quiesces
    the window first and the round trip stays bit-identical (the
    snapshot_carry leaf surface reads the COMMITTED page)."""
    pipe = _pipe()
    data = _frames(9, seed=21)
    expected = _solo(pipe, data)
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="evro",
                      buckets=(2,), queue_frames=4, inflight=3)
    s = eng.admit(tenant="t0")
    for f in data[:4]:
        assert eng.submit(s.sid, f)
    eng.step()                        # launch; groups may still be in flight
    eng.evict(s.sid)                  # quiesces, snapshots the page
    assert s.state == "evicted" and s.carry_leaves is not None
    eng.readmit(s.sid)
    _pump(eng, {s.sid: data[4:]})
    got = eng.results(s.sid)
    assert len(got) == len(expected)
    for g, e in zip(got, expected):
        np.testing.assert_array_equal(g, e)


# ---------------------------------------------------------------------------
# overlap evidence: trace interval-union (the test_wire.py discipline)
# ---------------------------------------------------------------------------

def test_serve_step_overlap_interval_union():
    """H2D(t+1) ∥ compute(t) ∥ D2H(t−1) on the SERVING path: under a
    deterministic fake link, the span recorder's lane intervals show
    union < sum at in-flight depth 4 (ratio ≤ 0.75) while depth 1 reads
    serialized (≥ 0.9) — the same bound discipline as the streamed wire
    test."""
    from futuresdr_tpu.ops import xfer
    from futuresdr_tpu.telemetry import spans

    frame = 8192
    pipe_of = lambda: Pipeline([rotator_stage(0.011)], np.complex64)  # noqa: E731
    rng = np.random.default_rng(5)
    data = [(rng.standard_normal(frame) + 1j * rng.standard_normal(frame))
            .astype(np.complex64) for _ in range(14)]

    def run(depth):
        eng = ServeEngine(pipe_of(), frame_size=frame, app=f"ovl{depth}",
                          buckets=(2,), queue_frames=4, inflight=depth)
        a = eng.admit(tenant="t0")
        b = eng.admit(tenant="t1")
        # warmup compile outside the span sample
        eng.submit(a.sid, data[0])
        eng.submit(b.sid, data[0])
        while eng.step():
            pass
        eng.results(a.sid), eng.results(b.sid)
        spans.drain()                          # fresh ring for this run
        for f in data[1:]:
            eng.submit(a.sid, f)
            eng.submit(b.sid, f)
            eng.step()
        while eng.step():
            pass
        return spans.overlap_report(spans.drain())

    was = spans.enabled()
    spans.enable(True)
    try:
        # [2, 8192] c64 = 128 KiB per crossing: 8 ms up at 16 MB/s, 16 ms
        # down at 8 MB/s — modeled wire time dominates the tiny rotator
        xfer.set_fake_link(16e6, 8e6)
        serial = run(1)
        xfer.set_fake_link(16e6, 8e6)          # fresh link timeline
        pipe4 = run(4)
    finally:
        xfer.set_fake_link()
        spans.enable(was)
    for rep in (serial, pipe4):
        for lane in ("H2D", "compute", "D2H"):
            assert rep["lanes"][lane]["spans"] > 0, (lane, rep)
    assert pipe4["sum_s"] >= 0.2, pipe4
    assert serial["ratio"] >= 0.9, f"serialized lanes overlapped: {serial}"
    assert pipe4["ratio"] <= 0.75, \
        f"no overlap: pipelined union/sum {pipe4['ratio']:.2f} ({pipe4})"


# ---------------------------------------------------------------------------
# lane-addressed retunes
# ---------------------------------------------------------------------------

def test_lane_retune_isolated_to_one_session():
    """Retuning one session's rotator mid-stream matches the bare pipeline
    with the same update applied at the same cursor — and the sibling's
    stream stays bit-identical to an untouched solo run."""
    from futuresdr_tpu.telemetry import journal
    pipe = _pipe()
    da, db = _frames(8, seed=31), _frames(8, seed=32)
    ref_b = _solo(pipe, db)
    # reference for A: 4 frames, retune, 4 more
    fn, carry = pipe.compile(FRAME, donate=False)
    ref_a = []
    for f in da[:4]:
        carry, y = fn(carry, f)
        ref_a.append(np.asarray(y))
    carry = pipe.update_stage(carry, "rotator", phase_inc=0.11)
    for f in da[4:]:
        carry, y = fn(carry, f)
        ref_a.append(np.asarray(y))

    eng = ServeEngine(_pipe(), frame_size=FRAME, app="retune",
                      buckets=(2,), queue_frames=8)
    a, b = eng.admit(tenant="ta"), eng.admit(tenant="tb")
    _pump(eng, {a.sid: da[:4], b.sid: db[:4]})
    since = journal.journal().seq
    eng.retune(a.sid, "rotator", phase_inc=0.11)
    evs = journal.events(since=since, cat="serve")["events"]
    assert any(e["event"] == "lane-retune" and e["session"] == a.sid
               for e in evs)
    _pump(eng, {a.sid: da[4:], b.sid: db[4:]})
    got_a, got_b = eng.results(a.sid), eng.results(b.sid)
    for g, e in zip(got_a, ref_a):
        np.testing.assert_array_equal(g, e)
    for g, e in zip(got_b, ref_b):     # sibling bit-frozen through it
        np.testing.assert_array_equal(g, e)
    assert eng.compiles == 1           # surgery never recompiles


def test_retune_fresh_lane_and_error_contract():
    """Retune of a never-dispatched (fresh) lane retunes the template it
    will start from; unknown sessions raise KeyError, bad stage addresses
    ValueError (the REST plane's 404 vs 409 split)."""
    pipe = _pipe()
    data = _frames(4, seed=33)
    fn, carry = pipe.compile(FRAME, donate=False)
    carry = pipe.update_stage(carry, "rotator", phase_inc=0.2)
    ref = []
    for f in data:
        carry, y = fn(carry, f)
        ref.append(np.asarray(y))
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="freshtune",
                      buckets=(2,), queue_frames=8)
    s = eng.admit(tenant="t0")        # fresh: never dispatched
    eng.retune(s.sid, "rotator", phase_inc=0.2)
    _pump(eng, {s.sid: data})
    got = eng.results(s.sid)
    for g, e in zip(got, ref):
        np.testing.assert_array_equal(g, e)
    with pytest.raises(KeyError):
        eng.retune("nosuch", "rotator", phase_inc=0.1)
    with pytest.raises(ValueError):
        eng.retune(s.sid, "nosuchstage", phase_inc=0.1)


def test_rest_session_ctrl_endpoint():
    """POST /api/serve/{app}/session/{sid}/ctrl/ applies a lane retune;
    unknown sid → 404, bad stage → 409, malformed body → 400."""
    from futuresdr_tpu import Runtime
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="ctrlapp",
                      buckets=(2,), queue_frames=8)
    register_app(eng)
    rt = Runtime()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29654")
    cp.start()
    base = "http://127.0.0.1:29654"

    def post(path, body):
        req = urllib.request.Request(
            f"{base}{path}", data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        return json.load(urllib.request.urlopen(req))

    try:
        s = post("/api/serve/ctrlapp/session/", {"tenant": "gold"})
        sid = s["sid"]
        view = post(f"/api/serve/ctrlapp/session/{sid}/ctrl/",
                    {"stage": "rotator", "params": {"phase_inc": 0.09}})
        assert view["sid"] == sid and view["state"] == "active"
        with pytest.raises(urllib.error.HTTPError) as e404:
            post(f"/api/serve/ctrlapp/session/{sid}x/ctrl/",
                 {"stage": "rotator", "params": {}})
        assert e404.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as e409:
            post(f"/api/serve/ctrlapp/session/{sid}/ctrl/",
                 {"stage": "nosuch", "params": {}})
        assert e409.value.code == 409
        with pytest.raises(urllib.error.HTTPError) as e400:
            post(f"/api/serve/ctrlapp/session/{sid}/ctrl/",
                 {"params": {}})
        assert e400.value.code == 400
    finally:
        cp.stop()
        unregister_app("ctrlapp")


# ---------------------------------------------------------------------------
# page-admit journal + narrow step lock
# ---------------------------------------------------------------------------

def test_admission_journals_page_admit():
    from futuresdr_tpu.telemetry import journal
    eng = ServeEngine(Pipeline([rotator_stage(0.02)], np.complex64),
                      frame_size=256, app="jadmit", buckets=(2,))
    since = journal.journal().seq
    s = eng.admit(tenant="t0")
    evs = [e for e in journal.events(since=since, cat="serve")["events"]
           if e["event"] == "page-admit"]
    assert len(evs) == 1
    assert evs[0]["session"] == s.sid
    assert evs[0]["slot"] == s.slot and evs[0]["page"] == s.page


def test_observability_answers_during_compile_bearing_step():
    """The small-fix pin: a long (compile-bearing) step must not block
    /metrics, health() or describe() — the state lock is held for
    assembly/commit bookkeeping only, never across the program call."""
    import futuresdr_tpu.serve.engine as engine_mod
    from futuresdr_tpu.telemetry import prom

    real_build = engine_mod.build_slot_program
    entered = threading.Event()
    release = threading.Event()

    def slow_build(pipeline, capacity, k=1):
        prog = real_build(pipeline, capacity, k)

        def slow(*args):
            entered.set()
            assert release.wait(10.0), "test hung"
            return prog(*args)
        return slow

    engine_mod.build_slot_program = slow_build
    try:
        eng = ServeEngine(Pipeline([rotator_stage(0.02)], np.complex64),
                          frame_size=256, app="locknarrow", buckets=(1,))
        s = eng.admit(tenant="t0")
        eng.submit(s.sid, np.zeros(256, np.complex64))
        t = threading.Thread(target=eng.step, daemon=True)
        t.start()
        assert entered.wait(10.0), "step never reached the program call"
        # the step thread is parked inside the "program" — every
        # observability surface must answer NOW, without waiting it out
        t0 = time.perf_counter()
        h = eng.health()
        d = eng.describe()
        v = eng.session_view(s.sid)
        text = prom.render_all()
        elapsed = time.perf_counter() - t0
        assert t.is_alive(), "step finished early — probe proved nothing"
        assert elapsed < 2.0, f"observability blocked {elapsed:.1f}s"
        assert h["active"] == 1 and d["app"] == "locknarrow"
        assert v["sid"] == s.sid and "fsdr_serve_sessions" in text
    finally:
        release.set()
        t.join(10.0)
        engine_mod.build_slot_program = real_build


# ---------------------------------------------------------------------------
# pool growth
# ---------------------------------------------------------------------------

def test_page_pool_growth_preserves_resident_streams():
    """Growing to the next bucket is page-pool growth: residents keep
    their pages (streams bit-identical across the growth) and only the
    NEW capacity compiles."""
    pipe = _pipe()
    data = [_frames(6, seed=40 + i) for i in range(3)]
    refs = [_solo(pipe, d) for d in data]
    eng = ServeEngine(_pipe(), frame_size=FRAME, app="pgrow",
                      buckets=(2, 4), queue_frames=8)
    s0 = eng.admit(tenant="t0")
    s1 = eng.admit(tenant="t1")
    _pump(eng, {s0.sid: data[0][:3], s1.sid: data[1][:3]})
    assert eng.compiles == 1 and eng.capacity == 2
    s2 = eng.admit(tenant="t2")       # forces growth 2 -> 4
    assert eng.capacity == 4
    _pump(eng, {s0.sid: data[0][3:], s1.sid: data[1][3:],
                s2.sid: data[2]})
    assert eng.compiles == 2          # exactly one new-capacity compile
    for s, ref in ((s0, refs[0]), (s1, refs[1]), (s2, refs[2])):
        got = eng.results(s.sid)
        assert len(got) == len(ref)
        for g, e in zip(got, ref):
            np.testing.assert_array_equal(g, e)
