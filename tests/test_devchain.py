"""Device-graph fusion (runtime/devchain.py): fused-vs-actor equivalence.

The fusion pass collapses ``TpuH2D → TpuStage* → TpuD2H`` runs (and adjacent
``TpuKernel`` pairs) into ONE fused TpuKernel dispatch per frame. The contract
tested here is the hard one: the fused flowgraph's output must be
BIT-IDENTICAL to the per-hop actor flowgraph (boundary carry-stash fences pin
each member segment's numerics), tags must rebase through the composed rate
contract, refusal cases must stay on the actor path, and the declined mode
(``FSDR_NO_DEVCHAIN=1``) must stand alone.
"""

import os
from contextlib import contextmanager

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSink, VectorSource
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import fir_stage, mag2_stage, rotator_stage
from futuresdr_tpu.runtime.devchain import find_device_chains
from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuKernel, TpuStage


@contextmanager
def _no_devchain(on: bool = True):
    old = os.environ.pop("FSDR_NO_DEVCHAIN", None)
    if on:
        os.environ["FSDR_NO_DEVCHAIN"] = "1"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("FSDR_NO_DEVCHAIN", None)
        else:
            os.environ["FSDR_NO_DEVCHAIN"] = old


def _stage_lists(split: str):
    """The same 3-stage compute chain under different member splits."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    s1 = fir_stage(t1, name="a")
    s2 = fir_stage(t2, decim=4, name="b")
    s3 = mag2_stage()
    return {
        "1|1|1": [[s1], [s2], [s3]],
        "2|1": [[s1, s2], [s3]],
        "1|2": [[s1], [s2, s3]],
    }[split]


def _frame_plane_fg(split: str, data, frame: int):
    fg = Flowgraph()
    src = VectorSource(data)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    stages = [TpuStage(sl, np.complex64) for sl in _stage_lists(split)]
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect_stream(src, "out", h2d, "in")
    prev = h2d
    for st in stages:
        fg.connect_inplace(prev, "out", st, "in")
        prev = st
    fg.connect_inplace(prev, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    return fg, snk


@pytest.mark.parametrize("split", ["1|1|1", "2|1", "1|2"])
@pytest.mark.parametrize("frames_n", [1, 3])      # one-shot vs chunked stream
def test_frame_plane_fused_bit_equals_actor(split, frames_n):
    frame = 4096
    rng = np.random.default_rng(7)
    n = frames_n * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk = _frame_plane_fg(split, data, frame)
        Runtime().run(fg)
        ref = snk.items()
    with _no_devchain(False):
        fg, snk = _frame_plane_fg(split, data, frame)
        assert len(find_device_chains(fg)) == 1     # the run actually fuses
        Runtime().run(fg)
        got = snk.items()
    assert len(ref) == n // 4
    np.testing.assert_array_equal(got, ref)


def test_kernel_run_fused_bit_equals_actor():
    """Adjacent TpuKernels (stream-plane hops) fuse into one kernel too."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    rng = np.random.default_rng(8)
    n = 4 * 4096
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)

    def build():
        fg = Flowgraph()
        src = VectorSource(data)
        k1 = TpuKernel([fir_stage(t1, decim=4)], np.complex64, frame_size=4096)
        k2 = TpuKernel([mag2_stage()], np.complex64, frame_size=1024)
        snk = VectorSink(np.float32)
        fg.connect(src, k1, k2, snk)
        return fg, snk

    with _no_devchain():
        fg, snk = build()
        Runtime().run(fg)
        ref = snk.items()
    with _no_devchain(False):
        fg, snk = build()
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].kind == "kernels"
        Runtime().run(fg)
        got = snk.items()
    np.testing.assert_array_equal(got, ref)


def test_fused_megabatch_bit_equals_actor():
    """frames_per_dispatch > 1 (lax.scan megabatch) through the fused chain
    keeps bit-equality, including the EOS partial batch padding."""
    from futuresdr_tpu.config import config
    frame = 4096
    rng = np.random.default_rng(9)
    n = 5 * frame                     # 5 frames: one K=2 batch stays partial
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk = _frame_plane_fg("1|1|1", data, frame)
        Runtime().run(fg)
        ref = snk.items()
    old = config().tpu_frames_per_dispatch
    config().tpu_frames_per_dispatch = 2
    try:
        with _no_devchain(False):
            fg, snk = _frame_plane_fg("1|1|1", data, frame)
            Runtime().run(fg)
            got = snk.items()
    finally:
        config().tpu_frames_per_dispatch = old
    np.testing.assert_array_equal(got, ref)


def test_tags_rebase_through_decimating_fused_run():
    """A tag crossing the FUSED device segment lands on the same rebased
    output index as on the per-hop path (test_tpu_tags contract)."""
    from tests.test_tpu_tags import (DECIM, TagRecordingSink,
                                     TaggedRampSource, _expect)

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    n = 3 * 4096
    with _no_devchain(False):
        fg = Flowgraph()
        src = TaggedRampSource(n)
        h2d = TpuH2D(np.complex64, frame_size=4096)
        st1 = TpuStage([fir_stage(taps, decim=DECIM)], np.complex64)
        st2 = TpuStage([mag2_stage()], np.complex64)
        d2h = TpuD2H(np.float32)
        snk = TagRecordingSink(np.float32)
        fg.connect_stream(src, "out", h2d, "in")
        fg.connect_inplace(h2d, "out", st1, "in")
        fg.connect_inplace(st1, "out", st2, "in")
        fg.connect_inplace(st2, "out", d2h, "in")
        fg.connect_stream(d2h, "out", snk, "in")
        assert len(find_device_chains(fg)) == 1
        Runtime().run(fg)
    assert snk.n_received == n // DECIM
    _expect(snk.seen)


def test_fused_member_metrics_bridge():
    """metrics() keeps reporting PER ORIGINAL BLOCK: fused provenance plus
    item counters derived through the composed rate contract."""
    frame = 4096
    data = np.zeros(3 * frame, np.complex64)
    with _no_devchain(False):
        fg, snk = _frame_plane_fg("1|1|1", data, frame)
        rt = Runtime()
        running = rt.start(fg)
        running.wait_sync()
    wrapped = {b.instance_name: b for b in fg._blocks if b is not None}
    mets = {n: b.metrics() for n, b in wrapped.items()}
    fused = {n: m for n, m in mets.items() if m.get("fused_devchain")}
    assert len(fused) == 5            # h2d + 3 stages + d2h
    for m in fused.values():
        assert m["devchain_frames"] == 3
        assert m["devchain_dispatches"] >= 1
    # rate contract: the decimating member (stage "b", block 3) reports in/4
    st_dec = next(m for n, m in fused.items() if "TpuStage_3" in n)
    assert st_dec["items_in"] == {"in": 3 * frame}
    assert st_dec["items_out"] == {"out": 3 * frame // 4}


# ---------------------------------------------------------------------------
# refuse-to-fuse cases: the run must stay on the actor path
# ---------------------------------------------------------------------------

def test_refuses_wired_retune_handler_without_static_optin():
    """A ctrl port wired to a MESSAGE EDGE refuses to fuse (live retunes are
    stream-synchronized there); the fastchain_static-style ``devchain_static``
    opt-in overrides."""
    from futuresdr_tpu.blocks.message import MessageSource

    taps = firdes.lowpass(0.2, 32).astype(np.float32)

    def build(static):
        fg = Flowgraph()
        src = VectorSource(np.zeros(8192, np.complex64))
        h2d = TpuH2D(np.complex64, frame_size=4096)
        st = TpuStage([fir_stage(taps, name="f")], np.complex64)
        if static:
            st.devchain_static = True
        d2h = TpuD2H(np.complex64)
        snk = VectorSink(np.complex64)
        msg = MessageSource({"stage": "f", "taps": taps.tolist()}, interval=1.0)
        fg.connect_stream(src, "out", h2d, "in")
        fg.connect_inplace(h2d, "out", st, "in")
        fg.connect_inplace(st, "out", d2h, "in")
        fg.connect_stream(d2h, "out", snk, "in")
        fg.connect_message(msg, "out", st, "ctrl")
        return fg

    with _no_devchain(False):
        assert find_device_chains(build(static=False)) == []
        assert len(find_device_chains(build(static=True))) == 1


def test_refuses_mismatched_instances():
    from futuresdr_tpu.tpu import TpuInstance

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=4096)
    st = TpuStage([fir_stage(taps)], np.complex64, inst=TpuInstance())
    d2h = TpuD2H(np.complex64)
    snk = VectorSink(np.complex64)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st, "in")
    fg.connect_inplace(st, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_refuses_branching_port():
    """A member output wired to several edges (broadcast) cannot fuse."""
    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    k1 = TpuKernel([fir_stage(taps)], np.complex64, frame_size=4096)
    k2 = TpuKernel([mag2_stage()], np.complex64, frame_size=4096)
    snk = VectorSink(np.float32)
    tap_snk = VectorSink(np.complex64)
    fg.connect(src, k1, k2, snk)
    fg.connect_stream(k1, "out", tap_snk, "in")   # second reader on the hop
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_refuses_frame_not_multiple_of_composed_contract():
    """H2D frame below the composed frame multiple stays per-hop."""
    from futuresdr_tpu.ops import fft_stage

    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=1024)
    st = TpuStage([fft_stage(2048)], np.complex64)   # needs 2048-multiples
    d2h = TpuD2H(np.complex64)
    snk = VectorSink(np.complex64)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st, "in")
    fg.connect_inplace(st, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_no_devchain_env_declines_everything():
    frame = 4096
    data = np.zeros(2 * frame, np.complex64)
    with _no_devchain():
        fg, snk = _frame_plane_fg("1|1|1", data, frame)
        assert find_device_chains(fg) == []
        Runtime().run(fg)                # the fallback path stands alone
        assert len(snk.items()) == 2 * frame // 4


# ---------------------------------------------------------------------------
# fuzz family entry (perf/fuzz_campaign.py)
# ---------------------------------------------------------------------------

def test_random_devchain_shapes_fuzz():
    """Randomized chain shapes: random stage mixes, member splits and frame
    sizes — every fused run must bit-equal its per-hop actor run."""
    master = np.random.default_rng(20250802)
    for case in range(4):
        rng = np.random.default_rng(master.integers(1 << 62))
        frame = int(rng.choice([2048, 4096]))
        n_frames = int(rng.integers(2, 5))
        decim = int(rng.choice([1, 2, 4]))
        nt = int(rng.choice([16, 33, 48]))
        taps = firdes.lowpass(0.3, nt).astype(np.float32)
        pool = [
            # fft_len=512 keeps the OS hop (and so the composed frame
            # multiple) at 256 — below every frame in the sweep
            fir_stage(taps, fft_len=512, name="fa"),
            fir_stage(firdes.lowpass(0.2, 24).astype(np.float32),
                      decim=decim, fft_len=512, name="fb"),
            rotator_stage(float(rng.uniform(-0.3, 0.3))),
            mag2_stage(),
        ]
        n_stages = int(rng.integers(2, len(pool) + 1))
        stages = pool[:n_stages]       # prefix keeps dtype contract valid
        # random split into 1..n_stages member groups
        cuts = sorted(rng.choice(range(1, n_stages),
                                 size=int(rng.integers(0, n_stages)),
                                 replace=False).tolist())
        groups, lo = [], 0
        for c in cuts + [n_stages]:
            groups.append(stages[lo:c])
            lo = c
        n = n_frames * frame
        data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
                ).astype(np.complex64)

        def build():
            fg = Flowgraph()
            src = VectorSource(data)
            h2d = TpuH2D(np.complex64, frame_size=frame)
            sts = [TpuStage(list(g), np.complex64) for g in groups if g]
            out_dt = np.float32 if any(
                s.name == "mag2" for g in groups for s in g) else np.complex64
            d2h = TpuD2H(out_dt)
            snk = VectorSink(out_dt)
            fg.connect_stream(src, "out", h2d, "in")
            prev = h2d
            for st in sts:
                fg.connect_inplace(prev, "out", st, "in")
                prev = st
            fg.connect_inplace(prev, "out", d2h, "in")
            fg.connect_stream(d2h, "out", snk, "in")
            return fg, snk

        with _no_devchain():
            fg, snk = build()
            Runtime().run(fg)
            ref = snk.items()
        with _no_devchain(False):
            fg, snk = build()
            Runtime().run(fg)
            got = snk.items()
        np.testing.assert_array_equal(
            got, ref, err_msg=f"case {case}: frame={frame} groups="
                              f"{[len(g) for g in groups]}")
