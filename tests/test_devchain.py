"""Device-graph fusion (runtime/devchain.py): fused-vs-actor equivalence.

The fusion pass collapses ``TpuH2D → TpuStage* → TpuD2H`` runs (and adjacent
``TpuKernel`` pairs) into ONE fused TpuKernel dispatch per frame. The contract
tested here is the hard one: the fused flowgraph's output must be
BIT-IDENTICAL to the per-hop actor flowgraph (boundary carry-stash fences pin
each member segment's numerics), tags must rebase through the composed rate
contract, refusal cases must stay on the actor path, and the declined mode
(``FSDR_NO_DEVCHAIN=1``) must stand alone.
"""

import os
from contextlib import contextmanager

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSink, VectorSource
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import fir_stage, mag2_stage, rotator_stage
from futuresdr_tpu.runtime.devchain import find_device_chains
from futuresdr_tpu.tpu import TpuD2H, TpuH2D, TpuKernel, TpuStage


@contextmanager
def _no_devchain(on: bool = True):
    old = os.environ.pop("FSDR_NO_DEVCHAIN", None)
    if on:
        os.environ["FSDR_NO_DEVCHAIN"] = "1"
    try:
        yield
    finally:
        if old is None:
            os.environ.pop("FSDR_NO_DEVCHAIN", None)
        else:
            os.environ["FSDR_NO_DEVCHAIN"] = old


def _stage_lists(split: str):
    """The same 3-stage compute chain under different member splits."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    s1 = fir_stage(t1, name="a")
    s2 = fir_stage(t2, decim=4, name="b")
    s3 = mag2_stage()
    return {
        "1|1|1": [[s1], [s2], [s3]],
        "2|1": [[s1, s2], [s3]],
        "1|2": [[s1], [s2, s3]],
    }[split]


def _frame_plane_fg(split: str, data, frame: int):
    fg = Flowgraph()
    src = VectorSource(data)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    stages = [TpuStage(sl, np.complex64) for sl in _stage_lists(split)]
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect_stream(src, "out", h2d, "in")
    prev = h2d
    for st in stages:
        fg.connect_inplace(prev, "out", st, "in")
        prev = st
    fg.connect_inplace(prev, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    return fg, snk


@pytest.mark.parametrize("split", ["1|1|1", "2|1", "1|2"])
@pytest.mark.parametrize("frames_n", [1, 3])      # one-shot vs chunked stream
def test_frame_plane_fused_bit_equals_actor(split, frames_n):
    frame = 4096
    rng = np.random.default_rng(7)
    n = frames_n * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk = _frame_plane_fg(split, data, frame)
        Runtime().run(fg)
        ref = snk.items()
    with _no_devchain(False):
        fg, snk = _frame_plane_fg(split, data, frame)
        assert len(find_device_chains(fg)) == 1     # the run actually fuses
        Runtime().run(fg)
        got = snk.items()
    assert len(ref) == n // 4
    np.testing.assert_array_equal(got, ref)


def test_kernel_run_fused_bit_equals_actor():
    """Adjacent TpuKernels (stream-plane hops) fuse into one kernel too."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    rng = np.random.default_rng(8)
    n = 4 * 4096
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)

    def build():
        fg = Flowgraph()
        src = VectorSource(data)
        k1 = TpuKernel([fir_stage(t1, decim=4)], np.complex64, frame_size=4096)
        k2 = TpuKernel([mag2_stage()], np.complex64, frame_size=1024)
        snk = VectorSink(np.float32)
        fg.connect(src, k1, k2, snk)
        return fg, snk

    with _no_devchain():
        fg, snk = build()
        Runtime().run(fg)
        ref = snk.items()
    with _no_devchain(False):
        fg, snk = build()
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].kind == "kernels"
        Runtime().run(fg)
        got = snk.items()
    np.testing.assert_array_equal(got, ref)


def test_fused_megabatch_bit_equals_actor():
    """frames_per_dispatch > 1 (lax.scan megabatch) through the fused chain
    keeps bit-equality, including the EOS partial batch padding."""
    from futuresdr_tpu.config import config
    frame = 4096
    rng = np.random.default_rng(9)
    n = 5 * frame                     # 5 frames: one K=2 batch stays partial
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk = _frame_plane_fg("1|1|1", data, frame)
        Runtime().run(fg)
        ref = snk.items()
    old = config().tpu_frames_per_dispatch
    config().tpu_frames_per_dispatch = 2
    try:
        with _no_devchain(False):
            fg, snk = _frame_plane_fg("1|1|1", data, frame)
            Runtime().run(fg)
            got = snk.items()
    finally:
        config().tpu_frames_per_dispatch = old
    np.testing.assert_array_equal(got, ref)


def test_tags_rebase_through_decimating_fused_run():
    """A tag crossing the FUSED device segment lands on the same rebased
    output index as on the per-hop path (test_tpu_tags contract)."""
    from tests.test_tpu_tags import (DECIM, TagRecordingSink,
                                     TaggedRampSource, _expect)

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    n = 3 * 4096
    with _no_devchain(False):
        fg = Flowgraph()
        src = TaggedRampSource(n)
        h2d = TpuH2D(np.complex64, frame_size=4096)
        st1 = TpuStage([fir_stage(taps, decim=DECIM)], np.complex64)
        st2 = TpuStage([mag2_stage()], np.complex64)
        d2h = TpuD2H(np.float32)
        snk = TagRecordingSink(np.float32)
        fg.connect_stream(src, "out", h2d, "in")
        fg.connect_inplace(h2d, "out", st1, "in")
        fg.connect_inplace(st1, "out", st2, "in")
        fg.connect_inplace(st2, "out", d2h, "in")
        fg.connect_stream(d2h, "out", snk, "in")
        assert len(find_device_chains(fg)) == 1
        Runtime().run(fg)
    assert snk.n_received == n // DECIM
    _expect(snk.seen)


def test_fused_member_metrics_bridge():
    """metrics() keeps reporting PER ORIGINAL BLOCK: fused provenance plus
    item counters derived through the composed rate contract."""
    frame = 4096
    data = np.zeros(3 * frame, np.complex64)
    with _no_devchain(False):
        fg, snk = _frame_plane_fg("1|1|1", data, frame)
        rt = Runtime()
        running = rt.start(fg)
        running.wait_sync()
    wrapped = {b.instance_name: b for b in fg._blocks if b is not None}
    mets = {n: b.metrics() for n, b in wrapped.items()}
    fused = {n: m for n, m in mets.items() if m.get("fused_devchain")}
    assert len(fused) == 5            # h2d + 3 stages + d2h
    for m in fused.values():
        assert m["devchain_frames"] == 3
        assert m["devchain_dispatches"] >= 1
    # rate contract: the decimating member (stage "b", block 3) reports in/4
    st_dec = next(m for n, m in fused.items() if "TpuStage_3" in n)
    assert st_dec["items_in"] == {"in": 3 * frame}
    assert st_dec["items_out"] == {"out": 3 * frame // 4}


# ---------------------------------------------------------------------------
# fan-out (broadcast) fusion: producer → N branches as ONE dispatch
# ---------------------------------------------------------------------------

def _fanout_stage_lists(split: str):
    """producer stages + two branch stage lists under different member splits
    (how the stages are distributed over TpuStage blocks)."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    p1 = fir_stage(t1, name="p1")
    p2 = rotator_stage(0.1, name="p2")
    b1 = fir_stage(t2, decim=4, name="b1")
    b2 = mag2_stage()
    # (producer member stage-lists, branch1 member stage-lists, branch2 ...)
    return {
        "1→1|1": ([[p1]], [[b1]], [[b2]]),
        "2→1|1": ([[p1], [p2]], [[b1]], [[b2]]),
        "1→2|1": ([[p1]], [[p2, b1]], [[b2]]),
    }[split]


def _fanout_frame_fg(split: str, data, frame: int):
    """TpuH2D → producer TpuStages → broadcast → two TpuStage chains, each
    exiting through its own TpuD2H."""
    prod_lists, br1_lists, br2_lists = _fanout_stage_lists(split)
    fg = Flowgraph()
    src = VectorSource(data)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    fg.connect_stream(src, "out", h2d, "in")
    prev = h2d
    for sl in prod_lists:
        st = TpuStage(sl, np.complex64)
        fg.connect_inplace(prev, "out", st, "in")
        prev = st
    sinks = []
    for lists, out_dt in ((br1_lists, np.complex64), (br2_lists, np.float32)):
        b_prev = prev
        for sl in lists:
            st = TpuStage(sl, np.complex64)
            fg.connect_inplace(b_prev, "out", st, "in")
            b_prev = st
        d2h = TpuD2H(out_dt)
        snk = VectorSink(out_dt)
        fg.connect_inplace(b_prev, "out", d2h, "in")
        fg.connect_stream(d2h, "out", snk, "in")
        sinks.append(snk)
    return fg, sinks


@pytest.mark.parametrize("split", ["1→1|1", "2→1|1", "1→2|1"])
@pytest.mark.parametrize("frames_n", [1, 3])      # one-shot vs chunked stream
def test_frames_fanout_fused_bit_equals_actor(split, frames_n):
    """A frame-plane 1→2 fan-out region fuses into ONE multi-output dispatch
    whose branch outputs are BIT-identical to the per-hop broadcast run."""
    frame = 4096
    rng = np.random.default_rng(17)
    n = frames_n * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, sinks = _fanout_frame_fg(split, data, frame)
        assert find_device_chains(fg) == []
        Runtime().run(fg)
        refs = [s.items() for s in sinks]
    with _no_devchain(False):
        fg, sinks = _fanout_frame_fg(split, data, frame)
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].fanout   # the region fuses
        Runtime().run(fg)
        got = [s.items() for s in sinks]
    assert len(refs[0]) == n // 4 and len(refs[1]) == n
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(g, r)


def test_kernels_fanout_1to3_bit_equals_actor():
    """A TpuKernel producer broadcasting to THREE TpuKernel branches over
    stream edges fuses (one upload, one dispatch) bit-identically."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    frame = 4096
    rng = np.random.default_rng(18)
    n = 4 * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)

    def build():
        fg = Flowgraph()
        src = VectorSource(data)
        prod = TpuKernel([fir_stage(t1, name="p")], np.complex64,
                         frame_size=frame)
        b1 = TpuKernel([fir_stage(t2, decim=4, name="b1")], np.complex64,
                       frame_size=frame)
        b2 = TpuKernel([mag2_stage()], np.complex64, frame_size=frame)
        b3 = TpuKernel([rotator_stage(0.2)], np.complex64, frame_size=frame)
        snks = [VectorSink(np.complex64), VectorSink(np.float32),
                VectorSink(np.complex64)]
        fg.connect(src, prod)
        for b, s in zip((b1, b2, b3), snks):
            fg.connect_stream(prod, "out", b, "in")
            fg.connect(b, s)
        return fg, snks, prod

    with _no_devchain():
        fg, snks, _ = build()
        Runtime().run(fg)
        refs = [s.items() for s in snks]
    with _no_devchain(False):
        fg, snks, prod = build()
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].fanout \
            and chains[0].kind == "kernels"
        assert len(chains[0].branches) == 3
        Runtime().run(fg)
        got = [s.items() for s in snks]
        m = prod.extra_metrics()
        assert m.get("fused_devchain")
        # ONE dispatch per frame for the whole 1→3 region (was 4 per frame)
        assert m["devchain_dispatches"] == m["devchain_frames"] == 4
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(g, r)


@pytest.mark.parametrize("k", [1, 4])
def test_fanout_megabatch_bit_equals_actor(k):
    """frames_per_dispatch K through the fused fan-out keeps bit-equality,
    including the EOS partial batch and a partial tail frame."""
    from futuresdr_tpu.config import config
    frame = 4096
    rng = np.random.default_rng(19)
    n = 5 * frame                     # 5 frames: one K=4 batch stays partial
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, sinks = _fanout_frame_fg("1→1|1", data, frame)
        Runtime().run(fg)
        refs = [s.items() for s in sinks]
    old = config().tpu_frames_per_dispatch
    config().tpu_frames_per_dispatch = k
    try:
        with _no_devchain(False):
            fg, sinks = _fanout_frame_fg("1→1|1", data, frame)
            Runtime().run(fg)
            got = [s.items() for s in sinks]
    finally:
        config().tpu_frames_per_dispatch = old
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(g, r)


def test_fanout_tags_rebase_through_decimating_branch():
    """A tag crossing the fused fan-out lands at the DECIMATED index on the
    decimating branch and the 1:1 index on the other — each branch applies
    its own path rate contract."""
    from tests.test_tpu_tags import (DECIM, TAG_AT, TagRecordingSink,
                                     TaggedRampSource, _expect)

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    n = 3 * 4096
    with _no_devchain(False):
        fg = Flowgraph()
        src = TaggedRampSource(n)
        h2d = TpuH2D(np.complex64, frame_size=4096)
        b1 = TpuStage([fir_stage(taps, decim=DECIM)], np.complex64)
        b2 = TpuStage([mag2_stage()], np.complex64)
        d1 = TpuD2H(np.complex64)
        d2 = TpuD2H(np.float32)
        s1 = TagRecordingSink(np.complex64)
        s2 = TagRecordingSink(np.float32)
        fg.connect_stream(src, "out", h2d, "in")
        fg.connect_inplace(h2d, "out", b1, "in")
        fg.connect_inplace(h2d, "out", b2, "in")
        fg.connect_inplace(b1, "out", d1, "in")
        fg.connect_inplace(b2, "out", d2, "in")
        fg.connect_stream(d1, "out", s1, "in")
        fg.connect_stream(d2, "out", s2, "in")
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].fanout
        Runtime().run(fg)
    assert s1.n_received == n // DECIM
    _expect(s1.seen)                   # decimated branch: index // DECIM
    assert s2.n_received == n
    got2 = {t.value: idx for idx, t in s2.seen}
    assert got2 == {a: a for a in TAG_AT}   # 1:1 branch: index unchanged


def test_fanout_member_metrics_bridge():
    """Fan-out members report fused provenance, per-branch identity and item
    counters derived through THEIR branch's path rate."""
    frame = 4096
    data = np.zeros(3 * frame, np.complex64)
    with _no_devchain(False):
        fg, _sinks = _fanout_frame_fg("1→1|1", data, frame)
        rt = Runtime()
        rt.start(fg).wait_sync()
    mets = {b.instance_name: b.metrics() for b in fg._blocks if b is not None}
    fused = {nm: m for nm, m in mets.items() if m.get("fused_devchain")}
    assert len(fused) == 6            # h2d + producer + 2 branches + 2 d2h
    branches = {m.get("devchain_branch") for m in fused.values()}
    assert branches == {None, 0, 1}
    # the decimating branch member reports in-rate 1:1 and out-rate 1:4
    dec = next(m for nm, m in fused.items()
               if m.get("devchain_branch") == 0 and nm.startswith("TpuStage"))
    assert dec["items_in"] == {"in": 3 * frame}
    assert dec["items_out"] == {"out": 3 * frame // 4}


# ---------------------------------------------------------------------------
# refuse-to-fuse cases: the run must stay on the actor path
# ---------------------------------------------------------------------------


def test_fanout_refuses_cross_instance_branch():
    """One branch on a different TpuInstance declines the WHOLE region."""
    from futuresdr_tpu.tpu import TpuInstance

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=4096)
    b1 = TpuStage([fir_stage(taps, name="b1")], np.complex64)
    b2 = TpuStage([mag2_stage()], np.complex64, inst=TpuInstance())
    d1 = TpuD2H(np.complex64)
    d2 = TpuD2H(np.float32)
    s1 = VectorSink(np.complex64)
    s2 = VectorSink(np.float32)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", b1, "in")
    fg.connect_inplace(h2d, "out", b2, "in")
    fg.connect_inplace(b1, "out", d1, "in")
    fg.connect_inplace(b2, "out", d2, "in")
    fg.connect_stream(d1, "out", s1, "in")
    fg.connect_stream(d2, "out", s2, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_fanout_refuses_policy_bearing_member():
    """A non-fail_fast failure policy on ANY member (here a branch kernel)
    declines the whole fan-out region to the per-hop actor path."""
    from futuresdr_tpu.runtime.block import BlockPolicy

    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    prod = TpuKernel([fir_stage(t1, name="p")], np.complex64, frame_size=4096)
    b1 = TpuKernel([mag2_stage()], np.complex64, frame_size=4096)
    b2 = TpuKernel([rotator_stage(0.1)], np.complex64, frame_size=4096)
    b2.policy = BlockPolicy(on_error="isolate")
    s1 = VectorSink(np.float32)
    s2 = VectorSink(np.complex64)
    fg.connect(src, prod)
    fg.connect_stream(prod, "out", b1, "in")
    fg.connect_stream(prod, "out", b2, "in")
    fg.connect(b1, s1)
    fg.connect(b2, s2)
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_no_devchain_env_declines_fanout():
    """FSDR_NO_DEVCHAIN=1 keeps fan-out regions per-hop too, and the
    broadcast actor path stands alone."""
    frame = 4096
    data = np.zeros(2 * frame, np.complex64)
    with _no_devchain():
        fg, sinks = _fanout_frame_fg("1→1|1", data, frame)
        assert find_device_chains(fg) == []
        Runtime().run(fg)
        assert len(sinks[0].items()) == 2 * frame // 4
        assert len(sinks[1].items()) == 2 * frame


def test_fanout_span_and_report_carry_branch_attribution():
    """The fused run's `devchain` span carries per-branch args, and
    doctor.report() surfaces them under its `devchain` key."""
    from futuresdr_tpu.telemetry import doctor as doc
    from futuresdr_tpu.telemetry import spans

    frame = 4096
    data = np.zeros(3 * frame, np.complex64)
    spans.enable(True)
    try:
        spans.recorder().drain()
        with _no_devchain(False):
            fg, _sinks = _fanout_frame_fg("1→1|1", data, frame)
            Runtime().run(fg)
        events = spans.recorder().drain()
    finally:
        spans.enable(False)
    dev = [e for e in events if e.cat == "devchain"]
    assert len(dev) == 1
    branches = dev[0].args["branches"]
    assert [b["branch"] for b in branches] == [0, 1]
    assert all(not b["retired"] and b["members"] == 2 for b in branches)
    assert branches[0]["items_out"] == 3 * frame // 4      # decimating branch
    assert branches[1]["items_out"] == 3 * frame
    rep = doc.doctor().report(events=events)
    assert rep["devchain"] and rep["devchain"][0]["frames"] == 3
    assert rep["devchain"][0]["branches"] == branches


def test_fanout_launches_with_cached_autotune_k():
    """A fan-out region whose SHAPE was tuned by autotune_streamed launches
    fused with the cached megabatch K (the streamed-pick cache keyed on
    producer + per-branch markers), and the raw-stage-list signature recorded
    alongside maps a devchain composition to the same pick even when the
    tuned pipeline merged stages."""
    from futuresdr_tpu.ops import FanoutPipeline
    from futuresdr_tpu.tpu import instance
    from futuresdr_tpu.tpu.autotune import (_fanout_names, _record_sig,
                                            _streamed_cache,
                                            cached_frames_per_dispatch)

    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    frame, k = 4096, 2
    n = 4 * frame
    rng = np.random.default_rng(23)
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain(False):
        fg, sinks = _fanout_frame_fg("1→1|1", data, frame)
        # record the pick under the raw fan-out shape the flowgraph's member
        # stage lists compose to (what autotune_streamed(_record_sig) writes)
        st_members = [b.kernel for b in fg._blocks if b is not None
                      and type(b.kernel).__name__ == "TpuStage"]
        prod = next(m for m in st_members
                    if any(s.name == "p1" for s in m.pipeline.stages))
        b1 = next(m for m in st_members
                  if any(s.name == "b1" for s in m.pipeline.stages))
        b2 = next(m for m in st_members
                  if any(s.name == "mag2" for s in m.pipeline.stages))
        _record_sig((instance().platform, str(np.dtype(np.complex64)),
                     _fanout_names(prod.pipeline.stages,
                                   [b1.pipeline.stages, b2.pipeline.stages])),
                    k)
        try:
            Runtime().run(fg)
            m = fg.wrapped(prod).metrics()
            assert m.get("fused_devchain") is True, m
            assert m.get("frames_per_dispatch") == k, m
            assert m["devchain_frames"] == 4 and m["devchain_dispatches"] == 2
        finally:
            _streamed_cache.clear()
    # the raw-signature alias: a FanoutPipeline built from split raw lists
    # records under BOTH its merged names and the raw names
    from futuresdr_tpu.tpu.autotune import autotune_streamed  # noqa: F401
    fo = FanoutPipeline([fir_stage(t2, name="x1"), fir_stage(t2, name="x2")],
                        [[mag2_stage()], [rotator_stage(0.1)]], np.complex64)
    assert [s.name for s in fo.producer.stages] == ["x1*x2"]   # LTI-merged
    raw_p, raw_b = fo.raw_stage_lists
    assert [s.name for s in raw_p] == ["x1", "x2"]


def test_donation_mask_fanout_compile():
    """ops/stages donation mask: True donates the carries; an explicit
    argnum mask donates exactly those argnums; the fan-out's widest mask
    covers the carries + input parts but can never name the boundary value
    (it is not an argument)."""
    import jax

    from futuresdr_tpu.ops import FanoutPipeline

    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    fo = FanoutPipeline([fir_stage(t1, name="p")],
                        [[fir_stage(t1, decim=4, name="b1")], [mag2_stage()]],
                        np.complex64, optimize=False)
    # widest mask = carries + the ONE f32-wire input part
    assert fo.donation_mask("f32") == (0, 1)
    frame = 4096
    x = np.zeros(frame, np.complex64)
    from futuresdr_tpu.ops import get_wire
    w = get_wire("f32")
    # donate=False: the input carry stays usable after the call
    fn, carry = fo.compile_wired(frame, "f32", donate=False)
    parts = tuple(jax.device_put(np.asarray(p)) for p in w.encode_host(x))
    c2, _ = fn(carry, *parts)
    np.asarray(carry[0][0])            # still alive
    # donate=(0,): the donated carries are consumed
    fn, carry = fo.compile_wired(frame, "f32", donate=(0,))
    parts = tuple(jax.device_put(np.asarray(p)) for p in w.encode_host(x))
    c2, _ = fn(carry, *parts)
    with pytest.raises(RuntimeError):
        np.asarray(jax.tree_util.tree_leaves(carry)[0])

def test_refuses_wired_retune_handler_without_static_optin():
    """A ctrl port wired to a MESSAGE EDGE refuses to fuse (live retunes are
    stream-synchronized there); the fastchain_static-style ``devchain_static``
    opt-in overrides."""
    from futuresdr_tpu.blocks.message import MessageSource

    taps = firdes.lowpass(0.2, 32).astype(np.float32)

    def build(static):
        fg = Flowgraph()
        src = VectorSource(np.zeros(8192, np.complex64))
        h2d = TpuH2D(np.complex64, frame_size=4096)
        st = TpuStage([fir_stage(taps, name="f")], np.complex64)
        if static:
            st.devchain_static = True
        d2h = TpuD2H(np.complex64)
        snk = VectorSink(np.complex64)
        msg = MessageSource({"stage": "f", "taps": taps.tolist()}, interval=1.0)
        fg.connect_stream(src, "out", h2d, "in")
        fg.connect_inplace(h2d, "out", st, "in")
        fg.connect_inplace(st, "out", d2h, "in")
        fg.connect_stream(d2h, "out", snk, "in")
        fg.connect_message(msg, "out", st, "ctrl")
        return fg

    with _no_devchain(False):
        assert find_device_chains(build(static=False)) == []
        assert len(find_device_chains(build(static=True))) == 1


def test_refuses_mismatched_instances():
    from futuresdr_tpu.tpu import TpuInstance

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=4096)
    st = TpuStage([fir_stage(taps)], np.complex64, inst=TpuInstance())
    d2h = TpuD2H(np.complex64)
    snk = VectorSink(np.complex64)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st, "in")
    fg.connect_inplace(st, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_refuses_branching_port():
    """A broadcast whose edges do NOT all open fusable device runs cannot
    fuse — here one edge taps straight into a host sink, so the whole region
    (including the otherwise-linear k1→k2 run) declines to the actor path
    (all-or-nothing; a clean all-device fan-out DOES fuse since round 11)."""
    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    k1 = TpuKernel([fir_stage(taps)], np.complex64, frame_size=4096)
    k2 = TpuKernel([mag2_stage()], np.complex64, frame_size=4096)
    snk = VectorSink(np.float32)
    tap_snk = VectorSink(np.complex64)
    fg.connect(src, k1, k2, snk)
    fg.connect_stream(k1, "out", tap_snk, "in")   # second reader on the hop
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_refuses_frame_not_multiple_of_composed_contract():
    """H2D frame below the composed frame multiple stays per-hop."""
    from futuresdr_tpu.ops import fft_stage

    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=1024)
    st = TpuStage([fft_stage(2048)], np.complex64)   # needs 2048-multiples
    d2h = TpuD2H(np.complex64)
    snk = VectorSink(np.complex64)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st, "in")
    fg.connect_inplace(st, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_no_devchain_env_declines_everything():
    frame = 4096
    data = np.zeros(2 * frame, np.complex64)
    with _no_devchain():
        fg, snk = _frame_plane_fg("1|1|1", data, frame)
        assert find_device_chains(fg) == []
        Runtime().run(fg)                # the fallback path stands alone
        assert len(snk.items()) == 2 * frame // 4


# ---------------------------------------------------------------------------
# fuzz family entry (perf/fuzz_campaign.py)
# ---------------------------------------------------------------------------

def test_random_devchain_shapes_fuzz():
    """Randomized chain shapes: random stage mixes, member splits and frame
    sizes — every fused run must bit-equal its per-hop actor run."""
    master = np.random.default_rng(20250802)
    for case in range(4):
        rng = np.random.default_rng(master.integers(1 << 62))
        frame = int(rng.choice([2048, 4096]))
        n_frames = int(rng.integers(2, 5))
        decim = int(rng.choice([1, 2, 4]))
        nt = int(rng.choice([16, 33, 48]))
        taps = firdes.lowpass(0.3, nt).astype(np.float32)
        pool = [
            # fft_len=512 keeps the OS hop (and so the composed frame
            # multiple) at 256 — below every frame in the sweep
            fir_stage(taps, fft_len=512, name="fa"),
            fir_stage(firdes.lowpass(0.2, 24).astype(np.float32),
                      decim=decim, fft_len=512, name="fb"),
            rotator_stage(float(rng.uniform(-0.3, 0.3))),
            mag2_stage(),
        ]
        n_stages = int(rng.integers(2, len(pool) + 1))
        stages = pool[:n_stages]       # prefix keeps dtype contract valid
        # random split into 1..n_stages member groups
        cuts = sorted(rng.choice(range(1, n_stages),
                                 size=int(rng.integers(0, n_stages)),
                                 replace=False).tolist())
        groups, lo = [], 0
        for c in cuts + [n_stages]:
            groups.append(stages[lo:c])
            lo = c
        n = n_frames * frame
        data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
                ).astype(np.complex64)

        def build():
            fg = Flowgraph()
            src = VectorSource(data)
            h2d = TpuH2D(np.complex64, frame_size=frame)
            sts = [TpuStage(list(g), np.complex64) for g in groups if g]
            out_dt = np.float32 if any(
                s.name == "mag2" for g in groups for s in g) else np.complex64
            d2h = TpuD2H(out_dt)
            snk = VectorSink(out_dt)
            fg.connect_stream(src, "out", h2d, "in")
            prev = h2d
            for st in sts:
                fg.connect_inplace(prev, "out", st, "in")
                prev = st
            fg.connect_inplace(prev, "out", d2h, "in")
            fg.connect_stream(d2h, "out", snk, "in")
            return fg, snk

        with _no_devchain():
            fg, snk = build()
            Runtime().run(fg)
            ref = snk.items()
        with _no_devchain(False):
            fg, snk = build()
            Runtime().run(fg)
            got = snk.items()
        np.testing.assert_array_equal(
            got, ref, err_msg=f"case {case}: frame={frame} groups="
                              f"{[len(g) for g in groups]}")

    # fan-out shapes: random producer depth × branch count × per-branch stage
    # mixes — every fused broadcast region must bit-equal its per-hop run
    for case in range(3):
        rng = np.random.default_rng(master.integers(1 << 62))
        frame = int(rng.choice([2048, 4096]))
        n_frames = int(rng.integers(2, 5))
        taps = firdes.lowpass(0.3, int(rng.choice([16, 33]))).astype(
            np.float32)
        prod_depth = int(rng.integers(0, 3))   # 0 = H2D broadcasts directly
        n_branches = int(rng.integers(2, 4))
        decim = int(rng.choice([1, 2, 4]))

        def branch_stages(j, rng=rng, taps=taps, decim=decim):
            pick = int(rng.integers(0, 3))
            if pick == 0:
                return ([fir_stage(taps, decim=decim, fft_len=512,
                                   name=f"bf{j}")], np.complex64)
            if pick == 1:
                return ([mag2_stage()], np.float32)
            return ([rotator_stage(float(rng.uniform(-0.3, 0.3)))],
                    np.complex64)

        branch_specs = [branch_stages(j) for j in range(n_branches)]
        n = n_frames * frame
        data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
                ).astype(np.complex64)

        def build_fanout():
            fg = Flowgraph()
            src = VectorSource(data)
            h2d = TpuH2D(np.complex64, frame_size=frame)
            fg.connect_stream(src, "out", h2d, "in")
            prev = h2d
            for d in range(prod_depth):
                st = TpuStage([fir_stage(taps, fft_len=512, name=f"pp{d}")],
                              np.complex64)
                fg.connect_inplace(prev, "out", st, "in")
                prev = st
            snks = []
            for sl, out_dt in branch_specs:
                st = TpuStage(list(sl), np.complex64)
                d2h = TpuD2H(out_dt)
                snk = VectorSink(out_dt)
                fg.connect_inplace(prev, "out", st, "in")
                fg.connect_inplace(st, "out", d2h, "in")
                fg.connect_stream(d2h, "out", snk, "in")
                snks.append(snk)
            return fg, snks

        with _no_devchain():
            fg, snks = build_fanout()
            Runtime().run(fg)
            refs = [s.items() for s in snks]
        with _no_devchain(False):
            fg, snks = build_fanout()
            chains = find_device_chains(fg)
            assert len(chains) == 1 and chains[0].fanout, chains
            Runtime().run(fg)
            for j, (s, r) in enumerate(zip(snks, refs)):
                np.testing.assert_array_equal(
                    s.items(), r,
                    err_msg=f"fanout case {case} branch {j}: frame={frame} "
                            f"prod_depth={prod_depth} "
                            f"branches={n_branches}")

    # DAG shapes (round 13): random diamonds (broadcast → K equal-rate
    # branches → merge, add/interleave/concat joins), and
    # broadcast-inside-a-branch (nested fan-out) — every fused region must
    # bit-equal its per-hop run
    from futuresdr_tpu.ops import (add_merge_stage, concat_merge_stage,
                                   interleave_merge_stage)
    from futuresdr_tpu.tpu.frames import TpuMergeStage
    for case in range(3):
        rng = np.random.default_rng(master.integers(1 << 62))
        frame = int(rng.choice([2048, 4096]))
        n_frames = int(rng.integers(2, 5))
        taps = firdes.lowpass(0.3, int(rng.choice([16, 33]))).astype(
            np.float32)
        shape = ("diamond", "nested")[case % 2]
        prod_depth = int(rng.integers(0, 2))   # 0 = H2D broadcasts directly
        k_in = int(rng.integers(2, 4))
        decim = int(rng.choice([1, 2]))
        pick = int(rng.integers(0, 3))
        n = n_frames * frame
        data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
                ).astype(np.complex64)

        def build_dag(shape=shape, taps=taps, frame=frame, data=data,
                      prod_depth=prod_depth, k_in=k_in, decim=decim,
                      pick=pick):
            fg = Flowgraph()
            src = VectorSource(data)
            h2d = TpuH2D(np.complex64, frame_size=frame)
            fg.connect_stream(src, "out", h2d, "in")
            prev = h2d
            for d in range(prod_depth):
                st = TpuStage([fir_stage(taps, fft_len=512, name=f"dp{d}")],
                              np.complex64)
                fg.connect_inplace(prev, "out", st, "in")
                prev = st
            snks = []
            if shape == "diamond":
                mg = TpuMergeStage(
                    [add_merge_stage(k_in), interleave_merge_stage(k_in),
                     concat_merge_stage(k_in)][pick])
                for i in range(k_in):
                    st = TpuStage([fir_stage(taps, decim=decim, fft_len=512,
                                             name=f"db{i}")], np.complex64)
                    fg.connect_inplace(prev, "out", st, "in")
                    fg.connect_inplace(st, "out", mg, f"in{i}")
                d2h = TpuD2H(np.complex64)
                snk = VectorSink(np.complex64)
                fg.connect_inplace(mg, "out", d2h, "in")
                fg.connect_stream(d2h, "out", snk, "in")
                snks.append(snk)
            else:
                mid = TpuStage([fir_stage(taps, fft_len=512, name="mid")],
                               np.complex64)
                fg.connect_inplace(prev, "out", mid, "in")
                ends = []
                for i in range(2):     # broadcast inside the mid branch
                    st = TpuStage([fir_stage(taps, fft_len=512,
                                             name=f"leaf{i}")], np.complex64)
                    fg.connect_inplace(mid, "out", st, "in")
                    ends.append(st)
                st2 = TpuStage([mag2_stage()], np.complex64)
                fg.connect_inplace(prev, "out", st2, "in")
                for st, dt in [(ends[0], np.complex64),
                               (ends[1], np.complex64), (st2, np.float32)]:
                    d2h = TpuD2H(dt)
                    snk = VectorSink(dt)
                    fg.connect_inplace(st, "out", d2h, "in")
                    fg.connect_stream(d2h, "out", snk, "in")
                    snks.append(snk)
            return fg, snks

        with _no_devchain():
            fg, snks = build_dag()
            Runtime().run(fg)
            refs = [s.items() for s in snks]
        with _no_devchain(False):
            fg, snks = build_dag()
            chains = find_device_chains(fg)
            assert len(chains) == 1 and chains[0].dag, (case, shape, chains)
            Runtime().run(fg)
            for j, (s, r) in enumerate(zip(snks, refs)):
                np.testing.assert_array_equal(
                    s.items(), r,
                    err_msg=f"dag case {case} ({shape}) sink {j}: "
                            f"frame={frame} prod_depth={prod_depth}")


# ---------------------------------------------------------------------------
# general DAG fusion (round 13): fan-IN (merge), the diamond closure, and
# NESTED fan-out — whole-receiver single-dispatch
# ---------------------------------------------------------------------------

from futuresdr_tpu.ops import (add_merge_stage, concat_merge_stage,  # noqa: E402
                               interleave_merge_stage)
from futuresdr_tpu.tpu.frames import TpuMergeStage  # noqa: E402


def _diamond_fg(split: str, data, frame: int, merge="add"):
    """``TpuH2D → producer? → broadcast → two decim-4 FIR branches →
    TpuMergeStage(+|x|²) → TpuD2H`` under different member splits. The
    DECIMATING merge branches are the acceptance shape; ``merge="concat"``
    swaps the equal-rate join for a concat of UNEQUAL rates (branch 2 runs
    1:1)."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    p = fir_stage(t1, name="p")
    b1 = fir_stage(t2, decim=4, fft_len=512, name="b1")
    b2 = fir_stage(t2, decim=4, fft_len=512, name="b2") if merge == "add" \
        else rotator_stage(0.1, name="b2")
    prod_lists, br1_lists, br2_lists = {
        "0|1|1": ([], [[b1]], [[b2]]),
        "1|1|1": ([[p]], [[b1]], [[b2]]),
        "1|2|1": ([[p]], [[rotator_stage(0.2)], [b1]], [[b2]]),
    }[split]
    if merge == "add":
        mg = TpuMergeStage(add_merge_stage(2), [mag2_stage()])
        out_dt = np.float32
    else:
        mg = TpuMergeStage(concat_merge_stage(2))
        out_dt = np.complex64
    fg = Flowgraph()
    src = VectorSource(data)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    fg.connect_stream(src, "out", h2d, "in")
    prev = h2d
    for sl in prod_lists:
        st = TpuStage(sl, np.complex64)
        fg.connect_inplace(prev, "out", st, "in")
        prev = st
    for port, lists in (("in0", br1_lists), ("in1", br2_lists)):
        b_prev = prev
        for sl in lists:
            st = TpuStage(sl, np.complex64)
            fg.connect_inplace(b_prev, "out", st, "in")
            b_prev = st
        fg.connect_inplace(b_prev, "out", mg, port)
    d2h = TpuD2H(out_dt)
    snk = VectorSink(out_dt)
    fg.connect_inplace(mg, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    return fg, snk, mg


@pytest.mark.parametrize("split", ["0|1|1", "1|1|1", "1|2|1"])
@pytest.mark.parametrize("frames_n", [1, 3])      # one-shot vs chunked stream
def test_diamond_fused_bit_equals_actor(split, frames_n):
    """The diamond ``broadcast → branches → merge`` closure fuses into ONE
    dispatch per frame, BIT-identical to the per-hop actor run (decimating
    merge branches, member splits, chunked/one-shot)."""
    frame = 4096
    rng = np.random.default_rng(31)
    n = frames_n * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk, _ = _diamond_fg(split, data, frame)
        assert find_device_chains(fg) == []
        Runtime().run(fg)
        ref = snk.items()
    with _no_devchain(False):
        fg, snk, _ = _diamond_fg(split, data, frame)
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].dag and not chains[0].fanout
        assert len(chains[0].sinks) == 1          # single-sink DAG
        Runtime().run(fg)
        got = snk.items()
    assert len(ref) == n // 4
    np.testing.assert_array_equal(got, ref)


@pytest.mark.parametrize("k", [1, 4])
def test_diamond_megabatch_bit_equals_actor(k):
    """frames_per_dispatch K through the fused diamond keeps bit-equality,
    including the EOS partial batch."""
    from futuresdr_tpu.config import config
    frame = 4096
    rng = np.random.default_rng(37)
    n = 5 * frame                     # 5 frames: one K=4 batch stays partial
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk, _ = _diamond_fg("1|1|1", data, frame)
        Runtime().run(fg)
        ref = snk.items()
    old = config().tpu_frames_per_dispatch
    config().tpu_frames_per_dispatch = k
    try:
        with _no_devchain(False):
            fg, snk, _ = _diamond_fg("1|1|1", data, frame)
            Runtime().run(fg)
            got = snk.items()
    finally:
        config().tpu_frames_per_dispatch = old
    np.testing.assert_array_equal(got, ref)


def test_concat_merge_unequal_rates_bit_equals_actor():
    """A concat merge joining a decim-4 branch with a 1:1 branch fuses —
    per-path rate contracts compose (out = 5/4 of the input)."""
    frame = 4096
    rng = np.random.default_rng(41)
    n = 3 * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk, _ = _diamond_fg("1|1|1", data, frame, merge="concat")
        Runtime().run(fg)
        ref = snk.items()
    with _no_devchain(False):
        fg, snk, _ = _diamond_fg("1|1|1", data, frame, merge="concat")
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].dag
        Runtime().run(fg)
        got = snk.items()
    assert len(ref) == n + n // 4     # concat: both branches' items
    np.testing.assert_array_equal(got, ref)


def _nested_kernel_fg(data, frame):
    """Stream-plane NESTED fan-out: ``prod → {a → {c, d}, b}`` (a broadcast
    inside a branch) — 3 sinks, 5 kernels, 5 dispatches/frame per-hop.
    The interior stays LTI (fir/mag2): the K>1 megabatch scan form is a
    different XLA compilation whose transcendental-phase rounding (rotator
    exp) may legitimately differ from the k=1 program — a pre-existing
    property of the scan megabatch, pinned LTI-only exactly like the linear
    megabatch tests."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    prod = TpuKernel([fir_stage(t1, name="p")], np.complex64,
                     frame_size=frame)
    a = TpuKernel([fir_stage(t2, fft_len=512, name="a")], np.complex64,
                  frame_size=frame)
    b = TpuKernel([mag2_stage()], np.complex64, frame_size=frame)
    c = TpuKernel([fir_stage(t2, decim=4, fft_len=512, name="c")],
                  np.complex64, frame_size=frame)
    d = TpuKernel([mag2_stage()], np.complex64, frame_size=frame)
    snks = [VectorSink(np.complex64), VectorSink(np.float32),
            VectorSink(np.float32)]
    fg.connect(src, prod)
    fg.connect_stream(prod, "out", a, "in")
    fg.connect_stream(prod, "out", b, "in")
    fg.connect_stream(a, "out", c, "in")
    fg.connect_stream(a, "out", d, "in")
    fg.connect(c, snks[0])
    fg.connect(d, snks[1])
    fg.connect(b, snks[2])
    return fg, snks, prod


@pytest.mark.parametrize("k", [1, 4])
def test_nested_fanout_kernels_bit_equals_actor(k):
    """A broadcast INSIDE a branch (nested fan-out) fuses into one
    multi-output dispatch per frame walking the region's SINK set."""
    from futuresdr_tpu.config import config
    frame = 4096
    rng = np.random.default_rng(43)
    n = 4 * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snks, _ = _nested_kernel_fg(data, frame)
        assert find_device_chains(fg) == []
        Runtime().run(fg)
        refs = [s.items() for s in snks]
    old = config().tpu_frames_per_dispatch
    config().tpu_frames_per_dispatch = k
    try:
        with _no_devchain(False):
            fg, snks, prod = _nested_kernel_fg(data, frame)
            chains = find_device_chains(fg)
            assert len(chains) == 1 and chains[0].dag \
                and chains[0].kind == "kernels"
            assert len(chains[0].sinks) == 3
            Runtime().run(fg)
            got = [s.items() for s in snks]
            m = prod.extra_metrics()
            assert m.get("fused_devchain")
            # ONE dispatch per frame for the whole nested 5-kernel region
            assert m["devchain_dispatches"] * k == m["devchain_frames"] == 4
    finally:
        config().tpu_frames_per_dispatch = old
    for g, r in zip(got, refs):
        np.testing.assert_array_equal(g, r)


def test_nested_fanout_frames_bit_equals_actor():
    """Frame-plane nested fan-out: ``h2d → p → {b1 → {s_a → d2h, s_b →
    d2h}, b2 → d2h}`` fuses whole (3 sinks) bit-identically."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    frame = 4096
    rng = np.random.default_rng(47)
    n = 3 * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)

    def build():
        fg = Flowgraph()
        src = VectorSource(data)
        h2d = TpuH2D(np.complex64, frame_size=frame)
        p = TpuStage([fir_stage(t1, name="p")], np.complex64)
        b1 = TpuStage([rotator_stage(0.1)], np.complex64)
        b2 = TpuStage([mag2_stage()], np.complex64)
        sa = TpuStage([fir_stage(t2, decim=4, fft_len=512, name="sa")],
                      np.complex64)
        sb = TpuStage([mag2_stage()], np.complex64)
        fg.connect_stream(src, "out", h2d, "in")
        fg.connect_inplace(h2d, "out", p, "in")
        fg.connect_inplace(p, "out", b1, "in")
        fg.connect_inplace(p, "out", b2, "in")
        fg.connect_inplace(b1, "out", sa, "in")
        fg.connect_inplace(b1, "out", sb, "in")
        snks = []
        for st, dt in ((sa, np.complex64), (sb, np.float32),
                       (b2, np.float32)):
            d2h = TpuD2H(dt)
            snk = VectorSink(dt)
            fg.connect_inplace(st, "out", d2h, "in")
            fg.connect_stream(d2h, "out", snk, "in")
            snks.append(snk)
        return fg, snks

    with _no_devchain():
        fg, snks = build()
        Runtime().run(fg)
        refs = [s.items() for s in snks]
    with _no_devchain(False):
        fg, snks = build()
        chains = find_device_chains(fg)
        assert len(chains) == 1 and chains[0].dag \
            and chains[0].kind == "frames"
        Runtime().run(fg)
        for s, r in zip(snks, refs):
            np.testing.assert_array_equal(s.items(), r)


def test_diamond_tags_cross_fused_merge():
    """A tag crossing the fused diamond lands exactly where the per-hop
    actor path (merge: tags ride the PRIMARY input) puts it."""
    from tests.test_tpu_tags import TagRecordingSink, TaggedRampSource

    frame = 4096
    n = 3 * frame

    def build():
        t2 = firdes.lowpass(0.2, 32).astype(np.float32)
        fg = Flowgraph()
        src = TaggedRampSource(n)
        h2d = TpuH2D(np.complex64, frame_size=frame)
        b1 = TpuStage([fir_stage(t2, decim=4, fft_len=512, name="b1")],
                      np.complex64)
        b2 = TpuStage([fir_stage(t2, decim=4, fft_len=512, name="b2")],
                      np.complex64)
        mg = TpuMergeStage(add_merge_stage(2), [mag2_stage()])
        d2h = TpuD2H(np.float32)
        snk = TagRecordingSink(np.float32)
        fg.connect_stream(src, "out", h2d, "in")
        fg.connect_inplace(h2d, "out", b1, "in")
        fg.connect_inplace(h2d, "out", b2, "in")
        fg.connect_inplace(b1, "out", mg, "in0")
        fg.connect_inplace(b2, "out", mg, "in1")
        fg.connect_inplace(mg, "out", d2h, "in")
        fg.connect_stream(d2h, "out", snk, "in")
        return fg, snk

    with _no_devchain():
        fg, snk = build()
        Runtime().run(fg)
        ref = [(idx, t.value) for idx, t in snk.seen]
    with _no_devchain(False):
        fg, snk = build()
        assert len(find_device_chains(fg)) == 1
        Runtime().run(fg)
        got = [(idx, t.value) for idx, t in snk.seen]
    assert snk.n_received == n // 4
    assert got == ref and ref            # same tags at the same indices


def test_dag_member_metrics_bridge():
    """DAG members bridge per-block metrics: the merge member reports one
    in-count PER PORT (each at its path rate) and the composed out-count;
    single-sink regions attribute every member to sink 0."""
    frame = 4096
    data = np.zeros(3 * frame, np.complex64)
    with _no_devchain(False):
        fg, _snk, mg = _diamond_fg("1|1|1", data, frame)
        rt = Runtime()
        rt.start(fg).wait_sync()
    mets = {b.instance_name: b.metrics() for b in fg._blocks if b is not None}
    fused = {nm: m for nm, m in mets.items() if m.get("fused_devchain")}
    assert len(fused) == 6            # h2d + producer + 2 branches + merge + d2h
    mm = fg.wrapped(mg).metrics()
    assert mm["items_in"] == {"in0": 3 * frame // 4, "in1": 3 * frame // 4}
    assert mm["items_out"] == {"out": 3 * frame // 4}
    assert all(m.get("devchain_branch") == 0 for m in fused.values())


def test_dag_span_and_report_carry_sink_attribution():
    """The fused DAG run's span carries per-SINK args + the merge count, and
    doctor.report() surfaces them."""
    from futuresdr_tpu.telemetry import doctor as doc
    from futuresdr_tpu.telemetry import spans

    frame = 4096
    rng = np.random.default_rng(53)
    data = (rng.standard_normal(3 * frame)
            + 1j * rng.standard_normal(3 * frame)).astype(np.complex64)
    spans.enable(True)
    try:
        spans.recorder().drain()
        with _no_devchain(False):
            fg, _snk, _mg = _diamond_fg("1|1|1", data, frame)
            Runtime().run(fg)
        events = spans.recorder().drain()
    finally:
        spans.enable(False)
    dev = [e for e in events if e.cat == "devchain"]
    assert len(dev) == 1
    sinks = dev[0].args["sinks"]
    assert len(sinks) == 1 and not sinks[0]["retired"]
    assert sinks[0]["items_out"] == 3 * frame // 4
    assert dev[0].args["merges"] == 1
    rep = doc.doctor().report(events=events)
    assert rep["devchain"] and rep["devchain"][0]["sinks"] == sinks
    assert rep["devchain"][0]["merges"] == 1


def test_dag_refuses_equal_merge_rate_violation():
    """An equal-mode merge fed by branches at DIFFERENT path rates is a
    rate-contract violation: the whole region declines honestly."""
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=4096)
    b1 = TpuStage([fir_stage(t2, decim=4, fft_len=512, name="b1")],
                  np.complex64)
    b2 = TpuStage([rotator_stage(0.1)], np.complex64)    # 1:1 branch
    mg = TpuMergeStage(add_merge_stage(2))
    d2h = TpuD2H(np.complex64)
    snk = VectorSink(np.complex64)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", b1, "in")
    fg.connect_inplace(h2d, "out", b2, "in")
    fg.connect_inplace(b1, "out", mg, "in0")
    fg.connect_inplace(b2, "out", mg, "in1")
    fg.connect_inplace(mg, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_dag_refuses_cycle_through_host_edges():
    """A region whose sink feeds host blocks that loop back into the root
    declines — the fused block cannot honor the per-hop loop's interior
    queue slack."""
    from futuresdr_tpu.blocks import Combine

    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    frame = 4096
    fg = Flowgraph()
    src = VectorSource(np.zeros(2 * frame, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=frame)
    st = TpuStage([fir_stage(t1, name="p")], np.complex64)
    d2h = TpuD2H(np.complex64)
    comb = Combine(lambda a, b: a + b, np.complex64)
    fg.connect_stream(src, "out", comb, "in0")
    fg.connect_stream(d2h, "out", comb, "in1")           # the loop edge
    fg.connect_stream(comb, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st, "in")
    fg.connect_inplace(st, "out", d2h, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_dag_refuses_merge_with_external_input():
    """A merge joining one branch of the region with a SECOND H2D chain
    (multi-root) declines the whole region."""
    fg = Flowgraph()
    src1 = VectorSource(np.zeros(8192, np.complex64))
    src2 = VectorSource(np.zeros(8192, np.complex64))
    h2d1 = TpuH2D(np.complex64, frame_size=4096)
    h2d2 = TpuH2D(np.complex64, frame_size=4096)
    st1 = TpuStage([rotator_stage(0.1)], np.complex64)
    st2 = TpuStage([rotator_stage(0.2)], np.complex64)
    mg = TpuMergeStage(add_merge_stage(2))
    d2h = TpuD2H(np.complex64)
    snk = VectorSink(np.complex64)
    fg.connect_stream(src1, "out", h2d1, "in")
    fg.connect_stream(src2, "out", h2d2, "in")
    fg.connect_inplace(h2d1, "out", st1, "in")
    fg.connect_inplace(h2d2, "out", st2, "in")
    fg.connect_inplace(st1, "out", mg, "in0")
    fg.connect_inplace(st2, "out", mg, "in1")
    fg.connect_inplace(mg, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    with _no_devchain(False):
        assert find_device_chains(fg) == []


def test_dag_launches_with_cached_autotune_k():
    """A DAG region whose CANONICALIZED shape was tuned by autotune_streamed
    launches fused with the cached megabatch K — the member-split composed
    region and the hand-built DagPipeline share one signature."""
    from futuresdr_tpu.ops import DagPipeline
    from futuresdr_tpu.tpu import instance
    from futuresdr_tpu.tpu.autotune import (_dag_names, _make_sig,
                                            _record_sig, _streamed_cache)

    frame, k = 4096, 2
    rng = np.random.default_rng(59)
    n = 4 * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain(False):
        fg, snk, mg = _diamond_fg("1|1|1", data, frame)
        # the hand-built pipeline a user would tune: same stages, coarser
        # node granularity than the per-member composition
        t1 = firdes.lowpass(0.25, 48).astype(np.float32)
        t2 = firdes.lowpass(0.2, 32).astype(np.float32)
        user = DagPipeline([
            ([fir_stage(t1, name="p")], []),
            ([fir_stage(t2, decim=4, fft_len=512, name="b1")], [0]),
            ([fir_stage(t2, decim=4, fft_len=512, name="b2")], [0]),
            ([add_merge_stage(2), mag2_stage()], [1, 2]),
        ], np.complex64)
        _record_sig(_make_sig(instance().platform, np.complex64,
                              _dag_names(user)), k)
        try:
            Runtime().run(fg)
            m = fg.wrapped(mg).metrics()
            assert m.get("fused_devchain") is True, m
            assert m.get("frames_per_dispatch") == k, m
            assert m["devchain_frames"] == 4 and m["devchain_dispatches"] == 2
        finally:
            _streamed_cache.clear()
    np.testing.assert_array_equal(
        snk.items().shape, (n // 4,))


def test_ctrl_retune_in_replay_window_warns(caplog):
    """The ROADMAP caveat made observable: a ctrl retune landing inside an
    active replay window logs a structured warning naming the block and the
    pending replayed-frame count."""
    import asyncio
    import logging

    from futuresdr_tpu.types import Pmt

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    k = TpuKernel([fir_stage(taps, name="f")], np.complex64, frame_size=4096)
    k.meta.instance_name = "replay_kernel"
    asyncio.run(k.init(k.mio, k.meta))
    # seed an active replay window: two queued groups of one frame each
    k._replay_queue.append((3, (), ((4096, (), 0),), False))
    k._replay_queue.append((4, (), ((4096, (), 0),), False))
    k._replay_high = 4
    pmt = Pmt.map({"stage": "f", "taps": taps.tolist()})
    with caplog.at_level(logging.WARNING, logger="futuresdr_tpu.tpu.kernel"):
        res = asyncio.run(k.ctrl_handler(None, k.mio, k.meta, pmt))
    assert res == Pmt.ok()
    recs = [r for r in caplog.records
            if "replay window" in r.getMessage()]
    assert recs, caplog.text
    msg = recs[0].getMessage()
    assert "replay_kernel" in msg and "2 replayed frame(s)" in msg
    # window drained → no further warning
    caplog.clear()
    k._replay_queue.clear()
    with caplog.at_level(logging.WARNING, logger="futuresdr_tpu.tpu.kernel"):
        asyncio.run(k.ctrl_handler(None, k.mio, k.meta, pmt))
    assert not [r for r in caplog.records
                if "replay window" in r.getMessage()]
    assert k._replay_high == -1          # disarmed once drained


def test_concat_merge_partial_tail_bit_equals_actor():
    """EOS partial tail through a CONCAT merge: the concat layout cannot
    represent a ragged tail as a valid-prefix count, so BOTH paths emit only
    the full frames (actor TpuMergeStage and fused DagPipeline.concat_sinks
    apply the same rule) — fused stays bit-identical to actor, and no
    zero-padding leaks into the output as data."""
    frame = 4096
    rng = np.random.default_rng(61)
    n = 3 * frame + 1000                  # ragged EOS tail
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)
    with _no_devchain():
        fg, snk, _ = _diamond_fg("1|1|1", data, frame, merge="concat")
        Runtime().run(fg)
        ref = snk.items()
    with _no_devchain(False):
        fg, snk, _ = _diamond_fg("1|1|1", data, frame, merge="concat")
        assert len(find_device_chains(fg)) == 1
        Runtime().run(fg)
        got = snk.items()
    # only the 3 full frames joined (5/4 items per input item); the ragged
    # tail dropped on both sides — and nothing in the output is pad garbage
    assert len(ref) == 3 * frame + 3 * frame // 4
    np.testing.assert_array_equal(got, ref)


def test_mixed_broadcast_truncates_not_declines():
    """A kernel-plane broadcast with one NON-fusable consumer no longer
    strands the graph: the producer prefix fuses up to (and including) the
    broadcast owner — whose port group still serves the host tap — and the
    clean branch chain fuses as its own region (the round-8/11 behavior,
    regression-pinned)."""
    t1 = firdes.lowpass(0.25, 48).astype(np.float32)
    t2 = firdes.lowpass(0.2, 32).astype(np.float32)
    frame = 4096
    rng = np.random.default_rng(67)
    n = 3 * frame
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
            ).astype(np.complex64)

    def build():
        fg = Flowgraph()
        src = VectorSource(data)
        k1 = TpuKernel([fir_stage(t1, name="k1")], np.complex64,
                       frame_size=frame)
        k2 = TpuKernel([rotator_stage(0.1)], np.complex64, frame_size=frame)
        b1 = TpuKernel([fir_stage(t2, decim=4, fft_len=512, name="b1")],
                       np.complex64, frame_size=frame)
        b2 = TpuKernel([mag2_stage()], np.complex64, frame_size=frame)
        tap = VectorSink(np.complex64)        # the non-fusable consumer
        s1 = VectorSink(np.complex64)
        s2 = VectorSink(np.float32)
        fg.connect(src, k1, k2)
        fg.connect_stream(k2, "out", b1, "in")     # mixed broadcast: b1, b2
        fg.connect_stream(k2, "out", b2, "in")     # are fusable, tap is not
        fg.connect_stream(k2, "out", tap, "in")
        fg.connect(b1, s1)
        fg.connect(b2, s2)
        return fg, (tap, s1, s2), (k1, b1)

    with _no_devchain():
        fg, snks, _ = build()
        Runtime().run(fg)
        refs = [s.items() for s in snks]
    with _no_devchain(False):
        fg, snks, (k1, b1) = build()
        chains = find_device_chains(fg)
        # the k1→k2 prefix fuses (truncated at the mixed broadcast); b1 and
        # b2 are single-member runs (len < 2) and stay actor blocks
        assert len(chains) == 1 and not chains[0].dag and not chains[0].fanout
        assert [type(m).__name__ for m in chains[0]] == \
            ["TpuKernel", "TpuKernel"]
        Runtime().run(fg)
        for s, r in zip(snks, refs):
            np.testing.assert_array_equal(s.items(), r)
        assert k1.extra_metrics().get("fused_devchain")


def test_message_ctrl_feedback_loop_still_fuses():
    """A MESSAGE edge closing a loop (sink → host measurement → ctrl of a
    devchain_static member: AGC/AFC-style retune feedback) is NOT a host
    cycle — message inboxes are unbounded and ctrl applies between
    dispatches, so only backpressure-coupled (stream/inplace) loops decline."""
    from futuresdr_tpu.blocks import Apply

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(np.zeros(8192, np.complex64))
    h2d = TpuH2D(np.complex64, frame_size=4096)
    st = TpuStage([fir_stage(taps, name="f")], np.complex64)
    st.devchain_static = True            # live retunes expected and opted in
    d2h = TpuD2H(np.complex64)
    meas = Apply(lambda x: x, np.complex64)    # stand-in measurement block
    meas.add_message_output("ctrl_out")
    snk = VectorSink(np.complex64)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st, "in")
    fg.connect_inplace(st, "out", d2h, "in")
    fg.connect_stream(d2h, "out", meas, "in")
    fg.connect_stream(meas, "out", snk, "in")
    fg.connect_message(meas, "ctrl_out", st, "ctrl")   # the feedback edge
    with _no_devchain(False):
        assert len(find_device_chains(fg)) == 1        # fuses, not a cycle
