"""Rational resampler stage tests + fused FM front-end (TPU compute plane)."""

import numpy as np
import pytest
from scipy import signal as sps

from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import (Pipeline, resample_stage, rotator_stage, fir_stage,
                               quad_demod_stage)


def run_pipeline(pipe, x, frame):
    fn, carry = pipe.compile(frame)
    outs = []
    for i in range(0, len(x) - frame + 1, frame):
        carry, y = fn(carry, x[i:i + frame])
        outs.append(np.asarray(y))
    return np.concatenate(outs)


@pytest.mark.parametrize("interp,decim", [(3, 2), (2, 1), (1, 4), (5, 3)])
def test_resample_stage_tone_scaling(interp, decim):
    taps = (firdes.lowpass(0.4 / max(interp, decim), 32 * max(interp, decim) + 1)
            * interp).astype(np.float32)
    pipe = Pipeline([resample_stage(interp, decim, taps, fft_len=1024)], np.complex64)
    f0 = 0.02
    n = pipe.frame_multiple * max(1, 16384 // pipe.frame_multiple)
    x = np.exp(2j * np.pi * f0 * np.arange(4 * n)).astype(np.complex64)
    y = run_pipeline(pipe, x, n)
    assert len(y) == 4 * n * interp // decim
    w = min(len(y) - 256, 4096)
    seg = y[256:256 + w]
    spec = np.abs(np.fft.fft(seg * np.hanning(w)))
    peak = np.fft.fftfreq(w)[np.argmax(spec)]
    assert abs(peak - f0 * decim / interp) < 2e-3


def test_resample_stage_matches_upfirdn():
    interp, decim = 3, 2
    taps = (firdes.lowpass(0.4 / 3, 97) * interp).astype(np.float32)
    pipe = Pipeline([resample_stage(interp, decim, taps, fft_len=512)], np.float32)
    m = pipe.frame_multiple
    n = m * max(1, 4096 // m)
    x = np.random.default_rng(0).standard_normal(4 * n).astype(np.float32)
    y = run_pipeline(pipe, x, n)
    ref = sps.upfirdn(taps, x, up=interp, down=decim)[:len(y)]
    np.testing.assert_allclose(y, ref, rtol=1e-3, atol=1e-3)


def test_fused_fm_frontend():
    """rotate → decimating FIR → quadrature demod as ONE program (the FM receiver's
    front half on the TPU)."""
    fs = 1e6
    decim = 4
    fdev = 75e3
    n = 1 << 18
    t = np.arange(n) / fs
    msg = np.sin(2 * np.pi * 3e3 * t)
    offset = 100e3
    phase = 2 * np.pi * fdev * np.cumsum(msg) / fs
    iq = np.exp(1j * (phase + 2 * np.pi * offset * t)).astype(np.complex64)

    taps = firdes.lowpass(0.5 / decim * 0.8, 128).astype(np.float32)
    pipe = Pipeline([
        rotator_stage(-2 * np.pi * offset / fs),
        fir_stage(taps, decim=decim, fft_len=2048),
        quad_demod_stage(fs / decim / (2 * np.pi * fdev)),
    ], np.complex64)
    frame = pipe.frame_multiple * max(1, (1 << 16) // pipe.frame_multiple)
    y = run_pipeline(pipe, iq, frame)
    fs2 = fs / decim
    # the demodulated spectrum must be dominated by the 3 kHz message tone
    seg = y[2000:2000 + 32768]
    spec = np.abs(np.fft.rfft(seg * np.hanning(len(seg))))
    freqs = np.fft.rfftfreq(len(seg), 1 / fs2)
    peak = freqs[np.argmax(spec[10:]) + 10]
    assert abs(peak - 3e3) < 50.0, peak
    tone_pow = spec[np.abs(freqs - 3e3) < 100].max()
    other = spec[(freqs > 500) & (np.abs(freqs - 3e3) > 500)].max()
    assert tone_pow > 5 * other