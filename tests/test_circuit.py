"""Circuit (in-place) buffer tests: custom source/add/sink blocks exercising the
zero-copy frame circulation (reference: `tests/connect_circuit.rs:4-80`)."""

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Kernel
from futuresdr_tpu.runtime.buffer.circuit import Circuit


class InplaceSource(Kernel):
    """Fills empty circuit frames with a ramp, n_frames times."""

    def __init__(self, circuit: Circuit, n_frames: int):
        super().__init__()
        self.circuit = circuit
        self.n_frames = n_frames
        self._sent = 0
        self.output = self.add_inplace_output("out", np.float32)

    async def work(self, io, mio, meta):
        while self._sent < self.n_frames:
            buf = self.circuit.get_empty()
            if buf is None:
                return          # wait: put_empty() notifies us
            buf[:] = np.arange(len(buf), dtype=np.float32) + self._sent
            self.output.put_full(buf, len(buf))
            self._sent += 1
        io.finished = True


class InplaceAdd(Kernel):
    """Mutates frames in place (+offset) and forwards them."""

    def __init__(self, offset: float):
        super().__init__()
        self.offset = offset
        self.input = self.add_inplace_input("in", np.float32)
        self.output = self.add_inplace_output("out", np.float32)

    async def work(self, io, mio, meta):
        while True:
            item = self.input.get_full()
            if item is None:
                break
            buf, n, _tags = item
            buf[:n] += self.offset
            self.output.put_full(buf, n)
        if self.input.finished() and len(self.input) == 0:
            io.finished = True


class InplaceSink(Kernel):
    """Checks frames and returns them to the circuit."""

    def __init__(self, circuit: Circuit):
        super().__init__()
        self.circuit = circuit
        self.received = []
        self.input = self.add_inplace_input("in", np.float32)

    async def work(self, io, mio, meta):
        while True:
            item = self.input.get_full()
            if item is None:
                break
            buf, n, _tags = item
            self.received.append(buf[:n].copy())
            self.circuit.put_empty(buf)
        if self.input.finished() and len(self.input) == 0:
            io.finished = True


def test_circuit_pipeline_zero_copy():
    circuit = Circuit(n_buffers=3, items_per_buffer=256, dtype=np.float32)
    fg = Flowgraph()
    src = InplaceSource(circuit, n_frames=50)
    add1 = InplaceAdd(10.0)
    add2 = InplaceAdd(100.0)
    snk = InplaceSink(circuit)
    fg.connect_inplace(src, "out", add1, "in")
    fg.connect_inplace(add1, "out", add2, "in")
    fg.connect_inplace(add2, "out", snk, "in")
    fg.close_circuit(circuit, src)
    Runtime().run(fg)
    assert len(snk.received) == 50
    for i, frame in enumerate(snk.received):
        np.testing.assert_array_equal(frame, np.arange(256, dtype=np.float32) + i + 110.0)


def test_circuit_backpressure():
    """With fewer buffers than frames, the source must recycle (backpressure works)."""
    circuit = Circuit(n_buffers=2, items_per_buffer=64, dtype=np.float32)
    fg = Flowgraph()
    src = InplaceSource(circuit, n_frames=20)
    snk = InplaceSink(circuit)
    fg.connect_inplace(src, "out", snk, "in")
    fg.close_circuit(circuit, src)
    Runtime().run(fg)
    assert len(snk.received) == 20


def test_inplace_reconnect_idempotent_and_mutable_broadcast_refused():
    """Re-materializing the same flowgraph re-connects the same peer — the
    port must not double-register it (frames would push twice and the
    broadcast guard would misfire on a single-reader circuit). A GENUINE
    broadcast of a writable host frame still refuses (mutable circuit frames
    are single-reader; immutable device-plane frames may broadcast)."""
    import numpy as np
    import pytest

    from futuresdr_tpu.runtime.buffer.circuit import InplaceInput, InplaceOutput

    op, ip = InplaceOutput("out"), InplaceInput("in")
    op.connect(ip)
    op.connect(ip)                      # rerun of the same flowgraph
    buf = np.zeros(4, np.float32)
    op.put_full(buf, 4)                 # single reader: no raise, ONE frame
    assert len(ip) == 1 and op.queue_depth() == 1
    ip2 = InplaceInput("in2")
    op.connect(ip2)                     # genuine second consumer
    with pytest.raises(RuntimeError, match="single-reader"):
        op.put_full(buf, 4)
    buf.flags.writeable = False         # immutable frames broadcast fine
    op.put_full(buf, 4)
    assert len(ip) == 2 and len(ip2) == 1
