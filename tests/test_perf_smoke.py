"""Tiny-workload smoke of the perf harnesses' CPU paths.

The perf scripts live outside the suite, so an API drift can break one
silently: ``perf/inplace.py`` sat broken from the stream-tag transport change
(``get_full`` grew a tags element) until round 5 because nothing executed it
in CI. Each harness runs here in a subprocess with a workload small enough to
finish in seconds — the assertion is "prints its CSV and exits 0", not any
rate. TPU-needing scripts (fm/wlan/lora/streamed_ab sweeps) stay out: their
CPU fallbacks are exercised via bench.py and their own tests."""

import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SMOKES = [
    ("inplace", ["--runs", "1", "--frames", "20", "--items", "16384"]),
    ("null", ["--runs", "1", "--pipes", "2", "--stages", "2",
              "--samples", "500000"]),
    ("null_rand", ["--runs", "1", "--pipes", "2", "--stages", "2",
                   "--samples", "200000"]),
    ("msg", ["--runs", "1", "--stages", "2", "--burst", "2000"]),
    ("buffer_size", ["--runs", "1", "--samples", "500000",
                     "--sizes", "65536"]),
    ("latency", ["--runs", "1", "--stages", "2", "--samples", "100000"]),
    ("fir", ["--runs", "1", "--pipes", "2", "--stages", "2",
             "--samples", "500000"]),
    ("buffer_rand", ["--runs", "1", "--samples", "200000", "--stages", "2",
                     "--rings", "4096"]),
    ("micro", ["--window", "16384", "--iters", "3"]),
]


@pytest.mark.integration
@pytest.mark.parametrize("name,args", _SMOKES, ids=[s[0] for s in _SMOKES])
def test_perf_harness_smoke(name, args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "perf", f"{name}.py"), *args],
        capture_output=True, text=True, timeout=180, cwd=_ROOT, env=env)
    assert r.returncode == 0, f"{name}: rc={r.returncode}\n{r.stderr[-1500:]}"
    rows = [ln for ln in r.stdout.splitlines() if "," in ln]
    assert len(rows) >= 2, f"{name}: no CSV rows\n{r.stdout[-800:]}"
