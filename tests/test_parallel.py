"""Multi-chip sharding tests on the virtual 8-device CPU mesh.

Validates that the sequence-parallel stream ops (halo exchange over ppermute) are
bit-identical to the single-device computation, and that the sharded MCLDNN train step
runs SPMD (the driver's dryrun_multichip path).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from scipy import signal as sps
from jax.sharding import NamedSharding, PartitionSpec as P

from futuresdr_tpu.parallel import (make_mesh, factor_devices, shard_params,
                                    sp_fir, sp_fir_fft_mag2, sp_channelizer,
                                    sp_channelizer_a2a)

pytestmark = pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")


def test_factor_devices():
    assert factor_devices(8, 2) == (4, 2)
    assert factor_devices(4, 2) == (2, 2)
    assert factor_devices(1, 2) == (1, 1)
    assert factor_devices(6, 2) == (3, 2)


def test_sp_fir_matches_global():
    mesh = make_mesh(("sp",), shape=(8,))
    taps = np.hanning(63).astype(np.float32)
    x = np.random.default_rng(0).standard_normal(8 * 512).astype(np.float32)
    xs = jax.device_put(x, NamedSharding(mesh, P("sp")))
    y = jax.jit(sp_fir(taps, mesh))(xs)
    ref = np.convolve(np.concatenate([np.zeros(62, np.float32), x]), taps, mode="valid")
    np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)


def test_sp_fir_fft_mag2_matches_global():
    mesh = make_mesh(("sp",), shape=(8,))
    taps = np.hanning(64).astype(np.float32)
    fft_size = 128
    x = (np.random.default_rng(1).standard_normal(8 * 4 * fft_size)).astype(np.complex64)
    xs = jax.device_put(x, NamedSharding(mesh, P("sp")))
    y = np.asarray(jax.jit(sp_fir_fft_mag2(taps, fft_size, mesh))(xs))
    filt = sps.lfilter(taps, 1.0, x)
    ref = np.abs(np.fft.fft(filt.reshape(-1, fft_size), axis=1)) ** 2
    np.testing.assert_allclose(y, ref.reshape(-1), rtol=1e-2, atol=1e-2)


def test_sp_channelizer_routes_tone():
    mesh = make_mesh(("sp",), shape=(8,))
    N = 4
    n = 8 * 64 * N
    c = 3
    x = np.exp(1j * 2 * np.pi * (c / N) * np.arange(n)).astype(np.complex64)
    from futuresdr_tpu.blocks.pfb import pfb_default_taps
    taps = pfb_default_taps(N)
    xs = jax.device_put(x, NamedSharding(mesh, P("sp")))
    y = np.asarray(jax.jit(sp_channelizer(N, taps, mesh))(xs))   # [N, n/N]
    powers = (np.abs(y[:, 32:]) ** 2).mean(axis=1)
    assert np.argmax(powers) == c
    assert powers[c] > 50 * np.delete(powers, c).max()


def test_sp_channelizer_a2a_matches_ring_variant():
    """Ulysses-style all-to-all resharding must produce the same channels as the
    time-sharded (ring/halo) variant."""
    mesh = make_mesh(("sp",), shape=(8,))
    N = 8
    n = 8 * 32 * N
    rng = np.random.default_rng(9)
    x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(np.complex64)
    from futuresdr_tpu.blocks.pfb import pfb_default_taps
    taps = pfb_default_taps(N)
    xs = jax.device_put(x, NamedSharding(mesh, P("sp")))
    y_ring = np.asarray(jax.jit(sp_channelizer(N, taps, mesh))(xs))
    y_a2a = np.asarray(jax.jit(sp_channelizer_a2a(N, taps, mesh))(xs))
    assert y_a2a.shape == y_ring.shape == (N, n // N)
    np.testing.assert_allclose(y_a2a, y_ring, rtol=1e-4, atol=1e-5)


def test_sharded_train_step_spmd():
    import optax
    from futuresdr_tpu.models import MCLDNN, init_params, make_train_step

    mesh = make_mesh(("dp", "mp"))
    model = MCLDNN(n_classes=5, conv_features=8, lstm_features=16)
    params = init_params(model, n=64)
    params, shardings = shard_params(params, mesh, axis="mp")
    # at least one large leaf must actually be sharded over mp
    specs = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda s: s.spec, shardings,
                               is_leaf=lambda x: isinstance(x, NamedSharding)))
    assert any("mp" in str(s) for s in specs)
    opt = optax.adam(1e-3)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(model, opt))
    b = 2 * mesh.shape["dp"]
    iq = jax.device_put(np.random.default_rng(0).standard_normal((b, 2, 64)).astype(np.float32),
                        NamedSharding(mesh, P("dp")))
    labels = jax.device_put(np.zeros(b, np.int32), NamedSharding(mesh, P("dp")))
    params2, opt_state, loss, acc = step(params, opt_state, iq, labels)
    assert np.isfinite(float(loss))
    # params keep their sharding through the step (no silent full replication)
    leaf = jax.tree_util.tree_leaves(params2)[0]
    assert leaf.sharding is not None


def test_sp_kernel_block_in_flowgraph():
    """A flowgraph block computing SPMD over the virtual 8-device mesh."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource, VectorSink
    from futuresdr_tpu.tpu import SpKernel
    from scipy import signal as sps

    mesh = make_mesh(("sp",), shape=(8,))
    taps = np.hanning(64).astype(np.float32)
    fft_size = 128
    frame = 8 * 8 * fft_size
    fn = sp_fir_fft_mag2(taps, fft_size, mesh)
    data = np.random.default_rng(3).standard_normal(4 * frame).astype(np.complex64)

    fg = Flowgraph()
    src = VectorSource(data)
    spk = SpKernel(fn, mesh, np.complex64, np.float32, frame)
    snk = VectorSink(np.float32)
    fg.connect(src, spk, snk)
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == 4 * frame
    filt = sps.lfilter(taps, 1.0, data[:frame])
    ref = (np.abs(np.fft.fft(filt.reshape(-1, fft_size), axis=1)) ** 2).reshape(-1)
    np.testing.assert_allclose(got[:frame], ref, rtol=1e-2, atol=1e-2)


def test_graft_entry_points():
    import sys, os
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from __graft_entry__ import entry, dryrun_multichip

    fn, args = entry()
    y = jax.jit(fn)(*args)
    assert y.shape == (8, 11)
    dryrun_multichip(8)


def test_sp_fir_stream_bitmatches_streaming_stage_across_frames():
    """Cross-frame carry: N frames through the stateful sharded FIR == the
    single-device streaming fir_stage, bit-for-bit at frame boundaries."""
    from futuresdr_tpu.parallel import sp_fir_stream
    from futuresdr_tpu.ops import fir_stage
    from futuresdr_tpu.ops.stages import Pipeline

    mesh = make_mesh(("sp",), shape=(8,))
    taps = np.hanning(31).astype(np.float32)
    frame = 8 * 512
    rng = np.random.default_rng(5)
    frames = [
        (rng.standard_normal(frame) + 1j * rng.standard_normal(frame))
        .astype(np.complex64) for _ in range(4)]

    fn, init_carry = sp_fir_stream(taps, mesh)
    jfn = jax.jit(fn, donate_argnums=(0,))
    carry = init_carry(np.complex64)
    got = []
    for f in frames:
        carry, y = jfn(carry, jax.device_put(f, NamedSharding(mesh, P("sp"))))
        got.append(np.asarray(y))
    got = np.concatenate(got)

    # single-device streaming reference: the overlap-save fir_stage pipeline
    pipe = Pipeline([fir_stage(taps)], np.complex64)
    pfn, pcarry = pipe.compile(frame, donate=False)
    ref = []
    for f in frames:
        pcarry, y = pfn(pcarry, jnp.asarray(f))
        ref.append(np.asarray(y))
    ref = np.concatenate(ref)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)
    # explicitly check continuity ACROSS the first frame boundary
    boundary = slice(frame - 16, frame + 16)
    np.testing.assert_allclose(got[boundary], ref[boundary], rtol=1e-4, atol=1e-4)


def test_sp_kernel_stateful_in_flowgraph():
    """SpKernel with init_carry: multi-frame sharded streaming matches scipy lfilter
    over the WHOLE stream (no frame-boundary discontinuity)."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource, VectorSink
    from futuresdr_tpu.tpu import SpKernel
    from futuresdr_tpu.parallel import sp_fir_stream
    from scipy import signal as sps

    mesh = make_mesh(("sp",), shape=(8,))
    taps = np.hanning(33).astype(np.float32)
    frame = 8 * 256
    data = (np.random.default_rng(9).standard_normal(4 * frame)
            .astype(np.complex64))
    fn, init_carry = sp_fir_stream(taps, mesh)

    fg = Flowgraph()
    src = VectorSource(data)
    spk = SpKernel(fn, mesh, np.complex64, np.complex64, frame,
                   init_carry=init_carry)
    snk = VectorSink(np.complex64)
    fg.connect(src, spk, snk)
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == 4 * frame
    ref = sps.lfilter(taps, 1.0, data)        # continuous over all frames
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_pp_pipeline_matches_sequential():
    """GPipe-style pipeline over a 4-device pp axis: microbatched outputs equal
    running the stages sequentially on one device."""
    import jax
    import jax.numpy as jnp
    from futuresdr_tpu.parallel import make_mesh, make_pp_pipeline, P, NamedSharding

    n_stages, n_micro, mb, d = 4, 6, 3, 16
    mesh = make_mesh(("pp",), shape=(n_stages,), devices=jax.devices()[:n_stages])
    rng = np.random.default_rng(0)
    W = jnp.asarray(rng.standard_normal((n_stages, d, d)) / np.sqrt(d),
                    dtype=jnp.float32)
    x = jnp.asarray(rng.standard_normal((n_micro, mb, d)), dtype=jnp.float32)

    def stage(w, a):
        return jnp.tanh(a @ w)

    Wsh = jax.device_put(W, NamedSharding(mesh, P("pp")))
    fn = jax.jit(make_pp_pipeline(stage, n_stages, n_micro, mesh))
    y = np.asarray(fn(Wsh, x))

    ref = x
    for s in range(n_stages):
        ref = jnp.tanh(ref @ W[s])
    np.testing.assert_allclose(y, np.asarray(ref), atol=1e-5)


def test_pp_pipeline_full_mesh():
    """pp over all 8 virtual devices, odd microbatch count, complex64 dtype
    (exercises the complex carry/accumulator/ppermute path)."""
    import jax
    import jax.numpy as jnp
    from futuresdr_tpu.parallel import make_mesh, make_pp_pipeline, P, NamedSharding

    n_stages, n_micro, d = 8, 5, 8
    mesh = make_mesh(("pp",), shape=(n_stages,))
    rng = np.random.default_rng(1)
    W = jnp.asarray((rng.standard_normal((n_stages, d, d))
                     + 1j * rng.standard_normal((n_stages, d, d))
                     ).astype(np.complex64))
    x = jnp.asarray((rng.standard_normal((n_micro, d))
                     + 1j * rng.standard_normal((n_micro, d))
                     ).astype(np.complex64))

    def stage(w, a):
        return a @ w / jnp.complex64(d)

    fn = jax.jit(make_pp_pipeline(stage, n_stages, n_micro, mesh))
    y = np.asarray(fn(jax.device_put(W, NamedSharding(mesh, P("pp"))), x))
    ref = x
    for s in range(n_stages):
        ref = ref @ W[s] / d
    np.testing.assert_allclose(y, np.asarray(ref), rtol=2e-5, atol=1e-5)


def test_sp_dechirp_scan_matches_host():
    """Time-sharded LoRa preamble scan: peak bins and concentrations bit-match
    the host scan (same chirp, same windows) with one right-halo ppermute —
    a real frame's preamble lights up constant bins at high concentration."""
    from futuresdr_tpu.parallel import sp_dechirp_scan
    from futuresdr_tpu.models.lora.phy import (LoraParams, modulate_frame,
                                               _downchirp)
    sf = 7
    n = 1 << sf
    hop = n // 4
    p = LoraParams(sf=sf, cr=2)
    rng = np.random.default_rng(3)
    sig = np.concatenate([np.zeros(777, np.complex64), modulate_frame(b"spscan", p)])
    total = 8 * 1024                                 # 8 shards x 1024
    x = np.zeros(total, np.complex64)
    x[:len(sig)] = sig[:total]
    x = (x + 0.02 * (rng.standard_normal(total)
                     + 1j * rng.standard_normal(total))).astype(np.complex64)

    mesh = make_mesh(("sp",), shape=(8,))
    xs = jax.device_put(x, NamedSharding(mesh, P("sp")))
    bins, conc = jax.jit(sp_dechirp_scan(sf, mesh, hop))(xs)
    bins, conc = np.asarray(bins), np.asarray(conc)
    assert bins.shape == (total // hop,)

    # host reference: same windows, same chirp, zero-padded tail
    ext = np.concatenate([x, np.zeros(n, np.complex64)])
    down = _downchirp(n)
    for w in range(total // hop):
        spec = np.abs(np.fft.fft(ext[w * hop:w * hop + n] * down))
        assert bins[w] == int(np.argmax(spec)), w
        ref_c = spec.max() ** 2 / max(np.sum(spec ** 2), 1e-12)
        assert abs(conc[w] - ref_c) < 1e-5, w

    # the preamble region shows high concentration, and windows at the SAME hop
    # phase (n apart) dechirp to the same bin — the detect_frames criterion
    pre = slice(780 // hop + 1, (780 + 6 * n) // hop - 1)
    assert (conc[pre] > 0.3).all()
    pre_bins = bins[pre]
    for phase in range(n // hop):
        same_phase = pre_bins[phase::n // hop]
        assert len(set(same_phase.tolist())) <= 2, (phase, same_phase)


def test_sp_fir_random_shapes_fuzz():
    """Seeded sweep: random tap counts/lengths/dtypes bit-match the global FIR
    on the virtual mesh (halo-exchange edge cases live at odd tap counts)."""
    rng = np.random.default_rng(808)
    mesh = make_mesh(("sp",), shape=(8,))
    for trial in range(4):
        nt = int(rng.integers(2, 97))
        per_shard = int(rng.integers(max(nt, 64), 512))
        n = 8 * per_shard
        complex_ = bool(rng.integers(0, 2))
        taps = rng.standard_normal(nt).astype(np.float32)
        if complex_:
            x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)
                 ).astype(np.complex64)
        else:
            x = rng.standard_normal(n).astype(np.float32)
        xs = jax.device_put(x, NamedSharding(mesh, P("sp")))
        y = np.asarray(jax.jit(sp_fir(taps, mesh))(xs))
        ref = np.convolve(x, taps)[:n].astype(x.dtype)
        np.testing.assert_allclose(y, ref, atol=2e-3), (trial, nt, per_shard)


def test_composed_2d_mesh_sp_plus_pp_with_midstream_checkpoint(tmp_path):
    """Round-4 verdict item 5: a 2D (pp, sp) mesh with SpKernel (sequence
    parallelism along sp) and PpKernel (pipeline stages along pp) in ONE
    flowgraph, carry chained — interrupted halfway, checkpointed (sharded
    carry), restored onto fresh kernels, and finished — bit-matched against
    the uninterrupted run and a single-device reference."""
    import jax
    import jax.numpy as jnp

    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.parallel import make_mesh, sp_fir_stream
    from futuresdr_tpu.tpu import PpKernel, SpKernel
    from futuresdr_tpu.utils.checkpoint import load_pytree, save_pytree

    pp_n, sp_n = 2, 2
    devices = jax.devices()[:pp_n * sp_n]
    mesh = make_mesh(("pp", "sp"), shape=(pp_n, sp_n), devices=devices)
    d, micro_b = 8, 2
    F = 128 * sp_n
    n_micro = F // (micro_b * d)
    taps = np.hanning(32).astype(np.float32)
    rng = np.random.default_rng(17)
    W = rng.standard_normal((pp_n, d, d)).astype(np.float32) / 4.0
    data = rng.standard_normal(4 * F).astype(np.float32)

    def build(carry_override=None, n_frames=4, offset=0):
        fn, initc = sp_fir_stream(taps, mesh)
        fg = Flowgraph()
        src = VectorSource(data[offset:offset + n_frames * F])
        snk = VectorSink(np.float32)
        spk = SpKernel(fn, mesh, np.float32, np.float32, F, init_carry=initc)
        ppk = PpKernel(lambda w, a: jnp.tanh(a @ w), W, mesh, np.float32,
                       np.float32, micro_shape=(micro_b, d), n_micro=n_micro,
                       axis="pp", frames_in_flight=1)
        if carry_override is not None:
            spk._carry = jax.tree.map(
                lambda f, l: jax.device_put(jnp.asarray(l), f.sharding),
                spk._carry, carry_override)
        fg.connect(src, spk, ppk, snk)
        return fg, spk, snk

    fg_a, _s, snk_a = build()
    Runtime().run(fg_a)
    full = np.asarray(snk_a.items())
    assert full.shape == (4 * F,)

    fg_b, spk_b, snk_b = build(n_frames=2)
    Runtime().run(fg_b)
    ckpt = str(tmp_path / "carry")
    save_pytree(ckpt, {"carry": jax.tree.map(np.asarray, spk_b._carry)})
    carry_l = load_pytree(ckpt)["carry"]
    fg_c, _s2, snk_c = build(carry_override=carry_l, n_frames=2, offset=2 * F)
    Runtime().run(fg_c)
    resumed = np.concatenate([np.asarray(snk_b.items()),
                              np.asarray(snk_c.items())])
    np.testing.assert_allclose(resumed, full, rtol=2e-5, atol=2e-5)

    # single-device reference: stateful FIR then the pp stages on the host
    mesh1 = make_mesh(("sp",), shape=(1,), devices=devices[:1])
    fn1, init1 = sp_fir_stream(taps, mesh1)
    j1 = jax.jit(fn1, donate_argnums=(0,))
    c1 = init1(np.float32)
    ref = []
    for k in range(4):
        c1, yk = j1(c1, jnp.asarray(data[k * F:(k + 1) * F]))
        ref.append(np.asarray(yk))
    ref = np.concatenate(ref).reshape(-1, micro_b, d)
    for s_ in range(pp_n):
        ref = np.tanh(ref @ W[s_])
    np.testing.assert_allclose(full, ref.reshape(-1), rtol=1e-4, atol=1e-4)


def test_pp_kernel_partial_tail_zero_padded():
    """Round-4 advisory: PpKernel must zero-pad the final partial frame and
    emit the valid prefix (the TpuKernel tail contract) instead of silently
    dropping up to frame_size-1 items at EOS."""
    import jax
    import jax.numpy as jnp

    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.parallel import make_mesh
    from futuresdr_tpu.tpu import PpKernel

    n_stages, d, micro_b, n_micro = 2, 4, 2, 3
    mesh = make_mesh(("pp",), shape=(n_stages,),
                     devices=jax.devices()[:n_stages])
    rng = np.random.default_rng(5)
    W = (rng.standard_normal((n_stages, d, d)) / 4.0).astype(np.float32)

    def apply_stage(w, a):
        return jnp.tanh(a @ w)

    frame_items = n_micro * micro_b * d
    tail = 10                                  # < frame_items, not a row multiple
    data = rng.standard_normal(frame_items + tail).astype(np.float32)

    fg = Flowgraph()
    src, snk = VectorSource(data), VectorSink(np.float32)
    fg.connect(src, PpKernel(apply_stage, W, mesh, np.float32, np.float32,
                             micro_shape=(micro_b, d), n_micro=n_micro), snk)
    Runtime().run(fg)
    got = np.asarray(snk.items())
    assert got.shape == (frame_items + tail,), "partial tail was dropped"

    padded = np.zeros(2 * frame_items, dtype=np.float32)
    padded[:len(data)] = data
    ref = padded.reshape(-1, micro_b, d)
    for s in range(n_stages):
        ref = np.tanh(ref @ W[s])
    np.testing.assert_allclose(got, ref.reshape(-1)[:len(data)],
                               rtol=2e-5, atol=2e-5)


def test_pp_kernel_flowgraph_matches_host():
    """PpKernel: a GPipe pipeline across the mesh's pp axis, fed from a REAL
    flowgraph — output matches applying the stages sequentially on the host,
    and update_params swaps weights between frames."""
    import jax
    import jax.numpy as jnp

    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.parallel import make_mesh
    from futuresdr_tpu.tpu import PpKernel

    n_stages, d, micro_b, n_micro = 4, 8, 3, 5
    mesh = make_mesh(("pp",), shape=(n_stages,),
                     devices=jax.devices()[:n_stages])
    rng = np.random.default_rng(0)
    W = (rng.standard_normal((n_stages, d, d)) / 4.0).astype(np.float32)

    def apply_stage(w, a):
        return jnp.tanh(a @ w)

    frame_items = n_micro * micro_b * d
    data = rng.standard_normal(3 * frame_items).astype(np.float32)

    fg = Flowgraph()
    src, snk = VectorSource(data), VectorSink(np.float32)
    ppk = PpKernel(apply_stage, W, mesh, np.float32, np.float32,
                   micro_shape=(micro_b, d), n_micro=n_micro)
    fg.connect(src, ppk, snk)
    Runtime().run(fg)
    got = np.asarray(snk.items())
    assert got.shape == (3 * frame_items,)

    x = data.reshape(-1, micro_b, d)
    ref = x
    for s in range(n_stages):
        ref = np.tanh(ref @ W[s])
    np.testing.assert_allclose(got, ref.reshape(-1), rtol=2e-5, atol=2e-5)

    # weight swap: a second run with scaled weights must differ accordingly
    ppk2_W = W * 0.5
    fg2 = Flowgraph()
    src2, snk2 = VectorSource(data[:frame_items]), VectorSink(np.float32)
    ppk2 = PpKernel(apply_stage, W, mesh, np.float32, np.float32,
                    micro_shape=(micro_b, d), n_micro=n_micro)
    ppk2.update_params(ppk2_W)
    fg2.connect(src2, ppk2, snk2)
    Runtime().run(fg2)
    ref2 = data[:frame_items].reshape(-1, micro_b, d)
    for s in range(n_stages):
        ref2 = np.tanh(ref2 @ (W[s] * 0.5))
    np.testing.assert_allclose(np.asarray(snk2.items()), ref2.reshape(-1),
                               rtol=2e-5, atol=2e-5)

    # wrong leading stage count must be rejected loudly, not silently truncated
    import pytest
    with pytest.raises(ValueError, match="n_stages"):
        PpKernel(apply_stage, W[:2], mesh, np.float32, np.float32,
                 micro_shape=(micro_b, d), n_micro=n_micro)
    with pytest.raises(ValueError, match="n_stages"):
        ppk2.update_params(np.concatenate([W, W]))
    # non-default axis name round-trips through update_params
    mesh_s = make_mesh(("stage",), shape=(n_stages,),
                       devices=jax.devices()[:n_stages])
    ppk3 = PpKernel(apply_stage, W, mesh_s, np.float32, np.float32,
                    micro_shape=(micro_b, d), n_micro=n_micro, axis="stage")
    ppk3.update_params(W * 2.0)


def test_composed_3d_mesh_stream_feeds_training():
    """3D (dp, pp, sp) composition on one mesh: SpKernel frames along sp and
    PpKernel stages along pp in one flowgraph, whose collected output then
    trains MCLDNN on the SAME mesh (batches dp-parallel, weights fsdp-sharded
    along pp) — all three paradigm axes by name on one device grid."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.models import MCLDNN, init_params, make_train_step
    from futuresdr_tpu.parallel import make_mesh, shard_params, sp_fir_stream
    from futuresdr_tpu.tpu import PpKernel, SpKernel

    devices = jax.devices()[:8]
    mesh3 = make_mesh(("dp", "pp", "sp"), shape=(2, 2, 2), devices=devices)
    d, micro_b = 16, 2
    F = 256
    taps = np.hanning(32).astype(np.float32)
    W = np.random.default_rng(10).standard_normal((2, d, d)) \
        .astype(np.float32) / 4.0
    data = np.random.default_rng(11).standard_normal(2 * F).astype(np.float32)
    fn, initc = sp_fir_stream(taps, mesh3)
    fg = Flowgraph()
    src, snk = VectorSource(data), VectorSink(np.float32)
    spk = SpKernel(fn, mesh3, np.float32, np.float32, F, init_carry=initc)
    ppk = PpKernel(lambda w, a: jnp.tanh(a @ w), W, mesh3, np.float32,
                   np.float32, micro_shape=(micro_b, d),
                   n_micro=F // (micro_b * d), axis="pp", frames_in_flight=1)
    fg.connect(src, spk, ppk, snk)
    Runtime().run(fg)
    got = np.asarray(snk.items())
    assert got.shape == (2 * F,)

    # reference: single-device stateful FIR + host pp stages
    mesh1 = make_mesh(("sp",), shape=(1,), devices=devices[:1])
    fn1, init1 = sp_fir_stream(taps, mesh1)
    j1 = jax.jit(fn1, donate_argnums=(0,))
    c1 = init1(np.float32)
    ref = []
    for k in range(2):
        c1, yk = j1(c1, jnp.asarray(data[k * F:(k + 1) * F]))
        ref.append(np.asarray(yk))
    ref = np.concatenate(ref).reshape(-1, micro_b, d)
    for s in range(2):
        ref = np.tanh(ref @ W[s])
    np.testing.assert_allclose(got, ref.reshape(-1), rtol=1e-4, atol=1e-4)

    # the stream's output trains on the same mesh
    b = 4
    L = got.size // (b * 2)
    iq = jax.device_put(got[:b * 2 * L].reshape(b, 2, L).astype(np.float32),
                        NamedSharding(mesh3, P("dp")))
    labels = jax.device_put(np.zeros(b, np.int32), NamedSharding(mesh3, P("dp")))
    model = MCLDNN(n_classes=11, conv_features=8, lstm_features=16)
    params = init_params(model, n=L)
    params, _ = shard_params(params, mesh3, axis="pp")
    opt = optax.adam(1e-3)
    opt_state = jax.device_put(opt.init(params))
    step = jax.jit(make_train_step(model, opt))
    params, opt_state, loss, _ = step(params, opt_state, iq, labels)
    assert np.isfinite(float(loss))
