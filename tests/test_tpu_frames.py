"""Device-frame plane tests: H2D → device-resident stages → D2H (reference vulkan
h2d/d2h staging pair, SURVEY §3.5), on the CPU jax backend in CI."""

import numpy as np
from scipy import signal as sps

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import VectorSource, VectorSink
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import fir_stage, fft_stage, mag2_stage
from futuresdr_tpu.tpu import TpuH2D, TpuStage, TpuD2H


def test_h2d_stage_d2h_pipeline():
    """Two separate device stages; the frame between them never touches the host."""
    taps = firdes.lowpass(0.2, 64).astype(np.float32)
    data = np.random.default_rng(0).standard_normal(200_000).astype(np.float32)
    frame = 16384

    fg = Flowgraph()
    src = VectorSource(data)
    h2d = TpuH2D(np.float32, frame_size=frame)
    s1 = TpuStage([fir_stage(taps, fft_len=1024)], np.float32)
    s2 = TpuStage([fir_stage(taps, fft_len=1024)], np.float32)
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", s1, "in")
    fg.connect_inplace(s1, "out", s2, "in")
    fg.connect_inplace(s2, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    Runtime().run(fg)

    got = snk.items()
    ref = sps.lfilter(taps, 1.0, sps.lfilter(taps, 1.0, data))
    n = (len(data) // frame) * frame
    assert len(got) >= n
    np.testing.assert_allclose(got[:n], ref[:n], rtol=1e-3, atol=1e-4)


def test_frame_pipeline_spectrum():
    frame = 8192
    n_fft = 256
    tone = np.exp(1j * 2 * np.pi * 0.2 * np.arange(65536)).astype(np.complex64)
    fg = Flowgraph()
    src = VectorSource(tone)
    h2d = TpuH2D(np.complex64, frame_size=frame)
    st = TpuStage([fft_stage(n_fft), mag2_stage()], np.complex64)
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect_stream(src, "out", h2d, "in")
    fg.connect_inplace(h2d, "out", st, "in")
    fg.connect_inplace(st, "out", d2h, "in")
    fg.connect_stream(d2h, "out", snk, "in")
    Runtime().run(fg)
    spec = snk.items()
    assert len(spec) == 65536
    assert np.argmax(spec[:n_fft]) == round(0.2 * n_fft)


def test_plain_connect_dispatches_inplace_edges():
    """fg.connect() must wire frame-plane (inplace) edges through the circuit
    path — it used to create silent stream edges over them, deadlocking the
    graph — and must reject a stream<->inplace port mix loudly."""
    import pytest
    from futuresdr_tpu.runtime.flowgraph import ConnectError

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    data = np.random.default_rng(1).standard_normal(65536).astype(np.float32)
    fg = Flowgraph()
    src, snk = VectorSource(data), VectorSink(np.float32)
    h2d = TpuH2D(np.float32, frame_size=16384)
    st = TpuStage([fir_stage(taps, fft_len=1024)], np.float32)
    d2h = TpuD2H(np.float32)
    fg.connect(src, h2d, st, d2h, snk)          # mixed chain, one call
    assert len(fg.inplace_edges) == 2 and len(fg.stream_edges) == 2
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == 65536
    np.testing.assert_allclose(got[:1000], np.convolve(data, taps)[:1000],
                               rtol=1e-3, atol=1e-4)

    fg2 = Flowgraph()
    with pytest.raises(ConnectError, match="inplace"):
        fg2.connect_stream(TpuH2D(np.float32, frame_size=1024), "out",
                           VectorSink(np.float32), "in")


def test_d2h_read_ahead_zero_is_serial_drain():
    """read_ahead=0 must mean 'no read-ahead' (serial drain), not silently
    substitute frames_in_flight — and the graph must still make progress."""
    taps = firdes.lowpass(0.25, 32).astype(np.float32)
    data = np.random.default_rng(2).standard_normal(65536).astype(np.float32)
    fg = Flowgraph()
    src, snk = VectorSource(data), VectorSink(np.float32)
    h2d = TpuH2D(np.float32, frame_size=8192)
    st = TpuStage([fir_stage(taps, fft_len=1024)], np.float32)
    d2h = TpuD2H(np.float32, read_ahead=0)
    assert d2h.read_ahead == 1          # 0 clamps to the minimum progress bound
    fg.connect(src, h2d, st, d2h, snk)
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == 65536
    np.testing.assert_allclose(got[:4096], np.convolve(data, taps)[:4096],
                               rtol=1e-3, atol=1e-4)


def test_parse_ctrl_preserves_int_bool_str():
    """Non-float scalars must pass through parse_ctrl unchanged; floats (and
    numpy floats) normalize to Python float (ADVICE r3)."""
    from futuresdr_tpu.tpu.frames import parse_ctrl
    from futuresdr_tpu.types import Pmt

    stage, params = parse_ctrl(Pmt.map({
        "stage": Pmt.string("st"),
        "phase_inc": Pmt.f64(0.25),
        "count": Pmt.u64(7),
        "enable": Pmt.bool_(True),
        "mode": Pmt.string("soft"),
    }))
    assert stage == "st"
    assert params["phase_inc"] == 0.25 and type(params["phase_inc"]) is float
    assert params["count"] == 7 and isinstance(params["count"], int) \
        and not isinstance(params["count"], bool)
    assert params["enable"] is True
    assert params["mode"] == "soft"
