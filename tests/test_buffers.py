"""Buffer backend tests: portable ring vs C++ double-mapped circular.

Reference behaviors: broadcast 1→N, tag transport with index rebasing, wrap handling
(`tests/slab.rs` runs flowgraphs over an alternate buffer; same idea here).
"""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime, Tag
from futuresdr_tpu.runtime.buffer.ring import RingWriter
from futuresdr_tpu.runtime.buffer import circular
from futuresdr_tpu.runtime.inbox import BlockInbox
from futuresdr_tpu.runtime.tag import ItemTag
from futuresdr_tpu.blocks import VectorSource, VectorSink, Copy


BACKENDS = [RingWriter]
if circular.available():
    BACKENDS.append(circular.CircularWriter)


@pytest.mark.parametrize("backend", BACKENDS)
def test_spsc_roundtrip_with_wrap(backend):
    wib, rib = BlockInbox(), BlockInbox()
    w = backend(np.float32, 1024, wib)
    r = w.add_reader(rib, 0)
    total = 10_000
    sent = np.arange(total, dtype=np.float32)
    got = []
    n_got = 0
    si = 0
    while n_got < total or si < total:
        s = w.slice()
        if si < total and len(s):
            k = min(len(s), total - si, 100)
            s[:k] = sent[si:si + k]
            w.produce(k)
            si += k
        rs = r.slice()
        if len(rs):
            k = min(len(rs), 37)
            got.append(rs[:k].copy())
            n_got += k
            r.consume(k)
    np.testing.assert_array_equal(np.concatenate(got), sent)


@pytest.mark.parametrize("backend", BACKENDS)
def test_broadcast_two_readers(backend):
    wib, r1ib, r2ib = BlockInbox(), BlockInbox(), BlockInbox()
    w = backend(np.int32, 256, wib)
    r1 = w.add_reader(r1ib, 0)
    r2 = w.add_reader(r2ib, 0)
    s = w.slice()
    n0 = min(100, len(s))
    s[:n0] = np.arange(n0)
    w.produce(n0)
    np.testing.assert_array_equal(r1.slice(), np.arange(n0))
    np.testing.assert_array_equal(r2.slice(), np.arange(n0))
    r1.consume(n0)
    # writer space limited by the slowest reader
    assert w.space_available() == w.capacity - n0


@pytest.mark.parametrize("backend", BACKENDS)
def test_tags_rebase_on_consume(backend):
    wib, rib = BlockInbox(), BlockInbox()
    w = backend(np.float32, 256, wib)
    r = w.add_reader(rib, 0)
    w.slice()[:50] = 0
    w.produce(50, [ItemTag(10, Tag.string("a")), ItemTag(40, Tag.string("b"))])
    tags = r.tags()
    assert [t.index for t in tags] == [10, 40]
    r.consume(20)
    tags = r.tags()
    assert [t.index for t in tags] == [20]
    assert tags[0].tag.value == "b"


@pytest.mark.skipif(not circular.available(), reason="native lib missing")
def test_circular_contiguous_across_wrap():
    """The double mapping must give contiguous windows spanning the wrap seam."""
    wib, rib = BlockInbox(), BlockInbox()
    w = circular.CircularWriter(np.uint8, 4096, wib)
    r = w.add_reader(rib, 0)
    cap = w.capacity
    # advance to near the end of the ring
    w.slice()[:cap - 10] = 1
    w.produce(cap - 10)
    r.consume(cap - 10)
    # now a 100-byte window spans the seam; must still be a single slice
    s = w.slice()
    assert len(s) == cap  # full capacity writable contiguously
    s[:100] = np.arange(100, dtype=np.uint8)
    w.produce(100)
    rs = r.slice()
    assert len(rs) == 100
    np.testing.assert_array_equal(rs, np.arange(100, dtype=np.uint8))


@pytest.mark.parametrize("backend", BACKENDS)
def test_flowgraph_roundtrip_on_backend(backend):
    data = np.random.default_rng(7).random(300_000).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cp = Copy(np.float32)
    snk = VectorSink(np.float32)
    fg.connect_stream(src, "out", cp, "in", buffer=backend)
    fg.connect_stream(cp, "out", snk, "in", buffer=backend)
    Runtime().run(fg)
    np.testing.assert_array_equal(snk.items(), data)


def test_per_edge_buffer_size_override():
    """connect_stream(buffer_size=...) bounds the negotiated capacity (latency knob)."""
    import numpy as np
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Copy, Head, NullSink, NullSource

    fg = Flowgraph()
    src = NullSource(np.float32)
    head = Head(np.float32, 100_000)
    cp = Copy(np.float32)
    snk = NullSink(np.float32)
    fg.connect_stream(src, "out", head, "in")
    fg.connect_stream(head, "out", cp, "in", buffer_size=16384)
    fg.connect_stream(cp, "out", snk, "in")
    fg._materialize()
    small = head.stream_outputs[0].writer.capacity
    big = src.stream_outputs[0].writer.capacity
    assert small == 16384 // 4          # 4096 float32 items
    assert big > small                  # other edges keep the config default


def test_preferred_buffer_size_port_hint():
    """A port's preferred_buffer_size shortens its edge unless overridden."""
    import numpy as np
    from futuresdr_tpu import Flowgraph
    from futuresdr_tpu.blocks import Head, NullSource
    from futuresdr_tpu.runtime.kernel import Kernel

    class ShortQueueSink(Kernel):
        def __init__(self):
            super().__init__()
            self.input = self.add_stream_input("in", np.float32,
                                               preferred_buffer_size=8192)

        async def work(self, io, mio, meta):
            self.input.consume(self.input.available())
            if self.input.finished():
                io.finished = True

    fg = Flowgraph()
    src = NullSource(np.float32)
    head = Head(np.float32, 1000)
    snk = ShortQueueSink()
    fg.connect_stream(src, "out", head, "in")
    fg.connect_stream(head, "out", snk, "in")
    fg._materialize()
    assert head.stream_outputs[0].writer.capacity == 8192 // 4
