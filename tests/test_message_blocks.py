"""Message-plane tests (reference: message blocks + `tests/flowgraph.rs` handler paths)."""

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import (MessageBurst, MessageCopy, MessageSink, MessageApply,
                                  MessageAnnotator, MessageSource)


def test_burst_copy_sink():
    fg = Flowgraph()
    burst = MessageBurst(Pmt.f64(2.5), 17)
    cp = MessageCopy()
    snk = MessageSink()
    fg.connect_message(burst, "out", cp, "in")
    fg.connect_message(cp, "out", snk, "in")
    Runtime().run(fg)
    assert len(snk.received) == 17
    assert all(p == Pmt.f64(2.5) for p in snk.received)


def test_message_apply_transform_and_drop():
    fg = Flowgraph()
    burst = MessageBurst(Pmt.usize(3), 10)
    app = MessageApply(lambda p: Pmt.usize(p.to_int() * 2) if p.to_int() else None)
    snk = MessageSink()
    fg.connect_message(burst, "out", app, "in")
    fg.connect_message(app, "out", snk, "in")
    Runtime().run(fg)
    assert [p.to_int() for p in snk.received] == [6] * 10


def test_annotator_wraps_in_map():
    fg = Flowgraph()
    burst = MessageBurst(Pmt.string("x"), 1)
    ann = MessageAnnotator({"source": Pmt.string("test")}, key="payload")
    snk = MessageSink()
    fg.connect_message(burst, "out", ann, "in")
    fg.connect_message(ann, "out", snk, "in")
    Runtime().run(fg)
    m = snk.received[0].to_map()
    assert m["payload"] == Pmt.string("x")
    assert m["source"] == Pmt.string("test")


def test_message_source_periodic():
    fg = Flowgraph()
    src = MessageSource(Pmt.null(), interval=0.01, count=5)
    snk = MessageSink()
    fg.connect_message(src, "out", snk, "in")
    Runtime().run(fg)
    assert len(snk.received) == 5


def test_bounded_inbox_try_send_drops_when_full():
    from futuresdr_tpu.runtime.inbox import BlockInbox, Call
    from futuresdr_tpu.types import Pmt, PortId
    ib = BlockInbox(capacity=3)
    msg = Call(PortId.coerce("in"), Pmt.ok())
    assert all(ib.try_send(msg) for _ in range(3))
    assert not ib.try_send(msg)          # full → bounded drop
    assert ib.try_recv() is not None     # drain one → space frees
    assert ib.try_send(msg)


def test_send_async_backpressures_until_consumer_drains():
    import asyncio
    from futuresdr_tpu.runtime.inbox import BlockInbox, Call
    from futuresdr_tpu.types import Pmt, PortId

    async def scenario():
        ib = BlockInbox(capacity=2)
        msg = Call(PortId.coerce("in"), Pmt.ok())
        await ib.send_async(msg)
        await ib.send_async(msg)
        parked = asyncio.ensure_future(ib.send_async(msg))
        await asyncio.sleep(0.02)
        assert not parked.done()         # producer parked on the full inbox
        assert ib.try_recv() is not None
        await asyncio.wait_for(parked, 1.0)
        assert len(ib) == 2

    asyncio.run(scenario())


def test_large_burst_bounded_inbox_delivers_all():
    # a burst far larger than the queue capacity must deliver every message
    # (backpressure, not drops)
    from futuresdr_tpu.config import config
    cap = config().queue_size
    n = cap * 4 + 7
    fg = Flowgraph()
    burst = MessageBurst(Pmt.usize(1), n)
    snk = MessageSink()
    fg.connect_message(burst, "out", snk, "in")
    Runtime().run(fg)
    assert len(snk.received) == n


def test_direct_dispatch_eligibility_gates():
    """The direct (same-frame) message path only targets PURE message blocks:
    base no-op work() + plain-function handler. Anything with a custom work
    coroutine or an async handler keeps the actor inbox path."""
    from futuresdr_tpu.blocks import MessageCopy, MessagePipe
    assert MessageCopy()._direct_ok
    assert MessageCopy()._sync_handler("in") is not None
    assert MessageSink()._direct_ok
    assert MessageSink()._sync_handler("in") is not None
    assert not MessageBurst(Pmt.usize(1), 1)._direct_ok     # custom work()
    pipe = MessagePipe()
    assert pipe._sync_handler("in") is None                 # async handler
    from futuresdr_tpu.blocks import Fft
    assert not Fft()._direct_ok                             # stream block


def test_direct_dispatch_preserves_order_and_metrics():
    """Distinct messages through a copy chain arrive exactly once, in order,
    and per-block messages_handled counts them (direct calls bump the same
    counter the actor loop does)."""
    from futuresdr_tpu.runtime.kernel import Kernel

    n = 5_000

    class CountSource(Kernel):
        def __init__(self):
            super().__init__()
            self.add_message_output("out")

        async def work(self, io, mio, meta):
            for i in range(n):
                await mio.post_async("out", Pmt.usize(i))
            io.finished = True

    fg = Flowgraph()
    src = CountSource()
    c1, c2 = MessageCopy(), MessageCopy()
    snk = MessageSink()
    fg.connect_message(src, "out", c1, "in")
    fg.connect_message(c1, "out", c2, "in")
    fg.connect_message(c2, "out", snk, "in")
    Runtime().run(fg)
    assert [p.to_int() for p in snk.received] == list(range(n))
    w1 = fg.wrapped(c1)
    assert w1.metrics()["messages_handled"] >= n            # + finished marker


def test_direct_dispatch_under_threaded_scheduler():
    """Multi-loop scheduler: same-loop pairs may direct-dispatch, cross-loop
    pairs must fall back to the inbox — either way every message arrives
    exactly once, in per-sender order, across worker assignments."""
    from futuresdr_tpu import ThreadedScheduler
    from futuresdr_tpu.runtime.kernel import Kernel

    n = 3_000

    class CountSource(Kernel):
        def __init__(self):
            super().__init__()
            self.add_message_output("out")

        async def work(self, io, mio, meta):
            for i in range(n):
                await mio.post_async("out", Pmt.usize(i))
            io.finished = True

    fg = Flowgraph()
    src = CountSource()
    chain = [MessageCopy() for _ in range(4)]
    snk = MessageSink()
    fg.connect_message(src, "out", chain[0], "in")
    for a, b in zip(chain, chain[1:]):
        fg.connect_message(a, "out", b, "in")
    fg.connect_message(chain[-1], "out", snk, "in")
    rt = Runtime(ThreadedScheduler(workers=3))
    rt.run(fg)
    rt.shutdown()
    assert [p.to_int() for p in snk.received] == list(range(n))
