"""Message-plane tests (reference: message blocks + `tests/flowgraph.rs` handler paths)."""

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import (MessageBurst, MessageCopy, MessageSink, MessageApply,
                                  MessageAnnotator, MessageSource)


def test_burst_copy_sink():
    fg = Flowgraph()
    burst = MessageBurst(Pmt.f64(2.5), 17)
    cp = MessageCopy()
    snk = MessageSink()
    fg.connect_message(burst, "out", cp, "in")
    fg.connect_message(cp, "out", snk, "in")
    Runtime().run(fg)
    assert len(snk.received) == 17
    assert all(p == Pmt.f64(2.5) for p in snk.received)


def test_message_apply_transform_and_drop():
    fg = Flowgraph()
    burst = MessageBurst(Pmt.usize(3), 10)
    app = MessageApply(lambda p: Pmt.usize(p.to_int() * 2) if p.to_int() else None)
    snk = MessageSink()
    fg.connect_message(burst, "out", app, "in")
    fg.connect_message(app, "out", snk, "in")
    Runtime().run(fg)
    assert [p.to_int() for p in snk.received] == [6] * 10


def test_annotator_wraps_in_map():
    fg = Flowgraph()
    burst = MessageBurst(Pmt.string("x"), 1)
    ann = MessageAnnotator({"source": Pmt.string("test")}, key="payload")
    snk = MessageSink()
    fg.connect_message(burst, "out", ann, "in")
    fg.connect_message(ann, "out", snk, "in")
    Runtime().run(fg)
    m = snk.received[0].to_map()
    assert m["payload"] == Pmt.string("x")
    assert m["source"] == Pmt.string("test")


def test_message_source_periodic():
    fg = Flowgraph()
    src = MessageSource(Pmt.null(), interval=0.01, count=5)
    snk = MessageSink()
    fg.connect_message(src, "out", snk, "in")
    Runtime().run(fg)
    assert len(snk.received) == 5
