"""Rattlegram FEC family: BCH(255,71), CRCs, OSD, systematic polar + list decode.

Golden strategy: every codec is validated by TWO independent constructions (polynomial
long-division vs generator-matrix product for BCH; LFSR bit-shift spec vs numpy mod for
parity; CRC residue-zero property for the polar CRC aid) plus noisy-channel roundtrips.
"""

import numpy as np
import pytest

from futuresdr_tpu.models.rattlegram import fec, polar


# ---------------------------------------------------------------------------
# BCH
# ---------------------------------------------------------------------------

def _lfsr_parity(data_bits):
    """Independent spec implementation: the reference's shift-register division
    (`bch.rs:62-85`) — MSB-first LFSR with the generator's low coefficients."""
    g = fec.bch_genpoly()            # ascending coeffs, g[184] = leading 1
    np_ = fec.BCH_NP
    # register holds the remainder, MSB (x^183) first
    reg = np.zeros(np_, np.uint8)
    gen = g[::-1][1:]                # descending, drop leading x^184 term
    for bit in data_bits:
        fb = bit ^ reg[0]
        reg = np.roll(reg, -1)
        reg[-1] = 0
        if fb:
            reg ^= gen
    return reg


def test_bch_genpoly_structure():
    g = fec.bch_genpoly()
    assert len(g) == 185 and g[0] == 1 and g[-1] == 1
    # generator divides x^255 - 1 (codeword polynomial property)
    x255 = np.zeros(256, np.uint8)
    x255[0] = x255[255] = 1
    r = x255.copy()
    gd = g[::-1]
    for i in range(255 - 184 + 1):
        if r[i]:
            r[i:i + 185] ^= gd
    assert not r.any(), "g(x) must divide x^255 + 1"


def test_bch_parity_two_constructions_agree():
    rng = np.random.default_rng(7)
    G = fec.bch_generator_matrix()
    for _ in range(16):
        data = rng.integers(0, 2, 71).astype(np.uint8)
        par_poly = fec.bch_parity(data)
        par_mat = ((data @ G) & 1)[71:]
        par_lfsr = _lfsr_parity(data)
        np.testing.assert_array_equal(par_poly, par_mat)
        np.testing.assert_array_equal(par_poly, par_lfsr)


def test_bch_min_distance_sample():
    """Random nonzero codewords weigh ≥ the designed distance 47."""
    rng = np.random.default_rng(8)
    G = fec.bch_generator_matrix()
    for _ in range(32):
        d = rng.integers(0, 2, 71).astype(np.uint8)
        if not d.any():
            continue
        w = int(((d @ G) & 1).sum())
        assert w >= 47, w


# ---------------------------------------------------------------------------
# CRCs
# ---------------------------------------------------------------------------

def test_crc32_residue_zero():
    """Appending the CRC32 LSB-first makes the bitwise residue zero — the property the
    polar decoder's path selection relies on (`polar.rs:219-228`)."""
    rng = np.random.default_rng(9)
    for n in (1, 7, 85, 128):
        msg = bytes(rng.integers(0, 256, n, dtype=np.uint8))
        crc = fec.crc32_rattlegram(msg)
        bits = np.concatenate([fec.bytes_to_le_bits(msg, 8 * n),
                               ((crc >> np.arange(32)) & 1).astype(np.uint8)])
        assert fec.crc32_bits(bits) == 0


def test_crc16_known_relation():
    # reflected CRC with init 0: crc(b"") == 0 and linearity over zero-padding prefix
    assert fec.crc16_rattlegram(b"") == 0
    assert fec.crc16_rattlegram(b"\x00" * 8) == 0
    a = fec.crc16_rattlegram(b"\x01")
    assert 0 < a < (1 << 16)


# ---------------------------------------------------------------------------
# MLS / scrambler
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("poly,period", [(0b10001001, 127), (0b100101011, 255),
                                         (0b100101010001, 2047)])
def test_mls_full_period(poly, period):
    bits = fec.mls_bits(poly, 2 * period)
    pm = bits.astype(np.int32) * 2 - 1
    # maximal length: period-n autocorrelation is -1 off-peak over one period
    seq = pm[:period]
    for lag in (1, 7, 31):
        assert abs(int(seq @ np.roll(seq, lag))) <= 1


def test_xorshift32_sequence():
    x = fec.Xorshift32()
    first = [x.next() for _ in range(3)]
    assert first[0] == 723471715          # published xorshift32 seed-2463534242 stream
    y = fec.Xorshift32()
    assert [y.next() for _ in range(3)] == first


# ---------------------------------------------------------------------------
# OSD
# ---------------------------------------------------------------------------

def _noisy_soft(cw, n_flips, rng, weak=16, strong=96):
    soft = np.where(cw > 0, -strong, strong).astype(np.int16)
    flip = rng.choice(255, n_flips, replace=False)
    soft[flip] = np.sign(-soft[flip]) * weak
    return np.clip(soft, -127, 127).astype(np.int8)


def test_osd_clean_and_weak_errors():
    rng = np.random.default_rng(10)
    G = fec.bch_generator_matrix().astype(np.int8)
    data = rng.integers(0, 2, 71).astype(np.uint8)
    cw = (data @ fec.bch_generator_matrix()) & 1
    hard, conf = fec.osd_decode(np.where(cw > 0, -64, 64).astype(np.int8), G)
    assert np.array_equal(hard, cw) and conf
    for n_err in (8, 24, 40):
        ok = 0
        for t in range(8):
            r = np.random.default_rng(100 + t)
            hard, _ = fec.osd_decode(_noisy_soft(cw, n_err, r), G)
            ok += np.array_equal(hard, cw)
        assert ok >= 7, (n_err, ok)


def test_osd_output_is_codeword():
    """Whatever the channel does, OSD must emit a valid codeword of the code."""
    rng = np.random.default_rng(11)
    G = fec.bch_generator_matrix()
    H_rows = G  # systematic G: parity check via re-encoding the data part
    soft = rng.integers(-100, 100, 255).astype(np.int8)
    hard, _ = fec.osd_decode(soft, G.astype(np.int8))
    reenc = (hard[:71] @ G) & 1
    np.testing.assert_array_equal(reenc, hard)


# ---------------------------------------------------------------------------
# polar
# ---------------------------------------------------------------------------

def test_frozen_tables_info_counts():
    for words, k in ((polar.FROZEN_2048_712, 712), (polar.FROZEN_2048_1056, 1056),
                     (polar.FROZEN_2048_1392, 1392)):
        mask = polar.frozen_mask(words)
        assert mask.shape == (2048,)
        assert int((mask == 0).sum()) == k


@pytest.mark.parametrize("data_bits,nbytes", [(680, 85), (1024, 128), (1360, 170)])
def test_polar_systematic_roundtrip_clean(data_bits, nbytes):
    rng = np.random.default_rng(12)
    msg = bytes(rng.integers(0, 256, nbytes, dtype=np.uint8))
    code = polar.polar_encode(msg, data_bits)
    assert set(np.unique(code)) <= {-1, 1}
    # systematic property: data bits appear at the non-frozen positions
    mask = polar.frozen_mask(polar.FROZEN_BY_DATA_BITS[data_bits])
    info = np.nonzero(mask == 0)[0]
    bits = (code[info[:data_bits]] < 0).astype(np.uint8)
    assert fec.le_bits_to_bytes(bits) == msg
    dec, flips = polar.polar_decode((code * 96).astype(np.int8), data_bits)
    assert dec == msg and flips == 0


def test_polar_decode_with_bit_flips():
    rng = np.random.default_rng(13)
    msg = bytes(rng.integers(0, 256, 85, dtype=np.uint8))
    code = polar.polar_encode(msg, 680)
    for n_flips in (20, 50):
        for t in range(3):
            r = np.random.default_rng(300 + 10 * n_flips + t)
            soft = (code.astype(np.int16) * 48)
            flip = r.choice(2048, n_flips, replace=False)
            soft[flip] = -soft[flip] // 3
            dec, flips = polar.polar_decode(np.clip(soft, -127, 127).astype(np.int8),
                                            680)
            assert dec == msg, (n_flips, t)
            assert flips >= 0


def test_polar_decode_garbage_returns_none():
    rng = np.random.default_rng(14)
    soft = rng.integers(-127, 128, 2048).astype(np.int8)
    dec, flips = polar.polar_decode(soft, 680)
    assert dec is None and flips == -1


def test_polar_awgn_gain_over_hard():
    """List-32 + CRC must decode at an SNR where hard decisions alone are hopeless."""
    rng = np.random.default_rng(15)
    msg = bytes(rng.integers(0, 256, 85, dtype=np.uint8))
    code = polar.polar_encode(msg, 680).astype(np.float64)
    snr_db = 2.0                        # measured envelope: 6/6 at 2 dB Es/N0
    sigma = 10 ** (-snr_db / 20)
    rx = code + sigma * rng.standard_normal(2048)
    n_hard_errors = int(((rx < 0) != (code < 0)).sum())
    assert n_hard_errors > 50           # channel genuinely flips many bits
    soft = np.clip(rx * 32, -127, 127).astype(np.int8)
    dec, flips = polar.polar_decode(soft, 680)
    assert dec == msg
    assert flips > 0                    # decoder really corrected channel errors


def test_modem_receiver_multi_burst_exact_once():
    """Interrogation standard: 5 noisy audio bursts with varying gaps decode
    exactly once each, in time order, through the ModemReceiver block — one
    rx() per work() call used to drop every burst but one in a big chunk."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.rattlegram.modem import Modem, ModemReceiver

    m = Modem(payload_size=32)
    rng = np.random.default_rng(8)
    parts, sent = [], []
    for i in range(5):
        payload = f"rattle {i}".encode()
        sent.append(payload)
        parts += [np.zeros(2000 + 311 * i, np.float32), m.tx(payload)]
    parts.append(np.zeros(2500, np.float32))
    sig = np.concatenate(parts).astype(np.float32)
    sig = (sig + 0.01 * rng.standard_normal(len(sig))).astype(np.float32)
    fg = Flowgraph()
    fg.connect_stream(VectorSource(sig), "out",
                      (rx := ModemReceiver(payload_size=32)), "in")
    Runtime().run(fg)
    assert rx.frames == sent, rx.frames


def test_modem_receiver_delivers_retransmissions():
    """Identical payload sent three times must arrive three times — dedup is by
    burst POSITION (tail-overlap re-decodes), not payload content."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSource
    from futuresdr_tpu.models.rattlegram.modem import Modem, ModemReceiver

    m = Modem(payload_size=32)
    rng = np.random.default_rng(8)
    sig = np.concatenate([np.zeros(2000, np.float32), m.tx(b"same"),
                          np.zeros(3000, np.float32), m.tx(b"same"),
                          np.zeros(3000, np.float32), m.tx(b"same"),
                          np.zeros(2000, np.float32)]).astype(np.float32)
    sig = (sig + 0.01 * rng.standard_normal(len(sig))).astype(np.float32)
    fg = Flowgraph()
    fg.connect_stream(VectorSource(sig), "out",
                      (rx := ModemReceiver(payload_size=32)), "in")
    Runtime().run(fg)
    assert rx.frames == [b"same"] * 3, rx.frames


def test_corrupted_burst_does_not_eat_neighbors():
    """A CRC-failing burst in the middle of a train must not claim samples past
    its own correlation lobe — both neighbors still decode."""
    from futuresdr_tpu.models.rattlegram.modem import Modem, demodulate_all

    m = Modem(payload_size=32)
    rng = np.random.default_rng(9)
    b0, b1, b2 = m.tx(b"first"), m.tx(b"corrupt-me"), m.tx(b"third")
    mid = b1.copy()
    mid[len(mid) // 3:] += 0.8 * rng.standard_normal(
        len(mid) - len(mid) // 3).astype(np.float32)
    sig = np.concatenate([np.zeros(1500, np.float32), b0,
                          np.zeros(1500, np.float32), mid,
                          np.zeros(1500, np.float32), b2,
                          np.zeros(1500, np.float32)]).astype(np.float32)
    got = [p.rstrip(b"\x00") for _, p in demodulate_all(sig, 32)]
    assert b"first" in got and b"third" in got, got
