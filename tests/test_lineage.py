"""Frame-lineage plane + lifecycle journal (docs/observability.md).

Covers the lineage-plane contracts end to end:

* ``LineageTracer`` — stride sampling (0 = off, 1 = every frame), stamp/
  finish record shape, lane-delta attribution with the dispatch→compute /
  emit→drain renames, bounded open-table eviction;
* :func:`lineage.tail_report` — per-lane decomposition, the slowest-lane
  verdict restricted to the five pipeline lanes (commensurable with the
  doctor's interval-union ``bottleneck_lane``), slowest-session/tenant
  attribution, slowest-frames detail;
* the journal — monotonic cursor, ring-eviction gap flag, category filter,
  limit pagination, reserved-key protection, the JSONL spool;
* Perfetto flow synthesis — ``spans.chrome_trace`` renders a completed
  record as one connected ``s``/``t``/``f`` chain sharing the trace id;
* OpenMetrics exemplars — ``Log2Hist.exemplar`` storage and the separate
  ``render_openmetrics`` exposition (the default v0.0.4 text is untouched);
* the REST surface — ``/api/fg/{fg}/lineage/``, ``/api/events/`` cursor
  reads, ``/metrics?openmetrics=1``;
* the PR-4 e2e stamp audit (per-sink AND per-session): serve lanes observe
  their own frame's latency in ``fsdr_e2e_latency_seconds{source}`` and
  sampled serve records carry session+tenant;
* the flight-record span snapshot covers codec worker rings and ShardRunner
  shard lanes without draining the trace ring.
"""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from futuresdr_tpu.telemetry import journal, lineage, prom, spans

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def every_frame():
    """Force 1-in-1 sampling on the process-global tracer; restore after."""
    from futuresdr_tpu.config import config
    c = config()
    old = c.lineage_stride
    c.lineage_stride = 1
    tr = lineage.reset_tracer()
    yield tr
    c.lineage_stride = old
    lineage.reset_tracer()


@pytest.fixture
def tracing():
    """Enable span recording for the test; drain + restore after."""
    rec = spans.recorder()
    was = rec.enabled
    rec.enabled = True
    rec.drain()
    yield rec
    rec.enabled = was
    rec.drain()


# ---------------------------------------------------------------------------
# tracer units
# ---------------------------------------------------------------------------

def test_sampling_stride():
    # stride 0: sampling OFF — every draw is the falsy-check fast path
    tr = lineage.LineageTracer(stride=0)
    assert [tr.sample() for _ in range(10)] == [0] * 10
    # stride 4: exactly 1-in-4 frames draw a (monotonic) trace id
    tr = lineage.LineageTracer(stride=4)
    ids = [tr.sample() for _ in range(16)]
    assert [i for i in ids if i] == [1, 2, 3, 4]
    assert ids[3] == 1 and ids[0] == 0
    # stride 1: every frame sampled (the check.sh smoke's forced mode)
    tr = lineage.LineageTracer(stride=1)
    assert [tr.sample() for _ in range(5)] == [1, 2, 3, 4, 5]


def test_stamp_finish_and_lane_attribution():
    tr = lineage.LineageTracer(stride=1)
    tid = tr.sample()
    t0 = 1_000_000
    for i, lane in enumerate(lineage.LANE_ORDER):
        tr.stamp(tid, lane, t0 + i * 1000)
    d = tr.finish(tid, source="unit", session="s0", tenant="t0")
    assert d["id"] == tid and d["source"] == "unit"
    assert d["session"] == "s0" and d["tenant"] == "t0"
    assert [s["lane"] for s in d["stamps"]] == list(lineage.LANE_ORDER)
    assert all(s["thread"] for s in d["stamps"])
    (r,) = tr.records()
    assert r.e2e_ns() == 6000
    # per-lane deltas named for the LATER lane, with the renames applied
    assert r.lane_ns() == {"encode": 1000, "H2D": 1000, "compute": 1000,
                           "D2H": 1000, "decode": 1000, "drain": 1000}
    # tid 0 (the unsampled 63-of-64 case) is a no-op everywhere
    tr.stamp(0, "encode")
    assert tr.finish(0) is None
    # double-finish: the record already moved to the done ring
    assert tr.finish(tid) is None
    assert len(tr.records()) == 1


def test_open_table_bounded_eviction():
    tr = lineage.LineageTracer(stride=1, ring=1)
    cap = tr._open_cap
    tids = [tr.sample() for _ in range(cap + 3)]
    assert tr.dropped == 3
    # the evicted oldest records no longer finish; the newest still does
    assert tr.finish(tids[0]) is None
    assert tr.finish(tids[-1]) is not None


def _mk_record(tr, deltas, sess=None, ten=None, t0=1_000_000):
    """One synthetic record: ingest at t0, then each stamp lane advanced by
    its delta (ns) in pipeline order."""
    tid = tr.sample()
    t = t0
    tr.stamp(tid, "ingest", t)
    for lane in ("encode", "H2D", "dispatch", "D2H", "decode", "emit"):
        if lane in deltas:
            t += deltas[lane]
            tr.stamp(tid, lane, t)
    tr.finish(tid, source="unit", session=sess, tenant=ten)
    return tid


def test_tail_report_attribution():
    tr = lineage.LineageTracer(stride=1)
    base = {"encode": 10_000, "H2D": 40_000, "dispatch": 20_000,
            "D2H": 5_000, "decode": 5_000, "emit": 500_000}
    _mk_record(tr, base, sess="a", ten="ta")
    _mk_record(tr, dict(base, H2D=90_000), sess="b", ten="tb")
    rep = lineage.tail_report(tr.records())
    assert rep["samples"] == 2 and rep["e2e_samples"] == 2
    # the drain wait (decode→emit) dominates raw totals but is NOT a
    # pipeline lane — the verdict must stay commensurable with the
    # doctor's interval-union bottleneck_lane
    assert rep["lanes"]["drain"]["frac"] > rep["lanes"]["H2D"]["frac"]
    assert rep["slowest_lane"] == "H2D"
    assert 0.0 < rep["slowest_lane_frac"] < 1.0
    # session attribution: b's H2D spike makes it the slowest session
    assert rep["slowest_session"] == "b" and rep["slowest_tenant"] == "tb"
    assert rep["slowest_session_mean_ms"] > 0
    assert rep["p99_ms"] >= rep["p50_ms"] > 0
    # slowest-frames detail rides slowest-first with its own lane split
    frames = rep["slowest_frames"]
    assert frames[0]["session"] == "b"
    assert frames[0]["e2e_ms"] >= frames[1]["e2e_ms"]
    assert frames[0]["lanes_ms"]["H2D"] == pytest.approx(0.09)
    # nothing sampled → no report (doctor renders the section as absent)
    assert lineage.tail_report([]) is None


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------

def test_journal_cursor_gap_cat_and_pagination():
    j = journal.Journal(maxlen=8)
    assert [j.emit("serve", f"e{i}", k=i) for i in range(12)] == \
        list(range(1, 13))
    # ring kept the newest 8; a fresh reader (since=0) sees the gap flagged
    out = j.events()
    assert [e["seq"] for e in out["events"]] == list(range(5, 13))
    assert out["gap"] and out["seq"] == 12 and out["next"] == 12
    # a cursor inside the retained window reads contiguously, no gap
    out = j.events(since=6)
    assert not out["gap"]
    assert [e["seq"] for e in out["events"]] == list(range(7, 13))
    # limit pages; `next` points at the last RETURNED event
    page = j.events(since=4, limit=3)
    assert not page["gap"]
    assert [e["seq"] for e in page["events"]] == [5, 6, 7]
    assert page["next"] == 7
    page2 = j.events(since=page["next"], limit=100)
    assert [e["seq"] for e in page2["events"]] == list(range(8, 13))
    # category filter sees only its events; the cursor keeps advancing
    j.emit("kernel", "init")
    only = j.events(cat="kernel")
    assert [e["event"] for e in only["events"]] == ["init"]
    assert only["next"] == j.seq
    # a caught-up reader gets an empty page and no gap
    tail = j.events(since=j.seq)
    assert tail["events"] == [] and not tail["gap"]
    # free-form fields must not clobber the envelope keys
    s = j.emit("serve", "x", seq=99, t_wall=-1)
    (ev,) = j.events(since=s - 1)["events"]
    assert ev["seq"] == s and ev["cat"] == "serve" and ev["t_wall"] > 0


def test_journal_spool_jsonl(tmp_path):
    j = journal.Journal(maxlen=4, spool_dir=str(tmp_path))
    j.emit("serve", "admit", session="s0", tenant="t0")
    j.emit("serve", "close", session="s0")
    j.close()
    (f,) = list(tmp_path.glob("events_*.jsonl"))
    lines = [json.loads(ln) for ln in f.read_text().splitlines()]
    assert [(e["cat"], e["event"]) for e in lines] == \
        [("serve", "admit"), ("serve", "close")]
    assert lines[0]["seq"] == 1 and lines[0]["session"] == "s0"
    # every spooled line carries the full envelope (post-crash readers
    # reconstruct the decision history from the file alone)
    assert {"seq", "t_wall", "t_mono_ns", "cat", "event"} <= set(lines[0])


def test_journal_last_and_singleton_config(tmp_path):
    from futuresdr_tpu.config import config
    c = config()
    old_ring, old_dir = c.journal_ring, c.journal_dir
    c.journal_ring, c.journal_dir = 16, str(tmp_path)
    try:
        j = journal.reset_journal()
        for i in range(20):
            journal.emit("chaos", "tick", i=i)
        assert journal.journal() is j
        # last-N rides oldest-first (the flight-record embedding)
        last = j.last(4)
        assert [e["i"] for e in last] == [16, 17, 18, 19]
        # the ring honored the config bound; the spool kept everything
        assert len(j.events()["events"]) == 16
        j.close()
        (f,) = list(tmp_path.glob("events_*.jsonl"))
        assert len(f.read_text().splitlines()) == 20
    finally:
        c.journal_ring, c.journal_dir = old_ring, old_dir
        journal.reset_journal()


# ---------------------------------------------------------------------------
# Perfetto flow synthesis
# ---------------------------------------------------------------------------

def test_chrome_trace_flow_synthesis(tracing, every_frame):
    tr = every_frame
    tid = tr.sample()
    base = time.perf_counter_ns()
    for i, lane in enumerate(("ingest", "encode", "dispatch", "emit")):
        tr.stamp(tid, lane, base + i * 1000)
    tr.finish(tid, source="unit")
    # a record with fewer than 2 stamps synthesizes no flow
    lone = tr.sample()
    tr.stamp(lone, "ingest", base)
    tr.finish(lone, source="unit")

    doc = spans.chrome_trace()
    evs = [e for e in doc["traceEvents"]
           if e.get("cat") == "lineage" and e.get("id") == tid]
    assert [e["ph"] for e in evs] == ["s", "t", "t", "f"]
    assert evs[-1]["bp"] == "e"          # bind the arrow to the enclosing
    assert all(e["name"] == "frame" for e in evs)     # slice's END
    assert [e["args"]["lane"] for e in evs] == \
        ["ingest", "encode", "dispatch", "emit"]
    assert all(e["args"]["source"] == "unit" for e in evs)
    ts = [e["ts"] for e in evs]
    assert ts == sorted(ts)
    assert doc["otherData"]["lineage_flows"] == 1
    assert not any(e.get("cat") == "lineage" and e.get("id") == lone
                   for e in doc["traceEvents"])
    json.dumps(doc)                      # export stays JSON-serializable


# ---------------------------------------------------------------------------
# OpenMetrics exemplars
# ---------------------------------------------------------------------------

def test_log2hist_exemplar_storage():
    from futuresdr_tpu.telemetry.hist import Log2Hist
    h = Log2Hist()
    h.exemplar(-1.0, "bad")              # negative value: rejected
    h.exemplar(1e-3, "")                 # empty trace id: rejected
    assert h.exemplars() == {}
    h.observe(1.0e-3)
    h.exemplar(1.0e-3, "41")
    h.observe(1.2e-3)
    h.exemplar(1.2e-3, "42")             # same log2 bucket: latest wins
    ex = h.exemplars()
    assert len(ex) == 1
    ((v, tid, ts),) = ex.values()
    assert tid == "42" and v == pytest.approx(1.2e-3) and ts > 0


def test_openmetrics_exposition_with_exemplars():
    hist = prom.histogram("test_lineage_exemplar_seconds",
                          "exemplar exposition probe", ("source",))
    c = hist.labels(source="probe")
    c.observe(3e-3)
    c.exemplar(3e-3, "7")
    # the default v0.0.4 exposition is byte-for-byte exemplar-free
    assert " # {" not in "\n".join(hist.render())
    om = hist.render_openmetrics()
    line = next(ln for ln in om if " # {" in ln)
    assert "test_lineage_exemplar_seconds_bucket" in line
    assert '# {trace_id="7"} 0.003' in line
    # exemplar rides exactly one bucket line, on the labeled child
    assert sum(ln.count(" # {") for ln in om) == 1
    assert 'source="probe"' in line
    # the registry-level exposition terminates with the required EOF marker
    text = prom.registry().render_openmetrics()
    assert text.rstrip("\n").endswith("# EOF")
    assert " # {" in text


# ---------------------------------------------------------------------------
# REST surface
# ---------------------------------------------------------------------------

def _start_live_fg():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import NullSink, NullSource
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), NullSink(np.float32))
    rt = Runtime()
    return rt, rt.start(fg)


def test_rest_lineage_events_and_openmetrics(every_frame):
    from futuresdr_tpu.runtime.ctrl_port import ControlPort
    tr = every_frame
    _mk_record(tr, {"encode": 10_000, "H2D": 40_000, "dispatch": 20_000,
                    "D2H": 5_000, "emit": 1_000}, sess="s9", ten="t9")
    _mk_record(tr, {"encode": 10_000, "dispatch": 20_000, "emit": 1_000})
    mark = journal.emit("chaos", "rest-probe", k=1)
    journal.emit("serve", "rest-probe", k=2)

    rt, running = _start_live_fg()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29476")
    cp.start()
    base = "http://127.0.0.1:29476"
    try:
        # ---- /api/fg/{fg}/lineage/: tail + records, non-destructive -----
        body = json.load(urllib.request.urlopen(
            base + "/api/fg/0/lineage/"))
        assert set(body) == {"stride", "dropped", "tail", "records"}
        assert body["stride"] == 1
        assert body["tail"]["slowest_lane"] == "H2D"
        assert body["tail"]["slowest_session"] == "s9"
        assert len(body["records"]) == 2
        assert body["records"][0]["stamps"][0]["lane"] == "ingest"
        one = json.load(urllib.request.urlopen(
            base + "/api/fg/0/lineage/?n=1"))
        assert len(one["records"]) == 1
        # the read stole nothing: the tracer still holds both records
        assert len(tr.records()) == 2
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/api/fg/99/lineage/")
        assert ei.value.code == 404
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/api/fg/0/lineage/?n=zap")
        assert ei.value.code == 400

        # ---- /api/events/: cursor + cat filter, NOT fg-scoped -----------
        body = json.load(urllib.request.urlopen(
            base + f"/api/events/?since={mark - 1}"))
        assert [e["event"] for e in body["events"][:2]] == \
            ["rest-probe", "rest-probe"]
        assert body["next"] >= mark + 1 and not body["gap"]
        only = json.load(urllib.request.urlopen(
            base + f"/api/events/?since={mark - 1}&cat=chaos&limit=5"))
        assert all(e["cat"] == "chaos" for e in only["events"])
        assert any(e["seq"] == mark for e in only["events"])
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/api/events/?since=zap")
        assert ei.value.code == 400

        # ---- /metrics?openmetrics=1: exemplar exposition + EOF ----------
        r = urllib.request.urlopen(base + "/metrics?openmetrics=1")
        assert "openmetrics-text" in r.headers["Content-Type"]
        text = r.read().decode()
        assert text.rstrip("\n").endswith("# EOF")
        # the default scrape stays plain v0.0.4 (no exemplars, no EOF)
        plain = urllib.request.urlopen(base + "/metrics").read().decode()
        assert " # {" not in plain and "# EOF" not in plain
    finally:
        running.stop_sync()
        cp.stop()


# ---------------------------------------------------------------------------
# the PR-4 e2e stamp audit: per-sink AND per-session serve latency
# ---------------------------------------------------------------------------

def test_serve_lanes_observe_their_own_e2e_latency(every_frame):
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.ops.stages import Pipeline
    from futuresdr_tpu.serve.engine import ServeEngine
    from futuresdr_tpu.telemetry.doctor import E2E_LATENCY

    app = "lineage-e2e"
    child = E2E_LATENCY.labels(source=f"serve:{app}")
    base_count = child.count
    eng = ServeEngine(Pipeline([mag2_stage()], np.complex64),
                      frame_size=1 << 10, app=app, buckets=(2,))
    s1 = eng.admit(tenant="ta")
    s2 = eng.admit(tenant="tb")
    rng = np.random.default_rng(0)
    x = (rng.standard_normal(1 << 10)
         + 1j * rng.standard_normal(1 << 10)).astype(np.complex64)
    steps = 3
    for _ in range(steps):
        eng.submit(s1.sid, x)
        eng.submit(s2.sid, x)
        eng.step()
    # per-sink: every served frame observed ITS OWN submit→fan-back stamp
    # under the serve:<app> source label
    assert child.count - base_count == steps * 2
    assert child.quantile(0.5) > 0
    # per-session: the sampled records carry session+tenant, so the tail
    # report can name the slowest session
    recs = [r for r in lineage.tracer().records()
            if r.source == f"serve:{app}"]
    assert len(recs) == steps * 2
    assert {r.session for r in recs} == {s1.sid, s2.sid}
    assert {r.tenant for r in recs} == {"ta", "tb"}
    for r in recs:
        lanes = [s[0] for s in r.stamps]
        assert lanes[0] == "ingest" and lanes[-1] == "emit"
        assert "dispatch" in lanes
    rep = lineage.tail_report(recs)
    assert rep["slowest_session"] in {s1.sid, s2.sid}
    assert rep["slowest_tenant"] in {"ta", "tb"}


def test_kernel_sink_observes_per_sink_e2e_latency(every_frame):
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Head, NullSink, NullSource
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.telemetry.doctor import E2E_LATENCY
    from futuresdr_tpu.tpu import TpuKernel

    frame = 1 << 12
    c = config()
    old_buf = c.buffer_size
    c.buffer_size = max(c.buffer_size, 4 * frame * 8)
    try:
        fg = Flowgraph()
        tk = TpuKernel([mag2_stage()], np.complex64, frame_size=frame,
                       frames_in_flight=2)
        fg.connect(NullSource(np.complex64), Head(np.complex64, 8 * frame),
                   tk, NullSink(np.float32))
        Runtime().run(fg)
    finally:
        c.buffer_size = old_buf
    src = tk.meta.instance_name or "TpuKernel"
    child = E2E_LATENCY.labels(source=src)
    assert child.count >= 4, \
        f"kernel lane must observe its own frames' e2e ({src})"
    # the sampled frames carry the same source on their finished records
    recs = [r for r in lineage.tracer().records() if r.source == src]
    assert recs, "1-in-1 sampling left no kernel lineage records"
    # and the bucket the sampled latency landed in carries its exemplar
    ex = child.exemplars()
    assert ex and all(tid for _v, tid, _ts in ex.values())


# ---------------------------------------------------------------------------
# flight-record span snapshot: codec worker rings + shard lanes
# ---------------------------------------------------------------------------

def test_flight_record_spans_cover_codec_workers(tracing, every_frame):
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Head, NullSink, NullSource
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.telemetry import doctor as doc
    from futuresdr_tpu.tpu import TpuKernel

    frame = 1 << 12
    c = config()
    old_buf = c.buffer_size
    c.buffer_size = max(c.buffer_size, 4 * frame * 8)
    try:
        fg = Flowgraph()
        tk = TpuKernel([mag2_stage()], np.complex64, frame_size=frame,
                       frames_in_flight=2)
        fg.connect(NullSource(np.complex64), Head(np.complex64, 8 * frame),
                   tk, NullSink(np.float32))
        Runtime().run(fg)
    finally:
        c.buffer_size = old_buf

    before = len(tracing.snapshot())
    rep = doc.doctor().flight_record("lineage-test")
    # the snapshot is NON-destructive: the ring still feeds other trace
    # consumers (chrome_trace, the REST trace route) afterwards
    assert len(tracing.snapshot()) == before
    rep2 = doc.doctor().flight_record("lineage-test")
    assert {k: len(v) for k, v in rep["spans"].items()} == \
        {k: len(v) for k, v in rep2["spans"].items()}
    # codec worker rings ride the snapshot under their own thread keys
    codec_threads = [k for k in rep["spans"] if k.startswith("fsdr-codec-")]
    assert codec_threads, sorted(rep["spans"])
    names = {s["name"] for k in codec_threads for s in rep["spans"][k]}
    assert names & {"encode", "decode"}, names
    # and the journal + tail sections ride the same black box
    assert rep["tail"] is not None and rep["tail"]["samples"] > 0
    assert any(e["cat"] == "kernel" and e["event"] == "init"
               for e in rep["journal"] or [])


_SHARD_SPANS_WORKER = r"""
import numpy as np
from futuresdr_tpu.ops.stages import Pipeline, fir_stage, mag2_stage
from futuresdr_tpu.shard.data import ShardRunner, shard_pipeline
from futuresdr_tpu.telemetry import doctor as doc, spans

rec = spans.recorder()
rec.enabled = True
D, F, K = 8, 1 << 12, 2
taps = np.random.default_rng(0).standard_normal(9).astype(np.float32)
prog = shard_pipeline(Pipeline([fir_stage(taps), mag2_stage()],
                               np.complex64), mode="data", n_devices=D,
                      name="lineage-shard")
runner = ShardRunner(prog, F, k=K, checkpoint_every=1)
rng = np.random.default_rng(1)
for _ in range(2):
    g = (rng.standard_normal((D, K, F))
         + 1j * rng.standard_normal((D, K, F))).astype(np.complex64)
    runner.run_group(g)

before = len(rec.snapshot())
rep = doc.doctor().flight_record("shard-spans")
assert len(rec.snapshot()) == before, "flight record drained the ring"
lanes = {s["name"] for v in rep["spans"].values() for s in v
         if s["cat"] == "shard"}
assert lanes == {"shard:d%d" % d for d in range(D)}, lanes
assert any(e["cat"] == "shard" and e["event"] == "checkpoint-commit"
           for e in rep["journal"] or []), rep["journal"]
print("WORKER OK")
"""


def test_flight_record_spans_cover_shard_lanes(tmp_path):
    """Every shard lane's span rides the flight record (fresh process on
    the virtual 8-device mesh — the test_shard.py worker pattern)."""
    wf = tmp_path / "worker.py"
    wf.write_text(_SHARD_SPANS_WORKER)
    pypath = _REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    env = dict(os.environ,
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               JAX_PLATFORMS="cpu",
               FUTURESDR_TPU_AUTOTUNE_CACHE_DIR="off",
               PYTHONPATH=pypath.rstrip(os.pathsep))
    r = subprocess.run([sys.executable, str(wf)], env=env,
                       capture_output=True, text=True, timeout=240.0)
    assert r.returncode == 0, \
        f"worker rc={r.returncode}\n{r.stdout[-3000:]}\n{r.stderr[-3000:]}"
    assert "WORKER OK" in r.stdout, r.stdout[-3000:]
