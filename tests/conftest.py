"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; shardings are validated on a virtual CPU mesh
(SURVEY §2.7 / environment notes). Must run before any jax import.

``FSDR_TEST_TPU=1`` skips the CPU forcing so a curated subset can run against a
live chip when the tunnel answers (round-5 practice: single-chip compute-plane
tests only — mesh/sharding tests still need the 8-device CPU run).
"""

import faulthandler
import gc
import os
import sys
import threading
import time

import pytest

if not os.environ.get("FSDR_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"   # override axon: tests are deterministic-CPU
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

# tests must not read or POLLUTE the user-level autotune pick store
# (tpu/autotune.py persistence): the devchain cached-K tests would otherwise
# leak their synthetic picks into later processes' launches
os.environ.setdefault("FUTURESDR_TPU_AUTOTUNE_CACHE_DIR", "off")

# dump-on-timeout (ISSUE 6 satellite): a future hang in tier-1 prints every
# thread's stack BEFORE the harness's `timeout -k` kill — set the dump a bit
# under the 870 s tier-1 budget; FSDR_TEST_HANG_DUMP_S=0 disables
faulthandler.enable()
_hang_dump_s = float(os.environ.get("FSDR_TEST_HANG_DUMP_S", "840"))
if _hang_dump_s > 0:
    faulthandler.dump_traceback_later(_hang_dump_s, exit=False)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# jax snapshots JAX_PLATFORMS at import; force it again via config in case the driver
# environment pre-set another platform before this conftest ran.
import jax  # noqa: E402

if not os.environ.get("FSDR_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# thread-leak gate (ISSUE 6 satellite): the chaos harness asserts "no leaked
# threads" — that invariant must hold on the HAPPY path too, so the runtime/
# doctor/devchain test modules get an autouse fixture asserting every
# non-daemon thread spawned during a test is gone by teardown (schedulers are
# dropped-not-shutdown in most tests; gc triggers their loop/pool finalizers)
# ---------------------------------------------------------------------------

_THREAD_CHECKED_MODULES = {
    "test_flowgraph", "test_fail", "test_doctor", "test_devchain",
    "test_faults", "test_policies",
}
#: process-global by design, exempt from the leak gate: the D2H fetch pool
#: (ops/xfer.py) and the codec worker pool (ops/codec_pool.py) live for the
#: process lifetime
_THREAD_ALLOW_PREFIXES = ("fsdr-d2h", "fsdr-codec")


@pytest.fixture(autouse=True)
def no_leaked_threads(request):
    mod = request.module.__name__.rsplit(".", 1)[-1]
    if mod not in _THREAD_CHECKED_MODULES:
        yield
        return
    before = set(threading.enumerate())
    yield
    deadline = time.monotonic() + 8.0
    leaked = []
    while True:
        gc.collect()      # drop Runtime refs → scheduler loop/pool finalizers
        leaked = [t for t in threading.enumerate()
                  if t not in before and t.is_alive() and not t.daemon
                  and not t.name.startswith(_THREAD_ALLOW_PREFIXES)]
        if not leaked or time.monotonic() > deadline:
            break
        time.sleep(0.05)
    assert not leaked, \
        f"leaked non-daemon threads: {sorted(t.name for t in leaked)}"
