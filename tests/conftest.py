"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; shardings are validated on a virtual CPU mesh
(SURVEY §2.7 / environment notes). Must run before any jax import.

``FSDR_TEST_TPU=1`` skips the CPU forcing so a curated subset can run against a
live chip when the tunnel answers (round-5 practice: single-chip compute-plane
tests only — mesh/sharding tests still need the 8-device CPU run).
"""

import os
import sys

if not os.environ.get("FSDR_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"   # override axon: tests are deterministic-CPU
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# jax snapshots JAX_PLATFORMS at import; force it again via config in case the driver
# environment pre-set another platform before this conftest ran.
import jax  # noqa: E402

if not os.environ.get("FSDR_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")
