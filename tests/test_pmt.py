"""Pmt tagged-union tests (reference: `crates/types/src/pmt.rs` test block)."""

import json

import numpy as np
import pytest

from futuresdr_tpu.types import Pmt, PmtKind, PmtConversionError


def test_constructors_and_kinds():
    assert Pmt.ok().kind is PmtKind.OK
    assert Pmt.null().kind is PmtKind.NULL
    assert Pmt.finished().is_finished()
    assert Pmt.f32(1.5).kind is PmtKind.F32
    assert Pmt.u32(2**32 + 2).value == 2  # wraps like the fixed-width type


def test_from_py_inference():
    assert Pmt.from_py(True).kind is PmtKind.BOOL
    assert Pmt.from_py(3).kind is PmtKind.USIZE
    assert Pmt.from_py(-3).kind is PmtKind.ISIZE
    assert Pmt.from_py(3.5).kind is PmtKind.F64
    assert Pmt.from_py("hi").kind is PmtKind.STRING
    assert Pmt.from_py(b"ab").kind is PmtKind.BLOB
    assert Pmt.from_py(np.zeros(4, np.float32)).kind is PmtKind.VEC_F32
    assert Pmt.from_py(np.zeros(4, np.complex64)).kind is PmtKind.VEC_CF32
    assert Pmt.from_py({"a": 1}).kind is PmtKind.MAP_STR_PMT
    assert Pmt.from_py([1, 2]).kind is PmtKind.VEC_PMT


def test_equality():
    assert Pmt.f64(2.0) == Pmt.f64(2.0)
    assert Pmt.f64(2.0) != Pmt.f32(2.0)
    assert Pmt.vec_f32([1, 2]) == Pmt.vec_f32([1, 2])
    assert Pmt.string("a") != Pmt.string("b")


def test_accessors_and_errors():
    assert Pmt.usize(7).to_int() == 7
    assert Pmt.f64(2.5).to_float() == 2.5
    assert Pmt.usize(7).to_float() == 7.0
    with pytest.raises(PmtConversionError):
        Pmt.string("x").to_int()
    with pytest.raises(PmtConversionError):
        Pmt.null().to_ndarray()


def test_json_roundtrip():
    cases = [
        Pmt.ok(),
        Pmt.null(),
        Pmt.finished(),
        Pmt.string("hello"),
        Pmt.bool_(True),
        Pmt.usize(42),
        Pmt.isize(-42),
        Pmt.u32(7),
        Pmt.u64(1 << 40),
        Pmt.f32(1.5),
        Pmt.f64(-2.25),
        Pmt.vec_f32([1.0, 2.0, 3.0]),
        Pmt.vec_cf32([1 + 2j, 3 - 4j]),
        Pmt.vec_u64([1, 2, 3]),
        Pmt.blob(b"\x00\x01\xff"),
        Pmt.vec([1, "two", 3.0]),
        Pmt.map({"freq": 100e6, "gain": 30}),
    ]
    for p in cases:
        wire = json.dumps(p.to_json())
        q = Pmt.from_json(json.loads(wire))
        assert q == p, f"roundtrip failed for {p!r}: got {q!r}"


def test_immutable():
    p = Pmt.f64(1.0)
    with pytest.raises(AttributeError):
        p.value = 2.0
    arr = Pmt.vec_f32([1, 2]).to_ndarray()
    with pytest.raises(ValueError):
        arr[0] = 9
