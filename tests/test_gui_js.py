"""CI coverage for gui/widgets.js (VERDICT r2 item 7 / weak 8).

Two layers:
- *structural validation* (always runs, no JS engine needed): brace balance
  outside strings/comments, the full widget-export inventory, and GLSL
  cross-checks — shader pairs share the vertex->fragment varying, every
  declared uniform is used AND fetched from JS by the same name, `#version
  300 es` leads each shader, outputs are written.
- *execution smoke* (``tests/gui_smoke.js``): runs the widget code headless
  under node with stub canvas/DOM — gated on a JS runtime being on PATH,
  because this image ships none.
"""

import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

WIDGETS = Path(__file__).resolve().parent.parent / "futuresdr_tpu/gui/widgets.js"
SRC = WIDGETS.read_text()

EXPORTS = [
    "Handle", "Pmt", "pollPeriodically", "callPeriodically",
    "FlowgraphCanvas", "FlowgraphTable", "PmtEditor",
    "Slider", "RadioSelector", "ListSelector",
    "GL", "Waterfall", "Waterfall2D", "TimeSink",
    "ConstellationSink", "ConstellationSinkDensity", "ConstellationSinkDensity2D",
    "ArrayView",
]


def _strip(src: str) -> str:
    """Remove comments and string/template literals (leaving brace-free stubs)."""
    out, i, n = [], 0, len(src)
    while i < n:
        c = src[i]
        if c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            i = (j + 2) if j != -1 else n
        elif c == "/" and i + 1 < n and src[i + 1] == "/":
            j = src.find("\n", i)
            i = j if j != -1 else n
        elif c in "'\"`":
            q, j = c, i + 1
            while j < n and src[j] != q:
                j += 2 if src[j] == "\\" else 1
            out.append("''")
            i = j + 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


def test_brace_balance():
    stripped = _strip(SRC)
    for o, c in ("{}", "()", "[]"):
        assert stripped.count(o) == stripped.count(c), f"unbalanced {o}{c}"
    # nesting never goes negative (catches transposed closers)
    depth = 0
    for ch in stripped:
        depth += ch == "{"
        depth -= ch == "}"
        assert depth >= 0
    assert depth == 0


def test_widget_inventory_complete():
    for name in EXPORTS:
        assert re.search(rf"FSDR\.{re.escape(name)}\s*=", SRC), f"missing FSDR.{name}"
    assert "module.exports = FSDR" in SRC


def _shader(name: str) -> str:
    """Extract a shader built as FSDR.NAME = [ '...', ... ].join('\\n')."""
    m = re.search(rf"FSDR\.{name}\s*=\s*\[(.*?)\]\.join", SRC, re.S)
    assert m, f"shader {name} not found"
    lines = re.findall(r"'((?:[^'\\]|\\.)*)'", m.group(1))
    return "\n".join(lines)


@pytest.mark.parametrize("frag", ["WATERFALL_FRAG", "DENSITY_FRAG"])
def test_glsl_structure(frag):
    vert, f = _shader("GL.VERT"), _shader(frag)
    for sh in (vert, f):
        assert sh.splitlines()[0].strip() == "#version 300 es"
        assert re.search(r"void\s+main\s*\(\s*\)", sh)
    # vertex out == fragment in (the varying)
    v_outs = set(re.findall(r"out\s+vec\d\s+(\w+)\s*;", vert))
    f_ins = set(re.findall(r"in\s+vec\d\s+(\w+)\s*;", f))
    assert v_outs == f_ins == {"uv"}
    assert "gl_Position" in vert
    # the fragment output is declared and written
    f_out = re.findall(r"out\s+vec4\s+(\w+)\s*;", f)
    assert len(f_out) == 1 and f"{f_out[0]} =" in f
    # every declared uniform is used in the body
    for u in re.findall(r"uniform\s+\w+\s+(\w+)\s*;", f):
        body = f.split("void main()", 1)[1]
        assert u in body, f"uniform {u} declared but unused in {frag}"


@pytest.mark.parametrize("frag,widget", [("WATERFALL_FRAG", "Waterfall"),
                                         ("DENSITY_FRAG", "ConstellationSinkDensity")])
def test_js_uniforms_match_glsl(frag, widget):
    """Every getUniformLocation(...) name in the widget's constructor exists in
    its shader — a renamed uniform fails CI instead of silently returning null."""
    f = _shader(frag)
    declared = set(re.findall(r"uniform\s+\w+\s+(\w+)\s*;", f))
    m = re.search(rf"FSDR\.{widget} = function(.*?)FSDR\.{widget}\.prototype",
                  SRC, re.S)
    assert m, widget
    fetched = set(re.findall(r"getUniformLocation\([^,]+,\s*'(\w+)'\)", m.group(1)))
    assert fetched <= declared, f"{widget} fetches unknown uniforms {fetched - declared}"
    assert declared <= fetched, f"{widget} never binds uniforms {declared - fetched}"


def test_gl_paths_guarded_by_fallback():
    """Both GPU sinks construct a canvas-2D fallback when WebGL2 is missing."""
    for widget in ("Waterfall", "ConstellationSinkDensity"):
        m = re.search(rf"FSDR\.{widget} = function(.*?)FSDR\.{widget}\.prototype",
                      SRC, re.S)
        assert "this.fallback" in m.group(1), f"{widget} lacks a fallback"


NODE = shutil.which("node") or shutil.which("nodejs")


@pytest.mark.skipif(NODE is None, reason="no JS runtime in this image")
def test_execution_smoke_under_node():
    r = subprocess.run(
        [NODE, str(Path(__file__).resolve().parent / "gui_smoke.js"), str(WIDGETS)],
        capture_output=True, text=True, timeout=60)
    sys.stdout.write(r.stdout)
    assert r.returncode == 0, r.stdout + r.stderr
