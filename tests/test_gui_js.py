"""CI coverage for gui/widgets.js.

Three layers:
- *structural validation* (cheap, always runs): brace balance outside
  strings/comments, the full widget-export inventory, and GLSL cross-checks —
  shader pairs share the vertex->fragment varying, every declared uniform is
  used AND fetched from JS by the same name, `#version 300 es` leads each
  shader, outputs are written.
- *execution* (VERDICT r3 item 9 — always runs, NO node needed): the widget
  code runs through the vendored jsmini interpreter (``gui/jsmini.py``) with
  recording DOM/canvas/WebGL stubs and a synchronous fetch bridge to a REAL
  control-port server — layout math, click dispatch, Pmt round-trips, 2D
  pixel rendering, histogram binning, and the GL call sequences all execute.
- *node smoke* (``tests/gui_smoke.js``): the same code under a actual JS
  engine — gated on node being on PATH, because this image ships none.
"""

import re
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

WIDGETS = Path(__file__).resolve().parent.parent / "futuresdr_tpu/gui/widgets.js"
SRC = WIDGETS.read_text()

EXPORTS = [
    "Handle", "Pmt", "pollPeriodically", "callPeriodically",
    "FlowgraphCanvas", "FlowgraphTable", "MetricsTable", "PmtEditor",
    "DoctorPanel",
    "Slider", "RadioSelector", "ListSelector",
    "GL", "Waterfall", "Waterfall2D", "TimeSink",
    "ConstellationSink", "ConstellationSinkDensity", "ConstellationSinkDensity2D",
    "ArrayView",
]


def _strip(src: str) -> str:
    """Remove comments and string/template literals (leaving brace-free stubs)."""
    out, i, n = [], 0, len(src)
    while i < n:
        c = src[i]
        if c == "/" and i + 1 < n and src[i + 1] == "*":
            j = src.find("*/", i + 2)
            i = (j + 2) if j != -1 else n
        elif c == "/" and i + 1 < n and src[i + 1] == "/":
            j = src.find("\n", i)
            i = j if j != -1 else n
        elif c in "'\"`":
            q, j = c, i + 1
            while j < n and src[j] != q:
                j += 2 if src[j] == "\\" else 1
            out.append("''")
            i = j + 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


def test_brace_balance():
    stripped = _strip(SRC)
    for o, c in ("{}", "()", "[]"):
        assert stripped.count(o) == stripped.count(c), f"unbalanced {o}{c}"
    # nesting never goes negative (catches transposed closers)
    depth = 0
    for ch in stripped:
        depth += ch == "{"
        depth -= ch == "}"
        assert depth >= 0
    assert depth == 0


def test_widget_inventory_complete():
    for name in EXPORTS:
        assert re.search(rf"FSDR\.{re.escape(name)}\s*=", SRC), f"missing FSDR.{name}"
    assert "module.exports = FSDR" in SRC


def _shader(name: str) -> str:
    """Extract a shader built as FSDR.NAME = [ '...', ... ].join('\\n')."""
    m = re.search(rf"FSDR\.{name}\s*=\s*\[(.*?)\]\.join", SRC, re.S)
    assert m, f"shader {name} not found"
    lines = re.findall(r"'((?:[^'\\]|\\.)*)'", m.group(1))
    return "\n".join(lines)


@pytest.mark.parametrize("frag", ["WATERFALL_FRAG", "DENSITY_FRAG"])
def test_glsl_structure(frag):
    vert, f = _shader("GL.VERT"), _shader(frag)
    for sh in (vert, f):
        assert sh.splitlines()[0].strip() == "#version 300 es"
        assert re.search(r"void\s+main\s*\(\s*\)", sh)
    # vertex out == fragment in (the varying)
    v_outs = set(re.findall(r"out\s+vec\d\s+(\w+)\s*;", vert))
    f_ins = set(re.findall(r"in\s+vec\d\s+(\w+)\s*;", f))
    assert v_outs == f_ins == {"uv"}
    assert "gl_Position" in vert
    # the fragment output is declared and written
    f_out = re.findall(r"out\s+vec4\s+(\w+)\s*;", f)
    assert len(f_out) == 1 and f"{f_out[0]} =" in f
    # every declared uniform is used in the body
    for u in re.findall(r"uniform\s+\w+\s+(\w+)\s*;", f):
        body = f.split("void main()", 1)[1]
        assert u in body, f"uniform {u} declared but unused in {frag}"


@pytest.mark.parametrize("frag,widget", [("WATERFALL_FRAG", "Waterfall"),
                                         ("DENSITY_FRAG", "ConstellationSinkDensity")])
def test_js_uniforms_match_glsl(frag, widget):
    """Every getUniformLocation(...) name in the widget's constructor exists in
    its shader — a renamed uniform fails CI instead of silently returning null."""
    f = _shader(frag)
    declared = set(re.findall(r"uniform\s+\w+\s+(\w+)\s*;", f))
    m = re.search(rf"FSDR\.{widget} = function(.*?)FSDR\.{widget}\.prototype",
                  SRC, re.S)
    assert m, widget
    fetched = set(re.findall(r"getUniformLocation\([^,]+,\s*'(\w+)'\)", m.group(1)))
    assert fetched <= declared, f"{widget} fetches unknown uniforms {fetched - declared}"
    assert declared <= fetched, f"{widget} never binds uniforms {declared - fetched}"


def test_gl_paths_guarded_by_fallback():
    """Both GPU sinks construct AS their canvas-2D sibling when WebGL2 is
    missing (constructor return value — state and controls then operate on the
    object that actually renders)."""
    for widget in ("Waterfall", "ConstellationSinkDensity"):
        m = re.search(rf"FSDR\.{widget} = function(.*?)FSDR\.{widget}\.prototype",
                      SRC, re.S)
        assert re.search(rf"return new FSDR\.\w+2D\(", m.group(1)), \
            f"{widget} lacks a 2D fallback construction"


NODE = shutil.which("node") or shutil.which("nodejs")


@pytest.mark.skipif(NODE is None, reason="no JS runtime in this image")
def test_execution_smoke_under_node():
    r = subprocess.run(
        [NODE, str(Path(__file__).resolve().parent / "gui_smoke.js"), str(WIDGETS)],
        capture_output=True, text=True, timeout=60)
    sys.stdout.write(r.stdout)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# EXECUTION layer (VERDICT r3 item 9): the widget code RUNS in CI through the
# vendored jsmini interpreter (gui/jsmini.py) — no node needed. DOM/canvas/GL
# hosts below are recording stubs; fetch is a SYNCHRONOUS bridge to a real
# control-port server where the test needs one.
# ---------------------------------------------------------------------------
import numpy as np

from futuresdr_tpu.gui.jsmini import Interp, JSObject, UNDEF


class _El:
    """Minimal DOM element: attributes + children + recorded text."""

    def __init__(self, tag="div"):
        self.tag = tag
        self.children = []
        self.textContent = ""
        self.innerHTML = ""
        self.className = ""
        self.value = ""
        self.rows = []
        self._listeners = {}
        self.style = JSObject()          # e.g. the MetricsTable busy bar width

    def appendChild(self, el):
        self.children.append(el)
        return el

    def addEventListener(self, name, fn):
        self._listeners[name] = fn

    def removeEventListener(self, name, fn):
        if self._listeners.get(name) is fn:
            del self._listeners[name]

    def getBoundingClientRect(self):
        o = JSObject()
        o.set("left", 0.0)
        o.set("top", 0.0)
        return o

    def insertRow(self):
        r = _El("tr")
        self.rows.append(r)
        return r

    def deleteRow(self, i):
        del self.rows[int(i)]

    def insertCell(self):
        c = _El("td")
        self.children.append(c)
        return c

    def getContext(self, kind, *a):
        if kind == "2d":
            if not hasattr(self, "_ctx2d"):
                self._ctx2d = _Ctx2D(self)
            return self._ctx2d
        return None                       # no WebGL2 → fallback paths


class _ImageData:
    def __init__(self, w, h):
        self.width, self.height = int(w), int(h)
        self.data = [0.0] * (4 * int(w) * int(h))


class _Ctx2D:
    """Recording canvas-2D context; putImageData keeps the last row/pixels."""

    def __init__(self, cv):
        self.cv = cv
        self.fillStyle = ""
        self.strokeStyle = ""
        self.font = ""
        self.imageSmoothingEnabled = True
        self.ops = []
        self.last_image = None

    def _rec(self, *a):
        self.ops.append(a)

    def fillRect(self, *a):
        self._rec("fillRect", *a)

    def strokeRect(self, *a):
        self._rec("strokeRect", *a)

    def fillText(self, *a):
        self._rec("fillText", *a)

    def beginPath(self, *a):
        self._rec("beginPath")

    def moveTo(self, *a):
        self._rec("moveTo", *a)

    def lineTo(self, *a):
        self._rec("lineTo", *a)

    def bezierCurveTo(self, *a):
        self._rec("bezier", *a)

    def stroke(self, *a):
        self._rec("stroke")

    def fill(self, *a):
        self._rec("fill")

    def setLineDash(self, *a):
        self._rec("dash", *a)

    def drawImage(self, *a):
        self._rec("drawImage", *a)

    def createImageData(self, w, h):
        return _ImageData(w, h)

    def putImageData(self, img, x, y):
        self.last_image = img
        self._rec("putImageData", x, y)


class _Doc:
    def createElement(self, tag):
        return _El(tag)

    def createTextNode(self, text):
        el = _El("#text")
        el.textContent = text
        return el


def _canvas(w=320, h=200):
    cv = _El("canvas")
    cv.width = float(w)
    cv.height = float(h)
    return cv


def _interp(fetch=None):
    i = Interp(hosts={"document": _Doc()})
    if fetch is not None:
        i.genv.vars["fetch"] = fetch
    i.run(SRC)
    return i


def test_exec_pmt_roundtrip():
    """FSDR.Pmt builders + parse() EXECUTE and serialize exactly like the
    Python Pmt JSON wire format (types/pmt.py)."""
    from futuresdr_tpu.types import Pmt
    i = _interp()
    cases = [
        ("FSDR.Pmt.f64(3.25)", Pmt.f64(3.25)),
        ("FSDR.Pmt.u32(7)", Pmt.u32(7)),
        ("FSDR.Pmt.bool_(true)", Pmt.bool_(True)),
        ("FSDR.Pmt.string('hi')", Pmt.string("hi")),
        ("FSDR.Pmt.parse('F64', '2.5')", Pmt.f64(2.5)),
        ("FSDR.Pmt.parse('Usize', '42')", Pmt.usize(42)),
        ("FSDR.Pmt.parse('Bool', 'true')", Pmt.bool_(True)),
        ("FSDR.Pmt.parse('Null', '')", Pmt.null()),
        ("FSDR.Pmt.parse('JSON', '{\"F32\": 1.5}')", Pmt.f32(1.5)),
    ]
    for js, py in cases:
        js_json = i.eval(f"JSON.stringify({js})")
        assert Pmt.from_json(json_mod.loads(js_json)) == py, (js, js_json)
    # u32 wraps like JS >>> 0
    assert i.eval("FSDR.Pmt.u32(4294967296 + 5).U32") == 5.0


import json as json_mod  # noqa: E402


def test_exec_flowgraph_canvas_layout_and_click():
    """FlowgraphCanvas lays out a real describe() JSON by topological rank and
    click dispatch selects the right block — executed, not grepped."""
    desc_py = {
        "id": 0,
        "blocks": [
            {"id": 0, "instance_name": "src", "stream_inputs": [],
             "stream_outputs": ["out"], "message_inputs": [], "blocking": False},
            {"id": 1, "instance_name": "fir", "stream_inputs": ["in"],
             "stream_outputs": ["out"], "message_inputs": ["taps"],
             "blocking": False},
            {"id": 2, "instance_name": "snk", "stream_inputs": ["in"],
             "stream_outputs": [], "message_inputs": [], "blocking": False},
        ],
        "stream_edges": [[0, "out", 1, "in"], [1, "out", 2, "in"]],
        "message_edges": [],
    }
    i = _interp()
    cv = _canvas(300, 120)
    i.genv.vars["__cv"] = cv
    i.run("const fgc = new FSDR.FlowgraphCanvas(__cv, "
          "{onSelect: b => { __sel.push(b.instance_name); }});")
    i.genv.vars["__sel"] = []
    i.run(f"fgc.update(JSON.parse({json_mod.dumps(json_mod.dumps(desc_py))}));")
    fgc = i.get("fgc")
    boxes = fgc.get("boxes")
    assert len(boxes) == 3
    xs = {b.get("blk").get("instance_name"): b.get("x") for b in boxes}
    assert xs["src"] < xs["fir"] < xs["snk"]     # rank order left→right
    # boxes live inside the canvas
    for b in boxes:
        assert 0 <= b.get("x") and b.get("x") + b.get("w") <= 300
        assert 0 <= b.get("y") and b.get("y") + b.get("h") <= 120
    # drawing recorded edges + boxes
    ctx = cv.getContext("2d")
    kinds = [op[0] for op in ctx.ops]
    assert kinds.count("bezier") == 2 and "fillText" in kinds
    # synthetic click on the middle block fires onSelect
    mid = [b for b in boxes if b.get("blk").get("instance_name") == "fir"][0]
    ev = JSObject()
    ev.set("clientX", mid.get("x") + 2.0)
    ev.set("clientY", mid.get("y") + 2.0)
    i.call(cv._listeners["click"], UNDEF, ev)
    assert i.genv.vars["__sel"] == ["fir"]
    assert fgc.get("selected") == 1.0


def test_exec_handle_against_real_rest_server():
    """FSDR.Handle + PmtEditor call path against the REAL control port: the
    fetch bridge is synchronous urllib, the server is a live flowgraph."""
    import time
    import urllib.request

    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import MessageSink, MessageSource
    from futuresdr_tpu.config import config
    from futuresdr_tpu.types import Pmt as PyPmt

    config().ctrlport_enable = True
    old_bind = config().ctrlport_bind
    config().ctrlport_bind = "127.0.0.1:18339"
    running = None
    try:
        fg = Flowgraph()
        src = MessageSource(PyPmt.string("x"), interval=0.05, count=400)
        snk = MessageSink()
        fg.connect_message(src, "out", snk, "in")
        rt = Runtime()
        running = rt.start(fg)
        # readiness poll: the control-port server binds on the scheduler loop
        # asynchronously — a fixed sleep raced it under full-suite load (the
        # one flaky failure of round 5's suite runs)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                urllib.request.urlopen(
                    "http://127.0.0.1:18339/api/fg/0/", timeout=2).read()
                break
            except Exception:
                time.sleep(0.1)
        else:
            raise RuntimeError("control port never became ready")

        def fetch(url, opts=UNDEF):
            req = urllib.request.Request(url)
            data = None
            if opts is not UNDEF and opts and opts.get("body") is not UNDEF:
                data = opts.get("body").encode()
                req = urllib.request.Request(url, data=data, method="POST")
                req.add_header("Content-Type", "application/json")
            body = urllib.request.urlopen(req, timeout=5).read().decode()
            resp = JSObject()
            resp.set("json", lambda: json_to_js(body))
            return resp

        i = _interp(fetch=fetch)

        def json_to_js(s):
            return i.eval(f"JSON.parse({json_mod.dumps(s)})")

        i.run("const h = new FSDR.Handle('http://127.0.0.1:18339/');")
        fgs = i.eval("h.flowgraphs()")
        assert i.eval("JSON.stringify(h.flowgraphs())") == "[0]"
        desc = i.eval("h.describe(0)")
        names = [b.get("instance_name") for b in desc.get("blocks")]
        assert any("MessageSource" in n for n in names)
        # FlowgraphTable renders the real description
        tbl = _El("table")
        tbl.rows.append(_El("tr"))        # header row
        i.genv.vars["__tbl"] = tbl
        i.genv.vars["__desc"] = desc
        i.run("new FSDR.FlowgraphTable(__tbl).update(__desc);")
        assert len(tbl.rows) == 1 + len(names)
        del fgs
    finally:
        if running is not None:
            running.stop_sync()
        config().ctrlport_enable = False
        config().ctrlport_bind = old_bind


def test_exec_waterfall2d_and_timesink_render():
    """The canvas-2D waterfall + TimeSink paint real pixel rows from data."""
    i = _interp()
    cv = _canvas(64, 32)
    i.genv.vars["__cv"] = cv
    i.run("const wf = new FSDR.Waterfall2D(__cv, {autorange: true});")
    ramp = list(np.linspace(0.0, 1.0, 64))
    i.genv.vars["__data"] = ramp
    for _ in range(30):                   # let autorange converge
        i.run("wf.frame(__data);")
    img = cv.getContext("2d").last_image
    assert img is not None and img.width == 64
    reds = [img.data[4 * x] for x in range(64)]
    assert reds[0] < reds[20] < reds[40]  # ramp maps to increasing intensity
    assert all(img.data[4 * x + 3] == 255 for x in range(64))

    cv2 = _canvas(64, 32)
    i.genv.vars["__cv2"] = cv2
    i.run("const ts = new FSDR.TimeSink(__cv2); ts.frame(__data);")
    ops = [o[0] for o in cv2.getContext("2d").ops]
    assert "lineTo" in ops and "stroke" in ops


def test_exec_density_histogram_finds_qpsk_clusters():
    """ConstellationSinkDensity.accumulate (shared by GL + 2D paths) bins QPSK
    points into exactly 4 hotspots."""
    i = _interp()
    cv = _canvas(64, 64)
    i.genv.vars["__cv"] = cv
    i.run("const cs = new FSDR.ConstellationSinkDensity2D(__cv, {bins: 32});")
    rng = np.random.default_rng(0)
    pts = []
    for _ in range(400):
        s = rng.integers(0, 4)
        re_ = (1 if s & 1 else -1) * 0.7 + rng.normal(0, 0.02)
        im = (1 if s & 2 else -1) * 0.7 + rng.normal(0, 0.02)
        pts += [float(re_), float(im)]
    i.genv.vars["__iq"] = pts
    i.run("cs.frame(__iq);")
    hist = np.asarray(list(i.eval("cs.hist")), dtype=float).reshape(32, 32)
    # 4 clusters: count cells above half-peak, grouped in 4 quadrants
    hot = hist > hist.max() / 2
    quads = [hot[:16, :16].sum(), hot[:16, 16:].sum(),
             hot[16:, :16].sum(), hot[16:, 16:].sum()]
    assert all(q >= 1 for q in quads), quads
    # the renderer paints into its offscreen scratch then blits to the canvas
    off_img = i.eval("cs.off").getContext("2d").last_image
    assert off_img is not None and off_img.width == 32
    assert any(op[0] == "drawImage" for op in cv.getContext("2d").ops)


class _GLRec:
    """Recording WebGL2 stub: enough surface for FSDR.GL + the GPU sinks."""

    def __init__(self):
        for i, name in enumerate(
            ("VERTEX_SHADER", "FRAGMENT_SHADER", "COMPILE_STATUS",
             "LINK_STATUS", "ARRAY_BUFFER", "STATIC_DRAW", "FLOAT",
             "TEXTURE_2D", "TEXTURE_WRAP_S", "TEXTURE_WRAP_T", "CLAMP_TO_EDGE",
             "REPEAT", "TEXTURE_MIN_FILTER", "TEXTURE_MAG_FILTER", "NEAREST",
             "LINEAR", "UNPACK_ALIGNMENT", "R32F", "RED", "RGBA",
             "UNSIGNED_BYTE", "TRIANGLE_STRIP")):
            setattr(self, name, float(i + 1))
        self.TEXTURE0 = 100.0
        self.calls = []
        self.uniforms = {}
        self._shader_srcs = {}

    def _rec(self, *a):
        self.calls.append(a)

    def createShader(self, t):
        sh = _El("shader")
        sh.type = t
        return sh

    def shaderSource(self, sh, src):
        self._shader_srcs[id(sh)] = src

    def compileShader(self, sh):
        self._rec("compile")

    def getShaderParameter(self, sh, p):
        return True

    def getShaderInfoLog(self, sh):
        return ""

    def createProgram(self):
        return _El("prog")

    def attachShader(self, p, sh):
        self._rec("attach")

    def linkProgram(self, p):
        self._rec("link")

    def getProgramParameter(self, p, s):
        return True

    def getProgramInfoLog(self, p):
        return ""

    def useProgram(self, p):
        self._rec("useProgram")

    def createBuffer(self):
        return _El("buf")

    def bindBuffer(self, *a):
        self._rec("bindBuffer")

    def bufferData(self, target, data, usage):
        self._rec("bufferData", list(data))

    def getAttribLocation(self, p, name):
        return 0.0

    def enableVertexAttribArray(self, loc):
        self._rec("enableVA")

    def vertexAttribPointer(self, *a):
        self._rec("vap")

    def createTexture(self):
        return _El("tex")

    def activeTexture(self, unit):
        self._rec("activeTexture", unit)

    def bindTexture(self, *a):
        self._rec("bindTexture")

    def texParameteri(self, *a):
        self._rec("texParameteri", *a)

    def pixelStorei(self, *a):
        self._rec("pixelStorei")

    def texImage2D(self, *a):
        self._rec("texImage2D", *a)

    def texSubImage2D(self, *a):
        self._rec("texSubImage2D", *a)

    def deleteTexture(self, t):
        self._rec("deleteTexture")

    def getUniformLocation(self, p, name):
        return name

    def uniform1i(self, name, v):
        self.uniforms[name] = v

    def uniform1f(self, name, v):
        self.uniforms[name] = v

    def viewport(self, *a):
        self._rec("viewport", *a)

    def drawArrays(self, *a):
        self._rec("drawArrays", *a)


def test_exec_waterfall_gl_path_ring_and_uniforms():
    """The WebGL2 waterfall EXECUTES against a recording GL stub: shaders
    compile+link, the LUT is a monotonic 256-entry ramp, each frame uploads
    one row and advances the ring, and yoffset tracks row/history."""
    i = _interp()
    gl = _GLRec()
    cv = _canvas(128, 64)
    cv.getContext = lambda kind, *a: gl if kind == "webgl2" else None
    i.genv.vars["__cv"] = cv
    i.run("const wf = new FSDR.Waterfall(__cv, {history: 8, autorange: true});")
    wf = i.get("wf")
    assert wf.get("fallback") is UNDEF     # took the GL path
    # LUT uploaded: 256 RGBA texels, alpha opaque, channels within range
    luts = [c for c in gl.calls if c[0] == "texImage2D" and len(c) > 9
            and isinstance(c[-1], list) and len(c[-1]) == 1024]
    assert luts, "LUT texture never uploaded"
    lut = luts[0][-1]
    assert all(lut[4 * k + 3] == 255 for k in range(256))
    assert lut[0] < lut[4 * 255]           # dark → bright ramp (red channel)
    data = [float(v) for v in np.linspace(-3, 3, 32)]
    i.genv.vars["__d"] = data
    n_before = len([c for c in gl.calls if c[0] == "texSubImage2D"])
    for k in range(3):
        i.run("wf.frame(__d);")
        assert wf.get("row") == float((k + 1) % 8)
        assert abs(gl.uniforms["yoffset"] - ((k + 1) % 8) / 8.0) < 1e-9
    uploads = [c for c in gl.calls if c[0] == "texSubImage2D"]
    assert len(uploads) - n_before == 3    # one row per frame
    assert gl.uniforms["u_min"] < gl.uniforms["u_max"]
    draws = [c for c in gl.calls if c[0] == "drawArrays"]
    assert len(draws) == 3


def test_jsmini_language_semantics():
    """The vendored interpreter's core semantics: closures, prototypes,
    switch fall-through, typed arrays, template literals, regex replace."""
    i = Interp()
    i.run("""
      function Counter(start) { this.n = start; }
      Counter.prototype.bump = function (k) { this.n += k; return this.n; };
      const c = new Counter(10);
      c.bump(5);
      const mk = (a) => (b) => a + b;
      const add3 = mk(3);
      let sw = '';
      switch ('B') { case 'A': case 'B': sw += 'ab'; case 'C': sw += 'c';
                     break; default: sw += 'd'; }
      const arr = new Float32Array(4); arr[2] = 7;
      const s = `n=${c.n} f=${(1.5).toFixed(2)}`;
      const trimmed = 'path///'.replace(/\\/+$/, '');
    """)
    assert i.eval("c.n") == 15.0
    assert i.eval("add3(4)") == 7.0
    assert i.eval("sw") == "abc"
    assert list(i.eval("arr")) == [0.0, 0.0, 7.0, 0.0]
    assert i.eval("s") == "n=15 f=1.50"
    assert i.eval("trimmed") == "path"
    assert i.eval("[3,1,2].sort((a,b)=>a-b).join('-')") == "1-2-3"
    assert i.eval("typeof missing") == "undefined"
    assert i.eval("(5 ?? 9)") == 5.0 and i.eval("(null ?? 9)") == 9.0
    # review-locked semantics: delete removes; try/finally re-raises;
    # function replacers run; parseInt takes the maximal numeric prefix
    i.run("const o2 = {a: 1}; delete o2.a;")
    assert i.eval("typeof o2.a") == "undefined"
    i.run("""
      let seen = 'no'; let fin = 0;
      try { try { throw 'E'; } finally { fin = 1; } }
      catch (e) { seen = e; }
    """)
    assert i.eval("seen") == "E" and i.eval("fin") == 1.0
    assert i.eval("'abc'.replace(/b/, m => m.toUpperCase())") == "aBc"
    assert i.eval("parseInt('42px', 10)") == 42.0
    assert i.eval("'a-b'.replace(/(\\w)-(\\w)/, '$2-$1')") == "b-a"


def _mkev(i, **kw):
    ev = JSObject()
    for k, v in kw.items():
        ev.set(k, float(v) if isinstance(v, (int, float)) else v)
    return ev


def test_exec_waterfall_zoom_pan_controls():
    """Frequency zoom (wheel around cursor), drag pan, double-click reset, dB
    mode and live range controls — the prophecy-parity interaction layer,
    executed on both the GL and 2D paths."""
    i = _interp()
    gl = _GLRec()
    cv = _canvas(128, 64)
    cv.getContext = lambda kind, *a: gl if kind == "webgl2" else None
    i.genv.vars["__cv"] = cv
    i.run("const wf = new FSDR.Waterfall(__cv, {history: 8, db: true});")
    wf = i.get("wf")
    assert wf.get("x0") == 0.0 and wf.get("x1") == 1.0
    # wheel-in at the 3/4 point: window shrinks, cursor fraction preserved
    i.call(cv._listeners["wheel"], UNDEF, _mkev(i, clientX=96, deltaY=-1))
    x0, x1 = wf.get("x0"), wf.get("x1")
    assert 0.0 < x0 < x1 < 1.0 and abs((x1 - x0) - 0.8) < 1e-6
    assert abs((0.75 - x0) / (x1 - x0) - 0.75) < 1e-6   # cursor-centred
    # drag pans left within bounds
    i.call(cv._listeners["mousedown"], UNDEF, _mkev(i, clientX=64))
    i.call(cv._listeners["mousemove"], UNDEF, _mkev(i, clientX=32))
    i.call(cv._listeners["mouseup"], UNDEF, _mkev(i))
    x0b = wf.get("x0")
    assert x0b > x0                                     # moved right (pan left)
    assert abs((wf.get("x1") - x0b) - (x1 - x0)) < 1e-9  # width preserved
    # frame uploads dB data and the window uniforms
    i.genv.vars["__d"] = [1.0, 10.0, 100.0, 1000.0] * 8
    i.run("wf.frame(__d);")
    up = [c for c in gl.calls if c[0] == "texSubImage2D"][-1]
    row = list(up[-1])
    assert abs(row[0] - 0.0) < 1e-6 and abs(row[3] - 30.0) < 1e-5  # 10log10
    assert abs(gl.uniforms["u_x0"] - x0b) < 1e-9
    # double-click resets the window
    i.call(cv._listeners["dblclick"], UNDEF, _mkev(i))
    assert wf.get("x0") == 0.0 and wf.get("x1") == 1.0

    # 2D path shares the contract: zoomed window remaps the painted indices
    cv2 = _canvas(64, 32)
    i.genv.vars["__cv2"] = cv2
    i.run("const w2 = new FSDR.Waterfall2D(__cv2, {autorange: false, "
          "min: 0, max: 63});")
    w2 = i.get("w2")
    i.genv.vars["__ramp"] = list(range(64))
    i.run("w2.x0 = 0.5; w2.x1 = 1.0; w2.frame(__ramp);")
    img = cv2.getContext("2d").last_image
    # left edge of the painted row now shows the MIDDLE of the spectrum
    t_left = img.data[0] / 255 / 2            # red = min(1, 2t) inverse for t<0.5
    assert abs(t_left - 32 / 63) < 0.05

    # live controls drive the running sink (prophecy Signal<f32> wiring)
    root = _El("div")
    i.genv.vars["__root"] = root
    i.run("const ctl = new FSDR.WaterfallControls(__root, w2);")
    min_inp = root.children[0].children[0]
    min_inp.value = "-40"
    i.call(min_inp.onchange, UNDEF)
    assert w2.get("min") == -40.0 and w2.get("autorange") is False
    auto_cb = root.children[2].children[0]
    auto_cb.checked = True
    i.call(auto_cb.onchange, UNDEF)
    assert w2.get("autorange") is True
    reset_btn = root.children[3]
    i.run("w2.x0 = 0.25; w2.x1 = 0.75;")
    i.call(reset_btn.onclick, UNDEF)
    assert w2.get("x0") == 0.0 and w2.get("x1") == 1.0


def test_exec_flowgraph_canvas_drag_blocks():
    """Blocks drag with the mouse and the position persists across update()
    (prophecy flowgraph_canvas on_mousedown parity)."""
    desc_py = {
        "id": 0,
        "blocks": [
            {"id": 0, "instance_name": "a", "stream_inputs": [],
             "stream_outputs": ["out"], "message_inputs": [], "blocking": False},
            {"id": 1, "instance_name": "b", "stream_inputs": ["in"],
             "stream_outputs": [], "message_inputs": [], "blocking": False},
        ],
        "stream_edges": [[0, "out", 1, "in"]],
        "message_edges": [],
    }
    i = _interp()
    cv = _canvas(300, 120)
    i.genv.vars["__cv"] = cv
    i.run("const fgc = new FSDR.FlowgraphCanvas(__cv, {});")
    i.run(f"fgc.update(JSON.parse({json_mod.dumps(json_mod.dumps(desc_py))}));")
    fgc = i.get("fgc")
    b0 = fgc.get("boxes")[0]
    ox, oy = b0.get("x"), b0.get("y")
    i.call(cv._listeners["mousedown"], UNDEF, _mkev(i, clientX=ox + 5,
                                                    clientY=oy + 5))
    i.call(cv._listeners["mousemove"], UNDEF, _mkev(i, clientX=ox + 45,
                                                    clientY=oy + 25))
    i.call(cv._listeners["mouseup"], UNDEF, _mkev(i))
    nb = fgc.get("boxes")[0]
    assert abs(nb.get("x") - (ox + 40)) < 1e-6
    assert abs(nb.get("y") - (oy + 20)) < 1e-6
    # the dragged position survives a fresh update()
    i.run(f"fgc.update(JSON.parse({json_mod.dumps(json_mod.dumps(desc_py))}));")
    nb2 = fgc.get("boxes")[0]
    assert abs(nb2.get("x") - (ox + 40)) < 1e-6


def test_exec_waterfall_fallback_is_the_renderer():
    """Without WebGL2, new FSDR.Waterfall() IS the 2D sink (constructor return)
    so zoom state + WaterfallControls operate on the rendering object."""
    i = _interp()
    cv = _canvas(64, 32)                  # getContext('webgl2') -> None
    i.genv.vars["__cv"] = cv
    i.run("const wf = new FSDR.Waterfall(__cv, {min: 1, max: 9});")
    assert i.eval("wf instanceof FSDR.Waterfall2D") is True
    root = _El("div")
    i.genv.vars["__root"] = root
    i.run("const c = new FSDR.WaterfallControls(__root, wf);")
    min_inp = root.children[0].children[0]
    min_inp.value = "3.5"
    i.call(min_inp.onchange, UNDEF)
    assert i.eval("wf.min") == 3.5        # the control reached the renderer
    min_inp.value = "garbage"
    i.call(min_inp.onchange, UNDEF)
    assert i.eval("wf.min") == 3.5        # NaN guard held
    # stuck-drag guard: after a block... (waterfall) pan drag ends on mouseup
    i.call(cv._listeners["mousedown"], UNDEF, _mkev(i, clientX=10))
    i.call(cv._listeners["mouseup"], UNDEF, _mkev(i))
    x0 = i.eval("wf.x0")
    i.call(cv._listeners["mousemove"], UNDEF, _mkev(i, clientX=50))
    assert i.eval("wf.x0") == x0          # no pan without a held button


def test_exec_waterfall2d_zoom_is_retroactive_and_disposable():
    """Zooming repaints the WHOLE 2D history in the new window (GL-path parity),
    and dispose() detaches the global mouseup listener."""
    i = _interp()
    cv = _canvas(32, 8)
    i.genv.vars["__cv"] = cv
    i.run("const wf = new FSDR.Waterfall2D(__cv, {autorange: false, "
          "min: 0, max: 31});")
    ramp = list(range(32))
    i.genv.vars["__r"] = ramp
    for _ in range(4):
        i.run("wf.frame(__r);")
    ctx = cv.getContext("2d")
    n_paints_before = len([o for o in ctx.ops if o[0] == "putImageData"])
    # zoom to the right half, then ONE frame must repaint history rows
    i.run("wf.x0 = 0.5; wf.x1 = 1.0; wf.frame(__r);")
    paints = [o for o in ctx.ops if o[0] == "putImageData"][n_paints_before:]
    assert len(paints) == 5                  # 5 stored rows, all repainted
    img = ctx.last_image
    t_left = img.data[0] / 255 / 2           # red channel inverse for t < 0.5
    assert abs(t_left - 16 / 31) < 0.06      # left edge shows mid-spectrum
    # steady-state zoomed frames go back to incremental painting
    i.run("wf.frame(__r);")
    paints2 = [o for o in ctx.ops if o[0] == "putImageData"][n_paints_before:]
    assert len(paints2) == 6                 # just one more row
    # dispose detaches the pan listener
    assert i.eval("typeof wf.dispose") == "function"
    i.run("wf.dispose();")
    assert "mouseup" not in cv._listeners
    # dB scratch is reused across frames (no per-frame allocation)
    i.run("const wd = new FSDR.Waterfall2D(__cv, {db: true});")
    i.run("wd.frame(__r); const b1 = wd._dbBuf; wd.frame(__r);")
    assert i.eval("b1 === wd._dbBuf") is True


def test_exec_metrics_table_busy_share_against_fused_chain():
    """FSDR.MetricsTable EXECUTES against a live control port serving a FUSED
    chain: the per-block rows render real counters, and the busy-share bars
    derive from the native driver's busy_ns — the FIR row must dominate its
    neighboring copy stage, matching what /metrics/ reports."""
    import json as json_mod
    import time
    import urllib.request

    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Copy, Fir, Head, NullSink, NullSource
    from futuresdr_tpu.config import config
    from futuresdr_tpu.dsp import firdes

    config().ctrlport_enable = True
    old_bind = config().ctrlport_bind
    config().ctrlport_bind = "127.0.0.1:18341"
    running = None
    try:
        fg = Flowgraph()
        fg.connect(NullSource(np.float32), Head(np.float32, 600_000_000),
                   Fir(firdes.lowpass(0.2, 64).astype(np.float32)),
                   Copy(np.float32), NullSink(np.float32))
        rt = Runtime()
        running = rt.start(fg)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                urllib.request.urlopen(
                    "http://127.0.0.1:18341/api/fg/0/", timeout=2).read()
                break
            except Exception:
                time.sleep(0.1)
        else:
            raise RuntimeError("control port never became ready")
        time.sleep(0.3)                       # let busy_ns accumulate

        def fetch(url, opts=UNDEF):
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            resp = JSObject()
            resp.set("json", lambda: i.eval(
                f"JSON.parse({json_mod.dumps(body)})"))
            return resp

        i = _interp(fetch=fetch)
        i.run("const h = new FSDR.Handle('http://127.0.0.1:18341/');")
        tbl = _El("table")
        tbl.rows.append(_El("tr"))            # header row
        i.genv.vars["__tbl"] = tbl
        i.run("new FSDR.MetricsTable(__tbl).update(h.metrics(0));")
        assert len(tbl.rows) == 1 + 5         # one row per block
        shares = {}
        for r in tbl.rows[1:]:
            cells = [c for c in r.children]
            name = cells[0].textContent
            bar_cell = cells[4]
            if bar_cell.children:             # busy bar rendered
                width = bar_cell.children[0].style.get("width")
                shares[name] = int(str(width).rstrip("%"))
        assert shares, "no busy bars rendered"
        fir_share = next(v for k, v in shares.items() if "Fir" in k)
        copy_share = next(v for k, v in shares.items() if "Copy_" in k
                          or k.startswith("Copy"))
        assert fir_share > copy_share, shares
        assert fir_share > 30, shares         # the FIR owns the chain's time
    finally:
        if running is not None:
            running.stop_sync()
        config().ctrlport_enable = False
        config().ctrlport_bind = old_bind


def test_exec_doctor_panel_renders_flight_record_markdown():
    """FSDR.DoctorPanel against the REAL doctor endpoint
    (GET /api/fg/{fg}/doctor/?md=1): the fetched flight-record markdown
    renders into headings + preformatted body — the ROADMAP 'wire the doctor
    endpoint into the browser GUI' follow-up, executed."""
    import time
    import urllib.request

    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import MessageSink, MessageSource
    from futuresdr_tpu.config import config
    from futuresdr_tpu.types import Pmt as PyPmt

    config().ctrlport_enable = True
    old_bind = config().ctrlport_bind
    config().ctrlport_bind = "127.0.0.1:18343"
    running = None
    try:
        fg = Flowgraph()
        src = MessageSource(PyPmt.string("x"), interval=0.05, count=400)
        snk = MessageSink()
        fg.connect_message(src, "out", snk, "in")
        rt = Runtime()
        running = rt.start(fg)
        deadline = time.time() + 15
        while time.time() < deadline:
            try:
                urllib.request.urlopen(
                    "http://127.0.0.1:18343/api/fg/0/", timeout=2).read()
                break
            except Exception:
                time.sleep(0.1)
        else:
            raise RuntimeError("control port never became ready")

        fetched_urls = []

        def fetch(url, opts=UNDEF):
            fetched_urls.append(url)
            body = urllib.request.urlopen(url, timeout=5).read().decode()
            resp = JSObject()
            resp.set("text", lambda: body)
            resp.set("json", lambda: i.eval(
                f"JSON.parse({json_mod.dumps(body)})"))
            return resp

        i = _interp(fetch=fetch)
        root = _El("div")
        i.genv.vars["__root"] = root
        i.run("const h = new FSDR.Handle('http://127.0.0.1:18343/');"
              "const dp = new FSDR.DoctorPanel(__root, h, 0);"
              "dp.refresh();")
        assert any(u.endswith("/api/fg/0/doctor/?md=1") for u in fetched_urls)
        # panel scaffold: refresh button + status + body
        assert root.children[0].tag == "button"
        body = root.children[2]
        tags = [c.tag for c in body.children]
        assert "h3" in tags and "pre" in tags, tags     # headings + body
        text = "".join(c.textContent for c in body.children)
        assert "flight record" in text.lower() or "doctor" in text.lower() \
            or "watchdog" in text.lower(), text[:200]
        # error path: unreachable endpoint reports, never throws (ValueError:
        # one of the Python exception kinds jsmini's try/catch translates)
        def bad_fetch(url, opts=UNDEF):
            raise ValueError("down")
        i2 = _interp(fetch=bad_fetch)
        root2 = _El("div")
        i2.genv.vars["__root"] = root2
        i2.run("const h = new FSDR.Handle('http://127.0.0.1:1/');"
               "const dp = new FSDR.DoctorPanel(__root, h, 0);"
               "dp.refresh();")
        assert "unavailable" in root2.children[1].textContent
    finally:
        if running is not None:
            running.stop_sync()
        config().ctrlport_enable = False
        config().ctrlport_bind = old_bind
