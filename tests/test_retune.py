"""Runtime retune/tap-swap on the device path (VERDICT r2 item 5).

Carry-resident parameters (FIR spectra/taps, rotator increment) are swapped by
host-side carry surgery between dispatches — no recompile, frames in flight
keep the old values. Reference workflow: the fm-receiver's retune-while-running
(``examples/fm-receiver/src/main.rs:83-155``), here reaching the DEVICE segment.
"""

import numpy as np
import pytest

from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.ops import (Pipeline, fir_stage, mag2_stage, rotator_stage)


def _stream(pipe, fn, carry, x, frame):
    outs = []
    for i in range(0, len(x), frame):
        carry, y = fn(carry, x[i:i + frame])
        outs.append(np.asarray(y))
    return carry, np.concatenate(outs)


@pytest.mark.parametrize("impl", ["os", "pallas", "poly"])
def test_fir_tap_swap_streaming(impl):
    """Swap taps mid-stream on each FIR implementation; after the nt-1 sample
    transient the output exactly matches a filter built with the new taps."""
    rng = np.random.default_rng(0)
    nt, frame, decim = 24, 4096, (2 if impl == "poly" else 1)
    t1 = firdes.kaiser_lowpass(0.1, 0.05)[:nt].astype(np.float32)
    t2 = -firdes.kaiser_lowpass(0.22, 0.05)[:nt].astype(np.float32)
    x = rng.standard_normal(8 * frame).astype(np.float32)

    st = fir_stage(t1, decim=decim, impl=impl)
    pipe = Pipeline([st], np.float32, optimize=False)
    fn = pipe.fn()
    carry = pipe.init_carry()

    half = 4 * frame
    carry, y_a = _stream(pipe, fn, carry, x[:half], frame)
    carry = pipe.update_stage(carry, "fir", taps=t2)
    carry, y_b = _stream(pipe, fn, carry, x[half:], frame)

    ref1 = np.convolve(x, t1)[:half][::decim]
    np.testing.assert_allclose(y_a, ref1.astype(np.float32), atol=2e-3)

    # post-swap steady state: filter t2 continuing with the REAL history of x
    ref2_full = np.convolve(x, t2)[half:half + half]
    ref2 = ref2_full[::decim] if decim > 1 else ref2_full
    settle = nt  # transient: old history filtered by new taps
    np.testing.assert_allclose(y_b[settle:], ref2.astype(np.float32)[settle:],
                               atol=2e-3)
    # and it genuinely changed the response
    assert np.abs(y_b[settle:] - (np.convolve(x, t1)[half:half + half][::decim]
                                  ).astype(np.float32)[settle:]).max() > 1e-2


def test_fir_tap_swap_rejects_length_change():
    st = fir_stage(np.ones(16, np.float32))
    pipe = Pipeline([st], np.float32, optimize=False)
    carry = pipe.init_carry()
    with pytest.raises(ValueError, match="tap count"):
        pipe.update_stage(carry, 0, taps=np.ones(17, np.float32))
    with pytest.raises(KeyError):
        pipe.update_stage(carry, "nope", taps=np.ones(16, np.float32))


def test_fir_tap_swap_rejects_complex_on_real_built():
    """Realness is baked at trace time (pallas / half-spectrum branches): a
    complex swap on a real-built stage must be rejected, not silently truncated."""
    for build in (lambda t: fir_stage(t),
                  lambda t: fir_stage(t, decim=2, impl="poly")):
        st = build(np.ones(16, np.float32))
        pipe = Pipeline([st], np.complex64, optimize=False)
        carry = pipe.init_carry()
        with pytest.raises(ValueError, match="complex"):
            pipe.update_stage(carry, 0, taps=np.ones(16, np.complex64) * 1j)


def test_ctrl_port_accepts_plain_list_taps():
    """Pmt.map wraps Python-list elements as Pmt (VecPmt); the ctrl handler must
    unwrap them — a retune with taps=[...] as a plain list has to work."""
    import asyncio
    from futuresdr_tpu.tpu import TpuKernel
    from futuresdr_tpu.types import Pmt

    taps = firdes.kaiser_lowpass(0.1, 0.05)[:16].astype(np.float32)
    tk = TpuKernel([fir_stage(taps, name="f")], np.float32, frame_size=8192)

    async def drive():
        await tk.init(None, None)
        new = (-taps).tolist()                       # plain Python list of floats
        r = await tk.ctrl_handler(None, None, None,
                                  Pmt.map({"stage": "f", "taps": new}))
        assert r == Pmt.ok(), "list taps rejected"
        # carried spectrum actually changed sign
        Hc = np.asarray(tk._carry[0][0])
        ref = np.fft.rfft(np.concatenate([-taps, np.zeros(tk.pipeline.stages[0].lti[2] - 16)]))
        np.testing.assert_allclose(Hc, ref.astype(np.complex64), atol=1e-5)

    asyncio.run(drive())


def test_rotator_retune_phase_continuous():
    """Retuning the rotator keeps phase continuity — no discontinuity click."""
    fs, frame = 1e6, 4096
    inc1, inc2 = 0.1, -0.3
    x = np.ones(4 * frame, np.complex64)
    st = rotator_stage(inc1)
    pipe = Pipeline([st], np.complex64, optimize=False)
    fn, carry = pipe.fn(), pipe.init_carry()
    carry, y_a = _stream(pipe, fn, carry, x[:2 * frame], frame)
    carry = pipe.update_stage(carry, "rotator", phase_inc=inc2)
    carry, y_b = _stream(pipe, fn, carry, x[2 * frame:], frame)
    y = np.concatenate([y_a, y_b])
    # per-sample phase increments: inc1 for the first half, inc2 after — and the
    # sample AT the boundary continues from the accumulated phase (no reset)
    dphi = np.angle(y[1:] * np.conj(y[:-1]))
    np.testing.assert_allclose(dphi[:2 * frame - 1], inc1, atol=1e-3)
    np.testing.assert_allclose(dphi[2 * frame:], inc2, atol=1e-3)
    # the step INTO the first new-segment sample continues from the accumulated
    # phase (old increment) — that IS the continuity property: no reset, no click
    assert abs(dphi[2 * frame - 1] - inc1) < 1e-3


def test_tpu_kernel_ctrl_port_retune():
    """End-to-end FM-style retune through a running TpuKernel: two stations, the
    device chain's rotator+lowpass selects one; a ctrl message switches to the
    other while frames are in flight."""
    import time
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Throttle, VectorSink, VectorSource
    from futuresdr_tpu.tpu import TpuKernel
    from futuresdr_tpu.types import Pmt

    fs = 256_000.0
    f_a, f_b = 60_000.0, -90_000.0           # two "stations", distinct amplitudes
    amp_b = 0.25                             # |.|^2: A -> ~1.0, B -> ~0.0625
    n = 1 << 18
    t = np.arange(n) / fs
    x = (np.exp(2j * np.pi * f_a * t) +
         amp_b * np.exp(2j * np.pi * f_b * t)).astype(np.complex64)

    taps = firdes.kaiser_lowpass(0.05, 0.02).astype(np.float32)
    stages = [rotator_stage(-2 * np.pi * f_a / fs, name="tuner"),
              fir_stage(taps, name="chan"),
              mag2_stage()]

    fg = Flowgraph()
    src = VectorSource(x)
    # pace the stream so the mid-flight retune lands before the tail is
    # processed — without this, a loaded machine can drain all frames first
    thr = Throttle(np.complex64, rate=250_000.0)
    tk = TpuKernel(stages, np.complex64, frame_size=16384, frames_in_flight=2)
    snk = VectorSink(np.float32)
    fg.connect(src, thr, tk, snk)
    rt = Runtime()
    running = rt.start(fg)

    # wait until a good chunk has streamed with station A selected
    t0 = time.perf_counter()
    while len(snk.items()) < n // 4 and time.perf_counter() - t0 < 30:
        time.sleep(0.02)
    n_before = len(snk.items())
    assert n_before >= n // 4, n_before

    # retune to station B through the ctrl port, mid-flight
    r = rt.scheduler.run_coro_sync(running.handle.call(
        tk, "ctrl", Pmt.map({"stage": "tuner",
                             "phase_inc": -2 * np.pi * f_b / fs})))
    assert r == Pmt.ok()
    running.wait_sync()
    got = snk.items()
    assert len(got) == n

    # |lowpass(shifted)|^2: station A in band → ~1.0; station B → ~0.0625.
    # The head must show A, the tail must show B — frames in flight at retune
    # time keep A, so only judge well clear of the switchover region.
    head = got[len(taps) * 2:max(n_before - 4 * 16384, len(taps) * 4)]
    tail = got[-(n - n_before) // 4:]
    assert np.median(head) > 0.5, "station A not selected before retune"
    assert np.median(tail) < 0.2, "retune did not take effect on the device path"
    assert np.median(tail) > 0.01, "station B vanished (filter broken post-swap)"


def test_ctrl_port_rejects_garbage():
    from futuresdr_tpu.tpu import TpuKernel
    from futuresdr_tpu.types import Pmt
    import asyncio

    tk = TpuKernel([rotator_stage(0.1, name="r")], np.complex64,
                   frame_size=4096)

    async def call(p):
        return await tk.ctrl_handler(None, None, None, p)

    # unknown stage name → InvalidValue, not a crash (queued pre-init path)
    assert asyncio.run(call(Pmt.f64(1.0))) == Pmt.invalid_value()


def test_tpu_stage_ctrl_port_retune():
    """The frame-plane TpuStage exposes the same ctrl retune contract: a tap
    swap lands mid-stream through the inplace pipeline."""
    import time
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource, Throttle
    from futuresdr_tpu.tpu import TpuH2D, TpuStage, TpuD2H
    from futuresdr_tpu.types import Pmt

    nt, frame = 24, 16384
    t1 = firdes.kaiser_lowpass(0.1, 0.05)[:nt].astype(np.float32)
    t2 = -firdes.kaiser_lowpass(0.22, 0.05)[:nt].astype(np.float32)
    n = 16 * frame
    rng = np.random.default_rng(2)
    x = rng.standard_normal(n).astype(np.float32)

    fg = Flowgraph()
    src = VectorSource(x)
    thr = Throttle(np.float32, rate=250_000.0)     # pace so the retune lands mid-run
    h2d = TpuH2D(np.float32, frame_size=frame)
    st = TpuStage([fir_stage(t1, name="f")], np.float32)
    d2h = TpuD2H(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, thr, h2d, st, d2h, snk)
    rt = Runtime()
    running = rt.start(fg)
    t0 = time.perf_counter()
    while len(snk.items()) < n // 4 and time.perf_counter() - t0 < 30:
        time.sleep(0.01)
    n_before = len(snk.items())
    assert n_before >= n // 4
    r = running.handle.call_sync(st, "ctrl",
                                 Pmt.map({"stage": "f", "taps": t2.tolist()}))
    assert r == Pmt.ok()
    running.wait_sync()
    got = snk.items()
    assert len(got) == n
    # well before the switch: filter t1; well after: filter t2
    ref1 = np.convolve(x, t1)[:n].astype(np.float32)
    ref2 = np.convolve(x, t2)[:n].astype(np.float32)
    head = slice(nt, max(n_before - 2 * frame, nt + 1))
    np.testing.assert_allclose(got[head], ref1[head], atol=2e-3)
    tail = slice(n - 2 * frame, n)
    np.testing.assert_allclose(got[tail], ref2[tail], atol=2e-3)


def test_tpu_stage_ctrl_before_first_frame():
    """A retune posted before the first frame reaches TpuStage (whose carry
    compiles lazily) must be QUEUED and applied, not silently dropped — the
    whole output then reflects the swapped taps."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.tpu import TpuH2D, TpuStage, TpuD2H
    from futuresdr_tpu.types import Pmt

    nt, frame = 16, 16384
    t1 = firdes.kaiser_lowpass(0.1, 0.05)[:nt].astype(np.float32)
    t2 = -firdes.kaiser_lowpass(0.22, 0.05)[:nt].astype(np.float32)
    n = 4 * frame
    x = np.random.default_rng(3).standard_normal(n).astype(np.float32)

    st = TpuStage([fir_stage(t1, name="f")], np.float32)
    # handler fires before any frame: carry is None -> queued
    import asyncio
    r = asyncio.run(st.ctrl_handler(None, None, None,
                                    Pmt.map({"stage": "f", "taps": t2.tolist()})))
    assert r == Pmt.ok()
    assert st._pending_ctrl, "early ctrl was not queued"

    fg = Flowgraph()
    fg.connect(VectorSource(x), TpuH2D(np.float32, frame_size=frame), st,
               TpuD2H(np.float32), (snk := VectorSink(np.float32)))
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == n
    ref2 = np.convolve(x, t2)[:n].astype(np.float32)
    np.testing.assert_allclose(got[nt:], ref2[nt:], atol=2e-3)


def test_tpu_stage_early_ctrl_rejects_bad_stage():
    """An early (pre-carry) ctrl with a bad stage name must reply InvalidValue
    immediately — not ok-then-silently-dropped at first-frame compile."""
    import asyncio
    from futuresdr_tpu.tpu import TpuStage
    from futuresdr_tpu.types import Pmt

    st = TpuStage([fir_stage(np.ones(8, np.float32), name="f")], np.float32)
    r = asyncio.run(st.ctrl_handler(None, None, None,
                                    Pmt.map({"stage": "nope", "taps": [1.0] * 8})))
    assert r == Pmt.invalid_value()
    assert not st._pending_ctrl


def test_xlating_fir_stage_matches_unfolded_chain():
    """The folded tuner (complex taps + decimated-rate residual rotator,
    `xlating_fir_stage`) must match rotator → decimating FIR within f32
    phase-accumulation noise, across frames (carry) and through a retune."""
    import jax

    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, rotator_stage, xlating_fir_stage
    from futuresdr_tpu.ops.stages import Pipeline

    theta = -2 * np.pi * 100e3 / 1e6
    taps = firdes.lowpass(0.5 / 16 * 0.8, 128).astype(np.float32)
    rng = np.random.default_rng(5)
    n = 1 << 15
    frames = [(rng.standard_normal(n) + 1j * rng.standard_normal(n))
              .astype(np.complex64) for _ in range(3)]

    pA = Pipeline([rotator_stage(theta, name="tuner"),
                   fir_stage(taps, decim=16, fft_len=4096, name="chan")],
                  np.complex64)
    pB = Pipeline([xlating_fir_stage(taps, theta, 16, name="tuner")],
                  np.complex64)
    fa, fb = jax.jit(pA.fn()), jax.jit(pB.fn())
    ca, cb = pA.init_carry(), pB.init_carry()
    for x in frames:
        ca, ya = fa(ca, x)
        cb, yb = fb(cb, x)
        # tolerance dominated by the UNFOLDED path's full-rate f32 phase ramp
        np.testing.assert_allclose(np.asarray(yb), np.asarray(ya), atol=5e-3)

    theta2 = -2 * np.pi * 250e3 / 1e6
    ca = pA.update_stage(ca, "tuner", phase_inc=theta2)
    cb = pB.update_stage(cb, "tuner", phase_inc=theta2)
    ca, ya = fa(ca, frames[0])
    cb, yb = fb(cb, frames[0])
    np.testing.assert_allclose(np.asarray(yb)[32:], np.asarray(ya)[32:],
                               atol=8e-3)
    # base-lowpass swap keeps the translation frequency
    t2 = firdes.lowpass(0.5 / 16 * 0.5, 128).astype(np.float32)
    cb = pB.update_stage(cb, "tuner", taps=t2)
    ca2 = pA.update_stage(pA.init_carry(), "tuner", phase_inc=theta2)
    pA2 = Pipeline([rotator_stage(theta2, name="tuner"),
                    fir_stage(t2, decim=16, fft_len=4096, name="chan")],
                   np.complex64)
    # run both fresh with the new taps at theta2; ignore carried-history transient
    cb2 = pB.update_stage(pB.init_carry(), "tuner", phase_inc=theta2)
    cb2 = pB.update_stage(cb2, "tuner", taps=t2)
    fa2 = jax.jit(pA2.fn())
    ca2, ya = fa2(pA2.init_carry(), frames[1])
    cb2, yb = fb(cb2, frames[1])
    np.testing.assert_allclose(np.asarray(yb), np.asarray(ya), atol=5e-3)
    import pytest
    with pytest.raises(ValueError, match="REAL base"):
        pB.update_stage(cb, "tuner", taps=t2.astype(np.complex64) * 1j)
    with pytest.raises(ValueError, match="tap count"):
        pB.update_stage(cb, "tuner", taps=t2[:64])


def test_xlating_taps_update_preserves_exact_theta():
    """Round-4 advisory: update(taps=...) without phase_inc must rebuild the
    complex weights with the EXACT translation theta, not a value re-derived
    from the carried float32 increment — the weights must be bit-identical to
    a fresh stage built at the same theta."""
    import jax
    import numpy as np

    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import xlating_fir_stage
    from futuresdr_tpu.ops.stages import Pipeline

    theta = -2 * np.pi * 0.1234567891234  # poorly representable in float32
    taps = firdes.lowpass(0.1, 64).astype(np.float32)
    t2 = firdes.lowpass(0.05, 64).astype(np.float32)

    pipe = Pipeline([xlating_fir_stage(taps, theta, 4, name="x")], np.complex64)
    c = pipe.init_carry()
    c = pipe.update_stage(c, "x", taps=t2)
    fresh = Pipeline([xlating_fir_stage(t2, theta, 4, name="x")],
                     np.complex64).init_carry()
    got_W = np.asarray(jax.device_get(c[0][0]))
    want_W = np.asarray(jax.device_get(fresh[0][0]))
    np.testing.assert_array_equal(got_W, want_W)


# ---------------------------------------------------------------------------
# replay-aware retunes (ISSUE 11 satellite, docs/robustness.md)
# ---------------------------------------------------------------------------

def _mocked_kernel(ck=10):
    """A stateful TpuKernel driven by the Mocker: sparse checkpoint cadence
    so a recovery's restore point predates recent dispatch groups — the
    regime where a logged retune must be RE-APPLIED during replay."""
    from futuresdr_tpu.tpu import TpuKernel
    taps = firdes.lowpass(0.2, 31).astype(np.float32)
    return TpuKernel([fir_stage(taps, fft_len=256, name="f"),
                      rotator_stage(0.05, name="rot")],
                     np.complex64, frame_size=2048, frames_in_flight=2,
                     checkpoint_every=ck)


def _retune_data(n_frames=9):
    rng = np.random.default_rng(21)
    n = 2048 * n_frames
    return (rng.standard_normal(n) + 1j * rng.standard_normal(n)) \
        .astype(np.complex64)


def _drive(m, data, lo, hi):
    """Feed frames [lo, hi) through the mocked kernel and drain."""
    m.input("in", data[lo * 2048:hi * 2048])
    m.run()


def test_replayed_retune_lands_on_exactly_the_original_frame():
    """Acceptance (replay-aware ctrl retunes): with a sparse checkpoint
    cadence, a recovery whose restore point PRECEDES a logged retune
    re-applies the carry surgery at exactly its original dispatch boundary
    during replay — the full output is BIT-IDENTICAL to the unfailed run
    with the same retune timing. (Before this PR the restored carry simply
    lost the surgery: the replayed and subsequent frames recomputed with
    the OLD parameters.)"""
    import asyncio

    from futuresdr_tpu import Mocker
    from futuresdr_tpu.types import Pmt
    data = _retune_data()
    pmt = Pmt.map({"stage": "rot", "phase_inc": -0.11})

    # unfailed reference: 3 frames, retune, 6 more frames
    mk_ref = _mocked_kernel()
    ref = Mocker(mk_ref)
    ref.init_output("out", len(data) * 2)
    ref.init()
    _drive(ref, data, 0, 3)
    assert ref.post("ctrl", pmt) == Pmt.ok()
    _drive(ref, data, 3, 9)
    expected = ref.output("out").copy()

    # faulted run: same timing, then a recovery AFTER the retune whose
    # restore point (the fresh-init sentinel — no commit yet at cadence 10)
    # precedes it: every group replays, the retune must re-land at group 3
    mk = _mocked_kernel()
    m = Mocker(mk)
    m.init_output("out", len(data) * 2)
    m.init()
    _drive(m, data, 0, 3)
    assert m.post("ctrl", pmt) == Pmt.ok()
    _drive(m, data, 3, 6)
    assert mk._retune_log and mk._retune_log[0][0] == 3
    assert asyncio.run(mk.recover(RuntimeError("injected test fault")))
    assert mk._replay_retunes and mk._replay_retunes[0][0] == 3
    _drive(m, data, 6, 9)
    got = m.output("out")
    np.testing.assert_array_equal(got, expected)
    assert not mk._replay_retunes        # consumed at its boundary


def test_retune_during_replay_rejects_bad_params_at_call_site():
    """A retune landing mid-replay with a valid stage but invalid params
    must reject at the call site (InvalidValue), exactly like the same
    retune outside a replay window — NOT return ok and then silently drop
    at the deferred boundary (the deferral branch validates the FULL
    surgery against the current carry, discarding the result)."""
    import asyncio

    from futuresdr_tpu import Mocker
    from futuresdr_tpu.types import Pmt

    data = _retune_data(6)
    mk = _mocked_kernel()
    m = Mocker(mk)
    m.init_output("out", len(data) * 2)
    m.init()
    _drive(m, data, 0, 3)
    assert asyncio.run(mk.recover(RuntimeError("injected test fault")))
    assert mk._replay_queue              # replay window armed, not drained
    assert m.post("ctrl", Pmt.map({"stage": "rot", "bogus_param": 1.0})) \
        == Pmt.invalid_value()
    assert not mk._replay_retunes        # nothing queued for the boundary


def test_retune_with_staged_backlog_logs_the_oldest_unlaunched_group():
    """A retune arriving while dispatch groups are STAGED but not yet
    launched (the credit budget holding them back) mutates the carry those
    groups will dispatch with — so the replay log must record the OLDEST
    unlaunched group's boundary, not the next group to be staged. Logging
    ``self._seq`` there would make a later replay re-dispatch the staged
    groups with the pre-retune parameters."""
    import asyncio

    mk = _mocked_kernel()
    asyncio.run(mk.init(None, None))

    # drained kernel: the boundary IS the next staged seq
    mk._seq = 4
    mk.apply_retune("rot", {"phase_inc": -0.07})
    assert mk._retune_log[-1][0] == 4

    # staged backlog: groups 5 and 6 are staged awaiting credits — the new
    # parameters are visible from group 5 onward
    mk._seq = 7
    mk._staged.append((None, [], 5, False))
    mk._staged.append((None, [], 6, False))
    try:
        mk.apply_retune("rot", {"phase_inc": 0.19})
    finally:
        mk._staged.clear()
    assert mk._retune_log[-1][0] == 5


def test_retune_during_replay_defers_to_post_window_boundary(caplog):
    """A NEW retune arriving while the replay window is still in flight is
    deferred to the post-replay boundary (structured warning upgraded from
    the PR 8 divergence note): replayed frames keep their original
    parameters and the final output is bit-identical to an unfailed run
    where the retune lands at that same frame."""
    import asyncio
    import logging

    from futuresdr_tpu import Mocker
    from futuresdr_tpu.types import Pmt
    data = _retune_data()
    pmt = Pmt.map({"stage": "rot", "phase_inc": 0.21})

    # unfailed reference: retune lands after frame 6
    mk_ref = _mocked_kernel()
    ref = Mocker(mk_ref)
    ref.init_output("out", len(data) * 2)
    ref.init()
    _drive(ref, data, 0, 6)
    assert ref.post("ctrl", pmt) == Pmt.ok()
    _drive(ref, data, 6, 9)
    expected = ref.output("out").copy()

    mk = _mocked_kernel()
    m = Mocker(mk)
    m.init_output("out", len(data) * 2)
    m.init()
    _drive(m, data, 0, 6)
    assert asyncio.run(mk.recover(RuntimeError("injected test fault")))
    assert mk._replay_queue              # replay window armed, not drained
    with caplog.at_level(logging.WARNING, logger="futuresdr_tpu.tpu.kernel"):
        assert m.post("ctrl", pmt) == Pmt.ok()
    msgs = [r.getMessage() for r in caplog.records
            if "replay window" in r.getMessage()]
    assert msgs and "deferred to the post-replay boundary" in msgs[0]
    assert mk._replay_retunes and mk._replay_retunes[0][0] == 6
    _drive(m, data, 6, 9)
    got = m.output("out")
    np.testing.assert_array_equal(got, expected)
