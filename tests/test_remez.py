"""Native Remez exchange vs scipy's (same Janovetz lineage) — cross-validation."""

import numpy as np
import pytest
from scipy import signal as sps

from futuresdr_tpu.dsp.remez import remez_exchange


@pytest.mark.parametrize("n_taps,bands,des", [
    (63, [0, 0.1, 0.15, 0.5], [1, 0]),            # type I lowpass
    (64, [0, 0.1, 0.15, 0.5], [1, 0]),            # type II lowpass
    (65, [0, 0.2, 0.25, 0.5], [1, 0]),
    (81, [0, 0.08, 0.12, 0.2, 0.24, 0.5], [0, 1, 0]),   # bandpass
    (55, [0, 0.15, 0.2, 0.5], [0, 1]),            # highpass-ish
])
def test_matches_scipy_response(n_taps, bands, des):
    mine = remez_exchange(n_taps, bands, des)
    ref = sps.remez(n_taps, np.asarray(bands), des, fs=1.0)
    _, hm = sps.freqz(mine, fs=1.0, worN=2048)
    _, hr = sps.freqz(ref, fs=1.0, worN=2048)
    assert np.max(np.abs(np.abs(hm) - np.abs(hr))) < 2e-3


def test_weighted_design():
    mine = remez_exchange(63, [0, 0.1, 0.15, 0.5], [1, 0], weight=[1, 10])
    _, h = sps.freqz(mine, fs=1.0, worN=2048)
    w = np.linspace(0, 0.5, 2048)
    stop = np.abs(h)[w > 0.16]
    passband = np.abs(h)[w < 0.09]
    # 10x stopband weight → stopband ripple ~10x smaller than passband ripple
    assert stop.max() < 0.3 * np.abs(passband - 1).max() + 1e-3


def test_linear_phase_symmetry():
    h = remez_exchange(63, [0, 0.1, 0.15, 0.5], [1, 0])
    np.testing.assert_allclose(h, h[::-1], atol=1e-10)
