"""Native Remez exchange vs scipy's (same Parks-McClellan lineage) — cross-validation.

Two grades of check:
- *response match*: in-band |H| agreement with scipy's design (transition bands are
  don't-care regions where two optimal designs may legitimately differ);
- *optimality*: the true max weighted ripple of our design, measured on a 200k-point
  dense grid, matches scipy's within 1% at the canonical grid density and strictly
  beats it at density 64 — the equiripple property itself, which is the actual spec
  of the reference's Janovetz port (crates/futuredsp/src/firdes/remez_impl.rs:713).
"""

import numpy as np
import pytest
from scipy import signal as sps

from futuresdr_tpu.dsp.remez import remez_exchange

# (name, n_taps, bands, desired, weights, filter_type) — all four linear-phase types
DESIGN_MATRIX = [
    ("lowpass_odd", 63, [(0, 0.2), (0.25, 0.5)], [1, 0], [1, 1], "bandpass"),
    ("lowpass_even", 64, [(0, 0.2), (0.25, 0.5)], [1, 0], [1, 1], "bandpass"),
    ("highpass_odd", 61, [(0, 0.18), (0.24, 0.5)], [0, 1], [1, 1], "bandpass"),
    ("bandpass_odd", 81, [(0, 0.08), (0.12, 0.22), (0.27, 0.5)], [0, 1, 0], [1, 1, 1], "bandpass"),
    ("bandpass_wts", 75, [(0, 0.1), (0.15, 0.3), (0.35, 0.5)], [0, 1, 0], [10, 1, 10], "bandpass"),
    ("multiband", 101, [(0, 0.06), (0.1, 0.16), (0.2, 0.28), (0.33, 0.5)], [1, 0, 1, 0], [1, 1, 1, 1], "bandpass"),
    ("hilbert_odd", 63, [(0.05, 0.45)], [1], [1], "hilbert"),
    ("hilbert_even", 64, [(0.05, 0.45)], [1], [1], "hilbert"),
    ("diff_odd", 45, [(0.02, 0.45)], [2], [1], "differentiator"),
    ("diff_even", 46, [(0.02, 0.48)], [1], [1], "differentiator"),
]


def _true_ripple(h, bands, des, wts, ftype, worN=200001):
    """Max weighted in-band deviation from the ideal response, densely sampled."""
    w, H = sps.freqz(h, worN=worN, fs=1.0)
    A = np.abs(H)
    worst = 0.0
    for (f0, f1), d, wt in zip(bands, des, wts):
        m = (w >= f0) & (w <= f1)
        if ftype == "differentiator":
            D = d * w[m]
            W = np.where(np.abs(D) > 1e-4, wt / np.maximum(np.abs(D), 1e-12), wt)
        else:
            D = np.full(m.sum(), d)
            W = np.full(m.sum(), wt)
        worst = max(worst, (W * np.abs(A[m] - D)).max())
    return worst


def _inband_err(h1, h2, bands, worN=8192):
    w, H1 = sps.freqz(h1, worN=worN, fs=1.0)
    _, H2 = sps.freqz(h2, worN=worN, fs=1.0)
    mask = np.zeros(len(w), bool)
    for f0, f1 in bands:
        mask |= (w >= f0) & (w <= f1)
    return np.abs(np.abs(H1) - np.abs(H2))[mask].max()


@pytest.mark.parametrize("name,nt,bands,des,wts,ftype", DESIGN_MATRIX,
                         ids=[c[0] for c in DESIGN_MATRIX])
def test_design_matrix_vs_scipy(name, nt, bands, des, wts, ftype):
    flat = [e for b in bands for e in b]
    hs = sps.remez(nt, flat, des, weight=wts, fs=1.0, type=ftype)
    hm = remez_exchange(nt, bands, des, weight=wts, filter_type=ftype)

    # in-band responses agree closely (both are grid-density-16 optima)
    assert _inband_err(hs, hm, bands) < 2e-5

    # equiripple quality within 5% of scipy at matched density (two different
    # discrete grids → two slightly different optima; the strict claim is below)
    rs = _true_ripple(hs, bands, des, wts, ftype)
    rm = _true_ripple(hm, bands, des, wts, ftype)
    assert rm <= rs * 1.05

    # at density 64 our optimum strictly beats scipy's density-16 design
    hm64 = remez_exchange(nt, bands, des, weight=wts, filter_type=ftype,
                          grid_density=64)
    rm64 = _true_ripple(hm64, bands, des, wts, ftype)
    assert rm64 <= rs * (1 + 1e-6)


@pytest.mark.parametrize("n_taps,bands,des", [
    (63, [0, 0.1, 0.15, 0.5], [1, 0]),            # type I lowpass
    (64, [0, 0.1, 0.15, 0.5], [1, 0]),            # type II lowpass
    (65, [0, 0.2, 0.25, 0.5], [1, 0]),
    (81, [0, 0.08, 0.12, 0.2, 0.24, 0.5], [0, 1, 0]),   # bandpass
    (55, [0, 0.15, 0.2, 0.5], [0, 1]),            # highpass-ish
])
def test_matches_scipy_response(n_taps, bands, des):
    mine = remez_exchange(n_taps, bands, des)
    ref = sps.remez(n_taps, np.asarray(bands), des, fs=1.0)
    bl = np.asarray(bands).reshape(-1, 2)
    # narrow-transition designs: the |H| gap is floored by scipy's own grid
    # discretization error (~1e-4); optimality is asserted in the matrix test
    assert _inband_err(mine, ref, bl) < 2e-4


def test_weighted_design():
    mine = remez_exchange(63, [0, 0.1, 0.15, 0.5], [1, 0], weight=[1, 10])
    _, h = sps.freqz(mine, fs=1.0, worN=2048)
    w = np.linspace(0, 0.5, 2048)
    stop = np.abs(h)[w > 0.16]
    passband = np.abs(h)[w < 0.09]
    # 10x stopband weight → stopband ripple ~10x smaller than passband ripple
    assert stop.max() < 0.3 * np.abs(passband - 1).max() + 1e-3


def test_linear_phase_symmetry():
    h = remez_exchange(63, [0, 0.1, 0.15, 0.5], [1, 0])
    np.testing.assert_allclose(h, h[::-1], atol=1e-10)


def test_antisymmetric_types():
    h3 = remez_exchange(63, [(0.05, 0.45)], [1], filter_type="hilbert")
    np.testing.assert_allclose(h3, -h3[::-1], atol=1e-10)
    h4 = remez_exchange(64, [(0.05, 0.45)], [1], filter_type="hilbert")
    np.testing.assert_allclose(h4, -h4[::-1], atol=1e-10)


def test_hilbert_quadrature():
    """A Hilbert design really does shift phase by ~90° with ~unit gain mid-band."""
    h = remez_exchange(101, [(0.05, 0.45)], [1], filter_type="hilbert")
    w, H = sps.freqz(h, worN=4096, fs=1.0)
    mid = (w > 0.1) & (w < 0.4)
    np.testing.assert_allclose(np.abs(H[mid]), 1.0, atol=2e-3)
    # amplitude is purely imaginary after delay compensation (antisymmetric taps)
    delay = (len(h) - 1) / 2
    Hc = H * np.exp(2j * np.pi * w * delay)
    assert np.abs(Hc.real)[mid].max() < 1e-8


def test_differentiator_slope():
    """Differentiator response follows |H| = 2π·f·gain/(2π) = gain·f scaled."""
    h = remez_exchange(45, [(0.02, 0.45)], [1], filter_type="differentiator")
    w, H = sps.freqz(h, worN=4096, fs=1.0)
    mid = (w > 0.05) & (w < 0.4)
    rel = np.abs(np.abs(H[mid]) / w[mid] - 1.0)
    assert rel.max() < 2e-3
