"""Host data path (ISSUE 10): staging arena, codec pool, credit controller,
and cross-process checkpoint persistence."""

import asyncio
import time

import numpy as np
import pytest

from futuresdr_tpu.ops.arena import ArenaBuffer, GroupAlloc, StagingArena


# ---------------------------------------------------------------------------
# staging arena
# ---------------------------------------------------------------------------

def test_arena_size_classes_and_recycle():
    a = StagingArena(max_bytes=64 << 20)
    b1 = a.take(100_000)                 # -> 128 KiB class
    assert b1.nbytes == 1 << 17
    b1.release()
    b2 = a.take(120_000)                 # same class: served from the pool
    assert b2 is b1
    assert a.hits == 1 and a.misses == 1
    # a different class allocates fresh
    b3 = a.take(1 << 20)
    assert b3 is not b1 and b3.nbytes == 1 << 20
    assert a.misses == 2
    b2.release()
    b3.release()
    st = a.stats()
    assert st["pinned_bytes"] == 0
    assert st["pooled_bytes"] == (1 << 17) + (1 << 20)


def test_arena_pinning_blocks_recycle():
    """A retained buffer (the replay log's reference) survives the taker's
    release — recycling only happens at refcount zero, and over-releasing is
    a no-op rather than a double-free."""
    a = StagingArena()
    b = a.take(4096)
    b.retain()                           # second holder (e.g. the rlog)
    b.release()                          # taker done
    assert a.stats()["pooled_bytes"] == 0    # still pinned
    b2 = a.take(4096)
    assert b2 is not b                   # must NOT recycle the pinned buffer
    b.release()                          # rlog pruned
    assert a.stats()["pooled_bytes"] == b.nbytes
    b.release()                          # over-release: defensive no-op
    assert a.stats()["pooled_bytes"] == b.nbytes
    b2.release()


def test_arena_pool_cap_drops():
    a = StagingArena(max_bytes=1 << 17)  # cap: one 128 KiB buffer
    b1, b2 = a.take(1 << 17), a.take(1 << 17)
    b1.release()
    b2.release()                         # past the cap: dropped, not pooled
    assert a.stats()["pooled_bytes"] == 1 << 17
    assert len(a._free[17]) == 1


def test_arena_copy_in_and_array_view():
    a = StagingArena()
    src = np.arange(1000, dtype=np.complex64)
    v, h = a.copy_in(src)
    np.testing.assert_array_equal(v, src)
    assert v.dtype == src.dtype and v.base is h.base
    h.release()


def test_encode_into_bit_identical_to_encode_host():
    """Arena-path encodes must produce bit-identical wire parts (the replay
    and retry planes re-ship them; any difference would break the
    bit-equality contracts) for every wire format, float and passthrough
    payloads alike."""
    from futuresdr_tpu.ops.wire import WIRE_FORMATS
    rng = np.random.default_rng(3)
    payloads = [
        ((rng.standard_normal(4096) + 1j * rng.standard_normal(4096))
         .astype(np.complex64)),
        rng.standard_normal(4096).astype(np.float32),
        rng.integers(-100, 100, 4096).astype(np.int32),
    ]
    # non-finite samples: the int wires' zeroing contract must match exactly
    # (float wires carry NaN through, and NaN-equality on the custom
    # bfloat16 dtype is unreliable in assert_array_equal — quant-only here)
    bad = payloads[0].copy()
    bad[7] = np.inf + 1j * np.nan
    a = StagingArena()
    for wire in WIRE_FORMATS.values():
        cases = payloads + ([bad] if wire.name in ("sc16", "sc8") else [])
        for x in cases:
            alloc = GroupAlloc(a)
            ref = wire.encode_host(x)
            got = wire.encode_into(x, alloc)
            assert len(ref) == len(got), wire.name
            for r, g in zip(ref, got):
                assert np.asarray(r).dtype == np.asarray(g).dtype, wire.name
                np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                              err_msg=wire.name)
            for h in alloc.handles:
                h.release()
            assert not alloc._temps, f"{wire.name} leaked temps"


def test_group_alloc_temps_only():
    a = StagingArena()
    alloc = GroupAlloc(a)
    sub = alloc.temps_only()
    sub(np.array([16]), np.float32)      # lands in the PARENT temp set
    assert not alloc.handles and len(alloc._temps) == 1
    alloc.drop_temps()
    assert a.stats()["pinned_bytes"] == 0


# ---------------------------------------------------------------------------
# codec pool
# ---------------------------------------------------------------------------

def test_codec_pool_preserves_join_order():
    from futuresdr_tpu.ops.codec_pool import CodecPool
    pool = CodecPool(2)
    try:
        def task(i):
            time.sleep(0.01 if i % 2 else 0.001)   # out-of-order completion
            return i
        futs = [pool.submit_encode(task, i) for i in range(12)]
        assert [f.result() for f in futs] == list(range(12))
    finally:
        pool.shutdown()


def test_codec_pool_config_off(monkeypatch):
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import codec_pool
    monkeypatch.setattr(config(), "host_codec_workers", 0)
    codec_pool.reset_pool()
    try:
        assert codec_pool.pool() is None
    finally:
        codec_pool.reset_pool()


def test_arena_config_off(monkeypatch):
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import arena
    monkeypatch.setattr(config(), "host_arena", False)
    arena.reset_arena()
    try:
        assert arena.arena() is None
    finally:
        arena.reset_arena()


# ---------------------------------------------------------------------------
# credit controller
# ---------------------------------------------------------------------------

def _window(cc, count=8, idle=0.0, limited=False, max_seen=0, span=1.0):
    """Feed one synthetic observation window and tick (white-box: the
    controller's signals are wall-clock derived, so unit tests drive the
    accumulators directly for determinism)."""
    cc._count = count
    cc._idle_s = idle
    cc._limited = limited
    cc._max_seen = max_seen
    cc._t0 = time.perf_counter() - span
    cc._tick()


def test_credit_controller_grow_needs_two_windows_and_keeps_on_improvement():
    from futuresdr_tpu.tpu.kernel_block import CreditController
    cc = CreditController(4, adaptive=True)
    _window(cc, count=8, idle=0.5, limited=True)
    assert cc.credits == 4               # one window is not a signal
    _window(cc, count=8, idle=0.5, limited=True)
    assert cc.credits == 5 and cc._probe == (4, pytest.approx(8.0, rel=0.2))
    _window(cc, count=12, idle=0.5, limited=True)   # rate improved: keep
    assert cc.credits == 5 and cc._probe is None


def test_credit_controller_rolls_back_unproductive_grow():
    from futuresdr_tpu.tpu.kernel_block import CreditController
    cc = CreditController(4, adaptive=True)
    _window(cc, count=8, idle=0.5, limited=True)
    _window(cc, count=8, idle=0.5, limited=True)
    assert cc.credits == 5
    _window(cc, count=8, idle=0.5, limited=True)    # no improvement
    # reverted, and growth backs off (the rollback window consumes one of
    # the four hold windows itself)
    assert cc.credits == 4 and cc._hold == 3
    for _ in range(4):                              # hold: no growth
        _window(cc, count=8, idle=0.5, limited=True)
        assert cc.credits == 4


def test_credit_controller_shrinks_on_slack():
    from futuresdr_tpu.tpu.kernel_block import CreditController
    cc = CreditController(6, adaptive=True)
    _window(cc, max_seen=2)
    assert cc.credits == 6               # hysteresis: one slack window
    _window(cc, max_seen=2)
    assert cc.credits == 5
    for _ in range(10):
        _window(cc, max_seen=1)
    assert cc.credits == cc.lo           # bounded below


def test_credit_controller_pinned_when_not_adaptive():
    from futuresdr_tpu.tpu.kernel_block import CreditController
    cc = CreditController(4, adaptive=False)
    cc.note_limited()
    for _ in range(64):
        cc.note_dispatch((0.0, 1.0), 4)
    assert cc.credits == 4 and cc.hi == 4
    # depth=1 serial baselines stay strictly serial
    cc1 = CreditController(1, adaptive=True)
    assert not cc1.adaptive and cc1.credits == 1


def test_credit_controller_idle_detection():
    from futuresdr_tpu.tpu.kernel_block import CreditController
    cc = CreditController(4, adaptive=True, window=64)
    cc.note_dispatch((10.0, 10.5), 1)
    cc.note_dispatch((11.5, 12.0), 2)    # service 1.0s after prev deadline
    assert cc._idle_s == pytest.approx(1.0)
    cc.note_dispatch((11.9, 12.4), 2)    # overlapping window: no new idle
    assert cc._idle_s == pytest.approx(1.0)


def test_kernel_seeds_credits_from_cached_pick(monkeypatch):
    """With no explicit depth and ``tpu_inflight`` at auto, TpuKernel seeds
    its credit budget from the cached autotune_streamed pick's winning
    depth; an explicit depth or pinned config wins over the cache."""
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import rotator_stage
    from futuresdr_tpu.tpu import TpuKernel
    from futuresdr_tpu.tpu.autotune import _streamed_cache, \
        record_streamed_pick
    monkeypatch.setattr(config(), "tpu_inflight", 0)
    stages = [rotator_stage(0.037)]
    try:
        record_streamed_pick(stages, np.complex64, "cpu", 1, inflight=6)
        tk = TpuKernel(stages, np.complex64, frame_size=4096)
        assert tk.depth == 6 and tk._credits.credits == 6
        assert tk._credits.adaptive
        # explicit per-kernel depth pins
        tk2 = TpuKernel(stages, np.complex64, frame_size=4096,
                        frames_in_flight=3)
        assert tk2.depth == 3 and not tk2._credits.adaptive
        # pinned config wins over the cache
        monkeypatch.setattr(config(), "tpu_inflight", 2)
        tk3 = TpuKernel(stages, np.complex64, frame_size=4096)
        assert tk3.depth == 2 and not tk3._credits.adaptive
    finally:
        _streamed_cache.clear()


def test_stage_copy_megabatch_always_leaves_ring():
    """A megabatch frame sits in ``_accum`` across work cycles AFTER its
    ring space was consumed — it must leave the ring at stage time even for
    quantizing wires (whose k==1 path legitimately encodes the live view
    pre-consume)."""
    from futuresdr_tpu.ops import rotator_stage
    from futuresdr_tpu.tpu import TpuKernel
    view = np.zeros(4096, np.complex64)
    tk1 = TpuKernel([rotator_stage(0.01)], np.complex64, frame_size=4096,
                    frames_in_flight=2, wire="sc16")
    f1, h1 = tk1._stage_copy(view)
    assert f1 is view and h1 is None     # k==1 quantizing: encode pre-consume
    tk4 = TpuKernel([rotator_stage(0.01)], np.complex64, frame_size=4096,
                    frames_in_flight=2, wire="sc16", frames_per_dispatch=4)
    f4, _h4 = tk4._stage_copy(view)
    assert f4 is not view                # k>1: retention outlives the ring


def test_adopt_credit_mode_honors_config_pin(monkeypatch):
    """Fusion must not un-pin a budget: a config ``tpu_inflight`` pin wins
    over the devchain builders' member-explicitness vote."""
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import rotator_stage
    from futuresdr_tpu.tpu import TpuKernel
    monkeypatch.setattr(config(), "tpu_inflight", 3)
    tk = TpuKernel([rotator_stage(0.01)], np.complex64, frame_size=4096)
    assert tk.depth == 3 and not tk._credits.adaptive
    tk._adopt_credit_mode(True)          # the builders' "members adaptive"
    assert not tk._credits.adaptive      # ... loses to the config pin


# ---------------------------------------------------------------------------
# cross-process checkpoint persistence (config `checkpoint_dir`)
# ---------------------------------------------------------------------------

_FRAME = 1 << 11


def _ckpt_stages():
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, rotator_stage
    taps = firdes.lowpass(0.2, 31).astype(np.float32)
    return [fir_stage(taps, fft_len=256), rotator_stage(0.05)]


def _make_kernel(ck=1):
    from futuresdr_tpu.tpu import TpuKernel
    tk = TpuKernel(_ckpt_stages(), np.complex64, frame_size=_FRAME,
                   frames_in_flight=2, checkpoint_every=ck)
    asyncio.run(tk.init(None, None))
    return tk


def _drive(tk, frames):
    """Push frames through the kernel's internal staged→launch→drain surface
    (one at a time: outputs land in order)."""
    outs = []
    for f in frames:
        tk._stage(f.copy(), len(f), ())
        tk._launch_staged()
        r = tk._drain_one()
        if r is not None:
            outs.append(r[0])
    return np.concatenate(outs)


def _frames(n, seed=5):
    rng = np.random.default_rng(seed)
    return [(rng.standard_normal(_FRAME) + 1j * rng.standard_normal(_FRAME))
            .astype(np.complex64) for _ in range(n)]


def _wait_for(cond, timeout=5.0):
    """Snapshot writes/purges ride the codec executor (off the drain
    thread) — poll for their filesystem effect."""
    deadline = time.monotonic() + timeout
    while not cond():
        if time.monotonic() > deadline:
            return False
        time.sleep(0.01)
    return True


def _drain_persist_queue():
    """Barrier on the single-thread persistence executor: every queued
    snapshot write/purge submitted before this call has completed after."""
    from futuresdr_tpu.tpu.kernel_block import _persist_executor
    _persist_executor().submit(lambda: None).result()


def test_checkpoint_persists_and_recovers_across_processes(tmp_path,
                                                           monkeypatch):
    """ISSUE 10 satellite (ROADMAP robustness follow-up): committed carry
    checkpoints serialize under ``checkpoint_dir`` (atomic rename, CRC
    integrity) and a NEW process's kernel — same name, same pipeline —
    restores the carry from disk in ``recover()``: the stream continues
    bit-identical to an uninterrupted run from the snapshot point on."""
    import os
    from futuresdr_tpu.config import config
    frames = _frames(10)
    # reference: uninterrupted run, persistence off
    monkeypatch.setattr(config(), "checkpoint_dir", "")
    ref = _drive(_make_kernel(ck=0), frames)

    monkeypatch.setattr(config(), "checkpoint_dir", str(tmp_path))
    tk1 = _make_kernel()
    out1 = _drive(tk1, frames[:6])
    path = tk1._ckpt_file()
    assert path and _wait_for(lambda: os.path.exists(path)), \
        "commit did not persist"
    _drain_persist_queue()

    # "process restart": a fresh kernel object, nothing in-kernel to restore
    tk2 = _make_kernel()
    assert asyncio.run(tk2.recover(RuntimeError("process restart"))) is True
    out2 = _drive(tk2, frames[6:])
    got = np.concatenate([out1, out2])
    np.testing.assert_array_equal(got, ref)


def test_checkpoint_disk_corruption_rejected(tmp_path, monkeypatch):
    from futuresdr_tpu.config import config
    monkeypatch.setattr(config(), "checkpoint_dir", str(tmp_path))
    tk1 = _make_kernel()
    _drive(tk1, _frames(4))
    path = tk1._ckpt_file()
    assert _wait_for(lambda: __import__("os").path.exists(path))
    _drain_persist_queue()
    data = bytearray(open(path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    open(path, "wb").write(bytes(data))
    tk2 = _make_kernel()
    assert tk2._load_disk_ckpt() is None      # CRC/parse rejects it
    # recover falls through to the fresh-init sentinel instead of crashing
    assert asyncio.run(tk2.recover(RuntimeError("restart"))) is True
    # and the restored carry is the FRESH one, not the corrupted snapshot
    import jax
    _, fresh = tk2.pipeline.compile_wired(tk2.frame_size, tk2.wire,
                                          device=tk2.inst.device,
                                          k=tk2.k_batch, donate=tk2._donate)
    for a, b in zip(jax.tree_util.tree_leaves(tk2._carry),
                    jax.tree_util.tree_leaves(fresh)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_checkpoint_dir_key_collisions(tmp_path, monkeypatch):
    """ISSUE 14 satellite: instance names are per-FLOWGRAPH, so two kernels
    in different flowgraphs can carry the SAME name. The snapshot filename
    is keyed by name + pipeline-signature hash (utils/snapshot.py
    ``snapshot_signature``): different pipelines under one reused name map
    to DIFFERENT files — neither can ever read the other's carry — and the
    true worst case (same name AND same pipeline) shares one file but still
    restores bit-consistently because the signature IS the carry contract."""
    import os
    from futuresdr_tpu.config import config
    from futuresdr_tpu.ops import rotator_stage
    from futuresdr_tpu.tpu import TpuKernel
    from futuresdr_tpu.utils import snapshot as snap
    monkeypatch.setattr(config(), "checkpoint_dir", str(tmp_path))

    tk_fir = _make_kernel()                      # fir+rotator chain
    tk_rot = TpuKernel([rotator_stage(0.05)], np.complex64,
                       frame_size=_FRAME, frames_in_flight=2,
                       checkpoint_every=1)
    asyncio.run(tk_rot.init(None, None))
    # same instance name, different pipelines
    tk_rot.meta.instance_name = tk_fir.meta.instance_name
    p_fir, p_rot = tk_fir._ckpt_file(), tk_rot._ckpt_file()
    assert p_fir != p_rot, "signature hash failed to separate the files"
    # the signature term is the pipeline (stage names + in dtype), pinned
    # at the shared-helper level too
    assert snap.snapshot_signature(tk_fir.pipeline,
                                   tk_fir.meta.instance_name) != \
        snap.snapshot_signature(tk_rot.pipeline, tk_rot.meta.instance_name)

    # drive both; each persists under its own file
    frames = _frames(4)
    _drive(tk_fir, frames)
    _drive(tk_rot, frames)
    assert _wait_for(lambda: os.path.exists(p_fir) and os.path.exists(p_rot))
    _drain_persist_queue()

    # a fresh incarnation of EACH kernel loads only its own snapshot: the
    # rotator kernel (same name!) never sees the FIR chain's carry
    tk_fir2 = _make_kernel()
    got = tk_fir2._load_disk_ckpt()
    assert got is not None
    _, leaves = got
    import jax
    _, fresh = tk_fir2.pipeline.compile_wired(
        tk_fir2.frame_size, tk_fir2.wire, device=tk_fir2.inst.device,
        k=tk_fir2.k_batch, donate=tk_fir2._donate)
    treedef = jax.tree_util.tree_flatten(fresh)[1]
    assert tk_fir2.pipeline.carry_matches(leaves, treedef, fresh)
    tk_rot2 = TpuKernel([rotator_stage(0.05)], np.complex64,
                        frame_size=_FRAME, frames_in_flight=2,
                        checkpoint_every=1)
    asyncio.run(tk_rot2.init(None, None))
    tk_rot2.meta.instance_name = tk_fir.meta.instance_name
    got2 = tk_rot2._load_disk_ckpt()
    assert got2 is not None
    assert len(got2[1]) != len(leaves), \
        "rotator kernel read the FIR chain's snapshot"


def test_checkpoint_clean_eos_purges_snapshot(tmp_path, monkeypatch):
    """A cleanly finished stream's state is complete — the persisted
    snapshot is removed so a later process starts fresh (the in-kernel
    clean-EOS reset contract, extended to disk)."""
    import os
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import VectorSink, VectorSource
    from futuresdr_tpu.config import config
    from futuresdr_tpu.tpu import TpuKernel
    monkeypatch.setattr(config(), "checkpoint_dir", str(tmp_path))
    rng = np.random.default_rng(1)
    n = _FRAME * 5
    data = (rng.standard_normal(n) + 1j * rng.standard_normal(n)) \
        .astype(np.complex64)
    fg = Flowgraph()
    tk = TpuKernel(_ckpt_stages(), np.complex64, frame_size=_FRAME,
                   frames_in_flight=2, checkpoint_every=1)
    snk = VectorSink(np.complex64)
    fg.connect(VectorSource(data), tk, snk)
    Runtime().run(fg, timeout=60.0)
    assert snk.items() is not None
    path = tk._ckpt_file()
    assert path and _wait_for(lambda: not os.path.exists(path)), \
        "clean EOS left a persisted snapshot behind"
