"""Fixed-point NCO (`fxpt_phase.rs:11-19` semantics): wrap, exactness, drift.

The property that matters: the i32 accumulator's phase after N samples is the
same whether computed in one shot or chunk-by-chunk through a streaming run —
and it never diverges from its integer formula, while a float accumulator's
phase error grows with run length.
"""

import numpy as np
import pytest

from futuresdr_tpu.dsp.fxpt import (FixedPointPhase, i32_to_radians,
                                    phase_ramp_i32)


def test_wrap_semantics():
    # -2^31 <-> -pi, 0 <-> 0, 2^31-1 <-> pi - eps
    assert FixedPointPhase(0.0).value == 0
    assert FixedPointPhase(-np.pi).value == -(2 ** 31)
    assert FixedPointPhase(np.pi).value == -(2 ** 31)     # pi folds to -pi
    p = FixedPointPhase(np.pi / 2)
    assert abs(p.to_radians() - np.pi / 2) < 1e-9
    # folding: 2pi + x == x
    assert FixedPointPhase(2 * np.pi + 0.3).value == FixedPointPhase(0.3).value


def test_advance_wraps_exactly():
    inc = FixedPointPhase.increment_for(0.25, 1.0)        # exactly 2^30
    assert inc == 2 ** 30
    p = FixedPointPhase(0.0)
    # 4 advances of 0.25 cycles = 1 cycle = back to start, exactly
    assert p.advance(inc, 4).value == 0
    assert abs(p.advance(inc, 2).to_radians()) in (0.0, np.pi)  # half cycle = ±pi
    # negative frequency wraps the other way
    inc_neg = FixedPointPhase.increment_for(-0.25, 1.0)
    assert p.advance(inc_neg, 4).value == 0
    # a NON-representable rate (0.3) still cancels its own quantization exactly:
    # chunked advance == one-shot advance, whatever the quantized inc is
    inc3 = FixedPointPhase.increment_for(0.3, 1.0)
    assert p.advance(inc3, 1000).value == \
        p.advance(inc3, 400).advance(inc3, 600).value


def test_phase_ramp_matches_scalar_advance():
    rng = np.random.default_rng(3)
    for _ in range(10):
        start = int(rng.integers(-2 ** 31, 2 ** 31))
        inc = int(np.int32(rng.integers(-2 ** 31, 2 ** 31)))
        n = int(rng.integers(1, 5000))
        ramp = phase_ramp_i32(start, inc, n)
        p = FixedPointPhase(raw=start)
        assert ramp[0] == p.value
        assert ramp[-1] == p.advance(inc, n - 1).value


def test_chunked_equals_oneshot():
    """Streaming chunk boundaries are invisible: the concatenated per-chunk ramps
    equal the one-shot ramp bit-for-bit."""
    inc = FixedPointPhase.increment_for(97_531.0, 1e6)
    one = phase_ramp_i32(1234, inc, 100_000)
    pieces, pos = [], 1234
    rng = np.random.default_rng(0)
    done = 0
    while done < 100_000:
        k = min(int(rng.integers(1, 7777)), 100_000 - done)
        pieces.append(phase_ramp_i32(pos, inc, k))
        pos = (pos + inc * k) & 0xFFFF_FFFF
        done += k
    np.testing.assert_array_equal(np.concatenate(pieces), one)


def test_long_run_drift_fxpt_vs_float():
    """After 10^8 samples the fxpt phase is EXACT (integer identity) while the
    float accumulator, stepped chunk-by-chunk as the float NCO does, has drifted
    by orders of magnitude more than one fxpt quantum."""
    freq, fs = 12_345.6789, 1e6
    n_total, chunk = 100_000_000, 65_536

    inc_i = FixedPointPhase.increment_for(freq, fs)
    # fxpt: O(1) exactness check — advance() IS the per-chunk update rule
    p = FixedPointPhase(0.0)
    n_chunks, rem = divmod(n_total, chunk)
    for _ in range(3):                     # spot-check a few chunk updates
        p = p.advance(inc_i, chunk)
    p_direct = FixedPointPhase(0.0).advance(inc_i, 3 * chunk)
    assert p == p_direct                   # chunked == one-shot, bit-exact
    final_fxpt = FixedPointPhase(0.0).advance(inc_i, n_total)
    expected = (inc_i * n_total) & 0xFFFF_FFFF
    assert np.uint32(final_fxpt.value & 0xFFFF_FFFF) == np.uint32(expected)

    # float32-precision accumulator (what a naive NCO state is), stepped per chunk
    inc_f = np.float32(2.0 * np.pi * freq / fs)
    ph = np.float32(0.0)
    for _ in range(n_chunks):
        ph = np.float32((ph + inc_f * chunk) % (2.0 * np.pi))
    ph = np.float32((ph + inc_f * rem) % (2.0 * np.pi))
    # ground truth in extended precision
    true_ph = float((int(n_total) * (2.0 * np.pi * freq / fs)) % (2.0 * np.pi))

    def circ_err(a, b):
        return abs((a - b + np.pi) % (2 * np.pi) - np.pi)

    float_err = circ_err(float(ph), true_ph)
    fxpt_err = circ_err(final_fxpt.to_radians(),
                        float((int(n_total) * (inc_i * np.pi / 2 ** 31)) % (2 * np.pi)))
    quantum = np.pi / 2 ** 31
    assert fxpt_err < 4 * quantum          # exact up to the radian conversion
    assert float_err > 1000 * quantum      # the float path has genuinely drifted
    assert float_err > 100 * fxpt_err if fxpt_err > 0 else True


def test_signal_source_fxpt_block():
    """SignalSource(nco='fxpt') streams the exact integer-phase waveform and the
    freq port retunes to the quantized frequency."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import SignalSource, Head, VectorSink

    fs, f0, n = 48_000.0, 1_234.5, 20_000
    fg = Flowgraph()
    src = SignalSource("complex", f0, fs, nco="fxpt")
    head = Head(np.complex64, n)
    snk = VectorSink(np.complex64)
    fg.connect(src, head, snk)
    Runtime().run(fg)
    got = snk.items()
    assert len(got) == n
    inc = FixedPointPhase.increment_for(f0, fs)
    ref = np.exp(1j * i32_to_radians(phase_ramp_i32(0, inc, n))).astype(np.complex64)
    np.testing.assert_allclose(got, ref, atol=1e-6)
    # the tone is where we asked (to fs/2^32 quantization)
    spec = np.abs(np.fft.fft(got * np.hanning(n)))
    peak = np.argmax(spec[:n // 2]) * fs / n
    assert abs(peak - f0) < 2 * fs / n
