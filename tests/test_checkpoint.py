"""Checkpoint/resume tests: orbax pytrees + flowgraph block state."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Kernel
from futuresdr_tpu.utils import (save_pytree, load_pytree, save_flowgraph_state,
                                 load_flowgraph_state)


def test_pytree_roundtrip(tmp_path):
    import jax.numpy as jnp

    tree = {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.zeros(4),
            "meta": {"step": jnp.asarray(7)}}
    path = str(tmp_path / "ckpt")
    save_pytree(path, tree)
    back = load_pytree(path, like=tree)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(tree["w"]))
    assert int(back["meta"]["step"]) == 7


def test_training_resume(tmp_path):
    """Save params mid-training, reload, keep training — the burn example workflow
    plus the checkpointing the reference lacks."""
    from futuresdr_tpu.models.mcldnn import MCLDNN
    from futuresdr_tpu.models.modrec import train, CLASSES

    model = MCLDNN(n_classes=len(CLASSES), conv_features=8, lstm_features=16)
    model, params, _ = train(n_steps=5, batch=16, n=64, model=model)
    path = str(tmp_path / "params")
    save_pytree(path, params)
    restored = load_pytree(path, like=params)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class StatefulBlock(Kernel):
    def __init__(self):
        super().__init__()
        self.counter = 0
        self.add_stream_input("in", np.float32)

    def state_dict(self):
        return {"counter": self.counter}

    def load_state_dict(self, d):
        self.counter = d["counter"]


def test_flowgraph_state_roundtrip(tmp_path):
    fg = Flowgraph()
    blk = StatefulBlock()
    fg.add(blk)
    blk.counter = 42
    path = str(tmp_path / "state.pkl")
    save_flowgraph_state(fg, path)

    fg2 = Flowgraph()
    blk2 = StatefulBlock()
    fg2.add(blk2)
    assert load_flowgraph_state(fg2, path) == 1
    assert blk2.counter == 42
