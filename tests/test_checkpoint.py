"""Checkpoint/resume tests: orbax pytrees + flowgraph block state."""

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Kernel
from futuresdr_tpu.utils import (save_pytree, load_pytree, save_flowgraph_state,
                                 load_flowgraph_state)


def test_pytree_roundtrip(tmp_path):
    import jax.numpy as jnp

    tree = {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.zeros(4),
            "meta": {"step": jnp.asarray(7)}}
    path = str(tmp_path / "ckpt")
    save_pytree(path, tree)
    back = load_pytree(path, like=tree)
    np.testing.assert_array_equal(np.asarray(back["w"]), np.asarray(tree["w"]))
    assert int(back["meta"]["step"]) == 7


def test_training_resume(tmp_path):
    """Save params mid-training, reload, keep training — the burn example workflow
    plus the checkpointing the reference lacks."""
    from futuresdr_tpu.models.mcldnn import MCLDNN
    from futuresdr_tpu.models.modrec import train, CLASSES

    model = MCLDNN(n_classes=len(CLASSES), conv_features=8, lstm_features=16)
    model, params, _ = train(n_steps=5, batch=16, n=64, model=model)
    path = str(tmp_path / "params")
    save_pytree(path, params)
    restored = load_pytree(path, like=params)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class StatefulBlock(Kernel):
    def __init__(self):
        super().__init__()
        self.counter = 0
        self.add_stream_input("in", np.float32)

    def state_dict(self):
        return {"counter": self.counter}

    def load_state_dict(self, d):
        self.counter = d["counter"]


def test_flowgraph_state_roundtrip(tmp_path):
    fg = Flowgraph()
    blk = StatefulBlock()
    fg.add(blk)
    blk.counter = 42
    path = str(tmp_path / "state.pkl")
    save_flowgraph_state(fg, path)

    fg2 = Flowgraph()
    blk2 = StatefulBlock()
    fg2.add(blk2)
    assert load_flowgraph_state(fg2, path) == 1
    assert blk2.counter == 42


def test_pipeline_carry_checkpoint_resume_bit_exact(tmp_path):
    """Device-pipeline carries — including RETUNED carries, whose swapped taps
    live in the carry — checkpoint and resume bit-exactly through the pytree
    saver (streams continue as if never interrupted)."""
    from futuresdr_tpu.ops import Pipeline, fir_stage

    taps = np.hanning(32).astype(np.float32)
    pipe = Pipeline([fir_stage(taps, name="f")], np.float32, optimize=False)
    fn, carry = pipe.fn(), pipe.init_carry()
    rng = np.random.default_rng(0)
    x = rng.standard_normal(1 << 16).astype(np.float32)
    carry, _ = fn(carry, x[:1 << 15])

    save_pytree(str(tmp_path / "ck"), carry)
    carry2 = load_pytree(str(tmp_path / "ck"), like=carry)
    _, ya = fn(carry, x[1 << 15:])
    _, yb = fn(carry2, x[1 << 15:])
    np.testing.assert_array_equal(np.asarray(ya), np.asarray(yb))

    carry3 = pipe.update_stage(carry, "f", taps=-taps)   # runtime retune
    save_pytree(str(tmp_path / "ck2"), carry3)
    carry4 = load_pytree(str(tmp_path / "ck2"), like=carry3)
    _, yc = fn(carry3, x[1 << 15:])
    _, yd = fn(carry4, x[1 << 15:])
    np.testing.assert_array_equal(np.asarray(yc), np.asarray(yd))
    # the retune is falsifiable: negated taps => negated output vs the original
    np.testing.assert_allclose(np.asarray(yc), -np.asarray(ya), atol=1e-5)
