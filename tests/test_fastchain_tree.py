"""Fast-chain v3 trees: broadcast rings and the native Throttle stage.

The v3 driver (`native/fastchain.cpp fc_run_core`) runs source-rooted TREES,
not just linear chains: a ring consumed by several stages broadcasts — every
consumer sees every item with its own read index, the actor runtime's
1-writer→N-reader port-group semantics (`runtime/buffer/circular.py:108`,
reference: one output port wired to several edges). A finished consumer's
slot is released so an early-finishing branch cannot wedge its siblings
(the actor runtime likewise drops a finished block's reader)."""

import os
import time

import numpy as np
import pytest

from futuresdr_tpu import Flowgraph, Runtime
from futuresdr_tpu.blocks import (Copy, CopyRand, Fir, Head, NullSink,
                                  NullSource, Throttle, VectorSink,
                                  VectorSource)
from futuresdr_tpu.dsp import firdes
from futuresdr_tpu.runtime.fastchain import fastchain_available, find_native_chains

pytestmark = pytest.mark.skipif(not fastchain_available(),
                                reason="native fastchain unavailable")


def _tree_fg(n=30_000, seed=5):
    """VectorSource → CopyRand → broadcast{VectorSink, Fir64 → VectorSink}."""
    rng = np.random.default_rng(seed)
    data = rng.standard_normal(n).astype(np.float32)
    taps = firdes.lowpass(0.25, 64).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cr = CopyRand(np.float32, max_copy=700, seed=seed)
    raw = VectorSink(np.float32)
    fir = Fir(taps)
    filt = VectorSink(np.float32)
    fg.connect(src, cr)
    fg.connect_stream(cr, "out", raw, "in")
    fg.connect(cr, fir, filt)
    return fg, data, taps, raw, filt


def test_broadcast_tree_data_exact_vs_actor():
    """Both branches of a fused broadcast see every item: the raw branch is
    BIT-exact vs the source data, the FIR branch matches the actor path run
    of the same flowgraph to float32 rounding."""
    fg, data, taps, raw, filt = _tree_fg()
    trees = find_native_chains(fg)
    assert len(trees) == 1 and len(trees[0]) == 5
    Runtime().run(fg)
    got_raw = raw.items()
    got_filt = filt.items()
    assert np.array_equal(got_raw, data)          # broadcast copy is bit-exact

    os.environ["FSDR_NO_FASTCHAIN"] = "1"
    try:
        fg2, data2, _, raw2, filt2 = _tree_fg()
        assert find_native_chains(fg2) == []
        Runtime().run(fg2)
    finally:
        os.environ.pop("FSDR_NO_FASTCHAIN", None)
    assert np.array_equal(raw2.items(), got_raw)
    np.testing.assert_allclose(filt2.items(), got_filt, rtol=2e-5, atol=2e-6)


def test_broadcast_counters_per_branch():
    """Per-member metrics stay honest on a tree: the broadcast producer
    reports its items once, each branch its own consumed/produced counts."""
    fg, data, taps, raw, filt = _tree_fg(n=10_000)
    Runtime().run(fg)
    w_cr = fg.wrapped(next(k for k in (b.kernel for b in fg._blocks
                                       if b is not None)
                           if isinstance(k, CopyRand)))
    m = w_cr.metrics()
    assert m["fused_native"] is True
    assert m["items_out"]["out"] == 10_000
    assert fg.wrapped(raw).metrics()["items_in"]["in"] == 10_000
    assert fg.wrapped(filt).metrics()["items_in"]["in"] == 10_000


def test_early_finishing_branch_releases_ring():
    """A Head-bounded branch that finishes first must not wedge its broadcast
    sibling: its ring slot is released (the actor runtime drops a finished
    reader the same way)."""
    fg = Flowgraph()
    src = NullSource(np.float32)
    cp = Copy(np.float32)
    h_short = Head(np.float32, 512)          # finishes almost immediately
    snk_short = NullSink(np.float32)
    h_long = Head(np.float32, 3_000_000)     # many ring generations later
    snk_long = NullSink(np.float32)
    fg.connect(src, cp)
    fg.connect(cp, h_short, snk_short)
    fg.connect_stream(cp, "out", h_long, "in")
    fg.connect(h_long, snk_long)
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    assert snk_short.n_received == 512
    assert snk_long.n_received == 3_000_000


def test_throttle_fuses_behind_static_opt_in_and_paces():
    """Throttle fuses only with the fastchain_static promise (it has a live
    rate retune handler), and the native stage paces by the same wall-clock
    budget math as the actor work() loop."""
    def build(static):
        fg = Flowgraph()
        src = VectorSource(np.ones(20_000, np.float32))
        th = Throttle(np.float32, 40_000.0)
        if static:
            th.fastchain_static = True
        snk = NullSink(np.float32)
        fg.connect(src, th, snk)
        return fg, snk

    fg, _ = build(static=False)
    assert find_native_chains(fg) == []      # no opt-in → actor path

    fg, snk = build(static=True)
    assert len(find_native_chains(fg)) == 1
    t0 = time.perf_counter()
    Runtime().run(fg)
    dt = time.perf_counter() - t0
    assert snk.n_received == 20_000
    # 20k items at 40k/s ≈ 0.5 s; generous upper bound for a loaded host
    assert 0.4 <= dt <= 5.0, dt

    # degenerate rates must not freeze the fused loop: inf is rejected at the
    # gate (actor path raises on it), a finite-but-huge rate fuses and runs
    # effectively unthrottled (the C budget clamps instead of overflowing
    # the int64 cast into a permanent 0-item sleep)
    fg3 = Flowgraph()
    src3 = VectorSource(np.ones(5_000, np.float32))
    th3 = Throttle(np.float32, 1e19)
    th3.fastchain_static = True
    snk3 = NullSink(np.float32)
    fg3.connect(src3, th3, snk3)
    assert len(find_native_chains(fg3)) == 1
    Runtime().run(fg3)
    assert snk3.n_received == 5_000


def test_tree_with_collecting_sinks_bounded_per_path():
    """Each collecting sink's capacity derives from its OWN source→sink path
    (a decimating branch collects fewer items than its sibling)."""
    rng = np.random.default_rng(11)
    data = rng.standard_normal(8_192).astype(np.float32)
    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    fg = Flowgraph()
    src = VectorSource(data)
    cp = Copy(np.float32)
    full = VectorSink(np.float32)
    dec = Fir(taps, decim=4)
    quarter = VectorSink(np.float32)
    fg.connect(src, cp)
    fg.connect_stream(cp, "out", full, "in")
    fg.connect(cp, dec, quarter)
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    assert len(full.items()) == 8_192
    assert len(quarter.items()) == 2_048
    assert np.array_equal(full.items(), data)


def test_random_tree_shapes_fuzz():
    """Seeded sweep over random ELIGIBLE trees: a random linear prefix, a
    fan-out point broadcasting to 2-3 branches, each branch a random stage
    suffix into its own VectorSink — every fused tree must match its actor
    twin per branch. The tree-composition analog of the chain fuzz
    (`test_fastchain_dsp.test_random_chain_shapes_fuzz`); also run by
    perf/fuzz_campaign.py with shifted seeds."""
    if not fastchain_available():
        return          # campaign calls this directly, bypassing the skipif
    rng = np.random.default_rng(24242)
    for trial in range(5):
        n = int(rng.integers(5_000, 16_000))
        data = rng.standard_normal(n).astype(np.float32)
        n_branches = int(rng.integers(2, 4))
        pre = [str(k) for k in rng.choice(["copyrand", "fir"],
                                          size=rng.integers(0, 3))]
        suff = [[str(k) for k in rng.choice(["copyrand", "fir", "decim"],
                                            size=rng.integers(0, 3))]
                for _ in range(n_branches)]
        pseed = int(rng.integers(0, 1 << 30))

        def stage(kind, r):
            if kind == "copyrand":
                return CopyRand(np.float32, int(r.integers(64, 1024)),
                                seed=int(r.integers(1, 99)))
            if kind == "fir":
                return Fir(firdes.lowpass(0.2, int(r.integers(8, 65))
                                          ).astype(np.float32))
            return Fir(firdes.lowpass(0.1, 32).astype(np.float32),
                       decim=int(r.integers(2, 5)))

        def build():
            r = np.random.default_rng(pseed)   # identical params per path
            fg = Flowgraph()
            last = VectorSource(data)
            fg.add(last)
            for k in pre:
                b = stage(k, r)
                fg.connect(last, b)
                last = b
            fan = Copy(np.float32)
            fg.connect(last, fan)
            sinks = []
            for br in suff:
                cur = fan
                for k in br:
                    b = stage(k, r)
                    fg.connect_stream(cur, "out", b, "in")
                    cur = b
                vs = VectorSink(np.float32)
                fg.connect_stream(cur, "out", vs, "in")
                sinks.append(vs)
            return fg, sinks

        fg, sinks = build()
        trees = find_native_chains(fg)
        assert len(trees) == 1, (trial, pre, suff)
        Runtime().run(fg)
        native = [vs.items() for vs in sinks]

        os.environ["FSDR_NO_FASTCHAIN"] = "1"
        try:
            fg2, sinks2 = build()
            assert find_native_chains(fg2) == []
            Runtime().run(fg2)
        finally:
            os.environ.pop("FSDR_NO_FASTCHAIN", None)
        for bi, (got, want_sink) in enumerate(zip(native, sinks2)):
            want = want_sink.items()
            assert len(got) == len(want), (trial, bi, pre, suff)
            np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-5,
                                       err_msg=f"{trial} branch {bi}")


def test_stream_duplicator_fuses_as_broadcast():
    """StreamDuplicator (1→N duplicate block, `stream_duplicator.rs`) fuses
    as one broadcast ring — N ports all carrying every item is exactly the
    per-consumer-tails ring; per-port produced counters match the actor's."""
    from futuresdr_tpu.blocks import StreamDuplicator
    rng = np.random.default_rng(7)
    data = rng.standard_normal(12_000).astype(np.float32)

    def build():
        fg = Flowgraph()
        src = VectorSource(data)
        dup = StreamDuplicator(np.float32, n_outputs=2)
        a, b = VectorSink(np.float32), VectorSink(np.float32)
        fg.connect(src, dup)
        fg.connect_stream(dup, "out0", a, "in")
        fg.connect_stream(dup, "out1", b, "in")
        return fg, dup, a, b

    fg, dup, a, b = build()
    assert len(find_native_chains(fg)) == 1
    Runtime().run(fg)
    assert np.array_equal(a.items(), data)
    assert np.array_equal(b.items(), data)
    m = fg.wrapped(dup).metrics()
    assert m["fused_native"] is True
    assert m["items_out"]["out0"] == 12_000
    assert m["items_out"]["out1"] == 12_000

    # an UNWIRED duplicator port must not fuse: the actor path raises on it,
    # and the substitution must stay invisible (review regression)
    fg2 = Flowgraph()
    dup2 = StreamDuplicator(np.float32, n_outputs=3)
    a2 = VectorSink(np.float32)
    fg2.connect(VectorSource(data), dup2)
    fg2.connect_stream(dup2, "out0", a2, "in")
    fg2.connect_stream(dup2, "out1", VectorSink(np.float32), "in")
    assert find_native_chains(fg2) == []
