"""Fleet observability plane (telemetry/fleet.py + serve/router.py).

Unit coverage: the routing score's lexicographic ordering (shed rung →
credit pressure → p99 headroom), the hysteresis band, ready-host
filtering, FleetView staleness transitions (fresh → stale → down →
recovered) against an injected fetch, the merged-exposition stable
ordering (histogram ``le=`` bucket order preserved), journal spool
rotation, and router failover honoring Retry-After.

Live coverage: three jax-free control-port subprocesses
(tests/_fleet_child.py) — ``GET /api/fleet/`` shows 3 ready, SIGKILL one,
the fleet flips it to down within 2 poll intervals (``fleet_down_errors``)
and the router sends 100% of subsequent admits to the survivors, with
every decision journaled.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from futuresdr_tpu.serve.router import AdmissionRouter, NoReadyHost, score, \
    _better
from futuresdr_tpu.telemetry import fleet
from futuresdr_tpu.telemetry import journal as journal_mod
from futuresdr_tpu.telemetry.fleet import FleetView, merge_metrics
from futuresdr_tpu.telemetry.journal import Journal

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CHILD = os.path.join(_ROOT, "tests", "_fleet_child.py")


def _summary(ready=True, shed=0, pressure=0.0, p99=0.01, app="app",
             app_ready=None, occupants=(), host="h"):
    return {
        "host": host, "ready": ready, "pressure": pressure,
        "shed_level": shed, "compile_storm": False,
        "sessions": len(occupants),
        "doctor": {"verdict": "ok"},
        "e2e": {"p50_s": p99 / 2, "p99_s": p99},
        "apps": {app: {"ready": ready if app_ready is None else app_ready,
                       "shed_level": shed, "pressure": pressure,
                       "sessions": len(occupants),
                       "occupants": list(occupants)}},
    }


# ---------------------------------------------------------------------------
# routing score: lexicographic rung -> pressure -> p99, ready filtering
# ---------------------------------------------------------------------------

def test_score_orders_rung_then_pressure_then_p99():
    calm = score(_summary(shed=0, pressure=0.9, p99=0.5), "app")
    shedding = score(_summary(shed=1, pressure=0.1, p99=0.001), "app")
    # a host one rung up loses to ANY host a rung down, whatever its
    # pressure or latency
    assert calm < shedding
    lo_p = score(_summary(pressure=0.2, p99=0.5), "app")
    hi_p = score(_summary(pressure=0.8, p99=0.001), "app")
    assert lo_p < hi_p                     # same rung: pressure decides
    fast = score(_summary(pressure=0.5, p99=0.01), "app")
    slow = score(_summary(pressure=0.5, p99=0.10), "app")
    assert fast < slow                     # same rung+pressure: p99 decides


def test_score_filters_unready():
    assert score(_summary(ready=False), "app") is None
    assert score({}, "app") is None
    # host ready but the NAMED app draining/unready -> filtered too
    assert score(_summary(ready=True, app_ready=False), "app") is None
    # unknown app falls back to the host-level signals, stays a candidate
    assert score(_summary(), "other_app") is not None


def test_hysteresis_band():
    h = 0.1
    cur = (0.0, 0.50, 0.020)
    # inside the band on the deciding component: stay
    assert not _better((0.0, 0.45, 0.020), cur, h)
    assert not _better((0.0, 0.50, 0.021), cur, h)
    # outside the band: switch
    assert _better((0.0, 0.30, 0.020), cur, h)
    assert _better((0.0, 0.50, 0.005), cur, h)
    # a WORSE candidate never switches, band or not
    assert not _better((0.0, 0.70, 0.020), cur, h)
    # rung differences always switch (the ladder is hysteretic upstream)
    assert _better((0.0, 0.9, 0.9), (1.0, 0.0, 0.0), h)
    assert not _better((1.0, 0.0, 0.0), (0.0, 0.9, 0.9), h)


class FakeView:
    def __init__(self, summaries):
        self._s = dict(summaries)

    def set(self, host, summary):
        self._s[host] = summary

    def ready_hosts(self):
        return {p: {"state": "up", "summary": s}
                for p, s in self._s.items() if s and s.get("ready")}


def test_router_picks_least_pressure_and_sticks_inside_band():
    view = FakeView({"a:1": _summary(pressure=0.6),
                     "b:1": _summary(pressure=0.2),
                     "c:1": _summary(ready=False)})
    r = AdmissionRouter(view, hysteresis=0.1, post=lambda *a: (201, {}, b"{}"))
    host, scores = r.pick("app")
    assert host == "b:1"
    assert set(scores) == {"a:1", "b:1"}   # the unready host never scored
    # a near-tie inside the band keeps the traffic where it is
    view.set("a:1", _summary(pressure=0.15))
    assert r.pick("app")[0] == "b:1"
    # outside the band: routing moves
    view.set("a:1", _summary(pressure=0.01))
    assert r.pick("app")[0] == "a:1"


def test_router_failover_honors_retry_after():
    view = FakeView({"a:1": _summary(pressure=0.1),
                     "b:1": _summary(pressure=0.5)})
    calls = []

    def post(url, body, timeout):
        calls.append(url)
        if "//a:1/" in url:                # best host sheds: 503 + backoff
            return 503, {"Retry-After": "7"}, b'{"error": "overloaded"}'
        return 201, {}, json.dumps({"sid": "s1", "tenant":
                                    body["tenant"]}).encode()

    r = AdmissionRouter(view, hysteresis=0.1, post=post)
    out = r.admit("app", tenant="t")
    assert out["host"] == "b:1" and out["failovers"] == 1
    assert out["session"]["sid"] == "s1"
    assert ["//a:1/" in c for c in calls] == [True, False]
    # every host refusing surfaces the largest Retry-After it saw
    view.set("b:1", None)
    with pytest.raises(NoReadyHost) as ei:
        r.admit("app")
    assert ei.value.retry_after >= 7
    evs = journal_mod.events(cat="fleet")["events"]
    names = [e["event"] for e in evs]
    assert "route-failover" in names and "route" in names \
        and "route-failed" in names
    routed = [e for e in evs if e["event"] == "route"][-1]
    assert routed["host"] == "b:1" and "b:1" in routed["scores"]
    assert routed["failovers"] == 1
    # the refused host's decision is its own journaled event
    fo = [e for e in evs if e["event"] == "route-failover"][-1]
    assert fo["host"] == "a:1" and fo["status"] == 503 \
        and fo["retry_after"] == 7


# ---------------------------------------------------------------------------
# FleetView staleness state machine (injected fetch, no sockets)
# ---------------------------------------------------------------------------

def test_fleetview_fresh_stale_down_recovered():
    up = {"p1:1": True, "p2:1": True}

    def fetch(url, timeout):
        peer = url.split("//")[1].split("/")[0]
        if not up[peer]:
            raise OSError("connection refused")
        return json.dumps(_summary(host=peer)).encode()

    v = FleetView(["p1:1", "p2:1"], poll_interval=0.05, down_errors=2,
                  fetch=fetch)
    j0 = journal_mod.journal().seq
    v.poll_once()
    assert {p: h["state"] for p, h in v.hosts().items()} == \
        {"p1:1": "up", "p2:1": "up"}
    assert v.snapshot()["ready"] and v.snapshot()["hosts_ready"] == 2
    # first failed poll: stale (not yet down), verdict surfaces it
    up["p2:1"] = False
    v.poll_once()
    assert v.hosts()["p2:1"]["state"] == "stale"
    assert any(x["verdict"] == "host-stale" and x["host"] == "p2:1"
               for x in v.verdicts())
    # second consecutive failure (= fleet_down_errors): down
    v.poll_once()
    assert v.hosts()["p2:1"]["state"] == "down"
    assert not v.snapshot()["ready"]       # a down host degrades the fleet
    assert "p2:1" not in v.ready_hosts() and "p1:1" in v.ready_hosts()
    # recovery on the next good poll
    up["p2:1"] = True
    v.poll_once()
    assert v.hosts()["p2:1"]["state"] == "up"
    # the journal tells the story in order: stale -> down -> recovered
    evs = [e for e in journal_mod.events(since=j0, cat="fleet")["events"]
           if e.get("host") == "p2:1"]
    assert [e["event"] for e in evs] == \
        ["host-up", "host-stale", "host-down", "host-recovered"]
    down = [e for e in evs if e["event"] == "host-down"][0]
    assert down["errors"] == 2             # within 2 poll intervals


def test_fleetview_age_staleness_between_polls():
    v = FleetView(["p:1"], poll_interval=0.05, stale_s=0.08,
                  fetch=lambda u, t: json.dumps(_summary()).encode())
    v.poll_once()
    assert v.hosts()["p:1"]["state"] == "up"
    time.sleep(0.1)                        # age past stale_s with no poll
    v._age_sweep()
    assert v.hosts()["p:1"]["state"] == "stale"


def test_fleet_verdicts_pressure_skew_and_storm():
    def fetch(url, timeout):
        peer = url.split("//")[1].split("/")[0]
        if peer == "hot:1":
            s = _summary(host=peer, pressure=0.9, occupants=("s1", "s2"))
            s["compile_storm"] = True
            return json.dumps(s).encode()
        s = _summary(host=peer, pressure=0.1)
        s["compile_storm"] = peer == "warm:1"
        return json.dumps(s).encode()

    v = FleetView(["hot:1", "cold:1", "warm:1"], poll_interval=0.05,
                  skew=0.5, fetch=fetch)
    v.poll_once()
    verdicts = {x["verdict"]: x for x in v.verdicts()}
    skew = verdicts["pressure-skew"]
    assert skew["hot"] == "hot:1" and skew["cold"] in ("cold:1", "warm:1")
    # the hottest host's resident sessions surface as eviction candidates
    assert {c["sid"] for c in skew["evict_candidates"]} == {"s1", "s2"}
    # 2 of 3 hosts storming -> fleet-wide compile storm
    storm = verdicts["fleet-compile-storm"]
    assert storm["hosts"] == ["hot:1", "warm:1"]


# ---------------------------------------------------------------------------
# merged exposition: host label + stable ordering
# ---------------------------------------------------------------------------

def test_merge_metrics_stable_order_and_bucket_order():
    hist = ("# TYPE fsdr_lat histogram\n"
            'fsdr_lat_bucket{le="0.5"} 1\n'
            'fsdr_lat_bucket{le="2"} 3\n'    # "2" sorts before "0.5"
            'fsdr_lat_bucket{le="+Inf"} 3\n'  # lexically — order must hold
            "fsdr_lat_sum 1.5\nfsdr_lat_count 3\n")
    texts = {"b:1": "# TYPE z_c counter\nz_c 1\n# TYPE a_g gauge\na_g 2\n",
             "a:1": hist}
    merged = merge_metrics(texts)
    # families sort by name; each host's sample lines keep original order
    fam_order = [ln.split()[2] for ln in merged.splitlines()
                 if ln.startswith("# TYPE")]
    assert fam_order == ["a_g", "fsdr_lat", "z_c"]
    lat = [ln for ln in merged.splitlines()
           if ln.startswith("fsdr_lat_bucket")]
    assert [ln.split('le="')[1].split('"')[0] for ln in lat] == \
        ["0.5", "2", "+Inf"]               # NOT resorted lexically
    assert all('host="a:1"' in ln for ln in lat)
    # merging twice is byte-identical (the stable-ordering contract the
    # fleet smoke diffs)
    assert merged == merge_metrics(dict(reversed(list(texts.items()))))
    # unlabelled samples gain {host=...}; labelled keep theirs after it
    assert 'z_c{host="b:1"} 1' in merged
    assert 'a_g{host="b:1"} 2' in merged


# ---------------------------------------------------------------------------
# journal spool rotation (satellite: size-capped, keep-N, atomic, journaled)
# ---------------------------------------------------------------------------

def test_journal_spool_rotation(tmp_path):
    j = Journal(maxlen=64, spool_dir=str(tmp_path), spool_cap_mb=1,
                spool_keep=2)
    blob = "x" * 4096
    # ~3 MiB of events through a 1 MiB cap: at least two rotations
    for i in range(3 * 256):
        j.emit("chaos", "fill", i=i, blob=blob)
    seq_after = j.seq
    base = tmp_path / f"events_{os.getpid()}.jsonl"
    assert base.exists()
    assert (tmp_path / f"{base.name}.1").exists()
    assert (tmp_path / f"{base.name}.2").exists()
    assert not (tmp_path / f"{base.name}.3").exists()   # keep-N enforced
    assert base.stat().st_size < 1 << 20   # active file restarted fresh
    # every rotated generation stays within ~cap
    for gen in (f"{base.name}.1", f"{base.name}.2"):
        assert (tmp_path / gen).stat().st_size < (1 << 20) + 8192
    # the rotation event is journaled — in the ring AND as the first line
    # of each post-rotation spool file — with seq continuity intact
    rot = [e for e in j.last(64) if e["event"] == "spool-rotate"]
    assert rot and rot[-1]["cat"] == "journal"
    assert rot[-1]["keep"] == 2 and rot[-1]["rotated_bytes"] >= 1 << 20
    first = json.loads(base.read_text().splitlines()[0])
    assert first["event"] == "spool-rotate"
    with open(tmp_path / f"{base.name}.1") as f:
        gen1 = [json.loads(ln) for ln in f]
    assert gen1[0]["event"] == "spool-rotate"
    seqs = [e["seq"] for e in gen1]
    assert seqs == sorted(seqs)            # monotonic within a generation
    # emission never raises and the counter never resets across rotation
    assert j.emit("chaos", "after") == seq_after + 1
    j.close()


def test_journal_spool_no_rotation_when_disabled(tmp_path):
    j = Journal(maxlen=8, spool_dir=str(tmp_path), spool_cap_mb=0)
    for i in range(64):
        j.emit("chaos", "fill", blob="y" * 1024)
    assert not list(tmp_path.glob("*.jsonl.1"))         # 0 = never rotate
    j.close()


# ---------------------------------------------------------------------------
# live: 3 control-port subprocesses, kill one, routing shifts
# ---------------------------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(url, timeout=5):
    return json.load(urllib.request.urlopen(url, timeout=timeout))


def _spawn_children(specs):
    """specs: [(port, pressure), ...] -> procs (READY line awaited)."""
    pypath = _ROOT + os.pathsep + os.environ.get("PYTHONPATH", "")
    env = dict(os.environ, PYTHONPATH=pypath.rstrip(os.pathsep))
    procs = [subprocess.Popen(
        [sys.executable, _CHILD, str(port), str(pressure)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for port, pressure in specs]
    deadline = time.monotonic() + 30
    for p, (port, _pr) in zip(procs, specs):
        seen = []
        while time.monotonic() < deadline:
            line = p.stdout.readline()     # log lines precede the marker
            seen.append(line)
            if "READY" in line or not line:
                break
        assert seen and "READY" in seen[-1], \
            f"child {port} failed: {seen!r}"
    return procs


def test_live_fleet_three_hosts_kill_one_routes_to_survivors():
    specs = [(_free_port(), 0.1), (_free_port(), 0.3), (_free_port(), 0.5)]
    peers = [f"127.0.0.1:{port}" for port, _ in specs]
    interval = 0.15
    procs = _spawn_children(specs)
    view = None
    parent_port = _free_port()
    cp = None
    try:
        # the parent is a host-only aggregator: fleet config via env ->
        # reload, its control port starts the FleetView + serves /api/fleet/
        os.environ["FUTURESDR_TPU_FLEET_PEERS"] = ",".join(peers)
        os.environ["FUTURESDR_TPU_FLEET_POLL_INTERVAL"] = str(interval)
        from futuresdr_tpu.config import reload_config
        from futuresdr_tpu.runtime.ctrl_port import ControlPort
        reload_config()

        class _Handle:                     # host-only port: no flowgraphs
            def flowgraph_ids(self):
                return []

            def get_flowgraph(self, fg):
                return None

        cp = ControlPort(_Handle(), bind=f"127.0.0.1:{parent_port}")
        cp.start()
        view = fleet.active_view()
        assert view is not None            # started by the control port
        base = f"http://127.0.0.1:{parent_port}"
        deadline = time.monotonic() + 15
        snap = {}
        while time.monotonic() < deadline:
            snap = _get(f"{base}/api/fleet/")
            if snap.get("hosts_ready") == 3:
                break
            time.sleep(interval)
        assert snap.get("hosts_ready") == 3 and snap["ready"], snap
        # per-host summaries rode the poll: pressure + app table visible
        hosts = snap["hosts"]
        assert hosts[peers[0]]["summary"]["pressure"] == 0.1
        assert "app" in hosts[peers[2]]["summary"]["apps"]
        # merged exposition: stably ordered, every sample host-labelled
        m1 = urllib.request.urlopen(
            f"{base}/api/fleet/metrics", timeout=5).read().decode()
        m2 = urllib.request.urlopen(
            f"{base}/api/fleet/metrics", timeout=5).read().decode()
        assert f'host="{peers[0]}"' in m1
        stable = [ln.partition(" ")[0] for ln in m1.splitlines()]
        assert stable == [ln.partition(" ")[0] for ln in m2.splitlines()]
        # routed admission lands on the least-pressure child
        router = AdmissionRouter(view, hysteresis=0.05)
        out = router.admit("app", tenant="t0")
        assert out["host"] == peers[0]
        # SIGKILL the current pick mid-serve
        j0 = journal_mod.journal().seq
        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=10)
        t_kill = time.monotonic()
        deadline = t_kill + 15
        while time.monotonic() < deadline:
            if view.hosts()[peers[0]]["state"] == "down":
                break
            time.sleep(interval / 3)
        assert view.hosts()[peers[0]]["state"] == "down"
        # the flip took exactly fleet_down_errors consecutive misses — the
        # "down within 2 poll intervals" contract (journal carries it)
        evs = [e for e in journal_mod.events(since=j0, cat="fleet")["events"]
               if e.get("host") == peers[0]]
        assert [e["event"] for e in evs][:2] == ["host-stale", "host-down"]
        assert evs[1]["errors"] == 2
        # 100% of subsequent admits route to the survivors, journaled
        targets = [router.admit("app", tenant=f"t{i}")["host"]
                   for i in range(10)]
        assert set(targets) <= {peers[1], peers[2]}
        routed = [e for e in journal_mod.events(since=j0,
                                                cat="fleet")["events"]
                  if e["event"] == "route"]
        assert len(routed) >= 10
        assert all(e["host"] != peers[0] for e in routed)
        # the doctor report carries the fleet section with the down verdict
        from futuresdr_tpu.telemetry import doctor as doc
        rep = doc.doctor().report(events=[])
        assert rep["fleet"]["states"]["down"] == [peers[0]]
        assert any(x["verdict"] == "host-down"
                   for x in rep["fleet"]["verdicts"])
    finally:
        if cp is not None:
            cp.stop()
        fleet.shutdown()
        os.environ.pop("FUTURESDR_TPU_FLEET_PEERS", None)
        os.environ.pop("FUTURESDR_TPU_FLEET_POLL_INTERVAL", None)
        from futuresdr_tpu.config import reload_config
        reload_config()
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(timeout=10)
