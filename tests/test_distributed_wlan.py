"""Distributed flowgraphs: WLAN TX in one runtime → ZMQ sample transport → RX in
another (the reference's inter-process distribution story: zeromq blocks carrying IQ
between runtimes, SURVEY §2.7)."""

import time

import numpy as np

from futuresdr_tpu import Flowgraph, Runtime, Pmt
from futuresdr_tpu.blocks import Apply, PubSink, SubSource, Throttle
from futuresdr_tpu.models.wlan import WlanDecoder, WlanEncoder


def test_wlan_over_zmq_between_runtimes():
    addr = "tcp://127.0.0.1:28123"
    rng = np.random.default_rng(0)

    # RX runtime: SUB → noisy channel → WLAN decoder
    fg_rx = Flowgraph()
    sub = SubSource(addr, np.complex64)
    chan = Apply(lambda x: (x + 0.01 * (rng.standard_normal(len(x))
                                        + 1j * rng.standard_normal(len(x)))
                            ).astype(np.complex64), np.complex64)
    dec = WlanDecoder(chunk=1 << 14)
    fg_rx.connect(sub, chan, dec)
    rt_rx = Runtime()
    running_rx = rt_rx.start(fg_rx)

    # TX runtime: encoder → throttle (outlive the ZMQ slow-joiner) → PUB
    fg_tx = Flowgraph()
    enc = WlanEncoder("qpsk_1_2", gap_samples=2000)
    thr = Throttle(np.complex64, rate=3e5)
    pub = PubSink(addr, np.complex64)
    fg_tx.connect(enc, thr, pub)
    rt_tx = Runtime()
    running_tx = rt_tx.start(fg_tx)

    payloads = [f"distributed frame {i}".encode() * 3 for i in range(6)]
    deadline = time.time() + 30
    sent = set()
    # keep retransmitting until the receiver confirms every payload (PUB/SUB is lossy
    # during join; the set() comparison tolerates the resulting repeats)
    while time.time() < deadline and len(set(dec.frames)) < len(payloads):
        for p in payloads:
            rt_tx.scheduler.run_coro_sync(running_tx.handle.call(enc, "tx",
                                                                 Pmt.blob(p)))
        time.sleep(1.0)
    got = set(dec.frames)
    running_tx.stop_sync()
    running_rx.stop_sync()
    assert set(payloads).issubset(got), f"missing: {set(payloads) - got}"
