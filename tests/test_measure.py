"""run_marginal: the honest scan-marginal throughput harness (docs/tpu_notes.md)."""
import numpy as np

from futuresdr_tpu.ops import fir_stage
from futuresdr_tpu.ops.stages import Pipeline
from futuresdr_tpu.utils.measure import run_marginal


def test_run_marginal_positive_rate():
    rng = np.random.default_rng(0)
    taps = rng.standard_normal(32).astype(np.float32)
    pipe = Pipeline([fir_stage(taps)], np.float32)
    x = rng.standard_normal(1 << 16).astype(np.float32)
    import jax
    rate = run_marginal(pipe.fn(), jax.device_put(pipe.init_carry()),
                        jax.device_put(x), k_pair=(4, 64), reps=2)
    assert rate > 0


def test_pipeline_roofline_accounting():
    """utils/roofline: XLA cost analysis per fused prefix; stage numbers are
    differences, totals match the full program, and rate_sps fills in the
    achieved-flops fields (mfu only on backends with a known peak)."""
    import numpy as np
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fft_stage, fir_stage, mag2_stage
    from futuresdr_tpu.utils.roofline import pipeline_roofline

    stages = [fir_stage(firdes.lowpass(0.2, 64).astype(np.float32)),
              fft_stage(1024), mag2_stage()]
    r = pipeline_roofline(stages, np.complex64, 1 << 16, rate_sps=1e6,
                          backend="cpu")
    assert [s["name"] for s in r["stages"]] == ["fir", "fft1024", "mag2"]
    assert r["flops_per_sample"] > 50            # an FFT chain is not free
    assert r["bytes_per_sample"] >= 12           # >= read cx64 + write f32
    total = sum(s["flops_per_sample"] for s in r["stages"])
    assert abs(total - r["flops_per_sample"]) < 1e-6
    assert r["achieved_flops"] == 1e6 * r["flops_per_sample"]
    assert "mfu" not in r                        # no public CPU peak
    r2 = pipeline_roofline(stages, np.complex64, 1 << 16, rate_sps=1e9,
                           backend="tpu")
    assert 0 < r2["mfu"] < 1 and "bound" in r2["stages"][0]
