"""run_marginal: the honest scan-marginal throughput harness (docs/tpu_notes.md)."""
import numpy as np

from futuresdr_tpu.ops import fir_stage
from futuresdr_tpu.ops.stages import Pipeline
from futuresdr_tpu.utils.measure import run_marginal


def test_run_marginal_positive_rate():
    rng = np.random.default_rng(0)
    taps = rng.standard_normal(32).astype(np.float32)
    pipe = Pipeline([fir_stage(taps)], np.float32)
    x = rng.standard_normal(1 << 16).astype(np.float32)
    import jax
    rate = run_marginal(pipe.fn(), jax.device_put(pipe.init_carry()),
                        jax.device_put(x), k_pair=(4, 64), reps=2)
    assert rate > 0
