"""run_marginal: the honest scan-marginal throughput harness (docs/tpu_notes.md)."""
import numpy as np
import pytest

from futuresdr_tpu.ops import fir_stage
from futuresdr_tpu.ops.stages import Pipeline
from futuresdr_tpu.utils.measure import run_marginal


def test_run_marginal_positive_rate():
    rng = np.random.default_rng(0)
    taps = rng.standard_normal(32).astype(np.float32)
    pipe = Pipeline([fir_stage(taps)], np.float32)
    x = rng.standard_normal(1 << 16).astype(np.float32)
    import jax
    rate = run_marginal(pipe.fn(), jax.device_put(pipe.init_carry()),
                        jax.device_put(x), k_pair=(4, 64), reps=2)
    assert rate > 0


def test_pipeline_roofline_accounting():
    """utils/roofline: XLA cost analysis per fused prefix; stage numbers are
    differences, totals match the full program, and rate_sps fills in the
    achieved-flops fields (mfu only on backends with a known peak)."""
    import numpy as np
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fft_stage, fir_stage, mag2_stage
    from futuresdr_tpu.utils.roofline import pipeline_roofline

    stages = [fir_stage(firdes.lowpass(0.2, 64).astype(np.float32)),
              fft_stage(1024), mag2_stage()]
    r = pipeline_roofline(stages, np.complex64, 1 << 16, rate_sps=1e6,
                          backend="cpu")
    assert [s["name"] for s in r["stages"]] == ["fir", "fft1024", "mag2"]
    assert r["flops_per_sample"] > 50            # an FFT chain is not free
    assert r["bytes_per_sample"] >= 12           # >= read cx64 + write f32
    total = sum(s["flops_per_sample"] for s in r["stages"])
    assert abs(total - r["flops_per_sample"]) < 1e-6
    assert r["achieved_flops"] == 1e6 * r["flops_per_sample"]
    assert "mfu" not in r                        # no public CPU peak
    r2 = pipeline_roofline(stages, np.complex64, 1 << 16, rate_sps=1e9,
                           backend="tpu")
    assert 0 < r2["mfu"] < 1 and "bound" in r2["stages"][0]


def test_roofline_decimating_stage():
    """A decimating FIR's roofline attribution: the per-stage prefix math
    holds through a rate change (the prefix output shrinks by the decimation
    factor), and the downstream stage is charged at its own (reduced) rate —
    per-sample numbers stay per REGION-INPUT sample."""
    import numpy as np
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, mag2_stage
    from futuresdr_tpu.utils.roofline import pipeline_roofline

    taps = firdes.lowpass(0.1, 64).astype(np.float32)
    stages = [fir_stage(taps, decim=4, name="decim4"), mag2_stage()]
    r = pipeline_roofline(stages, np.complex64, 1 << 16, backend="tpu")
    assert [s["name"] for s in r["stages"]] == ["decim4", "mag2"]
    assert all(s["flops_per_sample"] > 0 for s in r["stages"])
    assert r["stages"][0]["bytes_per_sample"] > 0
    # mag2's MARGINAL bytes may legitimately be <= 0: fusing |x|² onto the
    # decimator replaces the prefix's materialized complex output with a
    # quarter-rate f32 one — the prefix-difference charges that saving to
    # the stage that caused it. Totals stay positive and consistent.
    assert r["bytes_per_sample"] > 0
    # the decimator dominates: mag2 runs on 1/4 of the samples
    assert r["stages"][0]["flops_per_sample"] > \
        r["stages"][1]["flops_per_sample"]
    total = sum(s["flops_per_sample"] for s in r["stages"])
    assert abs(total - r["flops_per_sample"]) < 1e-6
    assert r["stages"][0]["bound"] in ("hbm", "compute")


def test_graph_roofline_fanout_per_node():
    """graph_roofline on a FanoutPipeline: one node per producer/branch,
    per-node differences sum to the full program's totals, and rate_sps
    fills the achieved/mfu fields exactly like the linear form."""
    import numpy as np
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, mag2_stage
    from futuresdr_tpu.ops.stages import FanoutPipeline
    from futuresdr_tpu.utils.roofline import graph_roofline

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    t2 = firdes.lowpass(0.1, 16).astype(np.float32)
    fo = FanoutPipeline([fir_stage(taps, name="prod")],
                        [[mag2_stage()], [fir_stage(t2, decim=4, name="b1")]],
                        np.complex64)
    r = graph_roofline(fo, 1 << 14, rate_sps=1e6, backend="tpu")
    assert [(n["name"], n["inputs"]) for n in r["nodes"]] == \
        [("prod", []), ("mag2", [0]), ("b1", [0])]
    total = sum(n["flops_per_sample"] for n in r["nodes"])
    assert abs(total - r["flops_per_sample"]) < 1e-6
    assert r["nodes"][0]["flops_per_sample"] > 0
    assert 0 < r["mfu"] < 1
    assert all(n["bound"] in ("hbm", "compute") for n in r["nodes"])


def test_graph_roofline_dag_diamond():
    """graph_roofline on a DagPipeline diamond (producer → {a, b} → merge):
    every node gets an attribution entry in topological order and the merge
    node is charged only its own marginal cost."""
    import numpy as np
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage, mag2_stage
    from futuresdr_tpu.ops.stages import DagPipeline, add_merge_stage
    from futuresdr_tpu.utils.roofline import graph_roofline

    taps = firdes.lowpass(0.2, 32).astype(np.float32)
    dag = DagPipeline([
        ([fir_stage(taps, name="prod")], []),
        ([fir_stage(taps, name="a")], [0]),
        ([fir_stage(taps, name="b")], [0]),
        ([add_merge_stage(2), mag2_stage()], [1, 2]),
    ], np.complex64)
    r = graph_roofline(dag, 1 << 14, backend="cpu")
    assert [n["inputs"] for n in r["nodes"]] == [[], [0], [0], [1, 2]]
    assert r["nodes"][3]["name"] == "add_merge+mag2"
    total = sum(n["flops_per_sample"] for n in r["nodes"])
    assert abs(total - r["flops_per_sample"]) < 1e-6
    # the two interior FIR branches cost the same program delta
    assert r["nodes"][1]["flops_per_sample"] == \
        pytest.approx(r["nodes"][2]["flops_per_sample"], rel=0.2)
    assert "mfu" not in r                       # cpu backend: no known peak


def test_cost_of_signature_cache_reuses_records():
    """cost_of caches by signature: the second ask never compiles (callable
    untouched), and an already-compiled executable can seed the record."""
    from futuresdr_tpu.utils.roofline import cost_of

    class _FakeCompiled:
        def cost_analysis(self):
            return {"flops": 42.0, "bytes accessed": 7.0}

    sig = ("test-cost-cache", id(object()))
    out = cost_of(None, signature=sig, compiled=_FakeCompiled())
    assert out == {"flops": 42.0, "bytes": 7.0}
    # cached: fn=None would explode if the cache missed
    assert cost_of(None, signature=sig) == out


def test_cost_of_bills_reason_cost():
    """An ACTUAL cost-analysis AOT compile bills
    fsdr_compiles_total{program="cost_analysis",reason="cost"}; cache hits
    and compiled= reuse bill nothing."""
    from futuresdr_tpu.telemetry import profile
    from futuresdr_tpu.utils.roofline import cost_of

    before = profile.COMPILES.get(program="cost_analysis", reason="cost")
    sig = ("test-cost-billing", id(object()))
    cost_of(lambda x: x + 1, np.zeros(8, np.float32), signature=sig)
    assert profile.COMPILES.get(program="cost_analysis",
                                reason="cost") == before + 1
    cost_of(None, signature=sig)          # cache hit: no new record
    assert profile.COMPILES.get(program="cost_analysis",
                                reason="cost") == before + 1


def test_program_cost_signature_disambiguates_stage_params():
    """Cost-cache signatures carry the structural stage fingerprint, not
    just names: fir stages with different tap counts / decimation (all
    named "fir") must not share one cost record."""
    from futuresdr_tpu.dsp import firdes
    from futuresdr_tpu.ops import fir_stage
    from futuresdr_tpu.ops.stages import Pipeline
    from futuresdr_tpu.utils.roofline import _stage_marker, program_cost

    t64 = firdes.lowpass(0.2, 64).astype(np.float32)
    t256 = firdes.lowpass(0.2, 256).astype(np.float32)
    # the fingerprint separates tap count and decimation where the name
    # alone ("fir" for all three) would collide in the cache
    m64 = _stage_marker(fir_stage(t64))
    m256 = _stage_marker(fir_stage(t256))
    m256d = _stage_marker(fir_stage(t256, decim=4))
    assert len({m64, m256, m256d}) == 3
    # and a cost determinant that DOES change the program (decimation: 4x
    # fewer output samples) yields a different record, not the full-rate
    # pipeline's cached one
    frame = 1 << 12
    full = program_cost(Pipeline([fir_stage(t256)], np.complex64), frame)
    decim = program_cost(Pipeline([fir_stage(t256, decim=4)], np.complex64),
                         frame)
    assert decim["bytes"] < full["bytes"]
