"""Telemetry subsystem: span recorder, Prometheus registry, control-port
endpoints, the supervisor post-close MetricsMsg drain, and the disabled-path
overhead gate (tier-1 acceptance: ≤ ~3% on a null_rand actor chain)."""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from futuresdr_tpu.telemetry import prom, spans
from futuresdr_tpu.telemetry.spans import SpanEvent, SpanRecorder


@pytest.fixture
def tracing():
    """Enable span recording for the test; drain + restore after."""
    rec = spans.recorder()
    was = rec.enabled
    rec.enabled = True
    rec.drain()
    yield rec
    rec.enabled = was
    rec.drain()


# ---------------------------------------------------------------------------
# span recorder units
# ---------------------------------------------------------------------------

def test_disabled_recorder_records_nothing():
    rec = SpanRecorder(capacity=64, enabled=False)
    rec.complete("cat", "a", rec.now())
    rec.instant("cat", "b")
    with rec.span("cat", "c"):
        pass
    assert rec.drain() == []


def test_complete_and_instant_events():
    rec = SpanRecorder(capacity=64, enabled=True)
    t0 = rec.now()
    rec.complete("tpu", "H2D", t0, args={"bytes": 7})
    rec.instant("runtime", "terminate_cascade")
    evs = rec.drain()
    assert [e.name for e in evs] == ["H2D", "terminate_cascade"]
    h2d, inst = evs
    assert h2d.cat == "tpu" and h2d.dur_ns >= 0 and h2d.args == {"bytes": 7}
    assert inst.dur_ns is None
    assert rec.drain() == []            # drain cleared the ring


def test_span_context_manager_measures():
    rec = SpanRecorder(capacity=64, enabled=True)
    with rec.span("cat", "sleepy", tag=1):
        time.sleep(0.01)
    (e,) = rec.drain()
    assert e.name == "sleepy" and e.args == {"tag": 1}
    assert e.dur_ns >= 8e6              # ≥ 8 ms recorded for a 10 ms sleep


def test_ring_bounds_and_drop_accounting():
    rec = SpanRecorder(capacity=16, enabled=True)
    for i in range(50):
        rec.complete("c", f"e{i}", rec.now())
    evs = rec.drain()
    assert len(evs) == 16
    # ring keeps the newest events, oldest-first on drain
    assert [e.name for e in evs] == [f"e{i}" for i in range(34, 50)]
    assert rec.dropped == 34


def test_thread_aware_rings():
    rec = SpanRecorder(capacity=64, enabled=True)

    def record():
        rec.complete("c", "worker", rec.now())

    t = threading.Thread(target=record, name="span-worker")
    t.start()
    t.join()
    rec.complete("c", "main", rec.now())
    evs = rec.drain()
    by_name = {e.name: e for e in evs}
    assert by_name["worker"].tid != by_name["main"].tid
    assert by_name["worker"].thread == "span-worker"


def test_chrome_trace_export_shape(tmp_path):
    rec = SpanRecorder(capacity=64, enabled=True)
    t0 = rec.now()
    rec.complete("tpu", "compute", t0, args={"frame": 8})
    rec.instant("jit", "sp_trace")
    doc = json.loads(json.dumps(rec.chrome_trace()))   # JSON-serializable
    evs = doc["traceEvents"]
    x = next(e for e in evs if e["ph"] == "X")
    assert x["name"] == "compute" and x["dur"] >= 0 and "ts" in x
    assert any(e["ph"] == "i" for e in evs)
    meta = [e for e in evs if e["ph"] == "M"]
    assert meta and meta[0]["name"] == "thread_name"
    # export writes the same document
    rec.complete("tpu", "compute", rec.now())
    path = rec.export(str(tmp_path / "t.json"))
    assert json.load(open(path))["traceEvents"]


def test_snapshot_is_non_destructive():
    rec = SpanRecorder(capacity=64, enabled=True)
    rec.complete("c", "a", rec.now())
    snap = rec.snapshot()
    assert [e.name for e in snap] == ["a"]
    assert [e.name for e in rec.snapshot()] == ["a"]   # still there
    assert [e.name for e in rec.drain()] == ["a"]      # drain still sees it
    assert rec.snapshot() == []


def test_dead_thread_rings_pruned_after_drain():
    rec = SpanRecorder(capacity=64, enabled=True)

    def record():
        rec.complete("c", "from_dead_thread", rec.now())

    t = threading.Thread(target=record)
    t.start()
    t.join()
    assert len(rec._rings) == 1
    evs = rec.drain()                   # events survive the thread's death...
    assert [e.name for e in evs] == ["from_dead_thread"]
    assert rec._rings == []             # ...then the dead ring is unregistered


def test_d2h_parts_billed_as_one_transfer(tracing):
    """A multi-part frame (complex f32-pair wire, quantized formats' scale+
    payload) must count as ONE D2H transfer and one lane span — symmetric with
    the H2D side — or counters and per-lane span counts would scale with the
    wire's part count instead of the frame count."""
    import jax.numpy as jnp

    from futuresdr_tpu.ops import xfer
    before = xfer._XFER_TRANSFERS.get(direction="d2h")
    parts = (jnp.zeros(64, jnp.float32), jnp.zeros(64, jnp.float32))
    out = xfer.start_host_transfer_parts(parts)()
    assert len(out) == 2
    assert xfer._XFER_TRANSFERS.get(direction="d2h") == before + 1
    d2h = [e for e in tracing.drain() if e.name == "D2H"]
    assert len(d2h) == 1 and d2h[0].args["bytes"] == 512


def test_union_and_overlap_arithmetic():
    assert spans.union_ns([]) == 0
    assert spans.union_ns([(0, 10), (5, 15), (20, 30)]) == 25
    mk = lambda name, s, e: SpanEvent(1, "t", s, e - s, "tpu", name, None)
    serial = [mk("H2D", 0, 10), mk("compute", 10, 20), mk("D2H", 20, 30)]
    rep = spans.overlap_report(serial)
    assert rep["ratio"] == pytest.approx(1.0)
    overlapped = [mk("H2D", 0, 10), mk("compute", 0, 10), mk("D2H", 0, 10)]
    rep = spans.overlap_report(overlapped)
    assert rep["ratio"] == pytest.approx(1 / 3)
    assert rep["lanes"]["H2D"]["spans"] == 1


# ---------------------------------------------------------------------------
# prometheus registry + exposition
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^(#.*|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE.+-]+|"
    r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [+-]?(Inf|NaN))$")


def _assert_valid_exposition(text: str):
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"invalid exposition line: {line!r}"


def test_registry_counter_gauge_render():
    reg = prom.Registry()
    c = reg.counter("t_bytes_total", "bytes", ("direction",))
    c.inc(10, direction="h2d")
    c.inc(5, direction="h2d")
    c.inc(3, direction="d2h")
    g = reg.gauge("t_snr_db", "snr", ("wire",))
    g.set(float("inf"), wire="f32")
    g.set(-90.5, wire="sc16")
    text = reg.render()
    _assert_valid_exposition(text)
    assert '# TYPE t_bytes_total counter' in text
    assert 't_bytes_total{direction="h2d"} 15' in text
    assert 't_snr_db{wire="f32"} +Inf' in text
    assert 't_snr_db{wire="sc16"} -90.5' in text
    assert c.get(direction="h2d") == 15


def test_registry_rejects_redefinition_and_bad_labels():
    reg = prom.Registry()
    reg.counter("x_total", "", ("a",))
    with pytest.raises(ValueError, match="re-registered"):
        reg.gauge("x_total", "", ("a",))
    with pytest.raises(ValueError, match="expected labels"):
        reg.counter("x_total", "", ("a",)).inc(b=1)
    with pytest.raises(ValueError, match="only go up"):
        reg.counter("x_total", "", ("a",)).inc(-1, a="v")


def test_render_block_metrics_families():
    fg_metrics = {0: {
        "TpuKernel_1": {
            "work_calls": 3, "work_time_s": 0.25, "messages_handled": 0,
            "items_in": {"in": 100}, "items_out": {"out": 50},
            "buffer_fill": {"in": 0.5}, "stalls": {"out": 2},
            "starved": {"in": 1},
            "frames_in_flight": 4,          # numeric extra → _extra gauge
            "wire": "sc16",                 # string extra  → _attr sample
        },
    }}
    text = prom.render_block_metrics(fg_metrics)
    _assert_valid_exposition(text)
    assert 'fsdr_block_work_calls_total{block="TpuKernel_1",fg="0"} 3' in text
    assert 'fsdr_block_items_in_total{block="TpuKernel_1",fg="0",port="in"} 100' in text
    assert 'fsdr_block_buffer_fill_ratio{block="TpuKernel_1",fg="0",port="in"} 0.5' in text
    assert 'fsdr_block_buffer_stalls_total{block="TpuKernel_1",fg="0",port="out"} 2' in text
    assert 'fsdr_block_starved_total' in text or \
        'fsdr_block_buffer_starved_total' in text
    assert 'key="frames_in_flight"' in text
    assert 'value="sc16"' in text


def test_label_escaping():
    reg = prom.Registry()
    g = reg.gauge("esc", "", ("k",))
    g.set(1, k='a"b\\c\nd')
    text = reg.render()
    assert r'k="a\"b\\c\nd"' in text


# ---------------------------------------------------------------------------
# instrumentation end-to-end: spans from a flowgraph run
# ---------------------------------------------------------------------------

def test_flowgraph_run_records_runtime_and_block_spans(tracing):
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Copy, VectorSink, VectorSource
    fg = Flowgraph()
    src = VectorSource(np.zeros(65536, np.float32))
    cp = Copy(np.float32)
    snk = VectorSink(np.float32)
    fg.connect(src, cp, snk)
    Runtime().run(fg)
    evs = tracing.drain()
    cats = {(e.cat, e.name) for e in evs}
    assert ("runtime", "init_barrier") in cats
    assert ("runtime", "flowgraph") in cats
    # block spans for actor-run blocks OR one fastchain span when fused
    assert any(c == "block" for c, _ in cats) or \
        any(c == "fastchain" for c, _ in cats)
    barrier = next(e for e in evs if e.name == "init_barrier")
    total = next(e for e in evs if e.name == "flowgraph")
    assert barrier.args["blocks"] == 3 and total.args["errors"] == 0
    assert total.dur_ns >= barrier.dur_ns


def test_buffer_stall_and_starve_counters(monkeypatch):
    """A throttled consumer backpressures the producer (stalls on its output),
    and a starved consumer counts starved parks on its input."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")   # the counters live in the
    from futuresdr_tpu import Flowgraph, Runtime   # Python actor event loop
    from futuresdr_tpu.blocks import Head, NullSink, NullSource, Throttle
    fg = Flowgraph()
    src = NullSource(np.float32)
    head = Head(np.float32, 2_000_000)
    thr = Throttle(np.float32, rate=4e6)
    snk = NullSink(np.float32)
    fg.connect(src, head, thr, snk)
    fg_done = Runtime().run(fg)
    m = {b.kernel.meta.instance_name: b.metrics()
         for b in map(fg_done.wrapped, (src, head, thr, snk))}
    stalls = sum(sum(v["stalls"].values()) for v in m.values())
    starved = sum(sum(v["starved"].values()) for v in m.values())
    assert stalls > 0, m        # the throttle backpressured someone upstream
    assert starved > 0, m       # and starved someone downstream
    assert all("buffer_fill" in v for v in m.values())


# ---------------------------------------------------------------------------
# control port: /metrics, /api/fg/{fg}/trace/, CORS on raised errors
# ---------------------------------------------------------------------------

def _start_live_fg():
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import NullSink, NullSource
    fg = Flowgraph()
    fg.connect(NullSource(np.float32), NullSink(np.float32))
    rt = Runtime()
    running = rt.start(fg)
    return rt, running


def test_ctrl_port_prometheus_and_trace_endpoints(tracing):
    from aiohttp import web

    from futuresdr_tpu.ops import xfer                    # noqa: F401 —
    # importing registers the link-plane counters in the global registry
    from futuresdr_tpu.runtime.ctrl_port import ControlPort

    async def failing_route(request):
        raise web.HTTPNotFound(text="nope")

    rt, running = _start_live_fg()
    cp = ControlPort(rt.handle, bind="127.0.0.1:29471",
                     extra_routes=[("GET", "/fail/", failing_route)])
    cp.start()
    base = "http://127.0.0.1:29471"
    try:
        # ---- /metrics: valid exposition with the per-block families -------
        deadline = time.perf_counter() + 10.0
        text = ""
        while time.perf_counter() < deadline:
            text = urllib.request.urlopen(base + "/metrics").read().decode()
            if "fsdr_block_work_calls_total" in text and \
                    re.search(r'fsdr_block_work_calls_total{[^}]*} [1-9]', text):
                break
            time.sleep(0.02)
        _assert_valid_exposition(text)
        assert re.search(r'fsdr_block_work_calls_total{[^}]*} [1-9]', text)
        assert "fsdr_block_buffer_fill_ratio" in text     # occupancy gauge
        assert "fsdr_block_buffer_stalls_total" in text   # stall counters
        assert "fsdr_block_items_out_total" in text
        assert "fsdr_xfer_bytes_total" in text            # registry counters

        # ---- /api/fg/{fg}/trace/: drains the ring as Chrome trace JSON ----
        tracing.complete("tpu", "H2D", tracing.now(), args={"bytes": 1})
        # ?keep=1 peeks without stealing events from other trace consumers
        peek = json.load(urllib.request.urlopen(
            base + "/api/fg/0/trace/?keep=1"))
        assert any(e.get("name") == "H2D" for e in peek["traceEvents"])
        doc = json.load(urllib.request.urlopen(base + "/api/fg/0/trace/"))
        assert any(e.get("name") == "H2D" for e in doc["traceEvents"])
        # drained: a second scrape no longer carries it
        doc2 = json.load(urllib.request.urlopen(base + "/api/fg/0/trace/"))
        assert not any(e.get("name") == "H2D" for e in doc2["traceEvents"])
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/api/fg/99/trace/")
        assert ei.value.code == 404

        # ---- CORS adorns RAISED error responses too (middleware fix) -----
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/fail/")
        assert ei.value.code == 404
        assert ei.value.headers["Access-Control-Allow-Origin"] == "*"
        # and non-error responses keep it
        r = urllib.request.urlopen(base + "/api/fg/")
        assert r.headers["Access-Control-Allow-Origin"] == "*"
    finally:
        running.stop_sync()
        cp.stop()


# ---------------------------------------------------------------------------
# supervisor post-close drain: MetricsMsg must be answered (satellite fix)
# ---------------------------------------------------------------------------

def test_metrics_racing_completion_gets_final_snapshot():
    """A MetricsMsg queued just before the supervisor closes its inbox (the
    metrics()-vs-completion race) must be answered with the final per-block
    snapshot — pre-fix it was silently dropped and the caller awaited forever."""
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import Copy, VectorSink, VectorSource
    from futuresdr_tpu.runtime.inbox import ReplySlot
    from futuresdr_tpu.runtime.runtime import MetricsMsg

    rt = Runtime()
    # the monkeypatch itself races flowgraph completion: on a loaded box the
    # supervisor can reach fg_inbox.close() before the patch below lands, and
    # the racer message is never sent at all (`armed` stays clear).  That run
    # did not exercise the race window — rebuild and try again
    for _ in range(20):
        fg = Flowgraph()
        src = VectorSource(np.zeros(10_000, np.float32))
        cp = Copy(np.float32)
        snk = VectorSink(np.float32)
        fg.connect(src, cp, snk)
        running = rt.start(fg)
        inbox = running.handle._inbox
        reply = ReplySlot()
        orig_close = inbox.close
        armed = threading.Event()

        def close_with_racer():
            # enqueue while the inbox is still open — exactly the race window:
            # sent before close, drained after the main loop already exited
            inbox.send(MetricsMsg(reply))
            armed.set()
            orig_close()

        inbox.close = close_with_racer
        running.wait_sync()
        if armed.is_set():
            break
    else:
        pytest.fail("patched close never won the race against completion")

    async def get():
        import asyncio
        return await asyncio.wait_for(reply.get(), timeout=10.0)

    snapshot = rt.scheduler.run_coro_sync(get())
    assert isinstance(snapshot, dict) and len(snapshot) == 3
    assert any(v.get("work_calls", 0) > 0 for v in snapshot.values())


# ---------------------------------------------------------------------------
# overhead gate (tier-1 acceptance): telemetry disabled ≤ ~3% on null_rand
# ---------------------------------------------------------------------------

def _null_rand_chain(samples=1_000_000, stages=3, max_copy=2048):
    from futuresdr_tpu import Flowgraph, Runtime
    from futuresdr_tpu.blocks import CopyRand, Head, NullSink, NullSource
    fg = Flowgraph()
    blocks = [NullSource(np.float32), Head(np.float32, samples)]
    fg.connect(blocks[0], blocks[1])
    last = blocks[1]
    for s in range(stages):
        c = CopyRand(np.float32, max_copy=max_copy, seed=1 + s)
        fg.connect(last, c)
        blocks.append(c)
        last = c
    snk = NullSink(np.float32)
    fg.connect(last, snk)
    blocks.append(snk)
    t0 = time.perf_counter()
    done = Runtime().run(fg)
    elapsed = time.perf_counter() - t0
    calls = sum(done.wrapped(b).work_calls for b in blocks)
    return elapsed, calls


def test_telemetry_disabled_overhead_null_rand(monkeypatch):
    """The ≤ ~3% gate, measured on the REAL null_rand actor chain — with the
    doctor watchdog armed at its default interval (the flowgraph-doctor PR
    extends the gate: always-on diagnosis must ride inside the same budget),
    the device-plane recovery PR's disabled checkpoint hook billed as a
    third per-call cost (checkpoint_every=0 must be free), and the profile
    plane's dispatch-unit counter billed as a fourth (live MFU attribution
    must ride inside the same budget too), the lineage plane's per-frame
    sample draw billed as a fifth (frame-lineage tracing at the default
    stride must ride inside the same budget as well), and the fleet plane's
    per-step tick billed as a sixth (the cross-host plane off by default
    must be one falsy check).

    The per-work-call cost of the disabled telemetry path (the `if
    rec.enabled:` guard, the ns-clock reads the loop already paid
    pre-telemetry, AND the doctor's per-call work-duration histogram observe)
    is micro-measured directly, then multiplied by the chain's actual
    work-call rate: `hook_cost × calls / elapsed` IS the fraction of the
    no-telemetry baseline the instrumentation costs. An interleaved
    wall-clock A/B at 3% precision would gate on CI noise instead
    (VERDICT item 3's instability bar exists for exactly that reason); the
    analytic bound is deterministic and measures the same thing. The
    watchdog itself samples at 1 Hz off the hot path — its cost shows up (if
    at all) in the measured chain elapsed, not in the per-call hook.
    """
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")  # the hooks live in the
    rec = spans.recorder()                        # Python actor event loop
    assert not rec.enabled, "gate must measure the DISABLED path"
    from futuresdr_tpu.telemetry import doctor as doc
    hist = doc.WORK_DURATION.labels(block="overhead-gate-probe")

    # per-call disabled-path cost, billed separately per site: a WORK call
    # pays guard + end-clock read + the work-duration histogram observe; a
    # PARK pays only the guard (runtime/block.py) — parks ≈ work calls at
    # worst, so the chain pays one of each per call
    n = 200_000

    def best_of(loop):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter_ns()
            loop()
            best = min(best, (time.perf_counter_ns() - t0) / n)
        return best

    def work_hook():
        for _ in range(n):
            if rec.enabled:                       # pragma: no cover
                rec.complete("block", "x", 0)
            hist.observe_sampled(1.5e-6)          # the work-duration observe
            time.perf_counter_ns()                # the end-timestamp read

    def park_hook():
        for _ in range(n):
            if rec.enabled:                       # pragma: no cover
                rec.complete("park", "x", 0)

    # checkpoint hook (device-plane recovery, tpu/kernel_block.py): with
    # checkpoint_every=0 the per-dispatch _checkpoint_tick must be one falsy
    # check — billed here as a THIRD per-call hook even though the host chain
    # never dispatches (a conservative over-count: the real rate is one tick
    # per device dispatch group, far below the work-call rate)
    from futuresdr_tpu.ops import mag2_stage
    from futuresdr_tpu.tpu import TpuKernel
    tk = TpuKernel([mag2_stage()], np.complex64, frame_size=1 << 12,
                   checkpoint_every=0)
    assert tk._ckpt_every == 0
    tick = tk._checkpoint_tick

    def ckpt_hook():
        for _ in range(n):
            tick(0)

    # profile-plane dispatch hook (telemetry/profile.py): the live-roofline
    # unit counter every kernel dispatch bills — a FOURTH per-call hook
    # class, again a conservative over-count (the real rate is one call per
    # dispatch GROUP, far below the work-call rate). One priming call first:
    # the first dispatch seeds the run-average window and swaps in the
    # steady-state hook — a bare counter add; the t_last group stamp is the
    # dispatch SITE's own clock, passed as t=, and real sites run at group
    # rate — which is what every later call pays
    from futuresdr_tpu.telemetry import profile as prof_mod
    entry = prof_mod.register("overhead-gate-probe")
    entry.dispatch()
    dispatch = entry.dispatch

    def prof_hook():
        for _ in range(n):
            dispatch()

    # lineage sample hook (telemetry/lineage.py): the per-frame trace-id
    # draw at the DEFAULT 1-in-64 stride — a FIFTH per-call hook class,
    # again a conservative over-count (the real rate is one sample per
    # FRAME, far below the work-call rate). Like the checkpoint and
    # profile classes, the bill is the steady-state per-call guard — the
    # unlocked countdown the contract promises — with the heavy-but-rare
    # companion (the 1-in-64 record build + stamps, a few µs at 1/64 the
    # frame rate) landing at group rate like checkpoint commits and
    # profile window swaps. The loop still drains each sampled id through
    # finish() so the open-table bound rides inside the measurement.
    # Journal emits live at lifecycle decision sites, not on the
    # per-frame path, so they bill into `elapsed`, not per call.
    from futuresdr_tpu.telemetry import lineage as lin_mod
    ltr = lin_mod.reset_tracer()
    assert ltr.stride >= 2, "gate must measure the default sampled stride"
    sample = ltr.sample

    def lineage_hook():
        for _ in range(n):
            tid = sample()
            if tid:
                ltr.finish(tid)

    # fleet tick (telemetry/fleet.py): the serve engine's step() guards the
    # tick INLINE (`if _fleet._tick_state is not None:` — a module-global
    # read, no call frame) — a SIXTH per-call hook class, again a
    # conservative over-count (the real rate is one tick per serve
    # DISPATCH, far below the work-call rate). With fleet_peers unset the
    # guard is one falsy check, like the park guard; the enabled-path
    # summary build runs at poll cadence off this bill.
    from futuresdr_tpu.telemetry import fleet as fleet_mod
    assert fleet_mod._tick_state is None, \
        "gate must measure the fleet-disabled path"

    def fleet_hook():
        for _ in range(n):
            if fleet_mod._tick_state is not None:  # pragma: no cover
                fleet_mod.tick()

    # paired trials: hook micro-costs and the chain rate are measured back to
    # back INSIDE each trial, and the gate takes the best trial — a transient
    # load spike that inflates only one side of one trial (the structural
    # flake mode: hooks and chain are necessarily sampled at different
    # instants) cannot flip the verdict as long as one trial runs clean.
    # Up to 12 trials, breaking on the first clean one: contention bursts
    # on a shared box last seconds, and the pure-CPU micro-loops inflate
    # more than the chain elapsed (which includes parks) — a settle sleep
    # after each dirty trial stretches the escape window past burst length,
    # and the healthy path never sleeps
    trials = []
    for _ in range(12):
        if trials:
            time.sleep(1.0)
        work_ns, park_ns, ckpt_ns, prof_ns, lin_ns, fleet_ns = \
            best_of(work_hook), best_of(park_hook), best_of(ckpt_hook), \
            best_of(prof_hook), best_of(lineage_hook), best_of(fleet_hook)
        # the chain's real call rate, measured with the watchdog running at
        # its DEFAULT interval (1 Hz sampling lands in `elapsed`, not per
        # call)
        doc.enable()
        assert doc.enabled()
        try:
            elapsed, calls = _null_rand_chain()
        finally:
            doc.disable()
        overhead = calls * (work_ns + park_ns + ckpt_ns + prof_ns
                            + lin_ns + fleet_ns) * 1e-9 / elapsed
        trials.append((overhead, work_ns, park_ns, ckpt_ns, prof_ns,
                       lin_ns, fleet_ns, calls, elapsed))
        if overhead <= 0.03:
            break
    (overhead, work_ns, park_ns, ckpt_ns, prof_ns, lin_ns, fleet_ns,
     calls, elapsed) = min(trials)
    ltr.clear()
    assert overhead <= 0.03, (
        f"telemetry-disabled hooks cost {overhead * 100:.2f}% of the "
        f"null_rand chain ({calls} work calls, {work_ns:.0f}+{park_ns:.0f}"
        f"+{ckpt_ns:.0f}+{prof_ns:.0f}+{lin_ns:.0f}+{fleet_ns:.0f} ns/hook, "
        f"{elapsed:.3f}s elapsed; best of {len(trials)} paired trials)")


def test_telemetry_enabled_stays_cheap(tracing, monkeypatch):
    """Coarse guard, not the 3% gate: recording spans for every work call must
    not blow up the chain (ring pushes are O(100ns)); generous 1.5× bound so
    CI noise cannot flake it."""
    monkeypatch.setenv("FSDR_NO_FASTCHAIN", "1")
    tracing.enabled = False
    t_off, _ = _null_rand_chain(samples=500_000)
    tracing.enabled = True
    t_on, _ = _null_rand_chain(samples=500_000)
    tracing.drain()
    assert t_on <= 1.5 * t_off + 0.05, (t_on, t_off)
