"""DSP math golden tests vs numpy/scipy references (SURVEY §4: Mocker doubles as the
numeric golden-test harness; reference per-block tests like `tests/fir.rs` compare against
hand-computed convolution)."""

import numpy as np
import pytest
from scipy import signal as sps

from futuresdr_tpu.dsp import (firdes, windows, FirFilter, DecimatingFirFilter,
                               PolyphaseResamplingFir, IirFilter, Rotator)


def test_fir_matches_convolution_streaming():
    rng = np.random.default_rng(0)
    taps = firdes.lowpass(0.2, 64)
    x = rng.standard_normal(10_000).astype(np.float64)
    f = FirFilter(taps)
    # feed in uneven chunks; result must equal one-shot lfilter
    chunks = [x[:100], x[100:101], x[101:5000], x[5000:]]
    y = np.concatenate([f.process(c) for c in chunks])
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(y, ref, rtol=1e-12)


def test_fir_complex_input():
    taps = firdes.lowpass(0.1, 31)
    x = (np.random.default_rng(1).standard_normal((2, 1000)) * [[1], [1j]]).sum(0).astype(np.complex64)
    f = FirFilter(taps)
    y = f.process(x)
    ref = sps.lfilter(taps, 1.0, x)
    np.testing.assert_allclose(y, ref, rtol=1e-5, atol=1e-6)
    assert y.dtype == np.complex64


def test_decimating_fir_streaming():
    rng = np.random.default_rng(2)
    taps = firdes.lowpass(0.1, 32)
    x = rng.standard_normal(9_999)
    d = DecimatingFirFilter(taps, 4)
    y = np.concatenate([d.process(c) for c in np.array_split(x, 13)])
    ref = sps.lfilter(taps, 1.0, x)[::4]
    np.testing.assert_allclose(y, ref, rtol=1e-12)


@pytest.mark.parametrize("interp,decim", [(1, 1), (2, 1), (3, 2), (7, 5), (1, 4)])
def test_polyphase_resampler_vs_upfirdn(interp, decim):
    rng = np.random.default_rng(3)
    taps = firdes.lowpass(0.4 / max(interp, decim), 8 * interp + 1)
    x = rng.standard_normal(4_000)
    r = PolyphaseResamplingFir(interp, decim, taps)
    y = np.concatenate([r.process(c) for c in np.array_split(x, 11)])
    full = sps.upfirdn(taps, x, up=interp, down=decim)
    n = min(len(y), len(full))
    assert n >= len(x) * interp // decim - r.K
    np.testing.assert_allclose(y[:n], full[:n], rtol=1e-10, atol=1e-12)


def test_iir_streaming():
    b, a = sps.butter(4, 0.2)
    x = np.random.default_rng(4).standard_normal(5_000)
    f = IirFilter(b, a)
    y = np.concatenate([f.process(c) for c in np.array_split(x, 7)])
    np.testing.assert_allclose(y, sps.lfilter(b, a, x), rtol=1e-10)


def test_rotator_continuous_phase():
    x = np.ones(1000, dtype=np.complex64)
    r = Rotator(0.1)
    y = np.concatenate([r.process(x[:300]), r.process(x[300:])])
    ref = np.exp(1j * 0.1 * np.arange(1000))
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-4)


def test_lowpass_response():
    taps = firdes.lowpass(0.125, 101, "hamming")
    w, h = sps.freqz(taps, fs=1.0)
    gain = np.abs(h)
    assert gain[w < 0.09].min() > 0.97
    assert gain[w > 0.16].max() < 0.01
    assert abs(taps.sum() - 1.0) < 1e-9


def test_highpass_response():
    taps = firdes.highpass(0.25, 101)
    w, h = sps.freqz(taps, fs=1.0)
    gain = np.abs(h)
    assert gain[w < 0.2].max() < 0.01
    assert gain[w > 0.3].min() > 0.97


def test_bandpass_response():
    taps = firdes.bandpass(0.1, 0.2, 128)
    w, h = sps.freqz(taps, fs=1.0)
    gain = np.abs(h)
    inband = gain[(w > 0.12) & (w < 0.18)]
    assert inband.min() > 0.9
    assert gain[w < 0.06].max() < 0.02
    assert gain[w > 0.24].max() < 0.02


def test_kaiser_order_reasonable():
    # standard Kaiser estimate: N ≈ (A-7.95)/(2.285·2π·Δf) ≈ 73 for A=60dB, Δf=0.05
    n, beta = firdes.kaiser_order(60.0, 0.05)
    assert 60 < n < 90
    assert 5.0 < beta < 6.5


def test_rrc_unit_energy_and_symmetry():
    h = firdes.root_raised_cosine(8, 4, 0.35)
    assert abs(np.sum(h**2) - 1.0) < 1e-9
    np.testing.assert_allclose(h, h[::-1], atol=1e-12)


def test_hilbert_quadrature():
    h = firdes.hilbert(65)
    # feeding cos should give ~sin (90° shift) in steady state
    n = np.arange(1000)
    x = np.cos(2 * np.pi * 0.1 * n)
    y = sps.lfilter(h, 1.0, x)[200:800]
    ref = np.sin(2 * np.pi * 0.1 * (n - 32))[200:800]
    assert np.corrcoef(y, ref)[0, 1] > 0.99


def test_remez_design():
    taps = firdes.remez(64, [0, 0.1, 0.15, 0.5], [1, 0])
    w, h = sps.freqz(taps, fs=1.0)
    gain = np.abs(h)
    assert gain[w < 0.08].min() > 0.95
    assert gain[w > 0.17].max() < 0.05


def test_windows_shapes():
    for name in ["rect", "bartlett", "blackman", "hamming", "hann"]:
        w = windows.get_window(name, 64)
        assert len(w) == 64
    assert len(windows.kaiser(33, 8.6)) == 33
    assert len(windows.gaussian(33)) == 33
