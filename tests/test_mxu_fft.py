"""MXU four-step FFT: correctness of the matmul decomposition vs numpy's FFT.

CI runs on the CPU backend where the `auto` policy picks jnp.fft; these tests force the
MXU (matmul) implementation so the four-step math itself is validated everywhere. On a
real TPU the same code runs on the systolic array (measured in docs/tpu_notes.md).
"""
import numpy as np
import pytest

from futuresdr_tpu.ops import mxu_fft


@pytest.fixture
def force_mxu():
    mxu_fft.set_impl("mxu")
    yield
    mxu_fft.set_impl("auto")


@pytest.mark.parametrize("n", [256, 1024, 2048, 8192])
def test_fft_matches_numpy(force_mxu, n):
    rng = np.random.default_rng(3)
    x = (rng.standard_normal((4, n)) + 1j * rng.standard_normal((4, n))).astype(np.complex64)
    got = np.asarray(mxu_fft.fft(x))
    ref = np.fft.fft(x, axis=-1)
    assert np.abs(got - ref).max() / np.abs(ref).max() < 1e-4


@pytest.mark.parametrize("n", [256, 2048])
def test_ifft_roundtrip(force_mxu, n):
    rng = np.random.default_rng(4)
    x = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(np.complex64)
    y = np.asarray(mxu_fft.ifft(mxu_fft.fft(x)))
    assert np.abs(y - x).max() < 1e-4


def test_auto_policy_on_cpu_uses_xla():
    # on the CPU test backend auto must not take the matmul path (bit-exactness with
    # jnp.fft is part of the CPU contract)
    assert not mxu_fft._use_mxu(2048)


@pytest.mark.parametrize("n", [48, 100, 320])
def test_direct_dft_non_pow2(force_mxu, n):
    # small / non-pow2 sizes run as a direct [n, n] DFT matmul
    rng = np.random.default_rng(8)
    x = (rng.standard_normal((3, n)) + 1j * rng.standard_normal((3, n))).astype(np.complex64)
    got = np.asarray(mxu_fft.fft(x))
    ref = np.fft.fft(x, axis=-1)
    assert np.abs(got - ref).max() / np.abs(ref).max() < 1e-4


def test_fir_stage_mxu_matches_xla():
    """Overlap-save FIR must produce the same stream on the MXU-FFT path."""
    from futuresdr_tpu.ops import fir_stage
    rng = np.random.default_rng(5)
    taps = rng.standard_normal(64).astype(np.float32)

    def run(x):
        st = fir_stage(taps)
        carry = st.init_carry(x.dtype)
        outs = []
        frame = 1 << 14
        for i in range(0, len(x), frame):
            carry, y = st.fn(carry, x[i:i + frame])
            outs.append(np.asarray(y))
        return np.concatenate(outs)

    for dtype in (np.float32, np.complex64):
        x = rng.standard_normal(1 << 15).astype(np.float32)
        if dtype == np.complex64:
            x = (x + 1j * rng.standard_normal(len(x))).astype(np.complex64)
        y_xla = run(x)
        mxu_fft.set_impl("mxu")
        try:
            y_mxu = run(x)
        finally:
            mxu_fft.set_impl("auto")
        assert np.abs(y_mxu - y_xla).max() < 2e-3, dtype


def test_fft_stage_mxu_matches_xla():
    from futuresdr_tpu.ops import fft_stage
    rng = np.random.default_rng(6)
    x = (rng.standard_normal(4096) + 1j * rng.standard_normal(4096)).astype(np.complex64)
    st = fft_stage(2048)
    _, y_xla = st.fn(st.init_carry(np.complex64), x)
    mxu_fft.set_impl("mxu")
    try:
        st2 = fft_stage(2048)
        _, y_mxu = st2.fn(st2.init_carry(np.complex64), x)
    finally:
        mxu_fft.set_impl("auto")
    assert np.abs(np.asarray(y_mxu) - np.asarray(y_xla)).max() < 2e-2


def test_fir_stage_pallas_impl_matches_os():
    """fir_stage(impl='pallas') streams identically to the overlap-save path."""
    from futuresdr_tpu.ops import fir_stage
    rng = np.random.default_rng(9)
    taps = rng.standard_normal(32).astype(np.float32)
    for dtype in (np.float32, np.complex64):
        x = rng.standard_normal(1 << 15).astype(np.float32)
        if dtype == np.complex64:
            x = (x + 1j * rng.standard_normal(len(x))).astype(np.complex64)

        def run(impl):
            st = fir_stage(taps, impl=impl)
            carry = st.init_carry(x.dtype)
            outs = []
            for i in range(0, len(x), 1 << 13):
                carry, y = st.fn(carry, x[i:i + (1 << 13)])
                outs.append(np.asarray(y))
            return np.concatenate(outs)

        y_os, y_pl = run("os"), run("pallas")
        assert np.abs(y_os - y_pl).max() < 2e-3, dtype


def test_forced_mxu_huge_nonpow2_falls_back():
    """impl='mxu' must not route a huge non-power-of-two n through a dense [n,n]
    DFT matmul (O(n^2) HBM) — it falls back to jnp.fft above the direct cap."""
    from futuresdr_tpu.ops import mxu_fft
    assert not mxu_fft._use_mxu(100_000, impl="mxu")      # would be ~80 GB dense
    assert mxu_fft._use_mxu(300, impl="mxu")              # small direct: fine
    assert mxu_fft._use_mxu(1 << 16, impl="mxu")          # pow2: four-step, fine
    # per-call override wins over the module global
    mxu_fft.set_impl("mxu")
    try:
        assert not mxu_fft._use_mxu(2048, impl="xla")
    finally:
        mxu_fft.set_impl("auto")
